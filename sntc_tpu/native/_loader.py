"""Shared build-and-load scaffolding for the native components.

Each ``sntc_tpu/native/*.cpp`` translation unit is compiled on first use
(``g++ -O3 -shared -fPIC``; the toolchain is in-image) and cached next to
its source; a stale ``.so`` (older than the source) rebuilds.  Failures
latch per-module so a missing toolchain costs one subprocess attempt, and
callers fall back to their pure-Python parsers.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional


class NativeLib:
    """Lazy ctypes loader for one .cpp/.so pair."""

    def __init__(self, src: str, so: str):
        self.src = src
        self.so = so
        self._lib: Optional[ctypes.CDLL] = None
        self._failed = False

    def _build(self) -> Optional[str]:
        if os.path.exists(self.so) and os.path.getmtime(
            self.so
        ) >= os.path.getmtime(self.src):
            return self.so
        try:
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", self.so, self.src],
                check=True, capture_output=True, timeout=120,
            )
            return self.so
        except (OSError, subprocess.SubprocessError):
            return None

    def get(self, configure) -> Optional[ctypes.CDLL]:
        """The loaded library, building it on first call; ``configure(lib)``
        declares argtypes/restypes once after a successful load."""
        if self._lib is not None or self._failed:
            return self._lib
        so = self._build()
        if so is None:
            self._failed = True
            return None
        lib = ctypes.CDLL(so)
        configure(lib)
        self._lib = lib
        return self._lib
