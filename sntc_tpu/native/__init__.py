"""Native host components — ctypes bindings over sntc_tpu/native/*.cpp.

The C++ NetFlow v5 parser is built on first use (g++ -O3 -shared; the
toolchain is in-image) and cached next to the source.  A pure-Python
``struct`` fallback keeps the feature available if no compiler exists;
both implementations are cross-checked by tests/test_netflow.py.
"""

from sntc_tpu.native.netflow import (
    NF5_FIELDS,
    NF5_FIELD_NAMES,
    make_datagram,
    netflow_to_flow_frame,
    parse_datagram,
    parse_stream,
    using_native,
)

__all__ = [
    "NF5_FIELDS",
    "NF5_FIELD_NAMES",
    "parse_datagram",
    "parse_stream",
    "make_datagram",
    "netflow_to_flow_frame",
    "using_native",
]
