"""Native host components — ctypes bindings over sntc_tpu/native/*.cpp.

The C++ NetFlow v5 and pcap parsers are built on first use (g++ -O3
-shared; the toolchain is in-image) and cached next to the source.
Pure-Python ``struct`` fallbacks keep the features available if no
compiler exists; both implementations are cross-checked by
tests/test_netflow.py and tests/test_pcap.py.
"""

from sntc_tpu.native.netflow import (
    NF5_FIELDS,
    NF5_FIELD_NAMES,
    make_datagram,
    netflow_to_flow_frame,
    parse_datagram,
    parse_stream,
    using_native,
)
from sntc_tpu.native.pcap import (
    PCAP_FIELD_NAMES,
    PCAP_FIELDS,
    make_packet,
    make_pcap,
    packets_to_flow_frame,
    parse_pcap,
    pcap_to_flow_frame,
)
from sntc_tpu.native.pcap import using_native as using_native_pcap

__all__ = [
    "NF5_FIELDS",
    "NF5_FIELD_NAMES",
    "parse_datagram",
    "parse_stream",
    "make_datagram",
    "netflow_to_flow_frame",
    "using_native",
    "PCAP_FIELDS",
    "PCAP_FIELD_NAMES",
    "parse_pcap",
    "make_pcap",
    "make_packet",
    "packets_to_flow_frame",
    "pcap_to_flow_frame",
    "using_native_pcap",
]
