"""NetFlow v5 decode: ctypes binding + Python fallback + schema mapping.

See sntc_tpu/native/netflow.cpp for the wire format and field order.
``netflow_to_flow_frame`` lifts parsed records into the 78-column
CICIDS2017 flow schema (sntc_tpu/data/schema.py) so a trained pipeline
serves live NetFlow directly; fields CICFlowMeter derives from packet
captures but NetFlow v5 does not carry are zero-filled (documented
approximation — flag "counts" are presence bits).
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from sntc_tpu.core.frame import Frame
from sntc_tpu.data.schema import CICIDS2017_FEATURES
from sntc_tpu.native._loader import NativeLib

_DIR = os.path.dirname(os.path.abspath(__file__))
_NATIVE = NativeLib(
    os.path.join(_DIR, "netflow.cpp"), os.path.join(_DIR, "libnetflow.so")
)

NF5_FIELDS = 16
NF5_FIELD_NAMES = [
    "srcaddr", "dstaddr", "srcport", "dstport",
    "protocol", "tcp_flags", "tos", "packets",
    "octets", "first_ms", "last_ms", "input_if",
    "output_if", "src_as", "dst_as", "duration_ms",
]

_HEADER = struct.Struct(">HHIIIIBBH")  # 24 bytes
_RECORD = struct.Struct(">IIIHHIIIIHHBBBBHHBBH")  # 48 bytes

def _configure(lib: ctypes.CDLL) -> None:
    for name in ("nf5_count", "nf5_parse", "nf5_parse_stream"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int
    lib.nf5_count.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    for name in ("nf5_parse", "nf5_parse_stream"):
        getattr(lib, name).argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int,
        ]


def _get_lib() -> Optional[ctypes.CDLL]:
    return _NATIVE.get(_configure)


def using_native() -> bool:
    return _get_lib() is not None


# ---------------------------------------------------------------------------
# pure-Python fallback (also the test oracle)
# ---------------------------------------------------------------------------


def _parse_py(data: bytes) -> Optional[np.ndarray]:
    if len(data) < 24:
        return None
    version, count = struct.unpack(">HH", data[:4])
    if version != 5 or count > 30 or len(data) < 24 + count * 48:
        return None
    out = np.zeros((count, NF5_FIELDS), np.float64)
    for i in range(count):
        rec = data[24 + i * 48 : 24 + (i + 1) * 48]
        (srcaddr, dstaddr, _nexthop, input_if, output_if, pkts, octets,
         first, last, srcport, dstport, _pad1, flags, proto, tos,
         src_as, dst_as, _smask, _dmask, _pad2) = _RECORD.unpack(rec)
        out[i] = [
            srcaddr, dstaddr, srcport, dstport, proto, flags, tos, pkts,
            octets, first, last, input_if, output_if, src_as, dst_as,
            max(last - first, 0),
        ]
    return out


def _parse_stream_py(data: bytes) -> np.ndarray:
    rows: List[np.ndarray] = []
    off = 0
    while off + 24 <= len(data):
        parsed = _parse_py(data[off:])
        if parsed is None:
            break
        rows.append(parsed)
        off += 24 + parsed.shape[0] * 48
    if not rows:
        return np.zeros((0, NF5_FIELDS), np.float64)
    return np.concatenate(rows, axis=0)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _emit_truncated(reason: str, valid_bytes: int, dropped: int) -> None:
    from sntc_tpu.resilience import emit_event

    emit_event(
        event="parse_truncated", site="source.parse", format="netflow",
        reason=reason, valid_bytes=valid_bytes, dropped_bytes=dropped,
    )


def scan_stream(data: bytes) -> tuple:
    """Bounds-check a concatenated-datagram stream: returns
    ``(clean_len, reason)`` where ``data[:clean_len]`` is the longest
    prefix of complete datagrams and ``reason`` is ``None`` (clean),
    ``"truncated"`` (tail cut mid-datagram) or ``"bad_header"``
    (mid-stream bytes that are not a v5 header — corruption)."""
    off, n = 0, len(data)
    while off + 24 <= n:
        version, count = struct.unpack(">HH", data[off : off + 4])
        if version != 5 or count > 30:
            return off, "bad_header"
        end = off + 24 + count * 48
        if end > n:
            return off, "truncated"
        off = end
    if off < n:
        return off, "truncated"
    return off, None


def parse_datagram(data: bytes) -> Optional[np.ndarray]:
    """One datagram -> [count, NF5_FIELDS] float64, or None if malformed.

    A datagram whose header is sound but whose body was cut short
    (partial capture write) salvages the records that fully fit — the
    valid prefix parses, the torn tail is reported as a structured
    ``parse_truncated`` event instead of failing the whole datagram."""
    if len(data) >= 24:
        version, count = struct.unpack(">HH", data[:4])
        want = 24 + count * 48
        if version == 5 and count <= 30 and len(data) < want:
            n_fit = (len(data) - 24) // 48
            clean = 24 + n_fit * 48
            _emit_truncated("truncated", clean, len(data) - clean)
            # re-frame the valid prefix so both parsers see a
            # self-consistent datagram (header count must match body)
            data = (
                data[:2] + struct.pack(">H", n_fit) + data[4:24]
                + data[24:clean]
            )
    lib = _get_lib()
    if lib is None:
        return _parse_py(data)
    count = lib.nf5_count(data, len(data))
    if count < 0:
        return None
    out = np.zeros((count, NF5_FIELDS), np.float64)
    wrote = lib.nf5_parse(
        data, len(data),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), count,
    )
    return out[:wrote] if wrote >= 0 else None


def parse_stream(data: bytes, max_records: int = 1_000_000) -> np.ndarray:
    """Concatenated datagrams (a capture file) -> stacked records.

    Bounds-checked: a stream torn mid-datagram, or poisoned mid-stream
    with bytes that are not a v5 header, yields the longest clean
    datagram prefix plus a structured ``parse_truncated`` event naming
    the reason and the dropped byte count — never an exception, never
    a silent stop.  A torn TAIL datagram with a sound header is
    additionally salvaged at record granularity (the records that
    fully fit parse; :func:`parse_datagram` emits the event)."""
    clean_len, reason = scan_stream(data)
    tail_rows: Optional[np.ndarray] = None
    if reason is not None:
        tail = data[clean_len:]
        if reason == "truncated" and len(tail) >= 24:
            tail_rows = parse_datagram(tail)
        else:
            _emit_truncated(reason, clean_len, len(tail))
        data = data[:clean_len]
    lib = _get_lib()
    if lib is None:
        out = _parse_stream_py(data)
    else:
        buf = np.zeros((max_records, NF5_FIELDS), np.float64)
        wrote = lib.nf5_parse_stream(
            data, len(data),
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            max_records,
        )
        out = buf[: max(wrote, 0)].copy()
    if tail_rows is not None and tail_rows.shape[0]:
        out = np.concatenate([out, tail_rows], axis=0)
    return out


def make_datagram(
    records: Sequence[Tuple],
    sys_uptime: int = 3_600_000,
    unix_secs: int = 1_700_000_000,
    seq: int = 0,
) -> bytes:
    """Encode records (tuples in NF5_FIELD_NAMES[:15] order, sans duration)
    into a v5 datagram — the test/demo exporter."""
    if len(records) > 30:
        raise ValueError("NetFlow v5 datagrams carry at most 30 records")
    head = _HEADER.pack(5, len(records), sys_uptime, unix_secs, 0, seq, 0, 0, 0)
    body = b""
    for r in records:
        (srcaddr, dstaddr, srcport, dstport, proto, flags, tos, pkts,
         octets, first, last, input_if, output_if, src_as, dst_as) = r
        body += _RECORD.pack(
            int(srcaddr), int(dstaddr), 0, int(input_if), int(output_if),
            int(pkts), int(octets), int(first), int(last), int(srcport),
            int(dstport), 0, int(flags), int(proto), int(tos),
            int(src_as), int(dst_as), 0, 0, 0,
        )
    return head + body


_F = {name: i for i, name in enumerate(NF5_FIELD_NAMES)}


def netflow_to_flow_frame(records: np.ndarray) -> Frame:
    """[n, NF5_FIELDS] records -> 78-column CICIDS2017-schema Frame.

    NetFlow v5 is unidirectional and packet-level-blind, so only the
    fields it carries are populated; the rest are 0.  Flag "counts" are
    0/1 presence bits from tcp_flags.
    """
    n = records.shape[0]
    cols = {name: np.zeros(n, np.float32) for name in CICIDS2017_FEATURES}
    r = records

    dur_us = r[:, _F["duration_ms"]] * 1000.0  # CICIDS durations are µs
    dur_s = np.maximum(r[:, _F["duration_ms"]] / 1000.0, 1e-9)
    pkts = r[:, _F["packets"]]
    octets = r[:, _F["octets"]]

    cols["Destination Port"] = r[:, _F["dstport"]].astype(np.float32)
    cols["Flow Duration"] = dur_us.astype(np.float32)
    cols["Total Fwd Packets"] = pkts.astype(np.float32)
    cols["Total Length of Fwd Packets"] = octets.astype(np.float32)
    cols["Flow Bytes/s"] = (octets / dur_s).astype(np.float32)
    cols["Flow Packets/s"] = (pkts / dur_s).astype(np.float32)
    cols["Fwd Packets/s"] = cols["Flow Packets/s"]
    mean_pkt = (octets / np.maximum(pkts, 1.0)).astype(np.float32)
    cols["Average Packet Size"] = mean_pkt
    cols["Packet Length Mean"] = mean_pkt
    cols["Fwd Packet Length Mean"] = mean_pkt
    cols["Avg Fwd Segment Size"] = mean_pkt
    cols["Subflow Fwd Packets"] = pkts.astype(np.float32)
    cols["Subflow Fwd Bytes"] = octets.astype(np.float32)

    flags = r[:, _F["tcp_flags"]].astype(np.int64)
    for bit, name in (
        (0x01, "FIN Flag Count"), (0x02, "SYN Flag Count"),
        (0x04, "RST Flag Count"), (0x08, "PSH Flag Count"),
        (0x10, "ACK Flag Count"), (0x20, "URG Flag Count"),
    ):
        cols[name] = ((flags & bit) > 0).astype(np.float32)
    return Frame(cols)
