"""pcap decode + flow metering: ctypes binding, Python fallback, and the
CICFlowMeter analog.

See sntc_tpu/native/pcap.cpp for the capture format and per-packet field
order.  ``packets_to_flow_frame`` aggregates the packet matrix into
bidirectional flows and emits the 78-column CICIDS2017 schema
(sntc_tpu/data/schema.py) — the role CICFlowMeter plays upstream of the
reference's CSVs ([B:11] "NetFlow/pcap micro-batches"; SURVEY.md §2.1).
The aggregation is fully vectorized: one lexsort groups packets into
flows, ``np.add.reduceat``/segment reductions produce every statistic —
no per-flow Python loop on the serving hot path.
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import Optional

import numpy as np

from sntc_tpu.core.frame import Frame
from sntc_tpu.data.schema import CICIDS2017_FEATURES
from sntc_tpu.native._loader import NativeLib

_DIR = os.path.dirname(os.path.abspath(__file__))
_NATIVE = NativeLib(
    os.path.join(_DIR, "pcap.cpp"), os.path.join(_DIR, "libpcapflow.so")
)

PCAP_FIELDS = 12
PCAP_FIELD_NAMES = [
    "ts", "src_ip", "dst_ip", "src_port", "dst_port", "protocol",
    "ip_len", "payload_len", "tcp_flags", "tcp_window", "header_len",
    "orig_len",
]
_P = {name: i for i, name in enumerate(PCAP_FIELD_NAMES)}

def _configure(lib: ctypes.CDLL) -> None:
    lib.pcap_ok.restype = ctypes.c_int
    lib.pcap_ok.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.pcap_parse.restype = ctypes.c_int
    lib.pcap_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int,
    ]


def _get_lib() -> Optional[ctypes.CDLL]:
    return _NATIVE.get(_configure)


def using_native() -> bool:
    return _get_lib() is not None


# ---------------------------------------------------------------------------
# pure-Python fallback (also the test oracle)
# ---------------------------------------------------------------------------

_MAGICS = {
    0xA1B2C3D4: (">", 1e-6),
    0xD4C3B2A1: ("<", 1e-6),
    0xA1B23C4D: (">", 1e-9),
    0x4D3CB2A1: ("<", 1e-9),
}


def _parse_pcap_py(data: bytes) -> Optional[np.ndarray]:
    if len(data) < 24:
        return None
    (magic_be,) = struct.unpack(">I", data[:4])
    if magic_be not in _MAGICS:
        return None
    endian, ts_scale = _MAGICS[magic_be]
    (linktype,) = struct.unpack(endian + "I", data[20:24])
    if linktype not in (1, 101):
        return None
    rows = []
    off = 24
    rec = struct.Struct(endian + "IIII")
    while off + 16 <= len(data):
        ts_sec, ts_frac, incl, orig = rec.unpack(data[off : off + 16])
        off += 16
        if incl > len(data) - off:
            break
        pkt = data[off : off + incl]
        off += incl
        ip_off = 0
        if linktype == 1:
            if incl < 14:
                continue
            ethertype = struct.unpack(">H", pkt[12:14])[0]
            ip_off = 14
            if ethertype == 0x8100:
                if incl < 18:
                    continue
                ethertype = struct.unpack(">H", pkt[16:18])[0]
                ip_off = 18
            if ethertype != 0x0800:
                continue
        if incl < ip_off + 20:
            continue
        ip = pkt[ip_off:]
        if (ip[0] >> 4) != 4:
            continue
        ihl = (ip[0] & 0x0F) * 4
        if ihl < 20 or incl < ip_off + ihl:
            continue
        ip_total = struct.unpack(">H", ip[2:4])[0]
        proto = ip[9]
        src = struct.unpack(">I", ip[12:16])[0]
        dst = struct.unpack(">I", ip[16:20])[0]
        l4 = ip[ihl:]
        sport = dport = flags = window = 0
        if proto == 6:
            if len(l4) < 20:
                continue
            sport, dport = struct.unpack(">HH", l4[:4])
            l4_hdr = (l4[12] >> 4) * 4
            if l4_hdr < 20 or len(l4) < l4_hdr:
                continue
            flags = l4[13]
            window = struct.unpack(">H", l4[14:16])[0]
        elif proto == 17:
            if len(l4) < 8:
                continue
            sport, dport = struct.unpack(">HH", l4[:4])
            l4_hdr = 8
        else:
            continue
        payload = max(ip_total - ihl - l4_hdr, 0)
        rows.append([
            ts_sec + ts_frac * ts_scale, src, dst, sport, dport, proto,
            ip_total, payload, flags, window, ihl + l4_hdr, orig,
        ])
    if not rows:
        return np.zeros((0, PCAP_FIELDS), np.float64)
    return np.asarray(rows, np.float64)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def scan_truncation(data: bytes) -> tuple:
    """Bounds-check the capture's record framing WITHOUT parsing packet
    bodies: returns ``(clean_len, dropped_bytes)`` where
    ``data[:clean_len]`` is the longest prefix made of complete records
    and ``dropped_bytes`` is the torn tail (0 = clean capture).  A
    header too short/bad to carry records reports the whole payload as
    clean (the parser's bad-header path owns that verdict)."""
    if len(data) < 24:
        return len(data), 0
    (magic_be,) = struct.unpack(">I", data[:4])
    if magic_be not in _MAGICS:
        return len(data), 0
    endian, _ = _MAGICS[magic_be]
    off = 24
    n = len(data)
    rec = struct.Struct(endian + "IIII")
    while off + 16 <= n:
        incl = rec.unpack_from(data, off)[2]
        if off + 16 + incl > n:
            break  # record header promises more bytes than exist
        off += 16 + incl
    return off, n - off


def parse_pcap(data: bytes) -> Optional[np.ndarray]:
    """Capture bytes -> ``[n, PCAP_FIELDS]`` float64 packet matrix
    (IPv4 TCP/UDP packets only), or None if the global header is bad.

    A capture torn mid-record (partial write, corrupt length field)
    does NOT raise and is never silently absorbed either: the longest
    complete-record prefix parses normally and the dropped tail is
    reported as a structured ``parse_truncated`` event on the
    ``source.parse`` site — the row-granular salvage contract applied
    at the byte level (docs/RESILIENCE.md "Data-plane admission").

    The output buffer is sized from the data itself (every packet record
    costs at least 16 header bytes), so small micro-batch captures stay
    cheap and large ones are never truncated.
    """
    clean_len, dropped = scan_truncation(data)
    if dropped:
        from sntc_tpu.resilience import emit_event

        emit_event(
            event="parse_truncated", site="source.parse", format="pcap",
            valid_bytes=clean_len, dropped_bytes=dropped,
        )
        data = data[:clean_len]
    lib = _get_lib()
    if lib is None:
        return _parse_pcap_py(data)
    if not lib.pcap_ok(data, len(data)):
        return None
    cap = max((len(data) - 24) // 16, 1)
    out = np.zeros((cap, PCAP_FIELDS), np.float64)
    wrote = lib.pcap_parse(
        data, len(data),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), cap,
    )
    if wrote < 0:
        return None
    return out[:wrote].copy()


def make_pcap(packets, linktype: int = 1, nanos: bool = False) -> bytes:
    """Encode packets into a classic pcap byte string — the test/demo
    capture writer.  ``packets`` is a sequence of ``(ts, bytes)``."""
    magic = 0xA1B23C4D if nanos else 0xA1B2C3D4
    scale = 1e9 if nanos else 1e6
    head = struct.pack(">IHHiIII", magic, 2, 4, 0, 0, 65535, linktype)
    body = b""
    for ts, pkt in packets:
        sec = int(ts)
        frac = int(round((ts - sec) * scale))
        body += struct.pack(">IIII", sec, frac, len(pkt), len(pkt)) + pkt
    return head + body


def make_packet(
    src: int, dst: int, sport: int, dport: int, proto: int = 6,
    payload: int = 100, flags: int = 0x18, window: int = 8192,
) -> bytes:
    """Build one Ethernet+IPv4+TCP/UDP packet with ``payload`` data bytes
    (zeros) — the synthetic traffic generator for tests/demos."""
    l4_hdr = 20 if proto == 6 else 8
    ip_total = 20 + l4_hdr + payload
    eth = b"\x02" * 6 + b"\x04" * 6 + struct.pack(">H", 0x0800)
    ip = struct.pack(
        ">BBHHHBBHII", 0x45, 0, ip_total, 0, 0, 64, proto, 0, src, dst
    )
    if proto == 6:
        l4 = struct.pack(
            ">HHIIBBHHH", sport, dport, 0, 0, 5 << 4, flags, window, 0, 0
        )
    else:
        l4 = struct.pack(">HHHH", sport, dport, 8 + payload, 0)
    return eth + ip + l4 + b"\x00" * payload


# ---------------------------------------------------------------------------
# the flow meter (CICFlowMeter analog)
# ---------------------------------------------------------------------------


def _seg_stat(values, starts, counts):
    """(sum, mean, std, min, max) per segment of a sorted-by-segment
    vector, via reduceat — no Python loop."""
    sums = np.add.reduceat(values, starts) if len(values) else np.zeros(0)
    sums = np.where(counts > 0, sums, 0.0)
    mean = sums / np.maximum(counts, 1)
    sq = np.add.reduceat(values * values, starts) if len(values) else np.zeros(0)
    sq = np.where(counts > 0, sq, 0.0)
    var = np.maximum(sq / np.maximum(counts, 1) - mean * mean, 0.0)
    # CICFlowMeter reports the SAMPLE std (n-1); guard n<=1 -> 0
    var = np.where(counts > 1, var * counts / np.maximum(counts - 1, 1), 0.0)
    mins = np.minimum.reduceat(values, starts) if len(values) else np.zeros(0)
    maxs = np.maximum.reduceat(values, starts) if len(values) else np.zeros(0)
    mins = np.where(counts > 0, mins, 0.0)
    maxs = np.where(counts > 0, maxs, 0.0)
    return sums, mean, np.sqrt(var), mins, maxs


def _masked_seg_stat(values, mask, seg_ids, n_seg):
    """Per-segment (sum, mean, std, min, max) over only ``mask`` rows
    (fwd/bwd direction splits); segments with no selected rows -> 0."""
    sel = np.flatnonzero(mask)
    v = values[sel]
    s = seg_ids[sel]
    counts = np.bincount(s, minlength=n_seg).astype(np.float64)
    sums = np.bincount(s, weights=v, minlength=n_seg)
    mean = sums / np.maximum(counts, 1)
    sq = np.bincount(s, weights=v * v, minlength=n_seg)
    var = np.maximum(sq / np.maximum(counts, 1) - mean * mean, 0.0)
    var = np.where(counts > 1, var * counts / np.maximum(counts - 1, 1), 0.0)
    mins = np.full(n_seg, np.inf)
    maxs = np.full(n_seg, -np.inf)
    np.minimum.at(mins, s, v)
    np.maximum.at(maxs, s, v)
    mins = np.where(counts > 0, mins, 0.0)
    maxs = np.where(counts > 0, maxs, 0.0)
    return counts, sums, mean, np.sqrt(var), mins, maxs


def packets_to_flow_frame(
    pkts: np.ndarray,
    flow_timeout: float = 120.0,
    activity_timeout: float = 5.0,
) -> Frame:
    """``[n, PCAP_FIELDS]`` packets -> 78-column CICIDS2017-schema Frame.

    Flow identity is the bidirectional 5-tuple; a quiet gap longer than
    ``flow_timeout`` starts a new flow (CICFlowMeter's timeout split).
    The forward direction is the direction of each flow's first packet.
    ``Active``/``Idle`` statistics split each flow at gaps longer than
    ``activity_timeout``.  Features pcap genuinely cannot produce (bulk
    rates) stay 0 — CICFlowMeter itself emits 0 for them on CICIDS2017.
    """
    n = pkts.shape[0]
    if n == 0:
        return Frame({name: np.zeros(0, np.float32) for name in CICIDS2017_FEATURES})

    ts = pkts[:, _P["ts"]]
    src = pkts[:, _P["src_ip"]].astype(np.int64)
    dst = pkts[:, _P["dst_ip"]].astype(np.int64)
    sport = pkts[:, _P["src_port"]].astype(np.int64)
    dport = pkts[:, _P["dst_port"]].astype(np.int64)
    proto = pkts[:, _P["protocol"]].astype(np.int64)
    paylen = pkts[:, _P["payload_len"]]
    flags = pkts[:, _P["tcp_flags"]].astype(np.int64)
    window = pkts[:, _P["tcp_window"]]
    hdrlen = pkts[:, _P["header_len"]]

    # canonical (order-free) endpoint key + direction bit
    ep_a = src * 65536 + sport
    ep_b = dst * 65536 + dport
    lo = np.minimum(ep_a, ep_b)
    hi = np.maximum(ep_a, ep_b)
    a_is_lo = ep_a <= ep_b  # this packet travels lo -> hi

    # sort by (key, time): flows become contiguous runs
    order = np.lexsort((ts, proto, hi, lo))
    lo_s, hi_s, proto_s, ts_s = lo[order], hi[order], proto[order], ts[order]
    new_key = np.empty(n, bool)
    new_key[0] = True
    new_key[1:] = (
        (lo_s[1:] != lo_s[:-1])
        | (hi_s[1:] != hi_s[:-1])
        | (proto_s[1:] != proto_s[:-1])
    )
    gap = np.empty(n, np.float64)
    gap[0] = 0.0
    gap[1:] = ts_s[1:] - ts_s[:-1]
    # a new FLOW starts at a new 5-tuple or after a long quiet gap
    new_flow = new_key | (gap > flow_timeout)
    seg_ids = np.cumsum(new_flow) - 1
    n_seg = int(seg_ids[-1]) + 1
    starts = np.flatnonzero(new_flow)
    counts = np.diff(np.append(starts, n)).astype(np.float64)

    # forward = direction of the flow's first packet
    a_lo_s = a_is_lo[order]
    first_dir = a_lo_s[starts]  # per segment
    fwd = a_lo_s == first_dir[seg_ids]
    bwd = ~fwd

    pay_s = paylen[order]
    hdr_s = hdrlen[order]
    flags_s = flags[order]
    win_s = window[order]
    dport_pkt = dport[order]

    dur = ts_s[np.append(starts[1:], n) - 1] - ts_s[starts]  # per segment, s
    dur_us = dur * 1e6
    dur_s_safe = np.maximum(dur, 1e-9)

    f_cnt, f_sum, f_mean, f_std, f_min, f_max = _masked_seg_stat(
        pay_s, fwd, seg_ids, n_seg
    )
    b_cnt, b_sum, b_mean, b_std, b_min, b_max = _masked_seg_stat(
        pay_s, bwd, seg_ids, n_seg
    )
    a_sum, a_mean, a_std, a_min, a_max = _seg_stat(pay_s, starts, counts)

    # inter-arrival times: within-flow diffs (flow IAT), and per-direction
    iat = np.where(new_flow, np.nan, gap) * 1e6  # µs; NaN marks flow starts
    valid_iat = ~np.isnan(iat)
    fi_cnt, fi_sum, fi_mean, fi_std, fi_min, fi_max = _masked_seg_stat(
        np.nan_to_num(iat), valid_iat, seg_ids, n_seg
    )
    # per-direction IATs need per-direction previous timestamps: compute by
    # sorting the direction subsets (they are already time-ordered)
    def dir_iat(mask):
        sel = np.flatnonzero(mask)
        t = ts_s[sel]
        s = seg_ids[sel]
        first = np.empty(len(sel), bool)
        if len(sel):
            first[0] = True
            first[1:] = s[1:] != s[:-1]
        d = np.empty(len(sel), np.float64)
        if len(sel):
            d[0] = 0.0
            d[1:] = (t[1:] - t[:-1]) * 1e6
        ok = ~first
        cnt, ssum, mean, std, mn, mx = _masked_seg_stat(d, ok, s, n_seg)
        return ssum, mean, std, mn, mx

    ffi_sum, ffi_mean, ffi_std, ffi_min, ffi_max = dir_iat(fwd)
    bfi_sum, bfi_mean, bfi_std, bfi_min, bfi_max = dir_iat(bwd)

    # ACTIVE/IDLE: split each flow at gaps > activity_timeout; idle = those
    # gaps, active = span durations between them
    idle_gap = valid_iat & (gap > activity_timeout)
    _, _, id_mean, id_std, id_min, id_max = _masked_seg_stat(
        gap * 1e6, idle_gap, seg_ids, n_seg
    )
    # active spans: sub-segment boundaries at flow starts OR idle gaps
    new_span = new_flow | idle_gap
    span_starts = np.flatnonzero(new_span)
    span_seg = seg_ids[span_starts]
    span_end = np.append(span_starts[1:], n) - 1
    span_dur = (ts_s[span_end] - ts_s[span_starts]) * 1e6
    ac_cnt, ac_sum, ac_mean, ac_std, ac_min, ac_max = _masked_seg_stat(
        span_dur, np.ones(len(span_dur), bool), span_seg, n_seg
    )

    # per-direction flag counts and header sums; mask=None means all rows
    def dir_count(mask, bit=None, weights=None):
        if bit is None:
            sel = mask
        else:
            sel = (flags_s & bit) > 0
            if mask is not None:
                sel = mask & sel
        if weights is None:
            return np.bincount(
                seg_ids[sel], minlength=n_seg
            ).astype(np.float64)
        return np.bincount(seg_ids[sel], weights=weights[sel], minlength=n_seg)

    # init window bytes: value of the first packet per direction
    def first_per_dir(mask, values):
        sel = np.flatnonzero(mask)
        s = seg_ids[sel]
        first = np.empty(len(sel), bool)
        if len(sel):
            first[0] = True
            first[1:] = s[1:] != s[:-1]
        out = np.full(n_seg, -1.0)
        out[s[first]] = values[sel][first]
        return out

    cols = {name: np.zeros(n_seg, np.float32) for name in CICIDS2017_FEATURES}

    def put(name, v):
        cols[name] = np.asarray(v, np.float32)

    # the flow's destination port is the first packet's dst port
    put("Destination Port", dport_pkt[starts])
    put("Flow Duration", dur_us)
    put("Total Fwd Packets", f_cnt)
    put("Total Backward Packets", b_cnt)
    put("Total Length of Fwd Packets", f_sum)
    put("Total Length of Bwd Packets", b_sum)
    put("Fwd Packet Length Max", f_max)
    put("Fwd Packet Length Min", f_min)
    put("Fwd Packet Length Mean", f_mean)
    put("Fwd Packet Length Std", f_std)
    put("Bwd Packet Length Max", b_max)
    put("Bwd Packet Length Min", b_min)
    put("Bwd Packet Length Mean", b_mean)
    put("Bwd Packet Length Std", b_std)
    put("Flow Bytes/s", (f_sum + b_sum) / dur_s_safe)
    put("Flow Packets/s", counts / dur_s_safe)
    put("Flow IAT Mean", fi_mean)
    put("Flow IAT Std", fi_std)
    put("Flow IAT Max", fi_max)
    put("Flow IAT Min", fi_min)
    put("Fwd IAT Total", ffi_sum)
    put("Fwd IAT Mean", ffi_mean)
    put("Fwd IAT Std", ffi_std)
    put("Fwd IAT Max", ffi_max)
    put("Fwd IAT Min", ffi_min)
    put("Bwd IAT Total", bfi_sum)
    put("Bwd IAT Mean", bfi_mean)
    put("Bwd IAT Std", bfi_std)
    put("Bwd IAT Max", bfi_max)
    put("Bwd IAT Min", bfi_min)
    put("Fwd PSH Flags", dir_count(fwd, 0x08))
    put("Bwd PSH Flags", dir_count(bwd, 0x08))
    put("Fwd URG Flags", dir_count(fwd, 0x20))
    put("Bwd URG Flags", dir_count(bwd, 0x20))
    put("Fwd Header Length", dir_count(fwd, weights=hdr_s))
    put("Bwd Header Length", dir_count(bwd, weights=hdr_s))
    put("Fwd Packets/s", f_cnt / dur_s_safe)
    put("Bwd Packets/s", b_cnt / dur_s_safe)
    put("Min Packet Length", a_min)
    put("Max Packet Length", a_max)
    put("Packet Length Mean", a_mean)
    put("Packet Length Std", a_std)
    put("Packet Length Variance", a_std**2)
    for bit, name in (
        (0x01, "FIN Flag Count"), (0x02, "SYN Flag Count"),
        (0x04, "RST Flag Count"), (0x08, "PSH Flag Count"),
        (0x10, "ACK Flag Count"), (0x20, "URG Flag Count"),
        (0x80, "CWE Flag Count"), (0x40, "ECE Flag Count"),
    ):
        put(name, dir_count(None, bit))
    put("Down/Up Ratio", np.floor(b_cnt / np.maximum(f_cnt, 1.0)))
    put("Average Packet Size", a_mean)
    put("Avg Fwd Segment Size", f_mean)
    put("Avg Bwd Segment Size", b_mean)
    put("Fwd Header Length.1", cols["Fwd Header Length"])
    put("Subflow Fwd Packets", f_cnt)
    put("Subflow Fwd Bytes", f_sum)
    put("Subflow Bwd Packets", b_cnt)
    put("Subflow Bwd Bytes", b_sum)
    put("Init_Win_bytes_forward", first_per_dir(fwd, win_s))
    put("Init_Win_bytes_backward", first_per_dir(bwd, win_s))
    put("act_data_pkt_fwd", dir_count(fwd & (pay_s > 0)))
    min_seg = np.where(
        f_cnt > 0,
        _masked_seg_stat(hdr_s, fwd, seg_ids, n_seg)[4],
        0.0,
    )
    put("min_seg_size_forward", min_seg)
    put("Active Mean", ac_mean)
    put("Active Std", ac_std)
    put("Active Max", ac_max)
    put("Active Min", ac_min)
    put("Idle Mean", id_mean)
    put("Idle Std", id_std)
    put("Idle Max", id_max)
    put("Idle Min", id_min)
    return Frame(cols)


def pcap_to_flow_frame(data: bytes, **kwargs) -> Frame:
    """Capture bytes -> flow-feature Frame (parse + meter in one call)."""
    pkts = parse_pcap(data)
    if pkts is None:
        raise ValueError("not a pcap capture (bad global header)")
    return packets_to_flow_frame(pkts, **kwargs)
