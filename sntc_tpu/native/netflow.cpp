// NetFlow v5 datagram parser — the native host-ingest component for live
// streaming inference (config 5 [B:11], SURVEY.md §3.5).
//
// Where the reference stack's native layer is OpenBLAS/netty/codec JNI
// (SURVEY.md §2.7), the TPU rebuild's device math is XLA-compiled; the one
// host-side hot path that genuinely wants native code is wire-format
// parsing of live flow telemetry.  This translation unit decodes NetFlow
// v5 export datagrams (24-byte header + N x 48-byte records, all fields
// big-endian) straight into a dense float64 feature matrix consumed
// zero-copy by numpy via ctypes (sntc_tpu/native/__init__.py).
//
// ABI (extern "C", stable):
//   nf5_count(buf, len)  -> record count, or -1 if malformed
//   nf5_parse(buf, len, out, cap) -> records written; `out` is row-major
//       [cap, NF5_FIELDS] float64, one row per record, fields as in
//       kFieldOrder below.

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

constexpr int kHeaderBytes = 24;
constexpr int kRecordBytes = 48;
constexpr int kMaxRecordsPerDatagram = 30;  // per the v5 spec

inline uint16_t be16(const uint8_t* p) {
  return static_cast<uint16_t>((p[0] << 8) | p[1]);
}

inline uint32_t be32(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) |
         (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | static_cast<uint32_t>(p[3]);
}

}  // namespace

extern "C" {

// Field order of one output row (doubles hold uint32 exactly):
//  0 srcaddr      1 dstaddr      2 srcport    3 dstport
//  4 protocol     5 tcp_flags    6 tos        7 packets
//  8 octets       9 first_ms    10 last_ms   11 input_if
// 12 output_if   13 src_as      14 dst_as    15 duration_ms
constexpr int NF5_FIELDS = 16;

int nf5_fields() { return NF5_FIELDS; }

int nf5_count(const uint8_t* buf, size_t len) {
  if (buf == nullptr || len < kHeaderBytes) return -1;
  if (be16(buf) != 5) return -1;  // version
  const int count = be16(buf + 2);
  if (count < 0 || count > kMaxRecordsPerDatagram) return -1;
  if (len < static_cast<size_t>(kHeaderBytes + count * kRecordBytes))
    return -1;
  return count;
}

int nf5_parse(const uint8_t* buf, size_t len, double* out, int cap) {
  const int count = nf5_count(buf, len);
  if (count < 0 || out == nullptr) return -1;
  const int n = count < cap ? count : cap;
  const uint8_t* rec = buf + kHeaderBytes;
  for (int i = 0; i < n; ++i, rec += kRecordBytes) {
    double* row = out + static_cast<ptrdiff_t>(i) * NF5_FIELDS;
    const uint32_t first = be32(rec + 24);
    const uint32_t last = be32(rec + 28);
    row[0] = be32(rec + 0);    // srcaddr
    row[1] = be32(rec + 4);    // dstaddr
    row[2] = be16(rec + 32);   // srcport
    row[3] = be16(rec + 34);   // dstport
    row[4] = rec[38];          // protocol
    row[5] = rec[37];          // tcp_flags
    row[6] = rec[39];          // tos
    row[7] = be32(rec + 16);   // dPkts
    row[8] = be32(rec + 20);   // dOctets
    row[9] = first;            // sysuptime of flow start (ms)
    row[10] = last;            // sysuptime of flow end (ms)
    row[11] = be16(rec + 12);  // input ifindex
    row[12] = be16(rec + 14);  // output ifindex
    row[13] = be16(rec + 40);  // src_as
    row[14] = be16(rec + 42);  // dst_as
    row[15] = last >= first ? static_cast<double>(last - first) : 0.0;
  }
  return n;
}

// Parse a concatenated stream of datagrams (a capture file): returns total
// records written, advancing datagram-by-datagram; stops at the first
// malformed datagram (returns what was parsed so far).
int nf5_parse_stream(const uint8_t* buf, size_t len, double* out, int cap) {
  size_t off = 0;
  int total = 0;
  while (off + kHeaderBytes <= len && total < cap) {
    const int count = nf5_count(buf + off, len - off);
    if (count < 0) break;
    const int wrote = nf5_parse(
        buf + off, len - off, out + static_cast<ptrdiff_t>(total) * NF5_FIELDS,
        cap - total);
    if (wrote < 0) break;
    total += wrote;
    off += kHeaderBytes + static_cast<size_t>(count) * kRecordBytes;
  }
  return total;
}

}  // extern "C"
