// pcap packet parser — the native host-ingest component for live packet
// capture inference (config 5 [B:11] names "NetFlow/pcap micro-batches";
// SURVEY.md §3.5).  The NetFlow half lives in netflow.cpp; this unit
// decodes classic libpcap capture files (the format CICIDS2017's own
// captures ship in) into a dense per-packet float64 matrix.  Flow
// aggregation into the 78-column CICIDS2017 schema happens vectorized in
// numpy (sntc_tpu/native/pcap.py) — the byte-level packet walk is the
// part Python is slow at, so only that is native.
//
// Format: 24-byte global header (magic 0xa1b2c3d4 / 0xd4c3b2a1 swapped,
// 0xa1b23c4d / 0x4d3cb2a1 for nanosecond variants), then per packet a
// 16-byte record header (ts_sec, ts_frac, incl_len, orig_len) + data.
// Linktype must be 1 (Ethernet) or 101 (raw IP).  Ethernet frames may
// carry one 802.1Q VLAN tag; only IPv4 TCP/UDP packets produce rows
// (others are skipped — the flow meter has no use for them).
//
// ABI (extern "C", stable):
//   pcap_ok(buf, len)                 -> 1 if the global header parses
//   pcap_parse(buf, len, out, cap)   -> rows written, or -1 if malformed;
//       `out` is row-major [cap, PCAP_FIELDS] float64, field order below.

#include <cstddef>
#include <cstdint>

namespace {

inline uint32_t rd32(const uint8_t* p, bool swap) {
  return swap ? (static_cast<uint32_t>(p[3]) << 24) |
                    (static_cast<uint32_t>(p[2]) << 16) |
                    (static_cast<uint32_t>(p[1]) << 8) | p[0]
              : (static_cast<uint32_t>(p[0]) << 24) |
                    (static_cast<uint32_t>(p[1]) << 16) |
                    (static_cast<uint32_t>(p[2]) << 8) | p[3];
}

// network byte order helpers for packet payloads (always big-endian)
inline uint16_t be16(const uint8_t* p) {
  return static_cast<uint16_t>((p[0] << 8) | p[1]);
}
inline uint32_t be32(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) |
         (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | static_cast<uint32_t>(p[3]);
}

struct GlobalHeader {
  bool ok;
  bool swap;       // file byte order != big-endian network order reader
  double ts_scale; // fractional part unit: 1e-6 (µs) or 1e-9 (ns)
  uint32_t linktype;
};

GlobalHeader read_global(const uint8_t* buf, size_t len) {
  GlobalHeader g{false, false, 1e-6, 0};
  if (buf == nullptr || len < 24) return g;
  const uint32_t magic_be = be32(buf);
  switch (magic_be) {
    case 0xa1b2c3d4: g.swap = false; g.ts_scale = 1e-6; break;
    case 0xd4c3b2a1: g.swap = true;  g.ts_scale = 1e-6; break;
    case 0xa1b23c4d: g.swap = false; g.ts_scale = 1e-9; break;
    case 0x4d3cb2a1: g.swap = true;  g.ts_scale = 1e-9; break;
    default: return g;
  }
  g.linktype = rd32(buf + 20, g.swap);
  g.ok = (g.linktype == 1 || g.linktype == 101);
  return g;
}

}  // namespace

extern "C" {

// Field order of one output row:
//  0 ts (seconds, f64)  1 src_ip   2 dst_ip    3 src_port  4 dst_port
//  5 protocol           6 ip_len   7 payload_len (L4 payload bytes)
//  8 tcp_flags          9 tcp_window  10 header_len (IP+L4 headers)
// 11 orig_len (wire bytes incl. link layer)
constexpr int PCAP_FIELDS = 12;

int pcap_fields() { return PCAP_FIELDS; }

int pcap_ok(const uint8_t* buf, size_t len) {
  return read_global(buf, len).ok ? 1 : 0;
}

int pcap_parse(const uint8_t* buf, size_t len, double* out, int cap) {
  const GlobalHeader g = read_global(buf, len);
  if (!g.ok || out == nullptr) return -1;
  size_t off = 24;
  int n = 0;
  while (off + 16 <= len && n < cap) {
    const uint32_t ts_sec = rd32(buf + off, g.swap);
    const uint32_t ts_frac = rd32(buf + off + 4, g.swap);
    const uint32_t incl = rd32(buf + off + 8, g.swap);
    const uint32_t orig = rd32(buf + off + 12, g.swap);
    off += 16;
    if (incl > len - off) break;  // truncated capture tail
    const uint8_t* pkt = buf + off;
    off += incl;

    // ---- link layer -> start of IPv4 ----
    size_t ip_off = 0;
    if (g.linktype == 1) {  // Ethernet
      if (incl < 14) continue;
      uint16_t ethertype = be16(pkt + 12);
      ip_off = 14;
      if (ethertype == 0x8100) {  // one 802.1Q tag
        if (incl < 18) continue;
        ethertype = be16(pkt + 16);
        ip_off = 18;
      }
      if (ethertype != 0x0800) continue;  // not IPv4
    }
    if (incl < ip_off + 20) continue;
    const uint8_t* ip = pkt + ip_off;
    if ((ip[0] >> 4) != 4) continue;  // IPv4 only
    const size_t ihl = static_cast<size_t>(ip[0] & 0x0f) * 4;
    if (ihl < 20 || incl < ip_off + ihl) continue;
    const uint16_t ip_total = be16(ip + 2);
    const uint8_t proto = ip[9];
    const uint32_t src = be32(ip + 12);
    const uint32_t dst = be32(ip + 16);

    const uint8_t* l4 = ip + ihl;
    const size_t l4_avail = incl - ip_off - ihl;
    double sport = 0, dport = 0, flags = 0, window = 0;
    size_t l4_hdr = 0;
    if (proto == 6) {  // TCP
      if (l4_avail < 20) continue;
      sport = be16(l4);
      dport = be16(l4 + 2);
      l4_hdr = static_cast<size_t>(l4[12] >> 4) * 4;
      if (l4_hdr < 20 || l4_avail < l4_hdr) continue;
      flags = l4[13];
      window = be16(l4 + 14);
    } else if (proto == 17) {  // UDP
      if (l4_avail < 8) continue;
      sport = be16(l4);
      dport = be16(l4 + 2);
      l4_hdr = 8;
    } else {
      continue;  // flow meter consumes TCP/UDP only
    }

    const double payload =
        ip_total > ihl + l4_hdr ? static_cast<double>(ip_total - ihl - l4_hdr)
                                : 0.0;
    double* row = out + static_cast<ptrdiff_t>(n) * PCAP_FIELDS;
    row[0] = static_cast<double>(ts_sec) + ts_frac * g.ts_scale;
    row[1] = src;
    row[2] = dst;
    row[3] = sport;
    row[4] = dport;
    row[5] = proto;
    row[6] = ip_total;
    row[7] = payload;
    row[8] = flags;
    row[9] = window;
    row[10] = static_cast<double>(ihl + l4_hdr);
    row[11] = orig;
    ++n;
  }
  return n;
}

}  // extern "C"
