"""IsotonicRegression — weighted PAVA.

Behavioral spec: upstream ``ml/regression/IsotonicRegression.scala`` [U]
(Spark ML regression breadth): pool-adjacent-violators on
``(feature, label, weight)`` rows sorted by feature, ``isotonic=True``
(increasing, default) or False (antitonic); the model keeps the pooled
``boundaries``/``predictions`` arrays and serves by LINEAR interpolation
between boundaries, clamped outside (Spark's ``predict``).
``featureIndex`` selects the column when ``featuresCol`` is a vector.

Host-side deliberately: PAVA is a sequential pooling scan (Spark runs
its final pass on the driver after a per-partition pre-pool); at the
bench's scales this is a seconds-at-most list-stack pass, the same host-side
exception class as the evaluators' sorted-threshold sweeps
(SURVEY.md §2.4 "on host" rule).  Ties on the feature value are
pre-pooled to their weighted mean, as Spark does.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


def _pava(y: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Weighted pool-adjacent-violators; returns the isotonic fit.

    Python-list block stacks (not numpy scalar indexing — ~10× cheaper
    per element): the O(n) scan handles millions of rows in seconds."""
    ys = y.tolist()
    ws_ = w.tolist()
    vals: list = []
    wts: list = []
    cnts: list = []
    for yi, wi in zip(ys, ws_):
        vals.append(yi)
        wts.append(wi)
        cnts.append(1)
        while len(vals) > 1 and vals[-2] > vals[-1]:
            v1, v0 = vals.pop(), vals[-1]
            w1, w0 = wts.pop(), wts[-1]
            tw = w0 + w1
            vals[-1] = (v0 * w0 + v1 * w1) / tw
            wts[-1] = tw
            c1 = cnts.pop()
            cnts[-1] += c1
    return np.repeat(np.asarray(vals), np.asarray(cnts, np.int64))


class _IsoParams:
    featuresCol = Param("feature column (scalar or vector)",
                        default="features")
    labelCol = Param("target column", default="label")
    predictionCol = Param("output prediction column", default="prediction")
    weightCol = Param("optional row weight column", default=None)
    isotonic = Param("True = increasing, False = decreasing", default=True,
                     validator=validators.is_bool())
    featureIndex = Param("vector column index to regress on", default=0,
                         validator=validators.gteq(0))

    def _feature_values(self, frame: Frame) -> np.ndarray:
        X = frame[self.getFeaturesCol()]
        if X.ndim == 2:
            return np.asarray(X[:, int(self.getFeatureIndex())], np.float64)
        return np.asarray(X, np.float64)


class IsotonicRegression(_IsoParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh  # accepted for API uniformity (host-side fit)

    def _fit(self, frame: Frame) -> "IsotonicRegressionModel":
        x = self._feature_values(frame)
        y = np.asarray(frame[self.getLabelCol()], np.float64)
        wcol = self.getWeightCol()
        w = (
            np.asarray(frame[wcol], np.float64)
            if wcol
            else np.ones_like(y)
        )
        if (w < 0).any():
            raise ValueError("weights must be non-negative")
        keep = w > 0
        x, y, w = x[keep], y[keep], w[keep]
        if not len(x):
            raise ValueError(
                "isotonic fit needs at least one positively-weighted row"
            )
        order = np.argsort(x, kind="stable")
        x, y, w = x[order], y[order], w[order]
        # pre-pool exact feature ties to their weighted mean (Spark)
        ux, first = np.unique(x, return_index=True)
        if len(ux) < len(x):
            wsum = np.add.reduceat(w, first)
            ysum = np.add.reduceat(y * w, first)
            x, y, w = ux, ysum / wsum, wsum
        sign = 1.0 if self.getIsotonic() else -1.0
        fit = sign * _pava(sign * y, w)
        # keep only block boundaries: first/last point of each constant run
        if len(fit):
            change = np.flatnonzero(np.diff(fit) != 0)
            idx = np.unique(np.concatenate(
                [[0], change, change + 1, [len(fit) - 1]]
            ))
        else:
            idx = np.array([], np.int64)
        model = IsotonicRegressionModel(
            boundaries=x[idx], predictions=fit[idx]
        )
        model.setParams(**{
            k: v for k, v in self.paramValues().items()
            if model.hasParam(k)
        })
        return model


class IsotonicRegressionModel(_IsoParams, Model):
    def __init__(self, boundaries=None, predictions=None, **kwargs):
        super().__init__(**kwargs)
        self.boundaries = np.asarray(
            boundaries if boundaries is not None else [], np.float64
        )
        self.predictions = np.asarray(
            predictions if predictions is not None else [], np.float64
        )

    def _save_extra(self):
        return {}, {
            "boundaries": self.boundaries, "predictions": self.predictions,
        }

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(boundaries=arrays["boundaries"],
                predictions=arrays["predictions"])
        m.setParams(**params)
        return m

    def predict(self, x: np.ndarray) -> np.ndarray:
        """Linear interpolation between boundaries, clamped outside
        (Spark ``IsotonicRegressionModel.predict``)."""
        return np.interp(
            np.asarray(x, np.float64), self.boundaries, self.predictions
        )

    def transform(self, frame: Frame) -> Frame:
        return frame.with_column(
            self.getPredictionCol(),
            self.predict(self._feature_values(frame)),
        )
