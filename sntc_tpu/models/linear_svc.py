"""LinearSVC — linear SVM with hinge loss.

Behavioral spec: upstream ``ml/classification/LinearSVC.scala`` +
``ml/optim/aggregator/HingeAggregator.scala`` [U]: binary only; minimize
``Σ wᵢ·max(0, 1 − (2yᵢ−1)·margin) / Σw + regParam·½‖coef‖²`` with LBFGS
(hinge subgradient, exactly Breeze's treatment); features standardized
internally with the penalty kept in the requested space
(``standardization`` flag, LR-style); ``rawPrediction = [−m, +m]``;
``prediction = m > threshold`` on the RAW margin (Spark thresholds raw,
not probability — LinearSVC emits no probability column).

TPU design: the whole fit is the same one-XLA-program shape as
LogisticRegression — a module-level jitted ``minimize_lbfgs`` over
mesh-sharded rows (hinge and its subgradient are elementwise + one
matmul; XLA inserts the gradient all-reduce), margins ride the MXU with
the scaling folded into the weights.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.base import ClassificationModel, ClassifierEstimator
from sntc_tpu.models.summary import BinaryClassificationTrainingSummary
from sntc_tpu.ops.lbfgs import minimize_lbfgs
from sntc_tpu.parallel.collectives import make_tree_aggregate, shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh


@partial(
    jax.jit,
    static_argnames=("fit_intercept", "max_iter", "tol"),
)
def _svc_optimize(
    xs, ys, ws, inv_std, mu, reg, pen_l2, theta0,
    *, fit_intercept, max_iter, tol,
):
    """The whole hinge-LBFGS fit as one cached XLA program (sharded data
    as arguments — compile once, fit many).

    With an intercept the optimization runs on CENTERED+scaled features
    (``mu`` nonzero): a pure reparametrization of the same objective —
    the caller folds the shift back into the exported intercept — but
    vastly better conditioned than Spark's scale-only internal space
    when a feature's mean dwarfs its spread.  Centering happens BEFORE
    the matmul (inside the fused elementwise prologue), because
    ``x·w − μ·w`` computed as two large f32 dot products cancels."""
    d = xs.shape[1]
    w_sum = jnp.sum(ws)

    def value_and_grad(theta):
        def loss_fn(theta):
            coef = theta[:d]
            b = theta[d] if fit_intercept else jnp.zeros((), theta.dtype)
            margins = (xs - mu[None, :]) @ (coef * inv_std) + b
            y_signed = 2.0 * ys.astype(margins.dtype) - 1.0
            hinge = jnp.maximum(0.0, 1.0 - y_signed * margins)
            data = jnp.sum(ws * hinge) / w_sum
            penalty = 0.5 * reg * jnp.sum(pen_l2 * coef**2)
            return data + penalty

        return jax.value_and_grad(loss_fn)(theta)

    return minimize_lbfgs(
        value_and_grad, theta0, max_iter=max_iter, tol=tol,
    )


class _SvcParams:
    regParam = Param("L2 regularization", default=0.0, validator=validators.gteq(0))
    maxIter = Param("max LBFGS iterations", default=100, validator=validators.gt(0))
    tol = Param("convergence tolerance", default=1e-6, validator=validators.gt(0))
    fitIntercept = Param("fit an intercept term", default=True,
                         validator=validators.is_bool())
    standardization = Param(
        "standardize features internally (penalty follows the flag, as in "
        "Spark)", default=True, validator=validators.is_bool())
    threshold = Param(
        "decision threshold applied to the RAW margin (Spark LinearSVC "
        "semantics)", default=0.0)


class LinearSVC(_SvcParams, ClassifierEstimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "LinearSVCModel":
        from sntc_tpu.feature.standard_scaler import standardization_moments

        mesh = self._mesh or get_default_mesh()
        X, y, w = self._extract(frame)
        if len(y) and int(y.max()) > 1:
            raise ValueError(
                "LinearSVC is binary-only (Spark parity); use OneVsRest "
                "for multiclass"
            )
        d = X.shape[1]
        xs, ys, _ = shard_batch(mesh, X, y)
        ws = shard_weights(mesh, w, xs.shape[0])

        # feature moments for internal standardization (one SPMD pass —
        # the same pilot-shifted aggregate StandardScaler uses; raw f32
        # sumsq cancels for large-mean flow features)
        n, mean, var = standardization_moments(
            mesh, xs, ws, X[0] if X.shape[0] else np.zeros(d)
        )
        std = np.sqrt(np.maximum(var, 0.0))
        inv_std = np.divide(
            1.0, std, out=np.ones_like(std), where=std > 0
        ).astype(np.float32)
        # penalty space (Spark): standardization=True penalizes the
        # STANDARDIZED coefficients (theta itself); =False penalizes the
        # original-space coefficients theta*inv_std -> weight by inv_std²
        pen = (
            np.ones(d, np.float32)
            if self.getStandardization()
            else inv_std**2
        )

        fit_b = self.getFitIntercept()
        # centering is a reparametrization ONLY when an intercept absorbs
        # the shift; without one, optimize on raw (scaled) features
        mu_opt = mean.astype(np.float32) if fit_b else np.zeros(d, np.float32)
        theta0 = jnp.zeros((d + 1 if fit_b else d,), jnp.float32)
        res = _svc_optimize(
            xs, ys, ws, jnp.asarray(inv_std), jnp.asarray(mu_opt),
            jnp.float32(self.getRegParam()), jnp.asarray(pen), theta0,
            fit_intercept=fit_b,
            max_iter=int(self.getMaxIter()), tol=float(self.getTol()),
        )
        theta = np.asarray(res.x, np.float64)
        coef = (theta[:d] * inv_std).astype(np.float64)  # original space
        # fold the centering shift back: margin = (x-mu)·coef + b
        intercept = (
            float(theta[d]) - float(mu_opt.astype(np.float64) @ coef)
            if fit_b
            else 0.0
        )
        model = LinearSVCModel(coefficients=coef, intercept=intercept)
        model.setParams(
            **{k2: v for k2, v in self.paramValues().items()
               if model.hasParam(k2)}
        )
        n_it = int(res.n_iters)
        # Spark's LinearSVCTrainingSummary: per-class metrics + threshold
        # curves over the training predictions (binary), lazily computed
        model.summary = BinaryClassificationTrainingSummary(
            np.asarray(res.history)[: n_it + 1], n_it, model, frame,
            labelCol=self.getLabelCol(), mesh=mesh,
        )
        return model


class LinearSVCModel(_SvcParams, ClassificationModel):
    def __init__(self, coefficients: np.ndarray, intercept: float, **kwargs):
        super().__init__(**kwargs)
        self.coefficients = np.asarray(coefficients, np.float64)
        self.coefficients.flags.writeable = False
        self.intercept = float(intercept)
        self.summary = None

    @property
    def num_classes(self) -> int:
        return 2

    def _save_extra(self):
        return {"intercept": self.intercept}, {"coefficients": self.coefficients}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(
            coefficients=arrays["coefficients"],
            intercept=float(extra["intercept"]),
        )
        m.setParams(**params)
        return m

    def _margin(self, X: np.ndarray) -> np.ndarray:
        # C-layout pinned: BLAS accumulates the f64 matvec in stride
        # order, so an F-contiguous feature matrix (the assembler's
        # stacked-.T fast path) rounds ~1e-14 differently than the same
        # values laid out C-contiguously (what a fused segment
        # materializes) — normalize so the margin is layout-invariant
        return (
            np.ascontiguousarray(X, dtype=np.float64) @ self.coefficients
            + self.intercept
        )

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Margin-thresholded labels (the probabilistic base's predict
        goes through probability, which LinearSVC does not define)."""
        return (
            self._margin(np.asarray(X)) > float(self.getThreshold())
        ).astype(np.float64)

    def _raw_predict(self, X: np.ndarray) -> np.ndarray:
        m = self._margin(np.asarray(X))
        return np.stack([-m, m], axis=1)

    def transform(self, frame: Frame) -> Frame:
        """rawPrediction + prediction only — Spark's LinearSVC emits no
        probability column; the threshold applies to the raw margin."""
        X = np.asarray(frame[self.getFeaturesCol()])
        m = self._margin(X)
        out = frame
        if self.getRawPredictionCol():
            out = out.with_column(
                self.getRawPredictionCol(), np.stack([-m, m], axis=1)
            )
        if self.getPredictionCol():
            out = out.with_column(
                self.getPredictionCol(),
                (m > float(self.getThreshold())).astype(np.float64),
            )
        return out
