"""GaussianMixture — full-covariance EM clustering.

Behavioral spec: upstream ``ml/clustering/GaussianMixture.scala`` [U]
(Spark ML clustering breadth alongside KMeans): ``k`` full-covariance
gaussians fit by EM, ``weights``/``gaussians`` (mean, cov) on the model,
``predict`` = argmax posterior, ``probabilityCol`` with the posterior
vector, ``tol`` on the mean log-likelihood change, ``seed``ed init.

TPU design: the WHOLE EM loop is one jitted ``lax.while_loop`` over
mesh-sharded rows.  Per iteration: E-step log-densities via K Cholesky
factorizations of [D, D] covariances (vmapped) + a triangular solve
whose mahalanobis reduction is an MXU contraction; M-step means/scatters
are ``respᵀX`` / weighted ``XᵀX`` einsums.  XLA all-reduces the
row-sums across the mesh — no per-iteration host involvement (Spark
aggregates ExpectationSums through the driver every step).

Deviations (documented): means init from a short run of our own KMeans
(k-means|| seeding + 10 Lloyd steps — sklearn's default; Spark samples
per-cluster subsets, which like plain random points regularly seeds two
means into one cluster) + the pooled diagonal covariance; a ``1e-6``
ridge keeps covariances SPD in f32 (sklearn's ``reg_covar`` default —
Spark has none and can throw on singular covariances).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.summary import TrainingSummary
from sntc_tpu.parallel.collectives import shard_batch
from sntc_tpu.parallel.context import get_default_mesh

_REG = 1e-6


def _log_gaussians(X, means, covs):
    """[N, K] log N(x | mu_k, Sigma_k) via per-component Cholesky."""
    d = X.shape[1]

    def one(mu, cov):
        L = jnp.linalg.cholesky(cov)
        diff = X - mu  # [N, D]
        z = jax.scipy.linalg.solve_triangular(L, diff.T, lower=True)
        maha = jnp.sum(z * z, axis=0)  # [N]
        logdet = 2.0 * jnp.sum(jnp.log(jnp.diagonal(L)))
        return -0.5 * (d * jnp.log(2.0 * jnp.pi) + logdet + maha)

    return jax.vmap(one)(means, covs).T  # [N, K]


@partial(jax.jit, static_argnames=("k", "max_iter"))
def _em(xs, ws, means0, covs0, weights0, *, k, max_iter, tol):
    """Full EM as one program; returns (means, covs, weights, n_iter,
    mean log-likelihood)."""
    n_eff = jnp.maximum(jnp.sum(ws), 1e-12)

    def e_step(means, covs, weights):
        logp = _log_gaussians(xs, means, covs) + jnp.log(weights)[None, :]
        norm = jax.scipy.special.logsumexp(logp, axis=1)  # [N]
        resp = jnp.exp(logp - norm[:, None]) * ws[:, None]
        loglik = jnp.sum(norm * ws) / n_eff
        return resp, loglik

    def m_step(resp):
        nk = jnp.maximum(jnp.sum(resp, axis=0), 1e-12)  # [K]
        means = (resp.T @ xs) / nk[:, None]  # [K, D]

        def cov_k(mu, r):
            diff = xs - mu
            s = (diff * r[:, None]).T @ diff  # MXU scatter
            return s

        covs = jax.vmap(cov_k)(means, resp.T) / nk[:, None, None]
        covs = covs + _REG * jnp.eye(xs.shape[1])[None]
        weights = nk / jnp.sum(nk)
        return means, covs, weights

    def cond(state):
        _, _, _, it, prev, delta = state
        return (it < max_iter) & (delta > tol)

    def body(state):
        means, covs, weights, it, prev, _ = state
        resp, loglik = e_step(means, covs, weights)
        means, covs, weights = m_step(resp)
        delta = jnp.abs(loglik - prev)
        return means, covs, weights, it + 1, loglik, delta

    big = jnp.float32(jnp.finfo(jnp.float32).max)
    means, covs, weights, n_iter, loglik, _ = jax.lax.while_loop(
        cond, body,
        (means0, covs0, weights0, jnp.int32(0), -big, big),
    )
    return means, covs, weights, n_iter, loglik


class _GmmParams:
    featuresCol = Param("feature vector column", default="features")
    predictionCol = Param("output cluster-id column", default="prediction")
    probabilityCol = Param("output posterior column", default="probability")
    k = Param("number of components", default=2, validator=validators.gt(1))
    maxIter = Param("max EM iterations", default=100,
                    validator=validators.gt(0))
    tol = Param("mean log-likelihood convergence delta", default=0.01,
                validator=validators.gteq(0))
    seed = Param("init seed", default=0)


class GaussianMixture(_GmmParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "GaussianMixtureModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                f"featuresCol {self.getFeaturesCol()!r} must be a vector "
                "column (use VectorAssembler)"
            )
        X = X.astype(np.float32, copy=False)
        n, d = X.shape
        k = int(self.getK())
        if n < k:
            raise ValueError(f"need at least k={k} rows, have {n}")
        # seed means from a short run of our own KMeans (k-means|| init +
        # a few Lloyd steps) — random-point seeding regularly drops two
        # means into one cluster and EM then converges to that local
        # optimum (sklearn seeds from k-means for the same reason; Spark
        # samples per-component subsets)
        from sntc_tpu.models.kmeans import KMeans

        km = KMeans(
            mesh=mesh, k=k, maxIter=10, seed=self.getSeed(),
            featuresCol=self.getFeaturesCol(),
        ).fit(frame)
        means0 = np.asarray(km.clusterCenters, np.float32)
        pooled = np.diag(np.maximum(X.var(axis=0), _REG)).astype(np.float32)
        covs0 = np.broadcast_to(pooled, (k, d, d)).copy()
        weights0 = np.full(k, 1.0 / k, np.float32)

        xs, ws = shard_batch(mesh, X)
        means, covs, weights, n_iter, loglik = _em(
            xs, ws, jnp.asarray(means0), jnp.asarray(covs0),
            jnp.asarray(weights0),
            k=k, max_iter=int(self.getMaxIter()),
            tol=jnp.float32(self.getTol()),
        )
        model = GaussianMixtureModel(
            weights=np.asarray(weights, np.float64),
            means=np.asarray(means, np.float64),
            covs=np.asarray(covs, np.float64),
        )
        model.setParams(**self.paramValues())
        model.summary = TrainingSummary([float(loglik)], int(n_iter))
        model.summary.logLikelihood = float(loglik)
        return model


@jax.jit
def _gmm_posterior(X, means, covs, weights):
    logp = _log_gaussians(X, means, covs) + jnp.log(weights)[None, :]
    norm = jax.scipy.special.logsumexp(logp, axis=1, keepdims=True)
    return jnp.exp(logp - norm)


class GaussianMixtureModel(_GmmParams, Model):
    def __init__(self, weights=None, means=None, covs=None, **kwargs):
        super().__init__(**kwargs)
        self.weights = np.asarray(
            weights if weights is not None else [], np.float64
        )
        self.means = np.asarray(means if means is not None else [], np.float64)
        self.covs = np.asarray(covs if covs is not None else [], np.float64)
        self.summary: Optional[TrainingSummary] = None

    @property
    def gaussians(self) -> List[Tuple[np.ndarray, np.ndarray]]:
        """[(mean, cov)] per component (Spark ``gaussians``)."""
        return [
            (self.means[i], self.covs[i]) for i in range(len(self.weights))
        ]

    def _save_extra(self):
        return {}, {
            "weights": self.weights, "means": self.means, "covs": self.covs,
        }

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(weights=arrays["weights"], means=arrays["means"],
                covs=arrays["covs"])
        m.setParams(**params)
        return m

    def predictProbability(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(
            _gmm_posterior(
                jnp.asarray(np.asarray(X, np.float32)),
                jnp.asarray(self.means, jnp.float32),
                jnp.asarray(self.covs, jnp.float32),
                jnp.asarray(self.weights, jnp.float32),
            ),
            np.float64,
        )

    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.argmax(self.predictProbability(X), axis=1).astype(
            np.float64
        )

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()].astype(np.float32, copy=False)
        prob = self.predictProbability(X)
        out = frame
        if self.getProbabilityCol():
            out = out.with_column(self.getProbabilityCol(), prob)
        return out.with_column(
            self.getPredictionCol(),
            np.argmax(prob, axis=1).astype(np.float64),
        )
