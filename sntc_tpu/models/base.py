"""Shared classifier plumbing — the ``ProbabilisticClassifier`` analog.

Behavioral spec: Spark's classifier hierarchy (upstream
``ml/classification/{Classifier,ProbabilisticClassifier}.scala`` [U],
SURVEY.md §3.4): every model's ``transform`` appends ``rawPrediction``
(margins), ``probability`` and ``prediction`` (float64 index) columns; binary
models honor ``threshold``.

Subclass models implement ``_raw_predict(X) -> [N, K]`` margins (device
compute, jitted by the subclass) and ``_raw_to_probability``.
"""

from __future__ import annotations

import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


class ClassifierParams:
    featuresCol = Param("feature vector column", default="features")
    labelCol = Param("label index column", default="label")
    predictionCol = Param("output prediction column", default="prediction")
    rawPredictionCol = Param("output margins column", default="rawPrediction")
    probabilityCol = Param("output probability column", default="probability")


class CheckpointParams:
    """Mid-fit checkpoint/resume (SURVEY.md §5.4 — beyond Spark parity)."""

    checkpointInterval = Param(
        "persist optimizer state every N iterations/boosting rounds "
        "(-1 = off); a re-run fit with the same checkpointDir resumes",
        default=-1,
    )
    checkpointDir = Param("directory for mid-fit optimizer state", default=None)


class ClassifierEstimator(ClassifierParams, Estimator):
    """Base estimator: extracts (X, y, w) from the frame."""

    weightCol = Param("optional row weight column", default=None)

    def _extract(self, frame: Frame):
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                f"featuresCol {self.getFeaturesCol()!r} must be a vector "
                "column (use VectorAssembler)"
            )
        X = X.astype(np.float32, copy=False)
        y_raw = frame[self.getLabelCol()].astype(np.float64)
        y = y_raw.astype(np.int32)
        if not np.array_equal(y_raw, y.astype(np.float64)) or (y < 0).any():
            raise ValueError("labelCol must contain non-negative integer indices")
        wcol = self.getWeightCol()
        w = (
            frame[wcol].astype(np.float32)
            if wcol
            else np.ones(len(y), dtype=np.float32)
        )
        return X, y, w


class ClassificationModel(ClassifierParams, Model):
    """Base fitted model: margins -> probability -> prediction columns."""

    threshold = Param(
        "binary decision threshold on P(class 1)",
        default=0.5,
        validator=validators.in_range(0.0, 1.0),
    )
    thresholds = Param(
        "per-class thresholds (length numClasses, at most one zero); "
        "prediction = argmax(probability[k] / thresholds[k]) — Spark "
        "ProbabilisticClassificationModel.probability2prediction",
        default=None,
    )

    @property
    def num_classes(self) -> int:
        raise NotImplementedError

    def _raw_predict(self, X: np.ndarray) -> np.ndarray:
        """Margins [N, K] (K=2 for binary: [-margin, margin], Spark-style)."""
        raise NotImplementedError

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _predict_raw_prob(self, X: np.ndarray):
        """(raw, probability) for a feature matrix.  Subclasses override to
        fuse both into ONE device program (one dispatch per micro-batch on
        the serving hot path [B:11]); the default is the two-step path."""
        raw = self._raw_predict(X)
        return raw, self._raw_to_probability(raw)

    def _prob_to_prediction(self, prob: np.ndarray) -> np.ndarray:
        ts = self.getThresholds()
        if ts is not None:
            ts = np.asarray(ts, np.float64)
            if ts.shape != (prob.shape[1],):
                raise ValueError(
                    f"thresholds length {ts.shape} must equal "
                    f"numClasses {prob.shape[1]}"
                )
            if (ts < 0).any() or (ts == 0).sum() > 1:
                raise ValueError(
                    "thresholds must be non-negative with at most one zero"
                )
            zero = ts == 0
            with np.errstate(divide="ignore", invalid="ignore"):
                scaled = prob / ts
            # Spark: p/0 -> +inf when p > 0; a 0/0 class never wins
            scaled = np.where(
                zero[None, :],
                np.where(prob > 0, np.inf, -np.inf),
                scaled,
            )
            return np.argmax(scaled, axis=1).astype(np.float64)
        if self.num_classes == 2:
            t = self.getThreshold()
            return (prob[:, 1] > t).astype(np.float64)
        return np.argmax(prob, axis=1).astype(np.float64)

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()].astype(np.float32, copy=False)
        raw, prob = self._predict_raw_prob(X)
        out = frame
        if self.getRawPredictionCol():
            out = out.with_column(self.getRawPredictionCol(), raw)
        if self.getProbabilityCol():
            out = out.with_column(self.getProbabilityCol(), prob)
        if self.getPredictionCol():
            out = out.with_column(
                self.getPredictionCol(), self._prob_to_prediction(prob)
            )
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Convenience: prediction indices for a raw feature matrix."""
        prob = self._raw_to_probability(self._raw_predict(X))
        return self._prob_to_prediction(prob)
