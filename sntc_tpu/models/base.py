"""Shared classifier plumbing — the ``ProbabilisticClassifier`` analog.

Behavioral spec: Spark's classifier hierarchy (upstream
``ml/classification/{Classifier,ProbabilisticClassifier}.scala`` [U],
SURVEY.md §3.4): every model's ``transform`` appends ``rawPrediction``
(margins), ``probability`` and ``prediction`` (float64 index) columns; binary
models honor ``threshold``.

Subclass models implement ``_raw_predict(X) -> [N, K]`` margins (device
compute, jitted by the subclass) and ``_raw_to_probability``.
"""

from __future__ import annotations

import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


class ClassifierParams:
    featuresCol = Param("feature vector column", default="features")
    labelCol = Param("label index column", default="label")
    predictionCol = Param("output prediction column", default="prediction")
    rawPredictionCol = Param("output margins column", default="rawPrediction")
    probabilityCol = Param("output probability column", default="probability")


class CheckpointParams:
    """Mid-fit checkpoint/resume (SURVEY.md §5.4 — beyond Spark parity)."""

    checkpointInterval = Param(
        "persist optimizer state every N iterations/boosting rounds "
        "(-1 = off); a re-run fit with the same checkpointDir resumes",
        default=-1,
    )
    checkpointDir = Param("directory for mid-fit optimizer state", default=None)


class ClassifierEstimator(ClassifierParams, Estimator):
    """Base estimator: extracts (X, y, w) from the frame."""

    weightCol = Param("optional row weight column", default=None)

    def _extract(self, frame: Frame):
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                f"featuresCol {self.getFeaturesCol()!r} must be a vector "
                "column (use VectorAssembler)"
            )
        X = X.astype(np.float32, copy=False)
        y_raw = frame[self.getLabelCol()].astype(np.float64)
        y = y_raw.astype(np.int32)
        if not np.array_equal(y_raw, y.astype(np.float64)) or (y < 0).any():
            raise ValueError("labelCol must contain non-negative integer indices")
        wcol = self.getWeightCol()
        w = (
            frame[wcol].astype(np.float32)
            if wcol
            else np.ones(len(y), dtype=np.float32)
        )
        return X, y, w


def pack_serve_outputs(raw, prob, thr, mode: str):
    """Traceable tail shared by every model's fused serve program:
    probability→prediction under ``mode`` (see ``_threshold_mode``), then
    raw|prob|prediction packed into ONE ``[N, 2K+1]`` array so a serving
    micro-batch costs a single device→host transfer."""
    import jax.numpy as jnp

    if mode == "thresholds":
        zero = thr == 0
        scaled = prob / jnp.where(zero, 1.0, thr)[None, :]
        scaled = jnp.where(
            zero[None, :],
            jnp.where(prob > 0, jnp.inf, -jnp.inf),
            scaled,
        )
        pred = jnp.argmax(scaled, axis=1)
    elif mode == "binary":
        pred = (prob[:, 1] > thr[0]).astype(jnp.int32)
    else:
        pred = jnp.argmax(prob, axis=1)
    return jnp.concatenate(
        [raw, prob, pred[:, None].astype(raw.dtype)], axis=1
    )


class ClassificationModel(ClassifierParams, Model):
    """Base fitted model: margins -> probability -> prediction columns."""

    threshold = Param(
        "binary decision threshold on P(class 1)",
        default=0.5,
        validator=validators.in_range(0.0, 1.0),
    )
    thresholds = Param(
        "per-class thresholds (length numClasses, at most one zero); "
        "prediction = argmax(probability[k] / thresholds[k]) — Spark "
        "ProbabilisticClassificationModel.probability2prediction",
        default=None,
    )

    @property
    def num_classes(self) -> int:
        raise NotImplementedError

    def _raw_predict(self, X: np.ndarray) -> np.ndarray:
        """Margins [N, K] (K=2 for binary: [-margin, margin], Spark-style)."""
        raise NotImplementedError

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _predict_raw_prob(self, X: np.ndarray):
        """(raw, probability) for a feature matrix.  Subclasses override to
        fuse both into ONE device program (one dispatch per micro-batch on
        the serving hot path [B:11]); the default is the two-step path."""
        raw = self._raw_predict(X)
        return raw, self._raw_to_probability(raw)

    def _prob_to_prediction(self, prob: np.ndarray) -> np.ndarray:
        # one rule + one validation: _threshold_mode (shared with the
        # fused device serve programs)
        mode, thr = self._threshold_mode()
        if mode == "thresholds":
            ts = thr.astype(np.float64)
            zero = ts == 0
            scaled = prob / np.where(zero, 1.0, ts)
            # Spark: p/0 -> +inf when p > 0; a 0/0 class never wins
            scaled = np.where(
                zero[None, :],
                np.where(prob > 0, np.inf, -np.inf),
                scaled,
            )
            return np.argmax(scaled, axis=1).astype(np.float64)
        if mode == "binary":
            return (prob[:, 1] > thr[0]).astype(np.float64)
        return np.argmax(prob, axis=1).astype(np.float64)

    def _build_output(self, frame: Frame, raw, prob) -> Frame:
        out = frame
        if self.getRawPredictionCol():
            out = out.with_column(self.getRawPredictionCol(), raw)
        if self.getProbabilityCol():
            out = out.with_column(self.getProbabilityCol(), prob)
        if self.getPredictionCol():
            out = out.with_column(
                self.getPredictionCol(), self._prob_to_prediction(prob)
            )
        return out

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()].astype(np.float32, copy=False)
        rp = (
            self._predict_raw_prob_host(X)
            if X.shape[0] <= self._host_serve_rows()
            else None
        )
        if rp is None:
            rp = self._predict_raw_prob(X)
        return self._build_output(frame, *rp)

    def _threshold_mode(self):
        """(mode, thr) describing the probability→prediction rule, with
        the same validation as :meth:`_prob_to_prediction` — ``mode`` is a
        static program variant, ``thr`` its parameter vector."""
        ts = self.getThresholds()
        if ts is not None:
            ts = np.asarray(ts, np.float64)
            if ts.shape != (self.num_classes,):
                raise ValueError(
                    f"thresholds length {ts.shape} must equal "
                    f"numClasses {self.num_classes}"
                )
            if (ts < 0).any() or (ts == 0).sum() > 1:
                raise ValueError(
                    "thresholds must be non-negative with at most one zero"
                )
            return "thresholds", ts.astype(np.float32)
        if self.num_classes == 2:
            return "binary", np.asarray([self.getThreshold()], np.float32)
        return "argmax", np.zeros(1, np.float32)

    def _predict_all_dev(self, X: np.ndarray):
        """Optional one-dispatch device path: a PACKED ``[N, 2K+1]`` device
        array of ``raw | prob | prediction`` columns (one device→host
        transfer materializes everything), or None when this model has no
        fused device program (callers fall back to the sync transform)."""
        return None

    def has_device_serve(self) -> bool:
        """True when ``_predict_all_dev`` returns a real packed program
        for THIS model — the static capability the fusion planner
        (``sntc_tpu.fuse``) checks before fusing a head into a segment.
        Subclasses whose device path is conditional (e.g. gaussian
        NaiveBayes) must override; ``_predict_all_dev`` must never
        return None when this returns True."""
        return (
            type(self)._predict_all_dev
            is not ClassificationModel._predict_all_dev
        )

    def _predict_raw_prob_host(self, X: np.ndarray):
        """Optional pure-host (numpy) predict path, or None.  Used for
        micro-batches below the host-serve crossover: at small batch sizes
        the device dispatch + transfer round trip (a full network RTT on a
        tunneled TPU; still dominant on PCIe at a few thousand rows of a
        tiny model) dwarfs the FLOPs."""
        return None

    @staticmethod
    def _host_serve_rows() -> int:
        import os

        return int(os.environ.get("SNTC_SERVE_HOST_ROWS", 16384))

    def transform_async(self, frame: Frame):
        """One fused device dispatch; host materialization deferred to the
        returned finalize (see Transformer.transform_async).  Small
        micro-batches take the pure-host path instead WHEN the model has
        one (no device round trip at all; ``transform`` applies the same
        placement rule) — models without a host path keep the fused
        async dispatch at every batch size."""
        X = frame[self.getFeaturesCol()].astype(np.float32, copy=False)
        if X.shape[0] <= self._host_serve_rows():
            rp = self._predict_raw_prob_host(X)
            if rp is not None:
                out = self._build_output(frame, *rp)
                return lambda: out
        dev = self._predict_all_dev(X)
        if dev is None:
            out = self.transform(frame)
            return lambda: out

        def finalize():
            packed = np.asarray(dev)
            k = self.num_classes
            out = frame
            if self.getRawPredictionCol():
                out = out.with_column(
                    self.getRawPredictionCol(), packed[:, :k]
                )
            if self.getProbabilityCol():
                out = out.with_column(
                    self.getProbabilityCol(), packed[:, k : 2 * k]
                )
            if self.getPredictionCol():
                out = out.with_column(
                    self.getPredictionCol(),
                    packed[:, 2 * k].astype(np.float64),
                )
            return out

        return finalize

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Convenience: prediction indices for a raw feature matrix."""
        prob = self._raw_to_probability(self._raw_predict(X))
        return self._prob_to_prediction(prob)
