"""NaiveBayes — multinomial / complement / bernoulli / gaussian.

Behavioral spec: upstream ``ml/classification/NaiveBayes.scala`` [U]
(all four Spark ``modelType``s):

  * ``multinomial``: θ_cj = log((Σ_c w·x_j + λ) / (Σ_c w·Σ_j x_j + λD));
    raw = x·θ_c + log π_c.  Features must be non-negative.
  * ``complement``  (Spark 3): per-class statistics of the COMPLEMENT
    (all other classes); raw uses the normalized negative complement
    log-probabilities.
  * ``bernoulli``: features must be 0/1; raw = Σ_j [x_j log p + (1−x_j)
    log(1−p)] + log π — folded to one matmul plus a per-class constant.
  * ``gaussian``: per-(class, feature) mean/variance with ε =
    1e-9·max var smoothing; raw = Gaussian log-likelihood + log π.

Priors: the discrete types use Spark's λ-smoothed priors
``log((n_c + λ) / (n + Cλ))`` (sklearn's are unsmoothed — a documented
delta; θ still matches sklearn exactly).  The gaussian type keeps
unsmoothed ``log(n_c / n)`` priors and sklearn's ε = 1e-9·max-global-
variance smoothing so it agrees with the GaussianNB oracle
prediction-for-prediction on flow-scale data (the regression test
locks this).

TPU design: every model type reduces to the per-(feature, class)
weighted moments of ONE SPMD pass (the same aggregate family the ANOVA
selector uses); prediction is one matmul on the MXU plus elementwise
terms, packed into the standard fused serve program.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.base import (
    ClassificationModel,
    ClassifierEstimator,
    pack_serve_outputs,
)
from sntc_tpu.parallel.collectives import make_tree_aggregate, shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh


@lru_cache(maxsize=None)
def _class_moments_agg(mesh, n_classes):
    """One pass: per-class weight, per-(feature, class) Σw·(x−p) and
    Σw·(x−p)² about a pilot row ``p`` — f32 accumulation of raw x²
    catastrophically cancels for large-mean features (flow bytes/s);
    shifting keeps magnitudes O(spread).  Callers reconstruct raw sums
    in f64 where needed (``s = s_shifted + cw·p``)."""

    def moments(xs, ys, w, pilot):
        xs = xs - pilot[None, :]
        oh = jax.nn.one_hot(ys, n_classes, dtype=jnp.float32) * w[:, None]
        return {
            "cw": oh.sum(axis=0),  # [C] weighted class counts
            "s": jnp.einsum("nf,nc->cf", xs, oh),  # [C, F] Σ w (x-p)
            "sq": jnp.einsum("nf,nc->cf", xs * xs, oh),  # Σ w (x-p)²
        }

    return make_tree_aggregate(moments, mesh, replicated_args=(3,))


@lru_cache(maxsize=None)
def _class_sq_about_mean_agg(mesh, n_classes):
    """Second gaussian pass: Σ_c w·(x − μ_c)² with each row deviated
    about ITS OWN class mean (replicated [C, F] arg).  A single-pass
    E[x²]−E[x]² — even pilot-shifted — cancels away small class
    variances when a feature's overall spread is huge (flow durations
    span ~1e8); deviating about the true class mean keeps every term
    O(class spread), the numerically safe two-pass form sklearn uses."""

    def sq(xs, ys, w, mu):
        diff = xs - mu[ys]  # [n, F] about the row's class mean
        oh = jax.nn.one_hot(ys, n_classes, dtype=jnp.float32) * w[:, None]
        return jnp.einsum("nf,nc->cf", diff * diff, oh)

    return make_tree_aggregate(sq, mesh, replicated_args=(3,))


@partial(jax.jit, static_argnames=("mode",))
def _nb_serve(X, theta, bias, thr, *, mode):
    """raw = X @ theta^T + bias (log-joint per class), softmax-in-log for
    probability, packed (one dispatch, one transfer)."""
    raw = X @ theta.T + bias[None, :]
    shifted = raw - raw.max(axis=1, keepdims=True)
    e = jnp.exp(shifted)
    prob = e / e.sum(axis=1, keepdims=True)
    return pack_serve_outputs(raw, prob, thr, mode)


class _NbParams:
    smoothing = Param(
        "additive (Laplace) smoothing λ", default=1.0,
        validator=validators.gteq(0.0),
    )
    modelType = Param(
        "multinomial | complement | bernoulli | gaussian",
        default="multinomial",
        validator=validators.one_of(
            "multinomial", "complement", "bernoulli", "gaussian"
        ),
    )


class NaiveBayes(_NbParams, ClassifierEstimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _validate_features(self, Xh: np.ndarray, mt: str) -> None:
        if mt in ("multinomial", "complement") and (Xh < 0).any():
            raise ValueError(f"{mt} NaiveBayes requires non-negative features")
        if mt == "bernoulli" and not np.isin(Xh, (0.0, 1.0)).all():
            raise ValueError("bernoulli NaiveBayes requires 0/1 features")

    def _with_params(self, model: "NaiveBayesModel") -> "NaiveBayesModel":
        model.setParams(
            **{k2: v for k2, v in self.paramValues().items()
               if model.hasParam(k2)}
        )
        return model

    def _discrete_model(self, cw, s, k, D) -> "NaiveBayesModel":
        """multinomial/complement/bernoulli model from the f64 class
        weights ``cw`` [C] and raw weighted feature sums ``s`` [C, F] —
        the ONE stats→model path shared by the batch fit and
        ``partial_fit`` (the statistics are additive, so both produce
        the same model up to device summation order)."""
        mt = self.getModelType()
        lam = float(self.getSmoothing())
        n = cw.sum()
        log_pi = np.log(np.maximum(cw, 1e-300)) - np.log(max(n, 1e-300))
        # Spark's λ-smoothed prior log((n_c + λ)/(n + Cλ))
        log_pi_smoothed = np.log(cw + lam) - np.log(max(n + k * lam, 1e-300))

        if mt == "multinomial":
            num = s + lam
            den = s.sum(axis=1, keepdims=True) + lam * D
            theta = np.log(num) - np.log(den)  # [C, F]
            bias = log_pi_smoothed
        elif mt == "complement":
            # Spark ComplementNB (Rennie et al.): statistics of all OTHER
            # classes, normalized, negated
            comp = s.sum(axis=0, keepdims=True) - s
            num = comp + lam
            den = comp.sum(axis=1, keepdims=True) + lam * D
            logp = np.log(num) - np.log(den)
            # weight normalization (Spark normalizes per class)
            theta = -logp / np.abs(logp).sum(axis=1, keepdims=True)
            # complement NB drops the class prior (Rennie et al.; both
            # Spark's complementCalculation and sklearn do the same)
            bias = np.zeros_like(log_pi)
        else:  # bernoulli
            p = (s + lam) / (cw[:, None] + 2.0 * lam)  # P(x_j=1 | c)
            logp, log1mp = np.log(p), np.log1p(-p)
            # Σ_j x_j·logp + (1-x_j)·log1mp = x·(logp - log1mp) + Σ log1mp
            theta = logp - log1mp
            bias = log_pi_smoothed + log1mp.sum(axis=1)
        return self._with_params(NaiveBayesModel(
            theta=theta.astype(np.float32), bias=bias.astype(np.float32),
            pi=log_pi, n_classes=k,
        ))

    def _gaussian_model(self, cw, mu, sq_c, k) -> "NaiveBayesModel":
        """gaussian model from class weights, f64 class means, and the
        per-(class, feature) squared deviations about those means
        (``sq_c`` = Σ_c w·(x−μ_c)²) — shared by the batch fit (which
        computes ``sq_c`` in a second device pass) and ``partial_fit``
        (which derives it from the accumulated pilot-shifted moments)."""
        n = cw.sum()
        # gaussian: unsmoothed priors (the sklearn-oracle contract)
        log_pi = np.log(np.maximum(cw, 1e-300)) - np.log(max(n, 1e-300))
        var = sq_c / np.maximum(cw[:, None], 1e-300)
        var = np.maximum(var, 0.0)
        # variance smoothing ε = 1e-9 · largest GLOBAL feature
        # variance (sklearn's var_smoothing semantics — the global
        # variance decomposes as within + between from the class
        # moments; the per-class max differs by ~10× on flow data
        # and shifts every small-variance likelihood)
        if var.size and n > 0:
            mu_bar = (cw[:, None] * mu).sum(axis=0) / n
            between = (cw[:, None] * (mu - mu_bar[None, :]) ** 2).sum(axis=0)
            global_var = (sq_c.sum(axis=0) + between) / n
            eps = 1e-9 * float(global_var.max())
        else:
            eps = 1e-12
        var = var + max(eps, 1e-12)
        return self._with_params(NaiveBayesModel(
            theta=None, bias=None, pi=log_pi,
            gaussian_mu=mu,  # f64: f32 mu at 1e9 scale loses the
            gaussian_var=var,  # class signal the f64 fit computed
            n_classes=k,
        ))

    def _fit(self, frame: Frame) -> "NaiveBayesModel":
        mesh = self._mesh or get_default_mesh()
        X, y, w = self._extract(frame)
        mt = self.getModelType()
        k = max(int(y.max()) + 1 if len(y) else 2, 2)
        D = X.shape[1]

        Xh = np.asarray(X)
        self._validate_features(Xh, mt)

        xs, ys, _ = shard_batch(mesh, X, y)
        ws = shard_weights(mesh, w, xs.shape[0])
        pilot = np.asarray(Xh[0], np.float32) if len(Xh) else np.zeros(D, np.float32)
        m = _class_moments_agg(mesh, k)(xs, ys, ws, jnp.asarray(pilot))
        cw = np.asarray(m["cw"], np.float64)  # [C]
        s_sh = np.asarray(m["s"], np.float64)  # [C, F] about the pilot
        p64 = pilot.astype(np.float64)

        if mt == "gaussian":
            # two-pass: means from the first pass, then deviations about
            # each class's own mean (single-pass variance cancels when a
            # feature's overall spread dwarfs a class's variance)
            mu_sh = s_sh / np.maximum(cw[:, None], 1e-300)
            mu = p64[None, :] + mu_sh
            sq_c = np.asarray(
                _class_sq_about_mean_agg(mesh, k)(
                    xs, ys, ws, jnp.asarray(mu, jnp.float32)
                ),
                np.float64,
            )
            return self._gaussian_model(cw, mu, sq_c, k)
        # raw weighted sums, reconstructed exactly in f64
        s = s_sh + cw[:, None] * p64[None, :]
        return self._discrete_model(cw, s, k, D)

    def partial_fit(self, frame: Frame, state=None, decay: float = 1.0,
                    n_classes: int = None):
        """One incremental update (the streaming-MLlib analog): fold
        this mini-batch's per-(class, feature) device moments into
        ``state`` and return ``(model, state)``.

        The statistics are additive, so ``partial_fit`` over K shards
        matches the batch fit on their concatenation up to f32 device
        summation order (discrete types: θ within ~1e-5 rel).  The
        gaussian variance comes from the accumulated pilot-shifted
        moments via the one-pass shift identity Σw(x−μ)² = Σw(x−p)² −
        n_c(μ−p)² where the batch fit runs a second pass about the
        class means — same statistic, looser rounding (documented
        tolerance in docs/RESILIENCE.md "Model lifecycle").  ``decay``
        < 1 down-weights history per update (forgetful streaming).
        The class count and feature width are FIXED by the first call —
        pass ``n_classes`` there when the label universe is known (a
        mini-batch rarely carries every class; the lifecycle layer
        passes the incumbent's count) — and a later shard introducing
        an out-of-range class raises."""
        from sntc_tpu.lifecycle.incremental import NBPartialFitState

        mesh = self._mesh or get_default_mesh()
        X, y, w = self._extract(frame)
        mt = self.getModelType()
        Xh = np.asarray(X)
        self._validate_features(Xh, mt)
        if state is None:
            k = max(int(y.max()) + 1 if len(y) else 2, 2)
            if n_classes is not None:
                if k > int(n_classes):
                    raise ValueError(
                        f"label {int(y.max())} outside the declared "
                        f"n_classes={int(n_classes)}"
                    )
                k = max(int(n_classes), 2)
            pilot = (
                np.asarray(Xh[0], np.float32)
                if len(Xh)
                else np.zeros(X.shape[1], np.float32)
            )
            state = NBPartialFitState(
                n_classes=k, n_features=X.shape[1], pilot=pilot
            )
        else:
            if X.shape[1] != state.n_features:
                raise ValueError(
                    f"partial_fit feature width {X.shape[1]} != state's "
                    f"{state.n_features}"
                )
            if len(y) and int(y.max()) >= state.n_classes:
                raise ValueError(
                    f"label {int(y.max())} outside the class set fixed "
                    f"at the first partial_fit call ({state.n_classes} "
                    "classes)"
                )
        xs, ys, _ = shard_batch(mesh, X, y)
        ws = shard_weights(mesh, w, xs.shape[0])
        m = _class_moments_agg(mesh, state.n_classes)(
            xs, ys, ws, jnp.asarray(state.pilot)
        )
        state.update(
            np.asarray(m["cw"], np.float64),
            np.asarray(m["s"], np.float64),
            np.asarray(m["sq"], np.float64),
            n_rows=len(y), decay=decay,
        )
        return self._model_from_state(state), state

    def _model_from_state(self, state) -> "NaiveBayesModel":
        cw, s_sh, sq_sh = state.cw, state.s_sh, state.sq_sh
        p64 = state.pilot.astype(np.float64)
        k = state.n_classes
        if self.getModelType() == "gaussian":
            mu_sh = s_sh / np.maximum(cw[:, None], 1e-300)
            mu = p64[None, :] + mu_sh
            # one-pass shift identity: Σw(x−μ_c)² = Σw(x−p)² − n_c(μ_c−p)²
            sq_c = np.maximum(sq_sh - cw[:, None] * mu_sh**2, 0.0)
            return self._gaussian_model(cw, mu, sq_c, k)
        s = s_sh + cw[:, None] * p64[None, :]
        return self._discrete_model(cw, s, k, state.n_features)


def _gaussian_raw(X, mu, var, log_pi):
    """[N, C]: -0.5 Σ_j (log 2πσ² + (x-μ)²/σ²) + log π.

    Host f64 deliberately: with 78 features spanning ~12 decades and
    near-tied classes, f32 likelihood sums flip argmax on a large
    fraction of rows (measured ~50% disagreement vs the f64 oracle on
    flow data).  Devices run f32 by default (no global x64), and NB
    prediction is two small matmuls — f64 on host is the accurate and
    cheap choice."""
    X = np.asarray(X, np.float64)  # [N, F]
    mu = np.asarray(mu, np.float64)
    var = np.asarray(var, np.float64)
    C = mu.shape[0]
    ll = np.empty((X.shape[0], C), np.float64)
    # per-class loop keeps peak memory at O(N·F), not O(N·C·F) — the
    # full broadcast would be ~26 GB f64 at CICIDS scale (2.8M×15×78)
    for c in range(C):
        ll[:, c] = -0.5 * (
            np.log(2.0 * np.pi * var[c]) + (X - mu[c]) ** 2 / var[c]
        ).sum(axis=1)
    return ll + np.asarray(log_pi, np.float64)[None, :]


class NaiveBayesModel(_NbParams, ClassificationModel):
    def __init__(
        self,
        theta=None,
        bias=None,
        pi=None,
        gaussian_mu=None,
        gaussian_var=None,
        n_classes: int = 2,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.theta = None if theta is None else np.asarray(theta, np.float32)
        self.bias = None if bias is None else np.asarray(bias, np.float32)
        self.pi = np.asarray(pi, np.float64) if pi is not None else None
        self.gaussian_mu = (
            None if gaussian_mu is None else np.asarray(gaussian_mu, np.float64)
        )
        self.gaussian_var = (
            None if gaussian_var is None else np.asarray(gaussian_var, np.float64)
        )
        self._n_classes = int(n_classes)

    @property
    def num_classes(self) -> int:
        return self._n_classes

    def _save_extra(self):
        arrays = {"pi": self.pi}
        if self.theta is not None:
            arrays["theta"] = self.theta
            arrays["bias"] = self.bias
        if self.gaussian_mu is not None:
            arrays["gaussian_mu"] = self.gaussian_mu
            arrays["gaussian_var"] = self.gaussian_var
        return {"n_classes": self._n_classes}, arrays

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(
            theta=arrays.get("theta"),
            bias=arrays.get("bias"),
            pi=arrays.get("pi"),
            gaussian_mu=arrays.get("gaussian_mu"),
            gaussian_var=arrays.get("gaussian_var"),
            n_classes=int(extra["n_classes"]),
        )
        m.setParams(**params)
        return m

    def _raw_predict(self, X: np.ndarray) -> np.ndarray:
        if self.getModelType() == "gaussian":
            return _gaussian_raw(
                X, self.gaussian_mu, self.gaussian_var, self.pi
            )
        X = jnp.asarray(X, jnp.float32)
        return np.asarray(
            X @ jnp.asarray(self.theta).T + jnp.asarray(self.bias)[None, :]
        )

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        shifted = raw - raw.max(axis=1, keepdims=True)
        e = np.exp(shifted)
        return e / e.sum(axis=1, keepdims=True)

    def has_device_serve(self) -> bool:
        # the gaussian log-likelihood runs in float64 on host (class
        # variances cancel in f32) — no packed device program to fuse
        return self.getModelType() != "gaussian"

    def _predict_all_dev(self, X: np.ndarray):
        if self.getModelType() == "gaussian":
            return None  # host fallback path builds the columns
        mode, thr = self._threshold_mode()
        return _nb_serve(
            jnp.asarray(X, jnp.float32),
            jnp.asarray(self.theta),
            jnp.asarray(self.bias),
            jnp.asarray(thr),
            mode=mode,
        )
