"""PowerIterationClustering — Lin & Cohen PIC over a similarity graph.

Behavioral spec: upstream ``ml/clustering/PowerIterationClustering.scala``
→ ``mllib/clustering/PowerIterationClustering.scala`` [U]: the input is an
edge list (``srcCol``, ``dstCol``, optional ``weightCol``, similarities
≥ 0, treated undirected), ``k``, ``maxIter``, ``initMode`` random |
degree; ``assignClusters`` returns an (id, cluster) frame.  Algorithm:
power-iterate ``v ← D⁻¹ A v`` (L1-normalized each step, stopping on the
acceleration criterion), then k-means the resulting 1-D embedding.

TPU design: the edge list shards over the mesh, and one power-iteration
step is a per-shard ``segment_sum`` mat-vec completed by a ``psum`` —
the whole iteration loop runs as a single XLA program inside
``lax.while_loop`` with ``v`` replicated (no per-step host hops; Spark's
per-iteration VertexRDD shuffle is one collective).  The final 1-D
embedding is clustered by the sharded KMeans Lloyd program.  Mirrored
edges are materialized once (Spark normalizes the same way in its graph
construction).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from sntc_tpu.parallel.mesh import map_at, payload_nbytes, record_collective
from sntc_tpu.core.base import Params
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.kmeans import KMeans


@lru_cache(maxsize=None)
def _power_iterate_sharded(mesh, n, max_iter):
    """The full PIC loop as one XLA program over MESH-SHARDED edges.

    ``v ← normalize₁(D⁻¹ A v)`` with the mllib stopping rule: stop when
    the ACCELERATION ‖(v_t − v_{t-1}) − (v_{t-1} − v_{t-2})‖∞ drops
    below 1e-5 / n [U].  Each shard ``segment_sum``s its edge slice of
    the mat-vec; ``psum`` completes it — the whole iteration loop stays
    on-device with ``v`` replicated (n floats).  ``wm`` masks the
    padding edges (shard_batch replicates a real edge into them)."""
    axis = mesh.axis_names[0]
    tol = 1e-5 / max(n, 1)

    def local(src, dst, w, wm, v0):
        wmk = w * wm
        deg = jax.lax.psum(
            jax.ops.segment_sum(wmk, src, num_segments=n), axis
        )
        inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1e-30), 0.0)

        def step(state):
            v, prev_delta, _, it = state
            av = jax.lax.psum(
                jax.ops.segment_sum(wmk * v[dst], src, num_segments=n),
                axis,
            )
            nv = inv_deg * av
            nv = nv / jnp.maximum(jnp.abs(nv).sum(), 1e-30)
            delta = jnp.abs(nv - v).max()
            accel = jnp.abs(delta - prev_delta)
            return nv, delta, accel, it + 1

        def cond(state):
            _, _, accel, it = state
            return jnp.logical_and(it < max_iter, accel > tol)

        v0 = v0 / jnp.maximum(jnp.abs(v0).sum(), 1e-30)
        init = (
            v0,
            jnp.asarray(jnp.inf, jnp.float32),
            jnp.asarray(jnp.inf, jnp.float32),
            jnp.asarray(0, jnp.int32),
        )
        v, _, _, it = jax.lax.while_loop(cond, step, init)
        return v, it

    return map_at(
        mesh, local,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(), P()),
    )


class PowerIterationClustering(Params):
    """Not an Estimator/Model pair — like Spark, PIC is a one-shot
    ``assignClusters`` over an edge frame [U]."""

    srcCol = Param("source vertex id column", default="src")
    dstCol = Param("destination vertex id column", default="dst")
    weightCol = Param("optional similarity column (default 1.0)",
                      default=None)
    k = Param("number of clusters", default=2, validator=validators.gt(1))
    maxIter = Param("max power iterations", default=20,
                    validator=validators.gt(0))
    initMode = Param(
        "random | degree", default="random",
        validator=validators.one_of("random", "degree"),
    )
    seed = Param("random seed", default=0)

    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def assignClusters(self, frame: Frame) -> Frame:
        src = np.asarray(frame[self.getSrcCol()]).astype(np.int64)
        dst = np.asarray(frame[self.getDstCol()]).astype(np.int64)
        wcol = self.getWeightCol()
        w = (
            np.asarray(frame[wcol], np.float64)
            if wcol else np.ones(len(src), np.float64)
        )
        if np.any(w < 0):
            raise ValueError("similarities must be non-negative (Spark)")
        if np.any(src == dst):
            # mllib rejects self-similarity edges (diagonal must be 0)
            raise ValueError("self-loop edges (src == dst) are not allowed")
        # compact ids -> [0, n); result reports the ORIGINAL ids
        ids = np.unique(np.concatenate([src, dst]))
        lut = {int(v): i for i, v in enumerate(ids)}
        s = np.fromiter((lut[int(v)] for v in src), np.int32, len(src))
        d = np.fromiter((lut[int(v)] for v in dst), np.int32, len(dst))
        n = len(ids)
        # undirected: mirror every edge (Spark's graph construction)
        s2 = np.concatenate([s, d])
        d2 = np.concatenate([d, s])
        w2 = np.concatenate([w, w]).astype(np.float32)

        rng = np.random.default_rng(self.getSeed())
        if self.getInitMode() == "degree":
            deg = np.bincount(s2, weights=w2, minlength=n)
            v0 = (deg / max(deg.sum(), 1e-30)).astype(np.float32)
        else:
            # mllib random init: uniform in [0, 1), centered implicitly by
            # the L1 normalization inside the loop
            v0 = rng.random(n).astype(np.float32)

        from sntc_tpu.parallel.collectives import shard_batch
        from sntc_tpu.parallel.context import get_default_mesh

        mesh = self._mesh or get_default_mesh()
        ss, dd, ww, wm = shard_batch(mesh, s2, d2, w2)
        v, it = _power_iterate_sharded(
            mesh, n, int(self.getMaxIter())
        )(ss, dd, ww, wm, jnp.asarray(v0))
        axis = mesh.axis_names[0]
        record_collective(
            "pic.power", axis, mesh.shape[axis], payload_nbytes((v, it))
        )
        v = np.asarray(v, np.float64)

        km = KMeans(
            mesh=self._mesh, k=int(self.getK()), seed=int(self.getSeed()),
            maxIter=40,
        ).fit(Frame({"features": v[:, None].astype(np.float32)}))
        assign = km.predict(v[:, None])
        return Frame({
            "id": ids.astype(np.int64),
            "cluster": assign.astype(np.int64),
        })