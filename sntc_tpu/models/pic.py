"""PowerIterationClustering — Lin & Cohen PIC over a similarity graph.

Behavioral spec: upstream ``ml/clustering/PowerIterationClustering.scala``
→ ``mllib/clustering/PowerIterationClustering.scala`` [U]: the input is an
edge list (``srcCol``, ``dstCol``, optional ``weightCol``, similarities
≥ 0, treated undirected), ``k``, ``maxIter``, ``initMode`` random |
degree; ``assignClusters`` returns an (id, cluster) frame.  Algorithm:
power-iterate ``v ← D⁻¹ A v`` (L1-normalized each step, stopping on the
acceleration criterion), then k-means the resulting 1-D embedding.

TPU design: one power-iteration step is ONE jitted ``segment_sum``
mat-vec over the device-resident COO edge list inside a
``lax.while_loop`` (the whole iteration loop is a single XLA program —
no per-step host hops); the final 1-D embedding is clustered by the
sharded KMeans Lloyd program.  Mirrored edges are materialized once
(Spark normalizes the same way in its graph construction).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Params
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.kmeans import KMeans


@partial(jax.jit, static_argnames=("n", "max_iter"))
def _power_iterate(src, dst, w, v0, *, n, max_iter):
    """The full PIC loop as one XLA program.

    ``v ← normalize₁(D⁻¹ A v)`` with the mllib stopping rule: stop when
    the ACCELERATION ‖(v_t − v_{t-1}) − (v_{t-1} − v_{t-2})‖∞ drops
    below 1e-5 / n [U]."""
    deg = jax.ops.segment_sum(w, src, num_segments=n)
    inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1e-30), 0.0)
    tol = jnp.float32(1e-5 / max(n, 1))

    def step(state):
        v, prev_delta, _, it = state
        av = jax.ops.segment_sum(w * v[dst], src, num_segments=n)
        nv = inv_deg * av
        nv = nv / jnp.maximum(jnp.abs(nv).sum(), 1e-30)
        delta = jnp.abs(nv - v).max()
        accel = jnp.abs(delta - prev_delta)
        return nv, delta, accel, it + 1

    def cond(state):
        _, _, accel, it = state
        return jnp.logical_and(it < max_iter, accel > tol)

    v0 = v0 / jnp.maximum(jnp.abs(v0).sum(), 1e-30)
    init = (
        v0,
        jnp.asarray(jnp.inf, jnp.float32),
        jnp.asarray(jnp.inf, jnp.float32),
        jnp.asarray(0, jnp.int32),
    )
    v, _, _, it = jax.lax.while_loop(cond, step, init)
    return v, it


class PowerIterationClustering(Params):
    """Not an Estimator/Model pair — like Spark, PIC is a one-shot
    ``assignClusters`` over an edge frame [U]."""

    srcCol = Param("source vertex id column", default="src")
    dstCol = Param("destination vertex id column", default="dst")
    weightCol = Param("optional similarity column (default 1.0)",
                      default=None)
    k = Param("number of clusters", default=2, validator=validators.gt(1))
    maxIter = Param("max power iterations", default=20,
                    validator=validators.gt(0))
    initMode = Param(
        "random | degree", default="random",
        validator=validators.one_of("random", "degree"),
    )
    seed = Param("random seed", default=0)

    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def assignClusters(self, frame: Frame) -> Frame:
        src = np.asarray(frame[self.getSrcCol()]).astype(np.int64)
        dst = np.asarray(frame[self.getDstCol()]).astype(np.int64)
        wcol = self.getWeightCol()
        w = (
            np.asarray(frame[wcol], np.float64)
            if wcol else np.ones(len(src), np.float64)
        )
        if np.any(w < 0):
            raise ValueError("similarities must be non-negative (Spark)")
        if np.any(src == dst):
            # mllib rejects self-similarity edges (diagonal must be 0)
            raise ValueError("self-loop edges (src == dst) are not allowed")
        # compact ids -> [0, n); result reports the ORIGINAL ids
        ids = np.unique(np.concatenate([src, dst]))
        lut = {int(v): i for i, v in enumerate(ids)}
        s = np.fromiter((lut[int(v)] for v in src), np.int32, len(src))
        d = np.fromiter((lut[int(v)] for v in dst), np.int32, len(dst))
        n = len(ids)
        # undirected: mirror every edge (Spark's graph construction)
        s2 = np.concatenate([s, d])
        d2 = np.concatenate([d, s])
        w2 = np.concatenate([w, w]).astype(np.float32)

        rng = np.random.default_rng(self.getSeed())
        if self.getInitMode() == "degree":
            deg = np.bincount(s2, weights=w2, minlength=n)
            v0 = (deg / max(deg.sum(), 1e-30)).astype(np.float32)
        else:
            # mllib random init: uniform in [0, 1), centered implicitly by
            # the L1 normalization inside the loop
            v0 = rng.random(n).astype(np.float32)

        v, _ = _power_iterate(
            jnp.asarray(s2), jnp.asarray(d2), jnp.asarray(w2),
            jnp.asarray(v0), n=n, max_iter=int(self.getMaxIter()),
        )
        v = np.asarray(v, np.float64)

        km = KMeans(
            mesh=self._mesh, k=int(self.getK()), seed=int(self.getSeed()),
            maxIter=40,
        ).fit(Frame({"features": v[:, None].astype(np.float32)}))
        assign = km.predict(v[:, None])
        return Frame({
            "id": ids.astype(np.int64),
            "cluster": assign.astype(np.int64),
        })