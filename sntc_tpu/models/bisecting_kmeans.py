"""BisectingKMeans — divisive hierarchical clustering.

Behavioral spec: upstream ``ml/clustering/BisectingKMeans.scala`` →
``mllib/clustering/BisectingKMeans.scala`` [U]: start from one root
cluster and repeatedly bisect divisible leaves with a local 2-means
(``maxIter`` Lloyd steps per split, split centers = parent ± tiny seeded
perturbation) until ``k`` leaves; ``minDivisibleClusterSize`` (≥1 →
absolute count, <1 → fraction of rows) gates which leaves may split, so
the result can hold FEWER than ``k`` clusters (Spark documents the same);
``predict`` descends the binary tree root→leaf by nearest child center
(NOT flat nearest-leaf-center — border points follow the tree).

Documented delta: Spark bisects all divisible leaves of a level together,
preferring larger ones when over budget; here the largest divisible leaf
splits per round (sklearn's ``largest_cluster`` strategy) — the same tree
whenever size order is unambiguous, and always the same leaf-count
semantics.

TPU design: every bisection reuses ONE compiled sharded Lloyd program
(`kmeans._lloyd_sharded` with k=2) at the STATIC full-data shape —
cluster membership rides the weight vector (non-members get weight 0, the
framework's masked-row idiom), so splitting never re-pads, re-shards, or
recompiles.  The host drives only the tiny tree loop (≤ k−1 splits).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.kmeans import (
    _lloyd_sharded,
    _normalize_rows,
    _sq_dists,
)
from sntc_tpu.models.summary import TrainingSummary
from sntc_tpu.parallel.collectives import shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh


class _BisectingParams:
    featuresCol = Param("input vector column", default="features")
    predictionCol = Param("output cluster column", default="prediction")
    k = Param("desired number of leaf clusters", default=4,
              validator=validators.gt(1))
    maxIter = Param("Lloyd steps per bisection", default=20,
                    validator=validators.gt(0))
    minDivisibleClusterSize = Param(
        "min size for a leaf to be split (>=1: count, <1: fraction)",
        default=1.0, validator=validators.gt(0),
    )
    distanceMeasure = Param(
        "euclidean | cosine", default="euclidean",
        validator=validators.one_of("euclidean", "cosine"),
    )
    seed = Param("random seed", default=0)


class BisectingKMeans(_BisectingParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "BisectingKMeansModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                f"featuresCol {self.getFeaturesCol()!r} must be a vector "
                "column (use VectorAssembler)"
            )
        X = np.asarray(X, np.float32)
        n = X.shape[0]
        k = int(self.getK())
        cosine = self.getDistanceMeasure() == "cosine"
        Xw = _normalize_rows(X).astype(np.float32) if cosine else X
        mds = float(self.getMinDivisibleClusterSize())
        min_size = mds if mds >= 1.0 else mds * n
        rng = np.random.default_rng(self.getSeed())

        xs, base_w = shard_batch(mesh, Xw)
        n_pad = xs.shape[0]
        lloyd2 = _lloyd_sharded(mesh, 2, int(self.getMaxIter()), cosine)
        tol = jnp.float32(1e-4)

        # tree arrays: center / left / right (-1 = leaf) per node
        centers = [Xw.mean(axis=0)]
        left, right = [-1], [-1]
        # leaf -> boolean membership over rows
        members = {0: np.ones(n, bool)}
        frozen = set()  # leaves whose split degenerated — never retried

        while len(members) < k:
            divisible = [
                (m.sum(), node) for node, m in members.items()
                if node not in frozen and m.sum() >= max(min_size, 2)
            ]
            if not divisible:
                break  # fewer than k clusters — Spark's documented case
            _, node = max(divisible)
            mask = members[node]
            # split centers: parent ± tiny seeded perturbation (Spark's
            # splitCenter [U])
            c = centers[node]
            noise = rng.normal(size=c.shape).astype(np.float32)
            noise *= 1e-4 * max(float(np.linalg.norm(c)), 1e-12) / max(
                float(np.linalg.norm(noise)), 1e-12
            )
            c0 = np.stack([c - noise, c + noise]).astype(np.float32)
            ws = shard_weights(mesh, mask.astype(np.float32), n_pad)
            new_centers, _, _, _ = lloyd2(xs, ws, jnp.asarray(c0), tol)
            new_centers = np.asarray(new_centers, np.float32)
            # final ownership of this split (host: one [M, 2] argmin over
            # the member rows)
            sub = Xw[mask]
            owner = _sq_dists(sub, new_centers, cosine).argmin(axis=1)
            if (owner == 0).all() or (owner == 1).all():
                # degenerate split (all identical points, say): keep the
                # leaf and never retry it
                frozen.add(node)
                continue
            li, ri = len(centers), len(centers) + 1
            centers.extend([new_centers[0], new_centers[1]])
            left.extend([-1, -1])
            right.extend([-1, -1])
            left[node], right[node] = li, ri
            idx = np.nonzero(mask)[0]
            m_l = np.zeros(n, bool)
            m_r = np.zeros(n, bool)
            m_l[idx[owner == 0]] = True
            m_r[idx[owner == 1]] = True
            del members[node]
            members[li], members[ri] = m_l, m_r

        model = BisectingKMeansModel(
            centers=np.asarray(centers, np.float64),
            left=np.asarray(left, np.int64),
            right=np.asarray(right, np.int64),
        )
        model.setParams(**self.paramValues())
        # training cost: Σ distance² (or cosine distance) to assigned leaf
        assign = model.predict(X)
        leaf_centers = model.clusterCenters
        d = _sq_dists(
            _normalize_rows(X.astype(np.float64)) if cosine
            else X.astype(np.float64),
            leaf_centers, cosine,
        )
        cost = float(d[np.arange(n), assign.astype(int)].sum())
        n_splits = (len(centers) - 1) // 2  # bisections performed
        model.summary = TrainingSummary([cost], n_splits)
        model.summary.trainingCost = cost
        return model


class BisectingKMeansModel(_BisectingParams, Model):
    """The fitted binary tree.  ``clusterCenters`` lists LEAF centers in
    discovery order; ``predict`` descends the tree (Spark semantics)."""

    def __init__(self, centers, left, right, **kwargs):
        super().__init__(**kwargs)
        self._centers = np.asarray(centers, np.float64)
        self._left = np.asarray(left, np.int64)
        self._right = np.asarray(right, np.int64)
        leaves = np.nonzero(self._left < 0)[0]
        self._leaf_nodes = leaves
        self._leaf_id = {int(nd): i for i, nd in enumerate(leaves)}
        self.summary = None

    @property
    def clusterCenters(self) -> np.ndarray:
        return self._centers[self._leaf_nodes]

    def _save_extra(self):
        return {}, {
            "centers": self._centers,
            "left": self._left,
            "right": self._right,
        }

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(
            centers=arrays["centers"],
            left=arrays["left"],
            right=arrays["right"],
        )
        m.setParams(**params)
        return m

    def predict(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, np.float64)
        cosine = self.getDistanceMeasure() == "cosine"
        if cosine:
            X = _normalize_rows(X)
        node = np.zeros(len(X), np.int64)
        # vectorized root→leaf descent: depth ≤ #splits
        for _ in range(len(self._centers)):
            internal = self._left[node] >= 0
            if not internal.any():
                break
            idx = np.nonzero(internal)[0]
            l_nodes = self._left[node[idx]]
            r_nodes = self._right[node[idx]]
            if cosine:
                dl = 1.0 - (X[idx] * _normalize_rows(self._centers[l_nodes])).sum(axis=1)
                dr = 1.0 - (X[idx] * _normalize_rows(self._centers[r_nodes])).sum(axis=1)
            else:
                dl = ((X[idx] - self._centers[l_nodes]) ** 2).sum(axis=1)
                dr = ((X[idx] - self._centers[r_nodes]) ** 2).sum(axis=1)
            node[idx] = np.where(dl <= dr, l_nodes, r_nodes)
        return np.array(
            [self._leaf_id[int(v)] for v in node], np.float64
        )

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()]
        return frame.with_column(
            self.getPredictionCol(), self.predict(np.asarray(X))
        )

    def computeCost(self, frame: Frame) -> float:
        X = np.asarray(frame[self.getFeaturesCol()], np.float64)
        cosine = self.getDistanceMeasure() == "cosine"
        if cosine:
            X = _normalize_rows(X)
        assign = self.predict(X).astype(int)
        d = _sq_dists(X, self.clusterCenters, cosine)
        return float(d[np.arange(len(X)), assign].sum())