"""MultilayerPerceptronClassifier — feed-forward ANN on TPU [B:8].

Behavioral spec: SURVEY.md §2.3/§3.3 (upstream
``ml/classification/MultilayerPerceptronClassifier.scala`` + ``ml/ann/Layer``
[U]): ``layers=[in, hidden..., out]`` topology, sigmoid hidden activations,
softmax output with cross-entropy, full-batch LBFGS by default (``solver=
"l-bfgs"``, ``maxIter=100``) or gradient descent (``solver="gd"``), seeded
weight init, optional ``initialWeights`` vector.

TPU design: where Spark stacks ``blockSize`` rows per partition to call JNI
BLAS gemms (§3.3 ⟦JVM→NATIVE⟧), here the whole dataset is device-resident
and the forward/backward chain is XLA ``dot_general`` on the MXU — the
"easiest big win" of SURVEY.md §2.3.  The optimizer is the same jitted
LBFGS as LogisticRegression, data mesh-sharded, gradients all-reduced over
ICI; ``blockSize`` is accepted for API parity (batching is XLA's concern).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.mlio.optimizer_checkpoint import run_segmented
from sntc_tpu.models.base import (
    CheckpointParams,
    ClassificationModel,
    ClassifierEstimator,
)
from sntc_tpu.ops.lbfgs import minimize_lbfgs
from sntc_tpu.parallel.collectives import shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh


def _layer_sizes(layers: Tuple[int, ...]) -> List[Tuple[int, int]]:
    return [(layers[i], layers[i + 1]) for i in range(len(layers) - 1)]


def _n_weights(layers: Tuple[int, ...]) -> int:
    return sum(d_in * d_out + d_out for d_in, d_out in _layer_sizes(layers))


def _unpack(theta: jnp.ndarray, layers: Tuple[int, ...]):
    """Flat vector -> [(W, b), ...] (Spark keeps MLP weights as one vector)."""
    out, off = [], 0
    for d_in, d_out in _layer_sizes(layers):
        W = theta[off : off + d_in * d_out].reshape(d_in, d_out)
        off += d_in * d_out
        b = theta[off : off + d_out]
        off += d_out
        out.append((W, b))
    return out


def _forward(
    theta: jnp.ndarray,
    X: jnp.ndarray,
    layers: Tuple[int, ...],
    compute_dtype=jnp.float32,
):
    """Margins (pre-softmax) of the final layer.

    ``compute_dtype=bfloat16`` feeds the MXU its native input width
    (double the f32 matmul throughput on v5e) while accumulating in f32
    (``preferred_element_type``); activations/params stay f32 elsewhere."""
    h = X
    wbs = _unpack(theta, layers)
    for i, (W, b) in enumerate(wbs):
        z = (
            jax.lax.dot(
                h.astype(compute_dtype),
                W.astype(compute_dtype),
                preferred_element_type=jnp.float32,
            )
            + b[None, :]
        )
        h = jax.nn.sigmoid(z) if i < len(wbs) - 1 else z
    return h


@partial(
    jax.jit,
    static_argnames=(
        "layers", "max_iter", "tol", "solver", "step_size", "resume",
        "compute_dtype",
    ),
)
def _mlp_optimize(
    xs, ys, ws, theta0, init_state, iter_limit,
    *, layers, max_iter, tol, solver, step_size, resume=False,
    compute_dtype=jnp.float32,
):
    w_sum = jnp.sum(ws)

    def value_and_grad(theta):
        def loss_fn(theta):
            margins = _forward(theta, xs, layers, compute_dtype)
            logp = jax.nn.log_softmax(margins, axis=1)
            picked = jnp.take_along_axis(
                logp, ys[:, None].astype(jnp.int32), axis=1
            )[:, 0]
            return -jnp.sum(ws * picked) / w_sum

        return jax.value_and_grad(loss_fn)(theta)

    if solver == "l-bfgs":
        return minimize_lbfgs(
            value_and_grad, theta0, max_iter=max_iter, tol=tol,
            init_state=init_state if resume else None,
            return_state=True, iter_limit=iter_limit,
        )

    # solver == "gd": full-batch gradient descent with constant step
    def gd_step(i, carry):
        theta, hist = carry
        f, g = value_and_grad(theta)
        hist = hist.at[i].set(f)
        return theta - step_size * g, hist

    hist0 = jnp.zeros((max_iter + 1,), theta0.dtype)
    theta, hist = jax.lax.fori_loop(
        0, max_iter, gd_step, (theta0, hist0)
    )
    f_final, _ = value_and_grad(theta)
    hist = hist.at[max_iter].set(f_final)
    from sntc_tpu.ops.lbfgs import LbfgsResult

    return (
        LbfgsResult(
            x=theta,
            loss=f_final,
            n_iters=jnp.asarray(max_iter, jnp.int32),
            history=hist,
            converged=jnp.asarray(True),
        ),
        None,  # gd has no resumable state (mid-fit checkpointing is l-bfgs)
    )


class _MlpParams:
    layers = Param(
        "layer sizes [in, hidden..., out]",
        validator=validators.list_of(lambda v: isinstance(v, (int, np.integer)) and v > 0),
    )
    maxIter = Param("max iterations", default=100, validator=validators.gteq(0))
    tol = Param("relative convergence tolerance", default=1e-6, validator=validators.gt(0))
    seed = Param("weight init seed", default=0)
    solver = Param(
        "l-bfgs | gd", default="l-bfgs", validator=validators.one_of("l-bfgs", "gd")
    )
    stepSize = Param("gd step size", default=0.03, validator=validators.gt(0))
    blockSize = Param(
        "row block size (API parity; XLA handles batching)",
        default=128,
        validator=validators.gt(0),
    )
    computeDtype = Param(
        "matmul input dtype: float32 | bfloat16 (bf16 feeds the MXU its "
        "native width — ~2x f32 throughput on v5e — accumulating in f32; "
        "beyond Spark parity, which is f64 on JVM)",
        default="float32",
        validator=validators.one_of("float32", "bfloat16"),
    )


class MultilayerPerceptronClassifier(_MlpParams, CheckpointParams, ClassifierEstimator):
    def __init__(self, mesh=None, initialWeights: Optional[np.ndarray] = None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh
        self._initial_weights = initialWeights

    def _fit(self, frame: Frame) -> "MultilayerPerceptronClassificationModel":
        mesh = self._mesh or get_default_mesh()
        X, y, w = self._extract(frame)
        layers = tuple(int(v) for v in self.getLayers())
        if X.shape[1] != layers[0]:
            raise ValueError(
                f"layers[0]={layers[0]} but features have {X.shape[1]} columns"
            )
        if y.max(initial=0) >= layers[-1]:
            raise ValueError(
                f"label index {int(y.max())} >= output layer size {layers[-1]}"
            )

        xs, ys, _ = shard_batch(mesh, X, y.astype(np.int32))
        ws = shard_weights(mesh, w, xs.shape[0])

        if self._initial_weights is not None:
            theta0 = np.asarray(self._initial_weights, np.float32)
            if theta0.shape != (_n_weights(layers),):
                raise ValueError(
                    f"initialWeights must have {_n_weights(layers)} entries"
                )
        else:
            # Glorot-uniform per layer, zero biases, seeded
            rng = np.random.default_rng(self.getSeed())
            parts = []
            for d_in, d_out in _layer_sizes(layers):
                limit = np.sqrt(6.0 / (d_in + d_out))
                parts.append(
                    rng.uniform(-limit, limit, size=d_in * d_out).astype(np.float32)
                )
                parts.append(np.zeros(d_out, np.float32))
            theta0 = np.concatenate(parts)

        def opt_call(init_state, resume, iter_limit):
            init_dev = (
                None if init_state is None
                else jax.tree.map(jnp.asarray, init_state)
            )
            return _mlp_optimize(
                xs, ys, ws, jnp.asarray(theta0), init_dev,
                jnp.asarray(iter_limit, jnp.int32),
                layers=layers,
                max_iter=self.getMaxIter(),
                tol=self.getTol(),
                solver=self.getSolver(),
                step_size=self.getStepSize(),
                resume=resume,
                compute_dtype=jnp.dtype(self.getComputeDtype()),
            )

        fingerprint = {
            "algo": "mlp", "layers": list(layers), "seed": self.getSeed(),
            "maxIter": self.getMaxIter(), "tol": self.getTol(),
            "solver": self.getSolver(), "n_rows": int(X.shape[0]),
            "computeDtype": self.getComputeDtype(),
        }
        interval = (
            self.getCheckpointInterval()
            if self.getSolver() == "l-bfgs"
            else -1  # gd state is just theta; not checkpointed
        )
        res = run_segmented(
            opt_call, self.getMaxIter(), interval,
            self.getCheckpointDir(), fingerprint,
        )

        model = MultilayerPerceptronClassificationModel(
            weights=np.asarray(res.x), layers=list(layers)
        )
        model.setParams(
            **{k: v for k, v in self.paramValues().items() if model.hasParam(k)}
        )
        from sntc_tpu.models.summary import ClassificationTrainingSummary

        n_iters = int(res.n_iters)
        model.summary = ClassificationTrainingSummary(
            np.asarray(res.history)[: n_iters + 1], n_iters, model, frame,
            labelCol=self.getLabelCol(), mesh=mesh,
        )
        return model


@partial(jax.jit, static_argnames=("layers",))
def _mlp_margins(theta, X, layers):
    return _forward(theta, X, layers)


@partial(jax.jit, static_argnames=("layers",))
def _mlp_predict_fused(theta, X, layers):
    """Margins + softmax probabilities in one program (one dispatch per
    serving micro-batch [B:11])."""
    raw = _forward(theta, X, layers)
    return raw, jax.nn.softmax(raw, axis=1)


@partial(jax.jit, static_argnames=("layers", "mode"))
def _mlp_serve(theta, X, thr, *, layers, mode):
    """raw + probability + prediction PACKED into one ``[N, 2K+1]`` output
    — one dispatch and ONE device→host transfer per serving micro-batch
    (transfers cost a full round trip each on a tunneled TPU)."""
    from sntc_tpu.models.base import pack_serve_outputs

    raw = _forward(theta, X, layers)
    prob = jax.nn.softmax(raw, axis=1)
    return pack_serve_outputs(raw, prob, thr, mode)


class MultilayerPerceptronClassificationModel(_MlpParams, ClassificationModel):
    def __init__(self, weights: np.ndarray, layers: List[int], **kwargs):
        super().__init__(**kwargs)
        self.weights = np.array(weights, np.float32)
        # read-only (own copy): predict caches a device copy, so silent
        # in-place mutation would serve stale weights — make it raise instead
        self.weights.flags.writeable = False
        self.set("layers", list(layers))
        self.summary = None
        self._dev_weights = None  # lazy device-resident flat weights

    def _device_weights(self):
        w = self._dev_weights
        if w is None:
            w = jnp.asarray(self.weights)
            # never cache a value created under an active trace (the
            # fusion planner jits THROUGH transform; a cached tracer
            # poisons every later trace with UnexpectedTracerError)
            if not isinstance(w, jax.core.Tracer):
                self._dev_weights = w
        return w

    def evaluate(self, frame: Frame):
        """Metrics summary on ``frame`` (Spark ``model.evaluate(dataset)``)."""
        from sntc_tpu.models.summary import ClassificationSummary

        return ClassificationSummary(self, frame, labelCol=self.getLabelCol())

    def _save_extra(self):
        return {}, {"weights": self.weights}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        layers = params.get("layers")
        m = cls(weights=arrays["weights"], layers=layers)
        m.setParams(**params)
        return m

    @property
    def num_classes(self) -> int:
        return int(self.getLayers()[-1])

    def _raw_predict(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(
            _mlp_margins(
                self._device_weights(),
                jnp.asarray(X),
                tuple(int(v) for v in self.getLayers()),
            )
        )

    def _predict_raw_prob(self, X: np.ndarray):
        raw, prob = _mlp_predict_fused(
            self._device_weights(),
            jnp.asarray(X),
            tuple(int(v) for v in self.getLayers()),
        )
        return np.asarray(raw), np.asarray(prob)

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        z = raw - raw.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)

    def _predict_all_dev(self, X: np.ndarray):
        mode, thr = self._threshold_mode()
        return _mlp_serve(
            self._device_weights(),
            jnp.asarray(X),
            jnp.asarray(thr),
            layers=tuple(int(v) for v in self.getLayers()),
            mode=mode,
        )

    def _predict_raw_prob_host(self, X: np.ndarray):
        """numpy forward pass for micro-batches below the host-serve
        crossover — a 78→64→15 MLP on ~1k rows is microseconds on host,
        cheaper than any device round trip."""
        h = X.astype(np.float64)
        theta = self.weights.astype(np.float64)
        sizes = _layer_sizes(tuple(int(v) for v in self.getLayers()))
        off = 0
        for i, (d_in, d_out) in enumerate(sizes):
            W = theta[off : off + d_in * d_out].reshape(d_in, d_out)
            off += d_in * d_out
            b = theta[off : off + d_out]
            off += d_out
            z = h @ W + b[None, :]
            if i < len(sizes) - 1:
                # sigmoid, overflow-safe
                e = np.exp(-np.abs(z))
                h = np.where(z >= 0, 1.0 / (1.0 + e), e / (1.0 + e))
            else:
                h = z
        raw = h.astype(np.float32)
        return raw, self._raw_to_probability(raw)
