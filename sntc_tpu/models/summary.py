"""Shared training-summary container (the ``TrainingSummary`` analog).

One generic (objectiveHistory, totalIterations) record used by every
iteratively-fitted model — LogisticRegression keeps its Spark-named
alias for API parity (``LogisticRegressionTrainingSummary`` upstream).
"""

from __future__ import annotations


class TrainingSummary:
    def __init__(self, objective_history, total_iterations: int):
        self.objectiveHistory = [float(v) for v in objective_history]
        self.totalIterations = int(total_iterations)
