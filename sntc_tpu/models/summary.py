"""Training summaries — the ``TrainingSummary`` family (SURVEY.md §5.5).

Spark parity: ``LogisticRegressionTrainingSummary`` (upstream
``ml/classification/LogisticRegression.scala`` summary classes [U])
carries the TRAINING-set predictions DataFrame plus per-class metrics
(``precisionByLabel``, ``recallByLabel``, ``fMeasureByLabel``, TPR/FPR
by label, the weighted aggregates, ``accuracy``) and, for binomial
models, the threshold curves (``roc``, ``areaUnderROC``, ``pr``,
``fMeasureByThreshold``, ``precisionByThreshold``,
``recallByThreshold``).  The same lazy design as Spark: the predictions
frame is produced on first access (one ``model.transform`` over the
training frame), and every metric derives from the one confusion matrix
/ threshold sweep, computed once and cached.

``TrainingSummary`` (objectiveHistory, totalIterations) stays the
lightweight record used by every iteratively-fitted model; classifiers
whose fit keeps the training frame get the full classification summary.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class TrainingSummary:
    def __init__(self, objective_history, total_iterations: int):
        self.objectiveHistory = [float(v) for v in objective_history]
        self.totalIterations = int(total_iterations)


class ClassificationSummary:
    """Per-class metrics over a predictions frame (Spark's
    ``ClassificationSummary`` trait).  Lazy: ``model.transform(frame)``
    runs on first access of :attr:`predictions`/any metric."""

    def __init__(
        self,
        model,
        frame,
        labelCol: str = "label",
        weightCol: Optional[str] = None,
        mesh=None,
    ):
        self._model = model
        self._frame = frame
        self.labelCol = labelCol
        self.predictionCol = model.getPredictionCol()
        self.probabilityCol = (
            model.getProbabilityCol()
            if model.hasParam("probabilityCol")
            else None
        )
        self.weightCol = weightCol
        self._mesh = mesh
        self._predictions = None
        self._metrics = None

    # -- lazy plumbing ----------------------------------------------------

    @property
    def predictions(self):
        if self._predictions is None:
            self._predictions = self._model.transform(self._frame)
        return self._predictions

    def _m(self):
        if self._metrics is None:
            from sntc_tpu.evaluation.multiclass import MulticlassMetrics

            out = self.predictions
            self._metrics = MulticlassMetrics(
                out[self.labelCol],
                out[self.predictionCol],
                weights=out[self.weightCol] if self.weightCol else None,
                mesh=self._mesh,
            )
        return self._metrics

    # -- Spark ClassificationSummary surface ------------------------------

    @property
    def labels(self) -> np.ndarray:
        """Class indices in ascending order (Spark ``labels``)."""
        return np.arange(self._m().num_classes, dtype=np.float64)

    @property
    def accuracy(self) -> float:
        return self._m().accuracy

    @property
    def precisionByLabel(self) -> np.ndarray:
        return self._m().precision_by_label()

    @property
    def recallByLabel(self) -> np.ndarray:
        return self._m().recall_by_label()

    @property
    def truePositiveRateByLabel(self) -> np.ndarray:
        return self._m().recall_by_label()

    @property
    def falsePositiveRateByLabel(self) -> np.ndarray:
        return self._m().false_positive_rate_by_label()

    def fMeasureByLabel(self, beta: float = 1.0) -> np.ndarray:
        return self._m().f_measure_by_label(beta)

    @property
    def weightedPrecision(self) -> float:
        return self._m().weighted_precision()

    @property
    def weightedRecall(self) -> float:
        return self._m().weighted_recall()

    @property
    def weightedTruePositiveRate(self) -> float:
        return self._m().weighted_true_positive_rate()

    @property
    def weightedFalsePositiveRate(self) -> float:
        return self._m().weighted_false_positive_rate()

    def weightedFMeasure(self, beta: float = 1.0) -> float:
        return self._m().weighted_f_measure(beta)


class BinaryClassificationSummary(ClassificationSummary):
    """Adds the threshold curves (Spark
    ``BinaryLogisticRegressionSummary``).  Curves sweep the
    positive-class score with ties grouped, exactly the evaluator's
    semantics (``sntc_tpu/evaluation/binary.py``)."""

    def _curve_inputs(self):
        out = self.predictions
        raw = out[self._model.getRawPredictionCol()]
        scores = raw[:, 1] if raw.ndim == 2 else raw
        w = out[self.weightCol] if self.weightCol else None
        return np.asarray(out[self.labelCol], np.float64), scores, w

    def _sweep(self):
        """(thresholds, tp, fp, total_p, total_n) at distinct-score
        boundaries, cached."""
        if not hasattr(self, "_sweep_cache"):
            from sntc_tpu.evaluation.binary import _curves

            y, s, w = self._curve_inputs()
            order = np.argsort(-np.asarray(s, np.float64), kind="stable")
            s_sorted = np.asarray(s, np.float64)[order]
            boundary = (
                np.flatnonzero(np.diff(s_sorted))
                if len(s_sorted)
                else np.array([], np.int64)
            )
            ends = (
                np.concatenate([boundary, [len(s_sorted) - 1]])
                if len(s_sorted)
                else boundary
            )
            tp, fp, p, n = _curves(y, s, w)
            self._sweep_cache = (s_sorted[ends], tp, fp, p, n)
        return self._sweep_cache

    @property
    def roc(self):
        """Frame with ``FPR``/``TPR`` columns, anchored at (0,0), (1,1)."""
        from sntc_tpu.core.frame import Frame

        _, tp, fp, p, n = self._sweep()
        tpr = np.concatenate([[0.0], tp / max(p, 1e-300), [1.0]])
        fpr = np.concatenate([[0.0], fp / max(n, 1e-300), [1.0]])
        return Frame({"FPR": fpr, "TPR": tpr})

    @property
    def areaUnderROC(self) -> float:
        from sntc_tpu.evaluation.binary import area_under_roc

        return area_under_roc(*self._curve_inputs())

    @property
    def pr(self):
        """Frame with ``recall``/``precision`` columns (Spark ``pr``)."""
        from sntc_tpu.core.frame import Frame

        _, tp, fp, p, _ = self._sweep()
        recall = tp / max(p, 1e-300)
        precision = tp / np.maximum(tp + fp, 1e-300)
        return Frame({
            "recall": np.concatenate([[0.0], recall]),
            "precision": np.concatenate([[precision[0] if len(precision) else 1.0],
                                         precision]),
        })

    def _by_threshold(self, values):
        from sntc_tpu.core.frame import Frame

        thr, *_ = self._sweep()
        return Frame({"threshold": thr, "metric": values})

    @property
    def precisionByThreshold(self):
        _, tp, fp, _, _ = self._sweep()
        return self._by_threshold(tp / np.maximum(tp + fp, 1e-300))

    @property
    def recallByThreshold(self):
        _, tp, _, p, _ = self._sweep()
        return self._by_threshold(tp / max(p, 1e-300))

    def fMeasureByThreshold(self, beta: float = 1.0):
        _, tp, fp, p, _ = self._sweep()
        prec = tp / np.maximum(tp + fp, 1e-300)
        rec = tp / max(p, 1e-300)
        b2 = beta * beta
        denom = np.maximum(b2 * prec + rec, 1e-300)
        return self._by_threshold((1 + b2) * prec * rec / denom)


class ClassificationTrainingSummary(ClassificationSummary, TrainingSummary):
    def __init__(self, objective_history, total_iterations, model, frame,
                 labelCol="label", weightCol=None, mesh=None):
        TrainingSummary.__init__(self, objective_history, total_iterations)
        ClassificationSummary.__init__(
            self, model, frame, labelCol=labelCol, weightCol=weightCol,
            mesh=mesh,
        )


class BinaryClassificationTrainingSummary(
    BinaryClassificationSummary, ClassificationTrainingSummary
):
    def __init__(self, objective_history, total_iterations, model, frame,
                 labelCol="label", weightCol=None, mesh=None):
        ClassificationTrainingSummary.__init__(
            self, objective_history, total_iterations, model, frame,
            labelCol=labelCol, weightCol=weightCol, mesh=mesh,
        )
