"""Factorization machines — FMClassifier / FMRegressor.

Behavioral spec: upstream ``ml/classification/FMClassifier.scala`` /
``ml/regression/FMRegressor.scala`` [U] (Spark 3.x estimator family —
breadth in the KMeans/PCA/GLR category): second-order FM

    s(x) = b + w·x + ½ Σ_f [ (x·V_f)² − (x² · V_f²) ]

with logistic loss (binary classification) or squared loss (regression),
``factorSize`` latent dims, ``fitIntercept``/``fitLinear`` switches, L2
``regParam`` on (w, V), N(0, ``initStd``) factor init, and an ``adamW``
(default) or ``gd`` solver.  Spark's ``miniBatchFraction`` default is
1.0 — full batch — which is exactly what static XLA shapes want, so
that is the one batching mode here (a sub-1.0 fraction would be a
dynamic-shape resample per step; not supported, documented deviation).

TPU design: the FM score is three MXU matmuls (``X@V``, ``X²@V²``,
``X@w``); the WHOLE optimizer run (optax adamW or plain GD) is one
jitted ``lax.while_loop`` over mesh-sharded rows with a relative
loss-change stop — XLA all-reduces the gradient row-sums over the mesh,
zero per-step host involvement.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sntc_tpu.core.base import Estimator
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.base import ClassificationModel, ClassifierParams
from sntc_tpu.core.base import Model
from sntc_tpu.models.summary import TrainingSummary
from sntc_tpu.parallel.collectives import shard_batch
from sntc_tpu.parallel.context import get_default_mesh


def _fm_score(params, X):
    """[N] FM scores; three MXU contractions."""
    V = params["V"]  # [D, k]
    xv = X @ V  # [N, k]
    x2v2 = (X * X) @ (V * V)  # [N, k]
    s = 0.5 * jnp.sum(xv * xv - x2v2, axis=1)
    if "w" in params:
        s = s + X @ params["w"]
    if "b" in params:
        s = s + params["b"]
    return s


def _fm_loss(params, X, y, w, *, classification, reg):
    s = _fm_score(params, X)
    if classification:
        # logistic loss on {0,1} labels (Spark FMClassifier)
        per_row = jax.nn.softplus(s) - y * s
    else:
        per_row = 0.5 * (s - y) ** 2
    wsum = jnp.maximum(jnp.sum(w), 1e-12)
    loss = jnp.sum(w * per_row) / wsum
    pen = jnp.sum(params["V"] ** 2)
    if "w" in params:
        pen = pen + jnp.sum(params["w"] ** 2)
    return loss + 0.5 * reg * pen


@partial(
    jax.jit,
    static_argnames=("classification", "solver", "max_iter"),
)
def _fm_optimize(xs, ys, ws, params0, *, classification, solver, max_iter,
                 step_size, tol, reg):
    """Full-batch adamW/GD as ONE program: while_loop with a relative
    loss-change stop; returns (params, n_iters, loss_history)."""
    loss_fn = partial(_fm_loss, classification=classification, reg=reg)

    if solver == "adamW":
        opt = optax.adamw(step_size, weight_decay=0.0)  # L2 is in the loss
    else:
        opt = optax.sgd(step_size)
    opt_state0 = opt.init(params0)

    hist0 = jnp.zeros((max_iter + 1,), jnp.float32)

    def cond(state):
        _, _, it, _, delta, _ = state
        return (it < max_iter) & (delta > tol)

    def body(state):
        params, opt_state, it, prev, _, hist = state
        # ONE forward+backward per step: hist[it] = f(params_it), and the
        # stop rule compares successive pre-update losses
        loss, grads = jax.value_and_grad(loss_fn)(params, xs, ys, ws)
        hist = hist.at[it].set(loss)
        delta = jnp.abs(prev - loss) / jnp.maximum(jnp.abs(prev), 1e-12)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, it + 1, loss, delta, hist

    # prev seed must be FINITE: |inf − loss| / inf is NaN, and NaN > tol
    # is False — the loop would exit after one step
    big = jnp.float32(jnp.finfo(jnp.float32).max)
    params, _, n_iter, _, _, hist = jax.lax.while_loop(
        cond, body,
        (params0, opt_state0, jnp.int32(0), big, big, hist0),
    )
    hist = hist.at[n_iter].set(loss_fn(params, xs, ys, ws))
    return params, n_iter, hist


class _FmParams:
    factorSize = Param("latent factor dimension", default=8,
                       validator=validators.gt(0))
    fitIntercept = Param("fit the global bias", default=True,
                         validator=validators.is_bool())
    fitLinear = Param("fit the 1-way (linear) term", default=True,
                      validator=validators.is_bool())
    regParam = Param("L2 on linear + factor weights", default=0.0,
                     validator=validators.gteq(0))
    initStd = Param("stddev of the factor init", default=0.01,
                    validator=validators.gt(0))
    maxIter = Param("max optimizer steps", default=100,
                    validator=validators.gt(0))
    stepSize = Param("optimizer step size", default=1.0,
                     validator=validators.gt(0))
    tol = Param("relative loss-change tolerance", default=1e-6,
                validator=validators.gteq(0))
    solver = Param("adamW | gd", default="adamW",
                   validator=validators.one_of("adamW", "gd"))
    seed = Param("factor init seed", default=0)


def _fit_fm(est, frame, *, classification):
    mesh = est._mesh or get_default_mesh()
    X = frame[est.getFeaturesCol()]
    if X.ndim != 2:
        raise ValueError(
            f"featuresCol {est.getFeaturesCol()!r} must be a vector "
            "column (use VectorAssembler)"
        )
    X = X.astype(np.float32, copy=False)
    y = np.asarray(frame[est.getLabelCol()], np.float32)
    if classification and not np.all((y == 0) | (y == 1)):
        raise ValueError(
            "FMClassifier is binary-only (labels in {0, 1}); wrap in "
            "OneVsRest for multiclass (Spark parity)"
        )
    n, d = X.shape
    # shard_batch's trailing return IS the 1/0 pad mask — the row weights
    xs, ys, ws = shard_batch(mesh, X, y)

    rng = np.random.default_rng(est.getSeed())
    k = int(est.getFactorSize())
    params0 = {
        "V": jnp.asarray(
            rng.normal(0.0, est.getInitStd(), size=(d, k)).astype(np.float32)
        )
    }
    if est.getFitLinear():
        params0["w"] = jnp.zeros(d, jnp.float32)
    if est.getFitIntercept():
        params0["b"] = jnp.float32(0.0)

    params, n_iter, hist = _fm_optimize(
        xs, ys, ws, params0,
        classification=classification,
        solver=est.getSolver(),
        max_iter=int(est.getMaxIter()),
        step_size=jnp.float32(est.getStepSize()),
        tol=jnp.float32(est.getTol()),
        reg=jnp.float32(est.getRegParam()),
    )
    n_iter = int(n_iter)
    out = {
        "factors": np.asarray(params["V"]),
        "linear": (
            np.asarray(params["w"])
            if "w" in params
            else np.zeros(d, np.float32)
        ),
        "intercept": float(params.get("b", 0.0)),
    }
    history = np.asarray(hist)[: n_iter + 1]
    return out, n_iter, history


@partial(jax.jit, static_argnames=())
def _fm_margin(X, V, w, b):
    # the ONE FM score definition (train loss and serving share it)
    return _fm_score({"V": V, "w": w, "b": b}, X)


class FMRegressor(_FmParams, Estimator):
    featuresCol = Param("feature vector column", default="features")
    labelCol = Param("target column", default="label")
    predictionCol = Param("output prediction column", default="prediction")

    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "FMRegressionModel":
        out, n_iter, history = _fit_fm(self, frame, classification=False)
        model = FMRegressionModel(**out)
        model.setParams(
            **{k: v for k, v in self.paramValues().items()
               if model.hasParam(k)}
        )
        model.summary = TrainingSummary(history, n_iter)
        return model


class FMRegressionModel(_FmParams, Model):
    featuresCol = Param("feature vector column", default="features")
    labelCol = Param("target column", default="label")
    predictionCol = Param("output prediction column", default="prediction")

    def __init__(self, factors=None, linear=None, intercept: float = 0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.factors = np.asarray(
            factors if factors is not None else [], np.float32
        )
        self.linear = np.asarray(
            linear if linear is not None else [], np.float32
        )
        self.intercept = float(intercept)
        self.summary: Optional[TrainingSummary] = None

    def _save_extra(self):
        return ({"intercept": self.intercept},
                {"factors": self.factors, "linear": self.linear})

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(factors=arrays["factors"], linear=arrays["linear"],
                intercept=float(extra.get("intercept", 0.0)))
        m.setParams(**params)
        return m

    def predict(self, X: np.ndarray) -> np.ndarray:
        s = _fm_margin(
            jnp.asarray(np.asarray(X, np.float32)),
            jnp.asarray(self.factors), jnp.asarray(self.linear),
            jnp.float32(self.intercept),
        )
        return np.asarray(s, np.float64)

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()].astype(np.float32, copy=False)
        return frame.with_column(self.getPredictionCol(), self.predict(X))


class FMClassifier(_FmParams, ClassifierParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "FMClassificationModel":
        out, n_iter, history = _fit_fm(self, frame, classification=True)
        model = FMClassificationModel(**out)
        model.setParams(
            **{k: v for k, v in self.paramValues().items()
               if model.hasParam(k)}
        )
        from sntc_tpu.models.summary import (
            BinaryClassificationTrainingSummary,
        )

        model.summary = BinaryClassificationTrainingSummary(
            history, n_iter, model, frame, labelCol=self.getLabelCol(),
            mesh=self._mesh,
        )
        return model


class FMClassificationModel(_FmParams, ClassificationModel):
    def __init__(self, factors=None, linear=None, intercept: float = 0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.factors = np.asarray(
            factors if factors is not None else [], np.float32
        )
        self.linear = np.asarray(
            linear if linear is not None else [], np.float32
        )
        self.intercept = float(intercept)
        self.summary = None

    @property
    def num_classes(self) -> int:
        return 2

    def _save_extra(self):
        return ({"intercept": self.intercept},
                {"factors": self.factors, "linear": self.linear})

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(factors=arrays["factors"], linear=arrays["linear"],
                intercept=float(extra.get("intercept", 0.0)))
        m.setParams(**params)
        return m

    def _raw_predict(self, X: np.ndarray) -> np.ndarray:
        s = np.asarray(
            _fm_margin(
                jnp.asarray(np.asarray(X, np.float32)),
                jnp.asarray(self.factors), jnp.asarray(self.linear),
                jnp.float32(self.intercept),
            ),
            np.float64,
        )
        return np.stack([-s, s], axis=1)

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        from scipy.special import expit  # overflow-free sigmoid

        p1 = expit(raw[:, 1])
        return np.stack([1.0 - p1, p1], axis=1)
