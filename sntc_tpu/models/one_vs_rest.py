"""OneVsRest — K binary reductions of a multiclass problem [B:10].

Behavioral spec: SURVEY.md §2.3 (upstream ``ml/classification/OneVsRest.
scala`` [U]): fit one copy of the base classifier per class on relabeled
{rest=0, class=1} data; prediction = argmax over per-class raw class-1
scores; ``parallelism`` is accepted for API parity (the fits are sequential
here — each inner fit already saturates the TPU mesh; Spark's thread pool
existed to overlap JVM scheduling, not compute).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.base import (
    ClassificationModel,
    ClassifierEstimator,
    ClassifierParams,
)


def _build_fused_ovr(models):
    """A ``f(X) -> [N, K]`` fused raw-score closure for homogeneous
    sub-models, or None (see ``OneVsRestModel._fused_raw``)."""
    from sntc_tpu.models.linear_svc import LinearSVCModel
    from sntc_tpu.models.logistic_regression import LogisticRegressionModel
    from sntc_tpu.models.tree.gbt import GBTClassificationModel

    if not models:
        return None
    if all(isinstance(m, LinearSVCModel) for m in models):
        # margins stack into one [D, K] f32 matmul, exactly the LR case
        WT = np.stack([m.coefficients for m in models]).T.astype(np.float32)
        b = np.asarray([m.intercept for m in models], np.float32)

        def svc_fused(X):
            return X.astype(np.float32, copy=False) @ WT + b

        return svc_fused
    if all(
        isinstance(m, LogisticRegressionModel) and m.is_binomial
        for m in models
    ):
        # [D, K] f32 once at build time; predict is one f32 host matmul
        # (tiny weights, raw margins only — cheaper than K device round
        # trips at any batch size, no f64 copy of the batch).  Margin is
        # the class-1/class-0 row DIFFERENCE — same as the per-model
        # loop's raw(1), which never assumes row 0 is zero (it isn't for
        # e.g. externally-constructed symmetric [-w, w] models)
        WT = np.stack(
            [m.coefficientMatrix[1] - m.coefficientMatrix[0] for m in models]
        ).T.astype(np.float32)
        b = np.asarray(
            [m.interceptVector[1] - m.interceptVector[0] for m in models],
            np.float32,
        )

        def lr_fused(X):
            return X.astype(np.float32, copy=False) @ WT + b

        return lr_fused
    if all(isinstance(m, GBTClassificationModel) for m in models) and (
        len({m.forest.max_depth for m in models}) == 1
    ):
        import jax.numpy as jnp

        from sntc_tpu.models.tree.gbt import _ovr_fused_raw

        feature = np.concatenate([m.forest.feature for m in models])
        threshold = np.concatenate([m.forest.threshold for m in models])
        leaf_stats = np.concatenate([m.forest.leaf_stats for m in models])
        K = len(models)
        M = feature.shape[0]
        sel = np.zeros((K, M), np.float32)
        off = 0
        for c, m in enumerate(models):
            t = m.forest.feature.shape[0]
            sel[c, off : off + t] = m.treeWeights
            off += t
        max_depth = models[0].forest.max_depth
        dev = tuple(
            jnp.asarray(a) for a in (feature, threshold, leaf_stats, sel)
        )

        def gbt_fused(X):
            return np.asarray(
                _ovr_fused_raw(jnp.asarray(X), *dev, max_depth=max_depth)
            )

        return gbt_fused
    return None


class _OvrParams(ClassifierParams):
    parallelism = Param(
        "API parity only; inner fits already saturate the mesh",
        default=1,
        validator=validators.gteq(1),
    )


class OneVsRest(_OvrParams, ClassifierEstimator):
    def __init__(self, classifier=None, mesh=None, **kwargs):
        super().__init__(**kwargs)
        if classifier is None:
            raise ValueError("OneVsRest requires a classifier estimator")
        self.classifier = classifier
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "OneVsRestModel":
        X, y, w = self._extract(frame)
        k = int(y.max()) + 1
        bin_col = f"ovr_label_{self.uid}"
        overrides = {
            "labelCol": bin_col,
            "featuresCol": self.getFeaturesCol(),
        }
        # forward sample weights to every binary sub-fit (Spark parity)
        if self.getWeightCol() and self.classifier.hasParam("weightCol"):
            overrides["weightCol"] = self.getWeightCol()
        models: List[ClassificationModel] = self._fit_vectorized(
            X, y, w, k, frame
        )
        if models is not None:
            # persisted metadata must be path-independent: vectorized
            # sub-models carry the same column overrides the sequential
            # sub-fits get via classifier.copy(overrides)
            for sub in models:
                sub.setParams(
                    **{k2: v for k2, v in overrides.items() if sub.hasParam(k2)}
                )
        else:
            models = []
            for c in range(k):
                y_c = (y == c).astype(np.float64)
                sub = frame.with_column(bin_col, y_c)
                models.append(self.classifier.copy(overrides).fit(sub))
        model = OneVsRestModel(models=models)
        model.setParams(
            **{k2: v for k2, v in self.paramValues().items() if model.hasParam(k2)}
        )
        return model

    def _fit_vectorized(self, X, y, w, k, frame):
        """All-classes-at-once fit when the base classifier supports riding
        a batched class axis (GBT: K trees per boosting round over the
        same binned features — SURVEY.md §7.2 item 4; LogisticRegression:
        K binary LBFGS lanes relabeled in-program).  Returns None when the
        classifier has no vectorized path or mid-fit checkpointing is
        requested (the sequential path owns that)."""
        from sntc_tpu.models.logistic_regression import LogisticRegression
        from sntc_tpu.models.tree.gbt import GBTClassifier, fit_gbt_ovr_vectorized
        from sntc_tpu.parallel.context import get_default_mesh

        if not isinstance(
            self.classifier, (LogisticRegression, GBTClassifier)
        ):
            return None
        # a weightCol set on the classifier itself (not this OvR) refers to
        # a column of the relabeled sub-frame — only the sequential path
        # reproduces that
        if self.classifier.getWeightCol() and not self.getWeightCol():
            return None
        mesh = self._mesh or self.classifier._mesh or get_default_mesh()

        if isinstance(self.classifier, LogisticRegression):
            if not self.classifier.supports_vectorized_ovr():
                return None
            return self.classifier._fit_ovr_lanes(X, y, w, k, mesh)
        # sequential only when checkpointing would actually happen (both
        # interval AND dir set — matching GBTClassifier._fit's own gate)
        if (
            self.classifier.getCheckpointInterval() > 0
            and self.classifier.getCheckpointDir()
        ):
            return None
        # validated boosting: the indicator column lives on the input frame
        vcol = self.classifier.getValidationIndicatorCol()
        val_mask = np.asarray(frame[vcol]).astype(bool) if vcol else None
        return fit_gbt_ovr_vectorized(
            self.classifier, X, y, w, k, mesh, val_mask=val_mask
        )

    def _sub_stages(self):
        return [self.classifier]

    @classmethod
    def _from_sub_stages(cls, stages, params, extra=None):
        obj = cls(classifier=stages[0])
        obj.setParams(**params)
        return obj


class OneVsRestModel(_OvrParams, ClassificationModel):
    def __init__(self, models: Optional[List[ClassificationModel]] = None, **kwargs):
        super().__init__(**kwargs)
        self.models = list(models or [])
        # lazy (models-identity-key, closure-or-False); keyed so mutating
        # ``self.models`` (public list) invalidates instead of serving the
        # stale fused weights
        self._fused = None

    @property
    def num_classes(self) -> int:
        return len(self.models)

    def _sub_stages(self):
        return self.models

    @classmethod
    def _from_sub_stages(cls, stages, params, extra=None):
        obj = cls(models=stages)
        obj.setParams(**params)
        return obj

    def _fused_raw(self):
        """Fused per-class raw scores — K sub-model predicts collapse into
        ONE pass when the sub-models are homogeneous:

        * LogisticRegression: the K binary coefficient rows stack into a
          single ``[K, D]`` matrix — raw is one matmul;
        * GBT: the K forests concatenate along the TREE axis; one
          traversal of all M trees + a ``[K, M]`` class-selection matmul
          yields every class's margin (one device dispatch instead of K).

        Mixed/unknown sub-model types fall back to the per-model loop.
        """
        # key on the model OBJECTS (kept alive by the tuple — identity
        # comparison; id() alone could be reused after GC)
        models = tuple(self.models)
        if self._fused is None or len(self._fused[0]) != len(models) or any(
            a is not b for a, b in zip(self._fused[0], models)
        ):
            self._fused = (models, _build_fused_ovr(self.models) or False)
        return self._fused[1] or None

    def _raw_predict(self, X: np.ndarray) -> np.ndarray:
        fused = self._fused_raw()
        if fused is not None:
            return fused(X)
        # per-class raw class-1 margin (Spark uses rawPrediction(1))
        cols = [m._raw_predict(X)[:, 1] for m in self.models]
        return np.stack(cols, axis=1)

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        # Spark OvR emits no probability column; we provide a normalized
        # softmax-free score for API convenience (documented extension)
        shifted = raw - raw.max(axis=1, keepdims=True)
        e = np.exp(shifted)
        return e / e.sum(axis=1, keepdims=True)

    def _prob_to_prediction(self, prob: np.ndarray) -> np.ndarray:
        return np.argmax(prob, axis=1).astype(np.float64)

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()].astype(np.float32, copy=False)
        raw = self._raw_predict(X)
        out = frame
        if self.getRawPredictionCol():
            out = out.with_column(self.getRawPredictionCol(), raw)
        if self.getPredictionCol():
            out = out.with_column(
                self.getPredictionCol(),
                np.argmax(raw, axis=1).astype(np.float64),
            )
        return out
