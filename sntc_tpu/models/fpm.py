"""FPGrowth — frequent-itemset mining + association rules.

Behavioral spec: upstream ``ml/fpm/FPGrowth.scala`` →
``mllib/fpm/FPGrowth.scala`` [U]: ``itemsCol`` (arrays of items),
``minSupport`` (0.3) filters itemsets by corpus frequency,
``minConfidence`` (0.8) filters the derived association rules; model
surface: ``freqItemsets`` (items, freq), ``associationRules``
(antecedent, consequent, confidence, lift, support — single-item
consequents, Spark's rule shape), ``transform`` appends each row's
predicted consequents (rules whose antecedent ⊆ basket, consequent not
already present).

Design: the classic FP-tree recursion (Han et al.), host-side — pattern
mining is pointer-chasing over a prefix tree with no dense numeric
kernel to place on an accelerator; Spark's distributed version shards
the conditional trees across executors, which collapses to the same
single-tree recursion in one address space (SURVEY.md §1's L5 collapse
argument).  Itemsets are mined exhaustively above ``minSupport`` —
identical output to Spark's, any algorithm.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Sequence, Tuple

import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame, object_column
from sntc_tpu.core.params import Param, validators


class _FPNode:
    __slots__ = ("item", "count", "parent", "children")

    def __init__(self, item, parent):
        self.item = item
        self.count = 0
        self.parent = parent
        self.children: Dict = {}


def _build_tree(baskets: List[Tuple[Tuple, int]], min_count: float):
    """FP-tree over (basket, multiplicity) pairs; returns (root, header
    links item -> [nodes]) after frequency-ordering and pruning."""
    counts: Dict = defaultdict(int)
    for items, mult in baskets:
        for it in items:
            counts[it] += mult
    freq = {it: c for it, c in counts.items() if c >= min_count}
    order = {
        it: i
        for i, it in enumerate(
            sorted(freq, key=lambda it: (-freq[it], str(it)))
        )
    }
    root = _FPNode(None, None)
    header: Dict = defaultdict(list)
    for items, mult in baskets:
        path = sorted(
            (it for it in set(items) if it in order), key=order.__getitem__
        )
        node = root
        for it in path:
            child = node.children.get(it)
            if child is None:
                child = _FPNode(it, node)
                node.children[it] = child
                header[it].append(child)
            child.count += mult
            node = child
    return root, header, freq, order


def _mine(baskets, min_count, suffix, out):
    """Recursive FP-growth: emit every frequent itemset extending
    ``suffix``."""
    _, header, freq, order = _build_tree(baskets, min_count)
    # least-frequent first (bottom of the order) — the classic traversal
    for it in sorted(order, key=order.__getitem__, reverse=True):
        support = freq[it]
        itemset = (it,) + suffix
        out[tuple(sorted(itemset, key=str))] = support
        # conditional pattern base: prefix paths of every `it` node
        cond: List[Tuple[Tuple, int]] = []
        for node in header[it]:
            path = []
            p = node.parent
            while p is not None and p.item is not None:
                path.append(p.item)
                p = p.parent
            if path:
                cond.append((tuple(path), node.count))
        if cond:
            _mine(cond, min_count, itemset, out)


class _FpParams:
    itemsCol = Param("basket column (arrays of items)", default="items")
    predictionCol = Param("output consequents column", default="prediction")
    minSupport = Param("min itemset frequency (fraction of rows)",
                       default=0.3, validator=validators.in_range(0, 1))
    minConfidence = Param("min rule confidence", default=0.8,
                          validator=validators.in_range(0, 1))


class FPGrowth(_FpParams, Estimator):
    def _fit(self, frame: Frame) -> "FPGrowthModel":
        # numpy scalars → native Python (keys must JSON-round-trip with
        # their types intact: int 1 and str "1" are different items)
        rows = [
            tuple(x.item() if hasattr(x, "item") else x for x in v)
            for v in frame[self.getItemsCol()]
        ]
        for r in rows:
            if len(set(r)) != len(r):
                raise ValueError(
                    "baskets must not contain duplicate items (Spark "
                    "raises SparkException on non-unique transactions)"
                )
        n = len(rows)
        min_count = float(self.getMinSupport()) * n
        out: Dict[Tuple, int] = {}
        _mine([(r, 1) for r in rows], max(min_count, 1e-12), (), out)
        model = FPGrowthModel(itemsets=out, numRows=n)
        model.setParams(**self.paramValues())
        return model


class FPGrowthModel(_FpParams, Model):
    def __init__(self, itemsets: Dict[Tuple, int], numRows: int, **kwargs):
        super().__init__(**kwargs)
        self._itemsets = dict(itemsets)
        self.numRows = int(numRows)
        self._rules = None
        self._rules_conf = None  # minConfidence the cache was built at

    @property
    def freqItemsets(self) -> Frame:
        keys = sorted(self._itemsets, key=lambda t: (len(t), [str(x) for x in t]))
        return Frame({
            "items": object_column([list(k) for k in keys]),
            "freq": np.array([self._itemsets[k] for k in keys], np.int64),
        })

    @property
    def associationRules(self) -> Frame:
        """Single-item-consequent rules above ``minConfidence`` [U], with
        confidence, lift and support."""
        min_conf = float(self.getMinConfidence())
        if self._rules is None or self._rules_conf != min_conf:
            self._rules_conf = min_conf
            ante, cons, confs, lifts, sups = [], [], [], [], []
            for itemset, freq in self._itemsets.items():
                if len(itemset) < 2:
                    continue
                for i, c in enumerate(itemset):
                    a = itemset[:i] + itemset[i + 1:]
                    fa = self._itemsets.get(a)
                    fc = self._itemsets.get((c,))
                    if not fa or not fc:
                        continue
                    conf = freq / fa
                    if conf >= min_conf:
                        ante.append(list(a))
                        cons.append([c])
                        confs.append(conf)
                        lifts.append(conf / (fc / self.numRows))
                        sups.append(freq / self.numRows)
            self._rules = (ante, cons, confs, lifts, sups)
        ante, cons, confs, lifts, sups = self._rules
        return Frame({
            "antecedent": object_column(ante),
            "consequent": object_column(cons),
            "confidence": np.array(confs, np.float64),
            "lift": np.array(lifts, np.float64),
            "support": np.array(sups, np.float64),
        })

    def transform(self, frame: Frame) -> Frame:
        rules = self.associationRules
        ante = rules["antecedent"]
        cons = rules["consequent"]
        out = []
        for basket in frame[self.getItemsCol()]:
            have = set(basket)
            pred = []
            for a, c in zip(ante, cons):
                if set(a) <= have and c[0] not in have and c[0] not in pred:
                    pred.append(c[0])
            out.append(pred)
        return frame.with_column(self.getPredictionCol(), object_column(out))

    def _save_extra(self):
        keys = list(self._itemsets)
        return (
            {
                "numRows": self.numRows,
                # items stored with native types (JSON keeps int vs str
                # distinct) — stringifying here would silently retype
                # integer baskets on load
                "itemsets": [
                    {"items": list(k), "freq": self._itemsets[k]}
                    for k in keys
                ],
            },
            {},
        )

    @classmethod
    def _load_from(cls, params, extra, arrays):
        itemsets = {
            tuple(rec["items"]): int(rec["freq"])
            for rec in extra["itemsets"]
        }
        m = cls(itemsets=itemsets, numRows=int(extra["numRows"]))
        m.setParams(**params)
        return m
