"""AFTSurvivalRegression — Weibull accelerated-failure-time survival model.

Behavioral spec: upstream ``ml/regression/AFTSurvivalRegression.scala`` [U]:
``log T = x·β + b + σ·ε`` with ε standard (minimum) extreme-value, censoring
indicator ``censorCol`` (1.0 = event observed, 0.0 = right-censored), no
regularization (Spark AFT has none), internal std-only feature scaling,
``predict = exp(x·β + b)`` and Weibull quantiles
``predict · (−log(1−p))^σ`` via ``quantileProbabilities``/``quantilesCol``.

Negative log-likelihood (per weighted row, δ the censor indicator):
``−[δ·(ε − log σ) − e^ε]`` with ``ε = (log t − x·β − b)/σ``.

TPU design: the whole fit is ONE jitted LBFGS program (`ops/lbfgs.py`) over
mesh-sharded rows — the NLL is a matvec + elementwise per evaluation, XLA
turns the closed-over sharded sums into ``psum``s exactly as in
LinearRegression's iterative path.  ``log σ`` rides as an extra coordinate,
so the optimizer stays unconstrained (σ > 0 by construction).
"""

from __future__ import annotations

from functools import partial
import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.summary import TrainingSummary
from sntc_tpu.ops.lbfgs import minimize_lbfgs
from sntc_tpu.parallel.collectives import shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh

_DEFAULT_QPS = (0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99)


@partial(jax.jit, static_argnames=("fit_intercept", "max_iter", "tol"))
def _aft_optimize(
    xs, logt, delta, ws, inv_std, theta0, *, fit_intercept, max_iter, tol
):
    """θ = [β (scaled space), intercept, log σ]; the intercept slot is
    inert (zero gradient) when ``fit_intercept`` is off."""
    d = xs.shape[1]
    w_sum = jnp.sum(ws)

    def value_and_grad(theta):
        def nll(theta):
            coef = theta[:d] * inv_std
            b = theta[d] if fit_intercept else jnp.zeros((), theta.dtype)
            log_sigma = theta[d + 1]
            eps = (logt - xs @ coef - b) * jnp.exp(-log_sigma)
            ll = delta * (eps - log_sigma) - jnp.exp(eps)
            return -jnp.sum(ws * ll) / w_sum

        return jax.value_and_grad(nll)(theta)

    return minimize_lbfgs(
        value_and_grad, theta0, max_iter=max_iter, tol=tol
    )


class _AftParams:
    featuresCol = Param("feature vector column", default="features")
    labelCol = Param("survival time column (> 0)", default="label")
    censorCol = Param(
        "censor column: 1.0 = event observed, 0.0 = right-censored",
        default="censor",
    )
    predictionCol = Param("output prediction column", default="prediction")
    quantilesCol = Param(
        "optional output column of Weibull quantiles", default=None
    )
    quantileProbabilities = Param(
        "probabilities for quantilesCol",
        default=_DEFAULT_QPS,
        validator=lambda v: len(v) > 0 and all(0.0 < p < 1.0 for p in v),
    )
    maxIter = Param("max LBFGS iterations", default=100,
                    validator=validators.gt(0))
    tol = Param("convergence tolerance", default=1e-6,
                validator=validators.gt(0))
    fitIntercept = Param("fit an intercept", default=True,
                         validator=validators.is_bool())
    weightCol = Param("optional row weight column", default=None)


class AFTSurvivalRegression(_AftParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "AFTSurvivalRegressionModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                f"featuresCol {self.getFeaturesCol()!r} must be a vector "
                "column (use VectorAssembler)"
            )
        X = X.astype(np.float32, copy=False)
        t = np.asarray(frame[self.getLabelCol()], np.float64)
        if np.any(t <= 0):
            raise ValueError(
                "survival times must be > 0 (Spark requires log t)"
            )
        delta = np.asarray(frame[self.getCensorCol()], np.float32)
        if not np.isin(delta, (0.0, 1.0)).all():
            raise ValueError("censorCol values must be 0.0 or 1.0")
        wcol = self.getWeightCol()
        w = (
            np.asarray(frame[wcol], np.float32)
            if wcol
            else np.ones(len(t), np.float32)
        )
        d = X.shape[1]

        xs, lt, dl = shard_batch(
            mesh, X, np.log(t).astype(np.float32), delta
        )[:3]
        ws = shard_weights(mesh, w, xs.shape[0])

        # std-only internal scaling (Spark AFT standardizes without
        # centering [U]); reuse the scaler's one-pass moments
        from sntc_tpu.feature.standard_scaler import standardization_moments

        _, _, var = standardization_moments(
            mesh, xs, ws, np.asarray(X[0]) if len(t) else np.zeros(d)
        )
        std = np.sqrt(np.maximum(var, 0.0))
        inv_std = np.divide(1.0, std, out=np.ones_like(std), where=std > 0)

        theta0 = np.zeros(d + 2, np.float32)
        res = _aft_optimize(
            xs, lt, dl, ws, jnp.asarray(inv_std, jnp.float32),
            jnp.asarray(theta0),
            fit_intercept=bool(self.getFitIntercept()),
            max_iter=int(self.getMaxIter()),
            tol=float(self.getTol()),
        )
        theta = np.asarray(res.x, np.float64)
        model = AFTSurvivalRegressionModel(
            coefficients=theta[:d] * inv_std,
            intercept=float(theta[d]),
            scale=float(np.exp(theta[d + 1])),
        )
        model.setParams(**self.paramValues())
        n_it = int(res.n_iters)
        model.summary = TrainingSummary(
            np.asarray(res.history)[: n_it + 1], n_it
        )
        return model


class AFTSurvivalRegressionModel(_AftParams, Model):
    def __init__(self, coefficients, intercept: float, scale: float, **kwargs):
        super().__init__(**kwargs)
        self.coefficients = np.asarray(coefficients, np.float64)
        self.intercept = float(intercept)
        self.scale = float(scale)  # σ — Spark's `scale`
        self.summary = None

    def _save_extra(self):
        return (
            {"intercept": self.intercept, "scale": self.scale},
            {"coefficients": self.coefficients},
        )

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(
            coefficients=arrays["coefficients"],
            intercept=float(extra["intercept"]),
            scale=float(extra["scale"]),
        )
        m.setParams(**params)
        return m

    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.exp(
            np.asarray(X, np.float64) @ self.coefficients + self.intercept
        )

    def predictQuantiles(self, X: np.ndarray) -> np.ndarray:
        """``[N, len(qps)]`` Weibull quantiles
        ``predict · (−log(1−p))^σ`` [U]."""
        qps = np.asarray(self.getQuantileProbabilities(), np.float64)
        lam = self.predict(X)[:, None]
        return lam * np.power(-np.log1p(-qps)[None, :], self.scale)

    def transform(self, frame: Frame) -> Frame:
        X = np.asarray(frame[self.getFeaturesCol()])
        out = frame.with_column(self.getPredictionCol(), self.predict(X))
        if self.getQuantilesCol():
            out = out.with_column(
                self.getQuantilesCol(), self.predictQuantiles(X)
            )
        return out
