"""DecisionTreeClassifier / DecisionTreeRegressor — single CART trees.

Behavioral spec: upstream ``ml/classification/DecisionTreeClassifier.
scala`` and ``ml/regression/DecisionTreeRegressor.scala`` [U] (the same
``tree/impl/RandomForest.run`` machinery the ensembles use, with
``numTrees=1``, every feature considered at every node, and no bagging —
SURVEY.md §2.3 lists the regressor path as GBT's building block).

TPU design: both are thin single-tree instantiations of the shared dense-
heap grower (sntc_tpu/models/tree/grower.py): the forest tensors simply
carry ``T=1``.  Classification leaves hold class-count vectors
(probability = normalized counts, Spark ``predictRaw``/``predictProbability``
semantics); regression leaves hold ``[w, wy, wy²]`` (prediction = wy/w,
variance impurity).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.base import (
    ClassificationModel,
    ClassifierEstimator,
    pack_serve_outputs,
)
from sntc_tpu.models.tree.grower import (
    Forest,
    ForestDeviceMixin,
    ForestPersistenceMixin,
    forest_leaf_stats,
    grow_forest,
)
from sntc_tpu.models.tree.random_forest import _one_hot_stats
from sntc_tpu.ops.binning import bin_features, quantile_bin_edges
from sntc_tpu.parallel.collectives import shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh


def _grow_single_tree(estimator, X, y_or_stats, w, mesh, impurity):
    """Shared fit body: bin, shard, grow one tree over every feature."""
    n, F = X.shape
    n_bins = estimator.getMaxBins()
    edges = quantile_bin_edges(X, max_bins=n_bins, seed=estimator.getSeed())
    if impurity == "variance":
        xs, ys, _ = shard_batch(mesh, X, y_or_stats)  # ys: float targets
        ws = shard_weights(mesh, w, xs.shape[0])
        row_stats = jnp.stack([ws, ws * ys, ws * ys * ys], axis=1)
        label_kwargs = {}
    else:
        xs, ys, _ = shard_batch(mesh, X, y_or_stats.astype(np.int32))
        ws = shard_weights(mesh, w, xs.shape[0])
        k = int(y_or_stats.max()) + 1 if n else 2
        row_stats = _one_hot_stats(ys, ws, max(k, 2))
        label_kwargs = {"row_label": ys, "row_weight": ws}
    binned = bin_features(xs, jnp.asarray(edges))
    w_trees = jax.device_put(
        np.ones((1, xs.shape[0]), np.float32),
        NamedSharding(mesh, P(None, mesh.axis_names[0])),
    )
    return grow_forest(
        binned, row_stats, w_trees, edges,
        n_bins=n_bins,
        max_depth=estimator.getMaxDepth(),
        min_instances_per_node=float(estimator.getMinInstancesPerNode()),
        min_info_gain=float(estimator.getMinInfoGain()),
        subset_k=F,  # a single Spark decision tree considers every feature
        impurity=impurity,
        seed=estimator.getSeed(),
        mesh=mesh,
        **label_kwargs,
    )


class _SingleTreeParams:
    """Spark's DecisionTree params — deliberately NOT the ensemble block:
    a single Spark decision tree has no subsamplingRate/bagging."""

    maxDepth = Param(
        "max tree depth", default=5, validator=validators.in_range(0, 15)
    )
    maxBins = Param(
        "max feature bins", default=32, validator=validators.in_range(2, 256)
    )
    minInstancesPerNode = Param(
        "min (weighted) rows per child", default=1, validator=validators.gteq(1)
    )
    minInfoGain = Param("min split gain", default=0.0, validator=validators.gteq(0))
    seed = Param("binning sample seed", default=0)


def _realized_depth(forest: Forest) -> int:
    """Depth of the deepest materialized node (Spark ``DecisionTreeModel.
    depth``), not the heap capacity ``maxDepth``."""
    exists = np.flatnonzero(forest.feature[0] >= -1)  # leaf or internal
    if exists.size == 0:
        return 0
    return int(np.floor(np.log2(exists[-1] + 1)))


class _DtClassifierParams(_SingleTreeParams):
    impurity = Param(
        "gini | entropy", default="gini",
        validator=validators.one_of("gini", "entropy"),
    )


class DecisionTreeClassifier(_DtClassifierParams, ClassifierEstimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "DecisionTreeClassificationModel":
        mesh = self._mesh or get_default_mesh()
        X, y, w = self._extract(frame)
        forest = _grow_single_tree(self, X, y, w, mesh, self.getImpurity())
        k = max(int(y.max()) + 1 if len(y) else 2, 2)
        model = DecisionTreeClassificationModel(
            forest=forest, n_classes=k, n_features=X.shape[1]
        )
        model.setParams(
            **{k2: v for k2, v in self.paramValues().items() if model.hasParam(k2)}
        )
        return model


@partial(jax.jit, static_argnames=("max_depth", "mode", "traversal"))
def _dt_serve(X, feature, threshold, leaf_stats, thr, *, max_depth, mode,
              traversal="xla"):
    """Traverse + normalize + predict packed into one dispatch and one
    device→host transfer per serving micro-batch (the [B:11] hot-path
    contract every model honors)."""
    from sntc_tpu.kernels.forest import traverse_forest

    raw = traverse_forest(
        X, feature, threshold, leaf_stats, max_depth=max_depth,
        traversal=traversal,
    )[0]  # [N, C] class counts — Spark DT rawPrediction
    prob = raw / jnp.maximum(raw.sum(axis=1, keepdims=True), 1e-12)
    return pack_serve_outputs(raw, prob, thr, mode)


class DecisionTreeClassificationModel(
    _DtClassifierParams, ForestPersistenceMixin, ForestDeviceMixin,
    ClassificationModel,
):
    def __init__(self, forest: Forest, n_classes: int, n_features: int = 0,
                 **kwargs):
        super().__init__(**kwargs)
        self.forest = forest
        self._n_classes = int(n_classes)
        self._n_features = int(n_features)

    @property
    def num_classes(self) -> int:
        return self._n_classes

    @property
    def depth(self) -> int:
        return _realized_depth(self.forest)

    def _predict_all_dev(self, X: np.ndarray):
        from sntc_tpu.kernels import serve_kernel_call

        mode, thr = self._threshold_mode()
        Xd = jnp.asarray(X)
        fa, ta, ls = self._device_forest()
        md = self.forest.max_depth

        def run(traversal):
            return _dt_serve(
                Xd, fa, ta, ls, jnp.asarray(thr),
                max_depth=md, mode=mode, traversal=traversal,
            )

        return serve_kernel_call(
            "forest_traversal", (Xd, fa, ta, ls), run,
            lambda: run("xla"), static=(md, mode),
            guard_kwargs={
                "n_nodes": fa.shape[1], "n_features": Xd.shape[1],
                "n_stats": ls.shape[2], "itemsize": Xd.dtype.itemsize,
            },
        )

    def _extra_meta(self):
        return {"n_classes": self._n_classes}

    @classmethod
    def _from_forest(cls, forest, extra):
        return cls(
            forest=forest,
            n_classes=int(extra["n_classes"]),
            n_features=int(extra.get("n_features", 0)),
        )

    def _raw_predict(self, X: np.ndarray) -> np.ndarray:
        # Spark DT rawPrediction is the leaf's class-count vector
        return np.asarray(
            forest_leaf_stats(
                jnp.asarray(X, jnp.float32), *self._device_forest(),
                max_depth=self.forest.max_depth,
            )[0]
        )

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        return raw / np.maximum(raw.sum(axis=1, keepdims=True), 1e-12)


class _DtRegressorParams(_SingleTreeParams):
    featuresCol = Param("feature vector column", default="features")
    labelCol = Param("target column", default="label")
    predictionCol = Param("output prediction column", default="prediction")
    impurity = Param(
        "variance", default="variance", validator=validators.one_of("variance")
    )


class DecisionTreeRegressor(_DtRegressorParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "DecisionTreeRegressionModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                f"featuresCol {self.getFeaturesCol()!r} must be a vector "
                "column (use VectorAssembler)"
            )
        X = X.astype(np.float32, copy=False)
        y = np.asarray(frame[self.getLabelCol()], np.float32)
        w = np.ones(len(y), np.float32)
        forest = _grow_single_tree(self, X, y, w, mesh, "variance")
        model = DecisionTreeRegressionModel(
            forest=forest, n_features=X.shape[1]
        )
        model.setParams(
            **{k2: v for k2, v in self.paramValues().items() if model.hasParam(k2)}
        )
        return model


@partial(jax.jit, static_argnames=("max_depth",))
def _dt_reg_predict(X, feature, threshold, leaf_stats, *, max_depth):
    stats = forest_leaf_stats(
        X, feature, threshold, leaf_stats, max_depth=max_depth
    )[0]  # [N, 3] = [w, wy, wy²]
    return stats[:, 1] / jnp.maximum(stats[:, 0], 1e-12)


class DecisionTreeRegressionModel(
    _DtRegressorParams, ForestPersistenceMixin, ForestDeviceMixin, Model
):
    def __init__(self, forest: Forest, n_features: int = 0, **kwargs):
        super().__init__(**kwargs)
        self.forest = forest
        self._n_features = int(n_features)

    @property
    def depth(self) -> int:
        return _realized_depth(self.forest)

    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(
            _dt_reg_predict(
                jnp.asarray(X, jnp.float32), *self._device_forest(),
                max_depth=self.forest.max_depth,
            )
        ).astype(np.float64)

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()].astype(np.float32, copy=False)
        return frame.with_column(self.getPredictionCol(), self.predict(X))
