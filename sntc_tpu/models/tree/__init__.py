from sntc_tpu.models.tree.random_forest import (
    RandomForestClassifier,
    RandomForestClassificationModel,
)
from sntc_tpu.models.tree.gbt import GBTClassifier, GBTClassificationModel

__all__ = [
    "RandomForestClassifier",
    "RandomForestClassificationModel",
    "GBTClassifier",
    "GBTClassificationModel",
]
