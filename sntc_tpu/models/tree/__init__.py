from sntc_tpu.models.tree.random_forest import (
    RandomForestClassifier,
    RandomForestClassificationModel,
)
from sntc_tpu.models.tree.gbt import GBTClassifier, GBTClassificationModel
from sntc_tpu.models.tree.gbt_regressor import GBTRegressor, GBTRegressionModel
from sntc_tpu.models.tree.random_forest_regressor import (
    RandomForestRegressor,
    RandomForestRegressionModel,
)
from sntc_tpu.models.tree.decision_tree import (
    DecisionTreeClassifier,
    DecisionTreeClassificationModel,
    DecisionTreeRegressor,
    DecisionTreeRegressionModel,
)

__all__ = [
    "RandomForestClassifier",
    "RandomForestClassificationModel",
    "GBTClassifier",
    "GBTClassificationModel",
    "GBTRegressor",
    "GBTRegressionModel",
    "RandomForestRegressor",
    "RandomForestRegressionModel",
    "DecisionTreeClassifier",
    "DecisionTreeClassificationModel",
    "DecisionTreeRegressor",
    "DecisionTreeRegressionModel",
]
