"""RandomForestClassifier — histogram CART forest on TPU [B:9].

Behavioral spec: SURVEY.md §2.3/§3.2 (upstream
``ml/classification/RandomForestClassifier.scala`` + ``tree/impl`` [U]):
quantile binning (``maxBins``), Poisson(subsamplingRate) bootstrap bagging,
level-wise growth with all trees per pass, gini/entropy impurity,
``featureSubsetStrategy`` per node, ``predictRaw`` = sum over trees of the
leaf's class-count vector normalized per tree, probability = normalized raw.

TPU design: sntc_tpu/models/tree/grower.py (dense heaps, segment-sum
histograms, psum across shards).  Differences from Spark, documented:
bagging without replacement uses Bernoulli(subsamplingRate) row masks
(Spark samples exactly); ``minInstancesPerNode`` compares weighted counts.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.base import ClassificationModel, ClassifierEstimator
from sntc_tpu.models.tree.grower import (
    Forest,
    ForestDeviceMixin,
    ForestPersistenceMixin,
    forest_leaf_stats,
    grow_forest,
    make_bagging_weights,
    resolve_feature_subset_k,
)
from sntc_tpu.ops.binning import bin_features, quantile_bin_edges
from sntc_tpu.parallel.collectives import shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh


@partial(jax.jit, static_argnames=("k",))
def _one_hot_stats(ys, ws, k):
    return jax.nn.one_hot(ys, k, dtype=jnp.float32) * ws[:, None]


class _TreeEnsembleParams:
    maxDepth = Param("max tree depth", default=5, validator=validators.in_range(0, 15))
    maxBins = Param("max feature bins", default=32, validator=validators.in_range(2, 256))
    minInstancesPerNode = Param(
        "min (weighted) rows per child", default=1, validator=validators.gteq(1)
    )
    minInfoGain = Param("min split gain", default=0.0, validator=validators.gteq(0))
    subsamplingRate = Param(
        "row sampling rate per tree", default=1.0, validator=validators.in_range(0, 1)
    )
    seed = Param("sampling seed", default=0)


class _RfParams(_TreeEnsembleParams):
    numTrees = Param("number of trees", default=20, validator=validators.gt(0))
    impurity = Param(
        "gini | entropy", default="gini", validator=validators.one_of("gini", "entropy")
    )
    featureSubsetStrategy = Param(
        "auto | all | sqrt | log2 | onethird | int | fraction string",
        default="auto",
    )
    bootstrap = Param("Poisson bootstrap bagging", default=True,
                      validator=validators.is_bool())


class RandomForestClassifier(_RfParams, ClassifierEstimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "RandomForestClassificationModel":
        mesh = self._mesh or get_default_mesh()
        X, y, w = self._extract(frame)
        n, F = X.shape
        k = int(y.max()) + 1 if n else 2
        k = max(k, 2)
        T = self.getNumTrees()
        n_bins = self.getMaxBins()

        edges = quantile_bin_edges(X, max_bins=n_bins, seed=self.getSeed())
        xs, ys, _ = shard_batch(mesh, X, y.astype(np.int32))
        ws = shard_weights(mesh, w, xs.shape[0])
        axis = mesh.axis_names[0]

        binned = bin_features(xs, jnp.asarray(edges))
        row_stats = _one_hot_stats(ys, ws, k)

        w_trees = make_bagging_weights(
            np.random.default_rng(self.getSeed()), self.getBootstrap(),
            self.getSubsamplingRate(), T, xs.shape[0], mesh,
        )

        subset_k = resolve_feature_subset_k(
            self.getFeatureSubsetStrategy(), F, T, is_classification=True
        )
        forest = grow_forest(
            binned, row_stats, w_trees, edges,
            n_bins=n_bins,
            max_depth=self.getMaxDepth(),
            min_instances_per_node=float(self.getMinInstancesPerNode()),
            min_info_gain=float(self.getMinInfoGain()),
            subset_k=subset_k,
            impurity=self.getImpurity(),
            seed=self.getSeed(),
            mesh=mesh,
            row_label=ys, row_weight=ws,  # label-fused scatter path
        )
        model = RandomForestClassificationModel(
            forest=forest, n_classes=k, n_features=F
        )
        model.setParams(
            **{k2: v for k2, v in self.paramValues().items() if model.hasParam(k2)}
        )
        # Spark 3.1+ RandomForestClassificationTrainingSummary: per-class
        # metrics over the training predictions (objectiveHistory is
        # empty — forests have no optimization trace), lazy; binary fits
        # get the threshold-curve variant, as upstream
        from sntc_tpu.models.summary import (
            BinaryClassificationTrainingSummary,
            ClassificationTrainingSummary,
        )

        summary_cls = (
            BinaryClassificationTrainingSummary
            if k == 2
            else ClassificationTrainingSummary
        )
        model.summary = summary_cls(
            [], 0, model, frame, labelCol=self.getLabelCol(), mesh=mesh
        )
        return model


@partial(jax.jit, static_argnames=("max_depth", "traversal"))
def _rf_raw(X, feature, threshold, leaf_stats, *, max_depth,
            traversal="xla"):
    from sntc_tpu.kernels.forest import traverse_forest

    stats = traverse_forest(
        X, feature, threshold, leaf_stats, max_depth=max_depth,
        traversal=traversal,
    )  # [T, N, C]
    totals = stats.sum(axis=2, keepdims=True)
    probs = stats / jnp.maximum(totals, 1e-12)
    return probs.sum(axis=0)  # [N, C] — Spark's summed per-tree votes


@partial(jax.jit, static_argnames=("max_depth", "mode", "traversal"))
def _rf_serve(X, feature, threshold, leaf_stats, thr, *, max_depth, mode,
              traversal="xla"):
    """Traverse + normalize + predict, packed: one dispatch and one
    device→host transfer per serving micro-batch."""
    from sntc_tpu.models.base import pack_serve_outputs

    raw = _rf_raw(
        X, feature, threshold, leaf_stats, max_depth=max_depth,
        traversal=traversal,
    )
    prob = raw / jnp.maximum(raw.sum(axis=1, keepdims=True), 1e-12)
    return pack_serve_outputs(raw, prob, thr, mode)


class RandomForestClassificationModel(
    _RfParams, ForestPersistenceMixin, ForestDeviceMixin, ClassificationModel
):
    def __init__(self, forest: Forest, n_classes: int, n_features: int = 0,
                 **kwargs):
        super().__init__(**kwargs)
        self.forest = forest
        self._n_classes = int(n_classes)
        self._n_features = int(n_features)

    @property
    def num_classes(self) -> int:
        return self._n_classes

    @property
    def trees(self) -> Forest:
        return self.forest

    def _extra_meta(self):
        return {"n_classes": self._n_classes}

    @classmethod
    def _from_forest(cls, forest, extra):
        return cls(
            forest=forest,
            n_classes=int(extra["n_classes"]),
            n_features=int(extra.get("n_features", 0)),
        )

    def _raw_predict(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(
            _rf_raw(
                jnp.asarray(X),
                *self._device_forest(),
                max_depth=self.forest.max_depth,
            )
        )

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        totals = raw.sum(axis=1, keepdims=True)
        return raw / np.maximum(totals, 1e-12)

    def _predict_all_dev(self, X: np.ndarray):
        from sntc_tpu.kernels import serve_kernel_call

        mode, thr = self._threshold_mode()
        Xd = jnp.asarray(X)
        fa, ta, ls = self._device_forest()
        md = self.forest.max_depth

        def run(traversal):
            return _rf_serve(
                Xd, fa, ta, ls, jnp.asarray(thr),
                max_depth=md, mode=mode, traversal=traversal,
            )

        return serve_kernel_call(
            "forest_traversal", (Xd, fa, ta, ls), run,
            lambda: run("xla"), static=(md, mode),
            guard_kwargs={
                "n_nodes": fa.shape[1], "n_features": Xd.shape[1],
                "n_stats": ls.shape[2], "itemsize": Xd.dtype.itemsize,
            },
        )
