"""RandomForestRegressor — averaged variance-impurity CART forest.

Behavioral spec: upstream ``ml/regression/RandomForestRegressor.scala``
[U]: the classification forest's machinery (quantile binning, Poisson
bagging, level-wise all-trees-per-pass growth, ``featureSubsetStrategy``
— whose ``auto`` default is onethird for regression) with variance
impurity and ``prediction = mean over trees of the leaf mean``.

TPU design: identical to RandomForestClassifier — the shared dense-heap
grower with regression stats ``[w, wy, wy²]`` (sntc_tpu/models/tree/
grower.py); serving is one traversal + mean, packed into a single
dispatch.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.tree.grower import (
    Forest,
    ForestDeviceMixin,
    ForestPersistenceMixin,
    forest_leaf_stats,
    grow_forest,
    make_bagging_weights,
    resolve_feature_subset_k,
)
from sntc_tpu.models.tree.random_forest import _TreeEnsembleParams
from sntc_tpu.ops.binning import bin_features, quantile_bin_edges
from sntc_tpu.parallel.collectives import shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh


class _RfRegParams(_TreeEnsembleParams):
    featuresCol = Param("feature vector column", default="features")
    labelCol = Param("target column", default="label")
    predictionCol = Param("output prediction column", default="prediction")
    numTrees = Param("number of trees", default=20, validator=validators.gt(0))
    impurity = Param(
        "variance", default="variance", validator=validators.one_of("variance")
    )
    featureSubsetStrategy = Param(
        "auto | all | sqrt | log2 | onethird | int | fraction string",
        default="auto",
    )
    bootstrap = Param("Poisson bootstrap bagging", default=True,
                      validator=validators.is_bool())


class RandomForestRegressor(_RfRegParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "RandomForestRegressionModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                f"featuresCol {self.getFeaturesCol()!r} must be a vector "
                "column (use VectorAssembler)"
            )
        X = X.astype(np.float32, copy=False)
        y = np.asarray(frame[self.getLabelCol()], np.float32)
        n, F = X.shape
        T = self.getNumTrees()
        n_bins = self.getMaxBins()

        edges = quantile_bin_edges(X, max_bins=n_bins, seed=self.getSeed())
        xs, ys, _ = shard_batch(mesh, X, y)
        ws = shard_weights(
            mesh, np.ones(n, np.float32), xs.shape[0]
        )
        binned = bin_features(xs, jnp.asarray(edges))
        row_stats = jnp.stack([ws, ws * ys, ws * ys * ys], axis=1)

        w_trees = make_bagging_weights(
            np.random.default_rng(self.getSeed()), self.getBootstrap(),
            self.getSubsamplingRate(), T, xs.shape[0], mesh,
        )

        subset_k = resolve_feature_subset_k(
            self.getFeatureSubsetStrategy(), F, T, is_classification=False
        )
        forest = grow_forest(
            binned, row_stats, w_trees, edges,
            n_bins=n_bins,
            max_depth=self.getMaxDepth(),
            min_instances_per_node=float(self.getMinInstancesPerNode()),
            min_info_gain=float(self.getMinInfoGain()),
            subset_k=subset_k,
            impurity="variance",
            seed=self.getSeed(),
            mesh=mesh,
        )
        model = RandomForestRegressionModel(forest=forest, n_features=F)
        model.setParams(
            **{k2: v for k2, v in self.paramValues().items()
               if model.hasParam(k2)}
        )
        return model


@partial(jax.jit, static_argnames=("max_depth",))
def _rf_reg_predict(X, feature, threshold, leaf_stats, *, max_depth):
    stats = forest_leaf_stats(
        X, feature, threshold, leaf_stats, max_depth=max_depth
    )  # [T, N, 3] = [w, wy, wy²]
    means = stats[:, :, 1] / jnp.maximum(stats[:, :, 0], 1e-12)
    return means.mean(axis=0)  # average over trees (Spark)


class RandomForestRegressionModel(
    _RfRegParams, ForestPersistenceMixin, ForestDeviceMixin, Model
):
    def __init__(self, forest: Forest, n_features: int = 0, **kwargs):
        super().__init__(**kwargs)
        self.forest = forest
        self._n_features = int(n_features)

    @property
    def trees(self) -> Forest:
        return self.forest

    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(
            _rf_reg_predict(
                jnp.asarray(X, jnp.float32), *self._device_forest(),
                max_depth=self.forest.max_depth,
            )
        ).astype(np.float64)

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()].astype(np.float32, copy=False)
        return frame.with_column(self.getPredictionCol(), self.predict(X))
