"""Level-wise binned forest grower — the ``RandomForest.run`` analog.

Behavioral spec: SURVEY.md §2.3/§3.2 (upstream ``ml/tree/impl/RandomForest.
scala`` + ``DTStatsAggregator`` [U]): quantile-binned features, level-wise
growth with ALL trees' nodes trained per data pass, per-(node,feature,bin)
sufficient statistics reduced across partitions, split = impurity-gain
argmax, ``minInstancesPerNode``/``minInfoGain`` pruning.

TPU redesign (SURVEY.md §7.2 item 1 — static shapes over dynamic trees):

  * trees are DENSE heaps of ``2^(maxDepth+1)-1`` node slots (masked, not
    grown) — no dynamic structure anywhere;
  * the per-level histogram ``[T, nodes, F, B, S]`` is a ``segment_sum``
    over mesh-sharded rows (``lax.map`` over trees × ``lax.scan`` over
    features keeps peak memory at one ``[N]`` id vector); XLA inserts the
    ICI all-reduce — Spark's shuffle (§3.2 ⟦DRV→EXEC⟧) becomes one psum;
  * split selection is vectorized argmax on device; children of a split get
    their stats from the chosen (left, right) cumsums, so the final level
    needs no extra pass;
  * a unified stats vector ``S`` serves classification (weighted class
    counts, gini/entropy) and regression (``[w, wy, wy²]``, variance) — the
    same kernel grows RF and GBT trees.

Row routing uses bin ids (``bin <= split_bin`` goes left ⟺ ``x < edges[f,
split_bin]``); serving traverses on raw floats with the stored thresholds.
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.parallel.mesh import map_at, payload_nbytes, record_collective


class Forest(NamedTuple):
    """Dense-heap forest. H = 2^(max_depth+1) - 1 slots per tree.

    ``feature[t, h] >= 0`` marks an internal node (split on that feature at
    ``threshold``); ``-1`` marks a leaf with ``leaf_stats[t, h]`` (class
    counts or [w, wy, wy²]); ``-2`` marks a never-created slot.
    ``gain``/``count`` are populated on internal nodes (0 elsewhere) and
    feed ``featureImportances`` (Spark ``computeFeatureImportance`` parity).
    """

    feature: np.ndarray  # [T, H] int32
    threshold: np.ndarray  # [T, H] f32
    leaf_stats: np.ndarray  # [T, H, S] f32
    max_depth: int
    gain: np.ndarray = None  # [T, H] f32
    count: np.ndarray = None  # [T, H] f32

    def feature_importances(
        self, n_features: int, per_tree_normalization: bool = True
    ) -> np.ndarray:
        """Gain×count importances — Spark ``TreeEnsembleModel.
        featureImportances`` semantics: each tree's contributions are
        normalized to sum 1 first for forests (RF), left raw for boosted
        ensembles (GBT passes ``perTreeNormalization=false`` upstream),
        then the total is normalized."""
        if self.gain is None or self.count is None:
            raise ValueError(
                "featureImportances unavailable: this model was saved "
                "without per-node split statistics (gain/count); re-fit "
                "to compute importances"
            )
        total = np.zeros(n_features, np.float64)
        for t in range(self.feature.shape[0]):
            imp = np.zeros(n_features, np.float64)
            internal = self.feature[t] >= 0
            np.add.at(
                imp,
                self.feature[t][internal],
                (self.gain[t] * self.count[t])[internal],
            )
            if per_tree_normalization:
                s = imp.sum()
                if s > 0:
                    total += imp / s
            else:
                total += imp
        s = total.sum()
        return (total / s if s > 0 else total).astype(np.float64)


def heap_offset(depth: int) -> int:
    return (1 << depth) - 1


class ForestPersistenceMixin:
    """Shared save/load payload + featureImportances for every model that
    is just a dense-heap forest plus ``_n_features`` (DT/RF, both tasks).
    Subclasses with extra identity (the classifiers' ``n_classes``)
    override ``_extra_meta``/``_from_forest``."""

    _per_tree_normalization = True  # RF semantics; GBT passes False

    def _extra_meta(self) -> dict:
        return {}

    @classmethod
    def _from_forest(cls, forest: "Forest", extra: dict):
        return cls(forest=forest, n_features=int(extra.get("n_features", 0)))

    def _save_extra(self):
        meta = {
            "max_depth": self.forest.max_depth,
            "n_features": self._n_features,
        }
        meta.update(self._extra_meta())
        return meta, {
            "feature": self.forest.feature,
            "threshold": self.forest.threshold,
            "leaf_stats": self.forest.leaf_stats,
            "gain": self.forest.gain,
            "count": self.forest.count,
        }

    @classmethod
    def _load_from(cls, params, extra, arrays):
        forest = Forest(
            arrays["feature"], arrays["threshold"], arrays["leaf_stats"],
            int(extra["max_depth"]),
            arrays.get("gain"), arrays.get("count"),
        )
        m = cls._from_forest(forest, extra)
        m.setParams(**params)
        return m

    @property
    def featureImportances(self) -> np.ndarray:
        n = self._n_features or int(self.forest.feature.max()) + 1
        return self.forest.feature_importances(
            n, per_tree_normalization=self._per_tree_normalization
        )


def make_bagging_weights(rng, bootstrap: bool, rate: float, T: int, n: int,
                         mesh):
    """Per-tree row weights, device-put sharded on the row axis — the ONE
    definition of the Spark bagging semantics (Poisson(subsamplingRate)
    with replacement; Bernoulli masks without — a documented deviation
    from Spark's exact sampling) shared by both forests."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if bootstrap:
        w = rng.poisson(rate, size=(T, n)).astype(np.float32)
    elif rate < 1.0:
        w = (rng.random((T, n)) < rate).astype(np.float32)
    else:
        w = np.ones((T, n), np.float32)
    return jax.device_put(
        w, NamedSharding(mesh, P(None, mesh.axis_names[0]))
    )


class ForestDeviceMixin:
    """Lazy device-resident copies of the dense forest tensors: model
    parameters upload once per process, not once per serving micro-batch
    (each upload is a host→device transfer on the [B:11] hot path).
    Subclasses override ``_forest_arrays`` to add extra tensors (GBT's
    tree weights)."""

    _dev_forest = None

    def _forest_arrays(self) -> tuple:
        f = self.forest
        return (f.feature, f.threshold, f.leaf_stats)

    def _device_forest(self) -> tuple:
        forest = self._dev_forest
        if forest is None:
            forest = tuple(
                jnp.asarray(a) for a in self._forest_arrays()
            )
            # never cache values created under an active trace: the
            # fusion planner jits THROUGH _predict_all_dev, so inside
            # its tracing these constants are tracers — caching one
            # would poison every later trace AND the eager host-
            # fallback path with UnexpectedTracerError (the same guard
            # LogisticRegression/MLP got in r12; bites exactly when a
            # fused trace runs before the first eager transform)
            import jax

            if not any(isinstance(a, jax.core.Tracer) for a in forest):
                self._dev_forest = forest
        return forest


def resolve_feature_subset_k(strategy, n_features: int, n_trees: int,
                             is_classification: bool) -> int:
    """Spark featureSubsetStrategy semantics (SURVEY.md §2.3)."""
    if isinstance(strategy, (int, np.integer)):
        k = int(strategy)
    elif strategy == "auto":
        if n_trees == 1:
            k = n_features
        elif is_classification:
            k = int(math.ceil(math.sqrt(n_features)))
        else:
            k = max(1, n_features // 3)
    elif strategy == "all":
        k = n_features
    elif strategy == "sqrt":
        k = int(math.ceil(math.sqrt(n_features)))
    elif strategy == "log2":
        k = max(1, int(math.floor(math.log2(n_features))))
    elif strategy == "onethird":
        k = max(1, n_features // 3)
    else:
        try:
            frac = float(strategy)
        except (TypeError, ValueError):
            raise ValueError(f"unknown featureSubsetStrategy {strategy!r}")
        if not 0 < frac <= 1:
            raise ValueError(f"featureSubsetStrategy fraction {frac} not in (0,1]")
        k = max(1, int(math.ceil(frac * n_features)))
    return min(max(k, 1), n_features)


def _weighted_impurity(stats: jnp.ndarray, impurity: str) -> jnp.ndarray:
    """``weight * impurity`` for a stats vector (last axis S).

    gini:    w - Σ s²/w          entropy: Σ -s·log(s/w)
    variance: Σwy² - (Σwy)²/w   (stats = [w, wy, wy²])
    """
    if impurity in ("gini", "entropy"):
        w = stats.sum(axis=-1)
        safe_w = jnp.maximum(w, 1e-12)
        if impurity == "gini":
            return w - (stats**2).sum(axis=-1) / safe_w
        p = stats / safe_w[..., None]
        return -(jnp.where(stats > 0, stats * jnp.log(jnp.maximum(p, 1e-12)), 0.0)).sum(
            axis=-1
        )
    # variance
    w = stats[..., 0]
    safe_w = jnp.maximum(w, 1e-12)
    return stats[..., 2] - stats[..., 1] ** 2 / safe_w


def _stat_count(stats: jnp.ndarray, impurity: str) -> jnp.ndarray:
    if impurity == "variance":
        return stats[..., 0]
    return stats.sum(axis=-1)


def node_group_size(T: int, F: int, n_bins: int, S: int) -> int:
    """Nodes per histogram pass, bounded so the level working set
    (histogram + cumsum + left/right slices + gain tensor, ~5× the raw
    histogram) stays under ``SNTC_TREE_NODE_GROUP_MB`` (default 2 GB;
    Spark's ``maxMemoryInMB=256`` bounds its node groups the same way
    [U] — we default 8× that, HBM being roomier than a 2010s JVM heap;
    measured on the depth-10 bench config, 2 GB more than halves deep-
    level wall-clock vs 512 MB and going past it buys nothing).
    Deep levels evaluate in several passes over the binned data instead
    of materializing a multi-GB ``[T, 2^d, F, B, S]`` tensor — the
    memory/compute tradeoff Spark makes."""
    budget = float(os.environ.get("SNTC_TREE_NODE_GROUP_MB", 2048))
    per_node = 5.0 * T * F * n_bins * S * 4
    raw = max(1, int(budget * 1024 * 1024 / per_node))
    return 1 << (raw.bit_length() - 1)  # pow2: levels split evenly


def _level_core(
    binned,  # [N, F] int32, row-sharded
    binned_t,  # [F, N] int32, row-sharded on axis 1 (pallas layout)
    row_stats,  # [N, S] f32 shared, or [T, N, S] per-tree (the vectorized
    #            one-vs-rest path: every "tree" is a different binary
    #            problem over the same binned features) — row-sharded
    row_label,  # [N] int32 class ids or None (label-fused scatter path)
    row_weight,  # [N] f32 row weights or None (with row_label)
    w_trees,  # [T, N] f32 bagging weights, sharded on N
    node_idx,  # [T, N] int32 (-1 = inactive), sharded on N
    key,  # PRNG key for feature subsetting
    min_instances,  # f32 scalar
    min_info_gain,  # f32 scalar
    parent_hist,  # [T, n_nodes/2, F, B, S] previous level's histograms
    #             (sibling-subtraction path) or None (direct)
    *,
    n_nodes: int,
    n_bins: int,
    impurity: str,
    subset_k: int,
    group: int,
    hist_impl: str = "segment",
    mesh=None,
    interpret: bool = False,
    route: bool = True,
    keep_hist: bool = False,
):
    """One level's histogram + split evaluation + (optional) row routing,
    with the node axis evaluated in memory-bounded groups of ``group``
    nodes (Spark's maxMemoryInMB node-group analog; resolved ONCE in
    :func:`grow_forest` so it participates in the jit cache key).  Traced
    inside :func:`_grow_fused`'s unrolled level loop."""
    n, F = binned.shape
    S = row_stats.shape[-1]
    T = w_trees.shape[0]

    # feature subsetting drawn ONCE for the level (tiny [T, nodes, F]),
    # so the chosen subsets don't depend on how the nodes are grouped
    fmask = None
    if subset_k < F:
        r = jax.random.uniform(key, (T, n_nodes, F))
        kth = -jax.lax.top_k(-r, subset_k)[0][..., -1]  # kth smallest
        fmask = r <= kth[..., None]

    if n_nodes <= group:
        out = _eval_node_group(
            binned, binned_t, row_stats, row_label, row_weight,
            w_trees, node_idx, fmask, min_instances, parent_hist,
            lo=jnp.int32(0), g=n_nodes, n_bins=n_bins,
            impurity=impurity, hist_impl=hist_impl, mesh=mesh,
            interpret=interpret, keep_hist=keep_hist,
        )
    else:
        # groups share shapes (pow2 group divides the pow2 level), so the
        # whole level is ONE lax.map over group offsets: one trace, and
        # only one group's histogram working set live at a time
        n_groups = n_nodes // group
        los = jnp.arange(n_groups, dtype=jnp.int32) * group
        if fmask is None:
            args = los

            def one(lo_t):
                return _eval_node_group(
                    binned, binned_t, row_stats, row_label, row_weight,
                    w_trees, node_idx, None, min_instances, parent_hist,
                    lo=lo_t, g=group, n_bins=n_bins, impurity=impurity,
                    hist_impl=hist_impl, mesh=mesh, interpret=interpret,
                    keep_hist=keep_hist,
                )
        else:
            fmask_g = fmask.reshape(T, n_groups, group, F).transpose(
                1, 0, 2, 3
            )
            args = (los, fmask_g)

            def one(a):
                return _eval_node_group(
                    binned, binned_t, row_stats, row_label, row_weight,
                    w_trees, node_idx, a[1], min_instances, parent_hist,
                    lo=a[0], g=group, n_bins=n_bins, impurity=impurity,
                    hist_impl=hist_impl, mesh=mesh, interpret=interpret,
                    keep_hist=keep_hist,
                )

        stacked = jax.lax.map(one, args)  # each: [n_groups, T, group, ...]
        out = {
            k: jnp.moveaxis(v, 0, 1).reshape(
                (T, n_nodes) + v.shape[3:]
            )
            for k, v in stacked.items()
        }

    best_feat = out["best_feat"]
    best_bin = out["best_bin"]
    best_gain = out["best_gain"]
    parent_cnt = out["parent_count"]
    has_rows = parent_cnt > 0
    do_split = has_rows & jnp.isfinite(best_gain) & (best_gain > min_info_gain)
    # Spark treats minInfoGain=0 as "any strictly positive gain"
    do_split = do_split & (best_gain > 0)

    # ---- route rows to children (skipped at the last level) ----------------
    if route:
        idx = jnp.where(node_idx >= 0, node_idx, 0)  # [T, N]
        splits = jnp.take_along_axis(do_split, idx, axis=1)  # [T, N]
        feats = jnp.take_along_axis(best_feat, idx, axis=1)  # [T, N]
        bins_thr = jnp.take_along_axis(best_bin, idx, axis=1)  # [T, N]
        row_bins = jax.vmap(
            lambda f_t: jnp.take_along_axis(binned, f_t[:, None], axis=1)[:, 0]
        )(feats)  # [T, N]
        go_right = (row_bins > bins_thr).astype(jnp.int32)
        child = 2 * idx + go_right
        new_node_idx = jnp.where(
            (node_idx >= 0) & splits, child, -1
        ).astype(jnp.int32)
    else:
        new_node_idx = node_idx

    res = {
        "best_feat": best_feat,
        "best_bin": best_bin,
        "best_gain": best_gain,
        "do_split": do_split,
        "has_rows": has_rows,
        "parent_stats": out["parent_stats"],
        "parent_count": parent_cnt,
        "left_stats": out["left_stats"],
        "right_stats": out["right_stats"],
        "new_node_idx": new_node_idx,
    }
    if keep_hist:
        res["hist"] = out["hist"]
    return res


def _eval_node_group(
    binned, binned_t, row_stats, row_label, row_weight,
    w_trees, node_idx, fmask, min_instances, parent_hist,
    *,
    lo,  # traced int32 scalar: first node id of the group
    g: int,
    n_bins: int,
    impurity: str,
    hist_impl: str,
    mesh,
    interpret: bool,
    keep_hist: bool,
):
    """Histogram + best-split evaluation for the ``g`` nodes starting at
    level-local offset ``lo`` (a traced scalar, so a whole level's groups
    run as one ``lax.map``); rows whose node lies outside the group are
    masked inactive (id −1), exactly like dead rows.

    With ``parent_hist`` (sibling-histogram subtraction — the
    LightGBM/XGBoost trick, absent from Spark's DTStatsAggregator): only
    the EVEN (left) children are histogrammed from rows; each odd sibling
    is ``parent − left``, since a split parent's rows partition exactly
    into its two children.  Halves the histogram width every level below
    the root — the dominant cost on the MXU one-hot path, and half the
    group passes on the segment path.  Children of non-split parents
    derive garbage (parent − 0) but are masked by ``exists_lvl`` in
    :func:`_grow_fused` before any heap write, and no row routes there."""
    n, F = binned.shape
    S = row_stats.shape[-1]
    T = w_trees.shape[0]

    if parent_hist is not None and g >= 2:
        ids_even = jnp.where(
            (node_idx >= lo) & (node_idx < lo + g) & ((node_idx & 1) == 0),
            (node_idx - lo) >> 1, -1,
        )
        h_even = _group_hist(
            binned, binned_t, row_stats, row_label, row_weight, w_trees,
            ids_even, g_eff=g // 2, n_bins=n_bins, hist_impl=hist_impl,
            mesh=mesh, interpret=interpret,
        )
        par = jax.lax.dynamic_slice(
            parent_hist, (0, lo // 2, 0, 0, 0),
            (T, g // 2, F, n_bins, S),
        )
        # exact for integer-valued weights (Poisson bagging, unit rows:
        # small-int f32 sums); with a fractional weightCol the
        # subtraction carries ~1-ulp f32 rounding — same class of noise
        # as any reduction reorder.  For non-negative class-count stats
        # the clamp keeps a true-zero sibling cell from surfacing as a
        # tiny negative count/probability; variance stats ([w, wy, wy²])
        # are legitimately signed in wy, so they must NOT be clamped.
        h_odd = par - h_even
        if impurity in ("gini", "entropy"):
            h_odd = jnp.maximum(h_odd, 0.0)
        hist = jnp.stack([h_even, h_odd], axis=2).reshape(
            T, g, F, n_bins, S
        )
    else:
        ids = jnp.where(
            (node_idx >= lo) & (node_idx < lo + g), node_idx - lo, -1
        )
        hist = _group_hist(
            binned, binned_t, row_stats, row_label, row_weight, w_trees,
            ids, g_eff=g, n_bins=n_bins, hist_impl=hist_impl, mesh=mesh,
            interpret=interpret,
        )

    out = _eval_from_hist(hist, fmask, min_instances, impurity=impurity)
    if keep_hist:
        out["hist"] = hist
    return out


def _group_hist(
    binned, binned_t, row_stats, row_label, row_weight, w_trees,
    node_idx,  # [T, N] int32 GROUP-LOCAL ids in [0, g_eff) (-1 = dead)
    *,
    g_eff: int,
    n_bins: int,
    hist_impl: str,
    mesh,
    interpret: bool,
):
    """Histogram ``[T, g_eff, F, B, S]`` over pre-mapped local node ids.

    Three impls: the pallas MXU one-hot matmul (TPU), the label-fused
    scalar ``segment_sum`` (classification with shared one-hot stats —
    scatters N scalars into ``(node·B + bin)·S + label`` instead of N×S
    vector rows, ~6× less scatter traffic; requires
    ``row_stats == one_hot(row_label) * row_weight[:, None]``), and the
    generic vector ``segment_sum``."""
    n, F = binned.shape
    S = row_stats.shape[-1]
    T = w_trees.shape[0]
    per_tree_stats = row_stats.ndim == 3
    n_nodes = g_eff  # group-local histogram width

    # ---- histogram: [T, nodes, F, B, S] ------------------------------------
    if hist_impl == "pallas":
        # MXU one-hot matmul kernel per shard, explicit psum over the mesh
        # (sntc_tpu/ops/pallas_histogram.py)
        from jax.sharding import PartitionSpec as P

        from sntc_tpu.ops.pallas_histogram import level_histogram_pallas

        axis = mesh.axis_names[0]
        rs_spec = (
            P(None, axis, None) if per_tree_stats else P(axis, None)
        )

        def shard_fn(bt, rs, wt, ni):
            def hist_one(w_t, node_t, rs_t):
                active = (node_t >= 0).astype(rs_t.dtype)
                data = rs_t * (w_t * active)[:, None]
                return level_histogram_pallas(
                    bt, node_t, data,
                    n_nodes=n_nodes, n_bins=n_bins, interpret=interpret,
                )  # [F, nodes*B, S]

            if per_tree_stats:
                hs = jax.lax.map(lambda a: hist_one(*a), (wt, ni, rs))
            else:
                # shared stats stay closure-captured (no [T, n, S]
                # broadcast materialized per shard)
                hs = jax.lax.map(
                    lambda a: hist_one(a[0], a[1], rs), (wt, ni)
                )  # [T, F, nodes*B, S]
            return jax.lax.psum(hs, axis)

        hists = map_at(
            mesh, shard_fn,
            in_specs=(P(None, axis), rs_spec, P(None, axis), P(None, axis)),
            out_specs=P(),
            check_vma=False,  # pallas_call outputs carry no vma metadata
            jit=False,  # rebuilt per level; an outer jit would recompile
        )(binned_t, row_stats, w_trees, node_idx)
        record_collective(
            "tree.histogram", axis, mesh.shape[axis], payload_nbytes(hists)
        )
    elif (
        row_label is not None
        and row_weight is not None
        and not per_tree_stats
    ):
        # label-fused scalar scatter: one weight per row lands directly in
        # its (node, bin, class) cell.  The scan runs over ``binned_t``
        # rows so each feature's bins are a CONTIGUOUS [N] slab (a
        # ``binned[:, f]`` column gather is stride-F and dominated the
        # level cost on CPU: 2.0 s → 0.70 s at the depth-10 bench shapes)
        def hist_one_scalar(w_t, node_t):
            wv = jnp.where(node_t >= 0, w_t * row_weight, 0.0)
            base = (
                jnp.where(node_t >= 0, node_t, 0) * (n_bins * S) + row_label
            )

            def per_feature(carry, col):
                h = jax.ops.segment_sum(
                    wv, base + col * S, num_segments=n_nodes * n_bins * S
                )
                return carry, h.reshape(n_nodes * n_bins, S)

            _, hists = jax.lax.scan(per_feature, 0, binned_t)
            return hists  # [F, nodes*B, S]

        hists = jax.lax.map(
            lambda args: hist_one_scalar(*args), (w_trees, node_idx)
        )  # [T, F, nodes*B, S]
    else:
        def hist_one(w_t, node_t, rs_t):
            active = (node_t >= 0).astype(rs_t.dtype)
            ids = jnp.where(node_t >= 0, node_t, 0)
            data = rs_t * (w_t * active)[:, None]

            def per_feature(carry, col):
                seg = ids * n_bins + col
                h = jax.ops.segment_sum(
                    data, seg, num_segments=n_nodes * n_bins
                )
                return carry, h

            _, hists = jax.lax.scan(per_feature, 0, binned_t)
            return hists  # [F, nodes*B, S]

        if per_tree_stats:
            hists = jax.lax.map(
                lambda args: hist_one(*args), (w_trees, node_idx, row_stats)
            )
        else:
            hists = jax.lax.map(
                lambda args: hist_one(args[0], args[1], row_stats),
                (w_trees, node_idx),
            )  # [T, F, nodes*B, S]
    return hists.reshape(T, F, n_nodes, n_bins, S).transpose(0, 2, 1, 3, 4)


def _eval_from_hist(hist, fmask, min_instances, *, impurity):
    """Best-split evaluation over a group histogram [T, g, F, B, S]."""
    T, n_nodes, F, n_bins, S = hist.shape

    # ---- split evaluation --------------------------------------------------
    cum = jnp.cumsum(hist, axis=3)  # left stats for split at bin b
    parent = cum[:, :, 0, -1, :]  # [T, nodes, S]
    left = cum[:, :, :, :-1, :]  # [T, nodes, F, B-1, S]
    right = parent[:, :, None, None, :] - left

    imp_parent = _weighted_impurity(parent, impurity)  # [T, nodes]
    gain_w = (
        imp_parent[:, :, None, None]
        - _weighted_impurity(left, impurity)
        - _weighted_impurity(right, impurity)
    )
    parent_cnt = _stat_count(parent, impurity)
    gain = gain_w / jnp.maximum(parent_cnt, 1e-12)[:, :, None, None]

    valid = (
        (_stat_count(left, impurity) >= min_instances)
        & (_stat_count(right, impurity) >= min_instances)
    )
    if fmask is not None:  # per-(tree,node) feature subset, level-drawn
        valid = valid & fmask[:, :, :, None]
    gain = jnp.where(valid, gain, -jnp.inf)

    flat = gain.reshape(T, n_nodes, F * (n_bins - 1))
    best = jnp.argmax(flat, axis=2)
    best_gain = jnp.take_along_axis(flat, best[..., None], axis=2)[..., 0]
    best_feat = (best // (n_bins - 1)).astype(jnp.int32)
    best_bin = (best % (n_bins - 1)).astype(jnp.int32)

    # children stats of the chosen split (used directly at the last level)
    bf = best_feat[..., None, None, None]
    take_f = jnp.take_along_axis(left, bf.clip(0), axis=2)[:, :, 0]  # [T,nodes,B-1,S]
    bl = jnp.take_along_axis(
        take_f, best_bin[..., None, None].clip(0), axis=2
    )[:, :, 0]  # [T, nodes, S]
    br = parent - bl

    return {
        "best_feat": best_feat,
        "best_bin": best_bin,
        "best_gain": best_gain,
        "parent_stats": parent,
        "parent_count": parent_cnt,
        "left_stats": bl,
        "right_stats": br,
    }


@jax.jit
def _root_stats(row_stats, w_trees):
    if row_stats.ndim == 3:
        return jnp.einsum("tn,tns->ts", w_trees, row_stats)
    return jnp.einsum("tn,ns->ts", w_trees, row_stats)


def grow_forest(
    binned,  # [N, F] int32 (device, row-sharded)
    row_stats,  # [N, S] shared or [T, N, S] per-tree f32 (device, row-sharded)
    w_trees,  # [T, N] f32 (device, sharded on N axis=1)
    edges: np.ndarray,  # [F, B-1] host bin thresholds
    *,
    n_bins: int,
    max_depth: int,
    min_instances_per_node: float,
    min_info_gain: float,
    subset_k: int,
    impurity: str,
    seed: int,
    mesh=None,
    hist_impl: str = None,
    row_label=None,  # [N] int32 (device, row-sharded): class ids
    row_weight=None,  # [N] f32 (device, row-sharded): per-row weights
) -> Forest:
    """Grow T trees level-synchronously; returns host-side dense heaps.

    ``hist_impl``: "pallas" (MXU one-hot matmul kernel; requires ``mesh``)
    or "segment" (XLA scatter-add).  Default: pallas on TPU, segment
    elsewhere — profiled on a real v5e chip (RF 20×d5, 200k×78 rows, warm):
    pallas 5.6 s vs segment 15.5 s (2.75×; GBT OvR 13.1 s vs 48.1 s;
    scatter-adds serialize on TPU, the one-hot contraction rides the MXU).
    Resolved PER LEVEL: deep levels whose node×bin width would overflow
    the kernel's VMEM budget fall back to segment_sum while shallow levels
    keep the MXU path.  Overridable via the ``SNTC_TREE_HIST`` env var.

    ``row_label``/``row_weight``: classification callers whose
    ``row_stats`` satisfy ``one_hot(row_label) * row_weight[:, None]``
    pass both to unlock the label-fused scalar scatter (~6× less scatter
    traffic than the [N, S] vector scatter on CPU/segment levels).

    Sibling-histogram subtraction (LightGBM-style, beyond Spark's
    DTStatsAggregator) engages per level when the NEXT level runs the
    pallas one-hot kernel (where histogram cost ∝ node-axis width — the
    matmul halves; a segment_sum scatter costs O(N) regardless, so on
    CPU the kept-histogram traffic would be pure overhead) AND the
    previous level's full histogram fits ``SNTC_TREE_SIBLING_MB``
    (default 1024 MB): only left children are histogrammed from rows,
    right siblings are derived as parent − left.
    ``SNTC_TREE_SIBLING=0`` disables everywhere; ``=1`` forces it on
    segment levels too (tests).
    """
    from sntc_tpu.ops.pallas_histogram import (
        hist_fits_pallas,
        resolve_hist_impl,
    )

    on_tpu = jax.default_backend() == "tpu"
    # per-level histogram width is bounded by the node-group size
    # (Spark maxMemoryInMB analog), so deep levels can keep the pallas
    # kernel: its VMEM test sees the group width, not 2^d
    group = node_group_size(
        w_trees.shape[0], binned.shape[1], n_bins, row_stats.shape[-1]
    )
    if (
        on_tpu
        and mesh is not None
        and hist_impl is None
        and "SNTC_TREE_HIST" not in os.environ
        and "SNTC_TREE_NODE_GROUP_MB" not in os.environ
    ):
        # on TPU a group whose node×bin width overflows the kernel's
        # VMEM budget would silently fall back to segment_sum — and
        # scatter-adds SERIALIZE there (profiled 2.75–15× slower), which
        # costs far more than extra group passes.  Shrink the group until
        # every level rides the MXU.
        while group > 1 and not hist_fits_pallas(group, n_bins):
            group //= 2
    hist_impls = tuple(
        hist_impl
        if hist_impl is not None
        else resolve_hist_impl(min(1 << d, group), n_bins, mesh)
        for d in range(max(max_depth, 1))
    )
    if mesh is None:
        hist_impls = tuple("segment" for _ in hist_impls)
    interpret = not on_tpu
    # every histogram impl scans the transposed layout: contiguous
    # per-feature bins (pallas lane layout; stride-F column gathers
    # dominated CPU level cost otherwise)
    binned_t = jnp.transpose(binned)
    T = w_trees.shape[0]
    S = row_stats.shape[-1]
    H = (1 << (max_depth + 1)) - 1

    if max_depth == 0:
        feature = np.full((T, H), -2, np.int32)
        threshold = np.zeros((T, H), np.float32)
        leaf_stats = np.zeros((T, H, S), np.float32)
        stats = np.asarray(_root_stats(row_stats, w_trees))
        feature[:, 0] = -1
        leaf_stats[:, 0] = stats
        return Forest(feature, threshold, leaf_stats, max_depth,
                      np.zeros((T, H), np.float32), np.zeros((T, H), np.float32))

    # sibling subtraction: level d+1 can subtract iff level d's FULL
    # histogram is worth keeping device-resident (size gate) and the
    # group width admits (even, ≥2) left/right pairs.  Profitable ONLY
    # on the pallas path, where histogram cost ∝ node-axis width (the
    # one-hot matmul halves); a segment_sum scatter costs O(N) regardless
    # of width, so on CPU the kept-histogram traffic is pure overhead
    # (measured 2.1× slower at the depth-10 bench shapes).
    # SNTC_TREE_SIBLING=1 forces it everywhere (tests), =0 disables.
    sib_env = os.environ.get("SNTC_TREE_SIBLING", "")
    if sib_env not in ("", "0", "1"):
        import warnings

        warnings.warn(
            f"SNTC_TREE_SIBLING={sib_env!r} is not one of '', '0', '1'; "
            "using the default (pallas-gated on)",
            stacklevel=2,
        )
        sib_env = ""
    sib_on = group >= 2 and sib_env in ("", "1")
    sib_mb = float(os.environ.get("SNTC_TREE_SIBLING_MB", 1024))
    per_node_hist_mb = (
        T * binned.shape[1] * n_bins * S * 4 / (1024 * 1024)
    )
    keep_hists = tuple(
        sib_on
        and d < max_depth - 1
        # the level that WOULD subtract (d+1) must be on the matmul path
        and (hist_impls[d + 1] == "pallas" or sib_env == "1")
        and (1 << d) * per_node_hist_mb <= sib_mb
        for d in range(max_depth)
    )

    keys = jax.random.split(jax.random.PRNGKey(seed), max_depth)
    if os.environ.get("SNTC_TREE_LABEL_FUSED", "1") == "0":
        row_label = row_weight = None  # field kill-switch: generic path
    if row_label is not None:
        # out-of-range labels (e.g. a -1 sentinel) must contribute ZERO,
        # exactly like one_hot's out-of-range zero vector — a raw scatter
        # of `label - 1`-style indices would corrupt a neighboring cell
        row_label = row_label.astype(jnp.int32)
        in_range = (row_label >= 0) & (row_label < S)
        row_label = jnp.clip(row_label, 0, S - 1)
        if row_weight is not None:
            row_weight = jnp.where(in_range, row_weight, 0.0)
    out = _grow_fused(
        binned, binned_t, row_stats, row_label, row_weight, w_trees,
        jnp.asarray(edges), keys,
        jnp.float32(min_instances_per_node), jnp.float32(min_info_gain),
        max_depth=max_depth, n_bins=n_bins, impurity=impurity,
        subset_k=subset_k, group=group, hist_impls=hist_impls,
        keep_hists=keep_hists, mesh=mesh,
        interpret=interpret,
    )
    feature, threshold, leaf_stats, gain_arr, count_arr = (
        np.asarray(a) for a in out
    )
    return Forest(feature, threshold, leaf_stats, max_depth,
                  gain_arr, count_arr)


@partial(
    jax.jit,
    static_argnames=(
        "max_depth", "n_bins", "impurity", "subset_k", "group",
        "hist_impls", "keep_hists", "mesh", "interpret",
    ),
)
def _grow_fused(
    binned, binned_t, row_stats, row_label, row_weight, w_trees,
    edges_dev, keys,
    min_instances, min_info_gain,
    *, max_depth, n_bins, impurity, subset_k, group, hist_impls,
    keep_hists, mesh, interpret,
):
    """The WHOLE level-wise growth as one XLA program: the depth loop is
    unrolled at trace time, so every level keeps its exact node count
    (``2^d`` — no padding waste) and heap updates are static slices.  No
    host round trip per level — the forest leaves the device exactly once
    (SURVEY.md §1 restack: the per-level driver synchronization of Spark's
    ``while nodeStack`` loop disappears entirely)."""
    T, n = w_trees.shape
    S = row_stats.shape[-1]
    H = (1 << (max_depth + 1)) - 1

    feature = jnp.full((T, H), -2, jnp.int32)
    threshold = jnp.zeros((T, H), jnp.float32)
    leaf_stats = jnp.zeros((T, H, S), jnp.float32)
    gain_a = jnp.zeros((T, H), jnp.float32)
    count_a = jnp.zeros((T, H), jnp.float32)
    node_idx = jnp.zeros((T, n), jnp.int32)
    exists_lvl = jnp.ones((T, 1), bool)  # root exists

    prev_hist = None
    for depth in range(max_depth):
        n_nodes = 1 << depth
        off = n_nodes - 1
        out = _level_core(
            binned, binned_t, row_stats, row_label, row_weight,
            w_trees, node_idx, keys[depth],
            min_instances, min_info_gain, prev_hist,
            n_nodes=n_nodes, n_bins=n_bins, impurity=impurity,
            subset_k=subset_k, group=group,
            hist_impl=hist_impls[depth], mesh=mesh,
            interpret=interpret,
            route=depth < max_depth - 1,
            keep_hist=keep_hists[depth],
        )
        prev_hist = out.get("hist")
        split_mask = out["do_split"] & exists_lvl
        leaf_mask = exists_lvl & ~split_mask

        lvl = slice(off, off + n_nodes)
        bf_c, bb_c = out["best_feat"].clip(0), out["best_bin"].clip(0)
        feature = feature.at[:, lvl].set(
            jnp.where(split_mask, out["best_feat"],
                      jnp.where(exists_lvl, -1, -2))
        )
        threshold = threshold.at[:, lvl].set(
            jnp.where(split_mask, edges_dev[bf_c, bb_c], 0.0)
        )
        leaf_stats = leaf_stats.at[:, lvl, :].set(
            jnp.where(leaf_mask[..., None], out["parent_stats"], 0.0)
        )
        gain_a = gain_a.at[:, lvl].set(
            jnp.where(split_mask, out["best_gain"], 0.0)
        )
        count_a = count_a.at[:, lvl].set(
            jnp.where(split_mask, out["parent_count"], 0.0)
        )

        # children written as leaves with the chosen split's child stats;
        # the next (deeper) level overwrites its whole slice, re-deciding
        # which of them split further
        child_exists = jnp.repeat(split_mask, 2, axis=1)  # [T, 2*n_nodes]
        child_stats = jnp.stack(
            [out["left_stats"], out["right_stats"]], axis=2
        ).reshape(T, 2 * n_nodes, S)
        lvl2 = slice(off + n_nodes, off + 3 * n_nodes)
        feature = feature.at[:, lvl2].set(
            jnp.where(child_exists, -1, -2)
        )
        leaf_stats = leaf_stats.at[:, lvl2, :].set(
            jnp.where(child_exists[..., None], child_stats, 0.0)
        )

        exists_lvl = child_exists
        if depth < max_depth - 1:
            node_idx = out["new_node_idx"]

    return feature, threshold, leaf_stats, gain_a, count_a


@partial(jax.jit, static_argnames=("max_depth",))
def forest_leaf_stats(X, feature, threshold, leaf_stats, *, max_depth: int):
    """Serve: route each row down each tree, return leaf stats [T, N, S].

    Dense traversal: ``max_depth`` gathers, no data-dependent control flow —
    XLA-friendly (SURVEY.md §1 restack: "no dynamic DAG").
    """
    T = feature.shape[0]
    N = X.shape[0]
    node = jnp.zeros((T, N), jnp.int32)
    for _ in range(max_depth):
        f = jnp.take_along_axis(feature, node, axis=1)  # [T, N]
        is_internal = f >= 0
        fc = jnp.where(is_internal, f, 0)
        xv = jax.vmap(
            lambda f_t: jnp.take_along_axis(X, f_t[:, None], axis=1)[:, 0]
        )(fc)  # [T, N]
        thr = jnp.take_along_axis(threshold, node, axis=1)
        go_right = (xv >= thr).astype(jnp.int32)
        child = 2 * node + 1 + go_right
        node = jnp.where(is_internal, child, node)
    return jax.vmap(lambda ls_t, n_t: ls_t[n_t])(leaf_stats, node)  # [T, N, S]
