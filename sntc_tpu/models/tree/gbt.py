"""GBTClassifier — gradient-boosted trees, binary logistic loss [B:10].

Behavioral spec: SURVEY.md §2.3 (upstream
``ml/tree/impl/GradientBoostedTrees.scala`` + ``GBTClassifier`` [U]):
labels map to {-1, +1}; the first tree is a plain regression fit to the
signed labels (weight 1.0); each later round fits a variance-impurity
regression tree to the Friedman pseudo-residuals ``2y / (1 + exp(2·y·F))``
and adds it with ``stepSize`` (default 0.1) shrinkage; **binary only** —
the reference wraps OneVsRest for 15 classes.  ``rawPrediction`` is
``[-2F, 2F]`` and probability the logistic of it, matching Spark's
loss-based probability.

TPU design: reuses the binned grower (variance stats ``[w, wy, wy²]``);
per-round residual updates run on-device from the previous margins — the
dataset never leaves HBM across rounds (SURVEY.md §7.1 step 4).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.mlio import optimizer_checkpoint as _ckpt
from sntc_tpu.models.base import (
    CheckpointParams,
    ClassificationModel,
    ClassifierEstimator,
)
from sntc_tpu.models.tree.grower import (
    Forest,
    ForestDeviceMixin,
    forest_leaf_stats,
    grow_forest,
    resolve_feature_subset_k,
)
from sntc_tpu.models.tree.random_forest import _TreeEnsembleParams
from sntc_tpu.ops.binning import bin_features, quantile_bin_edges
from sntc_tpu.parallel.collectives import shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh


@jax.jit
def _residual_stats(y_signed, ws, margin):
    """Friedman pseudo-residuals for logistic loss -> variance stats."""
    r = 2.0 * y_signed / (1.0 + jnp.exp(2.0 * y_signed * margin))
    return jnp.stack([ws, ws * r, ws * r * r], axis=1)


@jax.jit
def _label_stats(y_signed, ws):
    return jnp.stack([ws, ws * y_signed, ws * y_signed**2], axis=1)


@partial(jax.jit, static_argnames=("max_depth",))
def _forest_margins(X, feature, threshold, leaf_stats, *, max_depth):
    """Per-tree mean-residual leaf values [T, N] (the vectorized
    one-vs-rest path: tree t is class t's tree for this round)."""
    stats = forest_leaf_stats(
        X, feature, threshold, leaf_stats, max_depth=max_depth
    )  # [T, N, 3]
    return stats[..., 1] / jnp.maximum(stats[..., 0], 1e-12)


@partial(jax.jit, static_argnames=("num_classes",))
def _ovr_signed_labels(ys, *, num_classes):
    """[K, N] signed one-vs-rest labels: +1 where y==k else -1."""
    k = jnp.arange(num_classes)[:, None]
    return (2.0 * (ys[None, :] == k) - 1.0).astype(jnp.float32)


@jax.jit
def _ovr_label_stats(y_signed, ws):
    return jax.vmap(lambda ysk: _label_stats(ysk, ws))(y_signed)  # [K,N,3]


@jax.jit
def _ovr_residual_stats(y_signed, ws, margins):
    return jax.vmap(
        lambda ysk, mk: _residual_stats(ysk, ws, mk)
    )(y_signed, margins)  # [K, N, 3]


def _prepare_boosting(classifier: "GBTClassifier", X, y, w, mesh):
    """Shared boosting setup for the sequential (binary, checkpointable)
    and vectorized one-vs-rest paths — ONE place for the bin edges,
    sharding, grower kwargs, and the per-round subsample-mask seed, so the
    two paths cannot drift apart (they must train identical trees)."""
    n, F = X.shape
    n_bins = classifier.getMaxBins()
    seed = classifier.getSeed()
    rate = classifier.getSubsamplingRate()

    edges = quantile_bin_edges(X, max_bins=n_bins, seed=seed)
    xs, ys, _ = shard_batch(mesh, X, y.astype(np.int32))
    ws = shard_weights(mesh, w, xs.shape[0])
    binned = bin_features(xs, jnp.asarray(edges))

    subset_k = resolve_feature_subset_k(
        classifier.getFeatureSubsetStrategy(), F, 1, is_classification=False
    )
    grow_kwargs = dict(
        n_bins=n_bins,
        max_depth=classifier.getMaxDepth(),
        min_instances_per_node=float(classifier.getMinInstancesPerNode()),
        min_info_gain=float(classifier.getMinInfoGain()),
        subset_k=subset_k,
        impurity="variance",
    )

    def round_mask(i: int) -> np.ndarray:
        """Host [n_pad] subsample mask for boosting round ``i`` —
        per-round seeded: resume-deterministic (checkpointing)."""
        if rate < 1.0:
            r = np.random.default_rng(seed + 7919 * (i + 1))
            return (r.random(xs.shape[0]) < rate).astype(np.float32)
        return np.ones(xs.shape[0], np.float32)

    return edges, xs, ys, ws, binned, grow_kwargs, round_mask


class _GbtParams(_TreeEnsembleParams):
    maxIter = Param("boosting rounds (trees)", default=20, validator=validators.gt(0))
    stepSize = Param("shrinkage", default=0.1, validator=validators.in_range(0, 1))
    lossType = Param(
        "boosting loss", default="logistic", validator=validators.one_of("logistic")
    )
    featureSubsetStrategy = Param("feature subset per node", default="all")
    validationIndicatorCol = Param(
        "boolean column marking validation rows; when set, boosting stops "
        "early on validation-loss plateau (Spark runWithValidation)",
        default=None,
    )
    validationTol = Param(
        "early-stop threshold on validation-loss improvement",
        default=0.01,
        validator=validators.gteq(0),
    )


def _validation_error(margin, y_signed, w):
    """Spark ``LogLoss.computeError``: weighted mean of
    ``2·log1p(exp(-2·y·F))`` over the validation rows."""
    loss = 2.0 * np.logaddexp(
        0.0,
        -2.0 * np.asarray(y_signed, np.float64) * np.asarray(margin, np.float64),
    )
    w = np.asarray(w, np.float64)
    return np.sum(w * loss, axis=-1) / np.sum(w)


class _ValidationTracker:
    """Spark ``GradientBoostedTrees.boost`` validated-stop bookkeeping.

    After round 0 the first error seeds ``best``; for each later round,
    stop when the improvement over ``best`` falls below
    ``tol * max(current, 0.01)``, else record a new best.  The final model
    keeps ``best_m`` trees (the stopping round's tree is discarded).
    ``k > 1`` tracks one-vs-rest classes independently (per-class stop,
    global loop end when all classes are done).
    """

    def __init__(self, tol: float, k: int = 1):
        self.tol = float(tol)
        self.best_err = np.full(k, np.inf)
        self.best_m = np.zeros(k, np.int64)
        self.done = np.zeros(k, bool)

    def update(self, round_idx: int, errs) -> bool:
        errs = np.atleast_1d(np.asarray(errs, np.float64))
        for i, err in enumerate(errs):
            if self.done[i]:
                continue
            if round_idx == 0:
                self.best_err[i] = err
                self.best_m[i] = 1
            elif self.best_err[i] - err < self.tol * max(err, 0.01):
                self.done[i] = True
            elif err < self.best_err[i]:
                self.best_err[i] = err
                self.best_m[i] = round_idx + 1
        return bool(self.done.all())


class GBTClassifier(_GbtParams, CheckpointParams, ClassifierEstimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "GBTClassificationModel":
        mesh = self._mesh or get_default_mesh()
        val_col = self.getValidationIndicatorCol()
        X_val = y_val = w_val = None
        if val_col:
            vmask = np.asarray(frame[val_col]).astype(bool)
            if not vmask.any() or vmask.all():
                raise ValueError(
                    "validationIndicatorCol must mark a non-empty proper "
                    "subset of rows"
                )
            X_val, y_val, w_val = self._extract(frame.filter(vmask))
            frame = frame.filter(~vmask)
        X, y, w = self._extract(frame)
        n, F = X.shape
        y_max = int(y.max(initial=0))
        if y_val is not None:
            # validation rows must satisfy the binary contract too
            y_max = max(y_max, int(y_val.max(initial=0)))
        if y_max > 1:
            raise ValueError(
                "GBTClassifier is binary-only (Spark parity); wrap in "
                "OneVsRest for multiclass [B:10]"
            )
        n_bins = self.getMaxBins()
        n_rounds = self.getMaxIter()
        step = self.getStepSize()
        axis = mesh.axis_names[0]

        edges, xs, ys, ws, binned, grow_kwargs, round_mask = _prepare_boosting(
            self, X, y, w, mesh
        )
        y_signed = (2.0 * ys - 1.0).astype(jnp.float32)

        def round_weights(i):
            return jax.device_put(
                round_mask(i)[None, :], NamedSharding(mesh, P(None, axis))
            )

        # mid-fit round checkpointing (SURVEY.md §5.4): resume skips
        # completed boosting rounds, restoring trees and margins
        ckpt_dir = self.getCheckpointDir()
        interval = self.getCheckpointInterval()
        # NOTE: keep in lockstep with GBTRegressor._fit's checkpoint block
        # (gbt_regressor.py).  n_shards: saved arrays are padded to the
        # mesh size — a resume on a different mesh must restart cleanly.
        fingerprint = {
            "algo": "gbt", "maxIter": n_rounds, "maxDepth": self.getMaxDepth(),
            "n_shards": int(mesh.shape[axis]),
            "stepSize": step, "seed": self.getSeed(), "n_rows": n,
            "maxBins": n_bins,
            "subsamplingRate": float(self.getSubsamplingRate()),
            "minInstancesPerNode": float(self.getMinInstancesPerNode()),
            "minInfoGain": float(self.getMinInfoGain()),
            "featureSubsetStrategy": str(self.getFeatureSubsetStrategy()),
            "validation": bool(val_col),
            "validationTol": float(self.getValidationTol()),
        }
        tracker = (
            _ValidationTracker(self.getValidationTol()) if val_col else None
        )
        if val_col:
            X_val_j = jnp.asarray(X_val)
            y_signed_val = 2.0 * y_val.astype(np.float64) - 1.0
            margin_val = np.zeros(len(y_val), np.float64)
        features, thresholds, leaves, weights = [], [], [], []
        gains, counts = [], []
        margin = jnp.zeros(xs.shape[0], jnp.float32)
        start_round = 0
        if ckpt_dir and interval > 0:
            saved = _ckpt.load_state(ckpt_dir, fingerprint)
            # "gain" guards against state files written by older layouts:
            # a missing key means restart rather than crash mid-resume
            ok = saved is not None and int(saved["round"]) > 0 and "gain" in saved
            if ok and val_col and "val_done" not in saved:
                ok = False
            if ok:
                start_round = int(saved["round"])
                features = list(saved["feature"])
                thresholds = list(saved["threshold"])
                leaves = list(saved["leaf_stats"])
                weights = list(saved["tree_weights"])
                gains = list(saved["gain"])
                counts = list(saved["count"])
                margin = jnp.asarray(saved["margin"])
                if val_col:
                    margin_val = np.asarray(saved["val_margin"], np.float64)
                    tracker.best_err = np.asarray(
                        saved["val_best_err"], np.float64
                    ).reshape(1)
                    tracker.best_m = np.asarray(
                        saved["val_best_m"], np.int64
                    ).reshape(1)
                    tracker.done = np.asarray(
                        saved["val_done"], bool
                    ).reshape(1)
                    start_round = n_rounds if tracker.done[0] else start_round
        stopped = False
        for m in range(start_round, n_rounds):
            if m == 0:
                row_stats = _label_stats(y_signed, ws)
                tree_weight = 1.0
            else:
                row_stats = _residual_stats(y_signed, ws, margin)
                tree_weight = step
            forest = grow_forest(
                binned, row_stats, round_weights(m), edges,
                seed=self.getSeed() + m, mesh=mesh, **grow_kwargs,
            )
            contrib = _forest_margins(
                xs,
                jnp.asarray(forest.feature),
                jnp.asarray(forest.threshold),
                jnp.asarray(forest.leaf_stats),
                max_depth=forest.max_depth,
            )[0]
            margin = margin + tree_weight * contrib
            features.append(forest.feature[0])
            thresholds.append(forest.threshold[0])
            leaves.append(forest.leaf_stats[0])
            gains.append(forest.gain[0])
            counts.append(forest.count[0])
            weights.append(tree_weight)
            if val_col:
                contrib_val = _forest_margins(
                    X_val_j,
                    jnp.asarray(forest.feature),
                    jnp.asarray(forest.threshold),
                    jnp.asarray(forest.leaf_stats),
                    max_depth=forest.max_depth,
                )[0]
                margin_val = margin_val + tree_weight * np.asarray(
                    contrib_val, np.float64
                )
                err = _validation_error(margin_val, y_signed_val, w_val)
                if tracker.update(m, err):
                    stopped = True
            if ckpt_dir and interval > 0 and (m + 1) % interval == 0:
                state = {
                    "round": m + 1,
                    "feature": np.stack(features),
                    "threshold": np.stack(thresholds),
                    "leaf_stats": np.stack(leaves),
                    "gain": np.stack(gains),
                    "count": np.stack(counts),
                    "tree_weights": np.asarray(weights, np.float32),
                    "margin": np.asarray(margin),
                }
                if val_col:
                    state["val_margin"] = margin_val
                    state["val_best_err"] = tracker.best_err
                    state["val_best_m"] = tracker.best_m
                    state["val_done"] = tracker.done
                _ckpt.save_state(ckpt_dir, state, fingerprint)
            if stopped:
                break

        if val_col:
            keep = int(tracker.best_m[0])
            features, thresholds = features[:keep], thresholds[:keep]
            leaves, weights = leaves[:keep], weights[:keep]
            gains, counts = gains[:keep], counts[:keep]
        if ckpt_dir and interval > 0:
            _ckpt.clear_state(ckpt_dir)
        ensemble = Forest(
            feature=np.stack(features),
            threshold=np.stack(thresholds),
            leaf_stats=np.stack(leaves),
            max_depth=self.getMaxDepth(),
            gain=np.stack(gains),
            count=np.stack(counts),
        )
        model = GBTClassificationModel(
            forest=ensemble,
            tree_weights=np.asarray(weights, np.float32),
            n_features=F,
        )
        model.setParams(
            **{k2: v for k2, v in self.paramValues().items() if model.hasParam(k2)}
        )
        # Spark 3.1+ BinaryGBTClassifierTrainingSummary (GBT is
        # binary-only upstream and here; OvR wraps it for 15 classes)
        from sntc_tpu.models.summary import (
            BinaryClassificationTrainingSummary,
        )

        model.summary = BinaryClassificationTrainingSummary(
            [], len(weights), model, frame,
            labelCol=self.getLabelCol(), mesh=mesh,
        )
        return model


@partial(jax.jit, static_argnames=("max_depth", "traversal"))
def _gbt_margin(X, feature, threshold, leaf_stats, tree_weights, *,
                max_depth, traversal="xla"):
    from sntc_tpu.kernels.forest import traverse_forest

    stats = traverse_forest(
        X, feature, threshold, leaf_stats, max_depth=max_depth,
        traversal=traversal,
    )  # [M, N, 3]
    values = stats[..., 1] / jnp.maximum(stats[..., 0], 1e-12)  # [M, N]
    return jnp.einsum("m,mn->n", tree_weights, values)


@partial(jax.jit, static_argnames=("max_depth", "traversal"))
def _ovr_fused_raw(X, feature, threshold, leaf_stats, sel, *, max_depth,
                   traversal="xla"):
    """Fused OneVsRest(GBT) raw scores: ONE traversal of all K classes'
    trees (concatenated on the tree axis) + a [K, M] class-selection
    contraction — K device dispatches per serving batch become one."""
    from sntc_tpu.kernels.forest import traverse_forest

    stats = traverse_forest(
        X, feature, threshold, leaf_stats, max_depth=max_depth,
        traversal=traversal,
    )  # [M, N, 3]
    values = stats[..., 1] / jnp.maximum(stats[..., 0], 1e-12)  # [M, N]
    margins = sel @ values  # [K, N]
    return (2.0 * margins).T  # raw class-1 score = 2F


@partial(jax.jit, static_argnames=("max_depth", "mode", "traversal"))
def _gbt_serve(
    X, feature, threshold, leaf_stats, tree_weights, thr, *, max_depth,
    mode, traversal="xla"
):
    """Traverse + margin + sigmoid + predict, packed: one dispatch and one
    device→host transfer per serving micro-batch."""
    from sntc_tpu.models.base import pack_serve_outputs

    m = _gbt_margin(
        X, feature, threshold, leaf_stats, tree_weights,
        max_depth=max_depth, traversal=traversal,
    )
    raw = jnp.stack([-2.0 * m, 2.0 * m], axis=1)
    p1 = jax.nn.sigmoid(2.0 * m)
    prob = jnp.stack([1.0 - p1, p1], axis=1)
    return pack_serve_outputs(raw, prob, thr, mode)


class GBTClassificationModel(_GbtParams, ForestDeviceMixin, ClassificationModel):
    def __init__(self, forest: Forest, tree_weights: np.ndarray,
                 n_features: int = 0, **kwargs):
        super().__init__(**kwargs)
        self.forest = forest
        self.treeWeights = np.asarray(tree_weights, np.float32)
        self._n_features = int(n_features)

    def _forest_arrays(self) -> tuple:
        return super()._forest_arrays() + (self.treeWeights,)

    @property
    def num_classes(self) -> int:
        return 2

    @property
    def numTrees(self) -> int:
        """Trees kept — ``< maxIter`` after a validated-boosting stop."""
        return int(len(self.treeWeights))

    def _save_extra(self):
        return (
            {"max_depth": self.forest.max_depth,
             "n_features": self._n_features},
            {
                "feature": self.forest.feature,
                "threshold": self.forest.threshold,
                "leaf_stats": self.forest.leaf_stats,
                "gain": self.forest.gain,
                "count": self.forest.count,
                "tree_weights": self.treeWeights,
            },
        )

    @classmethod
    def _load_from(cls, params, extra, arrays):
        forest = Forest(
            arrays["feature"], arrays["threshold"], arrays["leaf_stats"],
            int(extra["max_depth"]),
            arrays.get("gain"), arrays.get("count"),
        )
        m = cls(
            forest=forest,
            tree_weights=arrays["tree_weights"],
            n_features=int(extra.get("n_features", 0)),
        )
        m.setParams(**params)
        return m

    @property
    def featureImportances(self) -> np.ndarray:
        n = self._n_features or int(self.forest.feature.max()) + 1
        # Spark's GBTClassificationModel passes perTreeNormalization=false
        return self.forest.feature_importances(
            n, per_tree_normalization=False
        )

    def margin(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(
            _gbt_margin(
                jnp.asarray(X),
                *self._device_forest(),
                max_depth=self.forest.max_depth,
            )
        )

    def _raw_predict(self, X: np.ndarray) -> np.ndarray:
        m = self.margin(X)
        return np.stack([-2.0 * m, 2.0 * m], axis=1)

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        p1 = 1.0 / (1.0 + np.exp(-raw[:, 1]))
        return np.stack([1.0 - p1, p1], axis=1)

    def _predict_all_dev(self, X: np.ndarray):
        from sntc_tpu.kernels import serve_kernel_call

        mode, thr = self._threshold_mode()
        Xd = jnp.asarray(X)
        fa, ta, ls, tw = self._device_forest()
        md = self.forest.max_depth

        def run(traversal):
            return _gbt_serve(
                Xd, fa, ta, ls, tw, jnp.asarray(thr),
                max_depth=md, mode=mode, traversal=traversal,
            )

        return serve_kernel_call(
            "forest_traversal", (Xd, fa, ta, ls), run,
            lambda: run("xla"), static=(md, mode),
            guard_kwargs={
                "n_nodes": fa.shape[1], "n_features": Xd.shape[1],
                "n_stats": ls.shape[2], "itemsize": Xd.dtype.itemsize,
            },
        )


def fit_gbt_ovr_vectorized(
    classifier: "GBTClassifier",
    X: np.ndarray,
    y: np.ndarray,
    w: np.ndarray,
    num_classes: int,
    mesh,
    val_mask: Optional[np.ndarray] = None,
) -> list:
    """All K one-vs-rest binary GBT fits in ONE boosting loop [B:10].

    The class axis rides the grower's tree axis: every round grows K trees
    over the SAME binned features with per-class residual stats
    (``row_stats[K, N, 3]``) — K× fewer level passes, host syncs, and
    binning passes than OneVsRest's sequential sub-fits, and the K-wide
    histograms batch better on the MXU (SURVEY.md §7.2 item 4).

    Exactly reproduces the sequential fits when ``featureSubsetStrategy=
    "all"`` (the GBT default): the per-round subsampling mask is shared
    across classes, matching sequential OneVsRest where every class copy
    carries the same seed.  With feature subsetting the per-class random
    subsets differ from the sequential run (documented deviation).

    Validated boosting (``val_mask`` rows held out, Spark
    ``runWithValidation``): classes stop **per-class** — each class keeps
    its own ``best_m`` trees — while the joint loop runs until every class
    has plateaued (trees grown for already-done classes are discarded at
    truncation), exactly matching the sequential per-class sub-fits.

    Returns a list of K fitted :class:`GBTClassificationModel`.
    """
    if val_mask is not None:
        val_mask = np.asarray(val_mask).astype(bool)
        if not val_mask.any() or val_mask.all():
            raise ValueError(
                "validationIndicatorCol must mark a non-empty proper "
                "subset of rows"
            )
        X_val, y_val, w_val = X[val_mask], y[val_mask], w[val_mask]
        X, y, w = X[~val_mask], y[~val_mask], w[~val_mask]
    n, F = X.shape
    K = int(num_classes)
    n_rounds = classifier.getMaxIter()
    step = classifier.getStepSize()
    seed = classifier.getSeed()
    axis = mesh.axis_names[0]

    edges, xs, ys, ws, binned, grow_kwargs, round_mask = _prepare_boosting(
        classifier, X, y, w, mesh
    )
    tracker = None
    if val_mask is not None:
        tracker = _ValidationTracker(classifier.getValidationTol(), k=K)
        X_val_j = jnp.asarray(X_val)
        ks = np.arange(K)[:, None]
        y_signed_val = (
            2.0 * (y_val[None, :] == ks) - 1.0
        ).astype(np.float64)  # [K, Nv]
        margins_val = np.zeros((K, len(y_val)), np.float64)
    n_pad = xs.shape[0]
    y_signed = _ovr_signed_labels(ys, num_classes=K)  # [K, Np]
    row_sharding = NamedSharding(mesh, P(None, axis))

    # built once: a per-round jit(lambda) would retrace every round
    broadcast_k = jax.jit(
        lambda v: jnp.broadcast_to(v[None], (K, n_pad)),
        out_shardings=row_sharding,
    )

    def round_weights(i):
        # one [n_pad] host->device transfer; the K-way copy happens
        # on-device (no K redundant host buffers on the fit hot loop)
        return broadcast_k(
            jax.device_put(round_mask(i), NamedSharding(mesh, P(axis)))
        )

    margins = jax.device_put(np.zeros((K, n_pad), np.float32), row_sharding)
    feats, thrs, lvs, gns, cnts, wts = [], [], [], [], [], []
    for m in range(n_rounds):
        if m == 0:
            row_stats = _ovr_label_stats(y_signed, ws)
            tree_weight = 1.0
        else:
            row_stats = _ovr_residual_stats(y_signed, ws, margins)
            tree_weight = step
        forest = grow_forest(
            binned, row_stats, round_weights(m), edges,
            seed=seed + m, mesh=mesh, **grow_kwargs,
        )
        contribs = _forest_margins(
            xs,
            jnp.asarray(forest.feature),
            jnp.asarray(forest.threshold),
            jnp.asarray(forest.leaf_stats),
            max_depth=forest.max_depth,
        )  # [K, Np]
        margins = margins + tree_weight * contribs
        feats.append(forest.feature)
        thrs.append(forest.threshold)
        lvs.append(forest.leaf_stats)
        gns.append(forest.gain)
        cnts.append(forest.count)
        wts.append(tree_weight)
        if tracker is not None:
            contribs_val = _forest_margins(
                X_val_j,
                jnp.asarray(forest.feature),
                jnp.asarray(forest.threshold),
                jnp.asarray(forest.leaf_stats),
                max_depth=forest.max_depth,
            )  # [K, Nv]
            margins_val = margins_val + tree_weight * np.asarray(
                contribs_val, np.float64
            )
            errs = _validation_error(margins_val, y_signed_val, w_val)
            if tracker.update(m, errs):
                break

    tree_weights = np.asarray(wts, np.float32)
    models = []
    for c in range(K):
        keep = int(tracker.best_m[c]) if tracker is not None else len(feats)
        ensemble = Forest(
            feature=np.stack([f[c] for f in feats[:keep]]),
            threshold=np.stack([t[c] for t in thrs[:keep]]),
            leaf_stats=np.stack([l[c] for l in lvs[:keep]]),
            max_depth=classifier.getMaxDepth(),
            gain=np.stack([g[c] for g in gns[:keep]]),
            count=np.stack([ct[c] for ct in cnts[:keep]]),
        )
        model = GBTClassificationModel(
            forest=ensemble, tree_weights=tree_weights[:keep], n_features=F,
        )
        model.setParams(
            **{
                k2: v
                for k2, v in classifier.paramValues().items()
                if model.hasParam(k2)
            }
        )
        models.append(model)
    return models
