"""GBTClassifier — gradient-boosted trees, binary logistic loss [B:10].

Behavioral spec: SURVEY.md §2.3 (upstream
``ml/tree/impl/GradientBoostedTrees.scala`` + ``GBTClassifier`` [U]):
labels map to {-1, +1}; the first tree is a plain regression fit to the
signed labels (weight 1.0); each later round fits a variance-impurity
regression tree to the Friedman pseudo-residuals ``2y / (1 + exp(2·y·F))``
and adds it with ``stepSize`` (default 0.1) shrinkage; **binary only** —
the reference wraps OneVsRest for 15 classes.  ``rawPrediction`` is
``[-2F, 2F]`` and probability the logistic of it, matching Spark's
loss-based probability.

TPU design: reuses the binned grower (variance stats ``[w, wy, wy²]``);
per-round residual updates run on-device from the previous margins — the
dataset never leaves HBM across rounds (SURVEY.md §7.1 step 4).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.mlio import optimizer_checkpoint as _ckpt
from sntc_tpu.models.base import (
    CheckpointParams,
    ClassificationModel,
    ClassifierEstimator,
)
from sntc_tpu.models.tree.grower import (
    Forest,
    forest_leaf_stats,
    grow_forest,
    resolve_feature_subset_k,
)
from sntc_tpu.models.tree.random_forest import _TreeEnsembleParams
from sntc_tpu.ops.binning import bin_features, quantile_bin_edges
from sntc_tpu.parallel.collectives import shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh


@jax.jit
def _residual_stats(y_signed, ws, margin):
    """Friedman pseudo-residuals for logistic loss -> variance stats."""
    r = 2.0 * y_signed / (1.0 + jnp.exp(2.0 * y_signed * margin))
    return jnp.stack([ws, ws * r, ws * r * r], axis=1)


@jax.jit
def _label_stats(y_signed, ws):
    return jnp.stack([ws, ws * y_signed, ws * y_signed**2], axis=1)


@partial(jax.jit, static_argnames=("max_depth",))
def _forest_margins(X, feature, threshold, leaf_stats, *, max_depth):
    """Per-tree mean-residual leaf values [T, N] (the vectorized
    one-vs-rest path: tree t is class t's tree for this round)."""
    stats = forest_leaf_stats(
        X, feature, threshold, leaf_stats, max_depth=max_depth
    )  # [T, N, 3]
    return stats[..., 1] / jnp.maximum(stats[..., 0], 1e-12)


@partial(jax.jit, static_argnames=("num_classes",))
def _ovr_signed_labels(ys, *, num_classes):
    """[K, N] signed one-vs-rest labels: +1 where y==k else -1."""
    k = jnp.arange(num_classes)[:, None]
    return (2.0 * (ys[None, :] == k) - 1.0).astype(jnp.float32)


@jax.jit
def _ovr_label_stats(y_signed, ws):
    return jax.vmap(lambda ysk: _label_stats(ysk, ws))(y_signed)  # [K,N,3]


@jax.jit
def _ovr_residual_stats(y_signed, ws, margins):
    return jax.vmap(
        lambda ysk, mk: _residual_stats(ysk, ws, mk)
    )(y_signed, margins)  # [K, N, 3]


def _prepare_boosting(classifier: "GBTClassifier", X, y, w, mesh):
    """Shared boosting setup for the sequential (binary, checkpointable)
    and vectorized one-vs-rest paths — ONE place for the bin edges,
    sharding, grower kwargs, and the per-round subsample-mask seed, so the
    two paths cannot drift apart (they must train identical trees)."""
    n, F = X.shape
    n_bins = classifier.getMaxBins()
    seed = classifier.getSeed()
    rate = classifier.getSubsamplingRate()

    edges = quantile_bin_edges(X, max_bins=n_bins, seed=seed)
    xs, ys, _ = shard_batch(mesh, X, y.astype(np.int32))
    ws = shard_weights(mesh, w, xs.shape[0])
    binned = bin_features(xs, jnp.asarray(edges))

    subset_k = resolve_feature_subset_k(
        classifier.getFeatureSubsetStrategy(), F, 1, is_classification=False
    )
    grow_kwargs = dict(
        n_bins=n_bins,
        max_depth=classifier.getMaxDepth(),
        min_instances_per_node=float(classifier.getMinInstancesPerNode()),
        min_info_gain=float(classifier.getMinInfoGain()),
        subset_k=subset_k,
        impurity="variance",
    )

    def round_mask(i: int) -> np.ndarray:
        """Host [n_pad] subsample mask for boosting round ``i`` —
        per-round seeded: resume-deterministic (checkpointing)."""
        if rate < 1.0:
            r = np.random.default_rng(seed + 7919 * (i + 1))
            return (r.random(xs.shape[0]) < rate).astype(np.float32)
        return np.ones(xs.shape[0], np.float32)

    return edges, xs, ys, ws, binned, grow_kwargs, round_mask


class _GbtParams(_TreeEnsembleParams):
    maxIter = Param("boosting rounds (trees)", default=20, validator=validators.gt(0))
    stepSize = Param("shrinkage", default=0.1, validator=validators.in_range(0, 1))
    lossType = Param(
        "boosting loss", default="logistic", validator=validators.one_of("logistic")
    )
    featureSubsetStrategy = Param("feature subset per node", default="all")


class GBTClassifier(_GbtParams, CheckpointParams, ClassifierEstimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "GBTClassificationModel":
        mesh = self._mesh or get_default_mesh()
        X, y, w = self._extract(frame)
        n, F = X.shape
        if int(y.max(initial=0)) > 1:
            raise ValueError(
                "GBTClassifier is binary-only (Spark parity); wrap in "
                "OneVsRest for multiclass [B:10]"
            )
        n_bins = self.getMaxBins()
        n_rounds = self.getMaxIter()
        step = self.getStepSize()
        axis = mesh.axis_names[0]

        edges, xs, ys, ws, binned, grow_kwargs, round_mask = _prepare_boosting(
            self, X, y, w, mesh
        )
        y_signed = (2.0 * ys - 1.0).astype(jnp.float32)

        def round_weights(i):
            return jax.device_put(
                round_mask(i)[None, :], NamedSharding(mesh, P(None, axis))
            )

        # mid-fit round checkpointing (SURVEY.md §5.4): resume skips
        # completed boosting rounds, restoring trees and margins
        ckpt_dir = self.getCheckpointDir()
        interval = self.getCheckpointInterval()
        fingerprint = {
            "algo": "gbt", "maxIter": n_rounds, "maxDepth": self.getMaxDepth(),
            "stepSize": step, "seed": self.getSeed(), "n_rows": n,
            "maxBins": n_bins,
        }
        features, thresholds, leaves, weights = [], [], [], []
        gains, counts = [], []
        margin = jnp.zeros(xs.shape[0], jnp.float32)
        start_round = 0
        if ckpt_dir and interval > 0:
            saved = _ckpt.load_state(ckpt_dir, fingerprint)
            # "gain" guards against state files written by older layouts:
            # a missing key means restart rather than crash mid-resume
            if saved is not None and int(saved["round"]) > 0 and "gain" in saved:
                start_round = int(saved["round"])
                features = list(saved["feature"])
                thresholds = list(saved["threshold"])
                leaves = list(saved["leaf_stats"])
                weights = list(saved["tree_weights"])
                gains = list(saved["gain"])
                counts = list(saved["count"])
                margin = jnp.asarray(saved["margin"])
        for m in range(start_round, n_rounds):
            if m == 0:
                row_stats = _label_stats(y_signed, ws)
                tree_weight = 1.0
            else:
                row_stats = _residual_stats(y_signed, ws, margin)
                tree_weight = step
            forest = grow_forest(
                binned, row_stats, round_weights(m), edges,
                seed=self.getSeed() + m, mesh=mesh, **grow_kwargs,
            )
            contrib = _forest_margins(
                xs,
                jnp.asarray(forest.feature),
                jnp.asarray(forest.threshold),
                jnp.asarray(forest.leaf_stats),
                max_depth=forest.max_depth,
            )[0]
            margin = margin + tree_weight * contrib
            features.append(forest.feature[0])
            thresholds.append(forest.threshold[0])
            leaves.append(forest.leaf_stats[0])
            gains.append(forest.gain[0])
            counts.append(forest.count[0])
            weights.append(tree_weight)
            if ckpt_dir and interval > 0 and (m + 1) % interval == 0:
                _ckpt.save_state(
                    ckpt_dir,
                    {
                        "round": m + 1,
                        "feature": np.stack(features),
                        "threshold": np.stack(thresholds),
                        "leaf_stats": np.stack(leaves),
                        "gain": np.stack(gains),
                        "count": np.stack(counts),
                        "tree_weights": np.asarray(weights, np.float32),
                        "margin": np.asarray(margin),
                    },
                    fingerprint,
                )

        if ckpt_dir and interval > 0:
            _ckpt.clear_state(ckpt_dir)
        ensemble = Forest(
            feature=np.stack(features),
            threshold=np.stack(thresholds),
            leaf_stats=np.stack(leaves),
            max_depth=self.getMaxDepth(),
            gain=np.stack(gains),
            count=np.stack(counts),
        )
        model = GBTClassificationModel(
            forest=ensemble,
            tree_weights=np.asarray(weights, np.float32),
            n_features=F,
        )
        model.setParams(
            **{k2: v for k2, v in self.paramValues().items() if model.hasParam(k2)}
        )
        return model


@partial(jax.jit, static_argnames=("max_depth",))
def _gbt_margin(X, feature, threshold, leaf_stats, tree_weights, *, max_depth):
    stats = forest_leaf_stats(
        X, feature, threshold, leaf_stats, max_depth=max_depth
    )  # [M, N, 3]
    values = stats[..., 1] / jnp.maximum(stats[..., 0], 1e-12)  # [M, N]
    return jnp.einsum("m,mn->n", tree_weights, values)


class GBTClassificationModel(_GbtParams, ClassificationModel):
    def __init__(self, forest: Forest, tree_weights: np.ndarray,
                 n_features: int = 0, **kwargs):
        super().__init__(**kwargs)
        self.forest = forest
        self.treeWeights = np.asarray(tree_weights, np.float32)
        self._n_features = int(n_features)

    @property
    def num_classes(self) -> int:
        return 2

    def _save_extra(self):
        return (
            {"max_depth": self.forest.max_depth,
             "n_features": self._n_features},
            {
                "feature": self.forest.feature,
                "threshold": self.forest.threshold,
                "leaf_stats": self.forest.leaf_stats,
                "gain": self.forest.gain,
                "count": self.forest.count,
                "tree_weights": self.treeWeights,
            },
        )

    @classmethod
    def _load_from(cls, params, extra, arrays):
        forest = Forest(
            arrays["feature"], arrays["threshold"], arrays["leaf_stats"],
            int(extra["max_depth"]),
            arrays.get("gain"), arrays.get("count"),
        )
        m = cls(
            forest=forest,
            tree_weights=arrays["tree_weights"],
            n_features=int(extra.get("n_features", 0)),
        )
        m.setParams(**params)
        return m

    @property
    def featureImportances(self) -> np.ndarray:
        n = self._n_features or int(self.forest.feature.max()) + 1
        # Spark's GBTClassificationModel passes perTreeNormalization=false
        return self.forest.feature_importances(
            n, per_tree_normalization=False
        )

    def margin(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(
            _gbt_margin(
                jnp.asarray(X),
                jnp.asarray(self.forest.feature),
                jnp.asarray(self.forest.threshold),
                jnp.asarray(self.forest.leaf_stats),
                jnp.asarray(self.treeWeights),
                max_depth=self.forest.max_depth,
            )
        )

    def _raw_predict(self, X: np.ndarray) -> np.ndarray:
        m = self.margin(X)
        return np.stack([-2.0 * m, 2.0 * m], axis=1)

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        p1 = 1.0 / (1.0 + np.exp(-raw[:, 1]))
        return np.stack([1.0 - p1, p1], axis=1)


def fit_gbt_ovr_vectorized(
    classifier: "GBTClassifier",
    X: np.ndarray,
    y: np.ndarray,
    w: np.ndarray,
    num_classes: int,
    mesh,
) -> list:
    """All K one-vs-rest binary GBT fits in ONE boosting loop [B:10].

    The class axis rides the grower's tree axis: every round grows K trees
    over the SAME binned features with per-class residual stats
    (``row_stats[K, N, 3]``) — K× fewer level passes, host syncs, and
    binning passes than OneVsRest's sequential sub-fits, and the K-wide
    histograms batch better on the MXU (SURVEY.md §7.2 item 4).

    Exactly reproduces the sequential fits when ``featureSubsetStrategy=
    "all"`` (the GBT default): the per-round subsampling mask is shared
    across classes, matching sequential OneVsRest where every class copy
    carries the same seed.  With feature subsetting the per-class random
    subsets differ from the sequential run (documented deviation).

    Returns a list of K fitted :class:`GBTClassificationModel`.
    """
    n, F = X.shape
    K = int(num_classes)
    n_rounds = classifier.getMaxIter()
    step = classifier.getStepSize()
    seed = classifier.getSeed()
    axis = mesh.axis_names[0]

    edges, xs, ys, ws, binned, grow_kwargs, round_mask = _prepare_boosting(
        classifier, X, y, w, mesh
    )
    n_pad = xs.shape[0]
    y_signed = _ovr_signed_labels(ys, num_classes=K)  # [K, Np]
    row_sharding = NamedSharding(mesh, P(None, axis))

    # built once: a per-round jit(lambda) would retrace every round
    broadcast_k = jax.jit(
        lambda v: jnp.broadcast_to(v[None], (K, n_pad)),
        out_shardings=row_sharding,
    )

    def round_weights(i):
        # one [n_pad] host->device transfer; the K-way copy happens
        # on-device (no K redundant host buffers on the fit hot loop)
        return broadcast_k(
            jax.device_put(round_mask(i), NamedSharding(mesh, P(axis)))
        )

    margins = jax.device_put(np.zeros((K, n_pad), np.float32), row_sharding)
    feats, thrs, lvs, gns, cnts, wts = [], [], [], [], [], []
    for m in range(n_rounds):
        if m == 0:
            row_stats = _ovr_label_stats(y_signed, ws)
            tree_weight = 1.0
        else:
            row_stats = _ovr_residual_stats(y_signed, ws, margins)
            tree_weight = step
        forest = grow_forest(
            binned, row_stats, round_weights(m), edges,
            seed=seed + m, mesh=mesh, **grow_kwargs,
        )
        contribs = _forest_margins(
            xs,
            jnp.asarray(forest.feature),
            jnp.asarray(forest.threshold),
            jnp.asarray(forest.leaf_stats),
            max_depth=forest.max_depth,
        )  # [K, Np]
        margins = margins + tree_weight * contribs
        feats.append(forest.feature)
        thrs.append(forest.threshold)
        lvs.append(forest.leaf_stats)
        gns.append(forest.gain)
        cnts.append(forest.count)
        wts.append(tree_weight)

    tree_weights = np.asarray(wts, np.float32)
    models = []
    for c in range(K):
        ensemble = Forest(
            feature=np.stack([f[c] for f in feats]),
            threshold=np.stack([t[c] for t in thrs]),
            leaf_stats=np.stack([l[c] for l in lvs]),
            max_depth=classifier.getMaxDepth(),
            gain=np.stack([g[c] for g in gns]),
            count=np.stack([ct[c] for ct in cnts]),
        )
        model = GBTClassificationModel(
            forest=ensemble, tree_weights=tree_weights, n_features=F,
        )
        model.setParams(
            **{
                k2: v
                for k2, v in classifier.paramValues().items()
                if model.hasParam(k2)
            }
        )
        models.append(model)
    return models
