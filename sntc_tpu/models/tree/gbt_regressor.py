"""GBTRegressor — gradient-boosted regression trees.

Behavioral spec: upstream ``ml/regression/GBTRegressor.scala`` →
``tree/impl/GradientBoostedTrees`` [U]: the FIRST tree fits the raw
labels with weight 1.0 for both losses (we fit the raw residuals of the
constant mean init, which is equivalent); each later round fits a
variance-impurity tree to the loss's negative gradient — squared loss:
``r = y − F`` (leaf = mean residual); absolute loss: ``r = sign(y − F)``
with mean-of-sign leaves, exactly Spark's treatment — then
``F += stepSize · tree(x)``.  ``validationIndicatorCol``
/ ``validationTol`` stop boosting on a validation plateau
(``runWithValidation`` semantics, as in the classifier).

TPU design: the shared dense-heap grower (variance stats) per round,
boosted predictions updated ON DEVICE, serving is one traversal +
tree-weighted contraction — the classifier's machinery with the loss
swapped and no sigmoid.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.models.base import CheckpointParams
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.tree.grower import (
    Forest,
    ForestDeviceMixin,
    ForestPersistenceMixin,
    forest_leaf_stats,
    grow_forest,
    resolve_feature_subset_k,
)
from sntc_tpu.models.tree.random_forest import _TreeEnsembleParams
from sntc_tpu.ops.binning import bin_features, quantile_bin_edges
from sntc_tpu.parallel.collectives import shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh


@jax.jit
def _sq_residual_stats(ys, ws, pred):
    r = ys - pred
    return jnp.stack([ws, ws * r, ws * r * r], axis=1)


@jax.jit
def _abs_residual_stats(ys, ws, pred):
    r = jnp.sign(ys - pred)
    return jnp.stack([ws, ws * r, ws * r * r], axis=1)


@partial(jax.jit, static_argnames=("max_depth",))
def _tree_prediction(X, feature, threshold, leaf_stats, *, max_depth):
    """Leaf mean of a single-round [1, H] tree -> [N]."""
    stats = forest_leaf_stats(
        X, feature, threshold, leaf_stats, max_depth=max_depth
    )[0]
    return stats[:, 1] / jnp.maximum(stats[:, 0], 1e-12)


@partial(jax.jit, static_argnames=("max_depth",))
def _gbt_reg_predict(X, feature, threshold, leaf_stats, tree_weights, *,
                     max_depth):
    """F(x) = Σ_m w_m · tree_m(x): one traversal of all M trees + a
    weighted contraction (one dispatch on the serve path)."""
    stats = forest_leaf_stats(
        X, feature, threshold, leaf_stats, max_depth=max_depth
    )  # [M, N, 3]
    means = stats[..., 1] / jnp.maximum(stats[..., 0], 1e-12)
    return jnp.einsum("m,mn->n", tree_weights, means)


class _GbtRegParams(_TreeEnsembleParams):
    featuresCol = Param("feature vector column", default="features")
    labelCol = Param("target column", default="label")
    predictionCol = Param("output prediction column", default="prediction")
    maxIter = Param("boosting rounds (trees)", default=20, validator=validators.gt(0))
    stepSize = Param("shrinkage", default=0.1, validator=validators.in_range(0, 1))
    lossType = Param(
        "squared | absolute", default="squared",
        validator=validators.one_of("squared", "absolute"),
    )
    featureSubsetStrategy = Param("feature subset per node", default="all")
    validationIndicatorCol = Param(
        "boolean column marking validation rows; when set, boosting stops "
        "early on validation-loss plateau (Spark runWithValidation)",
        default=None,
    )
    validationTol = Param(
        "relative validation-improvement threshold", default=0.01,
        validator=validators.gteq(0),
    )


class GBTRegressor(_GbtRegParams, CheckpointParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "GBTRegressionModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                f"featuresCol {self.getFeaturesCol()!r} must be a vector "
                "column (use VectorAssembler)"
            )
        X = X.astype(np.float32, copy=False)
        y_all = np.asarray(frame[self.getLabelCol()], np.float32)
        val_col = self.getValidationIndicatorCol()
        if val_col:
            val_mask = np.asarray(frame[val_col]).astype(bool)
            if not val_mask.any() or val_mask.all():
                raise ValueError(
                    "validationIndicatorCol must mark a non-empty proper "
                    "subset of rows"
                )
            X_train, y = X[~val_mask], y_all[~val_mask]
            X_val, y_val = X[val_mask], y_all[val_mask]
        else:
            X_train, y = X, y_all
        n, F = X_train.shape
        n_bins = self.getMaxBins()
        n_rounds = int(self.getMaxIter())
        step = float(self.getStepSize())
        loss = self.getLossType()
        seed = self.getSeed()
        rate = self.getSubsamplingRate()

        edges = quantile_bin_edges(X_train, max_bins=n_bins, seed=seed)
        xs, ys, _ = shard_batch(mesh, X_train, y)
        ws = shard_weights(mesh, np.ones(n, np.float32), xs.shape[0])
        binned = bin_features(xs, jnp.asarray(edges))
        axis = mesh.axis_names[0]
        subset_k = resolve_feature_subset_k(
            self.getFeatureSubsetStrategy(), F, 1, is_classification=False
        )
        grow_kwargs = dict(
            n_bins=n_bins, max_depth=self.getMaxDepth(),
            min_instances_per_node=float(self.getMinInstancesPerNode()),
            min_info_gain=float(self.getMinInfoGain()),
            subset_k=subset_k, impurity="variance",
        )

        def round_weights(i):
            if rate < 1.0:
                r = np.random.default_rng(seed + 7919 * (i + 1))
                mask = (r.random(xs.shape[0]) < rate).astype(np.float32)
            else:
                mask = np.ones(xs.shape[0], np.float32)
            return jax.device_put(
                mask[None, :], NamedSharding(mesh, P(None, axis))
            )

        from sntc_tpu.mlio import optimizer_checkpoint as _ckpt
        from sntc_tpu.models.tree.gbt import _ValidationTracker

        init = float(np.mean(y)) if n else 0.0
        pred = jnp.full(xs.shape[0], init, jnp.float32)
        tracker = (
            _ValidationTracker(float(self.getValidationTol()))
            if val_col
            else None
        )
        if val_col:
            X_val_j = jnp.asarray(X_val)
            pred_val = np.full(len(y_val), init, np.float64)
        resid_fn = _sq_residual_stats if loss == "squared" else _abs_residual_stats
        features, thresholds, leaves = [], [], []
        gains, counts = [], []
        weights = []

        # mid-fit round checkpointing (SURVEY.md §5.4), mirroring the
        # classifier: resume skips completed boosting rounds
        ckpt_dir = self.getCheckpointDir()
        interval = self.getCheckpointInterval()
        # NOTE: keep this block in lockstep with GBTClassifier._fit's
        # checkpoint machinery (sntc_tpu/models/tree/gbt.py) — same
        # fingerprint keys, same save-before-break ordering.  n_shards
        # matters because the saved device arrays are PADDED to the mesh
        # size: a resume on a different mesh must restart, not splice.
        fingerprint = {
            "algo": "gbt_reg", "boost_v": 2, "maxIter": n_rounds,
            "n_shards": int(mesh.shape[axis]),
            "maxDepth": self.getMaxDepth(), "stepSize": step,
            "seed": seed, "n_rows": n, "maxBins": n_bins, "loss": loss,
            "subsamplingRate": float(rate),
            "minInstancesPerNode": float(self.getMinInstancesPerNode()),
            "minInfoGain": float(self.getMinInfoGain()),
            "featureSubsetStrategy": str(self.getFeatureSubsetStrategy()),
            "validation": bool(val_col),
            "validationTol": float(self.getValidationTol()),
        }
        start_round = 0
        if ckpt_dir and interval > 0:
            saved = _ckpt.load_state(ckpt_dir, fingerprint)
            if saved is not None and int(saved["round"]) > 0:
                start_round = int(saved["round"])
                features = list(saved["feature"])
                thresholds = list(saved["threshold"])
                leaves = list(saved["leaf_stats"])
                gains = list(saved["gain"])
                counts = list(saved["count"])
                weights = [float(v) for v in saved["tree_weights"]]
                pred = jnp.asarray(saved["pred"])
                if val_col:
                    pred_val = np.asarray(saved["val_pred"], np.float64)
                    tracker.best_err = np.asarray(
                        saved["val_best_err"], np.float64
                    ).reshape(1)
                    tracker.best_m = np.asarray(
                        saved["val_best_m"], np.int64
                    ).reshape(1)
                    tracker.done = np.asarray(saved["val_done"], bool).reshape(1)
                    if tracker.done[0]:
                        start_round = n_rounds
        for m in range(start_round, n_rounds):
            # Spark boost() fits the FIRST tree to the raw labels with
            # weight 1.0 for BOTH losses; fitting the raw residuals of the
            # constant init is equivalent (variance splits are
            # shift-invariant, leaf means shift by init).  Sign residuals
            # (absolute loss) apply only from the second tree on.
            row_stats = (
                _sq_residual_stats(ys, ws, pred)
                if m == 0
                else resid_fn(ys, ws, pred)
            )
            forest = grow_forest(
                binned, row_stats, round_weights(m), edges,
                seed=seed + m, mesh=mesh, **grow_kwargs,
            )
            contrib = _tree_prediction(
                xs, jnp.asarray(forest.feature),
                jnp.asarray(forest.threshold),
                jnp.asarray(forest.leaf_stats),
                max_depth=forest.max_depth,
            )
            tree_w = 1.0 if m == 0 else step
            pred = pred + tree_w * contrib
            features.append(forest.feature[0])
            thresholds.append(forest.threshold[0])
            leaves.append(forest.leaf_stats[0])
            gains.append(forest.gain[0])
            counts.append(forest.count[0])
            weights.append(tree_w)
            if val_col:
                contrib_val = np.asarray(
                    _tree_prediction(
                        X_val_j, jnp.asarray(forest.feature),
                        jnp.asarray(forest.threshold),
                        jnp.asarray(forest.leaf_stats),
                        max_depth=forest.max_depth,
                    ),
                    np.float64,
                )
                pred_val = pred_val + tree_w * contrib_val
                err = (
                    float(np.mean((y_val - pred_val) ** 2))
                    if loss == "squared"
                    else float(np.mean(np.abs(y_val - pred_val)))
                )
                # the classifier's Spark runWithValidation bookkeeping —
                # one stop rule for both GBTs
                stopped = tracker.update(m, err)
            else:
                stopped = False
            # save BEFORE honoring the stop so a resume sees done=True
            # (the classifier's ordering)
            if ckpt_dir and interval > 0 and (m + 1) % interval == 0:
                state = {
                    "round": np.int64(m + 1),
                    "feature": np.stack(features),
                    "threshold": np.stack(thresholds),
                    "leaf_stats": np.stack(leaves),
                    "gain": np.stack(gains),
                    "count": np.stack(counts),
                    "tree_weights": np.asarray(weights, np.float64),
                    "pred": np.asarray(pred),
                }
                if val_col:
                    state["val_pred"] = pred_val
                    state["val_best_err"] = tracker.best_err
                    state["val_best_m"] = tracker.best_m
                    state["val_done"] = tracker.done
                _ckpt.save_state(ckpt_dir, state, fingerprint)
            if stopped:
                break

        # a COMPLETED fit owns no checkpoint: leftover state would make a
        # later fit with the same dir silently return this model
        if ckpt_dir and interval > 0:
            _ckpt.clear_state(ckpt_dir)
        # validated boosting always trims to the best round, whether the
        # loop broke early or ran to maxIter (Spark keeps bestM trees)
        keep = int(tracker.best_m[0]) if tracker else len(features)
        forest = Forest(
            feature=np.stack(features[:keep]),
            threshold=np.stack(thresholds[:keep]),
            leaf_stats=np.stack(leaves[:keep]),
            max_depth=self.getMaxDepth(),
            gain=np.stack(gains[:keep]),
            count=np.stack(counts[:keep]),
        )
        model = GBTRegressionModel(
            forest=forest,
            init_prediction=init,
            treeWeights=[float(v) for v in weights[:keep]],
            n_features=F,
        )
        model.setParams(
            **{k2: v for k2, v in self.paramValues().items()
               if model.hasParam(k2)}
        )
        return model


class GBTRegressionModel(
    _GbtRegParams, ForestPersistenceMixin, ForestDeviceMixin, Model
):
    _per_tree_normalization = False  # boosted ensembles (Spark)

    def __init__(self, forest: Forest, init_prediction: float = 0.0,
                 treeWeights=(), n_features: int = 0, **kwargs):
        super().__init__(**kwargs)
        self.forest = forest
        self.init_prediction = float(init_prediction)
        self.treeWeights = [float(v) for v in treeWeights]
        self._n_features = int(n_features)

    @property
    def numTrees(self) -> int:
        return self.forest.feature.shape[0]

    def _extra_meta(self):
        return {
            "init_prediction": self.init_prediction,
            "treeWeights": self.treeWeights,
        }

    @classmethod
    def _from_forest(cls, forest, extra):
        return cls(
            forest=forest,
            init_prediction=float(extra.get("init_prediction", 0.0)),
            treeWeights=extra.get("treeWeights", []),
            n_features=int(extra.get("n_features", 0)),
        )

    def _forest_arrays(self):
        f = self.forest
        return (
            f.feature, f.threshold, f.leaf_stats,
            np.asarray(self.treeWeights, np.float32),
        )

    def predict(self, X: np.ndarray) -> np.ndarray:
        feature, threshold, leaf_stats, tw = self._device_forest()
        out = _gbt_reg_predict(
            jnp.asarray(X, jnp.float32), feature, threshold, leaf_stats, tw,
            max_depth=self.forest.max_depth,
        )
        return self.init_prediction + np.asarray(out, np.float64)

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()].astype(np.float32, copy=False)
        return frame.with_column(self.getPredictionCol(), self.predict(X))
