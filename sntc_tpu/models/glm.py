"""GeneralizedLinearRegression — IRLS GLMs on the MXU.

Behavioral spec: upstream ``ml/regression/GeneralizedLinearRegression.
scala`` [U] (Spark ML breadth beyond the reference's four estimators,
like KMeans/PCA): family × link GLMs fit by iteratively reweighted least
squares — per iteration, working response ``z = η + (y − μ)·g′(μ)`` and
weights ``W = w / (Var(μ)·g′(μ)²)`` feed one weighted normal-equation
solve.  Spark's supported (family, link) grid for the four families
implemented here; ``regParam`` is L2 (Spark GLR supports only L2).

TPU design: the WHOLE IRLS loop is one jitted ``lax.while_loop`` over
mesh-sharded rows — each iteration is two MXU contractions
(``Xᵀ(WX)`` [D+1, D+1] and ``Xᵀ(Wz)``) whose row-sums XLA all-reduces
over the mesh, plus an O(D³) host-free solve of a tiny system.  No
per-iteration host involvement (the Spark driver runs its WLS solve per
iteration on collected aggregates).

Summary parity: ``model.summary`` carries deviance / nullDeviance /
dispersion / residual degrees of freedom / ``totalIterations`` and
``aic`` — the R-family log-likelihood forms Spark mirrors (gaussian uses
the closed form from the deviance; binomial treats weights as trial
counts; gamma plugs the deviance-based dispersion), plus ``2·rank``.
Tweedie has no AIC in Spark and raises, as upstream does.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.parallel.collectives import shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh

_EPS = 1e-10
# probability clip must survive f32: 1 − 1e-10 rounds to exactly 1.0
# in f32 (log(1−μ) → −inf); 1e-6 is the tightest safely-representable gap
_MU_EPS = 1e-6

_FAMILIES = ("gaussian", "binomial", "poisson", "gamma", "tweedie")
_LINKS = ("identity", "log", "logit", "inverse", "sqrt", "cloglog", "probit")
_DEFAULT_LINK = {
    "gaussian": "identity",
    "binomial": "logit",
    "poisson": "log",
    "gamma": "inverse",
}
# Spark's supported (family, link) grid
_SUPPORTED = {
    "gaussian": ("identity", "log", "inverse"),
    "binomial": ("logit", "probit", "cloglog", "log"),
    "poisson": ("log", "identity", "sqrt"),
    "gamma": ("inverse", "identity", "log"),
}


def _link_fns(link: str):
    """(g, g_inv, g_prime) for η = g(μ).  ``power:<lp>`` is the tweedie
    power link μ^lp (lp = 0 means log), Spark's ``linkPower``."""
    sn = jax.scipy.stats.norm
    if link.startswith("power:"):
        lp = float(link.split(":", 1)[1])
        if lp == 0.0:
            return (jnp.log, jnp.exp, lambda m: 1.0 / m)
        if lp == 1.0:
            return (lambda m: m, lambda e: e, lambda m: jnp.ones_like(m))
        # μ > 0 for every non-identity power link, so η = μ^lp is
        # positive too — clamp unconditionally (fractional 1/lp on a
        # transiently negative η would silently NaN the IRLS loop)
        return (
            lambda m: m**lp,
            lambda e: jnp.maximum(e, _EPS) ** (1.0 / lp),
            lambda m: lp * m ** (lp - 1.0),
        )
    if link == "identity":
        return (lambda m: m, lambda e: e, lambda m: jnp.ones_like(m))
    if link == "log":
        return (jnp.log, jnp.exp, lambda m: 1.0 / m)
    if link == "logit":
        return (
            lambda m: jnp.log(m / (1.0 - m)),
            jax.nn.sigmoid,
            lambda m: 1.0 / (m * (1.0 - m)),
        )
    if link == "inverse":
        return (lambda m: 1.0 / m, lambda e: 1.0 / e, lambda m: -1.0 / m**2)
    if link == "sqrt":
        return (jnp.sqrt, lambda e: e**2, lambda m: 0.5 / jnp.sqrt(m))
    if link == "cloglog":
        return (
            lambda m: jnp.log(-jnp.log1p(-m)),
            lambda e: -jnp.expm1(-jnp.exp(e)),
            lambda m: -1.0 / ((1.0 - m) * jnp.log1p(-m)),
        )
    if link == "probit":
        return (
            sn.ppf,
            sn.cdf,
            lambda m: 1.0 / jnp.maximum(sn.pdf(sn.ppf(m)), _EPS),
        )
    raise ValueError(f"unknown link {link!r}")


def _link_inv_np(link: str):
    """numpy-float64 inverse link — the host-side twin of
    ``_link_fns(link)[1]`` for summary statistics (the lazy AIC pass),
    where routing eta through the jnp implementations would silently
    downcast the float64 linear predictor to float32."""
    from scipy.special import expit, ndtr

    if link.startswith("power:"):
        lp = float(link.split(":", 1)[1])
        if lp == 0.0:
            return np.exp
        if lp == 1.0:
            return lambda e: e
        return lambda e: np.maximum(e, _EPS) ** (1.0 / lp)
    try:
        return {
            "identity": lambda e: e,
            "log": np.exp,
            "logit": expit,
            "inverse": lambda e: 1.0 / e,
            "sqrt": lambda e: e**2,
            "cloglog": lambda e: -np.expm1(-np.exp(e)),
            "probit": ndtr,
        }[link]
    except KeyError:
        raise ValueError(f"unknown link {link!r}") from None


def _clip_mu_np(family: str, mu, var_power: float = 0.0):
    """Float64 host-side twin of :func:`_clip_mu` (same bounds)."""
    if family == "binomial":
        return np.clip(mu, _MU_EPS, 1.0 - _MU_EPS)
    if family in ("poisson", "gamma"):
        return np.maximum(mu, _EPS)
    if family == "tweedie" and var_power != 0.0:
        return np.maximum(mu, _EPS)
    return mu


def _tweedie_link(stage) -> str:
    """The ONE resolution of a tweedie stage's power link: an explicit
    ``power:<lp>`` string (as persisted on fitted models) passes
    through; otherwise linkPower, defaulting to 1 − variancePower."""
    link = stage.getLink()
    if link is not None:
        if not link.startswith("power:"):
            raise ValueError(
                "family='tweedie' uses linkPower, not link (Spark)"
            )
        try:
            return f"power:{float(link[6:])}"  # validate + normalize
        except ValueError:
            raise ValueError(
                f"malformed tweedie power link {link!r} (expected "
                "'power:<float>')"
            ) from None
    lp = stage.getLinkPower()
    if lp is None:
        lp = 1.0 - float(stage.getVariancePower())
    return f"power:{float(lp)}"


def _variance(family: str, mu, var_power: float = 0.0):
    if family == "gaussian":
        return jnp.ones_like(mu)
    if family == "binomial":
        return mu * (1.0 - mu)
    if family == "poisson":
        return mu
    if family == "tweedie":
        if var_power == 0.0:
            return jnp.ones_like(mu)
        return jnp.maximum(mu, _EPS) ** var_power
    return mu**2  # gamma


def _clip_mu(family: str, mu, var_power: float = 0.0):
    if family == "binomial":
        return jnp.clip(mu, _MU_EPS, 1.0 - _MU_EPS)
    if family in ("poisson", "gamma"):
        return jnp.maximum(mu, _EPS)
    if family == "tweedie" and var_power != 0.0:
        return jnp.maximum(mu, _EPS)  # μ > 0 whenever Var(μ) = μ^p, p ≥ 1
    return mu


def _deviance(family: str, y, mu, w, var_power: float = 0.0):
    """Unit deviance summed with weights (Spark/R semantics)."""
    if family == "tweedie":
        p = var_power
        if p == 0.0:
            return jnp.sum(w * (y - mu) ** 2)
        if p == 1.0:
            ylog = jnp.where(
                y > 0, y * jnp.log(jnp.maximum(y, _EPS) / mu), 0.0
            )
            return 2.0 * jnp.sum(w * (ylog - (y - mu)))
        if p == 2.0:
            return 2.0 * jnp.sum(
                w * (-jnp.log(jnp.maximum(y, _EPS) / mu) + (y - mu) / mu)
            )
        # general Tweedie unit deviance (y = 0 contributes only the μ
        # term for 1 < p < 2; labels are validated > 0 for p > 2)
        yp = jnp.maximum(y, 0.0)
        t1 = jnp.where(
            yp > 0,
            yp ** (2.0 - p) / ((1.0 - p) * (2.0 - p)),
            0.0,
        )
        t2 = y * mu ** (1.0 - p) / (1.0 - p)
        t3 = mu ** (2.0 - p) / (2.0 - p)
        return 2.0 * jnp.sum(w * (t1 - t2 + t3))
    if family == "gaussian":
        return jnp.sum(w * (y - mu) ** 2)
    if family == "binomial":
        yc = jnp.clip(y, _MU_EPS, 1.0 - _MU_EPS)
        # zero-coefficient terms guarded: 0 · log(·) must not see an inf
        t1 = jnp.where(y > 0, y * jnp.log(yc / mu), 0.0)
        t0 = jnp.where(
            y < 1, (1.0 - y) * jnp.log((1.0 - yc) / (1.0 - mu)), 0.0
        )
        return 2.0 * jnp.sum(w * (t1 + t0))
    if family == "poisson":
        ylog = jnp.where(y > 0, y * jnp.log(jnp.maximum(y, _EPS) / mu), 0.0)
        return 2.0 * jnp.sum(w * (ylog - (y - mu)))
    # gamma
    return 2.0 * jnp.sum(
        w * (-jnp.log(jnp.maximum(y, _EPS) / mu) + (y - mu) / mu)
    )


@partial(
    jax.jit,
    static_argnames=(
        "family", "link", "fit_intercept", "max_iter", "var_power",
    ),
)
def _irls(xs, ys, ws, beta0, *, family, link, fit_intercept, max_iter,
          tol, reg, var_power=0.0):
    """Whole-fit IRLS: ``lax.while_loop`` whose body is two sharded MXU
    contractions + one tiny solve.  ``xs`` is AUGMENTED with a ones
    column when ``fit_intercept`` (the intercept is just another
    coefficient, unpenalized)."""
    g, g_inv, g_prime = _link_fns(link)
    d_aug = xs.shape[1]
    # λ applies to the weight-AVERAGED Gram (Spark WeightedLeastSquares /
    # models/linear_regression.py convention): scale the diagonal by Σw
    # since A below is the raw weighted Gram
    pen = (reg * jnp.sum(ws)) * jnp.ones(d_aug)
    if fit_intercept:
        pen = pen.at[-1].set(0.0)

    def eta_mu(beta):
        eta = xs @ beta
        return eta, _clip_mu(family, g_inv(eta), var_power)

    def cond(state):
        _, it, delta = state
        return (it < max_iter) & (delta > tol)

    def body(state):
        beta, it, _ = state
        eta, mu = eta_mu(beta)
        gp = g_prime(mu)
        z = eta + (ys - mu) * gp
        wls = ws / jnp.maximum(
            _variance(family, mu, var_power) * gp**2, _EPS
        )
        xw = xs * wls[:, None]
        A = xs.T @ xw + jnp.diag(pen)  # [D+1, D+1]; XLA psums row-shards
        b = xw.T @ z
        beta_new = jax.scipy.linalg.solve(A, b, assume_a="pos")
        delta = jnp.max(jnp.abs(beta_new - beta)) / jnp.maximum(
            jnp.max(jnp.abs(beta)), 1.0
        )
        return beta_new, it + 1, delta

    beta, n_iter, _ = jax.lax.while_loop(
        cond, body, (beta0, jnp.int32(0), jnp.float32(jnp.inf))
    )
    _, mu = eta_mu(beta)
    dev = _deviance(family, ys, mu, ws, var_power)
    # null deviance: intercept-only model -> mu = weighted mean response
    ybar = jnp.sum(ws * ys) / jnp.maximum(jnp.sum(ws), _EPS)
    mu0 = _clip_mu(family, jnp.broadcast_to(ybar, ys.shape), var_power)
    dev0 = _deviance(family, ys, mu0, ws, var_power)
    # Pearson chi² (dispersion numerator)
    pearson = jnp.sum(
        ws * (ys - mu) ** 2
        / jnp.maximum(_variance(family, mu, var_power), _EPS)
    )
    return beta, n_iter, dev, dev0, pearson


def _aic(family: str, y, mu, w, n: int, dev: float, rank: int) -> float:
    """Spark's ``Family.aic`` + 2·rank (the R family $aic forms [U]).

    Host-side float64 one-pass — a summary statistic, not a fit cost.
    ``mu`` is the converged mean from the fitted linear predictor.
    """
    from scipy.special import gammaln

    y = np.asarray(y, np.float64)
    mu = np.asarray(mu, np.float64)
    w = np.asarray(w, np.float64)
    if family == "gaussian":
        # closed form from the deviance; R gaussian()$aic incl. −Σ log w
        ll2 = (
            n * (np.log(dev / n * 2.0 * np.pi) + 1.0)
            + 2.0
            - float(np.sum(np.log(w)))
        )
        return float(ll2 + 2.0 * rank)
    if family == "binomial":
        # weights are trial counts: Binomial(round(w), μ) log-pmf of
        # round(y·w) successes; weight-0 rows contribute 0 (Spark).
        # Scala math.round is half-UP — floor(x + 0.5) — not numpy's
        # banker's rounding (np.round(2.5) == 2, math.round(2.5) == 3),
        # and half-integer weights hit exactly that difference
        wt = np.floor(w + 0.5)
        r = np.floor(y * w + 0.5)
        mu_c = np.clip(mu, _MU_EPS, 1.0 - _MU_EPS)
        logpmf = (
            gammaln(wt + 1.0)
            - gammaln(r + 1.0)
            - gammaln(wt - r + 1.0)
            + r * np.log(mu_c)
            + (wt - r) * np.log1p(-mu_c)
        )
        ll = float(np.sum(np.where(wt == 0, 0.0, logpmf)))
        return float(-2.0 * ll + 2.0 * rank)
    if family == "poisson":
        yi = np.floor(y)  # Poisson pmf is over integers (Spark y.toInt)
        logpmf = yi * np.log(np.maximum(mu, _EPS)) - mu - gammaln(yi + 1.0)
        return float(-2.0 * np.sum(w * logpmf) + 2.0 * rank)
    if family == "gamma":
        # dispersion from the deviance (Spark/R plug-in), shape 1/φ,
        # scale μ·φ
        disp = dev / float(np.sum(w))
        shape = 1.0 / disp
        scale = mu * disp
        logpdf = (
            (shape - 1.0) * np.log(y)
            - y / scale
            - gammaln(shape)
            - shape * np.log(scale)
        )
        return float(-2.0 * np.sum(w * logpdf) + 2.0 + 2.0 * rank)
    raise AssertionError(f"_aic called for unsupported family {family!r}")


class _GlrParams:
    featuresCol = Param("feature vector column", default="features")
    labelCol = Param("target column", default="label")
    predictionCol = Param("output prediction column", default="prediction")
    linkPredictionCol = Param(
        "optional output column for the link-scale prediction η",
        default=None,
    )
    family = Param(
        "gaussian | binomial | poisson | gamma | tweedie", default="gaussian",
        validator=validators.one_of(*_FAMILIES),
    )
    link = Param(
        "identity | log | logit | inverse | sqrt | cloglog | probit "
        "(default: the family's canonical link)",
        default=None,
    )
    maxIter = Param("max IRLS iterations", default=25,
                    validator=validators.gt(0))
    tol = Param("relative coefficient-change tolerance", default=1e-6,
                validator=validators.gt(0))
    regParam = Param("L2 regularization (Spark GLR is L2-only)",
                     default=0.0, validator=validators.gteq(0))
    variancePower = Param(
        "tweedie variance power p (Var = mu^p): 0 or >= 1 (Spark)",
        default=0.0,
        validator=lambda v: v == 0.0 or v >= 1.0,
    )
    linkPower = Param(
        "tweedie link power (None -> 1 - variancePower; 0 means log)",
        default=None,
        validator=lambda v: v is None or isinstance(v, (int, float)),
    )
    fitIntercept = Param("fit an intercept", default=True,
                         validator=validators.is_bool())
    weightCol = Param("optional row weight column", default=None)


class GeneralizedLinearRegressionTrainingSummary:
    def __init__(self, *, deviance, null_deviance, pearson, n, rank,
                 family, total_iterations, aic=None):
        self.deviance = float(deviance)
        self.nullDeviance = float(null_deviance)
        self.residualDegreeOfFreedom = int(n - rank)
        self.residualDegreeOfFreedomNull = int(n - 1)
        self.totalIterations = int(total_iterations)
        # Spark: dispersion is 1 for binomial/poisson, Pearson χ² / dof
        # otherwise
        self.dispersion = (
            1.0
            if family in ("binomial", "poisson")
            else float(pearson) / max(n - rank, 1)
        )
        # a value, a zero-arg thunk (computed lazily like Spark's lazy
        # val — most callers never read aic), or None (tweedie)
        self._aic = aic

    @property
    def aic(self) -> float:
        # Spark raises for tweedie (no AIC defined); mirror that instead
        # of returning a junk number
        if self._aic is None:
            raise ValueError(
                "No AIC available for the tweedie family (Spark parity)"
            )
        if callable(self._aic):
            self._aic = float(self._aic())
        return self._aic

    @property
    def objectiveHistory(self):  # API-compat shim (IRLS keeps no trace)
        return []


class GeneralizedLinearRegression(_GlrParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _resolved_link(self) -> str:
        family = self.getFamily()
        if family == "tweedie":
            # tweedie ignores named links and uses linkPower (Spark [U]);
            # a persisted "power:<lp>" (from a fitted model's params)
            # passes through so clone-and-refit works
            return _tweedie_link(self)
        link = self.getLink() or _DEFAULT_LINK[family]
        if link not in _LINKS:
            raise ValueError(f"unknown link {link!r}; one of {_LINKS}")
        if link not in _SUPPORTED[family]:
            raise ValueError(
                f"link {link!r} is not supported for family {family!r} "
                f"(Spark grid: {_SUPPORTED[family]})"
            )
        return link

    def _fit(self, frame: Frame) -> "GeneralizedLinearRegressionModel":
        mesh = self._mesh or get_default_mesh()
        family = self.getFamily()
        link = self._resolved_link()
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                f"featuresCol {self.getFeaturesCol()!r} must be a vector "
                "column (use VectorAssembler)"
            )
        X = X.astype(np.float32, copy=False)
        y = np.asarray(frame[self.getLabelCol()], np.float32)
        if family == "binomial" and not np.all((y >= 0) & (y <= 1)):
            # Spark Binomial accepts the full [0, 1] range: fractional
            # labels are success PROPORTIONS with weightCol trial counts
            raise ValueError("binomial family needs labels in [0, 1]")
        if family in ("poisson", "gamma") and (y < 0).any():
            raise ValueError(f"{family} family needs non-negative labels")
        if family == "gamma" and (y == 0).any():
            raise ValueError("gamma family needs strictly positive labels")
        vp = float(self.getVariancePower()) if family == "tweedie" else 0.0
        if family == "tweedie":
            if vp >= 1.0 and (y < 0).any():
                raise ValueError(
                    "tweedie with variancePower >= 1 needs non-negative "
                    "labels"
                )
            if vp >= 2.0 and (y == 0).any():
                raise ValueError(
                    "tweedie with variancePower >= 2 needs strictly "
                    "positive labels"
                )
        wcol = self.getWeightCol()
        w = (
            np.asarray(frame[wcol], np.float32)
            if wcol
            else np.ones(len(y), np.float32)
        )
        n, d = X.shape
        fit_b = self.getFitIntercept()
        Xa = np.concatenate([X, np.ones((n, 1), np.float32)], axis=1) if fit_b else X
        xs, ys, _ = shard_batch(mesh, Xa, y)
        ws = shard_weights(mesh, w, xs.shape[0])

        # init: zero coefficients, intercept at g(weighted mean response)
        # (μ starts at the sample mean — safe for every supported link)
        beta0 = np.zeros(Xa.shape[1], np.float32)
        g, _, _ = _link_fns(link)
        ybar = float(np.average(y, weights=w)) if n else 0.0
        # clamp by what the LINK's domain needs, not just the family —
        # gaussian+log on a ≤0-mean response must not seed a NaN intercept
        if link in ("logit", "cloglog", "probit"):
            ybar = min(max(ybar, 1e-6), 1.0 - 1e-6)
        elif link in ("log", "inverse", "sqrt"):
            ybar = max(ybar, 1e-6)
        elif link.startswith("power:") and link != "power:1.0":
            ybar = max(ybar, 1e-6)
        if fit_b:
            beta0[-1] = float(g(jnp.float32(ybar)))

        beta, n_iter, dev, dev0, pearson = _irls(
            xs, ys, ws, jnp.asarray(beta0),
            family=family, link=link, fit_intercept=fit_b,
            max_iter=int(self.getMaxIter()),
            tol=jnp.float32(self.getTol()),
            reg=jnp.float32(self.getRegParam()),
            var_power=vp,
        )
        beta = np.asarray(beta, np.float64)
        coef = beta[:d] if fit_b else beta
        intercept = float(beta[-1]) if fit_b else 0.0
        model = GeneralizedLinearRegressionModel(
            coefficients=coef, intercept=intercept
        )
        model.setParams(
            **{k: v for k, v in self.paramValues().items()
               if model.hasParam(k)}
        )
        model.set("link", link)  # persist the RESOLVED link
        rank = d + (1 if fit_b else 0)
        if family == "tweedie":
            aic = None  # Spark: no AIC for tweedie; property raises
        else:
            # lazy (Spark lazy val): the O(n·d) host matmul + gammaln
            # pass only runs if summary.aic is actually read.  The
            # closure keeps Xa/y/w alive for the summary's lifetime —
            # the same retention Spark's summary-holds-DataFrame has.
            dev_f = float(dev)

            def aic(_Xa=Xa, _y=y, _w=w, _fam=family, _link=link, _vp=vp,
                    _beta=beta, _dev=dev_f, _n=n, _rank=rank):
                # float64 end to end: the jnp link fns would downcast
                # eta to f32 (jax x64 is off), costing digits the
                # "host-side float64 one-pass" contract promises
                g_inv = _link_inv_np(_link)
                eta = _Xa.astype(np.float64) @ _beta
                mu_fit = _clip_mu_np(
                    _fam, np.asarray(g_inv(eta), np.float64), _vp
                )
                return _aic(_fam, _y, mu_fit, _w, _n, _dev, _rank)
        model.summary = GeneralizedLinearRegressionTrainingSummary(
            deviance=dev, null_deviance=dev0, pearson=pearson, n=n,
            rank=rank, family=family, total_iterations=int(n_iter),
            aic=aic,
        )
        return model


@partial(jax.jit, static_argnames=("link",))
def _glm_predict(X, coef, intercept, *, link):
    _, g_inv, _ = _link_fns(link)
    eta = X @ coef + intercept
    return eta, g_inv(eta)


def _model_link(stage) -> str:
    """Resolve a fitted/hand-built model's link: the persisted value if
    set, else the family default (tweedie: power link from linkPower or
    1 − variancePower)."""
    link = stage.getLink()
    if link is not None:
        return link
    fam = stage.getFamily()
    if fam == "tweedie":
        return _tweedie_link(stage)
    return _DEFAULT_LINK[fam]


class GeneralizedLinearRegressionModel(_GlrParams, Model):
    def __init__(self, coefficients=None, intercept: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self.coefficients = np.asarray(
            coefficients if coefficients is not None else [], np.float64
        )
        self.intercept = float(intercept)
        self.summary: Optional[
            GeneralizedLinearRegressionTrainingSummary
        ] = None

    def _save_extra(self):
        return (
            {"intercept": self.intercept},
            {"coefficients": self.coefficients},
        )

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(
            coefficients=arrays["coefficients"],
            intercept=float(extra.get("intercept", 0.0)),
        )
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()].astype(np.float32, copy=False)
        link = _model_link(self)
        eta, mu = _glm_predict(
            jnp.asarray(X),
            jnp.asarray(self.coefficients, jnp.float32),
            jnp.float32(self.intercept),
            link=link,
        )
        out = frame.with_column(
            self.getPredictionCol(), np.asarray(mu, np.float64)
        )
        link_col = self.getLinkPredictionCol()
        if link_col:
            out = out.with_column(link_col, np.asarray(eta, np.float64))
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        _, mu = _glm_predict(
            jnp.asarray(np.asarray(X, np.float32)),
            jnp.asarray(self.coefficients, jnp.float32),
            jnp.float32(self.intercept),
            link=_model_link(self),
        )
        return np.asarray(mu, np.float64)
