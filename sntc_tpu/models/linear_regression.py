"""LinearRegression — least squares with elastic-net.

Behavioral spec: upstream ``ml/regression/LinearRegression.scala`` [U]:
minimize ``1/(2n) Σ wᵢ(yᵢ − ŷᵢ)² + λ(α‖coef‖₁ + (1−α)/2‖coef‖²)``
(the same objective family sklearn's ElasticNet uses, so sklearn is an
exact oracle when ``standardization=False``); ``solver`` ∈ auto |
normal | l-bfgs — "normal" solves the regularized normal equations
(only valid for α=0, as in Spark) and "auto" picks it whenever legal;
internal standardization with the penalty in the requested space
(``standardization`` flag); intercept never penalized.

TPU design: the WHOLE fit preamble (count, means, Gram, cross moments)
is ONE SPMD pass — the pilot-shifted Gram is a single MXU matmul per
shard ``psum``-reduced over ICI — and the ``[D, D]`` normal-equation
solve runs on host f64 (78×78 — trivial), falling back to the
minimum-norm lstsq solution on a singular Gram.  The iterative path reuses the shared jitted
LBFGS/OWLQN over mesh-sharded rows, centered+scaled like LinearSVC for
conditioning, with the shift folded back into the intercept.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.summary import TrainingSummary
from sntc_tpu.ops.lbfgs import minimize_lbfgs
from sntc_tpu.parallel.collectives import make_tree_aggregate, shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh


@lru_cache(maxsize=None)
def _normal_agg(mesh):
    """EVERYTHING the fit needs in ONE SPMD pass, accumulated about
    pilot points (a data row / the first target): weighted count, Σ(x−p),
    the Gram Σ(x−p)(x−p)ᵀ (one MXU matmul per shard), Σ(y−q) and the
    cross moments Σ(x−p)(y−q).  Means/variances/centered moments are
    reconstructed exactly in f64 on host — shift-invariant, no f32
    cancellation for large-mean features."""

    def moments(xs, ys, w, px, py):
        xc = xs - px[None, :]
        yc = (ys - py) * w
        wx = xc * w[:, None]
        return {
            "count": w.sum(),
            "sum": wx.sum(axis=0),
            "xxt": jnp.einsum("nd,ne->de", xc, wx),
            "sy": yc.sum(),
            "xy": (xc * yc[:, None]).sum(axis=0),
        }

    return make_tree_aggregate(moments, mesh, replicated_args=(3, 4))


@partial(jax.jit, static_argnames=("fit_intercept", "max_iter", "tol", "use_l1"))
def _linreg_optimize(
    xs, ys, ws, inv_std, mu, y_mean, reg_l2, pen_l2, l1_vec, theta0,
    *, fit_intercept, max_iter, tol, use_l1,
):
    """Elastic-net least squares as one cached XLA program (centered +
    scaled internal space; see LinearSVC for why centering precedes the
    matmul)."""
    d = xs.shape[1]
    w_sum = jnp.sum(ws)

    def value_and_grad(theta):
        def loss_fn(theta):
            coef = theta[:d]
            b = theta[d] if fit_intercept else jnp.zeros((), theta.dtype)
            pred = (xs - mu[None, :]) @ (coef * inv_std) + b
            resid = pred - (ys - y_mean)
            data = 0.5 * jnp.sum(ws * resid**2) / w_sum
            penalty = 0.5 * reg_l2 * jnp.sum(pen_l2 * coef**2)
            return data + penalty

        return jax.value_and_grad(loss_fn)(theta)

    return minimize_lbfgs(
        value_and_grad, theta0, max_iter=max_iter, tol=tol,
        l1=l1_vec if use_l1 else None,
    )


class _LinRegParams:
    featuresCol = Param("feature vector column", default="features")
    labelCol = Param("target column", default="label")
    predictionCol = Param("output prediction column", default="prediction")
    maxIter = Param("max iterations (l-bfgs)", default=100, validator=validators.gt(0))
    regParam = Param("regularization λ", default=0.0, validator=validators.gteq(0))
    elasticNetParam = Param(
        "α: 0 = ridge (L2), 1 = lasso (L1)", default=0.0,
        validator=validators.in_range(0, 1),
    )
    tol = Param("convergence tolerance", default=1e-6, validator=validators.gt(0))
    fitIntercept = Param("fit an intercept", default=True,
                         validator=validators.is_bool())
    standardization = Param(
        "standardize internally; penalty follows the flag (Spark)",
        default=True, validator=validators.is_bool())
    solver = Param(
        "auto | normal | l-bfgs", default="auto",
        validator=validators.one_of("auto", "normal", "l-bfgs"))
    weightCol = Param("optional row weight column", default=None)


class LinearRegression(_LinRegParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "LinearRegressionModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                f"featuresCol {self.getFeaturesCol()!r} must be a vector "
                "column (use VectorAssembler)"
            )
        X = X.astype(np.float32, copy=False)
        y = np.asarray(frame[self.getLabelCol()], np.float32)
        wcol = self.getWeightCol()
        w = (
            np.asarray(frame[wcol], np.float32)
            if wcol
            else np.ones(len(y), np.float32)
        )
        d = X.shape[1]
        lam = float(self.getRegParam())
        alpha = float(self.getElasticNetParam())
        solver = self.getSolver()
        if solver == "normal" and lam > 0 and alpha > 0:
            raise ValueError(
                "the normal solver supports no L1 term (Spark parity); "
                "use solver='l-bfgs' for elasticNetParam > 0"
            )
        use_normal = solver == "normal" or (
            solver == "auto" and (lam == 0 or alpha == 0)
        )

        xs, ys, _ = shard_batch(mesh, X, y)
        ws = shard_weights(mesh, w, xs.shape[0])
        fit_b = self.getFitIntercept()
        if not use_normal:
            # the iterative path needs only count/mean/var — the lighter
            # moments pass, not the O(N·D²) Gram the normal solver uses
            from sntc_tpu.feature.standard_scaler import (
                standardization_moments,
            )

            n_w, mean, var = standardization_moments(
                mesh, xs, ws,
                np.asarray(X[0]) if X.shape[0] else np.zeros(d),
            )
            std = np.sqrt(np.maximum(var, 0.0))
            inv_std = np.divide(
                1.0, std, out=np.ones_like(std), where=std > 0
            )
            y_mean = float(np.average(y, weights=w)) if len(y) else 0.0
            pen = np.ones(d) if self.getStandardization() else inv_std**2
            return self._fit_lbfgs(
                xs, ys, ws, inv_std, mean, y_mean, lam, alpha, pen, d, fit_b
            )
        px = np.asarray(X[0], np.float32) if X.shape[0] else np.zeros(d, np.float32)
        qy = np.float32(y[0]) if len(y) else np.float32(0.0)
        m = _normal_agg(mesh)(xs, ys, ws, jnp.asarray(px), qy)
        n_w = float(m["count"])
        n = max(n_w, 1e-300)
        sum_p = np.asarray(m["sum"], np.float64)  # Σw(x-p)
        gram_p = np.asarray(m["xxt"], np.float64)  # Σw(x-p)(x-p)ᵀ
        sy_p = float(m["sy"])  # Σw(y-q)
        xy_p = np.asarray(m["xy"], np.float64)  # Σw(x-p)(y-q)
        p64 = px.astype(np.float64)
        mean = p64 + sum_p / n
        y_mean = float(qy) + sy_p / n
        # centered second moments, exactly reconstructed (shift-invariant)
        gram_c = gram_p - np.outer(sum_p, sum_p) / n  # Σw(x-μ)(x-μ)ᵀ
        xy_c = xy_p - sum_p * (sy_p / n)  # Σw(x-μ)(y-ȳ)
        var = np.maximum(np.diag(gram_c) / n, 0.0)
        std = np.sqrt(var)
        inv_std = np.divide(1.0, std, out=np.ones_like(std), where=std > 0)
        # [D, D] host f64 solve of the (regularized) normal equations;
        # penalty in ORIGINAL coefficient space: λ·std²
        # (standardization=True penalizes θ = w·std) or λ·I
        pen_orig = std**2 if self.getStandardization() else np.ones(d)
        if fit_b:
            A = gram_c / n
            b_vec = xy_c / n
        else:
            # uncentered moments from the centered ones, exactly:
            # Σw·x·xᵀ = gram_c + n·μμᵀ ;  Σw·x·y = xy_c + n·ȳ·μ
            A = gram_c / n + np.outer(mean, mean)
            b_vec = xy_c / n + y_mean * mean
        A_reg = A + lam * np.diag(pen_orig)
        try:
            coef = np.linalg.solve(A_reg, b_vec)
        except np.linalg.LinAlgError:
            # singular Gram (duplicated/constant features): take the
            # minimum-norm least-squares solution — the Spark auto
            # solver's own fallback behavior
            coef = np.linalg.lstsq(A_reg, b_vec, rcond=None)[0]
        intercept = y_mean - float(mean @ coef) if fit_b else 0.0
        model = LinearRegressionModel(
            coefficients=coef, intercept=intercept
        )
        model.setParams(
            **{k2: v for k2, v in self.paramValues().items()
               if model.hasParam(k2)}
        )
        model.summary = TrainingSummary([0.0], 0)
        return model

    def _fit_lbfgs(
        self, xs, ys, ws, inv_std, mean, y_mean, lam, alpha, pen, d, fit_b
    ):
        l1 = np.zeros(d + 1 if fit_b else d, np.float32)
        l1[:d] = lam * alpha * np.sqrt(pen)
        theta0 = jnp.zeros((d + 1 if fit_b else d,), jnp.float32)
        mu_opt = mean.astype(np.float32) if fit_b else np.zeros(d, np.float32)
        ym = y_mean if fit_b else 0.0
        res = _linreg_optimize(
            xs, ys, ws, jnp.asarray(inv_std.astype(np.float32)),
            jnp.asarray(mu_opt), jnp.float32(ym),
            jnp.float32(lam * (1.0 - alpha)), jnp.asarray(pen.astype(np.float32)),
            jnp.asarray(l1), theta0,
            fit_intercept=fit_b, max_iter=int(self.getMaxIter()),
            tol=float(self.getTol()), use_l1=alpha > 0 and lam > 0,
        )
        theta = np.asarray(res.x, np.float64)
        coef = theta[:d] * inv_std
        intercept = (
            float(theta[d]) + y_mean - float(mu_opt.astype(np.float64) @ coef)
            if fit_b
            else 0.0
        )
        model = LinearRegressionModel(coefficients=coef, intercept=intercept)
        model.setParams(
            **{k2: v for k2, v in self.paramValues().items()
               if model.hasParam(k2)}
        )
        n_it = int(res.n_iters)
        model.summary = TrainingSummary(
            np.asarray(res.history)[: n_it + 1], n_it
        )
        return model


class LinearRegressionModel(_LinRegParams, Model):
    def __init__(self, coefficients: np.ndarray, intercept: float, **kwargs):
        super().__init__(**kwargs)
        self.coefficients = np.asarray(coefficients, np.float64)
        self.coefficients.flags.writeable = False
        self.intercept = float(intercept)
        self.summary = None

    def _save_extra(self):
        return {"intercept": self.intercept}, {"coefficients": self.coefficients}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(
            coefficients=arrays["coefficients"],
            intercept=float(extra["intercept"]),
        )
        m.setParams(**params)
        return m

    def predict(self, X: np.ndarray) -> np.ndarray:
        return (
            np.asarray(X, np.float64) @ self.coefficients + self.intercept
        )

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()]
        return frame.with_column(self.getPredictionCol(), self.predict(np.asarray(X)))
