from sntc_tpu.models.logistic_regression import (
    LogisticRegression,
    LogisticRegressionModel,
)
from sntc_tpu.models.mlp import (
    MultilayerPerceptronClassifier,
    MultilayerPerceptronClassificationModel,
)
from sntc_tpu.models.tree import (
    DecisionTreeClassifier,
    DecisionTreeClassificationModel,
    DecisionTreeRegressor,
    DecisionTreeRegressionModel,
    GBTClassifier,
    GBTClassificationModel,
    GBTRegressor,
    GBTRegressionModel,
    RandomForestClassifier,
    RandomForestClassificationModel,
    RandomForestRegressor,
    RandomForestRegressionModel,
)
from sntc_tpu.models.isotonic import (
    IsotonicRegression,
    IsotonicRegressionModel,
)
from sntc_tpu.models.kmeans import KMeans, KMeansModel
from sntc_tpu.models.fm import (
    FMClassificationModel,
    FMClassifier,
    FMRegressionModel,
    FMRegressor,
)
from sntc_tpu.models.gaussian_mixture import (
    GaussianMixture,
    GaussianMixtureModel,
)
from sntc_tpu.models.glm import (
    GeneralizedLinearRegression,
    GeneralizedLinearRegressionModel,
)
from sntc_tpu.models.linear_regression import LinearRegression, LinearRegressionModel
from sntc_tpu.models.linear_svc import LinearSVC, LinearSVCModel
from sntc_tpu.models.pic import PowerIterationClustering
from sntc_tpu.models.lda import LDA, LDAModel
from sntc_tpu.models.als import ALS, ALSModel
from sntc_tpu.models.fpm import FPGrowth, FPGrowthModel
from sntc_tpu.models.bisecting_kmeans import (
    BisectingKMeans,
    BisectingKMeansModel,
)
from sntc_tpu.models.aft import (
    AFTSurvivalRegression,
    AFTSurvivalRegressionModel,
)
from sntc_tpu.models.naive_bayes import NaiveBayes, NaiveBayesModel
from sntc_tpu.models.one_vs_rest import OneVsRest, OneVsRestModel

__all__ = [
    "AFTSurvivalRegression",
    "AFTSurvivalRegressionModel",
    "ALS",
    "ALSModel",
    "BisectingKMeans",
    "BisectingKMeansModel",
    "FPGrowth",
    "FPGrowthModel",
    "LDA",
    "LDAModel",
    "PowerIterationClustering",
    "RandomForestClassifier",
    "RandomForestClassificationModel",
    "RandomForestRegressor",
    "RandomForestRegressionModel",
    "GBTClassifier",
    "GBTClassificationModel",
    "GBTRegressor",
    "GBTRegressionModel",
    "DecisionTreeClassifier",
    "DecisionTreeClassificationModel",
    "DecisionTreeRegressor",
    "DecisionTreeRegressionModel",
    "IsotonicRegression",
    "IsotonicRegressionModel",
    "KMeans",
    "KMeansModel",
    "FMClassificationModel",
    "FMClassifier",
    "FMRegressionModel",
    "FMRegressor",
    "GaussianMixture",
    "GaussianMixtureModel",
    "GeneralizedLinearRegression",
    "GeneralizedLinearRegressionModel",
    "LinearRegression",
    "LinearRegressionModel",
    "LinearSVC",
    "LinearSVCModel",
    "NaiveBayes",
    "NaiveBayesModel",
    "OneVsRest",
    "OneVsRestModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "MultilayerPerceptronClassifier",
    "MultilayerPerceptronClassificationModel",
]
