"""LogisticRegression — binomial/multinomial elastic-net logit on TPU.

Behavioral spec: SURVEY.md §2.3/§3.1 (upstream
``ml/classification/LogisticRegression.scala`` + ``LogisticAggregator`` [U]):

  * ``family`` auto/binomial/multinomial; elastic-net via ``regParam`` ×
    ``elasticNetParam`` (L1 -> OWLQN, else LBFGS), intercepts unpenalized;
  * internal feature standardization during optimization (coefficients
    returned in the original space); ``standardization=False`` keeps the
    scaled optimization but re-weights the penalty so the objective matches
    penalizing original-space coefficients, as Spark does;
  * intercept initialized to label-prior log odds;
  * ``objectiveHistory`` preserved on the training summary (SURVEY.md §5.5).

TPU design: one summarizer ``tree_aggregate`` pass (moments + class counts),
then the whole LBFGS/OWLQN loop runs as ONE jitted XLA program over
mesh-sharded data (sntc_tpu.ops.lbfgs) — Spark's per-iteration
broadcast/treeAggregate/driver-update cycle with zero host round trips.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.base import (
    CheckpointParams,
    ClassificationModel,
    ClassifierEstimator,
)
from sntc_tpu.mlio.optimizer_checkpoint import run_segmented
from sntc_tpu.ops.lbfgs import minimize_lbfgs
from sntc_tpu.parallel.collectives import shard_batch, shard_weights
from sntc_tpu.parallel.context import get_default_mesh


from functools import partial


def _lr_summarize_impl(xs, ys, ws, k):
    return (
        jnp.einsum("n,nd->d", ws, xs),
        jnp.einsum("n,nd->d", ws, xs * xs),
        jnp.sum(ws),
        jax.ops.segment_sum(ws, ys, num_segments=k),
    )


@partial(jax.jit, static_argnames=("k",))
def _lr_summarize(xs, ys, ws, k):
    """Moments + class counts in one pass; with mesh-sharded inputs XLA
    inserts the ICI all-reduce (the summarizer treeAggregate of §3.1)."""
    return _lr_summarize_impl(xs, ys, ws, k)


def _lr_value_and_grad(
    theta, xs, ys, ws, inv_std, l2, pen_l2, w_sum,
    *, binomial, fit_intercept, k, n_coef,
):
    """Smooth objective + gradient shared by the single and grid fits."""
    d = xs.shape[1]

    def loss_fn(theta):
        coef = theta[:n_coef]
        W = coef.reshape(d, 1) if binomial else coef.reshape(d, k)
        b = (
            theta[n_coef:]
            if fit_intercept
            else jnp.zeros((1 if binomial else k,), theta.dtype)
        )
        Wd = W * inv_std[:, None]  # fold scaling into the matmul
        margins = xs @ Wd + b[None, :]
        if binomial:
            z = margins[:, 0]
            yf = ys.astype(z.dtype)
            data = jnp.sum(ws * (jnp.logaddexp(0.0, z) - yf * z))
        else:
            logp = jax.nn.log_softmax(margins, axis=1)
            picked = jnp.take_along_axis(
                logp, ys[:, None].astype(jnp.int32), axis=1
            )[:, 0]
            data = -jnp.sum(ws * picked)
        data = data / w_sum
        penalty = 0.5 * l2 * jnp.sum(pen_l2 * theta[:n_coef] ** 2)
        return data + penalty

    return jax.value_and_grad(loss_fn)(theta)


@partial(
    jax.jit,
    static_argnames=(
        "binomial", "fit_intercept", "k", "max_iter", "tol", "use_l1",
        "resume", "use_bounds",
    ),
)
def _lr_optimize(
    xs, ys, ws, inv_std, l2, pen_l2, l1_vec, theta0, init_state, iter_limit,
    lb, ub,
    *, binomial, fit_intercept, k, max_iter, tol, use_l1, resume=False,
    use_bounds=False,
):
    """The whole LBFGS/OWLQN fit as one cached XLA program.

    Module-level jit with data as (sharded) ARGUMENTS: repeated fits on the
    same shapes reuse the compiled executable instead of re-tracing a
    closure (compile once, fit many — the Spark-analog of reusing the same
    job DAG every iteration).
    """
    d = xs.shape[1]
    n_coef = d if binomial else d * k
    w_sum = jnp.sum(ws)

    def value_and_grad(theta):
        return _lr_value_and_grad(
            theta, xs, ys, ws, inv_std, l2, pen_l2, w_sum,
            binomial=binomial, fit_intercept=fit_intercept, k=k,
            n_coef=n_coef,
        )

    return minimize_lbfgs(
        value_and_grad,
        theta0,
        max_iter=max_iter,
        tol=tol,
        l1=l1_vec if use_l1 else None,
        init_state=init_state if resume else None,
        return_state=True,
        iter_limit=iter_limit,
        bounds=(lb, ub) if use_bounds else None,
    )


@partial(
    jax.jit,
    static_argnames=(
        "binomial", "fit_intercept", "k", "max_iter", "tol", "use_l1",
    ),
)
def _lr_optimize_grid(
    xs, ys, ws, inv_std, l2_b, pen_l2_b, l1_vec_b, theta0_b,
    *, binomial, fit_intercept, k, max_iter, tol, use_l1,
):
    """G grid points fit in ONE XLA program via ``vmap`` over the
    hyperparameter axis (SURVEY.md §2.5 "task parallelism": Spark's
    CrossValidator/OneVsRest thread pools overlap independent fits; on TPU
    the same overlap is a batched axis — every LBFGS iteration's G matmuls
    fuse into one MXU-batched contraction over the SHARED sharded data).

    Lanes run until all converge (vmapped ``while_loop``); each lane's own
    ``n_iters``/``converged`` are per-lane exact.
    """
    d = xs.shape[1]
    n_coef = d if binomial else d * k
    w_sum = jnp.sum(ws)

    def one(l2, pen_l2, l1_vec, theta0):
        def value_and_grad(theta):
            return _lr_value_and_grad(
                theta, xs, ys, ws, inv_std, l2, pen_l2, w_sum,
                binomial=binomial, fit_intercept=fit_intercept, k=k,
                n_coef=n_coef,
            )

        return minimize_lbfgs(
            value_and_grad, theta0, max_iter=max_iter, tol=tol,
            l1=l1_vec if use_l1 else None,
        )

    return jax.vmap(one)(l2_b, pen_l2_b, l1_vec_b, theta0_b)


@partial(
    jax.jit,
    static_argnames=(
        "binomial", "fit_intercept", "k", "max_iter", "tol", "use_l1",
    ),
)
def _lr_optimize_lanes(
    xs, ys, ws_folds, fold_idx_b, inv_std_b, l2_b, pen_l2_b, l1_vec_b,
    theta0_b,
    *, binomial, fit_intercept, k, max_iter, tol, use_l1,
):
    """Fold×grid lanes in ONE program: like :func:`_lr_optimize_grid` but
    each lane reads its OWN row-weight vector — a CV fold is just a 0/1
    weight mask over the shared sharded data — and carries its own
    standardization, so the whole k-fold × grid sweep becomes one vmapped
    LBFGS.  Lanes index ``ws_folds[F, N]`` by ``fold_idx`` in-program:
    the masks upload once (sharded), not once per lane."""
    d = xs.shape[1]
    n_coef = d if binomial else d * k

    def one(fold_idx, inv_std, l2, pen_l2, l1_vec, theta0):
        ws = ws_folds[fold_idx]
        w_sum = jnp.sum(ws)

        def value_and_grad(theta):
            return _lr_value_and_grad(
                theta, xs, ys, ws, inv_std, l2, pen_l2, w_sum,
                binomial=binomial, fit_intercept=fit_intercept, k=k,
                n_coef=n_coef,
            )

        return minimize_lbfgs(
            value_and_grad, theta0, max_iter=max_iter, tol=tol,
            l1=l1_vec if use_l1 else None,
        )

    return jax.vmap(one)(
        fold_idx_b, inv_std_b, l2_b, pen_l2_b, l1_vec_b, theta0_b
    )


@partial(
    jax.jit,
    static_argnames=("fit_intercept", "max_iter", "tol", "use_l1"),
)
def _lr_optimize_ovr(
    xs, ys, ws, inv_std, l2, pen_l2, l1_vec, class_ids, theta0_b,
    *, fit_intercept, max_iter, tol, use_l1,
):
    """K one-vs-rest BINARY fits in ONE program: lane c relabels the
    shared sharded labels in-program (``ys == c``) — Spark's OvR
    ``parallelism`` thread pool becomes a vmapped class axis over data
    that uploads once (SURVEY.md §2.5 task parallelism)."""
    d = xs.shape[1]
    w_sum = jnp.sum(ws)

    def one(cid, theta0):
        ys_c = (ys == cid).astype(jnp.int32)

        def value_and_grad(theta):
            return _lr_value_and_grad(
                theta, xs, ys_c, ws, inv_std, l2, pen_l2, w_sum,
                binomial=True, fit_intercept=fit_intercept, k=2, n_coef=d,
            )

        return minimize_lbfgs(
            value_and_grad, theta0, max_iter=max_iter, tol=tol,
            l1=l1_vec if use_l1 else None,
        )

    return jax.vmap(one)(class_ids, theta0_b)


@partial(jax.jit, static_argnames=("k",))
def _lr_summarize_folds(xs, ys, ws_b, k):
    """Per-fold summarizer: vmapped moments + class counts over per-lane
    weight vectors (each CV fold standardizes on ITS train split, exactly
    as a sequential sub-fit would)."""
    return jax.vmap(lambda ws: _lr_summarize_impl(xs, ys, ws, k))(ws_b)


from sntc_tpu.models.summary import (
    BinaryClassificationSummary,
    BinaryClassificationTrainingSummary,
    ClassificationSummary,
    ClassificationTrainingSummary,
    TrainingSummary,
)

# Spark-parity names (upstream LogisticRegression.scala summary classes):
# multinomial fits carry per-class metrics + objectiveHistory; binomial
# fits add the threshold curves (roc/pr/fMeasureByThreshold)
LogisticRegressionTrainingSummary = ClassificationTrainingSummary
BinaryLogisticRegressionTrainingSummary = BinaryClassificationTrainingSummary
LogisticRegressionSummary = ClassificationSummary
BinaryLogisticRegressionSummary = BinaryClassificationSummary


class _LrParams:
    maxIter = Param("max LBFGS/OWLQN iterations", default=100, validator=validators.gteq(0))
    regParam = Param("regularization strength", default=0.0, validator=validators.gteq(0))
    elasticNetParam = Param(
        "elastic-net mixing: 0=L2, 1=L1", default=0.0, validator=validators.in_range(0, 1)
    )
    tol = Param("relative convergence tolerance", default=1e-6, validator=validators.gt(0))
    fitIntercept = Param("fit intercept term", default=True, validator=validators.is_bool())
    standardization = Param(
        "standardize features during optimization", default=True,
        validator=validators.is_bool(),
    )
    family = Param(
        "binomial | multinomial | auto", default="auto",
        validator=validators.one_of("auto", "binomial", "multinomial"),
    )
    lowerBoundsOnCoefficients = Param(
        "coefficient lower bounds, shape [1, D] (binomial) or [K, D]; "
        "requires elasticNetParam contributions of L1 to be zero",
        default=None,
    )
    upperBoundsOnCoefficients = Param(
        "coefficient upper bounds, same shape as the lower bounds",
        default=None,
    )
    lowerBoundsOnIntercepts = Param(
        "intercept lower bounds, length 1 (binomial) or K", default=None
    )
    upperBoundsOnIntercepts = Param(
        "intercept upper bounds, length 1 (binomial) or K", default=None
    )


_BOUND_PARAMS = (
    "lowerBoundsOnCoefficients", "upperBoundsOnCoefficients",
    "lowerBoundsOnIntercepts", "upperBoundsOnIntercepts",
)


def _bounds_digest(lb: np.ndarray, ub: np.ndarray) -> str:
    import hashlib

    h = hashlib.md5()
    h.update(np.ascontiguousarray(lb, np.float32).tobytes())
    h.update(np.ascontiguousarray(ub, np.float32).tobytes())
    return h.hexdigest()


class LogisticRegression(_LrParams, CheckpointParams, ClassifierEstimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _build_bounds(self, d, k, binomial, n_coef, n_int, std):
        """Flatten user bounds into theta-ordered (lb, ub) vectors.

        Bounds are declared on ORIGINAL-space coefficients (Spark
        ``lowerBoundsOnCoefficients`` etc.); the optimizer works in the
        scaled space ``coef_scaled = coef_orig * std``, so coefficient
        bounds scale by ``std`` per feature.  Intercepts are never scaled.
        """
        lbc = self.getLowerBoundsOnCoefficients()
        ubc = self.getUpperBoundsOnCoefficients()
        lbi = self.getLowerBoundsOnIntercepts()
        ubi = self.getUpperBoundsOnIntercepts()
        if lbc is None and ubc is None and lbi is None and ubi is None:
            z = np.zeros(n_coef + n_int, np.float32)
            return z, z, False
        if n_int == 0 and (lbi is not None or ubi is not None):
            raise ValueError(
                "intercept bounds require fitIntercept=True (the bound "
                "would otherwise silently constrain nothing)"
            )
        rows = 1 if binomial else k
        lb = np.full(n_coef + n_int, -np.inf, np.float64)
        ub = np.full(n_coef + n_int, np.inf, np.float64)

        def coef_part(mat, name):
            m = np.asarray(mat, np.float64)
            if m.shape != (rows, d):
                raise ValueError(
                    f"{name} must have shape ({rows}, {d}), got {m.shape}"
                )
            # theta coefficient layout is [D, rows] flattened; ±inf entries
            # stay infinite (inf * 0 would be NaN on std=0 features).  A
            # finite bound on a zero-variance feature collapses to 0 — its
            # original-space coefficient is identically 0 anyway (Spark
            # reports 0 for constant features too).
            with np.errstate(invalid="ignore"):  # inf * 0 in the dead branch
                scaled = np.where(np.isinf(m), m, m * std[None, :])
            return scaled.T.reshape(-1)

        if lbc is not None:
            lb[:n_coef] = coef_part(lbc, "lowerBoundsOnCoefficients")
        if ubc is not None:
            ub[:n_coef] = coef_part(ubc, "upperBoundsOnCoefficients")
        if n_int:
            def int_part(vec, name):
                v = np.asarray(vec, np.float64).reshape(-1)
                if v.shape != (rows,):
                    raise ValueError(
                        f"{name} must have length {rows}, got {v.shape}"
                    )
                return v

            if lbi is not None:
                lb[n_coef:] = int_part(lbi, "lowerBoundsOnIntercepts")
            if ubi is not None:
                ub[n_coef:] = int_part(ubi, "upperBoundsOnIntercepts")
        if not (lb <= ub).all():
            raise ValueError("lower bounds must not exceed upper bounds")
        return lb, ub, True

    def _resolve_family(self, y, n):
        """(binomial, num_classes) with Spark's auto/validation rules."""
        num_classes = int(y.max()) + 1 if n else 2
        family = self.getFamily()
        if family == "auto":
            family = "binomial" if num_classes <= 2 else "multinomial"
        if family == "binomial" and num_classes > 2:
            raise ValueError(
                f"binomial family with {num_classes} classes; use multinomial"
            )
        return family == "binomial", max(num_classes, 2)

    @staticmethod
    def _moments_to_stats(s1, s2, cnt, cc):
        """(std, inv_std, class_counts) from one summarizer pass."""
        w_sum = max(float(cnt), 1e-12)
        mean = np.asarray(s1, np.float64) / w_sum
        var = np.maximum(np.asarray(s2, np.float64) / w_sum - mean**2, 0.0)
        std = np.sqrt(var)
        inv_std = np.divide(1.0, std, out=np.zeros_like(std), where=std > 0)
        return std, inv_std, np.maximum(np.asarray(cc, np.float64), 1e-12)

    def _prep_data(self, frame: Frame, mesh) -> dict:
        """Shared per-dataset prep: shard, summarize (one treeAggregate).

        Split out so the grid-batched fit (``_fit_grid``) pays for the data
        upload and summarizer pass ONCE across all grid points."""
        X, y, w = self._extract(frame)
        n, d = X.shape
        binomial, k = self._resolve_family(y, n)

        xs, ys, _ = shard_batch(mesh, X, y.astype(np.int32))
        ws = shard_weights(mesh, w, xs.shape[0])

        # ---- summarizer pass: moments + class counts (one treeAggregate) ----
        std, inv_std, class_counts = self._moments_to_stats(
            *_lr_summarize(xs, ys, ws, k)
        )
        return {
            "xs": xs, "ys": ys, "ws": ws, "n": n, "d": d, "k": k,
            "binomial": binomial, "std": std,
            "inv_std": inv_std, "class_counts": class_counts,
            # kept for the training summary (lazy predictions frame)
            "frame": frame, "mesh": mesh,
        }

    def _penalty_vectors(self, d: int, k: int, binomial: bool, inv_std):
        """Elastic-net penalty weights in the SCALED optimization space —
        the ONE encoding of Spark's standardization=True/False penalty
        semantics, shared by single fits, grid lanes, and OvR lanes."""
        reg = self.getRegParam()
        alpha = self.getElasticNetParam()
        l2 = reg * (1.0 - alpha)
        l1 = reg * alpha
        fit_intercept = self.getFitIntercept()
        standardize = self.getStandardization()
        n_coef = d if binomial else d * k
        n_int = (1 if binomial else k) if fit_intercept else 0
        pen_scale = np.ones(d) if standardize else inv_std
        pen_l2 = np.tile(pen_scale**2, 1 if binomial else k).astype(np.float32)
        l1_vec = np.concatenate(
            [l1 * np.tile(pen_scale, 1 if binomial else k), np.zeros(n_int)]
        ).astype(np.float32)
        return {
            "l2": np.float32(l2), "pen_l2": pen_l2, "l1_vec": l1_vec,
            "use_l1": l1 > 0, "n_coef": n_coef, "n_int": n_int,
        }

    def _grid_vectors(self, prep: dict) -> dict:
        """Per-grid-point optimizer inputs from shared prep (called on a
        ``copy(params)`` of the estimator for each grid point)."""
        d, k, binomial = prep["d"], prep["k"], prep["binomial"]
        vec = self._penalty_vectors(d, k, binomial, prep["inv_std"])
        n_coef, n_int = vec["n_coef"], vec["n_int"]
        class_counts = prep["class_counts"]
        theta0 = np.zeros(n_coef + n_int, dtype=np.float32)
        if self.getFitIntercept():
            # prior-log-odds intercept init (Spark parity)
            priors = class_counts / class_counts.sum()
            if binomial:
                theta0[n_coef] = np.log(priors[1] / priors[0]) if k == 2 else 0.0
            else:
                theta0[n_coef:] = np.log(priors)
        vec["theta0"] = theta0
        return vec

    def _theta_to_model(
        self, theta, prep, n_iters, history, use_bounds=False
    ) -> "LogisticRegressionModel":
        """Unscale + canonicalize a solution vector into a fitted model."""
        d, k, binomial = prep["d"], prep["k"], prep["binomial"]
        inv_std = prep["inv_std"]
        fit_intercept = self.getFitIntercept()
        reg = self.getRegParam()
        n_coef = d if binomial else d * k
        theta = np.asarray(theta, np.float64)
        W_scaled, b = (
            (theta[:n_coef].reshape(d, 1), theta[n_coef:])
            if binomial
            else (theta[:n_coef].reshape(d, k), theta[n_coef:])
        )
        coef_orig = W_scaled * inv_std[:, None]  # back to original space
        if binomial:
            coefficients = np.zeros((2, d))
            coefficients[1] = coef_orig[:, 0]
            intercepts = np.zeros(2)
            if fit_intercept:
                intercepts[1] = b[0]
            coef_matrix = coefficients
        else:
            coef_matrix = coef_orig.T  # [K, D]
            intercepts = np.asarray(
                b if fit_intercept else np.zeros(k), np.float64
            )
            # Spark canonicalization: the softmax is invariant to uniform
            # shifts; unpenalized intercepts are mean-centered, and with no
            # regularization the coefficients are too — SKIPPED under bound
            # constraints (centering could move them outside the box), as
            # Spark does
            if fit_intercept and not use_bounds:
                intercepts = intercepts - intercepts.mean()
            if reg == 0.0 and not use_bounds:
                coef_matrix = coef_matrix - coef_matrix.mean(
                    axis=0, keepdims=True
                )

        n_iters = int(n_iters)
        model = LogisticRegressionModel(
            coefficient_matrix=coef_matrix.astype(np.float32),
            intercepts=np.asarray(intercepts, np.float32),
            is_binomial=binomial,
        )
        model.setParams(
            **{
                name: val
                for name, val in self.paramValues().items()
                if model.hasParam(name)
            }
        )
        hist = np.asarray(history)[: n_iters + 1]
        if prep.get("frame") is None:
            # fold/grid lane sub-models (preps built without the source
            # frame) keep the lightweight record — per-class metrics on
            # throwaway sub-models would only pin extra frame references
            model.summary = TrainingSummary(hist, n_iters)
            return model
        summary_cls = (
            BinaryClassificationTrainingSummary
            if binomial
            else ClassificationTrainingSummary
        )
        model.summary = summary_cls(
            hist, n_iters, model, prep["frame"],
            labelCol=self.getLabelCol(), mesh=prep.get("mesh"),
        )
        return model

    # ---- grid-batched fitting (CrossValidator/TrainValidationSplit) ----

    _GRID_VARYING = frozenset(
        {"regParam", "elasticNetParam", "standardization"}
    )
    _GRID_UNIFORM = frozenset({"maxIter", "tol", "fitIntercept", "family"})

    def supports_batched_grid(self, param_maps) -> bool:
        """True if ``param_maps`` can run as ONE vmapped device program:
        every key is a hyperparameter the batched program accepts, compile-
        time (static) knobs are uniform across points, and no bound
        constraints or mid-fit checkpointing are in play."""
        if len(param_maps) < 2:
            return False
        keys = set().union(*param_maps)
        if not keys <= (self._GRID_VARYING | self._GRID_UNIFORM):
            return False
        for kk in keys & self._GRID_UNIFORM:
            vals = {m.get(kk, self.paramValues().get(kk)) for m in param_maps}
            if len(vals) > 1:
                return False
        if any(
            self.paramValues().get(p) is not None for p in _BOUND_PARAMS
        ):
            return False
        return not self._would_checkpoint()

    def _fit_grid_folds(self, frame: Frame, param_maps, fold_of, num_folds):
        """CrossValidator's ENTIRE k-fold × grid sweep in (at most two)
        device programs: a fold is a 0/1 row-weight mask over the shared
        sharded data, so (fold, grid point) lanes vmap together — data is
        uploaded once, each lane standardizes on its own fold's moments
        (matching a sequential sub-fit), and every LBFGS iteration batches
        all lanes' matmuls on the MXU.  Returns ``[num_folds][G]`` fitted
        models."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._mesh or get_default_mesh()
        ests = [self.copy(m) for m in param_maps]
        G = len(ests)
        X, y, w = self._extract(frame)
        n, d = X.shape
        binomial, k = ests[0]._resolve_family(y, n)

        xs, ys, _ = shard_batch(mesh, X, y.astype(np.int32))
        n_pad = xs.shape[0]
        fold_of = np.asarray(fold_of)
        masks = np.zeros((num_folds, n_pad), np.float32)
        for f in range(num_folds):
            masks[f, :n] = (fold_of != f) * w  # zero weight = not in fold
        axis = mesh.axis_names[0]
        ws_folds = jax.device_put(masks, NamedSharding(mesh, P(None, axis)))

        s1, s2, cnt, cc = _lr_summarize_folds(xs, ys, ws_folds, k)
        s1, s2, cnt, cc = (np.asarray(a, np.float64) for a in (s1, s2, cnt, cc))
        preps = []
        for f in range(num_folds):
            std, inv_std, class_counts = self._moments_to_stats(
                s1[f], s2[f], cnt[f], cc[f]
            )
            preps.append({
                "xs": xs, "ys": ys, "n": n, "d": d, "k": k,
                "binomial": binomial, "std": std, "inv_std": inv_std,
                "class_counts": class_counts,
            })

        vecs = [
            [ests[g]._grid_vectors(preps[f]) for g in range(G)]
            for f in range(num_folds)
        ]
        max_iter, tol = ests[0].getMaxIter(), ests[0].getTol()
        fit_intercept = ests[0].getFitIntercept()
        models = [[None] * G for _ in range(num_folds)]
        for flag in (False, True):
            lanes = [
                (f, g)
                for f in range(num_folds)
                for g in range(G)
                if bool(vecs[f][g]["use_l1"]) == flag
            ]
            if not lanes:
                continue
            res = _lr_optimize_lanes(
                xs, ys,
                ws_folds,
                jnp.asarray(
                    np.asarray([f for f, _ in lanes], np.int32)
                ),
                jnp.asarray(
                    np.stack(
                        [preps[f]["inv_std"] for f, _ in lanes]
                    ).astype(np.float32)
                ),
                jnp.asarray(np.stack([vecs[f][g]["l2"] for f, g in lanes])),
                jnp.asarray(
                    np.stack([vecs[f][g]["pen_l2"] for f, g in lanes])
                ),
                jnp.asarray(
                    np.stack([vecs[f][g]["l1_vec"] for f, g in lanes])
                ),
                jnp.asarray(
                    np.stack([vecs[f][g]["theta0"] for f, g in lanes])
                ),
                binomial=binomial,
                fit_intercept=fit_intercept,
                k=k,
                max_iter=max_iter,
                tol=tol,
                use_l1=flag,
            )
            xs_h = np.asarray(res.x)
            iters_h = np.asarray(res.n_iters)
            hist_h = np.asarray(res.history)
            for lane, (f, g) in enumerate(lanes):
                models[f][g] = ests[g]._theta_to_model(
                    xs_h[lane], preps[f], iters_h[lane], hist_h[lane]
                )
        return models

    def supports_vectorized_ovr(self) -> bool:
        """True when OneVsRest can run this classifier's K binary fits as
        one vmapped program: binomial-compatible family, no bound
        constraints, no mid-fit checkpointing."""
        if self.getFamily() == "multinomial":
            return False  # a 2-class softmax parameterization differs
        if any(
            self.paramValues().get(p) is not None for p in _BOUND_PARAMS
        ):
            return False
        return not self._would_checkpoint()

    def _would_checkpoint(self) -> bool:
        """True iff a fit would actually persist mid-fit state — the gate
        ``run_segmented`` itself uses (interval AND dir set); batched
        paths defer to the sequential fit only in that case."""
        return (
            self.getCheckpointInterval() > 0
            and bool(self.getCheckpointDir())
        )

    def _fit_ovr_lanes(self, X, y, w, k, mesh):
        """K one-vs-rest binary models fit in one device program (see
        ``_lr_optimize_ovr``): the summarizer runs once (moments are
        class-independent), per-class intercepts init to each class's
        prior log odds, and lane c's labels are relabeled in-program."""
        n, d = X.shape
        xs, ys, _ = shard_batch(mesh, X, y.astype(np.int32))
        ws = shard_weights(mesh, w, xs.shape[0])
        std, inv_std, class_counts = self._moments_to_stats(
            *_lr_summarize(xs, ys, ws, k)
        )
        w_sum = float(class_counts.sum())

        fit_intercept = self.getFitIntercept()
        vec = self._penalty_vectors(d, 2, True, inv_std)
        n_int = vec["n_int"]

        theta0_b = np.zeros((k, d + n_int), np.float32)
        if fit_intercept:
            # per-class prior log odds — what each sequential relabeled
            # sub-fit's _grid_vectors init would compute
            pos = class_counts / max(w_sum, 1e-12)
            theta0_b[:, d] = np.log(
                np.maximum(pos, 1e-12) / np.maximum(1.0 - pos, 1e-12)
            )

        res = _lr_optimize_ovr(
            xs, ys, ws,
            jnp.asarray(inv_std, jnp.float32),
            jnp.asarray(vec["l2"]),
            jnp.asarray(vec["pen_l2"]),
            jnp.asarray(vec["l1_vec"]),
            jnp.arange(k, dtype=jnp.int32),
            jnp.asarray(theta0_b),
            fit_intercept=fit_intercept,
            max_iter=self.getMaxIter(),
            tol=self.getTol(),
            use_l1=bool(vec["use_l1"]),
        )
        xs_h = np.asarray(res.x)
        iters_h = np.asarray(res.n_iters)
        hist_h = np.asarray(res.history)
        prep = {
            "n": n, "d": d, "k": 2, "binomial": True,
            "std": std, "inv_std": inv_std,
        }
        return [
            self._theta_to_model(
                xs_h[c], prep, iters_h[c], hist_h[c]
            )
            for c in range(k)
        ]

    def _fit_grid(self, frame: Frame, param_maps):
        """Fit all ``param_maps`` over the SAME frame in (at most two)
        batched device programs; returns one fitted model per map, in
        order.  Data upload + summarizer run once; L1 (OWLQN) and L2-only
        (plain LBFGS) points batch separately — their update rules differ
        in-program (static ``use_l1``)."""
        mesh = self._mesh or get_default_mesh()
        ests = [self.copy(m) for m in param_maps]
        prep = ests[0]._prep_data(frame, mesh)
        vecs = [e._grid_vectors(prep) for e in ests]
        max_iter = ests[0].getMaxIter()
        tol = ests[0].getTol()
        fit_intercept = ests[0].getFitIntercept()

        models: list = [None] * len(ests)
        for flag in (False, True):
            idxs = [i for i, v in enumerate(vecs) if bool(v["use_l1"]) == flag]
            if not idxs:
                continue
            res = _lr_optimize_grid(
                prep["xs"], prep["ys"], prep["ws"],
                jnp.asarray(prep["inv_std"], jnp.float32),
                jnp.asarray(np.stack([vecs[i]["l2"] for i in idxs])),
                jnp.asarray(np.stack([vecs[i]["pen_l2"] for i in idxs])),
                jnp.asarray(np.stack([vecs[i]["l1_vec"] for i in idxs])),
                jnp.asarray(np.stack([vecs[i]["theta0"] for i in idxs])),
                binomial=prep["binomial"],
                fit_intercept=fit_intercept,
                k=prep["k"],
                max_iter=max_iter,
                tol=tol,
                use_l1=flag,
            )
            xs_h = np.asarray(res.x)
            iters_h = np.asarray(res.n_iters)
            hist_h = np.asarray(res.history)
            for lane, i in enumerate(idxs):
                models[i] = ests[i]._theta_to_model(
                    xs_h[lane], prep, iters_h[lane], hist_h[lane]
                )
        return models

    def _fit(self, frame: Frame) -> "LogisticRegressionModel":
        mesh = self._mesh or get_default_mesh()
        prep = self._prep_data(frame, mesh)
        xs, ys, ws = prep["xs"], prep["ys"], prep["ws"]
        n, d, k = prep["n"], prep["d"], prep["k"]
        binomial = prep["binomial"]
        std, inv_std = prep["std"], prep["inv_std"]

        reg = self.getRegParam()
        alpha = self.getElasticNetParam()
        fit_intercept = self.getFitIntercept()
        standardize = self.getStandardization()

        # penalty weights / init via the shared grid-vector builder
        # (standardization=True penalizes scaled coefs directly; False
        # matches original-space penalties; intercepts init to prior log
        # odds — Spark parity)
        vec = self._grid_vectors(prep)
        l2, pen_l2 = vec["l2"], vec["pen_l2"]
        l1_vec, theta0 = vec["l1_vec"], vec["theta0"]
        use_l1 = vec["use_l1"]
        n_coef, n_int = vec["n_coef"], vec["n_int"]

        # ---- bound constraints (Spark's bound-constrained variant) ----
        lb_t, ub_t, use_bounds = self._build_bounds(
            d, k, binomial, n_coef, n_int, std
        )
        if use_bounds and use_l1:
            raise ValueError(
                "bound-constrained optimization only supports none/L2 "
                "regularization (Spark parity): set elasticNetParam=0"
            )
        if use_bounds and fit_intercept:
            # the prior-log-odds init must start inside the box
            theta0[n_coef:] = np.clip(
                theta0[n_coef:], lb_t[n_coef:], ub_t[n_coef:]
            )

        def opt_call(init_state, resume, iter_limit):
            init_dev = (
                None
                if init_state is None
                else jax.tree.map(jnp.asarray, init_state)
            )
            return _lr_optimize(
                xs, ys, ws,
                jnp.asarray(inv_std, jnp.float32),
                jnp.asarray(l2, jnp.float32),
                jnp.asarray(pen_l2),
                jnp.asarray(l1_vec),
                jnp.asarray(theta0),
                init_dev,
                jnp.asarray(iter_limit, jnp.int32),
                jnp.asarray(lb_t, jnp.float32),
                jnp.asarray(ub_t, jnp.float32),
                binomial=binomial,
                fit_intercept=fit_intercept,
                k=k,
                max_iter=self.getMaxIter(),
                tol=self.getTol(),
                use_l1=use_l1,
                resume=resume,
                use_bounds=use_bounds,
            )

        fingerprint = {
            "algo": "logistic_regression",
            "n_coef": n_coef, "n_int": n_int, "num_classes": k,
            "binomial": binomial, "regParam": reg, "elasticNetParam": alpha,
            "maxIter": self.getMaxIter(), "tol": self.getTol(),
            "standardization": standardize, "n_rows": n,
            "bounds": (
                _bounds_digest(lb_t, ub_t) if use_bounds else None
            ),
        }
        res = run_segmented(
            opt_call,
            self.getMaxIter(),
            self.getCheckpointInterval(),
            self.getCheckpointDir(),
            fingerprint,
        )

        return self._theta_to_model(
            res.x, prep, res.n_iters, res.history, use_bounds=use_bounds
        )

    def partial_fit(self, frame: Frame, state=None, decay: float = 1.0,
                    n_classes: int = None):
        """One incremental update (the MLlib streaming-linear-model
        recipe): fold this mini-batch's summarizer moments into
        ``state`` and advance the solution with a warm-started run of
        the SAME jitted LBFGS program the batch fit uses; returns
        ``(model, state)``.

        The standardization moments and class counts are additive and
        accumulate EXACTLY (``decay`` < 1 down-weights history), so
        every call standardizes against all data seen — matching the
        batch fit's preprocessing on the concatenation.  The logistic
        loss has no finite sufficient statistic, so the optimization
        itself is approximate: each call minimizes the CURRENT shard's
        objective from the previous solution (the decayed-state
        gradient-step family).  The equivalence contract is therefore
        behavioral — held-out predictions agree with the batch fit on
        concatenated iid shards within the documented tolerance
        (docs/RESILIENCE.md "Model lifecycle";
        tests/test_lifecycle.py pins it).  The family/class count is
        fixed by the first call — pass ``n_classes`` there when the
        label universe is known, since a mini-batch rarely carries
        every class; bound constraints and mid-fit checkpointing are
        unsupported here."""
        from sntc_tpu.lifecycle.incremental import LRPartialFitState

        if any(
            self.paramValues().get(p) is not None for p in _BOUND_PARAMS
        ):
            raise ValueError(
                "partial_fit does not support bound constraints"
            )
        if self._would_checkpoint():
            raise ValueError(
                "partial_fit does not support mid-fit checkpointing"
            )
        mesh = self._mesh or get_default_mesh()
        X, y, w = self._extract(frame)
        n, d = X.shape
        if state is None:
            binomial, k = self._resolve_family(y, n)
            if n_classes is not None:
                if k > int(n_classes):
                    raise ValueError(
                        f"label {int(y.max())} outside the declared "
                        f"n_classes={int(n_classes)}"
                    )
                k = max(int(n_classes), 2)
                family = self.getFamily()
                binomial = k == 2 and family != "multinomial"
                if family == "binomial" and k > 2:
                    raise ValueError(
                        f"binomial family with {k} classes; use "
                        "multinomial"
                    )
            state = LRPartialFitState(d=d, k=k, binomial=binomial)
        else:
            if d != state.d:
                raise ValueError(
                    f"partial_fit feature width {d} != state's {state.d}"
                )
            if n and int(y.max()) >= state.k:
                raise ValueError(
                    f"label {int(y.max())} outside the class set fixed "
                    f"at the first partial_fit call ({state.k} classes)"
                )
        xs, ys, _ = shard_batch(mesh, X, y.astype(np.int32))
        ws = shard_weights(mesh, w, xs.shape[0])
        s1, s2, cnt, cc = _lr_summarize(xs, ys, ws, state.k)
        state.update(
            np.asarray(s1, np.float64), np.asarray(s2, np.float64),
            float(cnt), np.asarray(cc, np.float64), n_rows=n,
            decay=decay,
        )
        std, inv_std, class_counts = self._moments_to_stats(
            state.s1, state.s2, state.cnt, state.class_counts
        )
        prep = {
            "xs": xs, "ys": ys, "ws": ws, "n": n, "d": d, "k": state.k,
            "binomial": state.binomial, "std": std, "inv_std": inv_std,
            "class_counts": class_counts, "frame": None,
        }
        vec = self._grid_vectors(prep)
        n_coef, n_int = vec["n_coef"], vec["n_int"]
        theta0 = vec["theta0"]
        if state.coef_orig is not None:
            # warm start: the previous ORIGINAL-space solution rescaled
            # into THIS call's standardization space (std moves as the
            # moments accumulate; original space is the invariant)
            theta0 = theta0.copy()
            theta0[:n_coef] = (
                state.coef_orig * std[:, None]
            ).reshape(-1).astype(np.float32)
            if n_int:
                theta0[n_coef:] = state.intercepts
        z = np.zeros(n_coef + n_int, np.float32)
        res, _opt_state = _lr_optimize(
            xs, ys, ws,
            jnp.asarray(inv_std, jnp.float32),
            jnp.asarray(vec["l2"], jnp.float32),
            jnp.asarray(vec["pen_l2"]),
            jnp.asarray(vec["l1_vec"]),
            jnp.asarray(theta0, jnp.float32),
            None,
            jnp.asarray(self.getMaxIter(), jnp.int32),
            jnp.asarray(z), jnp.asarray(z),
            binomial=state.binomial,
            fit_intercept=self.getFitIntercept(),
            k=state.k,
            max_iter=self.getMaxIter(),
            tol=self.getTol(),
            use_l1=bool(vec["use_l1"]),
        )
        theta = np.asarray(res.x, np.float64)
        state.coef_orig = (
            theta[:n_coef].reshape(d, state.rows) * inv_std[:, None]
        )
        state.intercepts = (
            theta[n_coef:].astype(np.float32)
            if n_int
            else np.zeros(state.rows, np.float32)
        )
        model = self._theta_to_model(
            theta, prep, res.n_iters, res.history
        )
        return model, state


@jax.jit
def _margins(X, coefT, intercepts):
    return X @ coefT + intercepts[None, :]


@partial(jax.jit, static_argnames=("binomial",))
def _predict_fused(X, coefT, intercepts, *, binomial):
    """raw margins + probabilities in ONE program (one dispatch per
    serving micro-batch [B:11]).

    Probability is softmax of the ORIGINAL margins: for binomial models
    column 0 of the coefficient matrix is identically zero, so
    softmax([0, m]) == [1-σ(m), σ(m)] — Spark's sigmoid(margin), NOT the
    sigmoid(2m) that softmax of the symmetrized rawPrediction [-m, +m]
    would give."""
    margins = X @ coefT + intercepts[None, :]
    prob = jax.nn.softmax(margins, axis=1)
    if binomial:
        m = margins[:, 1] - margins[:, 0]
        raw = jnp.stack([-m, m], axis=1)
    else:
        raw = margins
    return raw, prob


@partial(jax.jit, static_argnames=("binomial", "mode"))
def _lr_serve(X, coefT, intercepts, thr, *, binomial, mode):
    """raw + probability + prediction in ONE device program, PACKED into a
    single ``[N, 2K+1]`` output — one dispatch and one device→host
    transfer per serving micro-batch ([B:11]; device→host transfers cost a
    full network round trip each on a tunneled TPU and do not overlap)."""
    from sntc_tpu.models.base import pack_serve_outputs

    raw, prob = _predict_fused(X, coefT, intercepts, binomial=binomial)
    return pack_serve_outputs(raw, prob, thr, mode)


class LogisticRegressionModel(_LrParams, ClassificationModel):
    def __init__(
        self,
        coefficient_matrix: np.ndarray,  # [K, D] original space
        intercepts: np.ndarray,  # [K]
        is_binomial: bool,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.coefficientMatrix = np.array(coefficient_matrix, np.float32)
        self.interceptVector = np.array(intercepts, np.float32)
        # read-only (own copy): predict caches device copies, so silent
        # in-place mutation would serve stale weights — make it raise instead
        self.coefficientMatrix.flags.writeable = False
        self.interceptVector.flags.writeable = False
        self.is_binomial = bool(is_binomial)
        self.summary: Optional[LogisticRegressionSummary] = None
        self._dev_params = None  # lazy device-resident (coefT, intercepts)

    def _device_params(self):
        params = self._dev_params
        if params is None:
            params = (
                jnp.asarray(self.coefficientMatrix.T),
                jnp.asarray(self.interceptVector),
            )
            # never cache values created under an active trace: the
            # fusion planner jits THROUGH transform, so inside its
            # tracing these constants are tracers — caching one would
            # poison every later trace with UnexpectedTracerError
            # (bites exactly when two engines share one predictor)
            if not isinstance(params[0], jax.core.Tracer):
                self._dev_params = params
        return params

    def evaluate(self, frame: Frame):
        """Metrics summary on ``frame`` (Spark ``model.evaluate(dataset)``)
        — the training summary's surface minus objectiveHistory, lazy."""
        cls = (
            BinaryClassificationSummary
            if self.is_binomial
            else ClassificationSummary
        )
        return cls(self, frame, labelCol=self.getLabelCol())

    def _save_extra(self):
        return (
            {"is_binomial": self.is_binomial},
            {
                "coefficientMatrix": self.coefficientMatrix,
                "interceptVector": self.interceptVector,
            },
        )

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(
            coefficient_matrix=arrays["coefficientMatrix"],
            intercepts=arrays["interceptVector"],
            is_binomial=extra["is_binomial"],
        )
        m.setParams(**params)
        return m

    # Spark binary-model accessors
    @property
    def coefficients(self) -> np.ndarray:
        if not self.is_binomial:
            raise AttributeError("use coefficientMatrix for multinomial models")
        return self.coefficientMatrix[1]

    @property
    def intercept(self) -> float:
        if not self.is_binomial:
            raise AttributeError("use interceptVector for multinomial models")
        return float(self.interceptVector[1])

    @property
    def num_classes(self) -> int:
        return self.coefficientMatrix.shape[0]

    def _raw_predict(self, X: np.ndarray) -> np.ndarray:
        coefT, b = self._device_params()
        raw = np.asarray(_margins(jnp.asarray(X), coefT, b))
        if self.is_binomial:
            # Spark binary rawPrediction is [-margin, +margin]
            m = raw[:, 1] - raw[:, 0]
            raw = np.stack([-m, m], axis=1)
        return raw

    def _predict_raw_prob(self, X: np.ndarray):
        coefT, b = self._device_params()
        raw, prob = _predict_fused(
            jnp.asarray(X), coefT, b, binomial=self.is_binomial
        )
        return np.asarray(raw), np.asarray(prob)

    def _predict_all_dev(self, X: np.ndarray):
        coefT, b = self._device_params()
        mode, thr = self._threshold_mode()
        return _lr_serve(
            jnp.asarray(X), coefT, b, jnp.asarray(thr),
            binomial=self.is_binomial, mode=mode,
        )

    def _predict_raw_prob_host(self, X: np.ndarray):
        """numpy predict for micro-batches below the host-serve crossover
        (a [N,78]×[78,K] matmul — the device round trip costs more)."""
        margins = X @ self.coefficientMatrix.T + self.interceptVector[None, :]
        if self.is_binomial:
            m = margins[:, 1] - margins[:, 0]
            raw = np.stack([-m, m], axis=1)
        else:
            raw = margins
        return raw, self._raw_to_probability(raw)

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        if self.is_binomial:
            # raw = [-m, +m]; Spark probability is sigmoid(m) — numerically
            # stable form, no exp overflow on extreme margins
            m = raw[:, 1]
            e = np.exp(-np.abs(m))
            p1 = np.where(m >= 0, 1.0, e) / (1.0 + e)
            return np.stack([1.0 - p1, p1], axis=1)
        z = raw - raw.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)
