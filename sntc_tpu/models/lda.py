"""LDA — latent Dirichlet allocation via online variational Bayes.

Behavioral spec: upstream ``ml/clustering/LDA.scala`` →
``mllib/clustering/OnlineLDAOptimizer.scala`` [U] (Hoffman, Blei & Bach
2010, the algorithm Spark's default-recommended online optimizer runs):
``k``, ``maxIter`` (each iteration processes one minibatch),
``docConcentration`` α (auto → 1/k), ``topicConcentration`` η (auto →
1/k), ``learningOffset`` τ₀ (1024), ``learningDecay`` κ (0.51),
``subsamplingRate`` (0.05), ``seed``; model surface: ``topicsMatrix``
(V×k expected word-topic distribution), ``describeTopics``,
``transform`` → ``topicDistribution``, ``logLikelihood`` /
``logPerplexity`` (the variational ELBO bound, token-normalized for
perplexity).  Spark's legacy "em" optimizer is not built — online is
the recommended path and the only one whose statistics are minibatch
matmuls (documented delta).

TPU design: one E-step is a jitted ``lax.while_loop`` over the WHOLE
minibatch at once — ``γ [B,k]``/``φ`` updates are two dense
``[B,V]×[V,k]`` contractions per inner iteration (MXU work; Spark loops
documents on the driver-side executor in Breeze), converging on mean
``γ`` change < 1e-3 like mllib.  The M-step blends sufficient
statistics into λ with the ``(τ₀ + t)^−κ`` schedule on host (a [k,V]
update — tiny next to the E-step).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from scipy.special import gammaln, psi

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators

_MEAN_CHANGE_TOL = 1e-3
_MAX_E_ITERS = 100


@jax.jit
def _dirichlet_expectation(x):
    """E[log θ] under Dirichlet(x), rowwise."""
    return jax.scipy.special.digamma(x) - jax.scipy.special.digamma(
        x.sum(axis=-1, keepdims=True)
    )


@partial(jax.jit, static_argnames=("max_iters",))
def _e_step(counts, exp_elog_beta, alpha, key, *, max_iters):
    """Minibatch E-step: returns ``gamma [B,k]`` and the sufficient
    statistic ``stat [k,V]`` (to be scaled by the corpus factor)."""
    b, v = counts.shape
    k = exp_elog_beta.shape[0]
    gamma0 = jax.random.gamma(key, 100.0, (b, k)) / 100.0

    def body(state):
        gamma, _, it = state
        exp_elog_theta = jnp.exp(_dirichlet_expectation(gamma))
        # phinorm[d, w] = Σ_k expElogθ[d,k] expElogβ[k,w]
        phinorm = exp_elog_theta @ exp_elog_beta + 1e-100
        new_gamma = alpha + exp_elog_theta * (
            (counts / phinorm) @ exp_elog_beta.T
        )
        change = jnp.abs(new_gamma - gamma).mean()
        return new_gamma, change, it + 1

    def cond(state):
        _, change, it = state
        return jnp.logical_and(it < max_iters, change > _MEAN_CHANGE_TOL)

    gamma, _, _ = jax.lax.while_loop(
        cond, body, (gamma0, jnp.asarray(jnp.inf, jnp.float32),
                     jnp.asarray(0, jnp.int32))
    )
    exp_elog_theta = jnp.exp(_dirichlet_expectation(gamma))
    phinorm = exp_elog_theta @ exp_elog_beta + 1e-100
    stat = exp_elog_theta.T @ (counts / phinorm)  # [k, V]
    return gamma, stat * exp_elog_beta


class _LdaParams:
    featuresCol = Param("count-vector column", default="features")
    topicDistributionCol = Param(
        "output topic-mixture column", default="topicDistribution"
    )
    k = Param("number of topics", default=10, validator=validators.gt(1))
    maxIter = Param("minibatch iterations", default=20,
                    validator=validators.gt(0))
    docConcentration = Param(
        "α (None = auto 1/k)", default=None,
        validator=lambda v: v is None or v > 0,
    )
    topicConcentration = Param(
        "η (None = auto 1/k)", default=None,
        validator=lambda v: v is None or v > 0,
    )
    learningOffset = Param("τ₀ downweights early iterations", default=1024.0,
                           validator=validators.gt(0))
    learningDecay = Param("κ ∈ (0.5, 1]", default=0.51,
                          validator=validators.gt(0.5))
    subsamplingRate = Param(
        "minibatch fraction per iteration, in (0, 1]", default=0.05,
        validator=lambda v: 0.0 < v <= 1.0,
    )
    seed = Param("random seed", default=0)


class LDA(_LdaParams, Estimator):
    def _fit(self, frame: Frame) -> "LDAModel":
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                "featuresCol must be a count-vector column "
                "(CountVectorizer output)"
            )
        X = np.asarray(X, np.float32)
        if np.any(X < 0):
            raise ValueError("LDA requires non-negative counts")
        n_docs, v = X.shape
        k = int(self.getK())
        dc = self.getDocConcentration()
        tc = self.getTopicConcentration()
        alpha = float(dc) if dc is not None else 1.0 / k
        eta = float(tc) if tc is not None else 1.0 / k
        tau0 = float(self.getLearningOffset())
        kappa = float(self.getLearningDecay())
        frac = float(self.getSubsamplingRate())
        batch = max(1, int(round(frac * n_docs)))
        rng = np.random.default_rng(self.getSeed())
        key = jax.random.PRNGKey(int(self.getSeed()))

        lam = rng.gamma(100.0, 1.0 / 100.0, size=(k, v)).astype(np.float64)
        for t in range(int(self.getMaxIter())):
            idx = rng.choice(n_docs, size=batch, replace=False)
            elog_beta = psi(lam) - psi(lam.sum(axis=1, keepdims=True))
            key, sub = jax.random.split(key)
            _, stat = _e_step(
                jnp.asarray(X[idx]),
                jnp.asarray(np.exp(elog_beta), jnp.float32),
                jnp.float32(alpha), sub, max_iters=_MAX_E_ITERS,
            )
            rho = (tau0 + t) ** (-kappa)
            lam_hat = eta + (n_docs / batch) * np.asarray(stat, np.float64)
            lam = (1.0 - rho) * lam + rho * lam_hat

        model = LDAModel(lam=lam, alpha=alpha, eta=eta, numDocs=n_docs)
        model.setParams(**self.paramValues())
        return model


class LDAModel(_LdaParams, Model):
    def __init__(self, lam, alpha: float, eta: float, numDocs: int = 0,
                 **kwargs):
        super().__init__(**kwargs)
        self.lam = np.asarray(lam, np.float64)  # [k, V] variational λ
        self.alpha = float(alpha)
        self.eta = float(eta)
        self.numDocs = int(numDocs)

    @property
    def vocabSize(self) -> int:
        return self.lam.shape[1]

    def topicsMatrix(self) -> np.ndarray:
        """[V, k] expected word probability per topic (Spark layout)."""
        return (self.lam / self.lam.sum(axis=1, keepdims=True)).T

    def describeTopics(self, maxTermsPerTopic: int = 10) -> Frame:
        probs = self.lam / self.lam.sum(axis=1, keepdims=True)
        order = np.argsort(-probs, axis=1)[:, :maxTermsPerTopic]
        weights = np.take_along_axis(probs, order, axis=1)
        return Frame({
            "topic": np.arange(self.lam.shape[0], dtype=np.int64),
            "termIndices": order.astype(np.int64),
            "termWeights": weights,
        })

    def _infer_gamma(self, X: np.ndarray) -> np.ndarray:
        elog_beta = psi(self.lam) - psi(self.lam.sum(axis=1, keepdims=True))
        gamma, _ = _e_step(
            jnp.asarray(X, jnp.float32),
            jnp.asarray(np.exp(elog_beta), jnp.float32),
            jnp.float32(self.alpha),
            jax.random.PRNGKey(int(self.getSeed())),
            max_iters=_MAX_E_ITERS,
        )
        return np.asarray(gamma, np.float64)

    def transform(self, frame: Frame) -> Frame:
        X = np.asarray(frame[self.getFeaturesCol()], np.float32)
        gamma = self._infer_gamma(X)
        theta = gamma / gamma.sum(axis=1, keepdims=True)
        return frame.with_column(self.getTopicDistributionCol(), theta)

    def _bound(self, X: np.ndarray) -> float:
        """Variational ELBO of ``X`` (Hoffman eq. 3; mllib's
        ``logLikelihoodBound`` [U]) — the quantity behind Spark's
        ``logLikelihood``/``logPerplexity``."""
        gamma = self._infer_gamma(X)
        k, v = self.lam.shape
        elog_theta = psi(gamma) - psi(gamma.sum(axis=1, keepdims=True))
        elog_beta = psi(self.lam) - psi(self.lam.sum(axis=1, keepdims=True))
        # E[log p(docs | theta, beta)]: token-level softmax bound
        score = 0.0
        norm = np.log(
            np.exp(elog_theta) @ np.exp(elog_beta) + 1e-100
        )
        score += float((X * norm).sum())
        # E[log p(theta | alpha) - log q(theta | gamma)]
        score += float(
            ((self.alpha - gamma) * elog_theta).sum()
            + (gammaln(gamma) - gammaln(self.alpha)).sum()
            + (gammaln(self.alpha * k) - gammaln(gamma.sum(axis=1))).sum()
        )
        # E[log p(beta | eta) - log q(beta | lambda)]
        score += float(
            ((self.eta - self.lam) * elog_beta).sum()
            + (gammaln(self.lam) - gammaln(self.eta)).sum()
            + (gammaln(self.eta * v) - gammaln(self.lam.sum(axis=1))).sum()
        )
        return score

    def logLikelihood(self, frame: Frame) -> float:
        return self._bound(
            np.asarray(frame[self.getFeaturesCol()], np.float32)
        )

    def logPerplexity(self, frame: Frame) -> float:
        X = np.asarray(frame[self.getFeaturesCol()], np.float32)
        tokens = float(X.sum())
        return -self._bound(X) / max(tokens, 1.0)

    def _save_extra(self):
        return (
            {"alpha": self.alpha, "eta": self.eta, "numDocs": self.numDocs},
            {"lam": self.lam},
        )

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(
            lam=arrays["lam"], alpha=float(extra["alpha"]),
            eta=float(extra["eta"]), numDocs=int(extra["numDocs"]),
        )
        m.setParams(**params)
        return m
