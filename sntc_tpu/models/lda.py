"""LDA — latent Dirichlet allocation via online variational Bayes.

Behavioral spec: upstream ``ml/clustering/LDA.scala`` →
``mllib/clustering/OnlineLDAOptimizer.scala`` [U] (Hoffman, Blei & Bach
2010, the algorithm Spark's default-recommended online optimizer runs):
``k``, ``maxIter`` (each iteration processes one minibatch),
``docConcentration`` α (auto → 1/k), ``topicConcentration`` η (auto →
1/k), ``learningOffset`` τ₀ (1024), ``learningDecay`` κ (0.51),
``subsamplingRate`` (0.05), ``seed``; model surface: ``topicsMatrix``
(V×k expected word-topic distribution), ``describeTopics``,
``transform`` → ``topicDistribution``, ``logLikelihood`` /
``logPerplexity`` (the variational ELBO bound, token-normalized for
perplexity).  ``optimizer`` selects "online" (default, as ml.LDA) or
"em": full-corpus batch variational EM with Spark's EM hyperparameter
defaults (docConcentration auto → (50/k)+1, topicConcentration auto →
1.1 [U: ``EMLDAOptimizer``]) — every iteration E-steps ALL documents
and sets λ = η + stat directly (no decay schedule).  Documented delta:
Spark's EM is the GraphX collapsed-count implementation returning a
``DistributedLDAModel``; ours is batch VB-EM over the same parameter
surface returning the same ``LDAModel`` (deterministic, minibatch-free
— the fixed point of the same variational objective).

TPU design: one E-step is a jitted ``lax.while_loop`` over the WHOLE
minibatch at once, MESH-SHARDED over documents — ``γ [b,k]``/``φ``
updates are two dense shard-local ``[b,V]×[V,k]`` contractions per
inner iteration (MXU work; Spark loops documents on executors in
Breeze), with the global mean-``γ``-change convergence test and the
``[k,V]`` sufficient statistic as ``psum``s.  The M-step blends
statistics into λ with the ``(τ₀ + t)^−κ`` schedule on host (a [k,V]
update — tiny next to the E-step).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from scipy.special import gammaln, psi

from sntc_tpu.parallel.mesh import map_at, payload_nbytes, record_collective
from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators

_MEAN_CHANGE_TOL = 1e-3
_MAX_E_ITERS = 100


@jax.jit
def _dirichlet_expectation(x):
    """E[log θ] under Dirichlet(x), rowwise."""
    return jax.scipy.special.digamma(x) - jax.scipy.special.digamma(
        x.sum(axis=-1, keepdims=True)
    )


@lru_cache(maxsize=None)
def _e_step_sharded(mesh, max_iters):
    """Minibatch E-step over MESH-SHARDED documents: γ updates are
    shard-local `[b,V]×[V,k]` contractions; the convergence test (mean
    |Δγ| over ALL real docs) and the `[k,V]` sufficient statistic are
    ``psum``s — Spark's per-iteration executor loop + driver reduce as
    one XLA program.  ``wm`` masks padding docs out of the statistic and
    the convergence mean; γ inits are keyed by GLOBAL doc index, so the
    same seed reproduces the same draws at any device count."""
    axis = mesh.axis_names[0]

    def local(counts, wm, exp_elog_beta, alpha, key):
        counts = counts * wm[:, None]  # padding docs contribute nothing
        b, v = counts.shape
        k = exp_elog_beta.shape[0]
        # γ init keyed by GLOBAL document index, not shard index: the
        # same seed draws the same init at ANY device count, so
        # inference is deterministic across environments
        offset = jax.lax.axis_index(axis) * b
        keys = jax.vmap(
            lambda i: jax.random.fold_in(key, offset + i)
        )(jnp.arange(b))
        gamma0 = jax.vmap(
            lambda kk: jax.random.gamma(kk, 100.0, (k,))
        )(keys) / 100.0
        n_docs = jax.lax.psum(wm.sum(), axis)

        def body(state):
            gamma, _, it = state
            exp_elog_theta = jnp.exp(_dirichlet_expectation(gamma))
            # phinorm[d, w] = Σ_k expElogθ[d,k] expElogβ[k,w]
            phinorm = exp_elog_theta @ exp_elog_beta + 1e-100
            new_gamma = alpha + exp_elog_theta * (
                (counts / phinorm) @ exp_elog_beta.T
            )
            change = jax.lax.psum(
                (jnp.abs(new_gamma - gamma).mean(axis=1) * wm).sum(), axis
            ) / jnp.maximum(n_docs, 1.0)
            return new_gamma, change, it + 1

        def cond(state):
            _, change, it = state
            return jnp.logical_and(
                it < max_iters, change > _MEAN_CHANGE_TOL
            )

        gamma, _, _ = jax.lax.while_loop(
            cond, body, (gamma0, jnp.asarray(jnp.inf, jnp.float32),
                         jnp.asarray(0, jnp.int32))
        )
        exp_elog_theta = jnp.exp(_dirichlet_expectation(gamma))
        phinorm = exp_elog_theta @ exp_elog_beta + 1e-100
        stat = jax.lax.psum(
            exp_elog_theta.T @ (counts / phinorm), axis
        )  # [k, V]
        return gamma, stat * exp_elog_beta

    return map_at(
        mesh, local,
        in_specs=(P(axis), P(axis), P(), P(), P()),
        out_specs=(P(axis), P()),
    )


def _run_e_step(mesh, counts_np, exp_elog_beta, alpha, key, max_iters):
    """Shard a doc batch, run the SPMD E-step, return host (γ, stat)
    with the padding rows stripped."""
    from sntc_tpu.parallel.collectives import shard_batch

    n = counts_np.shape[0]
    xs, wm = shard_batch(mesh, counts_np)
    gamma, stat = _e_step_sharded(mesh, max_iters)(
        xs, wm, jnp.asarray(exp_elog_beta, jnp.float32),
        jnp.float32(alpha), key,
    )
    axis = mesh.axis_names[0]
    # γ stays row-sharded (never crosses the mesh); the [k, V] stat is
    # the psum'd payload
    record_collective(
        "lda.e_step", axis, mesh.shape[axis], payload_nbytes(stat)
    )
    return np.asarray(gamma)[:n], stat


class _LdaParams:
    featuresCol = Param("count-vector column", default="features")
    topicDistributionCol = Param(
        "output topic-mixture column", default="topicDistribution"
    )
    k = Param("number of topics", default=10, validator=validators.gt(1))
    maxIter = Param(
        "iterations (online: one minibatch each; em: one full-corpus "
        "E+M step each)", default=20, validator=validators.gt(0),
    )
    docConcentration = Param(
        "α (None = auto: 1/k online, (50/k)+1 em — Spark per-optimizer "
        "defaults)", default=None,
        validator=lambda v: v is None or v > 0,
    )
    topicConcentration = Param(
        "η (None = auto: 1/k online, 1.1 em — Spark per-optimizer "
        "defaults)", default=None,
        validator=lambda v: v is None or v > 0,
    )
    learningOffset = Param("τ₀ downweights early iterations", default=1024.0,
                           validator=validators.gt(0))
    learningDecay = Param("κ ∈ (0.5, 1]", default=0.51,
                          validator=validators.gt(0.5))
    subsamplingRate = Param(
        "minibatch fraction per iteration, in (0, 1]", default=0.05,
        validator=lambda v: 0.0 < v <= 1.0,
    )
    optimizer = Param(
        "online (minibatch VB) | em (full-corpus batch VB-EM)",
        default="online", validator=validators.one_of("online", "em"),
    )
    seed = Param("random seed", default=0)


class LDA(_LdaParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "LDAModel":
        from sntc_tpu.parallel.context import get_default_mesh

        mesh = self._mesh or get_default_mesh()
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                "featuresCol must be a count-vector column "
                "(CountVectorizer output)"
            )
        X = np.asarray(X, np.float32)
        if np.any(X < 0):
            raise ValueError("LDA requires non-negative counts")
        n_docs, v = X.shape
        k = int(self.getK())
        em = self.getOptimizer() == "em"
        dc = self.getDocConcentration()
        tc = self.getTopicConcentration()
        # Spark's per-optimizer auto defaults [U: LDAOptimizer.initialize]
        alpha = float(dc) if dc is not None else (
            (50.0 / k) + 1.0 if em else 1.0 / k
        )
        eta = float(tc) if tc is not None else (1.1 if em else 1.0 / k)
        tau0 = float(self.getLearningOffset())
        kappa = float(self.getLearningDecay())
        frac = float(self.getSubsamplingRate())
        batch = max(1, int(round(frac * n_docs)))
        rng = np.random.default_rng(self.getSeed())
        key = jax.random.PRNGKey(int(self.getSeed()))

        lam = rng.gamma(100.0, 1.0 / 100.0, size=(k, v)).astype(np.float64)
        for t in range(int(self.getMaxIter())):
            elog_beta = psi(lam) - psi(lam.sum(axis=1, keepdims=True))
            key, sub = jax.random.split(key)
            if em:
                # batch VB-EM: E-step the WHOLE corpus, set λ at the
                # M-step fixed point — no minibatch scaling, no decay
                _, stat = _run_e_step(
                    mesh, X, np.exp(elog_beta), alpha, sub, _MAX_E_ITERS
                )
                lam = eta + np.asarray(stat, np.float64)
            else:
                idx = rng.choice(n_docs, size=batch, replace=False)
                _, stat = _run_e_step(
                    mesh, X[idx], np.exp(elog_beta), alpha, sub,
                    _MAX_E_ITERS,
                )
                rho = (tau0 + t) ** (-kappa)
                lam_hat = (
                    eta + (n_docs / batch) * np.asarray(stat, np.float64)
                )
                lam = (1.0 - rho) * lam + rho * lam_hat

        model = LDAModel(lam=lam, alpha=alpha, eta=eta, numDocs=n_docs)
        model.setParams(**self.paramValues())
        return model


class LDAModel(_LdaParams, Model):
    def __init__(self, lam, alpha: float, eta: float, numDocs: int = 0,
                 **kwargs):
        super().__init__(**kwargs)
        self.lam = np.asarray(lam, np.float64)  # [k, V] variational λ
        self.alpha = float(alpha)
        self.eta = float(eta)
        self.numDocs = int(numDocs)

    @property
    def vocabSize(self) -> int:
        return self.lam.shape[1]

    def topicsMatrix(self) -> np.ndarray:
        """[V, k] expected word probability per topic (Spark layout)."""
        return (self.lam / self.lam.sum(axis=1, keepdims=True)).T

    def describeTopics(self, maxTermsPerTopic: int = 10) -> Frame:
        probs = self.lam / self.lam.sum(axis=1, keepdims=True)
        order = np.argsort(-probs, axis=1)[:, :maxTermsPerTopic]
        weights = np.take_along_axis(probs, order, axis=1)
        return Frame({
            "topic": np.arange(self.lam.shape[0], dtype=np.int64),
            "termIndices": order.astype(np.int64),
            "termWeights": weights,
        })

    def _infer_gamma(self, X: np.ndarray) -> np.ndarray:
        from sntc_tpu.parallel.context import get_default_mesh

        elog_beta = psi(self.lam) - psi(self.lam.sum(axis=1, keepdims=True))
        gamma, _ = _run_e_step(
            get_default_mesh(), X.astype(np.float32), np.exp(elog_beta),
            self.alpha, jax.random.PRNGKey(int(self.getSeed())),
            _MAX_E_ITERS,
        )
        return np.asarray(gamma, np.float64)

    def transform(self, frame: Frame) -> Frame:
        X = np.asarray(frame[self.getFeaturesCol()], np.float32)
        gamma = self._infer_gamma(X)
        theta = gamma / gamma.sum(axis=1, keepdims=True)
        return frame.with_column(self.getTopicDistributionCol(), theta)

    def _bound(self, X: np.ndarray) -> float:
        """Variational ELBO of ``X`` (Hoffman eq. 3; mllib's
        ``logLikelihoodBound`` [U]) — the quantity behind Spark's
        ``logLikelihood``/``logPerplexity``."""
        gamma = self._infer_gamma(X)
        k, v = self.lam.shape
        elog_theta = psi(gamma) - psi(gamma.sum(axis=1, keepdims=True))
        elog_beta = psi(self.lam) - psi(self.lam.sum(axis=1, keepdims=True))
        # E[log p(docs | theta, beta)]: token-level softmax bound
        score = 0.0
        norm = np.log(
            np.exp(elog_theta) @ np.exp(elog_beta) + 1e-100
        )
        score += float((X * norm).sum())
        # E[log p(theta | alpha) - log q(theta | gamma)]
        score += float(
            ((self.alpha - gamma) * elog_theta).sum()
            + (gammaln(gamma) - gammaln(self.alpha)).sum()
            + (gammaln(self.alpha * k) - gammaln(gamma.sum(axis=1))).sum()
        )
        # E[log p(beta | eta) - log q(beta | lambda)]
        score += float(
            ((self.eta - self.lam) * elog_beta).sum()
            + (gammaln(self.lam) - gammaln(self.eta)).sum()
            + (gammaln(self.eta * v) - gammaln(self.lam.sum(axis=1))).sum()
        )
        return score

    def logLikelihood(self, frame: Frame) -> float:
        return self._bound(
            np.asarray(frame[self.getFeaturesCol()], np.float32)
        )

    def logPerplexity(self, frame: Frame) -> float:
        X = np.asarray(frame[self.getFeaturesCol()], np.float32)
        tokens = float(X.sum())
        return -self._bound(X) / max(tokens, 1.0)

    def _save_extra(self):
        return (
            {"alpha": self.alpha, "eta": self.eta, "numDocs": self.numDocs},
            {"lam": self.lam},
        )

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(
            lam=arrays["lam"], alpha=float(extra["alpha"]),
            eta=float(extra["eta"]), numDocs=int(extra["numDocs"]),
        )
        m.setParams(**params)
        return m
