"""KMeans — Lloyd's iterations on the MXU, k-means|| init.

Behavioral spec: upstream ``ml/clustering/KMeans.scala`` →
``mllib/clustering/KMeans.scala`` [U]: ``k``, ``maxIter`` (default 20),
``tol`` (1e-4, on center movement — squared shift vs tol²), ``initMode`` random |
k-means|| (default, ``initSteps=2``), ``distanceMeasure`` euclidean |
cosine, ``seed``; model exposes ``clusterCenters``, ``predict`` =
nearest center, ``summary.trainingCost`` (inertia / cosine cost).

TPU design: one Lloyd iteration is ONE jitted SPMD step over
mesh-sharded rows — the [N, k] distance matrix is a single MXU matmul
(``‖x‖² − 2x·Cᵀ + ‖c‖²``), assignments an argmin, and the new centers a
one-hot contraction ``psum``-reduced over ICI; the whole maxIter loop
runs as a ``lax.while_loop`` with the tol test on device (zero host
round trips per iteration — Spark's per-iteration driver collect
disappears).  k-means|| init runs on a host subsample (numpy, Spark's
candidate-sampling shape) — it is O(sample·initSteps) and off the hot
path.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.models.summary import TrainingSummary
from sntc_tpu.parallel.collectives import shard_batch
from sntc_tpu.parallel.context import get_default_mesh
from sntc_tpu.parallel.mesh import map_at, payload_nbytes, record_collective


def _normalize_rows(X, eps=1e-12):
    n = np.linalg.norm(X, axis=1, keepdims=True)
    return X / np.maximum(n, eps)


@partial(jax.jit, static_argnames=("k", "max_iter", "cosine", "mesh_axis"))
def _lloyd(xs, ws, centers0, tol, *, k, max_iter, cosine, mesh_axis):
    """The whole Lloyd loop as one XLA program over sharded rows.

    For cosine distance rows/centers arrive L2-normalized; the update
    re-normalizes centers each step (Spark's cosine KMeans)."""

    def distances(centers):
        # ‖x−c‖² = ‖x‖² − 2 x·cᵀ + ‖c‖²; the cross term is the MXU matmul
        cross = xs @ centers.T  # [n, k]
        cn = (centers**2).sum(axis=1)
        if cosine:
            return 1.0 - cross  # normalized rows: cosine distance
        xn = (xs**2).sum(axis=1)
        return xn[:, None] - 2.0 * cross + cn[None, :]

    def step(state):
        centers, _, it = state
        d = distances(centers)
        assign = jnp.argmin(d, axis=1)
        oh = jax.nn.one_hot(assign, k, dtype=jnp.float32) * ws[:, None]
        sums = jax.lax.psum(oh.T @ xs, mesh_axis)  # [k, D]
        counts = jax.lax.psum(oh.sum(axis=0), mesh_axis)  # [k]
        new = sums / jnp.maximum(counts, 1e-12)[:, None]
        # empty clusters keep their previous center (Spark behavior)
        new = jnp.where((counts > 0)[:, None], new, centers)
        if cosine:
            norm = jnp.linalg.norm(new, axis=1, keepdims=True)
            new = new / jnp.maximum(norm, 1e-12)
        shift = ((new - centers) ** 2).sum(axis=1).max()
        return new, shift, it + 1.0

    def cond(state):
        _, shift, it = state
        # Spark isCenterConverged: movement <= tol, i.e. SQUARED <= tol²
        return jnp.logical_and(it < max_iter, shift > tol * tol)

    init = (
        centers0,
        jnp.asarray(jnp.inf, jnp.float32),
        jnp.asarray(0.0, jnp.float32),
    )
    centers, shift, it = jax.lax.while_loop(cond, step, init)
    # cost computed ONCE after convergence (not per step — it would
    # double the per-iteration matmul work)
    cost = jax.lax.psum(
        jnp.sum(ws * jnp.min(distances(centers), axis=1)), mesh_axis
    )
    return centers, shift, it, cost


@lru_cache(maxsize=None)
def _lloyd_sharded(mesh, k, max_iter, cosine):
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]

    def run(xs, ws, centers0, tol):
        return _lloyd(
            xs, ws, centers0, tol,
            k=k, max_iter=max_iter, cosine=cosine, mesh_axis=axis,
        )

    return map_at(
        mesh, run,
        in_specs=(P(axis), P(axis), P(), P()),
        out_specs=(P(), P(), P(), P()),
    )


def _kmeans_parallel_init(X, k, seed, steps, cosine):
    """k-means|| (Bahmani et al.) on the host sample — Spark's init:
    oversample ~2k candidates per step by distance-weighted sampling,
    then cluster-weight the candidates and reduce to k via k-means++."""
    rng = np.random.default_rng(seed)
    n = X.shape[0]
    centers = X[rng.integers(0, n)][None, :]
    for _ in range(steps):
        d = _min_sq_dist(X, centers, cosine)
        total = d.sum()
        if total <= 0:
            break
        p = np.minimum(2.0 * k * d / total, 1.0)
        new = X[rng.random(n) < p]
        if len(new):
            centers = np.concatenate([centers, new], axis=0)
    # weight candidates by how many points they own, then k-means++ down
    d_all = _sq_dists(X, centers, cosine)
    owner = d_all.argmin(axis=1)
    wts = np.bincount(owner, minlength=len(centers)).astype(np.float64)
    return _kmeans_pp(centers, wts, k, rng, cosine)


def _sq_dists(X, C, cosine):
    if cosine:
        return 1.0 - X @ C.T
    return (
        (X**2).sum(axis=1)[:, None]
        - 2.0 * X @ C.T
        + (C**2).sum(axis=1)[None, :]
    )


def _min_sq_dist(X, C, cosine):
    return np.maximum(_sq_dists(X, C, cosine).min(axis=1), 0.0)


def _kmeans_pp(cand, wts, k, rng, cosine):
    """Weighted k-means++ over the (small) candidate set."""
    if len(cand) <= k:
        out = cand
        while len(out) < k:  # degenerate: duplicate to k
            out = np.concatenate([out, cand[: k - len(out)]], axis=0)
        return out
    centers = [cand[rng.choice(len(cand), p=wts / wts.sum())]]
    for _ in range(1, k):
        d = _min_sq_dist(cand, np.stack(centers), cosine) * wts
        total = d.sum()
        if total <= 0:
            idx = rng.integers(0, len(cand))
        else:
            idx = rng.choice(len(cand), p=d / total)
        centers.append(cand[idx])
    return np.stack(centers)


class _KMeansParams:
    featuresCol = Param("feature vector column", default="features")
    predictionCol = Param("output cluster-index column", default="prediction")
    k = Param("number of clusters", default=2, validator=validators.gt(1))
    maxIter = Param("max Lloyd iterations", default=20, validator=validators.gt(0))
    tol = Param(
        "convergence tolerance on center MOVEMENT (Spark compares the "
        "squared shift to tol²)", default=1e-4,
        validator=validators.gteq(0),
    )
    initMode = Param(
        "k-means|| | random", default="k-means||",
        validator=validators.one_of("k-means||", "random"),
    )
    initSteps = Param("k-means|| sampling rounds", default=2,
                      validator=validators.gt(0))
    distanceMeasure = Param(
        "euclidean | cosine", default="euclidean",
        validator=validators.one_of("euclidean", "cosine"),
    )
    seed = Param("init seed", default=0)


class KMeans(_KMeansParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "KMeansModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getFeaturesCol()]
        if X.ndim != 2:
            raise ValueError(
                f"featuresCol {self.getFeaturesCol()!r} must be a vector "
                "column (use VectorAssembler)"
            )
        X = np.asarray(X, np.float32)
        k = self.getK()
        if X.shape[0] < k:
            raise ValueError(f"k={k} exceeds the row count {X.shape[0]}")
        cosine = self.getDistanceMeasure() == "cosine"
        Xw = _normalize_rows(X).astype(np.float32) if cosine else X

        rng = np.random.default_rng(self.getSeed())
        sample = Xw
        if len(sample) > 100_000:
            sample = Xw[rng.choice(len(Xw), 100_000, replace=False)]
        if self.getInitMode() == "random":
            centers0 = sample[rng.choice(len(sample), k, replace=False)]
        else:
            centers0 = _kmeans_parallel_init(
                sample, k, self.getSeed(), int(self.getInitSteps()), cosine
            ).astype(np.float32)

        xs, ws = shard_batch(mesh, Xw)
        centers, shift, iters, cost = _lloyd_sharded(
            mesh, k, int(self.getMaxIter()), cosine
        )(xs, ws, jnp.asarray(centers0), jnp.float32(self.getTol()))
        record_collective(
            "kmeans.lloyd", mesh.axis_names[0], mesh.shape[mesh.axis_names[0]],
            payload_nbytes((centers, shift, iters, cost)),
        )
        model = KMeansModel(clusterCenters=np.asarray(centers, np.float64))
        model.setParams(**self.paramValues())
        model.summary = TrainingSummary([float(cost)], int(iters))
        model.summary.trainingCost = float(cost)
        return model


class KMeansModel(_KMeansParams, Model):
    def __init__(self, clusterCenters: np.ndarray = None, **kwargs):
        super().__init__(**kwargs)
        self.clusterCenters = np.asarray(clusterCenters, np.float64)
        self.summary = None

    def _save_extra(self):
        return {}, {"clusterCenters": self.clusterCenters}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(clusterCenters=arrays["clusterCenters"])
        m.setParams(**params)
        return m

    def predict(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, np.float64)
        cosine = self.getDistanceMeasure() == "cosine"
        if cosine:
            X = _normalize_rows(X)
        return _sq_dists(X, self.clusterCenters, cosine).argmin(axis=1).astype(
            np.float64
        )

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()]
        return frame.with_column(
            self.getPredictionCol(), self.predict(np.asarray(X))
        )
