"""ALS — collaborative filtering by alternating least squares.

Behavioral spec: upstream ``ml/recommendation/ALS.scala`` [U]:
``userCol``/``itemCol``/``ratingCol``, ``rank`` (10), ``maxIter`` (10),
``regParam`` (0.1) scaled per least-squares problem by that row's rating
count (ALS-WR, the documented Spark behavior), ``implicitPrefs`` with
``alpha`` confidence (Hu-Koren: c = 1 + α·r, preferences p = 1 at
observed cells), ``coldStartStrategy`` nan | drop, ``seed``; model
surface: ``userFactors``/``itemFactors`` frames, ``transform`` over
(user, item) pairs, ``recommendForAllUsers`` / ``recommendForAllItems``.
``nonnegative`` (Spark's NNLS mode): each row's regularized normal
system solves under a non-negativity constraint — Spark runs a modified
projected-CG NNLS per block row on executors; here EVERY row solves at
once as a vmapped projected cyclic coordinate descent on the same QP
(converges for the SPD ``A + λI``; KKT-verified in tests), which keeps
the solve a single batched XLA program like the Cholesky path.

TPU design: one half-step (all users, or all items) is fully batched
AND mesh-sharded — ratings shard over the data axis, each shard
``segment_sum``s its per-rating outer products into ``[n, r, r]``
partials, and ONE ``psum`` merges them (Spark's in/out-block shuffle as
a single collective); every row then solves at once under ``vmap``'d
Cholesky.  There is no per-user Python or driver loop anywhere.
Implicit mode adds the shared ``YᵀY`` Gram once per half-step (one MXU
matmul) exactly as Hu-Koren factorizes it.  ``recommendForAll*`` is one
``U @ Vᵀ`` matmul + ``top_k``.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.parallel.collectives import make_tree_aggregate, shard_batch
from sntc_tpu.parallel.context import get_default_mesh

_CHUNK = 250_000  # ratings per outer-product chunk (memory bound: _CHUNK·r²)


@lru_cache(maxsize=None)
def _normal_agg(mesh, n_rows, implicit):
    """Mesh-sharded sufficient statistics for one side's solve: ratings
    are row-sharded over the mesh, each shard ``segment_sum``s its
    per-rating outer products into ``[n_rows, r, r]`` partials, and the
    ``psum`` merges them — Spark's in/out-block shuffle collapsed to one
    collective.  The replicated ``[n_rows, r, r]`` result is the
    algorithm's inherent statistic (Spark materializes the same blocks
    per executor).  ``wm`` is the padding mask (shard_batch replicates a
    real rating row into the padding, so unmasked padding would
    double-count it).

    Explicit:  ``A += Σ v vᵀ``,        ``b += Σ r·v``.
    Implicit:  ``A += Σ (c−1) v vᵀ``,  ``b += Σ c·v`` (c = 1 + α·r)."""

    def stats(rows, factors_other, ratings, alpha, wm):
        if implicit:
            scale = wm * (alpha * ratings)  # (c − 1), masked
            rhs_w = wm * (1.0 + alpha * ratings)
        else:
            scale = wm
            rhs_w = wm * ratings
        outer = (
            scale[:, None, None]
            * factors_other[:, :, None] * factors_other[:, None, :]
        )
        A = jax.ops.segment_sum(outer, rows, num_segments=n_rows)
        b = jax.ops.segment_sum(
            rhs_w[:, None] * factors_other, rows, num_segments=n_rows
        )
        cnt = jax.ops.segment_sum(wm, rows, num_segments=n_rows)
        return A, b, cnt

    # alpha is a replicated scalar arg; wm is built by shard_batch
    return make_tree_aggregate(stats, mesh, replicated_args=(3,))


@jax.jit
def _solve_all(A, b, reg_diag):
    """vmapped PSD solve ``(A + diag(reg)) x = b`` for every row."""
    r = A.shape[1]
    A_reg = A + reg_diag[:, None, None] * jnp.eye(r, dtype=A.dtype)

    def solve_one(m, rhs):
        c, low = jax.scipy.linalg.cho_factor(m)
        return jax.scipy.linalg.cho_solve((c, low), rhs)

    return jax.vmap(solve_one)(A_reg, b)


_NNLS_TOL = 1e-6
_NNLS_MAX_SWEEPS = 500


@jax.jit
def _solve_all_nnls(A, b, reg_diag):
    """vmapped NNLS: ``argmin_{x≥0} ½xᵀ(A+diag(reg))x − bᵀx`` per row by
    projected cyclic coordinate descent — each coordinate's exact
    minimizer clipped at 0, swept until the largest update stalls.
    Globally convergent for SPD systems (the regularized normal matrix
    always is); whole-side batching via vmap keeps it one XLA program."""
    r = A.shape[1]
    A_reg = A + reg_diag[:, None, None] * jnp.eye(r, dtype=A.dtype)

    def solve_one(m, rhs):
        diag = jnp.maximum(jnp.diagonal(m), 1e-12)

        def coord(j, x):
            g = m[j] @ x - rhs[j]
            return x.at[j].set(jnp.maximum(x[j] - g / diag[j], 0.0))

        def sweep(state):
            x, _, it = state
            x_new = jax.lax.fori_loop(0, r, coord, x)
            return x_new, jnp.max(jnp.abs(x_new - x)), it + 1

        def unconverged(state):
            x, delta, it = state
            return (delta > _NNLS_TOL * (1.0 + jnp.max(jnp.abs(x)))) & (
                it < _NNLS_MAX_SWEEPS
            )

        x0 = jnp.zeros_like(rhs)
        x, _, _ = jax.lax.while_loop(
            unconverged, sweep,
            (x0, jnp.asarray(jnp.inf, x0.dtype), jnp.asarray(0, jnp.int32)),
        )
        return x

    return jax.vmap(solve_one)(A_reg, b)


class _AlsParams:
    userCol = Param("user id column", default="user")
    itemCol = Param("item id column", default="item")
    ratingCol = Param("rating column", default="rating")
    predictionCol = Param("output prediction column", default="prediction")
    rank = Param("factor dimension", default=10, validator=validators.gt(0))
    maxIter = Param("alternation rounds", default=10,
                    validator=validators.gt(0))
    regParam = Param("λ, ALS-WR scaled by each row's rating count",
                     default=0.1, validator=validators.gteq(0))
    implicitPrefs = Param("Hu-Koren implicit feedback", default=False,
                          validator=validators.is_bool())
    alpha = Param("implicit confidence slope", default=1.0,
                  validator=validators.gteq(0))
    coldStartStrategy = Param(
        "nan | drop for unseen ids at transform", default="nan",
        validator=validators.one_of("nan", "drop"),
    )
    nonnegative = Param(
        "constrain factors to be non-negative (NNLS solves)",
        default=False, validator=validators.is_bool(),
    )
    seed = Param("random seed", default=0)


class ALS(_AlsParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "ALSModel":
        users = np.asarray(frame[self.getUserCol()]).astype(np.int64)
        items = np.asarray(frame[self.getItemCol()]).astype(np.int64)
        ratings = np.asarray(frame[self.getRatingCol()], np.float32)
        implicit = bool(self.getImplicitPrefs())
        if implicit and np.any(ratings < 0):
            raise ValueError(
                "implicitPrefs requires non-negative ratings (they enter "
                "the confidence c = 1 + alpha*r)"
            )
        uids = np.unique(users)
        iids = np.unique(items)
        u_lut = {int(v): j for j, v in enumerate(uids)}
        i_lut = {int(v): j for j, v in enumerate(iids)}
        u = np.fromiter((u_lut[int(x)] for x in users), np.int32, len(users))
        i = np.fromiter((i_lut[int(x)] for x in items), np.int32, len(items))
        n_u, n_i = len(uids), len(iids)
        rank = int(self.getRank())
        lam = float(self.getRegParam())
        alpha = float(self.getAlpha())

        rng = np.random.default_rng(self.getSeed())
        # Spark init: abs(normal)/sqrt(rank)-style small positive factors
        U = (np.abs(rng.normal(size=(n_u, rank))) / np.sqrt(rank)).astype(
            np.float32
        )
        V = (np.abs(rng.normal(size=(n_i, rank))) / np.sqrt(rank)).astype(
            np.float32
        )

        mesh = self._mesh or get_default_mesh()

        def half_step(rows, other_idx, other, n_rows):
            A = np.zeros((n_rows, rank, rank), np.float32)
            b = np.zeros((n_rows, rank), np.float32)
            cnt = np.zeros(n_rows, np.float32)
            agg = _normal_agg(mesh, n_rows, implicit)
            for s in range(0, len(rows), _CHUNK):
                sl = slice(s, s + _CHUNK)
                rs, fo, rr, wm = shard_batch(
                    mesh, rows[sl], other[other_idx[sl]], ratings[sl]
                )
                dA, db, dc = agg(rs, fo, rr, jnp.float32(alpha), wm)
                A += np.asarray(dA)
                b += np.asarray(db)
                cnt += np.asarray(dc)
            if implicit:
                # Hu-Koren: every row shares the full Gram YᵀY
                A = A + np.asarray(other.T @ other)[None, :, :]
            # ALS-WR: λ scaled by the row's rating count (Spark [U]);
            # rows with no ratings keep a bare λ ridge (then solve to 0)
            reg = lam * np.maximum(cnt, 1.0)
            solver = (
                _solve_all_nnls if self.getNonnegative() else _solve_all
            )
            return np.asarray(
                solver(
                    jnp.asarray(A), jnp.asarray(b), jnp.asarray(reg)
                ),
                np.float32,
            )

        for _ in range(int(self.getMaxIter())):
            U = half_step(u, i, V, n_u)
            V = half_step(i, u, U, n_i)

        model = ALSModel(
            userIds=uids, itemIds=iids, userFactors=U, itemFactors=V
        )
        model.setParams(**self.paramValues())
        return model


class ALSModel(_AlsParams, Model):
    def __init__(self, userIds, itemIds, userFactors, itemFactors, **kwargs):
        super().__init__(**kwargs)
        self.userIds = np.asarray(userIds, np.int64)
        self.itemIds = np.asarray(itemIds, np.int64)
        self._uf = np.asarray(userFactors, np.float32)
        self._if = np.asarray(itemFactors, np.float32)
        self._u_lut = {int(v): j for j, v in enumerate(self.userIds)}
        self._i_lut = {int(v): j for j, v in enumerate(self.itemIds)}

    @property
    def rank(self) -> int:
        return self._uf.shape[1]

    @property
    def userFactors(self) -> Frame:
        return Frame({"id": self.userIds, "features": self._uf})

    @property
    def itemFactors(self) -> Frame:
        return Frame({"id": self.itemIds, "features": self._if})

    def _save_extra(self):
        return {}, {
            "userIds": self.userIds, "itemIds": self.itemIds,
            "userFactors": self._uf, "itemFactors": self._if,
        }

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(
            userIds=arrays["userIds"], itemIds=arrays["itemIds"],
            userFactors=arrays["userFactors"],
            itemFactors=arrays["itemFactors"],
        )
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        users = np.asarray(frame[self.getUserCol()]).astype(np.int64)
        items = np.asarray(frame[self.getItemCol()]).astype(np.int64)
        ui = np.array([self._u_lut.get(int(x), -1) for x in users])
        ii = np.array([self._i_lut.get(int(x), -1) for x in items])
        known = (ui >= 0) & (ii >= 0)
        pred = np.full(len(users), np.nan, np.float64)
        if known.any():
            pred[known] = np.einsum(
                "nr,nr->n",
                self._uf[ui[known]].astype(np.float64),
                self._if[ii[known]].astype(np.float64),
            )
        out = frame.with_column(self.getPredictionCol(), pred)
        if self.getColdStartStrategy() == "drop":
            out = out.filter(~np.isnan(pred))
        return out

    def _recommend(self, left, right, left_ids, right_ids, k):
        scores = jnp.asarray(left) @ jnp.asarray(right).T
        vals, idx = jax.lax.top_k(scores, min(k, right.shape[0]))
        return Frame({
            "id": left_ids,
            "recommendations": np.asarray(right_ids)[np.asarray(idx)],
            "ratings": np.asarray(vals, np.float64),
        })

    def recommendForAllUsers(self, numItems: int) -> Frame:
        return self._recommend(
            self._uf, self._if, self.userIds, self.itemIds, numItems
        )

    def recommendForAllItems(self, numUsers: int) -> Frame:
        return self._recommend(
            self._if, self._uf, self.itemIds, self.userIds, numUsers
        )
