"""``sntc_tpu.stat`` — the ``pyspark.ml.stat`` surface, TPU-first.

Behavioral spec: Spark's ``ml/stat/{Correlation,ChiSquareTest,ANOVATest,
FValueTest,KolmogorovSmirnovTest,Summarizer}.scala`` [U] (the hypothesis-test
statistics themselves live in ``mllib/stat/test/*`` [U]; SURVEY.md §2.2 maps
the χ² machinery).  Spark returns each result as a one-row DataFrame of
vector/matrix structs; here the same values come back as a one-row
:class:`~sntc_tpu.core.frame.Frame` whose 2-D columns are the vectors (and,
for ``Correlation``, an ``[F, F]`` frame of matrix rows) — the eager analog
of Spark's lazy result row.

TPU design: every O(N) reduction is ONE fused SPMD pass over the
mesh-sharded rows (``make_tree_aggregate`` → per-shard partials → ``psum``):

* ``Correlation`` (pearson): the Gram matrix ``Xᶜᵀ Xᶜ`` is a single [F,N]×
  [N,F] contraction per shard — pure MXU work; spearman is the same pass on
  average-tie ranks (rank transform on host: a global sort is host work,
  exactly Spark's ``zipWithIndex`` rank stage).
* ``Summarizer``: count/weightSum/mean/variance/L1/L2/nnz/min/max in one
  program.  min/max ride the sum-only ``psum`` via a one-hot-by-
  ``axis_index`` outer product (each shard deposits its row extrema in its
  own row of a ``[n_dev, F]`` partial; the host folds the tiny stack).
  Padding rows replicate a real row (collectives.shard_batch), so raw
  extrema need no masking.
* χ²/ANOVA/F-value reuse the selector aggregates (`feature/chisq_selector`,
  `feature/univariate_selector`) — one statistics engine, two surfaces,
  matching Spark where ``ChiSqSelector`` and ``ChiSquareTest`` share
  ``mllib.stat.Statistics``.
* KS runs host-side end to end (sort + CDF + Kolmogorov p): a 1-D sort
  whose downstream work is all host would only lose float64 precision on
  a device round-trip (x64 is off device-side; commons-math computes in
  double) — the SURVEY.md §2.4 "on host" exception class.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.frame import Frame
from sntc_tpu.feature.univariate_selector import (
    _anova_moments_agg,
    _regression_moments_agg,
    f_classif,
    f_regression,
)
from sntc_tpu.ops.histogram import (
    binned_contingency,
    binned_contingency_onehot,
    chi_square,
)
from sntc_tpu.ops.pallas_histogram import resolve_hist_impl
from sntc_tpu.parallel.collectives import (
    make_tree_aggregate,
    shard_batch,
    shard_weights,
)
from sntc_tpu.parallel.context import get_default_mesh
from sntc_tpu.parallel.mesh import DATA_AXIS

__all__ = [
    "ANOVATest",
    "ChiSquareTest",
    "Correlation",
    "FValueTest",
    "KolmogorovSmirnovTest",
    "Summarizer",
]


def _features_matrix(frame: Frame, col: str) -> np.ndarray:
    X = frame[col]
    if X.ndim == 1:
        X = np.asarray(X)[:, None]
    return X


# ---------------------------------------------------------------------------
# Correlation
# ---------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _corr_moments_agg(mesh):
    """``(Σw, Σw·xᶜ [F], xᶜᵀ diag(w) xᶜ [F,F])`` about a replicated pilot
    row — the Gram contraction is the MXU op; the pilot shift keeps f32
    squares from cancelling (same idiom as the selector aggregates)."""

    def moments(xs, w, pilot):
        xc = xs - pilot[None, :]
        wx = xc * w[:, None]
        return w.sum(), wx.sum(axis=0), xc.T @ wx

    return make_tree_aggregate(moments, mesh, replicated_args=(2,))


def _rank_columns(X: np.ndarray) -> np.ndarray:
    """Average-tie ranks per column (Spark's Spearman rank stage [U]:
    ties share the mean of their positional ranks)."""
    from scipy.stats import rankdata

    return np.stack(
        [rankdata(X[:, j], method="average") for j in range(X.shape[1])],
        axis=1,
    ).astype(np.float32)


class Correlation:
    """``ml.stat.Correlation.corr`` [U]: the F×F correlation matrix of a
    vector column.  Returns an ``[F, F]`` Frame (row ``i`` = matrix row
    ``i``) under the method-name column, the eager analog of Spark's
    one-Matrix-row DataFrame."""

    @staticmethod
    def corr(
        frame: Frame,
        column: str,
        method: str = "pearson",
        mesh=None,
    ) -> Frame:
        if method not in ("pearson", "spearman"):
            raise ValueError(
                f"method must be 'pearson' or 'spearman', got {method!r}"
            )
        mesh = mesh or get_default_mesh()
        X = _features_matrix(frame, column).astype(np.float32)
        if X.shape[0] < 1:
            raise ValueError("Correlation requires a non-empty dataset")
        if method == "spearman":
            X = _rank_columns(X)
        xs, w = shard_batch(mesh, X)
        n, s, gram = _corr_moments_agg(mesh)(xs, w, jnp.asarray(X[0]))
        n = float(n)
        s = np.asarray(s, np.float64)
        cov = np.asarray(gram, np.float64) - np.outer(s, s) / n
        d = np.sqrt(np.maximum(np.diag(cov), 0.0))
        with np.errstate(divide="ignore", invalid="ignore"):
            m = cov / np.outer(d, d)
        # Spark yields NaN for zero-variance features; the diagonal is 1
        m[np.isinf(m)] = np.nan
        np.fill_diagonal(m, 1.0)
        return Frame({method: np.clip(m, -1.0, 1.0)})


# ---------------------------------------------------------------------------
# Hypothesis tests
# ---------------------------------------------------------------------------

def _test_frame(stats, pvals, dofs, flatten: bool) -> Frame:
    stats = np.asarray(stats, np.float64)
    pvals = np.asarray(pvals, np.float64)
    dofs = np.asarray(dofs, np.int64)
    if flatten:
        return Frame(
            {
                "featureIndex": np.arange(stats.shape[0], dtype=np.int64),
                "pValue": pvals,
                "degreesOfFreedom": dofs,
                "statistic": stats,
            }
        )
    return Frame(
        {
            "pValues": pvals[None, :],
            "degreesOfFreedom": dofs[None, :],
            "statistics": stats[None, :],
        }
    )


class ChiSquareTest:
    """``ml.stat.ChiSquareTest`` [U]: Pearson χ² independence test of every
    categorical feature against a categorical label.  Feature values are
    factorized on host (Spark's ``distinct`` stage); the (feature, value,
    class) contingency is one SPMD ``segment_sum`` pass on the mesh."""

    #: Spark's ChiSqTest "maxCategories" guard [U]: a feature with more
    #: distinct values than this is almost surely continuous — reject it
    #: rather than build a degenerate table.
    MAX_CATEGORIES = 10_000

    @staticmethod
    def test(
        frame: Frame,
        featuresCol: str,
        labelCol: str,
        flatten: bool = False,
        mesh=None,
    ) -> Frame:
        mesh = mesh or get_default_mesh()
        X = _features_matrix(frame, featuresCol)
        y = np.asarray(frame[labelCol])
        classes, y_idx = np.unique(y, return_inverse=True)
        cols, cards = [], []
        for j in range(X.shape[1]):
            vals, idx = np.unique(X[:, j], return_inverse=True)
            if len(vals) > ChiSquareTest.MAX_CATEGORIES:
                raise ValueError(
                    f"feature {j} has {len(vals)} distinct values "
                    f"(> {ChiSquareTest.MAX_CATEGORIES}); χ² requires "
                    "categorical features — bin or discretize first"
                )
            cols.append(idx)
            cards.append(len(vals))
        binned = np.stack(cols, axis=1).astype(np.int32)
        n_bins = max(cards)
        xs, ys, w = shard_batch(mesh, binned, y_idx.astype(np.int32))
        on_tpu = jax.default_backend() == "tpu"
        impl = resolve_hist_impl(1, n_bins, mesh)
        agg = _contingency_count_agg(
            mesh, n_bins, len(classes), impl, not on_tpu
        )
        observed = np.asarray(agg(xs, ys, w))
        stats, pvals, dofs = chi_square(observed)
        return _test_frame(stats, pvals, dofs, flatten)


@lru_cache(maxsize=None)
def _contingency_count_agg(mesh, n_bins, n_classes, impl, interpret):
    """Same impl dispatch as ``chisq_selector._contingency_agg``: the
    one-hot MXU kernel on TPU (scatter-adds serialize there — profiled
    2.75–15× slower), ``segment_sum`` elsewhere."""

    def contingency(binned, ys, w):
        if impl == "pallas":
            return binned_contingency_onehot(
                binned, ys, w, n_bins=n_bins, n_classes=n_classes,
                interpret=interpret,
            )
        return binned_contingency(
            binned, ys, w, n_bins=n_bins, n_classes=n_classes
        )

    return make_tree_aggregate(
        contingency, mesh, check_vma=impl != "pallas"
    )


class ANOVATest:
    """``ml.stat.ANOVATest`` [U] (Spark 3.1): one-way ANOVA F-test of
    continuous features against a categorical label — the
    ``UnivariateFeatureSelector`` continuous/categorical score as a
    standalone test surface."""

    @staticmethod
    def test(
        frame: Frame,
        featuresCol: str,
        labelCol: str,
        flatten: bool = False,
        mesh=None,
    ) -> Frame:
        mesh = mesh or get_default_mesh()
        X = _features_matrix(frame, featuresCol).astype(np.float32)
        y = np.asarray(frame[labelCol]).astype(np.int32)
        if X.shape[0] == 0:
            raise ValueError("ANOVATest requires a non-empty dataset")
        n_classes = int(y.max()) + 1
        xs, ys, w = shard_batch(mesh, X, y)
        cnt, s, sq = _anova_moments_agg(mesh, n_classes)(
            xs, ys, w, jnp.asarray(X[0])
        )
        F, p = f_classif((cnt, s, sq))
        k = int((np.asarray(cnt) > 0).sum())
        n = float(np.asarray(cnt).sum())
        dof = np.full(F.shape[0], max(int(n) - k, 0), dtype=np.int64)
        return _test_frame(F, p, dof, flatten)


class FValueTest:
    """``ml.stat.FValueTest`` [U] (Spark 3.1): univariate linear-fit F-test
    of continuous features against a continuous label."""

    @staticmethod
    def test(
        frame: Frame,
        featuresCol: str,
        labelCol: str,
        flatten: bool = False,
        mesh=None,
    ) -> Frame:
        mesh = mesh or get_default_mesh()
        X = _features_matrix(frame, featuresCol).astype(np.float32)
        y = np.asarray(frame[labelCol]).astype(np.float32)
        if X.shape[0] == 0:
            raise ValueError("FValueTest requires a non-empty dataset")
        xs, ys, w = shard_batch(mesh, X, y)
        m = _regression_moments_agg(mesh)(
            xs, ys, w, jnp.asarray(X[0]), jnp.float32(y[0])
        )
        F, p = f_regression(m)
        n = float(np.asarray(m[0]))
        dof = np.full(F.shape[0], max(int(n) - 2, 0), dtype=np.int64)
        return _test_frame(F, p, dof, flatten)


class KolmogorovSmirnovTest:
    """``ml.stat.KolmogorovSmirnovTest`` [U]: one-sample, two-sided KS test
    of a sample column against a theoretical distribution, host-side in
    float64 (Spark delegates to commons-math ``KolmogorovSmirnovTest``
    [U], which computes in double; the asymptotic Kolmogorov p-value is
    the same form)."""

    @staticmethod
    def test(
        frame: Frame,
        sampleCol: str,
        distName: str = "norm",
        *params: float,
    ) -> Frame:
        from scipy import stats as sps

        if distName != "norm":
            raise ValueError(
                "only distName='norm' is supported (the one distribution "
                "Spark's KolmogorovSmirnovTest ships [U])"
            )
        x = np.asarray(frame[sampleCol]).astype(np.float64).ravel()
        n = x.shape[0]
        if n == 0:
            raise ValueError("KolmogorovSmirnovTest requires a non-empty sample")
        if len(params) not in (0, 2):
            raise ValueError(
                "distName='norm' takes zero params (standard normal) or "
                f"exactly (mean, std); got {len(params)}"
            )
        mean, std = (params if len(params) == 2 else (0.0, 1.0))
        # host sort: keeps the sample in float64 end to end (x64 is off
        # device-side, and commons-math/Spark compute in double); the
        # downstream CDF work is host-side anyway
        x_sorted = np.sort(x)
        cdf = sps.norm.cdf(x_sorted, loc=mean, scale=std)
        i = np.arange(1, n + 1, dtype=np.float64)
        d = float(np.max(np.maximum(cdf - (i - 1) / n, i / n - cdf)))
        p = float(sps.kstwobign.sf(d * np.sqrt(n)))
        return Frame(
            {"pValue": np.array([p]), "statistic": np.array([d])}
        )


# ---------------------------------------------------------------------------
# Summarizer
# ---------------------------------------------------------------------------

_SUMMARY_METRICS = (
    "mean",
    "sum",
    "variance",
    "std",
    "count",
    "numNonZeros",
    "max",
    "min",
    "normL1",
    "normL2",
    "weightSum",
)


@lru_cache(maxsize=None)
def _summary_agg(mesh):
    """Every Summarizer metric from ONE fused pass.  Moment sums are taken
    about a replicated pilot row (f32 cancellation); norms/nnz use the raw
    values (sums of non-negatives — no cancellation).  min/max become
    psum-able by depositing each shard's extrema into its own row of a
    ``[n_dev, F]`` one-hot outer product."""
    n_dev = mesh.shape[DATA_AXIS]

    def moments(xs, wr, pilot):
        xc = xs - pilot[None, :]
        wx = xc * wr[:, None]
        oh = jax.nn.one_hot(
            jax.lax.axis_index(DATA_AXIS), n_dev, dtype=jnp.float32
        )
        # Spark's SummarizerBuffer skips weight-0 instances entirely, so
        # extrema and count consider only wr>0 rows (this also masks the
        # padding rows).  ±FLT_MAX sentinels — not ±inf — keep the one-hot
        # outer product NaN-free when a shard holds no real rows.
        live = wr[:, None] > 0
        big = jnp.float32(np.finfo(np.float32).max)
        mn = oh[:, None] * jnp.where(live, xs, big).min(axis=0)[None, :]
        mx = oh[:, None] * jnp.where(live, xs, -big).max(axis=0)[None, :]
        return {
            "count": (wr > 0).sum().astype(jnp.float32),
            "wsum": wr.sum(),
            "w2sum": (wr * wr).sum(),
            "s1": wx.sum(axis=0),
            "s2": (xc * wx).sum(axis=0),
            "l1": (jnp.abs(xs) * wr[:, None]).sum(axis=0),
            "l2sq": (xs * xs * wr[:, None]).sum(axis=0),
            "nnz": ((xs != 0) * wr[:, None]).sum(axis=0),
            "mn": mn,
            "mx": mx,
        }

    return make_tree_aggregate(moments, mesh, replicated_args=(2,))


class SummaryBuilder:
    """The object ``Summarizer.metrics(...)`` returns [U].  ``summary``
    computes the requested metrics eagerly (our Frames are eager; Spark's
    builder emits a lazy struct column)."""

    def __init__(self, metrics):
        unknown = [m for m in metrics if m not in _SUMMARY_METRICS]
        if unknown:
            raise ValueError(
                f"unknown summary metrics {unknown}; choose from "
                f"{_SUMMARY_METRICS}"
            )
        self._metrics = tuple(metrics)

    def summary(
        self,
        frame: Frame,
        col: str = "features",
        weightCol: Optional[str] = None,
        mesh=None,
        weightNorm: str = "reliability",
    ) -> Frame:
        """``weightNorm`` (extension; Spark has no knob): "reliability"
        (default) matches ``ml.stat`` SummarizerBuffer's unbiased
        denominator Σw − Σw²/Σw; "frequency" uses Σw − 1, under which
        ``weightCol`` ≡ integer row replication (the contract the
        framework's weighted FITS pin).  Unweighted they coincide."""
        mesh = mesh or get_default_mesh()
        X = _features_matrix(frame, col).astype(np.float32)
        if X.shape[0] == 0:
            raise ValueError("Summarizer requires a non-empty dataset")
        xs, mask = shard_batch(mesh, X)
        if weightCol is not None:
            wr = shard_weights(
                mesh,
                np.asarray(frame[weightCol]).astype(np.float32),
                xs.shape[0],
            )
        else:
            wr = mask  # padding rows carry weight 0 either way
        m = _summary_agg(mesh)(xs, wr, jnp.asarray(X[0]))
        m = {k: np.asarray(v, np.float64) for k, v in m.items()}
        wsum, pilot = m["wsum"], X[0].astype(np.float64)
        if wsum <= 0:
            raise ValueError(
                "Summarizer: total weight is zero (all rows weight-0)"
            )
        mean = pilot + m["s1"] / wsum
        # unbiased variance.  Default denominator is the RELIABILITY-
        # weight form Σw − Σw²/Σw — exactly Spark's ml.stat
        # SummarizerBuffer/MultivariateOnlineSummarizer (parity; r5 closed
        # the former frequency-denominator delta).  "frequency" keeps the
        # Σw − 1 replication contract as an opt-in extension.
        if weightNorm not in ("reliability", "frequency"):
            raise ValueError(
                f"weightNorm must be 'reliability' or 'frequency', got "
                f"{weightNorm!r}"
            )
        denom = float(
            wsum - m["w2sum"] / wsum
            if weightNorm == "reliability"
            else wsum - 1.0
        )
        # Spark: a non-positive denominator (single row / one dominant
        # weight) yields zero variance, not a division blow-up
        if denom > 0:
            var = np.maximum(
                (m["s2"] - m["s1"] ** 2 / wsum) / denom, 0.0
            )
        else:
            var = np.zeros_like(mean)
        values = {
            "mean": mean,
            "sum": mean * wsum,
            "variance": var,
            "std": np.sqrt(var),
            "count": np.int64(round(float(m["count"]))),
            "numNonZeros": m["nnz"],
            "max": m["mx"].max(axis=0),
            "min": m["mn"].min(axis=0),
            "normL1": m["l1"],
            "normL2": np.sqrt(m["l2sq"]),
            "weightSum": float(wsum),
        }
        out = {}
        for name in self._metrics:
            v = values[name]
            out[name] = (
                np.asarray(v)[None, :] if np.ndim(v) == 1
                else np.asarray([v])
            )
        return Frame(out)


class Summarizer:
    """``ml.stat.Summarizer`` [U]: vector-column summary statistics in one
    pass.  ``Summarizer.metrics("mean", "variance").summary(df, "features",
    weightCol)`` — the Spark call shape, eager result."""

    @staticmethod
    def metrics(*names: str) -> SummaryBuilder:
        if not names:
            raise ValueError("Summarizer.metrics requires at least one metric")
        return SummaryBuilder(names)

    # Spark's single-metric shorthands [U]
    @staticmethod
    def mean(frame, col="features", weightCol=None, mesh=None):
        return SummaryBuilder(("mean",)).summary(frame, col, weightCol, mesh)

    @staticmethod
    def variance(frame, col="features", weightCol=None, mesh=None):
        return SummaryBuilder(("variance",)).summary(
            frame, col, weightCol, mesh
        )
