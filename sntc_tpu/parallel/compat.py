"""jax version compat for the collective layer.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace, and its replication-checking kwarg was
renamed ``check_rep`` → ``check_vma`` in the same move.  This repo's
call sites are written against the NEW surface; on an older jax (the
container ships 0.4.37, where ``jax.shard_map`` does not exist yet)
every mesh-sharded fit and collective died with
``AttributeError: module 'jax' has no attribute 'shard_map'``.  Resolve
the implementation once at import and translate the kwarg, so the rest
of the codebase stays on the modern spelling.
"""

from __future__ import annotations

import jax

try:
    _shard_map = jax.shard_map
    _CHECK_KW = "check_vma"
except AttributeError:  # pre-graduation jax: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the modern signature on any supported jax.

    On legacy jax the replication check is DISABLED outright: the old
    ``check_rep`` machinery has no rule for ``while`` (every
    ``lax.while_loop``/``scan`` body trips ``NotImplementedError``), and
    the check is advisory — out-spec correctness here is guaranteed by
    the psum-before-return convention of every call site, which the
    modern ``check_vma`` validates where available."""
    check = check_vma if _CHECK_KW == "check_vma" else False
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_CHECK_KW: check},
    )
