"""Guarded import of ``shard_map`` (top-level jax vs experimental).

The mesh substrate (``sntc_tpu.parallel.mesh``) is the ONLY consumer;
it translates the modern ``check_vma`` kwarg to the legacy spelling.
Delete outright once the container's jax grows ``jax.shard_map``.
"""

import jax

try:
    _shard_map = jax.shard_map
    _CHECK_KW = "check_vma"
except AttributeError:  # pre-graduation jax (container ships 0.4.37)
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"
