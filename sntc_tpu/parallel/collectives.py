"""SPMD collectives — the ``treeAggregate`` / ``TorrentBroadcast`` analog.

Spark's per-iteration comm triad (SURVEY.md §3.1, §5.8):

    broadcast(params)  ->  per-partition seqOp  ->  tree-reduce combOp to driver

collapses on TPU into one SPMD program: params are replicated by sharding,
the seqOp is the per-shard computation, and the combOp is ``jax.lax.psum``
over the ICI ``"data"`` axis — on-device, no host hop, no serialization
(netty RPC / shuffle / torrent broadcast all deleted per SURVEY.md §2.5).

Built on the r22 mesh substrate (``sntc_tpu.parallel.mesh``): the
per-shard map + named-axis reduce is expressed with
:func:`~sntc_tpu.parallel.mesh.map_reduce_at`, host↔device placement is
attributed through the :class:`~sntc_tpu.utils.profiling.TransferLedger`
plane, and every dispatch records ``sntc_collective_*`` evidence
(dispatches + ring-allreduce wire bytes per (op, axis)).

``tree_aggregate(fn, mesh, *arrays)`` is the named API estimators use; it
shards each array's leading axis over the mesh, applies ``fn`` per shard, and
``psum``s every leaf of the result.  Rows are padded to a shard multiple with
an explicit weight column so padding contributes zero (callers thread the
weight through ``fn``).

**Elastic mesh (r22):** a ``device_lost`` surfacing from a dispatch no
longer flips the whole host HOST_DEGRADED — the aggregate *resizes*: the
data axis shrinks to the largest power-of-two shard count the padded
batch still divides over, the batch is re-placed on the surviving
devices, the decision is journaled (``mesh_resize``) on the attached
:class:`~sntc_tpu.resilience.device.DeviceFaultDomain`, and the dispatch
retries on the smaller mesh.  A per-shard ``RESOURCE_EXHAUSTED`` rides
the existing ``device_oom`` ladder instead: the padded batch splits into
two shard-aligned row halves whose partials SUM to the full result
(every aggregate ``fn`` returns an additive sum-tree by contract), with
the recursion depth bounded by the domain's ``oom_split_depth``.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sntc_tpu.parallel.mesh import (
    DATA_AXIS,
    map_reduce_at,
    payload_nbytes,
    record_collective,
    record_mesh_shape,
)
from sntc_tpu.resilience import (
    CircuitOpenError,
    RetryPolicy,
    breaker_for,
    fault_point,
    with_retries,
)
from sntc_tpu.resilience.policy import int_from_env


def _dispatch_breaker():
    """Optional circuit breaker for aggregate dispatch:
    ``SNTC_COLLECTIVE_BREAKER=1`` shares one process-wide breaker for
    site ``collective.dispatch`` across every aggregate — when a
    backend is down hard, dispatch fails FAST with
    :class:`CircuitOpenError` instead of burning a retry budget per
    call.  Cooldown via ``SNTC_COLLECTIVE_BREAKER_COOLDOWN_S``
    (default 30).  Default off: dispatch behavior is unchanged."""
    if int_from_env("SNTC_COLLECTIVE_BREAKER", 0) <= 0:
        return None
    cooldown = int_from_env("SNTC_COLLECTIVE_BREAKER_COOLDOWN_S", 30)
    return breaker_for("collective.dispatch", cooldown_s=float(cooldown))


def _dispatch_policy() -> "RetryPolicy | None":
    """Optional retry for aggregate dispatch (site
    ``collective.dispatch``): ``SNTC_COLLECTIVE_RETRIES=N`` arms N
    in-place retries with deterministic backoff for dispatch failures
    that RAISE (transient backend RPC/transfer errors, injected
    faults).  It cannot help the XLA:CPU rendezvous-timeout class that
    SIGABRTs the whole process (VERDICT r5) — process-level isolation
    (``bench.py --isolate``) is the mitigation there.  Default 0
    (single-shot: dispatch failures propagate unchanged)."""
    retries = int_from_env("SNTC_COLLECTIVE_RETRIES", 0, minimum=0)
    if retries <= 0:
        return None
    return RetryPolicy(
        max_attempts=retries + 1, base_delay_s=0.1, multiplier=2.0,
        max_delay_s=10.0, jitter=0.1, seed=0,
    )


# ---------------------------------------------------------------------------
# compute fault-domain attachment — the collective layer's hook into the
# PR-13 device state machine.  Fits that want mesh_resize / oom_split
# decisions journaled attach a DeviceFaultDomain process-wide (bench
# chaos legs, the serve daemon's fit path); unattached, the elastic
# responses still run and still emit events/metrics, they just have no
# journal to land in.
# ---------------------------------------------------------------------------

_COLLECTIVE_DOMAIN = None


def set_collective_domain(domain) -> None:
    """Attach (or detach with ``None``) the process-wide
    :class:`~sntc_tpu.resilience.device.DeviceFaultDomain` that
    collective-layer survival decisions journal into."""
    global _COLLECTIVE_DOMAIN
    _COLLECTIVE_DOMAIN = domain


def get_collective_domain():
    return _COLLECTIVE_DOMAIN


def _resize_enabled() -> bool:
    """``SNTC_MESH_RESIZE=0`` disables the elastic response (a lost
    device then propagates to the caller / the host domain, the pre-r22
    behavior).  Default on."""
    return int_from_env("SNTC_MESH_RESIZE", 1) > 0


def _ledger_movement(nbytes: int) -> None:
    """Attribute one substrate upload to every active
    :class:`TransferLedger` (tenant/scope-attributed like serve
    dispatches).  ``record_movement`` counts arrays + bytes but NOT a
    dispatch — the dispatch series stays "fused program calls"."""
    try:
        from sntc_tpu.utils.profiling import active_ledgers

        for led in active_ledgers():
            led.record_movement(uploads=1, upload_bytes=int(nbytes))
    except Exception:
        pass


# ---------------------------------------------------------------------------
# device-residency cache — the BlockManager / ``df.cache()`` analog.
#
# Frames are immutable by contract (sntc_tpu.core.frame), so re-sharding the
# SAME host array (re-fit on one dataset, CrossValidator's final refit, a
# second estimator reading the same column) can return the already-resident
# device copy instead of re-crossing the host↔device link — on a tunneled
# TPU that link costs seconds per 100 MB, and Spark survives the same
# re-scan problem only via explicit ``.cache()``.  Identity-keyed through a
# WEAK reference to the host array: a live array re-used is a hit; once the
# caller drops the array the entry dies with it (no pinning of throwaway
# uploads) and a recycled ``id`` can never false-hit because the dead
# weakref invalidates the entry.  Byte-bounded LRU on the device side;
# ``SNTC_DEVICE_CACHE_MB=0`` disables.
# ---------------------------------------------------------------------------

_DEVICE_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()


def _device_cache_max_bytes() -> int:
    return int(os.environ.get("SNTC_DEVICE_CACHE_MB", "2048")) * (1 << 20)


def _spans_processes(mesh: Mesh) -> bool:
    """True when the mesh includes devices of OTHER processes — the
    multi-host case where plain ``device_put`` cannot build the global
    array."""
    if jax.process_count() == 1:
        return False
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def _global_shard_put(arr_p, sharding):
    """Multi-host construction of a row-sharded global array: every
    process holds the FULL host array (single-host data plane, same on
    all processes) and serves its addressable shards by slicing — the
    ``make_array_from_callback`` path ``device_put`` cannot take across
    processes.  A ``jax.Array`` input (a device-resident column from an
    upstream stage) is resharded globally instead: fetching it to host
    would fail when it spans non-addressable devices."""
    if isinstance(arr_p, jax.Array):
        return jax.device_put(arr_p, sharding)
    return jax.make_array_from_callback(
        arr_p.shape, sharding, lambda idx: np.asarray(arr_p[idx])
    )


def _put_sharded(arr, sharding):
    """The one routing point: global construction when the mesh spans
    processes, plain ``device_put`` otherwise.  Every byte that crosses
    here lands in the active transfer ledgers — the r22 fix for
    collective dispatches undercounting the ``sntc_transfer_*``
    series."""
    if _spans_processes(sharding.mesh):
        out = _global_shard_put(arr, sharding)
    else:
        out = jax.device_put(arr, sharding)
    _ledger_movement(getattr(arr, "nbytes", 0))
    return out


def _cached_shard_put(arr, n_pad: int, sharding):
    """Pad ``arr`` to ``n_pad`` rows (replicating row 0) and device_put it
    under ``sharding``, memoized on the identity of the UNPADDED array."""
    import weakref

    cacheable = (
        isinstance(arr, np.ndarray)
        and arr.nbytes >= (1 << 20)
        and _device_cache_max_bytes() > 0
    )
    # sweep entries whose host array was garbage-collected
    for k in [k for k, e in _DEVICE_CACHE.items() if e[0]() is None]:
        del _DEVICE_CACHE[k]
    key = (id(arr), n_pad, sharding)
    if cacheable:
        hit = _DEVICE_CACHE.get(key)
        if hit is not None and hit[0]() is arr:
            _DEVICE_CACHE.move_to_end(key)
            return hit[1]
    n = arr.shape[0]
    if n_pad != n:
        if isinstance(arr, jax.Array):
            # device-resident input: pad on device, never revisit the host
            import jax.numpy as jnp

            pad_block = jnp.broadcast_to(
                arr[:1], (n_pad - n,) + arr.shape[1:]
            )
            arr_p = jnp.concatenate([arr, pad_block], axis=0)
        else:
            pad_block = np.broadcast_to(
                arr[:1], (n_pad - n,) + arr.shape[1:]
            )
            arr_p = np.concatenate([arr, pad_block], axis=0)
    else:
        arr_p = arr
    dev = _put_sharded(arr_p, sharding)
    if cacheable:
        try:
            ref = weakref.ref(arr)
        except TypeError:  # non-weakref-able array subclass
            return dev
        _DEVICE_CACHE[key] = (ref, dev)
        total = sum(e[1].nbytes for e in _DEVICE_CACHE.values())
        while total > _device_cache_max_bytes() and len(_DEVICE_CACHE) > 1:
            _, old = _DEVICE_CACHE.popitem(last=False)
            total -= old[1].nbytes
    return dev


def pad_rows(n: int, n_shards: int) -> int:
    """Rows after padding ``n`` up to a multiple of ``n_shards``, then up to
    a shape BUCKET.

    Bucketing rounds the per-shard row count to ~1.6% granularity so nearly
    equal dataset sizes (e.g. the k train splits of a CrossValidator fold
    loop) compile ONE XLA program instead of k — distinct compiled shapes
    are O(log n) overall.  Padded rows carry weight 0 everywhere (the
    masked-row idiom of this module), so results are unchanged.  Disable
    with ``SNTC_SHAPE_BUCKETS=0`` for exact-shape debugging.
    """
    m = ((n + n_shards - 1) // n_shards) * n_shards
    per = m // n_shards
    if per <= 64 or os.environ.get("SNTC_SHAPE_BUCKETS", "1") == "0":
        return m
    q = 1 << (per.bit_length() - 6)  # 1/64 granularity of the leading bit
    per = ((per + q - 1) // q) * q
    return per * n_shards


def shard_batch(mesh: Mesh, *arrays: np.ndarray, axis_name: str = DATA_AXIS):
    """Pad + device_put arrays row-sharded over the mesh.

    Returns ``(*sharded_arrays, weights)`` where ``weights`` is f32 (N,) with
    1.0 on real rows and 0.0 on padding — the masked-row idiom every reduction
    in this framework uses (SURVEY.md §7.2 mitigation for static shapes).
    Padding replicates row 0 (not zeros) so padded rows stay numerically
    benign under ops like log/σ; their weight removes them from results.
    """
    n = arrays[0].shape[0]
    n_shards = mesh.shape[axis_name]
    n_pad = pad_rows(n, n_shards)
    out = []
    for arr in arrays:
        if arr.shape[0] != n:
            raise ValueError("all arrays must share the leading dimension")
        sharding = NamedSharding(
            mesh, P(axis_name, *([None] * (arr.ndim - 1)))
        )
        out.append(_cached_shard_put(arr, n_pad, sharding))
    weights = np.zeros(n_pad, dtype=np.float32)
    weights[:n] = 1.0
    out.append(_put_sharded(weights, NamedSharding(mesh, P(axis_name))))
    return tuple(out)


def shard_weights(
    mesh: Mesh,
    w: np.ndarray,
    n_padded: int,
    axis_name: str = DATA_AXIS,
):
    """Row weights padded with zeros to ``n_padded`` and sharded over the
    mesh — the companion of :func:`shard_batch` when callers carry their own
    weight column (user weights × padding mask in one array)."""
    w_pad = np.zeros(n_padded, dtype=np.float32)
    w_pad[: len(w)] = w
    return _put_sharded(w_pad, NamedSharding(mesh, P(axis_name)))


def _shrunk_axis_size(survivors: int, n_pad: int) -> int:
    """Largest power-of-two shard count ≤ ``survivors`` that the padded
    batch still divides over.  Power-of-two steps keep every
    shape-bucketed padding (always a multiple of the ORIGINAL shard
    count, itself a power of two on the target topologies) divisible
    without re-padding; 1 always qualifies."""
    c = 1 << max(0, survivors.bit_length() - 1)
    while c > 1 and n_pad % c:
        c //= 2
    return max(1, c)


def make_tree_aggregate(
    fn: Callable,
    mesh: Mesh,
    axis_name: str = DATA_AXIS,
    check_vma: bool = True,
    replicated_args: tuple = (),
    op: str = "tree_aggregate",
) -> Callable:
    """Build a jitted ``agg(*arrays) -> pytree`` that computes
    ``psum_over_shards(fn(shard_of(*arrays)))``.

    ``fn`` takes row-shards (leading axis = local rows) and returns a pytree
    of fixed-shape partials; every leaf is summed across the mesh axis.
    The result is replicated on all devices (the driver-side combOp result,
    but living on-device).  Argument positions in ``replicated_args`` are
    NOT row-sharded — every shard sees them whole (per-call constants like
    bin edges; passing them as arguments instead of closing over them keeps
    one compiled program across calls).

    **Additivity contract:** ``fn``'s output must be an additive sum-tree
    over row partitions (``fn(rows) == fn(rows[:k]) + fn(rows[k:])`` leafwise)
    — true of every aggregate in this framework (moments, gram matrices,
    gradients, histograms, counts) and REQUIRED by the ``device_oom``
    responder, which splits the padded batch into shard-aligned halves and
    sums the two partial trees.

    ``op`` labels this aggregate's ``sntc_collective_*`` evidence series.

    NOTE each call builds a fresh ``jit`` wrapper with its own compile
    cache: callers that aggregate repeatedly (every estimator ``fit``)
    must build ONCE and reuse — on a TPU a rebuilt wrapper recompiles the
    whole program per call (~8 s observed for the scaler's moments pass).
    """
    state = {"mesh": mesh, "resized": False}
    programs: dict = {}
    record_mesh_shape(mesh)

    def _program(m: Mesh):
        prog = programs.get(m)
        if prog is None:

            def agg(*arrays):
                in_specs = tuple(
                    P() if i in replicated_args
                    else P(axis_name, *([None] * (a.ndim - 1)))
                    for i, a in enumerate(arrays)
                )
                return map_reduce_at(
                    m, fn, axis_name=axis_name, in_specs=in_specs,
                    check_vma=check_vma,
                )(*arrays)

            prog = jax.jit(agg)
            programs[m] = prog
        return prog

    def _row_spec(a) -> P:
        return P(axis_name, *([None] * (a.ndim - 1)))

    def _place_on(m: Mesh, arrays: tuple) -> tuple:
        """Re-place a batch on mesh ``m`` (host round trip for the
        row-sharded arrays — acceptable under the duress paths that
        need it, and every byte lands in the transfer ledgers)."""
        out = []
        for i, a in enumerate(arrays):
            spec = P() if i in replicated_args else _row_spec(a)
            out.append(_put_sharded(np.asarray(a), NamedSharding(m, spec)))
        return tuple(out)

    def _ensure_on(m: Mesh, arrays: tuple) -> tuple:
        """After a resize, batches sharded on the ORIGINAL mesh by an
        earlier :func:`shard_batch` still arrive here — detect the
        mismatch and migrate them onto the live mesh."""
        if not state["resized"]:
            return arrays
        live = tuple(np.asarray(m.devices).flat)
        for a in arrays:
            sh = getattr(a, "sharding", None)
            msh = getattr(sh, "mesh", None)
            if msh is not None and tuple(np.asarray(msh.devices).flat) != live:
                return _place_on(m, arrays)
        return arrays

    def _oom_depth_limit() -> int:
        dom = get_collective_domain()
        if dom is not None:
            return dom.policy.oom_split_depth
        return int_from_env("SNTC_COLLECTIVE_OOM_DEPTH", 4, minimum=1)

    def _resize(exc: BaseException, arrays: tuple) -> tuple:
        """The elastic response to a participant dropping out: shrink
        the data axis, re-place the batch on the survivors, journal the
        ``mesh_resize`` decision.  Raises ``exc`` when a resize is not
        possible (1-device mesh, disabled, multi-host)."""
        old = state["mesh"]
        old_n = int(old.shape[axis_name])
        if old_n <= 1 or not _resize_enabled() or _spans_processes(old):
            raise exc
        row_idx = [
            i for i in range(len(arrays)) if i not in replicated_args
        ]
        n_pad = int(arrays[row_idx[0]].shape[0]) if row_idx else 1
        new_n = _shrunk_axis_size(old_n - 1, n_pad)
        fault_point("mesh.resize")
        # survivors = the leading new_n devices of the old mesh along the
        # data axis (faked CPU devices are interchangeable; on real
        # hardware the runtime only names the dead chip after reinit, so
        # the conservative shrink drops the tail of the axis)
        ax = old.axis_names.index(axis_name)
        take = [slice(None)] * old.devices.ndim
        take[ax] = slice(0, new_n)
        new_mesh = Mesh(old.devices[tuple(take)], old.axis_names)
        state["mesh"] = new_mesh
        state["resized"] = True
        try:
            from sntc_tpu.obs.metrics import inc

            inc("sntc_collective_resizes_total")
        except Exception:
            pass
        record_mesh_shape(new_mesh)
        dom = get_collective_domain()
        if dom is not None:
            dom.note_mesh_resize(
                old=old_n, new=new_n, axis=axis_name,
                site="collective.dispatch",
            )
        else:
            from sntc_tpu.resilience import emit_event

            emit_event(
                event="mesh_resize", component="model",
                site="collective.dispatch", axis=axis_name,
                old=old_n, new=new_n,
            )
        return _place_on(new_mesh, arrays)

    def _split(arrays: tuple, depth: int, exc: BaseException):
        """The ``device_oom`` responder: split the padded batch into two
        shard-aligned row halves and SUM their partial trees (valid by
        the additivity contract).  Shard-aligned means each half's row
        count stays divisible by the live shard count, so both halves
        dispatch through the same per-mesh program family."""
        m = state["mesh"]
        n_shards = int(m.shape[axis_name])
        row_idx = [
            i for i in range(len(arrays)) if i not in replicated_args
        ]
        if not row_idx or depth >= _oom_depth_limit():
            raise exc
        n_pad = int(arrays[row_idx[0]].shape[0])
        if n_pad < 2 * n_shards:
            raise exc  # already at one row-block per shard
        cut = ((n_pad // 2 + n_shards - 1) // n_shards) * n_shards
        host = {i: np.asarray(arrays[i]) for i in row_idx}
        halves = []
        for sl in (slice(0, cut), slice(cut, n_pad)):
            part = list(arrays)
            for i in row_idx:
                a = host[i][sl]
                part[i] = _put_sharded(
                    a, NamedSharding(m, _row_spec(a))
                )
            halves.append(tuple(part))
        dom = get_collective_domain()
        if dom is not None:
            dom.note_oom_split(
                rows=n_pad, depth=depth + 1, bucket_floor=n_shards
            )
        out = _run(halves[0], depth + 1)
        out2 = _run(halves[1], depth + 1)
        return jax.tree.map(lambda a, b: a + b, out, out2)

    def _run(arrays: tuple, depth: int = 0):
        from sntc_tpu.resilience.device import classify_device_error

        m = state["mesh"]
        arrays = _ensure_on(m, arrays)
        try:
            fault_point("collective.dispatch")
            out = _program(m)(*arrays)
        except Exception as e:  # noqa: BLE001 — classified below
            kind = classify_device_error(e) if m is not None else None
            if kind == "device_lost":
                return _run(_resize(e, arrays), depth)
            if kind == "device_oom":
                return _split(arrays, depth, e)
            raise
        # mesh=None is the unit-test stub shape (jit monkeypatched out);
        # a real dispatch always has a mesh
        n_shards = int(m.shape[axis_name]) if m is not None else 1
        record_collective(op, axis_name, n_shards, payload_nbytes(out))
        return out

    # resolved ONCE at build time: dispatch runs per optimizer iteration
    # and per streaming batch — thousands of calls per fit must not each
    # re-parse the env and rebuild a policy
    policy = _dispatch_policy()
    breaker = _dispatch_breaker()

    def dispatch(*arrays):
        # the fault/retry/breaker hooks live OUTSIDE the jit so they run
        # per call (inside the trace they would fire once, at compile time)
        def attempt():
            return _run(tuple(arrays))

        if breaker is not None and not breaker.allow():
            raise CircuitOpenError(
                "collective.dispatch", breaker.retry_after_s()
            )
        try:
            if policy is None:
                out = attempt()
            else:
                out = with_retries(
                    attempt, policy, site="collective.dispatch"
                )
        except Exception:
            # KeyboardInterrupt/SystemExit pass through uncounted — a
            # user interrupt is not evidence the backend is down
            if breaker is not None:
                breaker.record_failure()
            raise
        if breaker is not None:
            breaker.record_success()
        return out

    dispatch.mesh = lambda: state["mesh"]  # type: ignore[attr-defined]
    return dispatch


def tree_aggregate(fn: Callable, mesh: Mesh, *arrays, axis_name: str = DATA_AXIS):
    """One-shot convenience over :func:`make_tree_aggregate` (recompiles per
    call site — estimators with iteration loops should build once)."""
    return make_tree_aggregate(fn, mesh, axis_name)(*arrays)
