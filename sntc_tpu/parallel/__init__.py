from sntc_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    default_mesh,
    make_mesh,
    replicated_sharding,
)
from sntc_tpu.parallel.collectives import (
    make_tree_aggregate,
    pad_rows,
    shard_batch,
    shard_weights,
    tree_aggregate,
)
from sntc_tpu.parallel.distributed import global_mesh, initialize, process_info

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "default_mesh",
    "make_mesh",
    "data_sharding",
    "replicated_sharding",
    "pad_rows",
    "shard_batch",
    "shard_weights",
    "tree_aggregate",
    "make_tree_aggregate",
    "initialize",
    "global_mesh",
    "process_info",
]
