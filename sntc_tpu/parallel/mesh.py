"""The mesh substrate — ONE parallelism API for the whole framework.

Replaces Spark's cluster-manager / executor layer (SURVEY.md §1 L8):
instead of ``spark-submit --master local[*]`` placing tasks on executor
JVMs, we build a ``jax.sharding.Mesh`` over the TPU chips of one ICI
domain (v5e-8 target) and run every estimator SPMD over it.  Everything
that shards, maps, or reduces in this codebase goes through this module
(r22): the DrJAX-style primitives :func:`map_at` / :func:`reduce_at` /
:func:`map_reduce_at` express per-shard computation + named-axis
reduction, so sharding is a *deployment decision* (which mesh you pass)
rather than a code path — the five collective call sites
(``parallel/collectives.py``, ``models/kmeans.py``, ``models/lda.py``,
``models/pic.py``, ``models/tree/grower.py``) are all written against
these primitives and never touch ``shard_map``/``pmap`` directly.

Axis names are DECLARED in :data:`MESH_AXES` — the registry is the
single source of truth that ``scripts/check_mesh_axes.py`` drift-checks
against every ``PartitionSpec`` literal in the package and the axis
table in docs/PERFORMANCE.md, both directions.

Mesh construction covers three deployment shapes:

* :func:`default_mesh` — 1-D ``("data",)`` over the visible devices of
  one process (the common case, and the serve plane's shape);
* :func:`make_mesh` — 2-D ``("data", "model")`` within one process;
* :func:`hybrid_mesh` — the multi-host path: DCN-connected processes
  stack along the outer (data) axis, ICI neighbors fill within a host
  (the ``mesh_utils.create_hybrid_device_mesh`` idiom, SNIPPETS.md
  [1]–[3]).

Dev/test: ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` gives
8 fake CPU devices — the ``local[2]``/``local-cluster`` analog
(SURVEY.md §4.1); tier-1 runs the whole sharded plane over them.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sntc_tpu.parallel.compat import _CHECK_KW, _shard_map

#: Axis-name registry — every mesh axis the framework may declare, with
#: its role.  ``scripts/check_mesh_axes.py`` enforces that every
#: ``PartitionSpec``/``psum`` axis literal in ``sntc_tpu/`` names a key
#: here, and that the docs/PERFORMANCE.md axis table mirrors this dict
#: exactly (both directions).
MESH_AXES = {
    "data": (
        "batch rows — the RDD-partition analog; batches shard over it, "
        "reductions psum over it (SURVEY.md §5.8)"
    ),
    "model": (
        "parameter shards for wide layers — absent upstream (SURVEY.md "
        "§2.5) but plumbed for the multichip dryrun and future growth"
    ),
}

DATA_AXIS = "data"
MODEL_AXIS = "model"


def default_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over (the first ``n_devices``) available devices, axis "data"."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (DATA_AXIS,))


def make_mesh(
    data: int = -1,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """2-D ``(data, model)`` mesh.  ``data=-1`` means "all remaining devices".

    ``model`` should divide the device count; collectives for gradients ride
    the ``data`` axis, parameter shards the ``model`` axis.
    """
    devs = list(jax.devices() if devices is None else devices)
    if data == -1:
        if len(devs) % model:
            raise ValueError(f"{len(devs)} devices not divisible by model={model}")
        data = len(devs) // model
    devs = devs[: data * model]
    if len(devs) != data * model:
        raise ValueError(
            f"need {data * model} devices for mesh ({data},{model}), "
            f"have {len(devs)}"
        )
    arr = np.array(devs).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def hybrid_mesh(data: int = -1, model: int = 1) -> Mesh:
    """Multi-host ``(data, model)`` mesh: processes stack along the outer
    (data) axis over DCN, ICI neighbors fill within each host — the
    ``mesh_utils.create_hybrid_device_mesh`` construction (SNIPPETS.md
    [1]–[3]), which keeps the model axis inside one ICI domain so
    parameter-shard collectives never cross the slow DCN links.

    Single-process (including the faked-device CPU host) degrades to
    :func:`make_mesh` — the hybrid path needs per-granule device groups
    that only exist with ``jax.distributed`` initialized.
    """
    if jax.process_count() == 1:
        return make_mesh(data=data, model=model)
    n = jax.device_count()
    if data == -1:
        if n % model:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    procs = jax.process_count()
    if data % procs:
        raise ValueError(
            f"data={data} not divisible by process count {procs} — the "
            "hybrid mesh stacks whole processes along the data axis"
        )
    devs = jax.devices()
    slices = {getattr(d, "slice_index", None) for d in devs}
    if len(slices) > 1 and None not in slices:
        from jax.experimental import mesh_utils

        devices = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(data // procs, model),
            dcn_mesh_shape=(procs, 1),
        )
        return Mesh(devices, (DATA_AXIS, MODEL_AXIS))
    # no slice structure (faked CPU multi-process, single-slice pods):
    # jax.devices() order is globally consistent and groups each host's
    # devices contiguously, so a plain reshape already yields the
    # ICI-inner / DCN-outer hierarchy the hybrid construction builds
    return Mesh(
        np.array(devs[: data * model]).reshape(data, model),
        (DATA_AXIS, MODEL_AXIS),
    )


def data_sharding(mesh: Mesh, rank: int = 1) -> NamedSharding:
    """Shard the leading (row) axis over "data"; replicate trailing axes."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (rank - 1))))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# SPMD primitives — the DrJAX shape: computation is expressed as a *map*
# over a named mesh axis plus a *reduce* over that axis, with the axis
# name declared at the call site.  ``shard_map`` is the lowering detail,
# confined to this module (acceptance: no direct shard_map/pmap call
# sites outside parallel/mesh.py).
# ---------------------------------------------------------------------------


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the modern signature on any supported jax.

    On legacy jax the replication check is DISABLED outright: the old
    ``check_rep`` machinery has no rule for ``while`` (every
    ``lax.while_loop``/``scan`` body trips ``NotImplementedError``), and
    the check is advisory — out-spec correctness here is guaranteed by
    the psum-before-return convention of every call site, which the
    modern ``check_vma`` validates where available."""
    check = check_vma if _CHECK_KW == "check_vma" else False
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_CHECK_KW: check},
    )


def map_at(
    mesh: Mesh,
    fn: Callable,
    *,
    in_specs,
    out_specs,
    check_vma: bool = True,
    jit: bool = True,
):
    """DrJAX-style *map* primitive: run ``fn`` SPMD over ``mesh`` with the
    given placement specs.  ``fn`` sees per-shard blocks (leading axis =
    local rows for a ``P("data", ...)`` spec) and may call
    :func:`reduce_at` / ``jax.lax.psum`` over any declared mesh axis.

    ``jit=True`` wraps the mapped program in ``jax.jit`` — build ONCE and
    dispatch many (every estimator fit loop); ``jit=False`` returns the
    bare mapped callable for call sites already inside a traced context
    or that rebuild per call (the tree grower's per-level histogram).
    """
    mapped = shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=check_vma,
    )
    return jax.jit(mapped) if jit else mapped


def reduce_at(tree, axis_name: str = DATA_AXIS):
    """DrJAX-style *reduce* primitive: sum every leaf of ``tree`` across
    the named mesh axis.  Valid only inside a :func:`map_at` body (the
    axis must be bound)."""
    return jax.tree.map(lambda t: jax.lax.psum(t, axis_name), tree)


def map_reduce_at(
    mesh: Mesh,
    fn: Callable,
    *,
    axis_name: str = DATA_AXIS,
    in_specs,
    out_specs=P(),
    check_vma: bool = True,
    jit: bool = False,
):
    """``map_at`` + ``reduce_at`` fused: apply ``fn`` per shard and psum
    every output leaf over ``axis_name``; the result is replicated (the
    driver-side combOp result, living on-device).  The building block
    under ``collectives.make_tree_aggregate``."""

    def local(*shards):
        return reduce_at(fn(*shards), axis_name)

    return map_at(
        mesh, local, in_specs=in_specs, out_specs=out_specs,
        check_vma=check_vma, jit=jit,
    )


def sharded_jit(
    fun: Callable,
    in_shardings=None,
    out_shardings=None,
    **jit_kwargs,
):
    """Partitioned ``jit`` with the t5x-style fallback (SNIPPETS.md [1]):
    on a single-device backend the sharding annotations are dropped and
    ``fun`` is plain-jitted — annotations over a 1-device "mesh" only
    add partitioner overhead.  With >1 device (real TPUs or faked CPU
    devices) the annotations are honored."""
    if jax.device_count() == 1:
        return jax.jit(fun, **jit_kwargs)
    return jax.jit(
        fun, in_shardings=in_shardings, out_shardings=out_shardings,
        **jit_kwargs,
    )


# ---------------------------------------------------------------------------
# evidence plane — every collective dispatch records how often and how
# many bytes crossed the mesh, per (op, axis), extending the
# sntc_transfer_* discipline to the collective layer (SparCML makes
# bytes-moved the quantity compressed reductions must beat; these
# counters are the baseline they will be measured against).
# ---------------------------------------------------------------------------


def collective_wire_bytes(n_shards: int, payload_bytes: int) -> int:
    """Ring all-reduce cost model: reducing a replicated payload of
    ``payload_bytes`` across ``n_shards`` devices moves
    ``2*(n-1)/n * payload`` per device — ``2*(n-1) * payload / n * n``
    total on the wire.  One device moves nothing.  Loop-carried psums
    (a whole Lloyd/IRLS loop inside one program) count ONCE per
    dispatch — the series is a documented lower bound, not a trace."""
    if n_shards <= 1:
        return 0
    return 2 * (n_shards - 1) * int(payload_bytes)


def record_collective(
    op: str, axis_name: str, n_shards: int, payload_bytes: int
) -> None:
    """Host-side evidence for one collective dispatch (never inside a
    trace — these are python counters)."""
    try:
        from sntc_tpu.obs.metrics import inc

        inc("sntc_collective_dispatches_total", op=op, axis=axis_name)
        wire = collective_wire_bytes(n_shards, payload_bytes)
        if wire:
            inc(
                "sntc_collective_bytes_moved_total", wire,
                op=op, axis=axis_name,
            )
    except Exception:
        pass


def record_mesh_shape(mesh: Mesh) -> None:
    """Mirror the mesh shape into the per-axis device gauge."""
    try:
        from sntc_tpu.obs.metrics import set_gauge

        for axis_name, size in dict(mesh.shape).items():
            set_gauge(
                "sntc_collective_mesh_devices", size, axis=axis_name
            )
    except Exception:
        pass


def payload_nbytes(tree) -> int:
    """Total bytes of every leaf in ``tree`` — the reduced-payload size
    fed to :func:`collective_wire_bytes` (callers pass only the
    REPLICATED outputs; shard-local outputs never cross the mesh)."""
    return int(
        sum(getattr(t, "nbytes", 0) for t in jax.tree.leaves(tree))
    )
