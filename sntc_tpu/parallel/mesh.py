"""Device mesh construction — the executor-topology analog.

Replaces Spark's cluster-manager / executor layer (SURVEY.md §1 L8): instead
of ``spark-submit --master local[*]`` placing tasks on executor JVMs, we build
a ``jax.sharding.Mesh`` over the TPU chips of one ICI domain (v5e-8 target)
and run every estimator SPMD over it.  The leading mesh axis ``"data"`` is the
RDD-partition analog: batches shard over it, reductions ``psum`` over it
(SURVEY.md §5.8).  A second ``"model"`` axis is available for wide layers
(unused by the CICIDS2017 models, which are small — SURVEY.md §2.5 marks TP as
absent upstream — but the mesh plumbing supports it for the multichip dryrun
and future growth).

Dev/test: ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` gives 8 fake
CPU devices — the ``local[2]``/``local-cluster`` analog (SURVEY.md §4.1).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def default_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over (the first ``n_devices``) available devices, axis "data"."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (DATA_AXIS,))


def make_mesh(
    data: int = -1,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """2-D ``(data, model)`` mesh.  ``data=-1`` means "all remaining devices".

    ``model`` should divide the device count; collectives for gradients ride
    the ``data`` axis, parameter shards the ``model`` axis.
    """
    devs = list(jax.devices() if devices is None else devices)
    if data == -1:
        if len(devs) % model:
            raise ValueError(f"{len(devs)} devices not divisible by model={model}")
        data = len(devs) // model
    devs = devs[: data * model]
    if len(devs) != data * model:
        raise ValueError(
            f"need {data * model} devices for mesh ({data},{model}), "
            f"have {len(devs)}"
        )
    arr = np.array(devs).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh, rank: int = 1) -> NamedSharding:
    """Shard the leading (row) axis over "data"; replicate trailing axes."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (rank - 1))))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
