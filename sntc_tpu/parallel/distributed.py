"""Multi-host initialization — the driver/executor cluster analog.

Behavioral spec: SURVEY.md §5.8: Spark's comm backend is netty RPC between
the driver and executor JVMs; the TPU-native equivalent of "adding hosts"
is ``jax.distributed`` — each host runs the SAME SPMD program, XLA routes
gradient/histogram reductions over ICI within a slice and DCN across
slices.  No framework code changes: the mesh just gets bigger, and the
``"data"`` axis keeps carrying the treeAggregate-analog psums.

Single-host (the v5e-8 v0 target, one ICI domain) needs none of this —
``initialize()`` is a no-op unless multi-host env/args are present.

Usage on each host of a pod slice:

    from sntc_tpu.parallel.distributed import initialize, global_mesh
    initialize()                      # env-driven (TPU pods auto-detect)
    mesh = global_mesh()              # 1-D "data" mesh over ALL devices
    ... estimators take mesh= as usual ...
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

from sntc_tpu.parallel.mesh import default_mesh, hybrid_mesh

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host job.  With no arguments, relies on
    ``jax.distributed``'s environment auto-detection (TPU pod runtimes set
    it); returns False (no-op) when nothing indicates a multi-host setup.
    """
    global _initialized
    if _initialized:
        return True
    if coordinator_address is None and num_processes is None:
        import os

        multi_host_markers = (
            "JAX_COORDINATOR_ADDRESS",
            "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS",
        )
        if not any(os.environ.get(m) for m in multi_host_markers):
            return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def global_mesh(model: int = 1) -> Mesh:
    """Mesh over ALL devices of the job (local or multi-host).

    Multi-process jobs route through
    :func:`~sntc_tpu.parallel.mesh.hybrid_mesh` — processes stack along
    the outer data axis over DCN, ICI neighbors fill within each host
    (``create_hybrid_device_mesh``), so data-parallel psum segments
    reduce over ICI first, then cross-host DCN — the hierarchy
    SURVEY.md §5.8 prescribes.  Single-process jobs with ``model == 1``
    keep the plain 1-D ``("data",)`` mesh.
    """
    if model == 1:
        return default_mesh()
    return hybrid_mesh(model=model)


def process_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
