"""Process-wide default mesh — the SparkContext analog.

Estimators run SPMD over a mesh; users can pass one explicitly (the
``mesh=`` constructor argument every estimator takes) or rely on this
process-wide default, built lazily over all visible devices — like an app
inheriting the active ``SparkContext`` (SURVEY.md §1 L8).
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

from sntc_tpu.parallel.mesh import default_mesh

_default: Optional[Mesh] = None


def get_default_mesh() -> Mesh:
    global _default
    if _default is None:
        _default = default_mesh()
    return _default


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default
    _default = mesh


# ---------------------------------------------------------------------------
# serve-plane mesh (r22) — the fused serve programs / ServeDaemon shared
# predictors shard dispatched batch rows over this mesh when it is set.
# Separate from the fit-side default on purpose: a fit may want all 8
# devices while serving pins 2, and the serve mesh defaults OFF
# (single-device dispatch, the pre-r22 behavior).
# ---------------------------------------------------------------------------

_serve_mesh: Optional[Mesh] = None
_serve_set = False
_env_serve_meshes: dict = {}


def get_serve_mesh() -> Optional[Mesh]:
    """The mesh the serve plane shards fused dispatches over, or None
    (single-device programs).  Armed programmatically via
    :func:`set_serve_mesh` or by ``SNTC_SERVE_MESH_DEVICES=N`` (N>1)."""
    if _serve_set:
        return _serve_mesh
    import os

    try:
        n = int(os.environ.get("SNTC_SERVE_MESH_DEVICES", "0") or 0)
    except ValueError:
        return None
    if n <= 1:
        return None
    mesh = _env_serve_meshes.get(n)
    if mesh is None:
        mesh = default_mesh(n)
        _env_serve_meshes[n] = mesh
    return mesh


def set_serve_mesh(mesh: Optional[Mesh]) -> None:
    """Pin (or clear with ``None`` — which also stops the env knob from
    applying until the next :func:`reset_serve_mesh`) the serve mesh."""
    global _serve_mesh, _serve_set
    _serve_mesh = mesh
    _serve_set = True


def reset_serve_mesh() -> None:
    """Return serve-mesh resolution to the env knob (test hygiene)."""
    global _serve_mesh, _serve_set
    _serve_mesh = None
    _serve_set = False
