"""Process-wide default mesh — the SparkContext analog.

Estimators run SPMD over a mesh; users can pass one explicitly (the
``mesh=`` constructor argument every estimator takes) or rely on this
process-wide default, built lazily over all visible devices — like an app
inheriting the active ``SparkContext`` (SURVEY.md §1 L8).
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

from sntc_tpu.parallel.mesh import default_mesh

_default: Optional[Mesh] = None


def get_default_mesh() -> Mesh:
    global _default
    if _default is None:
        _default = default_mesh()
    return _default


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default
    _default = mesh
