"""Declarative ingest source graph + the zero-copy columnar plane.

Host-side ingest — every path into the device — is modeled as ONE
operator graph, **read → parse → admit → bucket → stage** (the tf.data
structure, arxiv 2101.12127), instead of ad-hoc thread pools sized by
static flags:

========  ==============================================================
stage     what it is in this codebase
========  ==============================================================
read      the engine-observed ``get_batch`` wait (staged hit ≈ 0; a
          miss pays the synchronous parse inline)
parse     one source file decoded to a Frame (CSV via pyarrow, pcap /
          NetFlow via the native parsers) — runs on the source's
          ``read_workers`` pool for multi-file batches
admit     schema-contract row admission on the read batch
bucket    shape-bucket padding + device dispatch of the admitted batch
stage     a background prefetch of an upcoming range (the bounded
          staging queue ``prefetch_batches`` deep — queue AND pool)
========  ==============================================================

Each stage carries a :class:`StageMeter` (EWMA latency, busy time,
counts → the ``sntc_ingest_stage_seconds`` histogram), and the graph's
three pool/queue knobs are first-class :class:`Knob` objects —
``read_workers``, ``prefetch_batches``, ``pipeline_depth`` — resolvable
live on a running engine (:func:`graph_knobs`) so the feedback
autotuner (:mod:`sntc_tpu.data.autotune`) can resize them from the
observed latency/backpressure profile instead of a human guessing
``--prefetch-batches``.  :func:`describe_graph` renders the declarative
structure (stages, queues, pools, meters) for status dumps and the
bench journal.

The second half is the **zero-copy columnar plane**:
:func:`read_flows_columnar` / :func:`load_flows_columnar` cast every
feature column to float32 ONCE inside Arrow at parse time (pyarrow
compute kernels, no per-column numpy ``astype(copy=True)``), apply the
NaN/Inf validity policy as ONE Arrow mask pass, and hand the engine
numpy VIEWS over the Arrow buffers — already in exactly the dtype the
fusion planner's ``f32cast`` upload policy wants, so nothing copies on
the host between parse and the single ``device_put``.  Bitwise equal
to the legacy ``load_csv`` → ``clean_flows`` path (pinned in
``tests/test_ingest_pipeline.py``).
"""

from __future__ import annotations

import glob
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from sntc_tpu.core.frame import Frame
from sntc_tpu.data.schema import LABEL_COLUMN, normalize_label
from sntc_tpu.obs.metrics import observe

#: the operator graph, in data-flow order (module docstring has the
#: mapping onto the live source/engine machinery)
STAGES = ("read", "parse", "admit", "bucket", "stage")

#: the graph's tunable pool/queue knobs — the autotuner's action space,
#: the serve CLI's flag surface, and the ``sntc_ingest_knob_value``
#: gauge's ``knob`` label values (scripts/check_ingest_flags.py pins
#: all three in tier-1)
KNOB_NAMES = ("read_workers", "prefetch_batches", "pipeline_depth")


class StageMeter:
    """Latency/occupancy accounting for one named ingest stage.

    ``record`` is the hot-path write: one EWMA update + one cataloged
    histogram observe per ITEM (a file parse, a batch read) — never per
    row.  ``tenant`` labels the emitted series when the owning source /
    engine serves a tenant (set post-construction by the engine for
    sources built without one)."""

    __slots__ = ("stage", "tenant", "count", "busy_s", "last_s",
                 "ewma_s", "_lock")

    #: EWMA smoothing: ~10-item memory, fast enough to follow a phase
    #: change within one autotune window, slow enough to ignore one
    #: outlier file
    ALPHA = 0.2

    def __init__(self, stage: str, tenant: Optional[str] = None):
        self.stage = stage
        self.tenant = tenant
        self.count = 0
        self.busy_s = 0.0
        self.last_s = 0.0
        self.ewma_s = 0.0
        self._lock = threading.Lock()

    def record(self, elapsed_s: float) -> None:
        with self._lock:
            self.count += 1
            self.busy_s += elapsed_s
            self.last_s = elapsed_s
            self.ewma_s = (
                elapsed_s if self.count == 1
                else self.ALPHA * elapsed_s + (1 - self.ALPHA) * self.ewma_s
            )
        labels = {} if self.tenant is None else {"tenant": self.tenant}
        observe(
            "sntc_ingest_stage_seconds", elapsed_s,
            stage=self.stage, **labels,
        )

    def snapshot(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "busy_s": round(self.busy_s, 6),
            "last_s": round(self.last_s, 6),
            "ewma_s": round(self.ewma_s, 6),
        }


def source_meters(tenant: Optional[str] = None) -> Dict[str, StageMeter]:
    """The source-side meters (read/parse/stage) every
    ``DirStreamSource`` carries."""
    return {s: StageMeter(s, tenant) for s in ("read", "parse", "stage")}


def engine_meters(tenant: Optional[str] = None) -> Dict[str, StageMeter]:
    """The engine-side meters (admit/bucket) every ``StreamingQuery``
    carries."""
    return {s: StageMeter(s, tenant) for s in ("admit", "bucket")}


@dataclass
class Knob:
    """One live pool/queue size: current value via ``get``, resized via
    ``set`` (thread-safe on the owner's side), bounded to ``[lo, hi]``.
    The autotuner only ever moves a knob by ``step`` per decision."""

    name: str
    get: Callable[[], int]
    set: Callable[[int], None]
    lo: int
    hi: int
    step: int = 1

    def clamp(self, value: int) -> int:
        return max(self.lo, min(self.hi, int(value)))


#: default knob bounds — floors keep every pool alive, ceilings keep a
#: runaway signal from allocating unbounded threads/queues; the
#: autotuner (or a daemon budget) can narrow but never widen these
DEFAULT_BOUNDS = {
    "read_workers": (1, max(4, (os.cpu_count() or 4))),
    "prefetch_batches": (1, 8),
    "pipeline_depth": (1, 4),
}


def graph_knobs(engine, bounds: Optional[dict] = None) -> Dict[str, Knob]:
    """Resolve the graph's knobs on a LIVE engine + source: only knobs
    the owner actually exposes (``set_read_workers`` /
    ``set_prefetch_batches`` on the source, ``pipeline_depth`` on the
    engine) are returned, so a MemorySource-backed engine simply has a
    smaller action space."""
    bounds = dict(DEFAULT_BOUNDS, **(bounds or {}))
    knobs: Dict[str, Knob] = {}
    source = engine.source
    if hasattr(source, "set_read_workers"):
        lo, hi = bounds["read_workers"]
        knobs["read_workers"] = Knob(
            "read_workers",
            lambda: source.read_workers,
            source.set_read_workers, lo, hi,
        )
    if hasattr(source, "set_prefetch_batches"):
        lo, hi = bounds["prefetch_batches"]
        knobs["prefetch_batches"] = Knob(
            "prefetch_batches",
            lambda: source.prefetch_batches,
            source.set_prefetch_batches, lo, hi,
        )
    if hasattr(engine, "pipeline_depth"):
        lo, hi = bounds["pipeline_depth"]

        def _set_depth(n: int, _e=engine) -> None:
            _e.pipeline_depth = max(1, int(n))

        knobs["pipeline_depth"] = Knob(
            "pipeline_depth",
            lambda: engine.pipeline_depth,
            _set_depth, lo, hi,
        )
    return knobs


def describe_graph(engine) -> Dict[str, dict]:
    """The declarative structure of a live engine's source graph:
    stage → {queue bound, pool width, meter snapshot}.  Pure read —
    status dumps and the bench journal call this per snapshot."""
    source = engine.source
    src_meters = getattr(source, "meters", {})
    eng_meters = getattr(engine, "ingest_meters", {})
    staged = len(getattr(source, "_staged", ()) or ())
    desc: Dict[str, dict] = {}
    for stage in STAGES:
        meter = src_meters.get(stage) or eng_meters.get(stage)
        row: Dict[str, object] = {
            "meter": meter.snapshot() if meter is not None else None,
        }
        if stage == "parse":
            row["workers"] = getattr(source, "read_workers", None)
        elif stage == "stage":
            row["queue_bound"] = getattr(source, "prefetch_batches", None)
            row["queue_depth"] = staged
        elif stage == "read":
            stats = getattr(source, "prefetch_stats", None)
            row["prefetch"] = stats() if stats is not None else None
        elif stage == "bucket":
            row["queue_bound"] = getattr(engine, "pipeline_depth", None)
            in_flight = getattr(engine, "in_flight_count", None)
            row["queue_depth"] = (
                in_flight() if in_flight is not None else None
            )
        desc[stage] = row
    return desc


# ---------------------------------------------------------------------------
# the zero-copy columnar plane
# ---------------------------------------------------------------------------


def _columnar_table(
    table: pa.Table, label_col: str, handle_invalid: Optional[str]
):
    """One in-Arrow pass over a parsed flow table: cast every feature
    column to float32 (pyarrow compute — no numpy intermediates), build
    the combined finite-AND-valid row mask, and apply the NaN/Inf
    policy (``drop`` filters once, ``zero`` fills per cell, ``None``
    keeps every row for a downstream admission layer).  Returns
    ``(feature_arrays, feature_names, label_array_or_None)``."""
    feature_names = [c for c in table.column_names if c != label_col]
    f32 = pa.float32()
    arrays: List[pa.Array] = []
    finite_masks: List[pa.Array] = []
    for name in feature_names:
        col = table[name]
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        # THE cast: one Arrow kernel per column at parse time, in place
        # of the legacy per-column astype(float32, copy=True) host pass
        col = pc.cast(col, f32, safe=False)
        arrays.append(col)
        if handle_invalid is not None:
            # a parse-time null (empty / "NaN" cell) is as non-finite
            # as an Infinity — coalesce folds both into one mask
            finite_masks.append(
                pc.coalesce(pc.is_finite(col), pa.scalar(False))
            )
    label = table[label_col] if label_col in table.column_names else None
    if handle_invalid == "zero":
        zero = pa.scalar(0.0, f32)
        arrays = [
            pc.if_else(mask, col, zero)
            for col, mask in zip(arrays, finite_masks)
        ]
    elif handle_invalid == "drop" and finite_masks:
        valid = finite_masks[0]
        for mask in finite_masks[1:]:
            valid = pc.and_(valid, mask)
        if not pc.all(valid).as_py():
            arrays = [col.filter(valid) for col in arrays]
            if label is not None:
                label = label.filter(valid)
    return arrays, feature_names, label


def _columnar_frame(arrays, feature_names, label, label_col) -> Frame:
    cols: Dict[str, np.ndarray] = {}
    for name, col in zip(feature_names, arrays):
        # zero-copy when the buffer allows it (float32, no nulls — the
        # drop/zero policies guarantee none; the serve face keeps NaN
        # VALUES, not Arrow nulls-from-parse, which fall back to one
        # materializing copy for that column only)
        try:
            cols[name] = col.to_numpy(zero_copy_only=True)
        except pa.ArrowInvalid:
            cols[name] = col.to_numpy(zero_copy_only=False)
    if label is not None:
        if isinstance(label, pa.ChunkedArray):
            label = label.combine_chunks()
        cols[label_col] = np.array(
            [normalize_label(str(v)) for v in label.to_pylist()],
            dtype=object,
        )
    return Frame(cols)


def read_flows_columnar(
    path: str,
    label_col: str = LABEL_COLUMN,
    handle_invalid: Optional[str] = "drop",
    *,
    salvage: bool = False,
    rejects: Optional[List[dict]] = None,
) -> Frame:
    """One flow CSV → a float32 columnar Frame with zero host copies
    after the in-Arrow cast (module docstring).  ``handle_invalid``:
    ``"drop"`` / ``"zero"`` replicate :func:`~sntc_tpu.data.ingest
    .clean_flows` bitwise; ``None`` keeps every row (non-finite values
    survive as float32 NaN/Inf) for the serve-time admission layer to
    police.  ``salvage``/``rejects`` forward to the parser exactly as
    in :func:`~sntc_tpu.data.ingest.load_csv`."""
    from sntc_tpu.data.ingest import load_csv_table

    if handle_invalid not in (None, "drop", "zero"):
        raise ValueError("handle_invalid must be 'drop', 'zero', or None")
    table = load_csv_table(path, salvage=salvage, rejects=rejects)
    arrays, names, label = _columnar_table(
        table, label_col, handle_invalid
    )
    return _columnar_frame(arrays, names, label, label_col)


def load_flows_columnar(
    path: str,
    pattern: str = "*.csv",
    label_col: str = LABEL_COLUMN,
    handle_invalid: Optional[str] = "drop",
    max_workers: int = 8,
) -> Frame:
    """Directory variant of :func:`read_flows_columnar` — the batch
    train-ingest face (the ``load_csv_dir`` + ``clean_flows`` pair in
    one parse).  Files parse in the same small thread pool
    ``load_csv_dir`` uses and concatenate in sorted-filename order."""
    paths = sorted(glob.glob(os.path.join(path, pattern)))
    if not paths:
        raise FileNotFoundError(f"no {pattern} files under {path}")

    def _load(p: str) -> Frame:
        return read_flows_columnar(
            p, label_col=label_col, handle_invalid=handle_invalid
        )

    if len(paths) == 1 or max_workers <= 1:
        return Frame.concat_all([_load(p) for p in paths])
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(
        max_workers=min(max_workers, len(paths))
    ) as pool:
        return Frame.concat_all(list(pool.map(_load, paths)))


def timed(meter: Optional[StageMeter], fn, *args, **kwargs):
    """Run ``fn`` recording its wall time into ``meter`` (None = run
    bare) — the one helper every instrumented stage call site shares."""
    if meter is None:
        return fn(*args, **kwargs)
    t0 = time.perf_counter()
    try:
        return fn(*args, **kwargs)
    finally:
        meter.record(time.perf_counter() - t0)
