"""Synthetic CICIDS2017-shaped data generator.

The real dataset is not in-image (SURVEY.md §7.2 item 6), so development and
benchmarking run against a schema-locked stand-in: 78 nonneg float flow
features, 15 labels with benign-heavy priors, and injected ``Infinity``/
``NaN`` values in ``Flow Bytes/s`` / ``Flow Packets/s`` to exercise the
cleaning pass (SURVEY.md §2.1).  Class-conditional structure is a lognormal
mixture with an AXIS-ALIGNED per-class signature over four salient flow
features (duration/IAT/packet-size levels — see ``_class_means``): separable
enough that a correct model reaches high macro-F1 — including depth-limited
trees, which need axis-aligned splits to show quality differences — noisy
enough that a broken one does not (the property the parity tests need).

Real CICIDS2017 CSVs drop in unchanged via ``sntc_tpu.data.ingest`` because
the column names match (``sntc_tpu/data/schema.py``).
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from sntc_tpu.core.frame import Frame
from sntc_tpu.data.schema import (
    CICIDS2017_FEATURES,
    CICIDS2017_LABELS,
    CLASS_PRIORS,
    LABEL_COLUMN,
    NUM_FEATURES,
)


# Salient axes carrying each class's signature — duration / IAT /
# packet-size levels, the columns a real CICIDS2017 attack visibly moves
# (DDoS: short IATs + long flows; PortScan: tiny packets; etc.).  All
# four are continuous, outside the int-floored set, and outside the
# dirty-injection (Inf/NaN) columns.
_CODE_FEATURES = (1, 16, 8, 12)  # Flow Duration, Flow IAT Mean,
#                                  Fwd/Bwd Packet Length Mean
_CODE_DELTA = 2.2  # per-bit log-space offset, ≈2.2σ vs unit noise —
# measured: a depth-10, 20-tree RF reads the code at macro-F1 ≈ 0.8
# (discriminative, neither saturated nor chance); depth 5 cannot exceed
# ~0.35 at ANY separation on 80%-benign 15-class data (greedy gini
# spends its budget on the large classes first), which is why the bench
# config uses depth 10


def _class_means(n_classes: int, rng: np.random.Generator) -> np.ndarray:
    """Per-class mean offsets in log-space.  Benign (class 0) is the
    origin.  Each attack class c carries (a) an AXIS-ALIGNED signature —
    bit b of c displaces code feature b by ±_CODE_DELTA — so a depth-4+
    tree can recover the class by thresholding the four code features
    one at a time (the structure a real RF exploits on flow data), and
    (b) a diffuse displacement along ~12 random other features (the
    part only a dense model like LR/MLP uses fully)."""
    means = np.zeros((n_classes, NUM_FEATURES), dtype=np.float64)
    rest = np.setdiff1d(np.arange(NUM_FEATURES), np.asarray(_CODE_FEATURES))
    for c in range(1, n_classes):
        for b, j in enumerate(_CODE_FEATURES):
            means[c, j] = _CODE_DELTA if (c >> b) & 1 else -_CODE_DELTA
        informative = rng.choice(rest, size=12, replace=False)
        means[c, informative] = rng.normal(0.0, 2.0, size=12)
    return means


def generate_frame(
    n_rows: int,
    seed: int = 0,
    n_classes: int = 15,
    dirty: bool = True,
    class_priors: Optional[List[float]] = None,
    min_class_fraction: float = 0.0005,
) -> Frame:
    """Generate a Frame with the CICIDS2017 schema (78 features + Label).

    ``dirty=True`` injects Inf/NaN into the two rate columns (0.1% of rows)
    like the real data.  ``min_class_fraction`` floors the rarest-class prior
    so small synthetic draws still contain every class (the real tail classes
    are vanishingly rare; tests need all 15 present).
    """
    if not 1 <= n_classes <= 15:
        raise ValueError("n_classes must be in [1, 15]")
    labels_vocab = CICIDS2017_LABELS[:n_classes]
    rng = np.random.default_rng(seed)

    if class_priors is None:
        priors = np.array([CLASS_PRIORS[l] for l in labels_vocab])
        priors = np.maximum(priors, min_class_fraction)
    else:
        priors = np.asarray(class_priors, dtype=np.float64)
    priors = priors / priors.sum()

    y = rng.choice(n_classes, size=n_rows, p=priors)
    means = _class_means(n_classes, np.random.default_rng(seed + 1))

    # lognormal flows: exp(class mean + noise), scaled per feature
    feature_scale = np.random.default_rng(seed + 2).uniform(
        0.5, 4.0, size=NUM_FEATURES
    )
    # pin the code features' scale so the per-bit separation is the
    # designed _CODE_DELTA·σ regardless of the random per-feature draw
    feature_scale[list(_CODE_FEATURES)] = 2.0
    log_x = means[y] + rng.normal(0.0, 1.0, size=(n_rows, NUM_FEATURES))
    x = np.exp(log_x * feature_scale * 0.5).astype(np.float32)

    # integer-ish columns (ports, counts, flags) get floored
    int_like = [0, 2, 3, 43, 44, 45, 46, 47, 48, 49, 50]
    x[:, int_like] = np.floor(x[:, int_like])

    if dirty:
        n_bad = max(1, int(n_rows * 0.001))
        bytes_col = CICIDS2017_FEATURES.index("Flow Bytes/s")
        pkts_col = CICIDS2017_FEATURES.index("Flow Packets/s")
        bad_rows = rng.choice(n_rows, size=n_bad, replace=False)
        half = n_bad // 2
        x[bad_rows[:half], bytes_col] = np.inf
        x[bad_rows[half:], pkts_col] = np.nan

    cols = {
        name: np.ascontiguousarray(x[:, j])
        for j, name in enumerate(CICIDS2017_FEATURES)
    }
    cols[LABEL_COLUMN] = np.array([labels_vocab[c] for c in y], dtype=object)
    return Frame(cols)


def _write_raw_csv(frame: Frame, path: str) -> str:
    """One CSV in the raw "MachineLearningCVE" style: erratic
    leading-space column headers, 'Fwd Header Length' duplicated (the
    ingest dedup maps the second occurrence to 'Fwd Header
    Length.1')."""
    raw_names = [
        "Fwd Header Length" if c == "Fwd Header Length.1" else c
        for c in frame.columns
    ]
    header = ",".join(
        (" " + c if i % 2 else c) for i, c in enumerate(raw_names)
    )
    with open(path, "w") as f:
        f.write(header + "\n")
        cols = [frame[c] for c in frame.columns]
        for i in range(frame.num_rows):
            f.write(
                ",".join(
                    str(col[i]) if col.dtype == object else repr(float(col[i]))
                    for col in cols
                )
                + "\n"
            )
    return path


def write_day_csvs(
    out_dir: str,
    n_rows_per_day: int = 1000,
    n_days: int = 8,
    seed: int = 0,
) -> List[str]:
    """Emulate the 8 "MachineLearningCVE" day files as CSVs on disk, with the
    raw files' erratic leading-space column headers, for ingest tests."""
    os.makedirs(out_dir, exist_ok=True)
    return [
        _write_raw_csv(
            generate_frame(n_rows_per_day, seed=seed + day),
            os.path.join(out_dir, f"day{day}.csv"),
        )
        for day in range(n_days)
    ]


def write_capture_stream(
    out_dir: str,
    n_files: int = 6,
    flows_per_file: int = 3,
    packets_per_flow: int = 6,
    seed: int = 0,
    format: str = "pcap",
    file_gap_s: float = 1.0,
    span_files: bool = True,
    defer_fraction: float = 0.0,
    flush: bool = True,
    flush_advance_s: float = 1e6,
    start_ts: float = 1_700_000_000.0,
) -> dict:
    """Synthetic raw-capture micro-batch stream with known ground-truth
    flows — the drift-fixture discipline applied to capture bytes.

    Writes ``n_files`` capture files (``capture_NNNN.pcap`` or
    ``.nf5``) under ``out_dir``; dropped under a ``serve
    --from-capture`` watch directory each file is one engine
    micro-batch.  File ``i`` starts ``flows_per_file`` new
    deterministic bidirectional TCP flows inside its
    ``[start_ts + i*file_gap_s, +file_gap_s)`` time slot; with
    ``span_files`` every odd flow carries half its packets into the
    NEXT file (windows genuinely cross micro-batch boundaries — what
    the kill-mid-window chaos needs).  ``defer_fraction`` additionally
    moves that fraction of each file's packets into the FOLLOWING
    file's byte stream without changing their timestamps — real
    out-of-order arrival whose fate (accepted out-of-order vs dropped
    ``late_record``) the consumer's lateness bound decides.
    ``flush=True`` appends one terminal file holding a single
    far-future sentinel packet (reserved UDP 5-tuple,
    ``flush_advance_s`` past the last real packet) that drives the
    watermark past every real window, so a full replay emits ALL
    ground-truth flows; the sentinel itself stays in state and never
    emits.

    Returns ``{"files", "packets"/"records", "n_flows",
    "flush_file"}`` where ``packets`` (pcap) is the full ground-truth
    packet matrix in timestamp order — feed it to
    ``packets_to_flow_frame`` for the reference feature rows —
    and ``records`` (netflow) is the ground-truth NF5 record matrix.
    """
    from sntc_tpu.native import make_datagram, make_packet, make_pcap

    if format not in ("pcap", "netflow"):
        raise ValueError(
            f"unknown capture format {format!r} (pcap|netflow)"
        )
    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    # per-file event schedules: (ts, payload bytes or record tuple)
    schedules: List[list] = [[] for _ in range(n_files + 1)]
    truth_rows: List[tuple] = []
    flow_idx = 0
    for i in range(n_files):
        t0 = start_ts + i * file_gap_s
        for f in range(flows_per_file):
            src = 0x0A000000 + flow_idx
            dst = 0x0A800000 + (flow_idx % 61)
            sport = 1024 + flow_idx % 40000
            dport = 80 + (flow_idx % 5)
            spans = span_files and (flow_idx % 2 == 1) and i + 1 < n_files
            n_pkts = int(packets_per_flow)
            for j in range(n_pkts):
                # second half of a spanning flow lands in the next
                # file's time slot (the window stays OPEN across the
                # micro-batch boundary)
                in_next = spans and j >= n_pkts // 2
                base = t0 + file_gap_s if in_next else t0
                frac = (f * n_pkts + j) / max(
                    flows_per_file * n_pkts * 2, 1
                )
                ts = base + frac * file_gap_s * 0.9
                fwd = j % 2 == 0
                payload = 40 + 20 * (j % 3) + 5 * (flow_idx % 4)
                file_slot = i + 1 if in_next else i
                if format == "pcap":
                    pkt = make_packet(
                        src if fwd else dst, dst if fwd else src,
                        sport if fwd else dport,
                        dport if fwd else sport,
                        proto=6, payload=payload,
                        flags=0x18 if j else 0x02,
                        window=4096 + 64 * (flow_idx % 8),
                    )
                    schedules[file_slot].append((ts, pkt))
                else:
                    first_ms = int((ts - start_ts) * 1000) + 3_600_000
                    rec = (
                        src if fwd else dst, dst if fwd else src,
                        sport if fwd else dport,
                        dport if fwd else sport,
                        6, 0x18 if j else 0x02, 0, 1 + j % 3,
                        (1 + j % 3) * payload, first_ms,
                        first_ms + 40 + 10 * j, 1, 2, 0, 0,
                    )
                    schedules[file_slot].append((ts, rec))
                truth_rows.append(schedules[file_slot][-1])
            flow_idx += 1
    if defer_fraction > 0:
        # move a deterministic sample of each file's events into the
        # NEXT file (arrival later than newer data; timestamps keep
        # their original event time)
        for i in range(n_files - 1):
            evs = schedules[i]
            n_defer = int(len(evs) * defer_fraction)
            if not n_defer:
                continue
            pick = set(
                rng.choice(len(evs), size=n_defer, replace=False)
                .tolist()
            )
            deferred = [e for j, e in enumerate(evs) if j in pick]
            schedules[i] = [
                e for j, e in enumerate(evs) if j not in pick
            ]
            schedules[i + 1].extend(deferred)
    last_ts = max(ts for ts, _ in truth_rows)
    flush_file = None
    if flush:
        ts = last_ts + flush_advance_s
        if format == "pcap":
            sentinel = make_packet(
                0x01010101, 0x02020202, 9, 9, proto=17, payload=8
            )
            schedules[n_files].append((ts, sentinel))
        else:
            first_ms = int((ts - start_ts) * 1000) + 3_600_000
            schedules[n_files].append((ts, (
                0x01010101, 0x02020202, 9, 9, 17, 0, 0, 1, 8,
                first_ms, first_ms, 1, 2, 0, 0,
            )))
    files: List[str] = []
    ext = "pcap" if format == "pcap" else "nf5"
    for i, events in enumerate(schedules):
        if not events:
            continue
        # arrival order inside a file: schedule order (deferred events
        # trail the file's own, preserving the out-of-order shape)
        path = os.path.join(out_dir, f"capture_{i:04d}.{ext}")
        if format == "pcap":
            data = make_pcap([(ts, pkt) for ts, pkt in events])
        else:
            recs = [rec for _ts, rec in events]
            data = b"".join(
                make_datagram(recs[k:k + 30], seq=k)
                for k in range(0, len(recs), 30)
            )
        tmp = path + ".tmp"
        with open(tmp, "wb") as fobj:
            fobj.write(data)
        # atomic: a watching source never sees partials
        os.replace(tmp, path)  # storage: unbounded(synthetic dataset output)
        files.append(path)
        if flush and i == n_files:
            flush_file = path
    out = {
        "files": files,
        "n_flows": flow_idx,
        "flush_file": flush_file,
    }
    truth_rows.sort(key=lambda e: e[0])
    if format == "pcap":
        from sntc_tpu.native import parse_pcap

        # ground truth via the parser itself (exactly the field
        # extraction the consumer sees), in timestamp order
        all_pcap = make_pcap(truth_rows)
        out["packets"] = parse_pcap(all_pcap)
    else:
        # NF5_FIELD_NAMES[:15] order + the derived duration_ms column
        out["records"] = np.asarray(
            [
                list(rec) + [max(rec[10] - rec[9], 0)]
                for _ts, rec in truth_rows
            ],
            np.float64,
        )
    return out


def generate_drift_frames(
    n_batches: int,
    rows_per_batch: int = 512,
    shift_at: Optional[int] = None,
    seed: int = 0,
    n_classes: int = 8,
    shift_seed: int = 101,
    shift_priors: Optional[List[float]] = None,
) -> List[Frame]:
    """A two-day CICIDS-style micro-batch stream with a DETERMINISTIC
    distribution shift at batch ``shift_at`` (default: halfway) — the
    drift-replay fixture the lifecycle tests and bench drive.

    Phase A batches slice one day drawn with the standard benign-heavy
    priors and the ``seed`` concept (class signatures); phase B slices
    a second day with ``shift_priors`` (default: benign collapses to
    ~15% and the attack mass spreads evenly — the day-boundary mix
    shift) AND a re-drawn concept from ``shift_seed`` — so both the
    prediction mix and the class-conditional structure move, degrading
    an incumbent trained on phase A.  Slicing two per-phase frames (not
    one frame per batch) keeps each phase's concept FIXED across its
    batches, which is what makes detection latency a deterministic
    constant the tests can pin.
    """
    if shift_at is None:
        shift_at = n_batches // 2
    if not 0 < shift_at <= n_batches:
        raise ValueError("shift_at must lie in (0, n_batches]")
    if shift_priors is None:
        shift_priors = [0.15] + [0.85 / (n_classes - 1)] * (n_classes - 1)
    pre = generate_frame(
        shift_at * rows_per_batch, seed=seed, n_classes=n_classes,
        dirty=False,
    )
    frames = [
        pre.slice(i * rows_per_batch, (i + 1) * rows_per_batch)
        for i in range(shift_at)
    ]
    n_post = n_batches - shift_at
    if n_post:
        post = generate_frame(
            n_post * rows_per_batch, seed=shift_seed,
            n_classes=n_classes, dirty=False,
            class_priors=shift_priors,
        )
        frames.extend(
            post.slice(i * rows_per_batch, (i + 1) * rows_per_batch)
            for i in range(n_post)
        )
    return frames


def write_drift_stream(
    out_dir: str,
    n_batches: int,
    rows_per_batch: int = 512,
    shift_at: Optional[int] = None,
    seed: int = 0,
    n_classes: int = 8,
    shift_seed: int = 101,
    shift_priors: Optional[List[float]] = None,
    frames: Optional[List[Frame]] = None,
) -> List[str]:
    """The :func:`generate_drift_frames` fixture as one raw-header CSV
    per micro-batch (``part_NNNN.csv``) — drop it under a serve
    ``--watch`` directory and each file is one engine micro-batch.

    Pass ``frames`` to write an already-generated fixture (the bench
    scores and streams the same frames — regenerating them here would
    double the setup cost); the generation kwargs are ignored then.
    """
    os.makedirs(out_dir, exist_ok=True)
    if frames is None:
        frames = generate_drift_frames(
            n_batches, rows_per_batch, shift_at=shift_at, seed=seed,
            n_classes=n_classes, shift_seed=shift_seed,
            shift_priors=shift_priors,
        )
    return [
        _write_raw_csv(f, os.path.join(out_dir, f"part_{i:04d}.csv"))
        for i, f in enumerate(frames)
    ]
