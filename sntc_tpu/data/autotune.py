"""Feedback autotuner for the ingest source graph — tf.data AUTOTUNE
(arxiv 2101.12127), generalized to this codebase's batch train ingest,
streaming serve source, and flow-capture source.

The PR-8 telemetry plane already collects the feedback signal — the
``ingest.parse``/``stream.read`` stage latencies and the prefetch
hit/miss/high-water counters.  :class:`IngestAutotuner` closes the
loop: once per observation window (``interval_ticks`` engine rounds,
the poll-tick cadence; the ServeDaemon drives the same hook at
daemon-tick cadence) it condenses those signals into a :class:`Signal`,
diagnoses the bottleneck stage, and moves ONE knob one step —
``prefetch_batches`` when the engine waits on cold reads (staging
first, the tf.data ordering), ``read_workers`` when intra-batch parse
dominates and staging has not absorbed it, ``pipeline_depth`` when
staging is full but the engine still trails, and back DOWN when the
graph is provably idle.

**The no-oscillation guarantee** (pinned by a property test): a
proposal must repeat ``confirm`` consecutive windows before it applies;
every applied change freezes the tuner for ``cooldown`` windows; and a
knob that reverses direction more than ``max_reversals`` times is
FROZEN for the tuner's lifetime.  Total knob changes are therefore
bounded by ``Σ_knobs (max_reversals + 1) × (hi − lo) / step``
regardless of the input signal — a flapping source can waste windows,
never flap a pool size forever.

Every applied decision (and every freeze) is journaled in memory
(``stats()["decisions"]``, the bench-evidence surface), emitted as an
``autotune_decision`` structured event, and mirrored to the cataloged
``sntc_ingest_autotune_decisions_total`` counter +
``sntc_ingest_knob_value`` gauges.

:class:`TuningBudget` is the multi-tenant arbiter: one budget shared by
every tenant's tuner caps the total EXTRA pool threads / staged ranges
/ pipeline slots the fleet may grow beyond its cold defaults, so ten
tenants autotuning on one box cannot each claim the whole host.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from sntc_tpu.data.pipeline import KNOB_NAMES, Knob, graph_knobs
from sntc_tpu.obs.metrics import inc, set_gauge
from sntc_tpu.resilience import emit_event


@dataclass
class AutotunePolicy:
    """The controller's constants.  Defaults are deliberately
    conservative — two confirming windows, two cooldown windows, two
    reversals — so a production engine changes a pool size at most a
    handful of times, then sits still."""

    interval_ticks: int = 4   # engine rounds per observation window
    confirm: int = 2          # consecutive agreeing windows to apply
    cooldown: int = 2         # windows frozen after an apply
    max_reversals: int = 2    # direction flips per knob before freezing
    miss_rate_hi: float = 0.5     # cold-read fraction → widen staging
    occupancy_hi: float = 0.9     # staging full + backlog → deepen pipe
    idle_occupancy_lo: float = 0.25   # everything idle → shrink
    parse_share_hi: float = 0.5   # parse / read-wait → more workers


@dataclass
class Signal:
    """One observation window, condensed.  Pure data so tests (and the
    convergence suite) can drive :meth:`IngestAutotuner.observe`
    synthetically without a live engine."""

    backlog: int = 0          # source offsets available but unplanned
    miss_rate: float = 0.0    # prefetch misses / (hits + misses)
    queue_occupancy: float = 0.0  # staged ranges / prefetch_batches
    read_wait_s: float = 0.0  # read-stage EWMA (engine-observed wait)
    parse_s: float = 0.0      # parse-stage EWMA (per file)
    files_per_batch: int = 1  # offsets one micro-batch covers


class TuningBudget:
    """Shared cap on the EXTRA capacity autotuners may grow beyond
    their cold defaults, per knob kind.  ``try_acquire`` charges one
    increase (False = budget exhausted, the decision is journaled as
    denied and not applied); ``release`` refunds a decrease.  All
    methods are thread-safe — tenants tick on one daemon thread today,
    but the budget must not care."""

    def __init__(
        self,
        read_workers: Optional[int] = None,
        prefetch_batches: Optional[int] = None,
        pipeline_depth: Optional[int] = None,
    ):
        self._caps = {
            "read_workers": read_workers,
            "prefetch_batches": prefetch_batches,
            "pipeline_depth": pipeline_depth,
        }
        self._used = {k: 0 for k in self._caps}
        self._lock = threading.Lock()

    @classmethod
    def default_for(cls, n_tenants: int) -> "TuningBudget":
        """The daemon default: the whole fleet may grow at most one
        host's worth of parse threads, two staged ranges per tenant,
        and one extra pipeline slot per tenant."""
        import os

        return cls(
            read_workers=max(4, (os.cpu_count() or 4)),
            prefetch_batches=max(4, 2 * n_tenants),
            pipeline_depth=max(2, n_tenants),
        )

    def try_acquire(self, knob: str, n: int = 1) -> bool:
        with self._lock:
            cap = self._caps.get(knob)
            if cap is not None and self._used[knob] + n > cap:
                return False
            self._used[knob] = self._used.get(knob, 0) + n
            return True

    def release(self, knob: str, n: int = 1) -> None:
        with self._lock:
            self._used[knob] = max(0, self._used.get(knob, 0) - n)

    def snapshot(self) -> Dict[str, Dict[str, Optional[int]]]:
        with self._lock:
            return {
                k: {"cap": self._caps[k], "used": self._used[k]}
                for k in self._caps
            }


class IngestAutotuner:
    """The feedback loop (module docstring).  Attach to one engine via
    ``StreamingQuery(autotuner=...)`` — the engine calls
    :meth:`on_tick` once per round; everything else is internal.
    Tests drive :meth:`observe` directly with synthetic signals."""

    def __init__(
        self,
        policy: Optional[AutotunePolicy] = None,
        budget: Optional[TuningBudget] = None,
        tenant: Optional[str] = None,
        bounds: Optional[dict] = None,
    ):
        self.policy = policy or AutotunePolicy()
        self.budget = budget
        self.tenant = tenant
        self.bounds = bounds
        #: applied/denied/frozen journal, oldest evicted past the cap
        #: (a budget-starved tenant re-denies every few windows
        #: forever; the in-memory journal must not grow with uptime —
        #: the event stream + metrics carry the full history)
        self.decisions: List[dict] = []
        self.decisions_total = 0
        self._journal_keep = 256
        self._baseline: Dict[str, int] = {}  # knob cold-start values
        self._budget_held: Dict[str, int] = {}  # EXTRA units we charged
        self._ticks = 0
        self._windows = 0
        self._pending: Optional[Tuple[str, int]] = None
        self._streak = 0
        self._cooldown = 0
        self._last_dir: Dict[str, int] = {}
        self._reversals: Dict[str, int] = {}
        self.frozen: set = set()
        self._last_hits = 0
        self._last_misses = 0
        self._knobs: Optional[Dict[str, Knob]] = None
        self._engine = None

    # -- engine cadence ------------------------------------------------------

    def on_tick(self, engine) -> Optional[dict]:
        """One engine round: cheap counter bump until the observation
        window closes, then observe + maybe act.  Returns the applied
        decision record, if any (the engine ignores it)."""
        self._ticks += 1
        if self._ticks % max(1, self.policy.interval_ticks):
            return None
        if self._knobs is None or engine is not self._engine:
            # (re)bind to this engine's live knob surface — a tuner
            # reused across successive queries over ONE source (the
            # bench's at-saturation reps) keeps its learned source
            # knobs; only the engine-owned pipeline_depth rebinds
            self._engine = engine
            self._knobs = graph_knobs(engine, self.bounds)
        return self.observe(self._signal(engine), self._knobs)

    def _signal(self, engine) -> Signal:
        source = engine.source
        latest = getattr(engine, "_tick_latest", None)
        backlog = (
            engine.backlog_offsets(latest) if latest is not None else 0
        )
        stats_fn = getattr(source, "prefetch_stats", None)
        miss_rate = occupancy = 0.0
        if stats_fn is not None:
            if getattr(source, "prefetch_batches", 0) <= 0:
                # staging disabled: every read of the backlog IS a
                # synchronous cold read (the source's miss counters are
                # gated on prefetch being armed, so they cannot say
                # it) — report the honest 100% miss rate so the tuner
                # can arm staging instead of ratcheting one way down
                miss_rate = 1.0 if backlog > 0 else 0.0
            else:
                stats = stats_fn()
                hits_d = stats["hits"] - self._last_hits
                misses_d = stats["misses"] - self._last_misses
                self._last_hits, self._last_misses = (
                    stats["hits"], stats["misses"],
                )
                if hits_d + misses_d > 0:
                    miss_rate = misses_d / (hits_d + misses_d)
                occupancy = stats["staged"] / max(
                    1, source.prefetch_batches
                )
        meters = getattr(source, "meters", {})
        read_m = meters.get("read")
        parse_m = meters.get("parse")
        unit = getattr(engine, "max_batch_offsets", None)
        return Signal(
            backlog=backlog,
            miss_rate=miss_rate,
            queue_occupancy=occupancy,
            read_wait_s=read_m.ewma_s if read_m is not None else 0.0,
            parse_s=parse_m.ewma_s if parse_m is not None else 0.0,
            files_per_batch=unit if unit is not None else max(1, backlog),
        )

    # -- the controller ------------------------------------------------------

    def propose(
        self, sig: Signal, knobs: Dict[str, Knob]
    ) -> Optional[Tuple[str, int]]:
        """Pure bottleneck diagnosis → (knob, direction) or None.
        Ranked: staging width first (the tf.data ordering — config
        10's journaled 0.913→0.986 delta came from this), then
        intra-batch parse workers (gated on misses persisting or
        staging maxed), then pipeline depth; shrink only when
        provably idle."""
        p = self.policy

        def usable(name: str, direction: int) -> bool:
            k = knobs.get(name)
            if k is None or name in self.frozen:
                return False
            cur = k.get()
            return cur < k.hi if direction > 0 else cur > k.lo

        if sig.backlog > 0:
            # staging first (the tf.data ordering): a deeper prefetch
            # queue hides parse AND I/O across batches, so it is the
            # cheapest fix for an engine falling through to cold reads
            if sig.miss_rate >= p.miss_rate_hi and usable(
                "prefetch_batches", +1
            ):
                return ("prefetch_batches", +1)
            # intra-batch parse parallelism only when parse dominates
            # what the engine actually WAITS for and staging has not
            # already absorbed it (misses persist, or staging is maxed)
            parse_share = sig.parse_s / max(sig.read_wait_s, 1e-9)
            if (
                sig.files_per_batch > 1
                and parse_share >= p.parse_share_hi
                and (
                    sig.miss_rate > 0.0
                    or not usable("prefetch_batches", +1)
                )
                and usable("read_workers", +1)
            ):
                return ("read_workers", +1)
            if sig.queue_occupancy >= p.occupancy_hi and usable(
                "pipeline_depth", +1
            ):
                return ("pipeline_depth", +1)
            return None
        if (
            sig.miss_rate <= 0.0
            and sig.queue_occupancy <= p.idle_occupancy_lo
        ):
            # idle: shrink the widest grown pool first (deterministic
            # order), reclaiming threads/queue slots (and budget)
            for name in ("prefetch_batches", "read_workers",
                         "pipeline_depth"):
                if usable(name, -1):
                    return (name, -1)
        return None

    def observe(
        self, sig: Signal, knobs: Dict[str, Knob]
    ) -> Optional[dict]:
        """One observation window: hysteresis + budget + apply.
        Returns the journaled record when a knob moved (or froze),
        None otherwise."""
        self._windows += 1
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        prop = self.propose(sig, knobs)
        if prop != self._pending:
            self._pending = prop
            self._streak = 1 if prop is not None else 0
            return None
        if prop is None:
            return None
        self._streak += 1
        if self._streak < self.policy.confirm:
            return None
        name, direction = prop
        self._pending, self._streak = None, 0
        knob = knobs[name]
        last = self._last_dir.get(name)
        if last is not None and last != direction:
            self._reversals[name] = self._reversals.get(name, 0) + 1
            if self._reversals[name] > self.policy.max_reversals:
                self.frozen.add(name)
                return self._journal(
                    name, direction, knob.get(), knob.get(),
                    action="frozen", signal=sig,
                )
        cur = knob.get()
        new = knob.clamp(cur + direction * knob.step)
        if new == cur:
            return None
        if self.budget is not None:
            # budget charges only the EXTRA capacity above this knob's
            # COLD-START value (captured at first contact): shrinking
            # below the baseline refunds nothing (nothing was charged),
            # and regrowing back to it costs nothing — so an idle fleet
            # that dipped under its defaults can always recover them
            baseline = self._baseline.setdefault(name, cur)
            held = self._budget_held.get(name, 0)
            want = max(0, new - baseline)
            if want > held:
                if not self.budget.try_acquire(name, want - held):
                    self._cooldown = self.policy.cooldown
                    return self._journal(
                        name, direction, cur, cur,
                        action="budget_denied", signal=sig,
                    )
            elif want < held:
                self.budget.release(name, held - want)
            self._budget_held[name] = want
        knob.set(new)
        self._last_dir[name] = direction
        self._cooldown = self.policy.cooldown
        labels = {} if self.tenant is None else {"tenant": self.tenant}
        inc(
            "sntc_ingest_autotune_decisions_total",
            knob=name, direction="up" if direction > 0 else "down",
            **labels,
        )
        set_gauge("sntc_ingest_knob_value", new, knob=name, **labels)
        return self._journal(
            name, direction, cur, new, action="applied", signal=sig
        )

    def _journal(self, name, direction, old, new, *, action, signal):
        rec = {
            "action": action,
            "knob": name,
            "direction": "up" if direction > 0 else "down",
            "from": old,
            "to": new,
            "window": self._windows,
            "signal": {
                "backlog": signal.backlog,
                "miss_rate": round(signal.miss_rate, 3),
                "queue_occupancy": round(signal.queue_occupancy, 3),
                "read_wait_s": round(signal.read_wait_s, 6),
                "parse_s": round(signal.parse_s, 6),
                "files_per_batch": signal.files_per_batch,
            },
        }
        self.decisions.append(rec)
        self.decisions_total += 1
        if len(self.decisions) > self._journal_keep:
            del self.decisions[0]
        fields = dict(
            event="autotune_decision", action=action, knob=name,
            direction=rec["direction"], value=new,
        )
        if self.tenant is not None:
            fields["tenant"] = self.tenant
        emit_event(**fields)
        return rec

    # -- evidence ------------------------------------------------------------

    def applied(self) -> List[dict]:
        return [d for d in self.decisions if d["action"] == "applied"]

    def knob_values(self) -> Dict[str, int]:
        if not self._knobs:
            return {}
        return {name: k.get() for name, k in self._knobs.items()}

    def stats(self) -> dict:
        out = {
            "windows": self._windows,
            "decisions": self.decisions_total,
            "applied": len(self.applied()),
            "frozen": sorted(self.frozen),
            "knobs": self.knob_values(),
            "recent": self.decisions[-8:],
        }
        if self.budget is not None:
            out["budget"] = self.budget.snapshot()
        return out
