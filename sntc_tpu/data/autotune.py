"""Feedback autotuner for the ingest source graph — tf.data AUTOTUNE
(arxiv 2101.12127), generalized to this codebase's batch train ingest,
streaming serve source, and flow-capture source.

The PR-8 telemetry plane already collects the feedback signal — the
``ingest.parse``/``stream.read`` stage latencies and the prefetch
hit/miss/high-water counters.  :class:`IngestAutotuner` closes the
loop: once per observation window (``interval_ticks`` engine rounds,
the poll-tick cadence; the ServeDaemon drives the same hook at
daemon-tick cadence) it condenses those signals into a :class:`Signal`,
diagnoses the bottleneck stage, and moves ONE knob one step —
``prefetch_batches`` when the engine waits on cold reads (staging
first, the tf.data ordering), ``read_workers`` when intra-batch parse
dominates and staging has not absorbed it, ``pipeline_depth`` when
staging is full but the engine still trails, and back DOWN when the
graph is provably idle.

**The no-oscillation guarantee** (pinned by a property test): a
proposal must repeat ``confirm`` consecutive windows before it applies;
every applied change freezes the tuner for ``cooldown`` windows; and a
knob that reverses direction more than ``max_reversals`` times is
FROZEN for the tuner's lifetime.  Total knob changes are therefore
bounded by ``Σ_knobs (max_reversals + 1) × (hi − lo) / step``
regardless of the input signal — a flapping source can waste windows,
never flap a pool size forever.

Every applied decision (and every freeze) is journaled in memory
(``stats()["decisions"]``, the bench-evidence surface), emitted as an
``autotune_decision`` structured event, and mirrored to the cataloged
``sntc_ingest_autotune_decisions_total`` counter +
``sntc_ingest_knob_value`` gauges.

:class:`TuningBudget` is the multi-tenant arbiter: one budget shared by
every tenant's tuner caps the total EXTRA pool threads / staged ranges
/ pipeline slots the fleet may grow beyond its cold defaults, so ten
tenants autotuning on one box cannot each claim the whole host.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from sntc_tpu.data.pipeline import Knob, graph_knobs
from sntc_tpu.obs.metrics import inc, set_gauge
from sntc_tpu.resilience import emit_event
from sntc_tpu.resilience.control import Guardrails, TuningBudget

__all__ = [
    "AutotunePolicy",
    "IngestAutotuner",
    "Signal",
    "TuningBudget",  # canonical home: sntc_tpu.resilience.control (r16)
]


@dataclass
class AutotunePolicy:
    """The controller's constants.  Defaults are deliberately
    conservative — two confirming windows, two cooldown windows, two
    reversals — so a production engine changes a pool size at most a
    handful of times, then sits still."""

    interval_ticks: int = 4   # engine rounds per observation window
    confirm: int = 2          # consecutive agreeing windows to apply
    cooldown: int = 2         # windows frozen after an apply
    max_reversals: int = 2    # direction flips per knob before freezing
    miss_rate_hi: float = 0.5     # cold-read fraction → widen staging
    occupancy_hi: float = 0.9     # staging full + backlog → deepen pipe
    idle_occupancy_lo: float = 0.25   # everything idle → shrink
    parse_share_hi: float = 0.5   # parse / read-wait → more workers


@dataclass
class Signal:
    """One observation window, condensed.  Pure data so tests (and the
    convergence suite) can drive :meth:`IngestAutotuner.observe`
    synthetically without a live engine."""

    backlog: int = 0          # source offsets available but unplanned
    miss_rate: float = 0.0    # prefetch misses / (hits + misses)
    queue_occupancy: float = 0.0  # staged ranges / prefetch_batches
    read_wait_s: float = 0.0  # read-stage EWMA (engine-observed wait)
    parse_s: float = 0.0      # parse-stage EWMA (per file)
    files_per_batch: int = 1  # offsets one micro-batch covers


class IngestAutotuner:
    """The feedback loop (module docstring).  Attach to one engine via
    ``StreamingQuery(autotuner=...)`` — the engine calls
    :meth:`on_tick` once per round; everything else is internal.
    Tests drive :meth:`observe` directly with synthetic signals."""

    def __init__(
        self,
        policy: Optional[AutotunePolicy] = None,
        budget: Optional[TuningBudget] = None,
        tenant: Optional[str] = None,
        bounds: Optional[dict] = None,
        exclude_knobs: Tuple[str, ...] = (),
    ):
        self.policy = policy or AutotunePolicy()
        self.budget = budget
        self.tenant = tenant
        self.bounds = bounds
        # a ServeController owning this tuner keeps pipeline_depth for
        # itself (one owner per knob): excluded knobs never bind
        self.exclude_knobs = tuple(exclude_knobs)
        # the shared hysteresis substrate (resilience/control.py):
        # confirm-streak + cooldown + reversal-freeze + bounded journal
        # + budget charge — extracted in r16 with zero behavior diff
        # (the r15 property tests pin it)
        self.guard = Guardrails(
            policy=self.policy, budget=budget,
            on_journal=self._on_journal,
        )
        self._ticks = 0
        self._last_hits = 0
        self._last_misses = 0
        self._knobs: Optional[Dict[str, Knob]] = None
        self._engine = None

    # the pre-extraction public surface, now views over the guardrails
    @property
    def decisions(self) -> List[dict]:
        return self.guard.decisions

    @property
    def decisions_total(self) -> int:
        return self.guard.decisions_total

    @property
    def frozen(self) -> set:
        return self.guard.frozen

    # -- engine cadence ------------------------------------------------------

    def on_tick(self, engine) -> Optional[dict]:
        """One engine round: cheap counter bump until the observation
        window closes, then observe + maybe act.  Returns the applied
        decision record, if any (the engine ignores it)."""
        self._ticks += 1
        if self._ticks % max(1, self.policy.interval_ticks):
            return None
        if self._knobs is None or engine is not self._engine:
            # (re)bind to this engine's live knob surface — a tuner
            # reused across successive queries over ONE source (the
            # bench's at-saturation reps) keeps its learned source
            # knobs; only the engine-owned pipeline_depth rebinds
            self._engine = engine
            self._knobs = {
                name: k
                for name, k in graph_knobs(engine, self.bounds).items()
                if name not in self.exclude_knobs
            }
        return self.observe(self._signal(engine), self._knobs)

    def _signal(self, engine) -> Signal:
        source = engine.source
        latest = getattr(engine, "_tick_latest", None)
        backlog = (
            engine.backlog_offsets(latest) if latest is not None else 0
        )
        stats_fn = getattr(source, "prefetch_stats", None)
        miss_rate = occupancy = 0.0
        if stats_fn is not None:
            if getattr(source, "prefetch_batches", 0) <= 0:
                # staging disabled: every read of the backlog IS a
                # synchronous cold read (the source's miss counters are
                # gated on prefetch being armed, so they cannot say
                # it) — report the honest 100% miss rate so the tuner
                # can arm staging instead of ratcheting one way down
                miss_rate = 1.0 if backlog > 0 else 0.0
            else:
                stats = stats_fn()
                hits_d = stats["hits"] - self._last_hits
                misses_d = stats["misses"] - self._last_misses
                self._last_hits, self._last_misses = (
                    stats["hits"], stats["misses"],
                )
                if hits_d + misses_d > 0:
                    miss_rate = misses_d / (hits_d + misses_d)
                occupancy = stats["staged"] / max(
                    1, source.prefetch_batches
                )
        meters = getattr(source, "meters", {})
        read_m = meters.get("read")
        parse_m = meters.get("parse")
        unit = getattr(engine, "max_batch_offsets", None)
        return Signal(
            backlog=backlog,
            miss_rate=miss_rate,
            queue_occupancy=occupancy,
            read_wait_s=read_m.ewma_s if read_m is not None else 0.0,
            parse_s=parse_m.ewma_s if parse_m is not None else 0.0,
            files_per_batch=unit if unit is not None else max(1, backlog),
        )

    # -- the controller ------------------------------------------------------

    def propose(
        self, sig: Signal, knobs: Dict[str, Knob]
    ) -> Optional[Tuple[str, int]]:
        """Pure bottleneck diagnosis → (knob, direction) or None.
        Ranked: staging width first (the tf.data ordering — config
        10's journaled 0.913→0.986 delta came from this), then
        intra-batch parse workers (gated on misses persisting or
        staging maxed), then pipeline depth; shrink only when
        provably idle."""
        p = self.policy

        def usable(name: str, direction: int) -> bool:
            k = knobs.get(name)
            if k is None or name in self.frozen:
                return False
            cur = k.get()
            return cur < k.hi if direction > 0 else cur > k.lo

        if sig.backlog > 0:
            # staging first (the tf.data ordering): a deeper prefetch
            # queue hides parse AND I/O across batches, so it is the
            # cheapest fix for an engine falling through to cold reads
            if sig.miss_rate >= p.miss_rate_hi and usable(
                "prefetch_batches", +1
            ):
                return ("prefetch_batches", +1)
            # intra-batch parse parallelism only when parse dominates
            # what the engine actually WAITS for and staging has not
            # already absorbed it (misses persist, or staging is maxed)
            parse_share = sig.parse_s / max(sig.read_wait_s, 1e-9)
            if (
                sig.files_per_batch > 1
                and parse_share >= p.parse_share_hi
                and (
                    sig.miss_rate > 0.0
                    or not usable("prefetch_batches", +1)
                )
                and usable("read_workers", +1)
            ):
                return ("read_workers", +1)
            if sig.queue_occupancy >= p.occupancy_hi and usable(
                "pipeline_depth", +1
            ):
                return ("pipeline_depth", +1)
            return None
        if (
            sig.miss_rate <= 0.0
            and sig.queue_occupancy <= p.idle_occupancy_lo
        ):
            # idle: shrink the widest grown pool first (deterministic
            # order), reclaiming threads/queue slots (and budget)
            for name in ("prefetch_batches", "read_workers",
                         "pipeline_depth"):
                if usable(name, -1):
                    return (name, -1)
        return None

    def observe(
        self, sig: Signal, knobs: Dict[str, Knob]
    ) -> Optional[dict]:
        """One observation window: the shared guardrails
        (hysteresis + budget, ``resilience/control.py``) arbitrate the
        proposal and apply it.  Returns the journaled record when a
        knob moved (or froze), None otherwise."""
        return self.guard.observe(
            lambda: self.propose(sig, knobs),
            knobs,
            lambda: {
                "backlog": sig.backlog,
                "miss_rate": round(sig.miss_rate, 3),
                "queue_occupancy": round(sig.queue_occupancy, 3),
                "read_wait_s": round(sig.read_wait_s, 6),
                "parse_s": round(sig.parse_s, 6),
                "files_per_batch": sig.files_per_batch,
            },
            on_applied=self._mirror_applied,
        )

    def _mirror_applied(self, name: str, direction: int, new: int) -> None:
        labels = {} if self.tenant is None else {"tenant": self.tenant}
        inc(
            "sntc_ingest_autotune_decisions_total",
            knob=name, direction="up" if direction > 0 else "down",
            **labels,
        )
        set_gauge("sntc_ingest_knob_value", new, knob=name, **labels)

    def _on_journal(self, rec: dict) -> None:
        fields = dict(
            event="autotune_decision", action=rec["action"],
            knob=rec["knob"], direction=rec["direction"],
            value=rec["to"],
        )
        if self.tenant is not None:
            fields["tenant"] = self.tenant
        emit_event(**fields)

    # -- evidence ------------------------------------------------------------

    def applied(self) -> List[dict]:
        return [d for d in self.decisions if d["action"] == "applied"]

    def knob_values(self) -> Dict[str, int]:
        if not self._knobs:
            return {}
        return {name: k.get() for name, k in self._knobs.items()}

    def stats(self) -> dict:
        out = {
            "windows": self.guard.windows,
            "decisions": self.decisions_total,
            "applied": len(self.applied()),
            "frozen": sorted(self.frozen),
            "knobs": self.knob_values(),
            "recent": self.decisions[-8:],
        }
        if self.budget is not None:
            out["budget"] = self.budget.snapshot()
        return out
