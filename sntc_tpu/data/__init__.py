from sntc_tpu.data.schema import (
    CICIDS2017_CONTRACT,
    CICIDS2017_FEATURES,
    CICIDS2017_LABELS,
    NUM_FEATURES,
    AdmissionResult,
    ColumnSpec,
    SchemaContract,
    SchemaViolation,
)
from sntc_tpu.data.synth import (
    generate_drift_frames,
    generate_frame,
    write_capture_stream,
    write_day_csvs,
    write_drift_stream,
)
from sntc_tpu.data.ingest import clean_flows, load_csv, load_csv_dir, cache_parquet

__all__ = [
    "CICIDS2017_FEATURES",
    "CICIDS2017_LABELS",
    "CICIDS2017_CONTRACT",
    "NUM_FEATURES",
    "AdmissionResult",
    "ColumnSpec",
    "SchemaContract",
    "SchemaViolation",
    "generate_frame",
    "generate_drift_frames",
    "write_capture_stream",
    "write_day_csvs",
    "write_drift_stream",
    "clean_flows",
    "load_csv",
    "load_csv_dir",
    "cache_parquet",
]
