"""CICIDS2017 flow schema — column names and label vocabulary.

The reference classifies CICIDS2017 "MachineLearningCVE" day CSVs: ~2.8M rows
of 78 numeric flow features + a 15-value label column (SURVEY.md §0.1, §2.1).
Feature names below follow the standard CICFlowMeter export (whitespace
normalized — the raw CSVs have erratic leading spaces; the ingest layer
strips them so real day files drop in unchanged, SURVEY.md §7.2 item 6).

The two rate features ``Flow Bytes/s`` / ``Flow Packets/s`` famously contain
``Infinity``/``NaN`` values in the real data; the synthetic generator injects
them and the cleaning pass must handle them (SURVEY.md §2.1).
"""

from __future__ import annotations

from typing import Dict, List

CICIDS2017_FEATURES: List[str] = [
    "Destination Port",
    "Flow Duration",
    "Total Fwd Packets",
    "Total Backward Packets",
    "Total Length of Fwd Packets",
    "Total Length of Bwd Packets",
    "Fwd Packet Length Max",
    "Fwd Packet Length Min",
    "Fwd Packet Length Mean",
    "Fwd Packet Length Std",
    "Bwd Packet Length Max",
    "Bwd Packet Length Min",
    "Bwd Packet Length Mean",
    "Bwd Packet Length Std",
    "Flow Bytes/s",
    "Flow Packets/s",
    "Flow IAT Mean",
    "Flow IAT Std",
    "Flow IAT Max",
    "Flow IAT Min",
    "Fwd IAT Total",
    "Fwd IAT Mean",
    "Fwd IAT Std",
    "Fwd IAT Max",
    "Fwd IAT Min",
    "Bwd IAT Total",
    "Bwd IAT Mean",
    "Bwd IAT Std",
    "Bwd IAT Max",
    "Bwd IAT Min",
    "Fwd PSH Flags",
    "Bwd PSH Flags",
    "Fwd URG Flags",
    "Bwd URG Flags",
    "Fwd Header Length",
    "Bwd Header Length",
    "Fwd Packets/s",
    "Bwd Packets/s",
    "Min Packet Length",
    "Max Packet Length",
    "Packet Length Mean",
    "Packet Length Std",
    "Packet Length Variance",
    "FIN Flag Count",
    "SYN Flag Count",
    "RST Flag Count",
    "PSH Flag Count",
    "ACK Flag Count",
    "URG Flag Count",
    "CWE Flag Count",
    "ECE Flag Count",
    "Down/Up Ratio",
    "Average Packet Size",
    "Avg Fwd Segment Size",
    "Avg Bwd Segment Size",
    "Fwd Header Length.1",
    "Fwd Avg Bytes/Bulk",
    "Fwd Avg Packets/Bulk",
    "Fwd Avg Bulk Rate",
    "Bwd Avg Bytes/Bulk",
    "Bwd Avg Packets/Bulk",
    "Bwd Avg Bulk Rate",
    "Subflow Fwd Packets",
    "Subflow Fwd Bytes",
    "Subflow Bwd Packets",
    "Subflow Bwd Bytes",
    "Init_Win_bytes_forward",
    "Init_Win_bytes_backward",
    "act_data_pkt_fwd",
    "min_seg_size_forward",
    "Active Mean",
    "Active Std",
    "Active Max",
    "Active Min",
    "Idle Mean",
    "Idle Std",
    "Idle Max",
    "Idle Min",
]

NUM_FEATURES = len(CICIDS2017_FEATURES)
assert NUM_FEATURES == 78, NUM_FEATURES

LABEL_COLUMN = "Label"

#: the 15 CICIDS2017 classes: benign + 14 attack types (SURVEY.md §0.1)
CICIDS2017_LABELS: List[str] = [
    "BENIGN",
    "DoS Hulk",
    "PortScan",
    "DDoS",
    "DoS GoldenEye",
    "FTP-Patator",
    "SSH-Patator",
    "DoS slowloris",
    "DoS Slowhttptest",
    "Bot",
    "Web Attack - Brute Force",
    "Web Attack - XSS",
    "Infiltration",
    "Web Attack - Sql Injection",
    "Heartbleed",
]
assert len(CICIDS2017_LABELS) == 15

#: approximate class priors of the real dataset (benign-heavy imbalance);
#: used by the synthetic generator so imbalance behavior is exercised.
CLASS_PRIORS: Dict[str, float] = {
    "BENIGN": 0.803,
    "DoS Hulk": 0.0816,
    "PortScan": 0.0561,
    "DDoS": 0.0452,
    "DoS GoldenEye": 0.00364,
    "FTP-Patator": 0.00280,
    "SSH-Patator": 0.00208,
    "DoS slowloris": 0.00205,
    "DoS Slowhttptest": 0.00194,
    "Bot": 0.000694,
    "Web Attack - Brute Force": 0.000532,
    "Web Attack - XSS": 0.000230,
    "Infiltration": 0.0000127,
    "Web Attack - Sql Injection": 0.0000074,
    "Heartbleed": 0.0000039,
}

#: raw-CSV label spellings seen in the wild (en-dash mojibake etc.) -> canonical
LABEL_ALIASES: Dict[str, str] = {
    "Web Attack \x96 Brute Force": "Web Attack - Brute Force",
    "Web Attack – Brute Force": "Web Attack - Brute Force",
    "Web Attack \x96 XSS": "Web Attack - XSS",
    "Web Attack – XSS": "Web Attack - XSS",
    "Web Attack \x96 Sql Injection": "Web Attack - Sql Injection",
    "Web Attack – Sql Injection": "Web Attack - Sql Injection",
}


def normalize_feature_name(name: str) -> str:
    """Strip the erratic leading/trailing whitespace of raw CICIDS2017 CSVs."""
    return name.strip()


def normalize_label(label: str) -> str:
    label = label.strip()
    return LABEL_ALIASES.get(label, label)
