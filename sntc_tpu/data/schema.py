"""CICIDS2017 flow schema — column names, label vocabulary, and the
declarative :class:`SchemaContract` the data plane enforces.

The reference classifies CICIDS2017 "MachineLearningCVE" day CSVs: ~2.8M rows
of 78 numeric flow features + a 15-value label column (SURVEY.md §0.1, §2.1).
Feature names below follow the standard CICFlowMeter export (whitespace
normalized — the raw CSVs have erratic leading spaces; the ingest layer
strips them so real day files drop in unchanged, SURVEY.md §7.2 item 6).

The two rate features ``Flow Bytes/s`` / ``Flow Packets/s`` famously contain
``Infinity``/``NaN`` values in the real data; the synthetic generator injects
them and the cleaning pass must handle them (SURVEY.md §2.1).

**Schema contracts** (r10): network traffic is adversarial input, so
the serve path admits rows through an explicit per-column contract
instead of trusting the parser's output.  A :class:`SchemaContract`
declares dtype/arity expectations plus NaN/Inf/range/domain policies
per column and admits a Frame in one of three modes:

* ``strict``   — any violation raises :class:`SchemaViolation` (the
  whole batch fails; the engine's poison-batch machinery takes over);
* ``salvage``  — valid rows proceed, poison rows are excised via a
  row-validity mask (the batch keeps its SHAPE — excision composes
  with shape-bucketed/fused serving without recompiles);
* ``permissive`` — per-value coercion first (numeric strings parse,
  non-finite values take the column's declared ``fill``), THEN salvage
  whatever remains poison.

:data:`CICIDS2017_CONTRACT` is the canonical contract for the 78-column
flow schema; ``clean_flows`` (training-time cleaning) and serve-time
admission are defined against the same constant so the two can never
drift (tests assert the equivalence).  See docs/RESILIENCE.md
"Data-plane admission".
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

import numpy as np

CICIDS2017_FEATURES: List[str] = [
    "Destination Port",
    "Flow Duration",
    "Total Fwd Packets",
    "Total Backward Packets",
    "Total Length of Fwd Packets",
    "Total Length of Bwd Packets",
    "Fwd Packet Length Max",
    "Fwd Packet Length Min",
    "Fwd Packet Length Mean",
    "Fwd Packet Length Std",
    "Bwd Packet Length Max",
    "Bwd Packet Length Min",
    "Bwd Packet Length Mean",
    "Bwd Packet Length Std",
    "Flow Bytes/s",
    "Flow Packets/s",
    "Flow IAT Mean",
    "Flow IAT Std",
    "Flow IAT Max",
    "Flow IAT Min",
    "Fwd IAT Total",
    "Fwd IAT Mean",
    "Fwd IAT Std",
    "Fwd IAT Max",
    "Fwd IAT Min",
    "Bwd IAT Total",
    "Bwd IAT Mean",
    "Bwd IAT Std",
    "Bwd IAT Max",
    "Bwd IAT Min",
    "Fwd PSH Flags",
    "Bwd PSH Flags",
    "Fwd URG Flags",
    "Bwd URG Flags",
    "Fwd Header Length",
    "Bwd Header Length",
    "Fwd Packets/s",
    "Bwd Packets/s",
    "Min Packet Length",
    "Max Packet Length",
    "Packet Length Mean",
    "Packet Length Std",
    "Packet Length Variance",
    "FIN Flag Count",
    "SYN Flag Count",
    "RST Flag Count",
    "PSH Flag Count",
    "ACK Flag Count",
    "URG Flag Count",
    "CWE Flag Count",
    "ECE Flag Count",
    "Down/Up Ratio",
    "Average Packet Size",
    "Avg Fwd Segment Size",
    "Avg Bwd Segment Size",
    "Fwd Header Length.1",
    "Fwd Avg Bytes/Bulk",
    "Fwd Avg Packets/Bulk",
    "Fwd Avg Bulk Rate",
    "Bwd Avg Bytes/Bulk",
    "Bwd Avg Packets/Bulk",
    "Bwd Avg Bulk Rate",
    "Subflow Fwd Packets",
    "Subflow Fwd Bytes",
    "Subflow Bwd Packets",
    "Subflow Bwd Bytes",
    "Init_Win_bytes_forward",
    "Init_Win_bytes_backward",
    "act_data_pkt_fwd",
    "min_seg_size_forward",
    "Active Mean",
    "Active Std",
    "Active Max",
    "Active Min",
    "Idle Mean",
    "Idle Std",
    "Idle Max",
    "Idle Min",
]

NUM_FEATURES = len(CICIDS2017_FEATURES)
assert NUM_FEATURES == 78, NUM_FEATURES

LABEL_COLUMN = "Label"

#: the 15 CICIDS2017 classes: benign + 14 attack types (SURVEY.md §0.1)
CICIDS2017_LABELS: List[str] = [
    "BENIGN",
    "DoS Hulk",
    "PortScan",
    "DDoS",
    "DoS GoldenEye",
    "FTP-Patator",
    "SSH-Patator",
    "DoS slowloris",
    "DoS Slowhttptest",
    "Bot",
    "Web Attack - Brute Force",
    "Web Attack - XSS",
    "Infiltration",
    "Web Attack - Sql Injection",
    "Heartbleed",
]
assert len(CICIDS2017_LABELS) == 15

#: approximate class priors of the real dataset (benign-heavy imbalance);
#: used by the synthetic generator so imbalance behavior is exercised.
CLASS_PRIORS: Dict[str, float] = {
    "BENIGN": 0.803,
    "DoS Hulk": 0.0816,
    "PortScan": 0.0561,
    "DDoS": 0.0452,
    "DoS GoldenEye": 0.00364,
    "FTP-Patator": 0.00280,
    "SSH-Patator": 0.00208,
    "DoS slowloris": 0.00205,
    "DoS Slowhttptest": 0.00194,
    "Bot": 0.000694,
    "Web Attack - Brute Force": 0.000532,
    "Web Attack - XSS": 0.000230,
    "Infiltration": 0.0000127,
    "Web Attack - Sql Injection": 0.0000074,
    "Heartbleed": 0.0000039,
}

#: raw-CSV label spellings seen in the wild (en-dash mojibake etc.) -> canonical
LABEL_ALIASES: Dict[str, str] = {
    "Web Attack \x96 Brute Force": "Web Attack - Brute Force",
    "Web Attack – Brute Force": "Web Attack - Brute Force",
    "Web Attack \x96 XSS": "Web Attack - XSS",
    "Web Attack – XSS": "Web Attack - XSS",
    "Web Attack \x96 Sql Injection": "Web Attack - Sql Injection",
    "Web Attack – Sql Injection": "Web Attack - Sql Injection",
}


def normalize_feature_name(name: str) -> str:
    """Strip the erratic leading/trailing whitespace of raw CICIDS2017 CSVs."""
    return name.strip()


def normalize_label(label: str) -> str:
    label = label.strip()
    return LABEL_ALIASES.get(label, label)


# ---------------------------------------------------------------------------
# schema contracts — the data-plane admission layer (r10)
# ---------------------------------------------------------------------------

#: machine-readable reason codes carried by rejects, dead-letter rows,
#: and :class:`SchemaViolation` (docs/RESILIENCE.md keeps the table).
#: The parser layer contributes ``ragged_row`` (CSV line with the wrong
#: field count), ``unparsable_file`` (a file no salvage can read), and
#: ``truncated`` (binary capture cut mid-record).
REASON_MISSING_COLUMN = "missing_column"
REASON_BAD_ARITY = "bad_arity"
REASON_NOT_NUMERIC = "not_numeric"
REASON_NON_FINITE = "non_finite"
REASON_OUT_OF_RANGE = "out_of_range"
REASON_OUT_OF_DOMAIN = "out_of_domain"
REASON_RAGGED_ROW = "ragged_row"
REASON_UNPARSABLE_FILE = "unparsable_file"
REASON_TRUNCATED = "truncated"

ADMISSION_MODES = ("strict", "salvage", "permissive")


class SchemaViolation(ValueError):
    """A batch violated its :class:`SchemaContract` in a way the active
    mode does not repair row-by-row: any violation under ``strict``, or
    a batch-granular defect (missing column, wrong column rank) under
    every mode.  ``reasons`` is a machine-readable list of
    ``{"column", "reason", "count"}`` dicts."""

    def __init__(self, reasons: List[dict]):
        self.reasons = reasons
        parts = ", ".join(
            f"{r['column']}: {r['reason']} x{r.get('count', 1)}"
            for r in reasons[:8]
        )
        more = f" (+{len(reasons) - 8} more)" if len(reasons) > 8 else ""
        super().__init__(f"schema contract violated — {parts}{more}")


@dataclass(frozen=True)
class ColumnSpec:
    """Per-column expectations: dtype/arity plus NaN/Inf/range/domain
    policy.  ``fill`` is the permissive-mode replacement for values that
    are non-finite (or unparsable text) — ``None`` means such values
    stay row-poison even under ``permissive``."""

    dtype: str = "float32"  # numpy dtype name, or "str" for text columns
    arity: int = 1  # column rank: 1 = scalar, 2 = fixed-width vector
    allow_nan: bool = False
    allow_inf: bool = False
    min_value: Optional[float] = None
    max_value: Optional[float] = None
    domain: Optional[Tuple[str, ...]] = None  # allowed values (text cols)
    fill: Optional[float] = None

    @property
    def is_text(self) -> bool:
        return self.dtype == "str"


def _truncate_repr(value, limit: int = 120) -> str:
    if isinstance(value, np.generic):
        value = value.item()  # 'nan', not 'np.float64(nan)'
    text = repr(value)
    return text if len(text) <= limit else text[: limit - 3] + "..."


@dataclass
class AdmissionResult:
    """Outcome of :meth:`SchemaContract.admit` in a row-granular mode.

    ``frame`` keeps the input's SHAPE: contract columns are cast to
    their declared dtypes, coercions applied, and every excised row's
    values replaced with a copy of a surviving row (so downstream
    device compute stays numerically in-domain — the same trick
    ``Frame.pad_rows`` uses for bucket padding).  ``valid`` marks the
    rows that really belong in the output; ``rejects`` carries one
    record per excised row with its first violation; ``coerced`` counts
    values permissive mode repaired in place."""

    frame: "object"
    valid: np.ndarray
    rejects: List[dict] = field(default_factory=list)
    coerced: int = 0

    @property
    def num_rejected(self) -> int:
        return int(self.valid.size - np.count_nonzero(self.valid))


@dataclass(frozen=True)
class SchemaContract:
    """Declarative admission contract for a Frame (see module docs).

    ``require_all=True`` makes a missing contract column a batch-level
    :class:`SchemaViolation` in every mode (absence cannot be salvaged
    row-by-row); ``allow_extra=True`` lets columns outside the contract
    (labels, engine bookkeeping) pass through untouched."""

    columns: Dict[str, ColumnSpec]
    mode: str = "strict"
    require_all: bool = True
    allow_extra: bool = True

    def __post_init__(self):
        if self.mode not in ADMISSION_MODES:
            raise ValueError(
                f"mode must be one of {ADMISSION_MODES}, got {self.mode!r}"
            )

    def with_mode(self, mode: str) -> "SchemaContract":
        """The same contract under a different admission mode (the CLI
        arms one canonical contract with ``--row-policy``)."""
        if mode == self.mode:
            return self
        return replace(self, mode=mode)

    # -- per-column checking ------------------------------------------------

    def _numeric_values(
        self, name: str, col: np.ndarray, mode: str,
        cell_reasons: Dict[int, Tuple[str, str]],
    ) -> Tuple[np.ndarray, int]:
        """Float64 working copy of a TEXT contract column plus the
        number of values that required repair/parsing (native numeric
        columns never reach this — ``admit`` validates them in place,
        copy-free).  Text cells are parsed where possible (reading
        "1.5" is not mutation) and the rest are NaN-marked with a
        ``not_numeric`` reason — ``permissive`` additionally repairs
        those with the declared fill."""
        values = np.full(col.shape[0], np.nan, np.float64)
        for i, raw in enumerate(col):
            try:
                values[i] = float(raw)
            except (TypeError, ValueError):
                cell_reasons.setdefault(
                    i, (REASON_NOT_NUMERIC, _truncate_repr(raw))
                )
        # parsing text is only COUNTED as coercion under permissive —
        # salvage/strict read numeric strings without claiming a repair.
        # Count FINITE parses only: a cell that parsed to NaN/Inf is the
        # bulk non-finite repair's to count (once), not ours
        coerced = (
            int(np.count_nonzero(np.isfinite(values)))
            if mode == "permissive"
            else 0
        )
        if mode == "permissive":
            # unparsable text is repairable when the column declares a
            # fill — the cell takes it and the row survives
            spec = self.columns[name]
            if spec.fill is not None:
                for i in list(cell_reasons):
                    if cell_reasons[i][0] == REASON_NOT_NUMERIC:
                        values[i] = spec.fill
                        del cell_reasons[i]
                        coerced += 1
        return values, coerced

    # -- admission ----------------------------------------------------------

    def admit(self, frame, mode: Optional[str] = None) -> AdmissionResult:
        """Validate ``frame`` against the contract.

        ``strict``: raises :class:`SchemaViolation` on ANY violation
        (current engine machinery then treats the batch as poison).
        ``salvage``/``permissive``: returns an :class:`AdmissionResult`
        whose frame has the input's shape and whose ``valid`` mask
        excises the poison rows — ride it through the shape-bucketed
        predict path and the jitted programs never see a new shape.
        Batch-granular defects (missing column, wrong rank) raise in
        every mode."""
        mode = mode or self.mode
        if mode not in ADMISSION_MODES:
            raise ValueError(
                f"mode must be one of {ADMISSION_MODES}, got {mode!r}"
            )
        batch_problems: List[dict] = []
        for name, spec in self.columns.items():
            if name not in frame:
                if self.require_all:
                    batch_problems.append(
                        {"column": name, "reason": REASON_MISSING_COLUMN,
                         "count": 1}
                    )
                continue
            if frame[name].ndim != spec.arity:
                batch_problems.append(
                    {"column": name, "reason": REASON_BAD_ARITY,
                     "count": 1,
                     "detail": f"rank {frame[name].ndim} != {spec.arity}"}
                )
        if batch_problems:
            raise SchemaViolation(batch_problems)

        n = frame.num_rows
        valid = np.ones(n, dtype=bool)
        # row -> (column, reason, value-repr): the FIRST violation wins
        row_reasons: Dict[int, Tuple[str, str, str]] = {}
        coerced_total = 0
        out_cols: Dict[str, np.ndarray] = {}

        for name, spec in self.columns.items():
            if name not in frame:
                continue  # require_all=False tolerated absence
            col = frame[name]
            if not isinstance(col, np.ndarray):
                col = np.asarray(col)
            if spec.is_text:
                text = np.array([str(v) for v in col], dtype=object)
                if spec.domain is not None:
                    domain = frozenset(spec.domain)
                    for i, v in enumerate(text):
                        if v not in domain:
                            row_reasons.setdefault(
                                i, (name, REASON_OUT_OF_DOMAIN,
                                    _truncate_repr(v)),
                            )
                            valid[i] = False
                out_cols[name] = text
                continue

            cell_reasons: Dict[int, Tuple[str, str]] = {}
            if col.dtype.kind in "fiub":
                # native numeric column: validate IN PLACE — no working
                # copy, so an all-clean batch (the hot-path common case)
                # costs one vectorized scan per column and zero copies
                flat = col
            else:
                flat, coerced_here = self._numeric_values(
                    name, col, mode, cell_reasons
                )
                coerced_total += coerced_here
            if flat.dtype.kind == "f":
                nan_mask = np.isnan(flat)
                inf_mask = np.isinf(flat)
            else:  # integer/bool columns cannot hold NaN/Inf
                nan_mask = np.zeros(flat.shape, dtype=bool)
                inf_mask = nan_mask
            if mode == "permissive" and spec.fill is not None:
                # _numeric_values already repaired unparsable text under
                # this configuration, so every remaining NaN/Inf is a
                # genuinely non-finite value — repairable in bulk
                repair = np.zeros(flat.shape, dtype=bool)
                if not spec.allow_nan:
                    repair |= nan_mask
                if not spec.allow_inf:
                    repair |= inf_mask
                if repair.any():
                    coerced_total += int(np.count_nonzero(repair))
                    flat = np.where(
                        repair, flat.dtype.type(spec.fill), flat
                    )
                    nan_mask = np.isnan(flat)
                    inf_mask = np.isinf(flat)
            bad = np.zeros(flat.shape, dtype=bool)
            if not spec.allow_nan:
                bad |= nan_mask
            if not spec.allow_inf:
                bad |= inf_mask
            finite = ~(nan_mask | inf_mask)
            if spec.min_value is not None:
                bad |= finite & (flat < spec.min_value)
            if spec.max_value is not None:
                bad |= finite & (flat > spec.max_value)
            bad_rows = bad.any(axis=-1) if bad.ndim > 1 else bad
            for i in np.flatnonzero(bad_rows):
                i = int(i)
                if i in cell_reasons:
                    reason, shown = cell_reasons[i]
                else:
                    if spec.arity == 1:
                        v = flat[i]
                    else:
                        v = flat[i][
                            int(np.flatnonzero(bad[i])[0])
                        ]
                    reason = (
                        REASON_NON_FINITE
                        if not np.isfinite(v)
                        else REASON_OUT_OF_RANGE
                    )
                    shown = _truncate_repr(v)
                row_reasons.setdefault(i, (name, reason, shown))
            for i in cell_reasons:  # unparsable text NOT caught above
                reason, shown = cell_reasons[i]
                row_reasons.setdefault(i, (name, reason, shown))
            valid &= ~bad_rows
            for i in cell_reasons:
                valid[i] = False
            target = np.dtype(spec.dtype)
            out_arr = (
                flat if flat.dtype == target
                else flat.astype(target, copy=False)
            )
            if out_arr is not col:  # unchanged columns stay shared
                out_cols[name] = out_arr

        if mode == "strict" and row_reasons:
            per_column: Dict[Tuple[str, str], int] = {}
            for col_name, reason, _ in row_reasons.values():
                key = (col_name, reason)
                per_column[key] = per_column.get(key, 0) + 1
            raise SchemaViolation(
                [
                    {"column": c, "reason": r, "count": k}
                    for (c, r), k in sorted(per_column.items())
                ]
            )

        out = frame
        for name, arr in out_cols.items():
            out = out.with_column(name, arr)
        rejects = [
            {
                "row": int(i),
                "column": col_name,
                "reason": reason,
                "value": shown,
            }
            for i, (col_name, reason, shown) in sorted(row_reasons.items())
        ]
        if not valid.all():
            # neutralize excised rows: copy a surviving row over them so
            # the (shape-preserving) dispatch stays numerically in-domain
            out = out.fill_invalid_rows(valid)
        return AdmissionResult(
            frame=out, valid=valid, rejects=rejects, coerced=coerced_total
        )


#: The canonical CICIDS2017 admission contract: all 78 flow features
#: are finite float32 scalars; non-finite values (the infamous
#: ``Flow Bytes/s``/``Flow Packets/s`` Infinity/NaN cells) are poison,
#: repairable with 0.0 under ``permissive``.  ``clean_flows`` is
#: defined against this constant — training-time cleaning
#: (``handle_invalid="drop"``/``"zero"``) and serve-time admission
#: (``salvage``/``permissive``) are the SAME policy at two call sites
#: (tests assert the equivalence row-for-row).
CICIDS2017_CONTRACT = SchemaContract(
    columns={
        name: ColumnSpec(dtype="float32", fill=0.0)
        for name in CICIDS2017_FEATURES
    },
    mode="salvage",
)
