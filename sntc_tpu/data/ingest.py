"""CICIDS2017 ingest + cleaning — the Spark CSV-source analog.

Replaces ``spark.read.csv(..., inferSchema)`` + the app's cleaning pass
(SURVEY.md §2.1): pyarrow's C++ CSV reader is the host data plane (the
sanctioned native layer, SURVEY.md §2.7), column names are whitespace-
normalized so real day CSVs load unchanged, ``Infinity``/``NaN`` rows in the
rate features are dropped (or zero-imputed), and labels are canonicalized.
A Parquet cache avoids re-parsing CSVs across runs.
"""

from __future__ import annotations

import glob
import os
from typing import List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as pq

from sntc_tpu.core.frame import Frame
from sntc_tpu.data.schema import (
    LABEL_COLUMN,
    REASON_RAGGED_ROW,
    normalize_feature_name,
    normalize_label,
)
from sntc_tpu.obs.metrics import inc
from sntc_tpu.obs.trace import span
from sntc_tpu.resilience import data_fault_armed, fault_data


def load_csv(
    path: str,
    *,
    salvage: bool = False,
    rejects: Optional[List[dict]] = None,
) -> Frame:
    """Read one flow CSV with pyarrow, normalizing column names
    (:func:`load_csv_table` materialized into a Frame; the zero-copy
    columnar loader in :mod:`sntc_tpu.data.pipeline` shares the table
    layer so the two paths cannot drift in parse behavior).

    Parse errors always NAME the offending file (and, for ragged rows,
    the 1-based line number plus the raw text) — never a bare
    ``ArrowInvalid``.  ``salvage=True`` arms per-line salvage instead:
    ragged lines are excised, the clean rows parse normally, and each
    excised line is appended to ``rejects`` as ``{"file", "line",
    "raw", "reason"}`` — the row-granular degradation the streaming
    admission layer rides (docs/RESILIENCE.md "Data-plane admission").

    The raw bytes pass through the ``source.parse`` fault site
    (``SNTC_FAULTS=source.parse:ragged:...``), so corrupt-input chaos
    can mutate real ingest payloads deterministically.
    """
    return Frame.from_arrow(
        load_csv_table(path, salvage=salvage, rejects=rejects)
    )


def load_csv_table(
    path: str,
    *,
    salvage: bool = False,
    rejects: Optional[List[dict]] = None,
) -> pa.Table:
    """:func:`load_csv`'s parse layer: the normalized/deduped Arrow
    table, before any numpy materialization — the shared substrate of
    the legacy Frame path and the zero-copy columnar plane
    (:func:`sntc_tpu.data.pipeline.read_flows_columnar`)."""
    if data_fault_armed("source.parse"):
        # chaos path only: buffer the payload so the armed DATA fault
        # can mutate it.  Unarmed (production), pyarrow streams from
        # the path — no whole-file copy in memory per in-flight read.
        with open(path, "rb") as f:
            data = fault_data("source.parse", f.read())
    else:
        data = None

    def _parse(single_thread: bool, bad: List[tuple]):
        def _on_invalid_row(row) -> str:
            # row.number is pyarrow's 1-based physical line number —
            # only attributed on single-threaded reads
            bad.append(
                (row.number, row.text, row.expected_columns,
                 row.actual_columns)
            )
            return "skip" if salvage else "error"

        return pacsv.read_csv(
            pa.BufferReader(data) if data is not None else path,
            read_options=pacsv.ReadOptions(use_threads=not single_thread),
            parse_options=pacsv.ParseOptions(
                invalid_row_handler=_on_invalid_row
            ),
            convert_options=pacsv.ConvertOptions(
                # the raw files spell missing/infinite rates several ways
                null_values=["", "NaN", "nan"],
            ),
        )

    bad_rows: List[tuple] = []
    try:
        with span("ingest.parse", file=os.path.basename(path)):
            table = _parse(single_thread=False, bad=bad_rows)
    except pa.ArrowInvalid as e:
        # rare path: re-parse single-threaded so the error can NAME the
        # line (the parallel reader cannot attribute row numbers)
        located: List[tuple] = []
        try:
            _parse(single_thread=True, bad=located)
        except pa.ArrowInvalid:
            pass
        reportable = located or bad_rows
        if reportable and not salvage:
            line, text, expected, actual = reportable[-1]
            where = f"line {line}" if line is not None else "unknown line"
            raise ValueError(
                f"{path}: {where}: ragged row ({actual} fields, expected "
                f"{expected}): {text!r}"
            ) from e
        raise ValueError(f"{path}: unparsable CSV: {e}") from e
    if salvage and bad_rows and rejects is not None:
        # the fast parallel parse cannot attribute line numbers — this
        # file demonstrably has bad lines, so pay one single-threaded
        # re-parse to journal each excised line with its exact location
        located = []
        _parse(single_thread=True, bad=located)
        for line, text, expected, actual in located or bad_rows:
            rejects.append(
                {
                    "file": path,
                    "line": line,
                    "raw": text,
                    "reason": REASON_RAGGED_ROW,
                    "detail": f"{actual} fields, expected {expected}",
                }
            )
    inc("sntc_ingest_files_parsed_total")
    inc("sntc_ingest_rows_parsed_total", table.num_rows)
    try:
        inc("sntc_ingest_bytes_read_total",
            len(data) if data is not None else os.path.getsize(path))
    except OSError:
        pass  # best-effort byte accounting (path may be a buffer name)
    names = [normalize_feature_name(c) for c in table.column_names]
    # Real MachineLearningCVE day files contain 'Fwd Header Length' TWICE;
    # pandas-style dedup (second copy -> '.1') matches the schema's
    # 'Fwd Header Length.1' so real files drop in unchanged.
    seen: dict = {}
    deduped = []
    for n in names:
        if n in seen:
            seen[n] += 1
            deduped.append(f"{n}.{seen[n]}")
        else:
            seen[n] = 0
            deduped.append(n)
    return table.rename_columns(deduped)


def load_csv_dir(
    path: str,
    pattern: str = "*.csv",
    max_workers: int = 8,
    *,
    salvage: bool = False,
    rejects: Optional[List[dict]] = None,
) -> Frame:
    """Read and concatenate all day CSVs in a directory (the all-days config
    [B:10] loads 8 files).  Files parse in a small thread pool —
    pyarrow's C++ CSV reader releases the GIL, so day files parse in
    parallel — but concatenate in sorted-filename order, byte-identical
    to the serial read.  Parse errors name the offending file and line
    (see :func:`load_csv`); ``salvage``/``rejects`` forward to the
    per-file reader (``list.append`` is atomic, so one shared rejects
    list is safe across the pool)."""
    paths = sorted(glob.glob(os.path.join(path, pattern)))
    if not paths:
        raise FileNotFoundError(f"no {pattern} files under {path}")

    def _load(p: str) -> Frame:
        return load_csv(p, salvage=salvage, rejects=rejects)

    if len(paths) == 1 or max_workers <= 1:
        return Frame.concat_all([_load(p) for p in paths])
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(
        max_workers=min(max_workers, len(paths))
    ) as pool:
        # executor.map preserves input order regardless of completion order
        frames = list(pool.map(_load, paths))
    return Frame.concat_all(frames)


def clean_flows(
    frame: Frame,
    label_col: str = LABEL_COLUMN,
    handle_invalid: str = "drop",
) -> Frame:
    """Clean a raw flow Frame:

    * coerce every feature column to float32,
    * ``±Infinity -> NaN``, then drop rows with any NaN (``handle_invalid=
      "drop"``, the common treatment of CICIDS2017) or zero-impute
      (``"zero"``),
    * canonicalize label strings (strip + mojibake aliases).

    **NaN/Inf policy contract**: this is the training-time face of
    :data:`sntc_tpu.data.schema.CICIDS2017_CONTRACT` — a non-finite
    value in ANY feature column poisons exactly that row, and the two
    treatments map 1:1 onto the serve-time admission modes:
    ``handle_invalid="drop"`` ≡ ``salvage`` (the row is excised),
    ``"zero"`` ≡ ``permissive`` (the cell takes the contract's declared
    ``fill=0.0`` and the row survives).  ``tests/test_admission.py``
    asserts the row-for-row equivalence, so training-time cleaning and
    serve-time admission cannot drift apart."""
    if handle_invalid not in ("drop", "zero"):
        raise ValueError("handle_invalid must be 'drop' or 'zero'")
    feature_cols = [c for c in frame.columns if c != label_col]
    cleaned = {}
    scalar_cols = [c for c in feature_cols if frame[c].ndim == 1]
    # ONE float32 block for every scalar feature column (one row per
    # feature, so each block[i] is a contiguous f32 column view): a
    # single cast-on-copy per column INTO the block replaces the old
    # astype(float32, copy=True)-then-mask double materialization, and
    # the finite mask is one vectorized pass over the whole block
    block = np.empty((len(scalar_cols), frame.num_rows), dtype=np.float32)
    for i, name in enumerate(scalar_cols):
        np.copyto(block[i], frame[name], casting="unsafe")
    finite = np.isfinite(block)
    if handle_invalid == "zero":
        block[~finite] = 0.0
        bad_mask = np.zeros(frame.num_rows, dtype=bool)
    else:
        bad_mask = ~finite.all(axis=0)
    scalar_index = {name: i for i, name in enumerate(scalar_cols)}
    for name in feature_cols:  # original column order preserved
        i = scalar_index.get(name)
        if i is not None:
            cleaned[name] = block[i]
            continue
        # rare non-scalar feature column (already-assembled vectors):
        # legacy per-column treatment
        col = frame[name].astype(np.float32, copy=True)
        invalid = ~np.isfinite(col)
        if invalid.any():
            if handle_invalid == "drop":
                bad_mask = bad_mask | invalid.any(axis=1)
            else:
                col[invalid] = 0.0
        cleaned[name] = col
    if label_col in frame:
        labels = frame[label_col]
        cleaned[label_col] = np.array(
            [normalize_label(str(l)) for l in labels], dtype=object
        )
    out = Frame(cleaned)
    if handle_invalid == "drop" and bad_mask.any():
        out = out.filter(~bad_mask)
    return out


def cache_parquet(frame: Frame, path: str) -> str:
    """Write a cleaned Frame to Parquet (zstd) — the fast-reload cache."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    pq.write_table(frame.to_arrow(), path, compression="zstd")
    return path


def load_parquet(path: str, memory_map: bool = True) -> Frame:
    """Reload a cached Frame.  ``memory_map=True`` (default) maps the
    file instead of buffering it — uncompressed column pages then land
    as views over the page cache, the zero-copy reload path the
    columnar plane (``data/pipeline.py``) expects."""
    return Frame.from_arrow(pq.read_table(path, memory_map=memory_map))
