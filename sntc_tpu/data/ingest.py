"""CICIDS2017 ingest + cleaning — the Spark CSV-source analog.

Replaces ``spark.read.csv(..., inferSchema)`` + the app's cleaning pass
(SURVEY.md §2.1): pyarrow's C++ CSV reader is the host data plane (the
sanctioned native layer, SURVEY.md §2.7), column names are whitespace-
normalized so real day CSVs load unchanged, ``Infinity``/``NaN`` rows in the
rate features are dropped (or zero-imputed), and labels are canonicalized.
A Parquet cache avoids re-parsing CSVs across runs.
"""

from __future__ import annotations

import glob
import os
from typing import List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as pq

from sntc_tpu.core.frame import Frame
from sntc_tpu.data.schema import (
    LABEL_COLUMN,
    normalize_feature_name,
    normalize_label,
)


def load_csv(path: str) -> Frame:
    """Read one flow CSV with pyarrow, normalizing column names."""
    table = pacsv.read_csv(
        path,
        convert_options=pacsv.ConvertOptions(
            # the raw files spell missing/infinite rates several ways
            null_values=["", "NaN", "nan"],
        ),
    )
    names = [normalize_feature_name(c) for c in table.column_names]
    # Real MachineLearningCVE day files contain 'Fwd Header Length' TWICE;
    # pandas-style dedup (second copy -> '.1') matches the schema's
    # 'Fwd Header Length.1' so real files drop in unchanged.
    seen: dict = {}
    deduped = []
    for n in names:
        if n in seen:
            seen[n] += 1
            deduped.append(f"{n}.{seen[n]}")
        else:
            seen[n] = 0
            deduped.append(n)
    table = table.rename_columns(deduped)
    return Frame.from_arrow(table)


def load_csv_dir(
    path: str, pattern: str = "*.csv", max_workers: int = 8
) -> Frame:
    """Read and concatenate all day CSVs in a directory (the all-days config
    [B:10] loads 8 files).  Files parse in a small thread pool —
    pyarrow's C++ CSV reader releases the GIL, so day files parse in
    parallel — but concatenate in sorted-filename order, byte-identical
    to the serial read."""
    paths = sorted(glob.glob(os.path.join(path, pattern)))
    if not paths:
        raise FileNotFoundError(f"no {pattern} files under {path}")
    if len(paths) == 1 or max_workers <= 1:
        return Frame.concat_all([load_csv(p) for p in paths])
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(
        max_workers=min(max_workers, len(paths))
    ) as pool:
        # executor.map preserves input order regardless of completion order
        frames = list(pool.map(load_csv, paths))
    return Frame.concat_all(frames)


def clean_flows(
    frame: Frame,
    label_col: str = LABEL_COLUMN,
    handle_invalid: str = "drop",
) -> Frame:
    """Clean a raw flow Frame:

    * coerce every feature column to float32,
    * ``±Infinity -> NaN``, then drop rows with any NaN (``handle_invalid=
      "drop"``, the common treatment of CICIDS2017) or zero-impute
      (``"zero"``),
    * canonicalize label strings (strip + mojibake aliases).
    """
    if handle_invalid not in ("drop", "zero"):
        raise ValueError("handle_invalid must be 'drop' or 'zero'")
    feature_cols = [c for c in frame.columns if c != label_col]
    cleaned = {}
    bad_mask = np.zeros(frame.num_rows, dtype=bool)
    for name in feature_cols:
        col = frame[name].astype(np.float32, copy=True)
        invalid = ~np.isfinite(col)
        if invalid.any():
            if handle_invalid == "drop":
                bad_mask |= invalid
            else:
                col[invalid] = 0.0
        cleaned[name] = col
    if label_col in frame:
        labels = frame[label_col]
        cleaned[label_col] = np.array(
            [normalize_label(str(l)) for l in labels], dtype=object
        )
    out = Frame(cleaned)
    if handle_invalid == "drop" and bad_mask.any():
        out = out.filter(~bad_mask)
    return out


def cache_parquet(frame: Frame, path: str) -> str:
    """Write a cleaned Frame to Parquet (zstd) — the fast-reload cache."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    pq.write_table(frame.to_arrow(), path, compression="zstd")
    return path


def load_parquet(path: str) -> Frame:
    return Frame.from_arrow(pq.read_table(path))
