"""sntc_tpu — TPU-native network-traffic classification framework.

A brand-new, TPU-first (JAX/XLA/pjit) framework with the capabilities of
``biagiom/spark-network-traffic-classifier`` (see SURVEY.md): an
Estimator/Transformer/Pipeline API over a pyarrow/numpy host data plane whose
estimator ``.fit()`` inner loops run as JAX/XLA kernels on TPU, with Spark's
partition-data-parallel ``treeAggregate`` replaced by SPMD ``psum`` reductions
over the ICI mesh (SURVEY.md §1, §5.8).

Package map (SURVEY.md §7.0):
  core/        Params system, Frame columnar container, Pipeline/Estimator base
  parallel/    device mesh, SPMD collectives (the treeAggregate analog)
  data/        CICIDS2017 ingest + cleaning, synthetic generator, batching
  feature/     StringIndexer, VectorAssembler, StandardScaler, ChiSqSelector
  ops/         device kernels: binned histograms, segment reductions
  models/      LogisticRegression, MLP, RandomForest, GBT, OneVsRest
  evaluation/  MulticlassMetrics (macro/weighted F1), BinaryClassificationEvaluator
  tuning/      ParamGridBuilder, CrossValidator, TrainValidationSplit
  mlio/        model save/load manifests
  serve/       Arrow batch-predict bridge, micro-batch streaming inference
               with offset/commit exactly-once resume
  utils/       structured JSONL metrics logging, profiling hooks
"""

__version__ = "0.1.0"

from sntc_tpu.core.frame import Frame
from sntc_tpu.core.base import (
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    Transformer,
)
from sntc_tpu.core.params import Param, Params

__all__ = [
    "Frame",
    "Estimator",
    "Transformer",
    "Model",
    "Pipeline",
    "PipelineModel",
    "Param",
    "Params",
    "__version__",
]
