"""Deterministic fault injection — named sites, armable by tests or env.

Every external-world boundary in the framework calls
``fault_point("<site>")`` before doing its real work.  Unarmed, that is
a dictionary miss — effectively free.  Armed (programmatically via
:func:`arm` or through the ``SNTC_FAULTS`` env knob), the point raises a
typed :class:`InjectedFault` on a deterministic schedule, so every retry
/ quarantine / fallback path in the codebase is exercisable in tier-1
CPU tests without real hardware failures.

Wired sites:

======================  =====================================================
``stream.wal``          ``StreamingQuery`` before the intent WAL write
``stream.read``         ``StreamingQuery`` micro-batch source read
``stream.commit``       ``StreamingQuery`` after sink delivery, before commit
``sink.write``          ``StreamingQuery`` sink delivery (per batch)
``source.parse``        byte-level parse boundaries (CSV/pcap/netflow) —
                        a :func:`fault_data` site taking the DATA kinds
``ckpt.save``           ``mlio.save_model`` (before the atomic publish)
``ckpt.load``           ``mlio.load_model`` (before manifest verification)
``probe.init``          ``utils.backend_probe`` backend-liveness attempt
``collective.dispatch`` ``parallel.collectives`` aggregate dispatch
``cv.fit``              ``CrossValidator`` per-(fold, grid-point) fit
``model.publish``       ``lifecycle.ModelPromoter`` before the candidate
                        checkpoint publish
``model.swap``          ``lifecycle`` promotion: post-publish/pre-swap
                        (first call) and post-swap (second call)
``flow.emit``           ``flow.FlowCaptureSource`` after window state
                        mutated, before the emitted batch is returned
``flow.evict``          ``flow.FlowFeatureEngine`` eviction pass, before
                        completed windows leave the keyed state
``flow.state_snapshot`` ``flow.FlowStateStore`` before a state snapshot
                        reaches disk
``ctl.apply``           ``serve.ServeController`` inside every live knob
                        setter, after the decision cleared the guardrails
                        and before the knob actually moves
``storage.wal``         physical WAL writes (append-log lines, files-mode
                        intent/commit json, compaction checkpoints) — a
                        :func:`fault_disk` site taking the IO kinds
``storage.journal``     every JSONL journal append (shed / controller /
                        promotion / dead-letter / repair journals)
``storage.dead_letter`` dead-letter evidence dumps (poison-batch CSVs,
                        row-level reject journals)
``storage.marker``      atomic marker/status writes (drain marker, health
                        dumps, model marker, metrics snapshots)
``storage.state``       flow-state snapshot blob writes (the physical
                        side of ``flow.state_snapshot``)
``predict.compile``     ``BatchPredictor`` before a FRESH row shape's
                        dispatch (the predict-program compile) — takes
                        the DEVICE kinds
``fuse.compile``        ``fuse.FusedSegment`` before a fresh input
                        signature compiles its fused XLA program
``device.dispatch``     ``BatchPredictor`` before every device dispatch
``kernel.compile``      ``kernels.registry`` before a FRESH
                        (kernel, signature) compiles its Pallas kernel —
                        a ``compile_error`` here poisons exactly that
                        kernel signature onto the XLA twin path
``fleet.lease``         ``serve.fleet`` worker lease renewal, before the
                        heartbeat marker reaches the coordinator root
``fleet.assign``        ``serve.fleet`` coordinator assignment publish
                        (epoch marker + assignment journal append)
``fleet.migrate``       ``serve.fleet`` tenant migration mid-ship, after
                        the source drain and before the sealed manifest
                        lands at the destination
``ingress.recv``        ``serve.ingress`` listener receive boundary —
                        also a :func:`fault_data` site taking the DATA
                        kinds (corrupt/truncated datagrams)
``ingress.spool``       ``serve.ingress`` capture-file seal, before the
                        atomic publish — a :func:`fault_disk` site
                        taking the IO kinds
``mesh.resize``         ``parallel.collectives`` elastic mesh resize,
                        after a ``device_lost`` classified and before
                        the data axis shrinks onto the survivors
======================  =====================================================

Env grammar (comma-separated specs)::

    SNTC_FAULTS=site[:kind[:prob[:seed]]][,site2:...]

``kind`` is ``exc`` (RuntimeError), ``io`` (OSError), ``timeout``
(TimeoutError), ``kill`` (``os._exit`` — the chaos-harness process
crash), a DATA kind — ``corrupt_bytes``/``truncate``/``ragged`` —
which mutates the payload at a :func:`fault_data` site instead of
raising, or a DEVICE kind —
``device_oom``/``compile_error``/``device_lost`` — which raises an
:class:`InjectedDeviceFault` whose message replicates the matching
XlaRuntimeError shape; ``prob`` in [0, 1] is evaluated per call with a
generator seeded by ``seed`` — the same env string yields the same
fault sequence in every run.  Example: arm the sink to fail ~30% of
writes deterministically::

    SNTC_FAULTS=sink.write:io:0.3:7

Programmatic arming adds Nth-call precision: ``arm("sink.write",
after=2, times=1)`` raises on exactly the 3rd call.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from sntc_tpu.resilience.policy import emit_event


class InjectedFault(RuntimeError):
    """Base class of every injected fault (never raised by real code)."""


class InjectedIOFault(InjectedFault, OSError):
    pass


class InjectedTimeoutFault(InjectedFault, TimeoutError):
    pass


class InjectedDiskFault(InjectedIOFault):
    """An injected *disk* failure (r17): an OSError whose ``errno`` is
    the real ENOSPC/EIO code, so ``except OSError`` handlers and
    errno-keyed failure policies treat it exactly like the genuine
    article."""

    def __init__(self, errno_code: int, msg: str):
        super().__init__(errno_code, msg)
        self.errno = errno_code


class InjectedDeviceFault(InjectedFault):
    """An injected *device/XLA-runtime* failure (r18): the message
    mimics the real ``XlaRuntimeError`` status shapes
    (``RESOURCE_EXHAUSTED: Out of memory ...``, ``INTERNAL: during
    XLA compilation ...``, ``UNAVAILABLE: device lost ...``) so
    :func:`sntc_tpu.resilience.device.classify_device_error` treats
    injected and genuine device faults identically — the compute-plane
    response ladder is exercisable without real hardware."""

    def __init__(self, msg: str, kind: str):
        super().__init__(msg)
        self.device_kind = kind


_KINDS = {
    "exc": InjectedFault,
    "io": InjectedIOFault,
    "timeout": InjectedTimeoutFault,
}

# ``kill`` is the chaos-harness kind: instead of raising, the armed
# site hard-exits the process (``os._exit``, skipping every handler and
# atexit hook — a real crash, not an exception) so crash-consistency
# tests can kill a forked engine at an exact protocol boundary.
KILL_KIND = "kill"
KILL_EXIT_CODE = 137

# Data-corruption kinds: instead of raising, an armed DATA kind mutates
# the bytes flowing through a :func:`fault_data` site (``source.parse``)
# on the same deterministic schedule — the corrupt-input chaos analog
# of ``kill``.  ``corrupt_bytes`` overwrites a few bytes with seeded
# garbage, ``truncate`` drops a seeded-length tail (a partial write /
# torn capture), ``ragged`` splices an extra delimited field into one
# line (the classic ragged-CSV row).  A data kind armed at a plain
# ``fault_point`` site is inert, and vice versa.
DATA_KINDS = ("corrupt_bytes", "truncate", "ragged")

# IO/disk kinds (r17): the storage survival plane's fault vocabulary.
# ``enospc`` and ``io_error`` raise :class:`InjectedDiskFault` — an
# OSError carrying the real errno (ENOSPC / EIO) — at any armed
# :func:`fault_point` OR :func:`fault_disk` site, modeling a full or
# failing disk at a durable write boundary.  ``torn_write`` only fires
# at :func:`fault_disk` sites (the storage plane's physical write
# helpers): the helper writes a seeded PREFIX of the payload, flushes
# it, and then raises — exactly what a crash mid-``write(2)`` leaves
# behind, so torn-tail repair paths are exercisable without a real
# kill.  ``torn_write`` armed at a plain ``fault_point`` is inert.
IO_KINDS = ("enospc", "io_error", "torn_write")

# DEVICE kinds (r18): the compute-plane fault domain's vocabulary.
# Each raises :class:`InjectedDeviceFault` whose MESSAGE replicates the
# XlaRuntimeError status shape the real backend produces (so the
# classifier in ``resilience/device.py`` cannot tell them apart):
# ``device_oom`` = RESOURCE_EXHAUSTED allocation failure, the per-batch
# OOM the dispatch splitter responds to; ``compile_error`` = a failed
# XLA compilation, the per-signature poisoning trigger;
# ``device_lost`` = the backend disappeared mid-run (tunnel drop,
# preemption), the HOST_DEGRADED trigger.  Armable at the compute
# sites ``predict.compile`` / ``fuse.compile`` / ``device.dispatch``.
DEVICE_KINDS = ("device_oom", "compile_error", "device_lost")

#: every kind the SNTC_FAULTS grammar accepts (docs/RESILIENCE.md keeps
#: a matching marker-delimited table; scripts/check_fault_sites.py
#: fails tier-1 when the two drift)
ALL_KINDS = (
    tuple(sorted(_KINDS)) + (KILL_KIND,) + DATA_KINDS + IO_KINDS
    + DEVICE_KINDS
)

# the documented wired sites (arming others is allowed — custom call
# sites can declare their own — but a typo'd WIRED site should be loud)
SITES = (
    "stream.wal",
    "stream.read",
    "stream.commit",
    "sink.write",
    "source.parse",
    "ckpt.save",
    "ckpt.load",
    "probe.init",
    "collective.dispatch",
    "cv.fit",
    "model.publish",
    "model.swap",
    "flow.emit",
    "flow.evict",
    "flow.state_snapshot",
    "ctl.apply",
    # durable-storage survival plane (r17): the PHYSICAL write
    # boundaries behind the logical protocol sites above — one
    # fault_disk site per durable artifact class, so an ENOSPC sweep
    # can hit every byte that reaches disk (docs/RESILIENCE.md
    # "Durable storage lifecycle" maps artifact -> site -> policy)
    "storage.wal",
    "storage.journal",
    "storage.dead_letter",
    "storage.marker",
    "storage.state",
    # compute-plane fault domain (r18): the DEVICE boundaries —
    # ``predict.compile`` fires on a FRESH dispatched row shape (the
    # predict program compile), ``fuse.compile`` on a fresh FusedSegment
    # input signature (the fused XLA program compile), and
    # ``device.dispatch`` on every device dispatch.  DEVICE kinds armed
    # here raise realistic XlaRuntimeError shapes; the response ladder
    # (OOM split / signature poison / HOST_DEGRADED) lives in
    # ``resilience/device.py`` — see docs/RESILIENCE.md "Compute-plane
    # fault domain".
    "predict.compile",
    "fuse.compile",
    "device.dispatch",
    # serving-kernel forge (r21): ``kernel.compile`` fires before a
    # FRESH (kernel, signature) compiles its hand-written Pallas kernel
    # (host-level or inside a fused trace).  A ``compile_error`` armed
    # here exercises the kernel poison ladder: exactly that kernel
    # signature falls back to its lowered-jnp twin on the XLA path —
    # never a tenant strike, never a quarantine.  See
    # docs/RESILIENCE.md "Kernel forge".
    "kernel.compile",
    # elastic serve fleet (r19): the COORDINATION boundaries of the
    # multi-process serve plane — ``fleet.lease`` before a worker's
    # lease/heartbeat marker is renewed, ``fleet.assign`` before the
    # coordinator publishes an assignment epoch, ``fleet.migrate``
    # mid-ship of a tenant's state tree (after the source drain,
    # before the sealed manifest lands).  A ``kill`` armed here is the
    # worker-crash / torn-migration chaos scenario; see
    # docs/RESILIENCE.md "Elastic serve fleet".
    "fleet.lease",
    "fleet.assign",
    "fleet.migrate",
    # live network front door (r20): the socket-ingress boundaries —
    # ``ingress.recv`` at the listener receive path (DATA kinds corrupt
    # the datagram/frame exactly like ``source.parse``; a ``kill`` here
    # crashes mid-receive, before anything reached the spool) and
    # ``ingress.spool`` at the capture-file seal (IO kinds model a
    # full/failing spool disk — the artifact's SHED policy counts the
    # loss instead of dying; a ``kill`` is the kill-mid-spool chaos
    # scenario).  See docs/RESILIENCE.md "Network ingress".
    "ingress.recv",
    "ingress.spool",
    # mesh substrate (r22): ``mesh.resize`` fires inside the collective
    # layer's elastic response, after a ``device_lost`` classified but
    # before the data axis shrinks and the batch re-places on the
    # survivors — arming it exercises a resize that itself dies (the
    # double-fault path falls through to the caller / host domain).
    # See docs/RESILIENCE.md "Mesh substrate".
    "mesh.resize",
    # warm-standby disaster recovery (r23): the REPLICATION boundaries
    # of the standby plane — ``repl.ship`` before each changed artifact
    # file is copied into the replica tree, ``repl.apply`` before the
    # sealed replica manifest publishes (the point where the ship
    # becomes visible), ``repl.barrier`` before a commit-barrier record
    # is appended to the replicated barrier log.  A ``kill`` armed here
    # is the torn-ship / torn-barrier chaos scenario: the replica must
    # converge bitwise on restart and a half-shipped file must
    # quarantine, never promote.  IO kinds degrade (counted, journaled)
    # — replication failures never fail the serving engine.  See
    # docs/RESILIENCE.md "Disaster recovery".
    "repl.ship",
    "repl.apply",
    "repl.barrier",
)


@dataclass
class _Armed:
    site: str
    kind: str = "exc"
    prob: float = 1.0
    seed: int = 0
    after: int = 0  # calls to let through before fault logic starts
    times: Optional[int] = None  # max faults to raise; None = unlimited
    from_env: bool = False
    calls: int = 0
    raised: int = 0
    rng: np.random.Generator = field(default=None)  # type: ignore[assignment]

    def __post_init__(self):
        if self.kind not in ALL_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; expected one of "
                f"{list(ALL_KINDS)}"
            )
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"fault prob must lie in [0, 1], got {self.prob}")
        self.rng = np.random.default_rng(self.seed)

    def decide(self) -> bool:
        """Called under the registry lock, once per fault_point hit."""
        self.calls += 1
        if self.calls <= self.after:
            return False
        if self.times is not None and self.raised >= self.times:
            return False
        # consume one deterministic draw per eligible call, so the
        # fault sequence depends only on (seed, call index)
        fire = (
            self.prob >= 1.0 or float(self.rng.uniform()) < self.prob
        )
        if fire:
            self.raised += 1
        return fire


_registry: Dict[str, _Armed] = {}
_lock = threading.Lock()
_env_installed: Optional[str] = None


def arm(
    site: str,
    kind: str = "exc",
    prob: float = 1.0,
    seed: int = 0,
    *,
    after: int = 0,
    times: Optional[int] = 1,
    _from_env: bool = False,
) -> None:
    """Arm ``site``; default raises on the next call, exactly once."""
    spec = _Armed(
        site=site, kind=kind, prob=prob, seed=seed, after=after,
        times=times, from_env=_from_env,
    )
    with _lock:
        _registry[site] = spec


def disarm(site: str) -> None:
    with _lock:
        _registry.pop(site, None)


def clear() -> None:
    """Drop every armed fault (programmatic AND env-installed; the env
    string is re-installed on the next fault_point if still set)."""
    global _env_installed
    with _lock:
        _registry.clear()
        _env_installed = None


def call_count(site: str) -> int:
    with _lock:
        spec = _registry.get(site)
        return spec.calls if spec else 0


def parse_faults_env(raw: str) -> list:
    """Parse the ``SNTC_FAULTS`` grammar into arm() argument dicts.

    Every grammar failure raises a ``ValueError`` that NAMES the
    offending comma-separated segment and says which field broke —
    wrong arity, empty site, unknown kind, non-numeric or out-of-range
    prob, non-integer seed — never a bare unpack/conversion error."""
    out = []
    for chunk in raw.split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        parts = chunk.split(":")
        if len(parts) > 4:
            raise ValueError(
                f"malformed SNTC_FAULTS spec {chunk!r}: expected at most "
                f"4 ':'-separated fields (site[:kind[:prob[:seed]]]), "
                f"got {len(parts)}"
            )
        if not parts[0]:
            raise ValueError(
                f"malformed SNTC_FAULTS spec {chunk!r}: empty site name"
            )
        spec = {"site": parts[0]}
        if len(parts) > 1:
            if parts[1] not in ALL_KINDS:
                raise ValueError(
                    f"malformed SNTC_FAULTS spec {chunk!r}: unknown kind "
                    f"{parts[1]!r}; expected one of {list(ALL_KINDS)}"
                )
            spec["kind"] = parts[1]
        if len(parts) > 2:
            try:
                prob = float(parts[2])
            except ValueError:
                raise ValueError(
                    f"malformed SNTC_FAULTS spec {chunk!r}: prob "
                    f"{parts[2]!r} is not a float"
                ) from None
            if not 0.0 <= prob <= 1.0:
                raise ValueError(
                    f"malformed SNTC_FAULTS spec {chunk!r}: prob {prob} "
                    "must lie in [0, 1]"
                )
            spec["prob"] = prob
        if len(parts) > 3:
            try:
                spec["seed"] = int(parts[3])
            except ValueError:
                raise ValueError(
                    f"malformed SNTC_FAULTS spec {chunk!r}: seed "
                    f"{parts[3]!r} is not an int"
                ) from None
        out.append(spec)
    return out


def _sync_env() -> None:
    """(Re)install env-armed faults when SNTC_FAULTS changed; never
    touches programmatically armed sites.  A malformed string warns
    ONCE on stderr and arms nothing — raising from here would surface
    inside arbitrary fault_point call sites, where the retry/quarantine
    machinery would misclassify the config typo as a real site fault."""
    global _env_installed
    raw = os.environ.get("SNTC_FAULTS") or None
    if raw == _env_installed:
        return
    with _lock:
        for site in [s for s, a in _registry.items() if a.from_env]:
            del _registry[site]
    if raw:
        import sys

        try:
            specs = parse_faults_env(raw)
            for spec in specs:
                # env faults are probabilistic and unlimited — the knob
                # models an unreliable environment, not a one-shot test
                arm(times=None, _from_env=True, **spec)
        except ValueError as e:
            with _lock:
                for site in [
                    s for s, a in _registry.items() if a.from_env
                ]:
                    del _registry[site]
            print(
                f"sntc_tpu: ignoring malformed SNTC_FAULTS: {e}",
                file=sys.stderr,
            )
    _env_installed = raw


def _count_injection(site: str, kind: str) -> None:
    """Mirror one fired injection into the metrics plane (obs) — chaos
    evidence next to the production counters it perturbs.  Never fatal:
    the injection itself is the point, not its accounting."""
    try:
        from sntc_tpu.obs.metrics import inc

        inc("sntc_faults_injected_total", site=site, kind=kind)
    except Exception:
        pass


def fault_point(site: str, tenant: Optional[str] = None) -> None:
    """The per-site hook real code calls; raises when armed + scheduled.
    A spec armed with a DATA kind is inert here — byte corruption only
    makes sense where bytes flow (:func:`fault_data`).

    ``tenant`` (r12) checks the tenant-NAMESPACED site first —
    ``tenant/<id>/<site>`` — then falls back to the bare site, so
    multi-tenant chaos can arm one tenant's boundary
    (``SNTC_FAULTS=tenant/a/stream.wal:kill``) without touching its
    neighbors, while a bare-site fault still hits every tenant (the
    shared-environment failure mode)."""
    _sync_env()
    spec = None
    if tenant is not None:
        spec = _registry.get(f"tenant/{tenant}/{site}")
    if spec is None:
        spec = _registry.get(site)
    if spec is None or spec.kind in DATA_KINDS or spec.kind == "torn_write":
        return
    site = spec.site  # event/error name the ARMED site (namespaced)
    with _lock:
        fire = spec.decide()
        call = spec.calls
    if fire:
        _count_injection(site, spec.kind)
        emit_event(
            event="fault_injected", site=site, kind=spec.kind, call=call
        )
        if spec.kind == KILL_KIND:
            # hard crash, not an exception: no finally blocks, no WAL
            # flushes, no atexit — what a SIGKILL/OOM/preemption does
            os._exit(KILL_EXIT_CODE)
        if spec.kind in ("enospc", "io_error"):
            raise _disk_fault(spec.kind, site, call)
        if spec.kind in DEVICE_KINDS:
            raise _device_fault(spec.kind, site, call)
        raise _KINDS[spec.kind](
            f"injected {spec.kind} fault at site {site!r} (call {call})"
        )


def _disk_fault(kind: str, site: str, call: int) -> "InjectedDiskFault":
    import errno as _errno

    code = _errno.ENOSPC if kind == "enospc" else _errno.EIO
    return InjectedDiskFault(
        code,
        f"injected {kind} fault at site {site!r} (call {call})",
    )


def _device_fault(kind: str, site: str, call: int) -> "InjectedDeviceFault":
    """The message replicates the real XlaRuntimeError status line for
    the kind, so ``classify_device_error`` exercises the SAME pattern
    match genuine backend failures would hit."""
    if kind == "device_oom":
        msg = (
            "RESOURCE_EXHAUSTED: Out of memory while trying to "
            "allocate 1073741824 bytes. "
            f"[injected device_oom at site {site!r} (call {call})]"
        )
    elif kind == "compile_error":
        msg = (
            "INTERNAL: during XLA compilation: injected compile_error "
            f"at site {site!r} (call {call})"
        )
    else:  # device_lost
        msg = (
            "UNAVAILABLE: device lost: backend restarted "
            f"[injected device_lost at site {site!r} (call {call})]"
        )
    return InjectedDeviceFault(msg, kind)


def fault_disk(site: str, tenant: Optional[str] = None) -> Optional[float]:
    """The physical-write hook the storage plane's helpers call before
    bytes reach disk (``storage.*`` sites).  Unarmed — or armed with a
    non-IO kind — it returns None.  Armed with ``enospc``/``io_error``
    it raises :class:`InjectedDiskFault` (nothing was written, the
    full-disk shape).  Armed with ``torn_write`` it returns a seeded
    fraction in (0, 1): the CALLER writes that prefix of its payload,
    flushes it, and raises — so the injected failure leaves exactly the
    torn tail a crash mid-``write(2)`` would, for the repair paths to
    find.  Same tenant-namespaced lookup as :func:`fault_point`."""
    _sync_env()
    spec = None
    if tenant is not None:
        spec = _registry.get(f"tenant/{tenant}/{site}")
    if spec is None:
        spec = _registry.get(site)
    if spec is None or spec.kind not in IO_KINDS:
        return None
    site = spec.site
    with _lock:
        fire = spec.decide()
        call = spec.calls
        torn = float(spec.rng.uniform(0.2, 0.8)) if fire else 0.0
    if not fire:
        return None
    _count_injection(site, spec.kind)
    emit_event(
        event="fault_injected", site=site, kind=spec.kind, call=call
    )
    if spec.kind == "torn_write":
        return torn
    raise _disk_fault(spec.kind, site, call)


def _mutate(kind: str, data: bytes, draws: "np.ndarray") -> bytes:
    """Apply one deterministic corruption to ``data``.  ``draws`` is a
    flat vector of uniform [0, 1) floats consumed positionally, so the
    mutation depends only on (seed, call index, payload length)."""
    n = len(data)
    if n == 0:
        return data
    if kind == "truncate":
        # keep a strict prefix: the torn-write / partial-capture shape
        return data[: int(draws[0] * n)]
    if kind == "corrupt_bytes":
        buf = bytearray(data)
        k = max(1, n // 64)
        for i in range(k):
            pos = int(draws[2 * i] * n)
            buf[pos] = int(draws[2 * i + 1] * 256) % 256
        return bytes(buf)
    # ragged: splice an extra delimited field into one line.  Pick a
    # DATA line when the payload is line-structured (never the header);
    # otherwise splice at a raw offset — for binary payloads this is
    # mid-stream junk, the framing analog of a ragged row.
    lines = data.split(b"\n")
    if len(lines) > 2:
        li = 1 + int(draws[0] * max(1, len(lines) - 2))
        lines[li] = lines[li] + b",__sntc_ragged__"
        return b"\n".join(lines)
    pos = int(draws[0] * n)
    return data[:pos] + b",__sntc_ragged__," + data[pos:]


def data_fault_armed(site: str) -> bool:
    """True when a DATA kind is armed at ``site`` — callers that would
    have to buffer a whole payload just to route it through
    :func:`fault_data` (e.g. a CSV reader that otherwise streams from
    the path) check this first and skip the buffering when unarmed."""
    _sync_env()
    spec = _registry.get(site)
    return spec is not None and spec.kind in DATA_KINDS


def fault_data(site: str, data: bytes) -> bytes:
    """The byte-corruption hook parse boundaries call on their raw
    input (``source.parse``).  Unarmed — or armed with a non-DATA
    kind — it returns ``data`` untouched; armed with ``corrupt_bytes``
    / ``truncate`` / ``ragged`` it deterministically mutates the
    payload.

    Unlike :func:`fault_point`, the fire decision and the mutation
    randomness derive from ``(seed, payload bytes)`` — NOT from the
    shared call-order rng — because parse sites run on reader/prefetch
    threads whose interleaving varies run to run: the same corpus under
    the same ``SNTC_FAULTS`` string corrupts the same payloads the same
    way regardless of reader concurrency.  ``after``/``times`` are
    still honored (bookkept under the registry lock), but which
    payloads they gate depends on arrival order — use ``prob`` for
    reproducible multi-threaded chaos."""
    import zlib

    _sync_env()
    spec = _registry.get(site)
    if spec is None or spec.kind not in DATA_KINDS:
        return data
    with _lock:
        spec.calls += 1
        call = spec.calls
        if call <= spec.after or (
            spec.times is not None and spec.raised >= spec.times
        ):
            return data
    rng = np.random.default_rng(
        [spec.seed, zlib.crc32(data), len(data)]
    )
    if not (spec.prob >= 1.0 or float(rng.uniform()) < spec.prob):
        return data
    with _lock:
        spec.raised += 1
    draws = rng.uniform(size=2 * max(1, len(data) // 64))
    mutated = _mutate(spec.kind, data, draws)
    _count_injection(site, spec.kind)
    emit_event(
        event="fault_injected", site=site, kind=spec.kind, call=call,
        bytes_in=len(data), bytes_out=len(mutated),
    )
    return mutated
