"""Health aggregation and the batch watchdog.

:class:`HealthMonitor` keeps one :class:`HealthState` per named
component (``engine``, ``sink.write``, ``collective.dispatch``, ...),
fed two ways:

* **explicitly** — ``health.report("engine", HealthState.DEGRADED,
  "backlog over limit")``;
* **from the structured event stream** — ``health.attach()``
  subscribes to :func:`sntc_tpu.resilience.emit_event`, mapping the
  resilience vocabulary to states (``retry`` → DEGRADED,
  ``retry_exhausted``/``quarantine``/``breaker_open`` → UNHEALTHY,
  ``retry_success``/``breaker_closed`` → OK, ...), so every wired
  site's health tracks automatically.

State changes themselves emit ``health_changed`` events, making
transitions observable in the same JSONL stream.  :meth:`overall`
returns the worst component state — the single value ``--health-json``
and the supervisor act on.

Recovery is evidence-driven, which means it needs a recovery SIGNAL: a
mapped OK event (``retry_success``, ``breaker_closed``), an explicit
:meth:`report`, or — for the serving-path sites — the supervisor's
clean-commit reset.  Components outside the serving loop
(``collective.dispatch``, ``ckpt.save``, ``cv.fit``) only recover when
their own site next emits, because a plain first-attempt success emits
nothing; treat a long-stale UNHEALTHY there as "last observed
evidence", not a live probe.

The **watchdog** flags a wedged batch: the engine (or supervisor)
calls :meth:`batch_started` / :meth:`batch_finished` around each
micro-batch; :meth:`check_watchdog` compares the in-flight batch's age
on the monitor's injectable clock against ``max_batch_wall_time`` and,
on breach, marks the engine UNHEALTHY and emits a ``watchdog_stall``
event (once per stalled batch).  Poll it from any thread — the
supervisor runs a daemon heartbeat thread so a batch that wedges the
engine loop still trips the alarm.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from sntc_tpu.resilience.policy import (
    add_event_observer,
    emit_event,
    remove_event_observer,
)


class HealthState(enum.IntEnum):
    """Ordered severity: max() over components is the overall state."""

    OK = 0
    DEGRADED = 1
    UNHEALTHY = 2


# event name -> state it implies for the component that emitted it
_EVENT_STATES: Dict[str, HealthState] = {
    "retry": HealthState.DEGRADED,
    "retry_success": HealthState.OK,
    "retry_exhausted": HealthState.UNHEALTHY,
    "quarantine": HealthState.UNHEALTHY,
    "ckpt_fallback": HealthState.DEGRADED,
    "cv_cell_degraded": HealthState.DEGRADED,
    "breaker_open": HealthState.UNHEALTHY,
    "breaker_half_open": HealthState.DEGRADED,
    "breaker_closed": HealthState.OK,
    "load_shed": HealthState.DEGRADED,
    "watchdog_stall": HealthState.UNHEALTHY,
    # data-plane admission (r10): rejected rows / torn captures mark the
    # SOURCE degraded — the query keeps serving the clean rows, but a
    # rising reject rate is operator-visible through the same stream
    "rows_rejected": HealthState.DEGRADED,
    "parse_truncated": HealthState.DEGRADED,
    # model lifecycle (r11): the drift monitor flips the model
    # component DEGRADED on a divergence breach; a completed hot-swap
    # is the recovery signal; a rollback records that the promoted
    # candidate misbehaved (the restored incumbent recovers it on the
    # next swap event); a lifecycle hook failure degrades, not kills
    "drift_detected": HealthState.DEGRADED,
    "model_swapped": HealthState.OK,
    "model_rollback": HealthState.DEGRADED,
    "lifecycle_error": HealthState.DEGRADED,
    # durable-storage survival plane (r17): a journal/marker that
    # cannot write degrades (records buffer in memory, counted) and
    # recovers when the disk does; a breached disk budget is the same
    # operator-visible DEGRADED until usage falls back under it
    "storage_degraded": HealthState.DEGRADED,
    "storage_recovered": HealthState.OK,
    "disk_budget_exceeded": HealthState.DEGRADED,
    # compute-plane fault domain (r18): HOST_DEGRADED flips the model
    # component DEGRADED (serving continues on the host path — degraded,
    # not dead); the probe-gated recovery is the paired OK signal.
    # Individual device_fault / signature_poisoned events deliberately
    # do NOT map: they carry a site, would create a component with no
    # recovery signal, and the response ladder already absorbed them —
    # their evidence lives in the sntc_device_* series instead.
    "device_degraded": HealthState.DEGRADED,
    "device_recovered": HealthState.OK,
}


class HealthMonitor:
    """Per-component health registry + heartbeat watchdog (thread-safe,
    injectable clock)."""

    def __init__(
        self,
        *,
        max_batch_wall_time: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._clock = clock
        self.max_batch_wall_time = max_batch_wall_time
        self._lock = threading.RLock()
        self._components: Dict[str, Dict[str, Any]] = {}
        self._inflight: Dict[int, float] = {}  # batch_id -> started_at
        self._stalled_flagged: set = set()
        self._observer = None

    # -- component states ---------------------------------------------------

    def report(
        self, component: str, state: HealthState, reason: str = ""
    ) -> None:
        """Set ``component``'s state; emits ``health_changed`` on
        change.  Every entry carries BOTH clocks: ``since`` on the
        monitor's (injectable, monotonic) clock for interval math, and
        ``since_wall`` on the wall clock so reports from different
        tenants/processes order on replay analysis."""
        state = HealthState(state)
        with self._lock:
            prev = self._components.get(component)
            changed = prev is None or prev["state"] != state
            self._components[component] = {
                "state": state,
                "reason": reason,
                "since": self._clock() if changed else prev["since"],
                "since_wall": (
                    time.time() if changed else prev["since_wall"]
                ),
            }
        if changed:
            try:  # the metrics plane tracks the live state per component
                from sntc_tpu.obs.metrics import set_gauge

                set_gauge(
                    "sntc_health_state", int(state), component=component
                )
            except Exception:
                pass
            emit_event(
                event="health_changed", component=component,
                state=state.name,
                previous=prev["state"].name if prev else None,
                reason=reason,
            )

    def state_of(self, component: str) -> HealthState:
        with self._lock:
            entry = self._components.get(component)
            return entry["state"] if entry else HealthState.OK

    def overall(self) -> HealthState:
        with self._lock:
            if not self._components:
                return HealthState.OK
            return max(e["state"] for e in self._components.values())

    def worst_under(self, prefix: str) -> HealthState:
        """Worst state among components whose name starts with
        ``prefix`` (OK when none match) — tenant-scoped health: the
        serve daemon namespaces every tenant site ``tenant/<id>/...``,
        so one tenant's aggregate is the worst of its own components
        and NOTHING of its neighbors'."""
        with self._lock:
            states = [
                e["state"]
                for name, e in self._components.items()
                if name.startswith(prefix)
            ]
            return max(states) if states else HealthState.OK

    def reset_under(self, prefix: str, reason: str = "") -> None:
        """Set every component under ``prefix`` back to OK (a tenant
        leaving quarantine on probation: its past evidence is served;
        fresh failures re-escalate on their own)."""
        with self._lock:
            names = [
                n for n in self._components if n.startswith(prefix)
            ]
        for name in names:
            self.report(name, HealthState.OK, reason=reason)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "overall": self.overall().name,
                "components": {
                    name: {
                        "state": e["state"].name,
                        "reason": e["reason"],
                        "since": e["since"],
                        "since_wall": e["since_wall"],
                    }
                    for name, e in sorted(self._components.items())
                },
            }

    # -- event-stream aggregation ------------------------------------------

    def observe_event(self, record: Dict[str, Any]) -> None:
        """Fold one structured event into component health (component =
        the event's ``site``, falling back to ``component``)."""
        state = _EVENT_STATES.get(record.get("event"))
        if state is None:
            return
        component = record.get("site") or record.get("component")
        if not component:
            return
        self.report(
            component, state,
            reason=f"event {record['event']}",
        )

    def attach(self) -> "HealthMonitor":
        """Subscribe to the process event stream (idempotent)."""
        if self._observer is None:
            self._observer = self.observe_event
            add_event_observer(self._observer)
        return self

    def detach(self) -> None:
        if self._observer is not None:
            remove_event_observer(self._observer)
            self._observer = None

    def close(self) -> None:
        """Monitor teardown: unsubscribe from the process event stream.
        Every component that ``attach()``es a monitor must call this
        (supervisor/daemon teardown does) — the observer list is
        process-global, so a leaked subscription outlives its monitor
        and keeps folding events into dead state forever.  Idempotent;
        a closed monitor still serves explicit :meth:`report` calls."""
        self.detach()

    # -- heartbeat watchdog -------------------------------------------------

    def batch_started(self, batch_id: int) -> None:
        """Idempotent: re-announcing a batch that is already in flight
        (a retirement round that deferred and retries next tick) keeps
        the ORIGINAL start time, so a batch stuck across many short
        ticks still ages toward ``max_batch_wall_time``."""
        with self._lock:
            self._inflight.setdefault(batch_id, self._clock())

    def batch_finished(self, batch_id: int) -> None:
        with self._lock:
            self._inflight.pop(batch_id, None)
            self._stalled_flagged.discard(batch_id)

    def check_watchdog(self) -> List[int]:
        """Flag in-flight batches older than ``max_batch_wall_time``;
        returns the batch ids NEWLY flagged this call (each stalled
        batch alarms once, not once per poll)."""
        if self.max_batch_wall_time is None:
            return []
        now = self._clock()
        newly = []
        with self._lock:
            for batch_id, started in self._inflight.items():
                age = now - started
                if (
                    age > self.max_batch_wall_time
                    and batch_id not in self._stalled_flagged
                ):
                    self._stalled_flagged.add(batch_id)
                    newly.append((batch_id, age))
        for batch_id, age in newly:
            emit_event(
                event="watchdog_stall", component="engine",
                batch_id=batch_id, age_s=round(age, 3),
                max_batch_wall_time=self.max_batch_wall_time,
            )
            self.report(
                "engine", HealthState.UNHEALTHY,
                reason=(
                    f"batch {batch_id} running {age:.1f}s > "
                    f"max_batch_wall_time={self.max_batch_wall_time}s"
                ),
            )
        return [b for b, _ in newly]
