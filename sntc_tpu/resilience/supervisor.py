"""Query supervision: admission control, watchdog, preemption-safe
drain.

:class:`QuerySupervisor` owns a ``StreamingQuery``'s engine loop the
way an operator would — it is the layer between "the engine can
retry" (PR 1 primitives) and "the query survives production":

* **Admission control / load shedding** — when the source backlog
  exceeds ``max_pending_batches`` micro-batches, the supervisor sheds
  before dispatching: policy ``"oldest"`` drops the oldest pending
  offsets outright (freshness wins — the Spark
  ``maxOffsetsPerTrigger``-backlog failure mode, resolved instead of
  ignored), policy ``"sample"`` processes the whole backlog as one
  row-subsampled batch (coverage wins, at reduced resolution).  Every
  shed is journaled to ``<checkpoint>/shed.jsonl`` and emitted as a
  ``load_shed`` event — shedding is a recorded decision, never silent
  data loss.
* **Health & watchdog** — a :class:`~sntc_tpu.resilience.health
  .HealthMonitor` (attached to the structured-event stream) aggregates
  per-site health; a daemon heartbeat thread trips
  ``watchdog_stall``/UNHEALTHY when a batch exceeds
  ``max_batch_wall_time`` even while the engine loop is wedged.
* **Preemption-safe drain** — SIGTERM (or :meth:`request_drain`)
  finishes the in-flight batches, commits them, writes an atomic
  ``drain_marker.json`` into the checkpoint dir, and returns cleanly
  (exit 0 from the CLI).  A restart on the same checkpoint resumes
  exactly-once from the offset log — drain is just the graceful
  version of the crash contract the WAL already guarantees.
* **Status** — :meth:`status` (and the ``--health-json`` CLI flag)
  dumps overall/component health, breaker states, engine offsets,
  backlog, and shed totals as one JSON object, rewritten atomically
  each tick.

The clock is injectable and the loop is steppable (:meth:`tick`), so
every behavior above is unit-testable without threads or sleeps.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Any, Dict, Optional

from sntc_tpu.resilience import storage as storage_plane
from sntc_tpu.resilience.circuit import CircuitBreaker, breakers_snapshot
from sntc_tpu.resilience.health import HealthMonitor, HealthState
from sntc_tpu.resilience.policy import emit_event, events_dropped

DRAIN_MARKER = "drain_marker.json"


def _atomic_json(path: str, obj: Dict[str, Any], **dump_kwargs: Any) -> str:
    """Write ``obj`` as JSON via tmp-then-rename: readers never see a
    torn file (the drain marker and health dump both promise this).
    Routed through the storage plane's marker writer (r17): the
    ``storage.marker`` fault site injects disk failures here, and the
    failure policy is DEGRADE — a status dump that cannot write counts
    a ``storage_degraded`` episode instead of killing the loop it
    reports on."""
    storage_plane.write_marker(
        path, obj, indent=dump_kwargs.get("indent"), fsync=False,
    )
    return path


def default_breakers(
    clock=time.monotonic, **kwargs: Any
) -> Dict[str, CircuitBreaker]:
    """The serving-path breaker set: sink delivery and model dispatch."""
    return {
        site: CircuitBreaker(site, clock=clock, **kwargs)
        for site in ("sink.write", "predict.dispatch")
    }


class QuerySupervisor:
    """Supervises one ``StreamingQuery`` (single-threaded loop owner)."""

    def __init__(
        self,
        query,
        *,
        max_pending_batches: Optional[int] = None,
        shed_policy: str = "oldest",
        max_batch_wall_time: Optional[float] = None,
        health: Optional[HealthMonitor] = None,
        health_json: Optional[str] = None,
        clock=time.monotonic,
        slo=None,
        controller_policy=None,
        disk_budget_mb: Optional[float] = None,
    ):
        if max_pending_batches is not None and max_pending_batches < 1:
            raise ValueError("max_pending_batches must be >= 1 (or None)")
        if shed_policy not in ("oldest", "sample"):
            raise ValueError("shed_policy must be 'oldest' or 'sample'")
        self.query = query
        self.max_pending_batches = max_pending_batches
        self.shed_policy = shed_policy
        self.health_json = health_json
        self._clock = clock
        # a monitor WE create is ours: attached to the event stream here
        # and detached in close().  A caller-supplied monitor keeps its
        # own subscription lifecycle — the caller decides whether it is
        # attach()ed, and close() must not pull it out from under them.
        self._owns_health = health is None
        self.health = health or HealthMonitor(
            max_batch_wall_time=max_batch_wall_time, clock=clock
        ).attach()
        if max_batch_wall_time is not None and health is not None:
            self.health.max_batch_wall_time = max_batch_wall_time
        self._drain = threading.Event()
        self._drain_reason: Optional[str] = None
        self.shed_total_offsets = 0
        self.batches_done = 0
        self.drained = False
        # durable-storage accounting (r17): per-tick throttled disk
        # measurement of the engine's checkpoint root into the
        # sntc_disk_* gauges, with an optional byte budget whose breach
        # emits disk_budget_exceeded (DEGRADED) — the "storage" block
        # of status()/--health-json
        self.storage = storage_plane.StoragePlane(
            query.checkpoint_dir,
            budget_bytes=(
                int(disk_budget_mb * (1 << 20))
                if disk_budget_mb else None
            ),
        )
        # closed-loop SLO control (r16): a declared SloPolicy arms a
        # ServeController over this one engine — it steers
        # pipeline_depth / shape_buckets / the shed knob and owns the
        # ingest tuner, journaling to <checkpoint>/controller.jsonl.
        # Imported lazily: the controller lives in the serve package,
        # which imports this module at its own load time.
        self.controller = None
        if slo is not None:
            from sntc_tpu.serve.controller import ServeController

            self.controller = ServeController.for_supervisor(
                self, slo, policy=controller_policy, clock=clock,
            )

    def close(self) -> None:
        """Supervisor teardown: detach the health monitor from the
        event stream IF this supervisor created it (a caller-supplied
        monitor's subscription belongs to the caller)."""
        if self._owns_health:
            self.health.close()

    # -- preemption ---------------------------------------------------------

    def request_drain(self, reason: str = "request_drain") -> None:
        """Ask the loop to finish in-flight work, commit, and stop."""
        if not self._drain.is_set():
            self._drain_reason = reason
            self._drain.set()

    @property
    def drain_requested(self) -> bool:
        return self._drain.is_set()

    def install_signal_handlers(self) -> bool:
        """Route SIGTERM to :meth:`request_drain` (preemption notice →
        graceful drain).  Returns False off the main thread, where
        CPython forbids installing handlers."""
        try:
            signal.signal(
                signal.SIGTERM,
                lambda signum, frame: self.request_drain("SIGTERM"),
            )
            return True
        except ValueError:
            return False

    # -- supervision steps --------------------------------------------------

    def maybe_shed(self, latest: Optional[int] = None) -> Optional[dict]:
        """One admission-control decision; returns the shed record when
        load was shed.  ``latest`` lets the loop reuse one per-tick
        source offset read."""
        if self.max_pending_batches is None:
            return None
        record = self.query.shed_backlog(
            self.max_pending_batches, policy=self.shed_policy,
            latest=latest,
        )
        if record is not None:
            self.shed_total_offsets += record.get("offsets_shed", 0)
            self.health.report(
                "engine", HealthState.DEGRADED,
                reason=f"load shed ({self.shed_policy}): "
                f"backlog > {self.max_pending_batches} batches",
            )
        return record

    def tick(self) -> int:
        """One supervised engine step: shed if needed, advance the
        engine by (at most) one committed batch, update health
        bookkeeping.  Returns batches committed this tick."""
        latest = self.query.source.latest_offset()  # ONE read per tick
        shed = self.maybe_shed(latest)
        tick_id = self.query.last_committed() + 1
        # watchdog-track the tick's batch only when there is actual work
        # (in-flight or unplanned backlog): an idle stream must not age
        # a phantom batch into a watchdog_stall.  started is idempotent:
        # a batch deferring across ticks (sink down, breaker open) keeps
        # its first start time and AGES toward max_batch_wall_time; it
        # leaves the watchdog only on commit.
        have_work = (
            self.query.in_flight_count() > 0
            or latest > self.query.planned_offset()
        )
        if have_work:
            self.health.batch_started(tick_id)
        before = self.query.last_committed()
        try:
            self.query._run_one_batch()
        finally:
            if self.query.last_committed() >= tick_id:
                self.health.batch_finished(tick_id)
        delta = self.query.last_committed() - before
        self.batches_done += delta
        # a committing engine is healthy — this also RECOVERS from a
        # past watchdog stall (the stalled batch evidently finished);
        # but a tick that also shed load stays DEGRADED, so sustained
        # overload is visible in health dumps, not only in the event
        # stream
        if delta and shed is None:
            self.health.report("engine", HealthState.OK, reason="committing")
            progress = self.query.lastProgress
            if progress and not progress.get("quarantined"):
                # a CLEAN commit traversed read → predict → sink: any
                # stage component a past failure left DEGRADED/UNHEALTHY
                # has demonstrably recovered (retry_success never fires
                # for first-attempt successes, so without this a single
                # quarantined batch would pin health UNHEALTHY forever)
                for site in (
                    "stream.read", "predict.dispatch", "sink.write"
                ):
                    if self.health.state_of(site) != HealthState.OK:
                        self.health.report(
                            site, HealthState.OK, reason="batch committed"
                        )
        if self.controller is not None:
            # degrade-never-kill, the lifecycle/autotune-tick contract
            try:
                self.controller.on_tick()
            except Exception as e:
                emit_event(event="controller_error", error=repr(e))
        if self.health_json:
            self.write_health_json(latest=latest)
        return delta

    def run(
        self,
        poll_interval: float = 1.0,
        max_batches: Optional[int] = None,
    ) -> Dict[str, Any]:
        """The supervised foreground loop (the ``query.run()`` analog).

        Runs until ``max_batches`` commits or a drain request; an idle
        tick waits ``poll_interval`` (interruptibly — a drain request
        cuts the wait short).  Returns the final :meth:`status` dict.
        """
        watchdog = self._start_watchdog()
        try:
            while not self._drain.is_set():
                delta = self.tick()
                if (
                    max_batches is not None
                    and self.batches_done >= max_batches
                ):
                    break
                if delta == 0:
                    self._drain.wait(poll_interval)
        finally:
            if watchdog is not None:
                watchdog["stop"].set()
                watchdog["thread"].join()
        if self._drain.is_set():
            self._do_drain()
        if self.health_json:
            self.write_health_json()
        return self.status()

    def _start_watchdog(self) -> Optional[dict]:
        """Daemon heartbeat poller: flags a wedged batch even while the
        engine loop thread is stuck inside it."""
        if self.health.max_batch_wall_time is None:
            return None
        stop = threading.Event()
        interval = max(0.05, self.health.max_batch_wall_time / 4.0)

        def _poll():
            while not stop.wait(interval):
                self.health.check_watchdog()

        t = threading.Thread(
            target=_poll, name="sntc-watchdog", daemon=True
        )
        t.start()
        return {"thread": t, "stop": stop}

    def drain_now(self, reason: str = "drain_now") -> Dict[str, Any]:
        """Drain synchronously (the non-loop entry: Ctrl-C handlers,
        tests) and return the final status."""
        self.request_drain(reason)
        self._do_drain()
        if self.health_json:
            self.write_health_json()
        return self.status()

    def _do_drain(self) -> None:
        """Finish in-flight batches, commit, write the drain marker."""
        if self.drained:
            return
        committed = self.query.drain()
        self.batches_done += committed
        marker = {
            "ts": time.time(),
            "reason": self._drain_reason,
            "last_committed": self.query.last_committed(),
            "end_offset": self.query.committed_end(),
            "batches_committed_at_drain": committed,
            "in_flight_left": self.query.in_flight_count(),
            "pid": os.getpid(),
            # final controller-steered knob state: a restart (cold
            # defaults) reads this to log the delta
            "controller_knobs": (
                self.controller.knob_values()
                if self.controller is not None else None
            ),
        }
        _atomic_json(
            os.path.join(self.query.checkpoint_dir, DRAIN_MARKER), marker
        )
        self.drained = True
        emit_event(
            event="drained", component="engine", reason=self._drain_reason,
            last_committed=marker["last_committed"],
            in_flight_left=marker["in_flight_left"],
        )
        self.query.stop()

    # -- status -------------------------------------------------------------

    def status(self, latest: Optional[int] = None) -> Dict[str, Any]:
        """Status snapshot; ``latest`` reuses a caller's source offset
        read instead of re-scanning the source per dump."""
        q = self.query
        breakers = {
            site: br.snapshot()
            for site, br in getattr(q, "breakers", {}).items()
        }
        # process-registry breakers (collective.dispatch &c.) ride along
        for site, snap in breakers_snapshot().items():
            breakers.setdefault(site, snap)
        out = {
            "health": self.health.snapshot(),
            "breakers": breakers,
            "engine": {
                "last_committed": q.last_committed(),
                "end_offset": q.committed_end(),
                "in_flight": q.in_flight_count(),
                "backlog_offsets": q.backlog_offsets(latest),
                "batches_done": self.batches_done,
            },
            "shed_total_offsets": self.shed_total_offsets,
            "events_dropped": events_dropped(),
            "drain_requested": self.drain_requested,
            "drained": self.drained,
        }
        # durable-storage lifecycle evidence (r17): engine-side bound
        # config + compaction/rotation counters, plus the throttled
        # disk-usage measurement and budget verdict for the root
        engine_storage = getattr(q, "storage_stats", None)
        out["storage"] = dict(
            engine_storage() if engine_storage is not None else {},
            disk=self.storage.status(),
        )
        # compute-plane fault domain evidence (r18): serving state +
        # response-ladder counters for the predictor's device domain
        dom = getattr(q.predictor, "device_domain", None)
        if dom is not None:
            out["device"] = dom.stats()
        # closed-loop SLO control evidence (r16): declared setpoints,
        # per-axis compliance, and the controller's knob/decision state
        if self.controller is not None:
            out["slo"] = self.controller.slo_status()
            out["controller"] = self.controller.stats()
        # model-lifecycle evidence (drift / promotion / swap state)
        # rides the same dump when the engine has a lifecycle armed
        lc = getattr(q, "lifecycle", None)
        lc_stats = getattr(lc, "stats", None) if lc is not None else None
        if lc_stats is not None:
            out["lifecycle"] = dict(
                lc_stats(),
                models_swapped=getattr(q, "models_swapped", 0),
            )
        return out

    def write_health_json(self, latest: Optional[int] = None) -> str:
        """Atomically (re)write the status dump; returns the path."""
        return _atomic_json(self.health_json, self.status(latest), indent=1)
