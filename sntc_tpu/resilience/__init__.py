"""Resilience layer: retry/backoff policies, deterministic fault
injection, circuit breakers, health monitoring, query supervision, and
the structured-event stream behind all of them.  See
``docs/RESILIENCE.md`` for the site map and env knobs."""

from sntc_tpu.resilience.circuit import (
    CircuitBreaker,
    CircuitOpenError,
    breaker_for,
    breakers_snapshot,
    reset_breakers,
)
from sntc_tpu.resilience.faults import (
    ALL_KINDS,
    DATA_KINDS,
    IO_KINDS,
    KILL_EXIT_CODE,
    SITES,
    InjectedDiskFault,
    InjectedFault,
    InjectedIOFault,
    InjectedTimeoutFault,
    arm,
    call_count,
    clear,
    data_fault_armed,
    disarm,
    fault_data,
    fault_disk,
    fault_point,
    parse_faults_env,
)
from sntc_tpu.resilience.control import (
    ControlPolicy,
    Guardrails,
    TuningBudget,
)
from sntc_tpu.resilience.health import HealthMonitor, HealthState
from sntc_tpu.resilience.policy import (
    RetryExhausted,
    RetryPolicy,
    add_event_observer,
    clear_events,
    emit_event,
    event_observer_count,
    events_dropped,
    recent_events,
    remove_event_observer,
    with_retries,
)
from sntc_tpu.resilience.supervisor import QuerySupervisor, default_breakers

__all__ = [
    "RetryPolicy",
    "RetryExhausted",
    "with_retries",
    "emit_event",
    "recent_events",
    "events_dropped",
    "event_observer_count",
    "add_event_observer",
    "remove_event_observer",
    "clear_events",
    "fault_point",
    "fault_data",
    "fault_disk",
    "data_fault_armed",
    "arm",
    "disarm",
    "clear",
    "call_count",
    "parse_faults_env",
    "InjectedFault",
    "InjectedIOFault",
    "InjectedTimeoutFault",
    "InjectedDiskFault",
    "SITES",
    "ALL_KINDS",
    "DATA_KINDS",
    "IO_KINDS",
    "KILL_EXIT_CODE",
    "CircuitBreaker",
    "CircuitOpenError",
    "breaker_for",
    "breakers_snapshot",
    "reset_breakers",
    "ControlPolicy",
    "Guardrails",
    "TuningBudget",
    "HealthMonitor",
    "HealthState",
    "QuerySupervisor",
    "default_breakers",
]
