"""Resilience layer: retry/backoff policies, deterministic fault
injection, and the structured-event stream behind both.  See
``docs/RESILIENCE.md`` for the site map and env knobs."""

from sntc_tpu.resilience.faults import (
    SITES,
    InjectedFault,
    InjectedIOFault,
    InjectedTimeoutFault,
    arm,
    call_count,
    clear,
    disarm,
    fault_point,
    parse_faults_env,
)
from sntc_tpu.resilience.policy import (
    RetryExhausted,
    RetryPolicy,
    clear_events,
    emit_event,
    recent_events,
    with_retries,
)

__all__ = [
    "RetryPolicy",
    "RetryExhausted",
    "with_retries",
    "emit_event",
    "recent_events",
    "clear_events",
    "fault_point",
    "arm",
    "disarm",
    "clear",
    "call_count",
    "parse_faults_env",
    "InjectedFault",
    "InjectedIOFault",
    "InjectedTimeoutFault",
    "SITES",
]
