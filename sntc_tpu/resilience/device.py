"""Compute-plane fault domain (r18): classify device/XLA runtime
errors and respond per kind instead of blind-retrying the same doomed
program.

Every other layer of the framework has a declared survival story —
storage (r17), ingest (r10/r15), serving (r12/r16), lifecycle (r11) —
but until now a device OOM, a wedged or failed compile, or a lost
backend surfaced as a generic ``predict.dispatch`` retry that re-ran
the exact same program against the exact same dead device.  This
module is the missing fault domain:

* :func:`classify_device_error` maps any exception chain onto the
  DEVICE kind vocabulary (``device_oom`` / ``compile_error`` /
  ``device_lost``) by the same message patterns the real
  ``XlaRuntimeError`` status lines carry — injected faults
  (:class:`~sntc_tpu.resilience.faults.InjectedDeviceFault`) and
  genuine backend failures classify identically.

* :class:`DeviceFaultDomain` holds the response state machine:

  - **device_oom** → the dispatcher splits the micro-batch in half
    (recursively, floored at the shape-bucket minimum) and steps the
    bucket floor down, journaling a ``device_oom_split`` decision —
    retry ON device with a smaller program, not the same one.
  - **compile_error** (or a compile exceeding the per-signature
    wall-time watchdog, ``compile_budget_s``) → exactly that
    (segment, signature) is POISONED in the plan cache and served
    through the eager host fallback forever after; other signatures
    keep compiling on device.
  - **device_lost**, or ``degrade_after`` consecutive device-attributed
    failures → the whole predictor flips **HOST_DEGRADED**: every
    dispatch takes the host path, the model component reports DEGRADED,
    the ``sntc_device_state`` gauge flips to 1, and a probe-gated
    recovery tick re-runs the backend probe OFF the hot path until the
    device answers again — then serving returns to the device with the
    compile ledger intact (no churn on re-entry).

  Device-attributed errors are PLATFORM faults: the serving engine
  routes them here instead of into the per-batch poison machinery, so
  they never quarantine a batch prematurely and never strike a tenant's
  escalation ladder (docs/RESILIENCE.md "Compute-plane fault domain").
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from sntc_tpu.resilience.policy import emit_event

DEVICE_OK = "DEVICE_OK"
HOST_DEGRADED = "HOST_DEGRADED"

_OOM_RE = re.compile(
    r"RESOURCE_EXHAUSTED|out of memory|OOM when allocating"
    r"|failed to allocate.*(?:memory|bytes)",
    re.IGNORECASE,
)
_COMPILE_RE = re.compile(
    r"XLA compilation|during compile|compilation fail|failed to compile"
    r"|compile_error",
    re.IGNORECASE,
)
_LOST_RE = re.compile(
    r"device (?:lost|halted|removed|reset)|UNAVAILABLE"
    r"|FAILED_PRECONDITION|backend (?:restart|lost|unavailable)"
    r"|heartbeat|device_lost",
    re.IGNORECASE,
)


def _xla_shaped(exc: BaseException) -> bool:
    """Only XLA-runtime-shaped errors may classify: the injected device
    fault, jaxlib's ``XlaRuntimeError`` (matched by type name — jaxlib
    moves the class between releases), or an error another layer
    already tagged with ``device_kind``.  A ``ValueError("cannot
    compile regex")`` from user code must never flip serving onto the
    host path."""
    if getattr(exc, "device_kind", None) is not None:
        return True
    for klass in type(exc).__mro__:
        if klass.__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
            return True
    return False


def classify_device_error(exc: Optional[BaseException]) -> Optional[str]:
    """The DEVICE kind an exception chain carries, or None for
    anything that is not a device/XLA runtime failure.  Walks
    ``__cause__``/``__context__`` (bounded) so a wrapped finalize error
    still classifies; patterns are checked OOM → compile → lost so a
    ``RESOURCE_EXHAUSTED`` raised during compilation responds as the
    OOM it is."""
    seen = 0
    while exc is not None and seen < 8:
        kind = getattr(exc, "device_kind", None)
        if kind is not None:
            return kind
        if _xla_shaped(exc):
            msg = str(exc)
            if _OOM_RE.search(msg):
                return "device_oom"
            if _COMPILE_RE.search(msg):
                return "compile_error"
            if _LOST_RE.search(msg):
                return "device_lost"
        exc = exc.__cause__ or exc.__context__
        seen += 1
    return None


class DeviceExecError(RuntimeError):
    """A device-attributed dispatch/finalize failure with its execution
    context threaded through (the r17 file+offset discipline applied to
    the compute plane): which batch, which fused segment, which input
    signature — so an error surfacing on the overlap-sink delivery
    thread still names the work that died, not just the symptom.
    ``device_kind`` makes it classify without re-matching patterns."""

    def __init__(
        self,
        message: str,
        *,
        kind: Optional[str] = None,
        batch_id: Optional[int] = None,
        segment: Optional[int] = None,
        signature: Optional[str] = None,
    ):
        super().__init__(message)
        self.device_kind = kind
        self.batch_id = batch_id
        self.segment = segment
        self.signature = signature


def annotate_batch(exc: BaseException, batch_id: int) -> BaseException:
    """Thread the batch id through an in-flight error chain without
    changing its type (retry/breaker/quarantine handlers keep working):
    a ``__notes__`` entry where the runtime supports it, and a
    ``batch_id`` attribute either way."""
    if getattr(exc, "batch_id", None) is None:
        try:
            exc.batch_id = batch_id
        except Exception:
            pass
        note = f"[sntc] while finalizing/delivering batch {batch_id}"
        add_note = getattr(exc, "add_note", None)
        if add_note is not None:
            try:
                add_note(note)
            except Exception:
                pass
    return exc


@dataclass
class DevicePolicy:
    """Response-ladder tuning for one :class:`DeviceFaultDomain`.

    ``oom_split_depth`` bounds the recursive micro-batch halvings one
    dispatch may attempt; ``bucket_floor_min`` is where the OOM
    responder stops stepping the predictor's shape-bucket floor down;
    ``compile_budget_s`` arms the per-signature compile wall-time
    watchdog (None/0 = unarmed); ``degrade_after`` consecutive
    device-attributed failures (any kind) flip HOST_DEGRADED even
    without a ``device_lost``; ``probe_interval_s`` paces the
    recovery probe while degraded."""

    oom_split_depth: int = 4
    bucket_floor_min: int = 1
    #: clean dispatches after the last OOM before a stepped-down
    #: bucket floor is restored to its cold value — the step-down is
    #: an emergency response to transient memory pressure, not a
    #: permanent ratchet (a tiny floor forever = fresh compiles for
    #: every small batch size, the churn the buckets exist to prevent)
    floor_restore_after: int = 64
    compile_budget_s: Optional[float] = None
    degrade_after: int = 3
    probe_interval_s: float = 30.0
    journal_keep: int = 256

    def __post_init__(self):
        if self.compile_budget_s is not None and self.compile_budget_s <= 0:
            self.compile_budget_s = None
        self.oom_split_depth = max(1, int(self.oom_split_depth))
        self.bucket_floor_min = max(1, int(self.bucket_floor_min))
        self.degrade_after = max(1, int(self.degrade_after))


def _metrics():
    from sntc_tpu.obs import metrics

    return metrics


class DeviceFaultDomain:
    """The compute-plane survival state machine (module docstring).

    One domain models ONE device: the ServeDaemon shares a single
    domain across every tenant's predictor, exactly as the tenants
    share the physical device — a platform fault degrades the plane
    once, not once per tenant.  Thread-safe: predictors dispatch from
    engine AND delivery threads.

    ``probe_fn`` (default: :func:`sntc_tpu.utils.backend_probe
    .probe_for_recovery`) decides recovery; with ``probe_async=True``
    (the default) it runs on a background daemon thread so a hung
    backend init can never stall the serving loop — the verdict is
    applied at the next :meth:`tick`.  Tests inject a synchronous
    ``probe_fn`` and a fake clock for deterministic arcs."""

    def __init__(
        self,
        policy: Optional[DevicePolicy] = None,
        *,
        probe_fn: Optional[Callable[[], bool]] = None,
        clock: Callable[[], float] = time.monotonic,
        probe_async: bool = True,
    ):
        self.policy = policy or DevicePolicy()
        self._probe_fn = probe_fn
        self._clock = clock
        self._probe_async = bool(probe_async)
        self._lock = threading.Lock()
        self._state = DEVICE_OK
        self._degraded_reason: Optional[str] = None
        self._degraded_at: Optional[float] = None
        self._consecutive = 0
        self._last_probe: Optional[float] = None
        self._probe_inflight = False
        self._probe_verdict: Optional[bool] = None
        # evidence
        self.faults: Dict[str, int] = {}
        self.oom_splits = 0
        self.bucket_floor_steps = 0
        self.poisoned_signatures = 0
        self.fallback_batches = 0
        self.recoveries = 0
        self.degradations = 0
        self.probes = 0
        self.last_recovery_latency_s: Optional[float] = None
        self.journal: List[dict] = []
        self._gauge(0)

    # -- state --------------------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    @property
    def host_degraded(self) -> bool:
        return self._state == HOST_DEGRADED

    def _gauge(self, value: int) -> None:
        try:
            _metrics().set_gauge("sntc_device_state", value)
        except Exception:
            pass

    def _journal(self, record: dict) -> None:
        record = dict(record, ts=time.time())
        with self._lock:
            self.journal.append(record)
            if len(self.journal) > self.policy.journal_keep:
                del self.journal[: -self.policy.journal_keep]

    # -- fault intake --------------------------------------------------------

    def note_fault(self, kind: str, *, site: str, **context: Any) -> None:
        """One device-attributed failure: count it, emit the
        ``device_fault`` event (never a strike event), and escalate to
        HOST_DEGRADED on ``device_lost`` or on the ``degrade_after``-th
        consecutive failure of any kind."""
        with self._lock:
            self.faults[kind] = self.faults.get(kind, 0) + 1
            self._consecutive += 1
            consecutive = self._consecutive
        try:
            _metrics().inc("sntc_device_faults_total", kind=kind, site=site)
        except Exception:
            pass
        emit_event(
            event="device_fault", component="model", site=site,
            kind=kind, consecutive=consecutive, **context,
        )
        if kind == "device_lost" or consecutive >= self.policy.degrade_after:
            self.enter_host_degraded(
                f"{kind} at {site}"
                if kind == "device_lost"
                else f"{consecutive} consecutive device faults "
                f"(last: {kind} at {site})"
            )

    def fault_count(self) -> int:
        """Total device faults noted so far — the dispatcher snapshots
        this around a dispatch so a fault ABSORBED inside it (a fused
        segment poisoning its signature) is not immediately cancelled
        by the enclosing dispatch's success."""
        with self._lock:
            return sum(self.faults.values())

    def note_success(self) -> None:
        """A clean device dispatch: the consecutive-failure streak
        resets (the degradation trigger is *sustained* failure)."""
        if self._consecutive:
            with self._lock:
                self._consecutive = 0

    def note_oom_split(self, *, rows: int, depth: int,
                       bucket_floor: int) -> None:
        with self._lock:
            self.oom_splits += 1
        try:
            _metrics().inc("sntc_device_oom_splits_total")
        except Exception:
            pass
        self._journal({
            "decision": "device_oom_split", "rows": rows,
            "depth": depth, "bucket_floor": bucket_floor,
        })
        emit_event(
            event="device_oom_split", component="model",
            site="device.dispatch", rows=rows, depth=depth,
        )

    def note_mesh_resize(self, *, old: int, new: int, axis: str,
                         site: str) -> None:
        """A mesh participant dropped out and the collective layer
        RESIZED (r22): the data axis shrank ``old`` → ``new`` and the
        fit continues on the survivors.  Journaled as a first-class
        decision — it is the elastic alternative to
        :meth:`enter_host_degraded`, so it must leave the same kind of
        evidence trail.  Counts as a device fault for the metrics/event
        plane but does NOT feed the consecutive-failure streak: the
        resize already IS the response."""
        with self._lock:
            self.faults["device_lost"] = (
                self.faults.get("device_lost", 0) + 1
            )
        try:
            _metrics().inc(
                "sntc_device_faults_total", kind="device_lost", site=site
            )
        except Exception:
            pass
        self._journal({
            "decision": "mesh_resize", "axis": axis,
            "from": old, "to": new, "site": site,
        })
        emit_event(
            event="mesh_resize", component="model", site=site,
            axis=axis, old=old, new=new,
        )

    def note_bucket_floor(self, old: int, new: int) -> None:
        with self._lock:
            self.bucket_floor_steps += 1
        self._journal({
            "decision": "bucket_floor_down", "from": old, "to": new,
        })

    def note_bucket_restore(self, old: int, new: int) -> None:
        self._journal({
            "decision": "bucket_floor_restored", "from": old, "to": new,
        })

    def note_unpoisoned(self, count: int) -> None:
        """Poisons cleared (a hot-swap discarded the programs they
        belonged to): keep the live poisoned-signatures gauge honest —
        it reports pairs CURRENTLY serving the host fallback, not a
        lifetime total."""
        if count <= 0:
            return
        with self._lock:
            self.poisoned_signatures = max(
                0, self.poisoned_signatures - count
            )
            current = self.poisoned_signatures
        try:
            _metrics().set_gauge(
                "sntc_device_poisoned_signatures", current
            )
        except Exception:
            pass
        self._journal({"decision": "poisons_cleared", "count": count})

    def note_poisoned(self, *, site: str, signature: str,
                      reason: str, segment: Optional[int] = None) -> None:
        """One (segment, signature) left the device path for good —
        compile failure or watchdog breach."""
        with self._lock:
            self.poisoned_signatures += 1
            count = self.poisoned_signatures
        try:
            m = _metrics()
            m.set_gauge("sntc_device_poisoned_signatures", count)
        except Exception:
            pass
        self._journal({
            "decision": "signature_poisoned", "site": site,
            "segment": segment, "signature": signature, "reason": reason,
        })
        emit_event(
            event="signature_poisoned", component="model", site=site,
            segment=segment, signature=signature, reason=reason,
        )

    def note_fallback(self, poisoned: bool = False) -> None:
        """One batch served through the eager host path (poisoned
        signature or HOST_DEGRADED)."""
        with self._lock:
            self.fallback_batches += 1
        try:
            _metrics().inc("sntc_device_fallback_batches_total")
        except Exception:
            pass

    # -- the HOST_DEGRADED state machine -------------------------------------

    def enter_host_degraded(self, reason: str) -> None:
        with self._lock:
            if self._state == HOST_DEGRADED:
                return
            self._state = HOST_DEGRADED
            self._degraded_reason = reason
            self._degraded_at = self._clock()
            self._last_probe = None
            self._probe_verdict = None
            self.degradations += 1
        self._gauge(1)
        self._journal({"decision": "host_degraded", "reason": reason})
        emit_event(
            event="device_degraded", component="model", reason=reason,
        )

    def _run_probe(self) -> None:
        probe = self._probe_fn
        if probe is None:
            from sntc_tpu.utils.backend_probe import probe_for_recovery

            probe = probe_for_recovery
        try:
            verdict = bool(probe())
        except Exception:
            verdict = False
        with self._lock:
            self._probe_verdict = verdict
            self._probe_inflight = False
            self.probes += 1

    def tick(self) -> None:
        """The recovery tick, called once per engine round (cheap when
        DEVICE_OK).  While degraded: apply a finished probe's verdict
        (recover on success), and launch the next probe once
        ``probe_interval_s`` has passed — on a background thread by
        default, so a backend init that HANGS (the exact failure the
        probe subprocess exists for) never wedges serving."""
        if self._state != HOST_DEGRADED:
            return
        with self._lock:
            verdict, self._probe_verdict = self._probe_verdict, None
            inflight = self._probe_inflight
            last = self._last_probe
        if verdict:
            self._recover()
            return
        now = self._clock()
        if inflight or (
            last is not None and now - last < self.policy.probe_interval_s
        ):
            return
        with self._lock:
            self._last_probe = now
            self._probe_inflight = True
        if self._probe_async:
            threading.Thread(
                target=self._run_probe, name="sntc-device-probe",
                daemon=True,
            ).start()
        else:
            self._run_probe()
            with self._lock:
                verdict, self._probe_verdict = self._probe_verdict, None
            if verdict:
                self._recover()

    def _recover(self) -> None:
        with self._lock:
            if self._state != HOST_DEGRADED:
                return
            self._state = DEVICE_OK
            self._consecutive = 0
            latency = (
                self._clock() - self._degraded_at
                if self._degraded_at is not None else None
            )
            self.last_recovery_latency_s = latency
            self._degraded_reason = None
            self._degraded_at = None
            self.recoveries += 1
        self._gauge(0)
        try:
            _metrics().inc("sntc_device_recoveries_total")
        except Exception:
            pass
        self._journal({
            "decision": "device_recovered",
            "recovery_latency_s": latency,
        })
        emit_event(
            event="device_recovered", component="model",
            recovery_latency_s=latency,
        )

    # -- evidence -----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "degraded_reason": self._degraded_reason,
                "consecutive_faults": self._consecutive,
                "faults": dict(self.faults),
                "oom_splits": self.oom_splits,
                "bucket_floor_steps": self.bucket_floor_steps,
                "poisoned_signatures": self.poisoned_signatures,
                "fallback_batches": self.fallback_batches,
                "degradations": self.degradations,
                "recoveries": self.recoveries,
                "probes": self.probes,
                "recovery_latency_s": self.last_recovery_latency_s,
                "journal": list(self.journal[-8:]),
            }
