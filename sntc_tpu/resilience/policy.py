"""Retry policies — the Spark task-retry (``spark.task.maxFailures``)
analog for a single-process JAX pipeline.

Behavioral spec: Spark's execution layer retries failed tasks with
backoff and keeps the job alive (MLlib rode on it for free); tf.data
treats input-pipeline fault handling as a first-class concern.  Here the
substrate is one process talking to flaky externals — a TPU tunnel that
times out, a sink volume that hiccups, a checkpoint torn mid-write — so
the unit of retry is a *site*: a named callable boundary
(``stream.read``, ``sink.write``, ``ckpt.load``, ``probe.init``, ...).

:class:`RetryPolicy` is a frozen value object: max attempts, exponential
backoff with DETERMINISTIC seeded jitter (the schedule is a pure
function of the policy — tests assert it exactly), an optional overall
deadline, and a retryable-exception classifier.
:func:`with_retries` executes a thunk under a policy and emits
structured JSONL events (``retry`` / ``retry_success`` /
``retry_exhausted``) through :mod:`sntc_tpu.utils.logging` — set
``SNTC_RESILIENCE_LOG=<path>`` to persist them; the last 512 events are
always inspectable in-process via :func:`recent_events`.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

import numpy as np

# top-level on purpose: the ring-eviction mirror below runs under
# _events_lock, and a lazy import THERE could re-enter this module's
# machinery mid-import; obs.metrics imports only the stdlib
from sntc_tpu.obs.metrics import inc as _metrics_inc
from sntc_tpu.utils.logging import MetricsLogger


class RetryExhausted(RuntimeError):
    """Every attempt a policy allowed has failed; wraps the last error."""

    def __init__(self, site: str, attempts: int, last: BaseException):
        super().__init__(
            f"{site}: {attempts} attempt(s) failed; last error: {last!r}"
        )
        self.site = site
        self.attempts = attempts
        self.last_exception = last


@dataclass(frozen=True)
class RetryPolicy:
    """Immutable retry spec; the backoff schedule is deterministic.

    ``jitter`` is a ± fraction applied to each exponential delay with a
    ``numpy`` generator seeded by ``seed`` — the same policy always
    yields the same schedule, so sleep sequences are assertable in
    tests and reproducible in incident logs.  ``deadline_s`` bounds the
    TOTAL elapsed time: a backoff sleep that would overshoot it is
    CLAMPED to the remaining budget (the final attempt still runs at
    the deadline), and once the deadline has elapsed no further attempt
    is made.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 5.0
    jitter: float = 0.1
    seed: int = 0
    deadline_s: Optional[float] = None
    retryable: Tuple[Type[BaseException], ...] = (Exception,)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.base_delay_s < 0 or self.max_delay_s < 0:
            raise ValueError("delays must be >= 0")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("jitter must lie in [0, 1]")

    def is_retryable(self, exc: BaseException) -> bool:
        return isinstance(exc, self.retryable)

    def backoff_schedule(self) -> List[float]:
        """Delay before retry i (i = 1 .. max_attempts-1), exactly."""
        rng = np.random.default_rng(self.seed)
        out = []
        for i in range(max(0, self.max_attempts - 1)):
            base = min(
                self.base_delay_s * self.multiplier**i, self.max_delay_s
            )
            u = float(rng.uniform(-1.0, 1.0))
            out.append(max(0.0, base * (1.0 + self.jitter * u)))
        return out


def int_from_env(var: str, default: int, minimum: int = 0) -> int:
    """Shared env-int parser for retry knobs (``SNTC_PROBE_ATTEMPTS``,
    ``SNTC_COLLECTIVE_RETRIES``, ...): malformed values warn once on
    stderr and fall back — a config typo must never crash startup."""
    raw = os.environ.get(var)
    if raw is None:
        return default
    try:
        val = int(raw)
    except (TypeError, ValueError):
        print(
            f"sntc_tpu: malformed {var}={raw!r}; using {default}",
            file=sys.stderr,
        )
        return default
    return max(minimum, val)


# ---------------------------------------------------------------------------
# structured events: JSONL through MetricsLogger + an in-process ring
# ---------------------------------------------------------------------------

_RECENT_MAX = 512
_recent: "deque[Dict[str, Any]]" = deque(maxlen=_RECENT_MAX)
_events_lock = threading.Lock()
_events_dropped = 0
# per-tenant eviction breakdown (r12): records carrying a ``tenant``
# field count against their tenant when the ring evicts them, so a
# flooding tenant's event pressure is attributable — the fair-share
# evidence the serve daemon journals.  Untagged records count under
# the int total only (single-tenant emit paths stay unchanged).
_events_dropped_by_tenant: Dict[str, int] = {}
_logger: Optional[MetricsLogger] = None
_observers: List[Callable[[Dict[str, Any]], None]] = []


def _events_logger() -> MetricsLogger:
    # pathless: the MetricsLogger only shapes records (step/elapsed);
    # file persistence is handled below in APPEND mode — the run-logger's
    # truncate-on-construction would clobber a log shared with parent or
    # sibling processes (bench --isolate children, probe subprocesses)
    global _logger
    if _logger is None:
        _logger = MetricsLogger(None)
    return _logger


def emit_event(**fields: Any) -> Dict[str, Any]:
    """Append one structured resilience event (JSONL when
    ``SNTC_RESILIENCE_LOG`` is set; always kept in the in-process ring).

    The ring is hard-capped at ``_RECENT_MAX`` records — a long-running
    query emits events for the life of the process, and the cap turns
    that into bounded memory.  Evictions are counted
    (:func:`events_dropped`), never silent.  Thread-safe: the engine
    loop, the watchdog thread, and ``--health-json`` snapshots all
    touch the ring concurrently.
    """
    global _events_dropped
    path = os.environ.get("SNTC_RESILIENCE_LOG")
    with _events_lock:
        # logger init, the step counter, file append, and the ring all
        # mutate under the ONE lock — the engine loop and the watchdog
        # thread emit concurrently, and a torn step sequence would break
        # the step-watermark windows bench journaling relies on
        record = _events_logger().log(**fields)
        # wall AND monotonic timestamps on EVERY event record: replay
        # analysis across tenants (or processes) orders by ``ts``;
        # intra-process interval math uses ``mono``, which never jumps
        # with the system clock.  Emitter-supplied values win.
        if "ts" not in record:
            record["ts"] = time.time()
        if "mono" not in record:
            record["mono"] = time.monotonic()
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "a") as f:  # storage: unbounded(opt-in debug event log)
                f.write(json.dumps(record) + "\n")
        if len(_recent) == _recent.maxlen:
            _events_dropped += 1
            evicted_tenant = _recent[0].get("tenant")
            if evicted_tenant is not None:
                _events_dropped_by_tenant[evicted_tenant] = (
                    _events_dropped_by_tenant.get(evicted_tenant, 0) + 1
                )
            try:  # mirror into the metrics plane (obs), never fatally
                _metrics_inc(
                    "sntc_events_dropped_total",
                    **(
                        {"tenant": evicted_tenant}
                        if evicted_tenant is not None else {}
                    ),
                )
            except Exception:
                pass
        _recent.append(record)
        observers = list(_observers)
    # observers run OUTSIDE the ring lock: an observer that emits (a
    # health change triggered by this event) must not deadlock.  A
    # RAISING observer is evicted, not propagated — emit_event runs
    # inside retry loops and breaker transitions, and an exception here
    # would replace the real error the resilience machinery is handling
    for fn in observers:
        try:
            fn(record)
        except Exception as e:
            remove_event_observer(fn)
            print(
                f"sntc_tpu: event observer {fn!r} raised {e!r}; "
                "observer removed",
                file=sys.stderr,
            )
    return record


def recent_events(
    site: Optional[str] = None, event: Optional[str] = None
) -> List[Dict[str, Any]]:
    """The in-process event ring, optionally filtered by site/event."""
    with _events_lock:
        snapshot = list(_recent)
    return [
        r
        for r in snapshot
        if (site is None or r.get("site") == site)
        and (event is None or r.get("event") == event)
    ]


def events_dropped(by_tenant: bool = False):
    """Events evicted from the ring since the last :func:`clear_events`
    — nonzero means ``recent_events`` is a suffix, not the full story.
    ``by_tenant=True`` returns the per-tenant breakdown instead (a
    dict of tenant → evictions, only tenant-tagged records counted) —
    the serve daemon's noisy-neighbor evidence."""
    with _events_lock:
        if by_tenant:
            return dict(_events_dropped_by_tenant)
        return _events_dropped


def add_event_observer(fn: Callable[[Dict[str, Any]], None]) -> None:
    """Register ``fn(record)`` to run on every future event (the
    :class:`~sntc_tpu.resilience.health.HealthMonitor` feed)."""
    with _events_lock:
        if fn not in _observers:
            _observers.append(fn)


def remove_event_observer(fn: Callable[[Dict[str, Any]], None]) -> None:
    with _events_lock:
        if fn in _observers:
            _observers.remove(fn)


def event_observer_count() -> int:
    """Registered observers right now — the leak regression's probe: a
    component that attaches an observer must detach it on teardown, so
    the count stays flat across component lifecycles."""
    with _events_lock:
        return len(_observers)


def clear_events() -> None:
    global _events_dropped
    with _events_lock:
        _recent.clear()
        _events_dropped = 0
        _events_dropped_by_tenant.clear()


# ---------------------------------------------------------------------------
# the executor
# ---------------------------------------------------------------------------


def with_retries(
    fn: Callable[[], Any],
    policy: Optional[RetryPolicy] = None,
    *,
    site: str = "unspecified",
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
) -> Any:
    """Run ``fn()`` under ``policy``; emit structured events per retry.

    Non-retryable exceptions propagate unchanged.  Retryable failures
    sleep the policy's deterministic backoff and re-invoke; when
    attempts (or the deadline) run out, :class:`RetryExhausted` wraps
    the last error.  The deadline clamps, not truncates: a backoff that
    would overshoot ``deadline_s`` is shortened to exactly the
    remaining budget and the final attempt still runs — the executor
    never sleeps past the deadline just to raise
    :class:`RetryExhausted` late, and never gives up with budget left.
    ``sleep`` and ``clock`` are injectable so tests assert schedules
    and deadline behavior without wall-clock cost.
    """
    policy = policy or RetryPolicy()
    schedule = policy.backoff_schedule()
    t0 = clock()
    for attempt in range(1, policy.max_attempts + 1):
        try:
            out = fn()
        except BaseException as e:
            if not policy.is_retryable(e):
                raise
            delay = schedule[attempt - 1] if attempt <= len(schedule) else 0.0
            elapsed = clock() - t0
            remaining = (
                None if policy.deadline_s is None
                else policy.deadline_s - elapsed
            )
            out_of_time = remaining is not None and remaining <= 0
            if attempt >= policy.max_attempts or out_of_time:
                emit_event(
                    event="retry_exhausted", site=site, attempts=attempt,
                    error=repr(e), deadline_hit=bool(out_of_time),
                )
                raise RetryExhausted(site, attempt, e) from e
            if remaining is not None:
                delay = min(delay, remaining)
            emit_event(
                event="retry", site=site, attempt=attempt,
                delay_s=round(delay, 6), error=repr(e),
            )
            sleep(delay)
        else:
            if attempt > 1:
                emit_event(
                    event="retry_success", site=site, attempts=attempt
                )
            return out
    raise AssertionError("unreachable")
