"""Per-site circuit breakers — fail fast instead of hammering a dead
dependency.

A :class:`CircuitBreaker` guards one named site (the same site names
the fault/retry machinery uses: ``sink.write``, ``predict.dispatch``,
``collective.dispatch``, ...).  It watches a sliding window of recent
call outcomes and walks the classic three-state machine:

``closed``
    Calls flow.  When the window holds at least ``min_calls`` outcomes
    and the failure rate reaches ``failure_threshold``, the breaker
    OPENS.
``open``
    Calls are refused immediately (:meth:`allow` is False;
    :meth:`call` raises :class:`CircuitOpenError`) — the retry layer
    stops burning its budget against a dependency that is down.  After
    ``cooldown_s`` on the breaker's clock, the next :meth:`allow`
    moves to half-open.
``half_open``
    Up to ``half_open_max_calls`` probe calls are admitted.  Any probe
    failure re-opens (a fresh cooldown); ``half_open_max_calls``
    consecutive probe successes close the breaker and clear the
    window.

The clock is injectable (``clock=lambda: t``) so every transition is
unit-testable without sleeping; transitions emit structured events
(``breaker_open`` / ``breaker_half_open`` / ``breaker_closed``)
through :func:`sntc_tpu.resilience.emit_event`, so they land in the
same JSONL stream the retry layer writes and in ``--health-json``
dumps.

A process-level registry (:func:`breaker_for`) hands out one breaker
per site for call sites that don't thread instances explicitly
(collective dispatch); engines that own their lifecycle
(``StreamingQuery``/``QuerySupervisor``) construct their own.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from sntc_tpu.resilience.policy import emit_event

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitOpenError(RuntimeError):
    """Raised by :meth:`CircuitBreaker.call` while the breaker refuses
    calls; carries the site and seconds until the next probe window."""

    def __init__(self, site: str, retry_after_s: float):
        super().__init__(
            f"circuit breaker for site {site!r} is open; "
            f"next probe in {retry_after_s:.3f}s"
        )
        self.site = site
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    """Sliding-window failure-rate breaker for one site.

    Thread-safe: the streaming engine records outcomes from its loop
    thread while ``--health-json`` snapshots from the supervisor.
    """

    def __init__(
        self,
        site: str,
        *,
        window: int = 20,
        failure_threshold: float = 0.5,
        min_calls: int = 5,
        cooldown_s: float = 30.0,
        half_open_max_calls: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        if window < 1:
            raise ValueError("window must be >= 1")
        if not 0.0 < failure_threshold <= 1.0:
            raise ValueError("failure_threshold must lie in (0, 1]")
        if min_calls < 1 or min_calls > window:
            raise ValueError("min_calls must lie in [1, window]")
        if cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        if half_open_max_calls < 1:
            raise ValueError("half_open_max_calls must be >= 1")
        self.site = site
        self.window = window
        self.failure_threshold = failure_threshold
        self.min_calls = min_calls
        self.cooldown_s = cooldown_s
        self.half_open_max_calls = half_open_max_calls
        self._clock = clock
        self._lock = threading.RLock()
        self._state = CLOSED
        self._outcomes: "deque[bool]" = deque(maxlen=window)  # True = failure
        self._opened_at: Optional[float] = None
        self._probes_in_flight = 0
        self._probe_successes = 0
        self._open_count = 0

    # -- state machine ------------------------------------------------------

    _STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def _transition(self, new_state: str, **fields: Any) -> None:
        old, self._state = self._state, new_state
        try:  # live state on the metrics plane (obs), never fatally
            from sntc_tpu.obs.metrics import set_gauge

            set_gauge(
                "sntc_breaker_state", self._STATE_GAUGE[new_state],
                site=self.site,
            )
        except Exception:
            pass
        emit_event(
            event=f"breaker_{new_state}", site=self.site, from_state=old,
            **fields,
        )

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        """open → half_open once the cooldown elapsed (lock held)."""
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.cooldown_s
        ):
            self._probes_in_flight = 0
            self._probe_successes = 0
            self._transition(HALF_OPEN)

    def _failure_rate(self) -> float:
        if not self._outcomes:
            return 0.0
        return sum(self._outcomes) / len(self._outcomes)

    def allow(self) -> bool:
        """May a call proceed right now?  A half-open True reserves one
        probe slot; the caller MUST follow with record_success/failure."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return False
            if self._probes_in_flight >= self.half_open_max_calls:
                return False
            self._probes_in_flight += 1
            return True

    def retry_after_s(self) -> float:
        """Seconds until an open breaker next admits a probe (0 when
        calls are currently admissible)."""
        with self._lock:
            self._maybe_half_open()
            if self._state != OPEN:
                return 0.0
            return max(
                0.0, self.cooldown_s - (self._clock() - self._opened_at)
            )

    def record_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_successes += 1
                if self._probe_successes >= self.half_open_max_calls:
                    self._outcomes.clear()
                    self._transition(CLOSED)
                return
            if self._state == OPEN:
                return  # stray outcome from a call admitted pre-open
            self._outcomes.append(False)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # the dependency is still down: back to a fresh cooldown
                self._opened_at = self._clock()
                self._open_count += 1
                self._transition(OPEN, probe_failed=True)
                return
            if self._state == OPEN:
                return  # stray outcome from a call admitted pre-open
            self._outcomes.append(True)
            if (
                len(self._outcomes) >= self.min_calls
                and self._failure_rate() >= self.failure_threshold
            ):
                self._opened_at = self._clock()
                self._open_count += 1
                self._transition(
                    OPEN,
                    failure_rate=round(self._failure_rate(), 4),
                    window=len(self._outcomes),
                )

    def release(self) -> None:
        """Withdraw a reserved half-open probe slot WITHOUT recording
        an outcome — for an allowed call whose result cannot fairly
        score this dependency (r18: a device-classified platform fault
        is the compute plane's evidence, not the guarded site's; the
        slot must not leak, or the breaker wedges half-open forever).
        No-op outside HALF_OPEN."""
        with self._lock:
            if self._state == HALF_OPEN and self._probes_in_flight > 0:
                self._probes_in_flight -= 1

    def reset(self) -> None:
        """Administrative reset to a fresh CLOSED breaker: window
        cleared, probes cleared, cooldown forgotten.  ``open_count``
        survives as cumulative evidence.  The serve daemon calls this
        when a quarantined tenant is released on probation — the tenant
        gets a clean window to re-earn (or re-lose) trust; an OPEN
        breaker left behind would refuse every call and starve the
        ladder of fresh evidence."""
        with self._lock:
            self._outcomes.clear()
            self._opened_at = None
            self._probes_in_flight = 0
            self._probe_successes = 0
            if self._state != CLOSED:
                self._transition(CLOSED, reset=True)

    def call(self, fn: Callable[[], Any]) -> Any:
        """Run ``fn()`` through the breaker: refuse when open, record
        the outcome otherwise.  KeyboardInterrupt/SystemExit pass
        through WITHOUT counting as failures — a user interrupt is not
        evidence the dependency is down."""
        if not self.allow():
            raise CircuitOpenError(self.site, self.retry_after_s())
        try:
            out = fn()
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out

    def snapshot(self) -> Dict[str, Any]:
        """State dump for health JSON / bench journaling."""
        with self._lock:
            self._maybe_half_open()
            return {
                "site": self.site,
                "state": self._state,
                "failure_rate": round(self._failure_rate(), 4),
                "window_calls": len(self._outcomes),
                "open_count": self._open_count,
                "retry_after_s": round(self.retry_after_s(), 3),
            }


# ---------------------------------------------------------------------------
# process-level registry — for call sites that don't thread instances
# ---------------------------------------------------------------------------

_registry: Dict[str, CircuitBreaker] = {}
_registry_lock = threading.Lock()


def breaker_for(site: str, **kwargs: Any) -> CircuitBreaker:
    """The process-wide breaker for ``site`` (created on first use with
    ``kwargs``; later calls return the existing instance unchanged)."""
    with _registry_lock:
        br = _registry.get(site)
        if br is None:
            br = _registry[site] = CircuitBreaker(site, **kwargs)
        return br


def reset_breakers(prefix: Optional[str] = None) -> None:
    """Drop registered breakers: every one (test isolation), or — with
    ``prefix`` — only the sites under one namespace (``prefix=
    "tenant/<id>/"``: the serve daemon evicts a STOPPED tenant's
    breakers so its failure history cannot outlive it and leak into
    later tenants or tests reusing the id)."""
    with _registry_lock:
        if prefix is None:
            _registry.clear()
            return
        for site in [s for s in _registry if s.startswith(prefix)]:
            del _registry[site]


def breakers_snapshot() -> Dict[str, Dict[str, Any]]:
    """Snapshot of every registered breaker, keyed by site."""
    with _registry_lock:
        return {site: br.snapshot() for site, br in _registry.items()}
