"""Durable-storage survival plane (r17): bounded artifact lifecycle,
ENOSPC-proof writes, and the ``fsck`` doctor.

The serving plane is crash-safe *logically* (WAL replay, snapshot-at-
commit, atomic publish) — this module makes it crash-safe *physically*:

* **Artifact registry** — every durable artifact class the framework
  writes (WAL logs, JSONL journals, dead-letter dirs, flow-state
  snapshots, markers, model checkpoints) is declared in
  :data:`ARTIFACTS` with its retention policy and its failure policy.
  ``scripts/check_durable_artifacts.py`` pins the registry against the
  code's write sites and the docs table in tier-1, so an unregistered
  append-forever file cannot ship silently.
* **Bounded journals** — :class:`RotatingJsonlWriter` puts a size cap
  under every JSONL journal (shed / controller / promotion /
  dead-letter / repair): the current segment rotates to ``<path>.1``
  (… ``.keep``) at the cap, so a journal's footprint is
  ``(keep + 1) × max_bytes`` forever.
* **Disk failure as a first-class fault** — every physical write
  routes through helpers that call :func:`~sntc_tpu.resilience.faults
  .fault_disk` (``SNTC_FAULTS`` kinds ``enospc`` / ``io_error`` /
  ``torn_write``) and follow the artifact's declared policy: the WAL
  and flow snapshots FAIL (the engine's retry/breaker/quarantine
  machinery owns the consequence), journals and markers DEGRADE
  (records buffer in memory behind a counted ``storage_degraded``
  health state and flush when the disk recovers — telemetry never
  kills serving), dead-letter dirs SHED (oldest evidence dropped with
  a counted ``dead_letter_dropped`` reason).
* **Disk accounting & budgets** — :class:`StoragePlane` measures every
  registered artifact under a checkpoint root into the ``sntc_disk_*``
  gauge series, checks per-tenant/global byte budgets, and feeds the
  ``storage`` block of supervisor/daemon status dumps.
* **The doctor** — :func:`fsck` walks a checkpoint root (or a whole
  tenant tree), verifies every artifact's manifests/seals/tails,
  repairs what is safe (torn JSONL tails truncate with a journaled
  repair record), quarantines corrupt blobs to ``.corrupt/``, and
  returns a machine-readable report; :func:`quick_scan` is the light
  construction-time subset every engine runs.

See docs/RESILIENCE.md "Durable storage lifecycle".
"""

from __future__ import annotations

import errno
import glob
import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from sntc_tpu.obs.metrics import inc, set_gauge
from sntc_tpu.resilience.faults import InjectedDiskFault, fault_disk
from sntc_tpu.resilience.policy import emit_event

REPAIR_JOURNAL = "storage_repair.jsonl"


class StorageCorruptError(RuntimeError):
    """A sealed storage record fails its integrity check (bad seal,
    torn payload) — names the offending file."""


# ---------------------------------------------------------------------------
# artifact registry
# ---------------------------------------------------------------------------

#: failure policies an artifact may declare.  ``fail``: the write error
#: propagates to the caller — the engine's existing retry / breaker /
#: quarantine path owns the consequence (the WAL cannot degrade: losing
#: it loses exactly-once).  ``degrade``: the record buffers in memory
#: behind a counted ``storage_degraded`` health state and flushes when
#: the disk recovers — evidence journals must never kill serving.
#: ``shed``: the write (or the oldest retained evidence) is dropped
#: with a counted reason — bounded dead-letter dirs under a poison
#: flood.
FAIL, DEGRADE, SHED = "fail", "degrade", "shed"


@dataclass(frozen=True)
class ArtifactSpec:
    """One durable artifact class: where it lives under a checkpoint
    root, which ``storage.*`` fault site guards its writes, how it is
    bounded, and what a failed write does."""

    name: str
    kind: str  # wal | journal | dead_letter | snapshot | marker | checkpoint
    site: str  # the fault_disk site guarding its physical writes
    patterns: Tuple[str, ...]  # globs relative to a checkpoint root
    retention: str  # human-readable bound (docs table mirrors this)
    failure_policy: str  # FAIL | DEGRADE | SHED


#: THE registry: every durable artifact class the framework writes.
#: ``scripts/check_durable_artifacts.py`` pins this against the code's
#: annotated write sites AND the marker-delimited artifact table in
#: docs/RESILIENCE.md, both directions, in tier-1.
ARTIFACTS: Dict[str, ArtifactSpec] = {
    spec.name: spec
    for spec in (
        ArtifactSpec(
            "wal_append", "wal", "storage.wal",
            ("offsets.log", "commits.log", "wal_checkpoint.json"),
            "compacted every wal_compact_every commits: sealed "
            "checkpoint + truncated logs (replay = checkpoint + tail)",
            FAIL,
        ),
        ArtifactSpec(
            "wal_files", "wal", "storage.wal",
            ("offsets/*.json", "commits/*.json"),
            "committed intent/commit pairs pruned beyond "
            "wal_keep_commits (uncommitted intents never pruned)",
            FAIL,
        ),
        ArtifactSpec(
            "shed_journal", "journal", "storage.journal",
            ("shed.jsonl*",),
            "RotatingJsonlWriter: size-capped segments, keep 2 rotated",
            DEGRADE,
        ),
        ArtifactSpec(
            "controller_journal", "journal", "storage.journal",
            ("controller.jsonl*",),
            "RotatingJsonlWriter: size-capped segments, keep 2 rotated",
            DEGRADE,
        ),
        ArtifactSpec(
            "promotion_journal", "journal", "storage.journal",
            ("promotion.jsonl*",),
            "RotatingJsonlWriter: size-capped segments, keep 2 rotated",
            DEGRADE,
        ),
        ArtifactSpec(
            "repair_journal", "journal", "storage.journal",
            (REPAIR_JOURNAL + "*",),
            "RotatingJsonlWriter: size-capped segments, keep 2 rotated",
            DEGRADE,
        ),
        ArtifactSpec(
            "dead_letter", "dead_letter", "storage.dead_letter",
            ("dead_letter/*",),
            "keep-N newest batch dumps (dead_letter_keep), oldest "
            "dropped with a counted dead_letter_dropped",
            SHED,
        ),
        ArtifactSpec(
            "dead_letter_rows", "dead_letter", "storage.dead_letter",
            ("dead_letter_rows/*",),
            "keep-N newest batch journals (dead_letter_keep), oldest "
            "dropped with a counted dead_letter_dropped",
            SHED,
        ),
        ArtifactSpec(
            "flow_state", "snapshot", "storage.state",
            ("flow_state/state-*.bin",),
            "FlowStateStore keep-2 bracketing snapshots (pre-existing)",
            FAIL,
        ),
        ArtifactSpec(
            "markers", "marker", "storage.marker",
            ("drain_marker.json", "model_marker.json",
             "daemon_drain_marker.json", "health.json"),
            "atomic overwrite in place (bounded by construction)",
            DEGRADE,
        ),
        ArtifactSpec(
            "telemetry", "marker", "storage.marker",
            (),  # --metrics-out/--trace-out paths live outside the root
            "atomic snapshot overwrite / bounded span ring (bounded "
            "by construction)",
            DEGRADE,
        ),
        ArtifactSpec(
            "checkpoint", "checkpoint", "storage.marker",
            ("model/*", "model.prev/*"),
            "atomic publish, exactly one .prev retained (mlio, "
            "pre-existing)",
            FAIL,
        ),
        # -- the elastic serve fleet (serve/fleet, r19): patterns are
        # relative to a coordinator FLEET root, not a daemon root -----
        ArtifactSpec(
            "fleet_lease", "marker", "storage.marker",
            ("fleet/workers/*/lease.json",),
            "atomic overwrite per heartbeat (one lease per worker)",
            DEGRADE,
        ),
        ArtifactSpec(
            "fleet_assignments", "marker", "storage.marker",
            ("fleet/assignments.json",),
            "atomic epoch overwrite in place (one marker per fleet)",
            FAIL,
        ),
        ArtifactSpec(
            "fleet_assignment_journal", "journal", "storage.journal",
            ("fleet/assignments.jsonl*",),
            "RotatingJsonlWriter: size-capped segments, keep 2 rotated",
            DEGRADE,
        ),
        ArtifactSpec(
            "fleet_migration_manifest", "marker", "storage.marker",
            ("fleet/migrations/*.json",),
            "sealed, one per tenant, overwritten by the next migration",
            FAIL,
        ),
        ArtifactSpec(
            "fleet_markers", "marker", "storage.marker",
            ("fleet/coordinator.json", "fleet/fleet_drain_marker.json",
             "fleet/workers/*/release/*.json"),
            "atomic overwrite in place; release markers removed once "
            "the coordinator consumes them",
            DEGRADE,
        ),
        ArtifactSpec(
            "fleet_request_journal", "journal", "storage.journal",
            ("fleet/workers/*/requests.jsonl*",),
            "append-only, offset-consumed by the coordinator, bounded "
            "by the one-shot fleet knobs (≤2 lines per tenant lifetime)",
            DEGRADE,
        ),
        # -- live network front door (serve/ingress, r20): patterns are
        # relative to the listener's SPOOL directory (the --watch dir
        # of a socket-fed serve) -------------------------------------
        ArtifactSpec(
            "ingress_spool", "wal", "ingress.spool",
            ("capture_*.nf5", "rows_*.csv", "ingress_stats.json",
             "quarantine/*"),
            "keep-N newest COMMITTED capture files (committed_end "
            "horizon; uncommitted never pruned), oldest dropped with a "
            "counted sntc_ingress_pruned_files_total; over-budget "
            "payloads shed at ingress (counted), never ENOSPC death",
            SHED,
        ),
        # -- warm-standby disaster recovery (resilience/replicate,
        # r23): these artifacts live under the STANDBY root
        # (<standby>/<tenant>/), never under a replicated primary
        # root, so their patterns are empty — like telemetry — and
        # they are verified by fsck --standby / promote_standby, not
        # by the per-root walk ----------------------------------------
        ArtifactSpec(
            "repl_barrier", "journal", "repl.barrier",
            (),  # <standby>/<tenant>/barriers.jsonl* (standby-resident)
            "RotatingJsonlWriter: size-capped segments, keep 2 rotated; "
            "promotion walks newest-first to the last SEALED record",
            DEGRADE,
        ),
        ArtifactSpec(
            "repl_manifest", "marker", "repl.apply",
            (),  # <standby>/<tenant>/replica_manifest.json
            "sealed atomic overwrite per ship pass (one per replica); "
            "a failed publish degrades and the next commit re-ships",
            DEGRADE,
        ),
    )
}


# ---------------------------------------------------------------------------
# degradation bookkeeping (module-global: one episode flag per artifact)
# ---------------------------------------------------------------------------

_deg_lock = threading.Lock()
_degraded: set = set()  # {(artifact, tenant)} currently degraded


def _labels(artifact: str, tenant: Optional[str]) -> Dict[str, str]:
    out = {"artifact": artifact}
    if tenant is not None:
        out["tenant"] = tenant
    return out


def _component(artifact: str, tenant: Optional[str]) -> str:
    base = f"storage.{artifact}"
    return base if tenant is None else f"tenant/{tenant}/{base}"


def note_write_error(
    artifact: str, path: str, exc: BaseException,
    tenant: Optional[str] = None, **detail: Any,
) -> None:
    """Count one failed durable write and open a ``storage_degraded``
    episode for the artifact (the event fires once per episode, the
    counter every time).  The event names the path and error so the
    operator sees WHERE the disk is failing, the PR-5 attribution
    discipline applied to writes."""
    inc("sntc_storage_write_errors_total", **_labels(artifact, tenant))
    key = (artifact, tenant)
    with _deg_lock:
        fresh = key not in _degraded
        _degraded.add(key)
    set_gauge("sntc_storage_degraded_state", 1, **_labels(artifact, tenant))
    if fresh:
        fields = dict(
            event="storage_degraded",
            component=_component(artifact, tenant),
            artifact=artifact, path=path, error=repr(exc), **detail,
        )
        if tenant is not None:
            fields["tenant"] = tenant
        emit_event(**fields)


def note_write_ok(artifact: str, tenant: Optional[str] = None) -> None:
    """Close the artifact's degradation episode (if one is open):
    gauge back to 0 and one ``storage_recovered`` event."""
    key = (artifact, tenant)
    with _deg_lock:
        was = key in _degraded
        _degraded.discard(key)
    if was:
        set_gauge(
            "sntc_storage_degraded_state", 0, **_labels(artifact, tenant)
        )
        fields = dict(
            event="storage_recovered",
            component=_component(artifact, tenant), artifact=artifact,
        )
        if tenant is not None:
            fields["tenant"] = tenant
        emit_event(**fields)


def degraded_artifacts() -> List[Tuple[str, Optional[str]]]:
    """Currently-degraded (artifact, tenant) pairs (status dumps)."""
    with _deg_lock:
        return sorted(_degraded, key=lambda k: (k[0], k[1] or ""))


def reset_degradation() -> None:
    """Drop every open degradation episode and cached repair writer
    (test isolation)."""
    with _deg_lock:
        _degraded.clear()
    with _repair_writers_lock:
        _repair_writers.clear()


def _torn_error(site: str, path: str, cut: int, total: int) -> OSError:
    return InjectedDiskFault(
        errno.EIO,
        f"injected torn_write at site {site!r}: {cut}/{total} bytes of "
        f"{path} reached disk",
    )


def _oserror_with_path(exc: OSError, path: str, offset: int) -> OSError:
    """Re-raise shape for real write failures: same errno, message
    naming file + byte offset (the parser-error attribution discipline
    from PR 5, applied to writes)."""
    return OSError(
        exc.errno or errno.EIO,
        f"durable write to {path} failed at offset {offset}: "
        f"{exc.strerror or exc}",
        path,
    )


# ---------------------------------------------------------------------------
# physical write helpers (every durable byte flows through one of these)
# ---------------------------------------------------------------------------


def append_line(
    f, text: str, *, site: str, tenant: Optional[str] = None,
) -> None:
    """One flushed append of ``text`` to the open file object ``f``,
    under IO fault injection.  A failed append — ``torn_write``'s
    injected partial line, or a real flush failure that persisted a
    prefix (ENOSPC mid-line) — is ROLLED BACK (best-effort truncate to
    the pre-write offset) before the error propagates: the caller may
    survive and keep appending, and a partial line left mid-file would
    be unrepairable corruption, not the benign torn TAIL only a process
    death can leave (which the tolerant readers repair at startup).  A
    closed handle (a failed compaction reopen) surfaces as an OSError
    so the caller's declared failure policy owns it."""
    if getattr(f, "closed", False):
        raise OSError(
            errno.EIO,
            f"WAL/journal handle for {getattr(f, 'name', '?')} is "
            "closed (a failed compaction reopen); caller must reopen",
            getattr(f, "name", None),
        )
    pos = None

    def _rollback():
        if pos is None:
            return
        try:
            f.truncate(pos)
            f.seek(pos)
        except OSError:
            pass

    try:
        pos = f.tell()
        frac = fault_disk(site, tenant=tenant)
        if frac is not None:  # torn_write armed and fired
            cut = max(1, int(len(text) * frac))
            f.write(text[:cut])
            f.flush()
            _rollback()
            raise _torn_error(site, getattr(f, "name", "?"), cut, len(text))
        f.write(text)
        f.flush()
    except InjectedDiskFault:
        raise
    except OSError as e:
        _rollback()
        raise _oserror_with_path(
            e, getattr(f, "name", "?"), pos if pos is not None else -1
        ) from e


def atomic_write_bytes(
    path: str, data: bytes, *, site: str, tenant: Optional[str] = None,
    fsync: bool = True,
) -> None:
    """Tmp-then-rename publish of ``data`` at ``path`` under IO fault
    injection: readers never see a torn file; an injected (or real)
    failure leaves at most a ``.tmp`` orphan that :func:`fsck` and
    :func:`quick_scan` sweep."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        frac = fault_disk(site, tenant=tenant)
        with open(tmp, "wb") as f:
            if frac is not None:
                cut = max(1, int(len(data) * frac))
                f.write(data[:cut])
                f.flush()
                raise _torn_error(site, tmp, cut, len(data))
            f.write(data)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if fsync:
            dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
    except InjectedDiskFault:
        raise
    except OSError as e:
        raise _oserror_with_path(e, path, -1) from e


def atomic_write_json(
    path: str, obj: Any, *, site: str, tenant: Optional[str] = None,
    fsync: bool = True, indent: Optional[int] = None,
) -> None:
    atomic_write_bytes(
        path, json.dumps(obj, indent=indent).encode(),
        site=site, tenant=tenant, fsync=fsync,
    )


def write_marker(
    path: str, obj: Any, *, tenant: Optional[str] = None,
    indent: Optional[int] = None, fsync: bool = True,
) -> bool:
    """Marker/status writes under the DEGRADE policy: an atomic JSON
    publish that, on disk failure, counts + events ``storage_degraded``
    and returns False instead of raising — a status dump must never
    kill the loop it reports on."""
    try:
        atomic_write_json(
            path, obj, site="storage.marker", tenant=tenant,
            fsync=fsync, indent=indent,
        )
    except OSError as e:
        note_write_error("markers", path, e, tenant=tenant)
        return False
    note_write_ok("markers", tenant=tenant)
    return True


# -- sealed records (the WAL-compaction checkpoint format) ----------------


def seal_record(core: Dict[str, Any]) -> Dict[str, Any]:
    """Attach a sha256 seal over the canonical JSON of ``core``."""
    digest = hashlib.sha256(
        json.dumps(core, sort_keys=True).encode()
    ).hexdigest()
    return dict(core, sha256=digest)


def verify_sealed(obj: Dict[str, Any], path: str = "?") -> Dict[str, Any]:
    """Verify a sealed record; returns the core (seal stripped) or
    raises :class:`StorageCorruptError` naming the file."""
    if not isinstance(obj, dict) or "sha256" not in obj:
        raise StorageCorruptError(f"sealed record {path}: missing seal")
    core = {k: v for k, v in obj.items() if k != "sha256"}
    want = obj["sha256"]
    got = hashlib.sha256(
        json.dumps(core, sort_keys=True).encode()
    ).hexdigest()
    if got != want:
        raise StorageCorruptError(
            f"sealed record {path}: sha256 mismatch (expected "
            f"{str(want)[:12]}…, got {got[:12]}…)"
        )
    return core


def load_sealed_json(path: str) -> Dict[str, Any]:
    with open(path) as f:
        try:
            obj = json.load(f)
        except ValueError as e:
            raise StorageCorruptError(
                f"sealed record {path}: unparseable JSON ({e})"
            ) from e
    return verify_sealed(obj, path)


# ---------------------------------------------------------------------------
# tolerant JSONL reading + torn-tail repair
# ---------------------------------------------------------------------------


class JsonlCorruptError(StorageCorruptError):
    """A JSONL file has an unparseable line that is NOT the tail — a
    torn tail is the crash shape and repairable; mid-file corruption is
    not, and must be surfaced, not silently skipped."""


def read_jsonl_tolerant(
    path: str,
    *,
    repair: bool = False,
    artifact: str = "journal",
    tenant: Optional[str] = None,
    repair_dir: Optional[str] = None,
) -> Tuple[List[dict], Optional[dict]]:
    """Parse a JSONL file, tolerating exactly the damage a crash
    mid-append leaves: an unparseable (or unterminated) FINAL line.

    Returns ``(records, repair_record)``.  With ``repair=True`` a torn
    tail is truncated out of the file and the action is journaled to
    ``<repair_dir>/storage_repair.jsonl`` (default: the file's own
    directory) plus a ``storage_repair`` event + counter — the repair
    is itself evidence.  With ``repair=False`` the torn tail is
    reported in ``repair_record`` but the file is left untouched.
    An unparseable line ANYWHERE ELSE raises :class:`JsonlCorruptError`
    naming file and line number."""
    if not os.path.exists(path):
        return [], None
    with open(path, "rb") as f:
        raw = f.read()
    records: List[dict] = []
    torn_at: Optional[int] = None  # byte offset where the torn tail starts
    lines = raw.split(b"\n")
    offset = 0
    for i, line in enumerate(lines):
        text = line.strip()
        nxt = offset + len(line) + 1
        if text:
            rest_blank = all(not l.strip() for l in lines[i + 1:])
            try:
                records.append(json.loads(text.decode()))
            except (ValueError, UnicodeDecodeError) as e:
                if not rest_blank:
                    # mid-file damage — a torn line followed by later
                    # appends — is NOT the simple crash shape; eliding
                    # it could silently rewrite history
                    raise JsonlCorruptError(
                        f"{path}: unparseable JSONL at line {i + 1} "
                        f"(byte {offset}): {e}"
                    ) from e
                torn_at = offset
                break
        offset = nxt
    if torn_at is None:
        return records, None
    rec = {
        "action": "truncate_torn_tail",
        "path": path,
        "artifact": artifact,
        "torn_at_byte": torn_at,
        "torn_bytes": len(raw) - torn_at,
        "repaired": bool(repair),
        "ts": time.time(),
    }
    if repair:
        with open(path, "r+b") as f:
            f.truncate(torn_at)
        journal_repair(
            rec, root=repair_dir or (os.path.dirname(path) or "."),
            tenant=tenant,
        )
    return records, rec


_repair_writers_lock = threading.Lock()
_repair_writers: Dict[Tuple[str, Optional[str]], "RotatingJsonlWriter"] = {}


def _repair_writer(root: str, tenant: Optional[str]):
    """One PERSISTENT writer per (root, tenant): a repair record that
    could only buffer (disk full during the repair itself) must
    survive to flush when the disk recovers — a throwaway writer would
    drop the buffered record with the object."""
    key = (os.path.abspath(root), tenant)
    with _repair_writers_lock:
        w = _repair_writers.get(key)
        if w is None:
            w = RotatingJsonlWriter(
                os.path.join(root, REPAIR_JOURNAL),
                artifact="repair_journal", tenant=tenant,
            )
            _repair_writers[key] = w
        return w


def journal_repair(
    record: dict, *, root: str, tenant: Optional[str] = None
) -> None:
    """Append one repair record to ``<root>/storage_repair.jsonl``
    (rotating, DEGRADE policy — a repair journal that cannot write
    must not turn a successful repair into a failure), count it, and
    emit a ``storage_repair`` event."""
    inc(
        "sntc_storage_repairs_total",
        **_labels(record.get("artifact", "journal"), tenant),
    )
    fields = dict(
        event="storage_repair", component=_component("repair", tenant),
        **{k: v for k, v in record.items() if k != "ts"},
    )
    if tenant is not None:
        fields["tenant"] = tenant
    emit_event(**fields)
    _repair_writer(root, tenant).write(record)


# ---------------------------------------------------------------------------
# the rotating journal writer (size-capped JSONL under every journal)
# ---------------------------------------------------------------------------


class RotatingJsonlWriter:
    """Size-capped JSONL appender with the DEGRADE failure policy.

    ``write(record)`` appends one JSON line to ``path``; when the
    current segment would exceed ``max_bytes`` it first rotates
    ``path -> path.1 -> … -> path.keep`` (oldest deleted), so the
    journal's on-disk footprint is bounded at ``(keep + 1) ×
    max_bytes`` forever.  A failed write (real ENOSPC/EIO or an armed
    ``storage.journal`` fault) buffers the record in a bounded
    in-memory ring, opens a counted ``storage_degraded`` episode, and
    returns False — the caller keeps serving; the next successful
    write flushes the buffered backlog first and closes the episode
    with ``storage_recovered``.  A torn partial line from a failed
    append is truncated back out (best-effort) so the file stays
    parseable.  Thread-safe; cheap (no open handle held between
    writes, matching the append-journal callers it replaces)."""

    BUFFER_KEEP = 256

    def __init__(
        self,
        path: str,
        *,
        artifact: str = "shed_journal",
        max_bytes: int = 8 << 20,
        keep: int = 2,
        tenant: Optional[str] = None,
        site: str = "storage.journal",
    ):
        self.path = path
        self.artifact = artifact
        self.max_bytes = int(max_bytes)
        self.keep = max(0, int(keep))
        self.tenant = tenant
        self.site = site
        self._lock = threading.Lock()
        self._buffer: List[str] = []
        self.records_written = 0
        self.records_dropped = 0
        self.write_errors = 0
        self.rotations = 0

    # -- rotation ----------------------------------------------------------

    def _rotate_locked(self) -> None:
        if self.keep == 0:
            os.unlink(self.path)
            self.rotations += 1
            return
        oldest = f"{self.path}.{self.keep}"
        if os.path.exists(oldest):
            os.unlink(oldest)
        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self.rotations += 1

    def _append_locked(self, lines: List[str]) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        size = (
            os.path.getsize(self.path)
            if os.path.exists(self.path) else 0
        )
        payload = "".join(lines)
        if size and size + len(payload) > self.max_bytes:
            self._rotate_locked()
        with open(self.path, "a") as f:  # storage: registered-artifact
            append_line(f, payload, site=self.site, tenant=self.tenant)

    # -- the one public call ----------------------------------------------

    def write(self, record: dict) -> bool:
        """Append ``record``; returns False when it (only) buffered."""
        line = json.dumps(record) + "\n"
        with self._lock:
            pending = self._buffer + [line]
            try:
                self._append_locked(pending)
            except OSError as e:
                self.write_errors += 1
                self._buffer = pending[-self.BUFFER_KEEP:]
                self.records_dropped += len(pending) - len(self._buffer)
                note_write_error(
                    self.artifact, self.path, e, tenant=self.tenant,
                    buffered=len(self._buffer),
                )
                return False
            self._buffer = []
            self.records_written += len(pending)
            note_write_ok(self.artifact, tenant=self.tenant)
            return True

    def stats(self) -> dict:
        with self._lock:
            return {
                "path": self.path,
                "records_written": self.records_written,
                "buffered": len(self._buffer),
                "records_dropped": self.records_dropped,
                "write_errors": self.write_errors,
                "rotations": self.rotations,
            }


# ---------------------------------------------------------------------------
# dead-letter retention (keep-N newest, drop oldest, counted)
# ---------------------------------------------------------------------------


def prune_dir_keep_newest(
    path: str,
    keep: int,
    *,
    artifact: str,
    tenant: Optional[str] = None,
    max_bytes: Optional[int] = None,
    protect: Tuple[str, ...] = (),
) -> int:
    """Enforce a keep-N (and optional byte-cap) policy on a flat
    evidence directory: the OLDEST entries (sorted name order — batch
    ids sort chronologically) are deleted until at most ``keep`` files
    and ``max_bytes`` bytes remain.  Every deletion counts into
    ``sntc_dead_letter_dropped_total`` and one ``dead_letter_dropped``
    event summarizes the pass — bounded growth is a recorded decision,
    never silent.  Returns files dropped."""
    if not os.path.isdir(path):
        return 0
    names = sorted(
        n for n in os.listdir(path)
        if n not in protect and not n.startswith(".")
        and os.path.isfile(os.path.join(path, n))
    )
    drop = names[:-keep] if keep > 0 and len(names) > keep else []
    kept = [n for n in names if n not in set(drop)]
    if max_bytes is not None:
        total = 0
        sizes = {}
        for n in kept:
            try:
                sizes[n] = os.path.getsize(os.path.join(path, n))
            except OSError:
                sizes[n] = 0
            total += sizes[n]
        i = 0
        while total > max_bytes and i < len(kept) - 1:
            drop.append(kept[i])
            total -= sizes[kept[i]]
            i += 1
    if not drop:
        return 0
    dropped = 0
    for n in drop:
        try:
            os.unlink(os.path.join(path, n))
            dropped += 1
        except OSError:
            pass
    if dropped:
        inc(
            "sntc_dead_letter_dropped_total", dropped,
            **_labels(artifact, tenant),
        )
        fields = dict(
            event="dead_letter_dropped",
            component=_component(artifact, tenant),
            artifact=artifact, path=path, dropped=dropped,
            keep=keep, reason="retention",
        )
        if tenant is not None:
            fields["tenant"] = tenant
        emit_event(**fields)
    return dropped


# ---------------------------------------------------------------------------
# disk accounting & budgets
# ---------------------------------------------------------------------------


class StoragePlane:
    """Disk accounting for one checkpoint root: per-artifact bytes and
    file counts into the ``sntc_disk_*`` gauges, an optional byte
    budget with a counted breach event, and the ``storage`` status
    block the supervisor/daemon dumps."""

    def __init__(
        self,
        root: str,
        *,
        tenant: Optional[str] = None,
        budget_bytes: Optional[int] = None,
        min_interval_s: float = 5.0,
        clock=time.monotonic,
    ):
        self.root = root
        self.tenant = tenant
        self.budget_bytes = budget_bytes
        self._over_budget = False
        # status() rides per-tick dumps — the tree walk is throttled so
        # accounting stays off the hot path (force with usage())
        self.min_interval_s = float(min_interval_s)
        self._clock = clock
        self._cached_usage: Optional[Dict[str, Any]] = None
        self._measured_at: Optional[float] = None
        self._published_artifacts: set = set()

    def usage(self) -> Dict[str, Any]:
        """Measure every registered artifact under the root (plus the
        whole-tree total) and publish the gauges."""
        per: Dict[str, Dict[str, int]] = {}
        for spec in ARTIFACTS.values():
            b = n = 0
            for pattern in spec.patterns:
                for p in glob.glob(os.path.join(self.root, pattern)):
                    if os.path.isfile(p):
                        try:
                            b += os.path.getsize(p)
                            n += 1
                        except OSError:
                            pass
            if n:
                per.setdefault(spec.name, {"bytes": 0, "files": 0})
                per[spec.name]["bytes"] += b
                per[spec.name]["files"] += n
        total_b = total_n = 0
        for dirpath, _dirs, files in os.walk(self.root):
            for name in files:
                try:
                    total_b += os.path.getsize(os.path.join(dirpath, name))
                    total_n += 1
                except OSError:
                    pass
        # zero out gauges for artifacts that HAD files last pass and
        # have none now (fsck quarantined them, retention emptied the
        # dir) — a skipped series would report phantom bytes forever
        for name in self._published_artifacts - set(per):
            set_gauge("sntc_disk_bytes", 0, **_labels(name, self.tenant))
            set_gauge("sntc_disk_files", 0, **_labels(name, self.tenant))
        self._published_artifacts = set(per)
        for name, row in per.items():
            set_gauge(
                "sntc_disk_bytes", row["bytes"],
                **_labels(name, self.tenant),
            )
            set_gauge(
                "sntc_disk_files", row["files"],
                **_labels(name, self.tenant),
            )
        set_gauge(
            "sntc_disk_bytes", total_b, **_labels("total", self.tenant)
        )
        set_gauge(
            "sntc_disk_files", total_n, **_labels("total", self.tenant)
        )
        if self.budget_bytes is not None:
            labels = (
                {} if self.tenant is None else {"tenant": self.tenant}
            )
            set_gauge(
                "sntc_disk_budget_bytes", self.budget_bytes, **labels
            )
        out = {
            "artifacts": per,
            "total_bytes": total_b,
            "total_files": total_n,
        }
        self._cached_usage = out
        self._measured_at = self._clock()
        return out

    def _usage_throttled(self) -> Dict[str, Any]:
        if (
            self._cached_usage is not None
            and self._measured_at is not None
            and self._clock() - self._measured_at < self.min_interval_s
        ):
            return self._cached_usage
        return self.usage()

    def check_budget(
        self, usage: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """One accounting pass: measure, compare against the budget,
        emit ``disk_budget_exceeded`` once per breach episode.  The
        caller (supervisor/daemon tick, engine commit cadence) decides
        what retention to tighten; this plane only measures and
        reports."""
        usage = usage or self._usage_throttled()
        over = (
            self.budget_bytes is not None
            and usage["total_bytes"] > self.budget_bytes
        )
        if over and not self._over_budget:
            # register the breach as a degradation episode so the
            # recovery branch below can actually close it (emit
            # storage_recovered -> OK health) when usage falls back
            with _deg_lock:
                _degraded.add(("budget", self.tenant))
            set_gauge(
                "sntc_storage_degraded_state", 1,
                **_labels("budget", self.tenant),
            )
            fields = dict(
                event="disk_budget_exceeded",
                component=_component("budget", self.tenant),
                root=self.root, total_bytes=usage["total_bytes"],
                budget_bytes=self.budget_bytes,
            )
            if self.tenant is not None:
                fields["tenant"] = self.tenant
            emit_event(**fields)
        elif not over and self._over_budget:
            note_write_ok("budget", tenant=self.tenant)
        self._over_budget = over
        return dict(
            usage,
            budget_bytes=self.budget_bytes,
            over_budget=over,
            degraded=[
                {"artifact": a, "tenant": t}
                for a, t in degraded_artifacts()
                if t == self.tenant or t is None
            ],
        )

    def status(self) -> Dict[str, Any]:
        return self.check_budget()


# ---------------------------------------------------------------------------
# fsck: the doctor
# ---------------------------------------------------------------------------


def quarantine_blob(
    path: str, *, artifact: str, detail: str, root: str,
    tenant: Optional[str] = None,
) -> Optional[str]:
    """Move a corrupt blob aside to ``.corrupt/`` beside its directory
    and journal the action to ``<root>/storage_repair.jsonl`` —
    returns the destination, or None when the move itself failed.
    Shared by the fsck doctor and the engine's own recovery paths (a
    torn files-mode commit record), so 'quarantine' means one thing."""
    corrupt_dir = os.path.join(os.path.dirname(path), ".corrupt")
    os.makedirs(corrupt_dir, exist_ok=True)
    dest = os.path.join(corrupt_dir, os.path.basename(path))
    try:
        os.replace(path, dest)  # storage: registered-artifact
    except OSError:
        return None
    journal_repair(
        {
            "action": "quarantine_corrupt",
            "path": path,
            "artifact": artifact,
            "quarantined_to": dest,
            "detail": detail,
            "ts": time.time(),
        },
        root=root, tenant=tenant,
    )
    return dest


def _quarantine_file(
    path: str, report: dict, *, artifact: str, detail: str,
    repair: bool, root: str, tenant: Optional[str] = None,
) -> None:
    """Move a corrupt blob aside to ``<dir>/.corrupt/`` (repair mode)
    or report it; either way the report carries the evidence."""
    entry = {"path": path, "artifact": artifact, "detail": detail}
    if not repair:
        report["errors"].append(entry)
        return
    dest = quarantine_blob(
        path, artifact=artifact, detail=detail, root=root, tenant=tenant,
    )
    if dest is None:
        report["errors"].append(
            dict(entry, detail=f"{detail}; quarantine failed")
        )
        return
    entry["quarantined_to"] = dest
    report["quarantined"].append(entry)


def _check(report: dict, artifact: str, n: int = 1) -> None:
    report["checked"][artifact] = report["checked"].get(artifact, 0) + n


def _fsck_journals(root: str, report: dict, repair: bool,
                   tenant: Optional[str]) -> None:
    patterns = [
        "shed.jsonl*", "controller.jsonl*", "promotion.jsonl*",
        REPAIR_JOURNAL + "*",
        os.path.join("dead_letter", "dead_letter.jsonl*"),
        os.path.join("dead_letter_rows", "*.jsonl"),
    ]
    for pattern in patterns:
        for path in sorted(glob.glob(os.path.join(root, pattern))):
            artifact = _artifact_for(os.path.relpath(path, root))
            _check(report, artifact)
            try:
                _records, rec = read_jsonl_tolerant(
                    path, repair=repair, artifact=artifact,
                    tenant=tenant, repair_dir=root,
                )
            except JsonlCorruptError as e:
                _quarantine_file(
                    path, report, artifact=artifact, detail=str(e),
                    repair=repair, root=root, tenant=tenant,
                )
                continue
            if rec is not None:
                (report["repaired"] if repair else report["errors"]).append(
                    {"path": path, "artifact": artifact, **rec}
                )


def _artifact_for(rel: str) -> str:
    """Best-match artifact name for a root-relative path."""
    import fnmatch

    for spec in ARTIFACTS.values():
        for pattern in spec.patterns:
            if fnmatch.fnmatch(rel, pattern):
                return spec.name
    return "journal"


def _fsck_append_wal(root: str, report: dict, repair: bool,
                     tenant: Optional[str]) -> None:
    for name in ("offsets.log", "commits.log"):
        path = os.path.join(root, name)
        if not os.path.exists(path):
            continue
        _check(report, "wal_append")
        try:
            _records, rec = read_jsonl_tolerant(
                path, repair=repair, artifact="wal_append",
                tenant=tenant, repair_dir=root,
            )
        except JsonlCorruptError as e:
            # mid-file WAL corruption is NOT auto-repairable: eliding a
            # commit record would silently replay (and double-sink) a
            # committed batch.  Surface it; the operator decides.
            report["errors"].append(
                {"path": path, "artifact": "wal_append", "detail": str(e)}
            )
            continue
        if rec is not None:
            (report["repaired"] if repair else report["errors"]).append(
                {"path": path, "artifact": "wal_append", **rec}
            )
    ckpt = os.path.join(root, "wal_checkpoint.json")
    if os.path.exists(ckpt):
        _check(report, "wal_append")
        try:
            load_sealed_json(ckpt)
        except StorageCorruptError as e:
            # a corrupt compaction checkpoint loses the truncated
            # history — nothing safe to rebuild it from; loud error
            report["errors"].append(
                {"path": ckpt, "artifact": "wal_append", "detail": str(e)}
            )


def _fsck_files_wal(root: str, report: dict, repair: bool,
                    tenant: Optional[str]) -> None:
    for sub in ("offsets", "commits"):
        for path in sorted(
            glob.glob(os.path.join(root, sub, "*.json"))
        ):
            _check(report, "wal_files")
            try:
                with open(path) as f:
                    json.load(f)
            except ValueError as e:
                # a torn per-batch intent/commit file reads as absent —
                # exactly the crash contract (the batch replays) — so
                # quarantining it is safe AND preserves the evidence
                _quarantine_file(
                    path, report, artifact="wal_files",
                    detail=f"unparseable WAL record: {e}",
                    repair=repair, root=root, tenant=tenant,
                )


def _fsck_flow_state(root: str, report: dict, repair: bool,
                     tenant: Optional[str]) -> None:
    state_dir = os.path.join(root, "flow_state")
    if not os.path.isdir(state_dir):
        return
    from sntc_tpu.flow.state import FlowStateCorruptError, verify_snapshot

    for path in sorted(glob.glob(os.path.join(state_dir, "state-*.bin"))):
        _check(report, "flow_state")
        try:
            verify_snapshot(path)
        except FlowStateCorruptError as e:
            _quarantine_file(
                path, report, artifact="flow_state", detail=str(e),
                repair=repair, root=root, tenant=tenant,
            )


def _fsck_markers(root: str, report: dict, repair: bool,
                  tenant: Optional[str]) -> None:
    for name in ARTIFACTS["markers"].patterns:
        path = os.path.join(root, name)
        if not os.path.exists(path):
            continue
        _check(report, "markers")
        try:
            with open(path) as f:
                json.load(f)
        except ValueError as e:
            _quarantine_file(
                path, report, artifact="markers",
                detail=f"unparseable marker: {e}",
                repair=repair, root=root, tenant=tenant,
            )


def _fsck_checkpoints(root: str, report: dict) -> None:
    """Verify any mlio model checkpoint (a dir with ``_manifest.json``)
    under the root against its sha256 manifest — read-only: a failed
    model dir has its own ``.prev`` fallback machinery; fsck reports."""
    from sntc_tpu.mlio.save_load import verify_checkpoint

    for manifest in glob.glob(
        os.path.join(root, "**", "_manifest.json"), recursive=True
    ):
        ckpt_dir = os.path.dirname(manifest)
        if os.sep + ".corrupt" + os.sep in ckpt_dir + os.sep:
            continue
        _check(report, "checkpoint")
        try:
            verify_checkpoint(ckpt_dir)
        except Exception as e:
            report["errors"].append(
                {
                    "path": ckpt_dir, "artifact": "checkpoint",
                    "detail": f"manifest verification failed: {e}",
                }
            )


def _fsck_tmp_orphans(root: str, report: dict, repair: bool) -> None:
    """Sweep ``*.tmp`` / ``*.tmp-<pid>`` orphans our atomic publishes
    leave behind when they die mid-write."""
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = [d for d in dirs if d != ".corrupt"]
        for name in files:
            stem, _, suffix = name.rpartition(".tmp")
            if not stem or (suffix and not suffix.lstrip("-").isdigit()):
                continue
            path = os.path.join(dirpath, name)
            _check(report, "tmp_orphans")
            if repair:
                try:
                    os.unlink(path)
                    report["cleaned"].append({"path": path})
                except OSError as e:
                    report["errors"].append(
                        {"path": path, "detail": f"unlink failed: {e}"}
                    )
            else:
                report["errors"].append(
                    {"path": path, "detail": "orphaned tmp file"}
                )


def fsck_root(
    root: str, *, repair: bool = True, tenant: Optional[str] = None,
) -> Dict[str, Any]:
    """Doctor ONE checkpoint root: verify every registered artifact,
    repair what is safe, quarantine what is not, report everything."""
    report: Dict[str, Any] = {
        "root": root,
        "tenant": tenant,
        "repair": bool(repair),
        "checked": {},
        "repaired": [],
        "quarantined": [],
        "cleaned": [],
        "errors": [],
    }
    if not os.path.isdir(root):
        report["errors"].append(
            {"path": root, "detail": "checkpoint root does not exist"}
        )
        report["ok"] = False
        return report
    _fsck_append_wal(root, report, repair, tenant)
    _fsck_files_wal(root, report, repair, tenant)
    _fsck_journals(root, report, repair, tenant)
    _fsck_flow_state(root, report, repair, tenant)
    _fsck_markers(root, report, repair, tenant)
    _fsck_checkpoints(root, report)
    _fsck_tmp_orphans(root, report, repair)
    report["ok"] = not report["errors"]
    return report


def fsck(
    root: str, *, repair: bool = True, tenant_tree: bool = False,
) -> Dict[str, Any]:
    """The ``sntc fsck`` entry: doctor a single checkpoint root, or —
    with ``tenant_tree=True`` — a ServeDaemon root plus every
    ``<root>/tenant/<id>/ckpt`` under it.  Returns one machine-readable
    report; ``ok`` is the AND over every walked root."""
    roots: List[Tuple[str, Optional[str]]] = [(root, None)]
    if tenant_tree:
        for p in sorted(glob.glob(os.path.join(root, "tenant", "*"))):
            ckpt = os.path.join(p, "ckpt")
            if os.path.isdir(ckpt):
                roots.append((ckpt, os.path.basename(p)))
    reports = [
        fsck_root(r, repair=repair, tenant=t) for r, t in roots
    ]
    if not tenant_tree:
        return reports[0]
    return {
        "root": root,
        "tenant_tree": True,
        "repair": bool(repair),
        "ok": all(r["ok"] for r in reports),
        "roots": reports,
    }


def quick_scan(
    root: str, tenant: Optional[str] = None
) -> Optional[Dict[str, Any]]:
    """The light construction-time doctor every engine runs over its
    checkpoint dir: repair torn tails on the top-level journals and
    sweep tmp orphans — cheap (no hashing, no snapshot verification)
    and NEVER fatal: a scan bug must not stop serving (the append-WAL's
    own torn-tail repair lives in its reader and runs regardless)."""
    try:
        if not os.path.isdir(root):
            return None
        report: Dict[str, Any] = {
            "root": root, "tenant": tenant, "repair": True,
            "checked": {}, "repaired": [], "quarantined": [],
            "cleaned": [], "errors": [],
        }
        _fsck_journals(root, report, True, tenant)
        _fsck_tmp_orphans(root, report, True)
        report["ok"] = not report["errors"]
        return report
    except Exception as e:  # pragma: no cover - defensive
        try:
            emit_event(
                event="storage_degraded",
                component=_component("scan", tenant),
                artifact="scan", path=root, error=repr(e),
            )
        except Exception:
            pass
        return None
