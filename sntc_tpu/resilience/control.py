"""Shared closed-loop control guardrails — the hysteresis substrate
under every knob-steering controller in the codebase.

PR 10 proved the control-loop idiom on the ingest graph: feedback
signal → hysteresis-guarded single-knob step → journaled decision →
provable no-oscillation bound.  PR 13 generalizes it to the whole
serve plane, so the guardrail machinery moves HERE — one
implementation under both the :class:`~sntc_tpu.data.autotune
.IngestAutotuner` (which keeps its exact pre-extraction behavior;
its property tests pass unchanged) and the
:class:`~sntc_tpu.serve.controller.ServeController`.

:class:`Guardrails` is the state machine:

* **confirm streak** — a proposal must repeat ``confirm`` consecutive
  observation windows before it applies; any different proposal (or
  no proposal) resets the streak.
* **cooldown** — every applied (or budget-denied) decision freezes the
  controller for ``cooldown`` windows.
* **reversal freeze** — a knob that reverses direction more than
  ``max_reversals`` times is FROZEN for the controller's lifetime.
  Total knob changes are therefore bounded by
  ``Σ_knobs (max_reversals + 1) × (hi − lo) / step`` regardless of the
  input signal — THE no-oscillation bound, property-tested over the
  union of serving + ingest knobs in ``tests/test_controller.py`` and
  over the ingest knobs alone in ``tests/test_ingest_pipeline.py``.
* **bounded journal** — every applied/denied/frozen decision is kept
  in memory (oldest evicted past ``journal_keep``; ``decisions_total``
  preserved) and handed to ``on_journal`` so owners can mirror it to
  events, metrics, and durable journals.

:class:`TuningBudget` is the multi-controller arbiter: one budget
shared by every tenant's controller caps the total EXTRA capacity
(pool threads, staged ranges, pipeline slots, ...) the fleet may grow
beyond its cold defaults.  The budget charges only capacity ABOVE each
knob's cold-start baseline: shrinking below the baseline refunds
nothing, and regrowing back to it is free — an idle fleet can always
recover its defaults on an exhausted budget.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple


@dataclass
class ControlPolicy:
    """The guardrail constants every controller shares.  Deliberately
    conservative defaults — two confirming windows, two cooldown
    windows, two reversals — so a production plane changes a knob at
    most a handful of times, then sits still."""

    confirm: int = 2          # consecutive agreeing windows to apply
    cooldown: int = 2         # windows frozen after an apply
    max_reversals: int = 2    # direction flips per knob before freezing


class TuningBudget:
    """Shared cap on the EXTRA capacity controllers may grow beyond
    their cold defaults, per knob kind.  ``try_acquire`` charges one
    increase (False = budget exhausted, the decision is journaled as
    denied and not applied); ``release`` refunds a decrease.  All
    methods are thread-safe — tenants tick on one daemon thread today,
    but the budget must not care.

    Kinds are open-ended: any keyword cap names a kind (``None`` =
    uncapped); kinds never declared are uncapped but still tracked.
    """

    def __init__(self, **caps: Optional[int]):
        self._caps: Dict[str, Optional[int]] = dict(caps)
        self._used: Dict[str, int] = {k: 0 for k in self._caps}
        self._lock = threading.Lock()

    @classmethod
    def default_for(cls, n_tenants: int) -> "TuningBudget":
        """The serve-daemon default: the whole fleet may grow at most
        one host's worth of parse threads, two staged ranges per
        tenant, and one extra pipeline slot per tenant."""
        import os

        return cls(
            read_workers=max(4, (os.cpu_count() or 4)),
            prefetch_batches=max(4, 2 * n_tenants),
            pipeline_depth=max(2, n_tenants),
        )

    def try_acquire(self, knob: str, n: int = 1) -> bool:
        with self._lock:
            cap = self._caps.get(knob)
            if cap is not None and self._used.get(knob, 0) + n > cap:
                return False
            self._used[knob] = self._used.get(knob, 0) + n
            return True

    def release(self, knob: str, n: int = 1) -> None:
        with self._lock:
            self._used[knob] = max(0, self._used.get(knob, 0) - n)

    def snapshot(self) -> Dict[str, Dict[str, Optional[int]]]:
        with self._lock:
            keys = set(self._caps) | set(self._used)
            return {
                k: {"cap": self._caps.get(k),
                    "used": self._used.get(k, 0)}
                for k in sorted(keys)
            }


class Guardrails:
    """The hysteresis state machine (module docstring).  Owners call
    :meth:`observe` once per observation window with a pure
    ``propose`` callable; the guardrails decide whether this window's
    proposal survives confirm/cooldown/freeze/budget and, when it
    does, apply it through the knob's live setter and journal it.

    ``policy`` may be any object with ``confirm`` / ``cooldown`` /
    ``max_reversals`` attributes (:class:`ControlPolicy`, or the
    autotuner's richer ``AutotunePolicy``).  ``budget_kind`` maps a
    knob name to its budget kind (identity by default — the serve
    controller strips its ``tenant/<id>/`` namespacing here so ten
    tenants' ``quota`` knobs draw one budget line)."""

    def __init__(
        self,
        policy=None,
        budget: Optional[TuningBudget] = None,
        *,
        journal_keep: int = 256,
        budget_kind: Optional[Callable[[str], str]] = None,
        on_journal: Optional[Callable[[dict], None]] = None,
    ):
        self.policy = policy or ControlPolicy()
        self.budget = budget
        self.budget_kind = budget_kind or (lambda name: name)
        self.on_journal = on_journal
        #: applied/denied/frozen journal, oldest evicted past the cap
        #: (a budget-starved controller re-denies every few windows
        #: forever; the in-memory journal must not grow with uptime —
        #: the event stream + metrics carry the full history)
        self.decisions: List[dict] = []
        self.decisions_total = 0
        self._journal_keep = int(journal_keep)
        self._baseline: Dict[str, int] = {}  # knob cold-start values
        self._budget_held: Dict[str, int] = {}  # EXTRA units charged
        self.windows = 0
        self._pending: Optional[Tuple[str, int]] = None
        self._streak = 0
        self._cooldown = 0
        self._last_dir: Dict[str, int] = {}
        self._reversals: Dict[str, int] = {}
        self.frozen: set = set()

    def usable(self, knobs: Dict, name: str, direction: int) -> bool:
        """Can ``name`` move one step in ``direction``?  (Bounds +
        freeze; the shared precondition every propose() checks.)"""
        k = knobs.get(name)
        if k is None or name in self.frozen:
            return False
        cur = k.get()
        return cur < k.hi if direction > 0 else cur > k.lo

    def observe(
        self,
        propose: Callable[[], Optional[Tuple[str, int]]],
        knobs: Dict,
        signal_fields,
        on_applied: Optional[Callable[[str, int, int], None]] = None,
    ) -> Optional[dict]:
        """One observation window: hysteresis + budget + apply.
        ``signal_fields`` is the journal's ``signal`` payload — a dict,
        or a zero-arg callable evaluated only when a record is actually
        journaled.  Returns the journaled record when a knob moved (or
        froze, or was denied), None otherwise."""
        self.windows += 1
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        prop = propose()
        if prop != self._pending:
            self._pending = prop
            self._streak = 1 if prop is not None else 0
            return None
        if prop is None:
            return None
        self._streak += 1
        if self._streak < self.policy.confirm:
            return None
        name, direction = prop
        self._pending, self._streak = None, 0
        knob = knobs[name]
        last = self._last_dir.get(name)
        if last is not None and last != direction:
            self._reversals[name] = self._reversals.get(name, 0) + 1
            if self._reversals[name] > self.policy.max_reversals:
                self.frozen.add(name)
                return self._journal(
                    name, direction, knob.get(), knob.get(),
                    action="frozen", signal_fields=signal_fields,
                )
        cur = knob.get()
        new = knob.clamp(cur + direction * knob.step)
        if new == cur:
            return None
        if self.budget is not None:
            # budget charges only the EXTRA capacity above this knob's
            # COLD-START value (captured at first contact): shrinking
            # below the baseline refunds nothing (nothing was charged),
            # and regrowing back to it costs nothing — so an idle fleet
            # that dipped under its defaults can always recover them
            kind = self.budget_kind(name)
            baseline = self._baseline.setdefault(name, cur)
            held = self._budget_held.get(name, 0)
            want = max(0, new - baseline)
            if want > held:
                if not self.budget.try_acquire(kind, want - held):
                    self._cooldown = self.policy.cooldown
                    return self._journal(
                        name, direction, cur, cur,
                        action="budget_denied",
                        signal_fields=signal_fields,
                    )
            elif want < held:
                self.budget.release(kind, held - want)
            self._budget_held[name] = want
        knob.set(new)
        self._last_dir[name] = direction
        self._cooldown = self.policy.cooldown
        if on_applied is not None:
            on_applied(name, direction, new)
        return self._journal(
            name, direction, cur, new, action="applied",
            signal_fields=signal_fields,
        )

    def _journal(self, name, direction, old, new, *, action,
                 signal_fields) -> dict:
        rec = {
            "action": action,
            "knob": name,
            "direction": "up" if direction > 0 else "down",
            "from": old,
            "to": new,
            "window": self.windows,
            "signal": (
                signal_fields() if callable(signal_fields)
                else signal_fields
            ),
        }
        self.decisions.append(rec)
        self.decisions_total += 1
        if len(self.decisions) > self._journal_keep:
            del self.decisions[0]
        if self.on_journal is not None:
            self.on_journal(rec)
        return rec

    def applied(self) -> List[dict]:
        return [d for d in self.decisions if d["action"] == "applied"]

    @staticmethod
    def change_bound(knobs: Dict, max_reversals: int) -> int:
        """The analytic no-oscillation bound over ``knobs``:
        ``Σ (max_reversals + 1) × (hi − lo) / step`` applied changes,
        regardless of the input signal."""
        return sum(
            (max_reversals + 1) * (k.hi - k.lo) // max(1, k.step)
            for k in knobs.values()
        )
