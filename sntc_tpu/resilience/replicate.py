"""Warm-standby disaster recovery (r23): continuous tenant-state
replication with commit barriers, promotion, and RPO/RTO evidence.

Every durability guarantee the stack has earned lives on ONE root
filesystem; a lost disk is still unrecoverable data loss.  This module
closes that hole with a :class:`ReplicationPlane` that continuously
ships a tenant's durable artifact tree — WAL segments + compaction
checkpoints, flow-state snapshots, model checkpoints, markers,
rotating journals, everything the PR-12 ``ARTIFACTS`` registry
declares, so NEW artifact classes replicate by construction — to a
warm-standby root under the fleet's sealed-sha256-manifest shipping
discipline.

**Commit barriers.**  At each engine commit (the ``commit_listener``
hook on :class:`~sntc_tpu.serve.streaming.StreamingQuery`) the plane
ships the changed files, publishes a sealed ``replica_manifest.json``,
and appends a sealed **barrier record** keyed to the committed batch
and offset to a standby-resident barrier log.  The barrier is the
durable ack "the replica holds everything through batch B": the
standby always has a provably consistent prefix to promote from, and
batch ids are engine-sequential so ``batches_through == batch_id + 1``
stays exact across plane restarts.

**Promotion.**  :func:`promote_standby` fscks the replica, verifies
every manifest entry (immutable artifacts re-hashed against their
sealed sha256 — a mismatch quarantines to ``.corrupt/`` and the
promotion REFUSES to serve), sweeps un-manifested stragglers from a
torn ship aside, copies the verified tree to the destination root,
and truncates it to the last sealed barrier (post-barrier commits and
sink files are dropped; the promoted engine re-serves them from the
source).  RPO is the measured barrier lag (bytes + seconds), RTO is
the measured promotion wall-clock, and the loss-accounting law

    committed == replicated_through_barrier + counted_tail_loss

holds EXACTLY in batches (the ingress conservation-law discipline) —
any loss is loud, never silent.

**Anti-entropy.**  :func:`fsck_standby` (``sntc fsck --standby``)
cross-verifies primary vs replica manifests and journals a
``replica_diverged`` event per mismatch.

Fault sites: ``repl.ship`` (per shipped file), ``repl.apply`` (the
manifest publish), ``repl.barrier`` (the barrier append) — all three
in the chaos kill matrix.  A replication failure DEGRADES (counted,
journaled); it never fails the serving engine.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from sntc_tpu.obs.metrics import inc, set_gauge
from sntc_tpu.resilience import storage as _storage
from sntc_tpu.resilience.faults import fault_point
from sntc_tpu.resilience.policy import emit_event
from sntc_tpu.resilience.storage import (
    ARTIFACTS,
    RotatingJsonlWriter,
    StorageCorruptError,
    atomic_write_bytes,
    atomic_write_json,
    load_sealed_json,
    read_jsonl_tolerant,
    seal_record,
    verify_sealed,
)

MANIFEST_NAME = "replica_manifest.json"
BARRIER_LOG = "barriers.jsonl"
TREE_DIR = "tree"
SINK_DIR = "sink"
DEFAULT_TENANT = "default"
DEFAULT_SINK_PATTERNS = ("batch_*.csv",)

#: artifacts whose files are rewritten/appended in place — verified by
#: their own formats (sealed records, tolerant JSONL readers, fsck),
#: not by a point-in-time manifest hash.  Everything else is immutable
#: once published and MUST re-hash to its manifest sha256 at promotion.
_MUTABLE_BASENAMES = frozenset(
    (
        "offsets.log",
        "commits.log",
        "wal_checkpoint.json",
        "ingress_stats.json",
        "drain_marker.json",
        "model_marker.json",
        "daemon_drain_marker.json",
        "health.json",
    )
)

_SINK_IDX_RE = re.compile(r"batch_(\d+)")


def _labels(tenant: Optional[str]) -> Dict[str, str]:
    return {"tenant": tenant} if tenant else {}


def _is_mutable(rel: str) -> bool:
    base = os.path.basename(rel)
    return base in _MUTABLE_BASENAMES or ".jsonl" in base


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def replica_dir(standby_root: str, tenant: str) -> str:
    return os.path.join(standby_root, tenant)


def _ckpt_root(root: str) -> str:
    """A replicated root is either a bare checkpoint dir (``serve``)
    or a tenant tree with ``ckpt/`` inside (daemon / fleet worker)."""
    ckpt = os.path.join(root, "ckpt")
    return ckpt if os.path.isdir(ckpt) else root


def artifact_files(root: str) -> Dict[str, str]:
    """``rel -> artifact name`` for every live file under ``root``
    matched by a registered ``ARTIFACTS`` pattern — applied at the
    root AND at ``root/ckpt`` so bare-engine and tenant-tree layouts
    both enumerate.  New artifact classes added to the registry
    replicate by construction; ``.corrupt/`` quarantine and ``*.tmp-``
    orphans never ship."""
    out: Dict[str, str] = {}
    for spec in ARTIFACTS.values():
        for pat in spec.patterns:
            for base in ("", "ckpt"):
                for p in glob.glob(os.path.join(root, base, pat)):
                    if not os.path.isfile(p):
                        continue
                    rel = os.path.relpath(p, root)
                    if ".corrupt" in rel.split(os.sep) or ".tmp-" in rel:
                        continue
                    out.setdefault(rel, spec.name)
    return out


def committed_batches(ckpt_root: str) -> Dict[str, Any]:
    """Post-mortem committed-batch census of a checkpoint root, both
    WAL modes.  Batch ids are engine-sequential from 0, so ``count``
    is ``last committed id + 1`` even where retention/compaction has
    pruned the individual records."""
    last, end = -1, 0
    cdir = os.path.join(ckpt_root, "commits")
    if os.path.isdir(cdir):
        for p in glob.glob(os.path.join(cdir, "*.json")):
            try:
                with open(p) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue  # a torn commit never landed
            bid = int(rec["batch_id"])
            if bid > last:
                last, end = bid, int(rec["end"])
        return {"count": last + 1, "last_id": last, "last_end": end}
    ck = os.path.join(ckpt_root, "wal_checkpoint.json")
    if os.path.exists(ck):
        try:
            core = load_sealed_json(ck)
            last = int(core["last_committed"])
            end = int(core["end"])
        except (OSError, StorageCorruptError):
            pass
    clog = os.path.join(ckpt_root, "commits.log")
    if os.path.exists(clog):
        try:
            recs, _ = read_jsonl_tolerant(clog, repair=False)
        except _storage.JsonlCorruptError:
            recs = []
        for rec in recs:
            bid = int(rec.get("batch_id", -1))
            if bid > last:
                last, end = bid, int(rec["end"])
    return {"count": last + 1, "last_id": last, "last_end": end}


def last_barrier(standby_root: str, tenant: str) -> Optional[Dict[str, Any]]:
    """The newest VALID sealed barrier record, or None.  Walks the
    active barrier log then its rotated segments, newest line first;
    torn/corrupt lines (a crash mid-append, a broken seal) are simply
    skipped — the last *sealed* barrier is the promotion point."""
    base = os.path.join(replica_dir(standby_root, tenant), BARRIER_LOG)
    for path in (base, base + ".1", base + ".2"):
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in reversed(lines):
            line = line.strip()
            if not line:
                continue
            try:
                return verify_sealed(json.loads(line), path)
            except (ValueError, StorageCorruptError):
                continue
    return None


class ReplicationPlane:
    """Continuously replicate one primary root's durable artifact tree
    to ``<standby_root>/<tenant>/`` with sealed manifests and commit
    barriers.  Wire :meth:`on_commit` as the engine's
    ``commit_listener``; every ``barrier_every`` commits the plane
    ships changed files (``repl.ship`` per file), publishes the sealed
    manifest (``repl.apply``), and seals a barrier record
    (``repl.barrier``).  Failures degrade and retry at the next
    commit — replication never fails the serving engine."""

    def __init__(
        self,
        primary_root: str,
        standby_root: str,
        *,
        tenant: str = DEFAULT_TENANT,
        barrier_every: int = 1,
        sink_dir: Optional[str] = None,
        sink_patterns: Tuple[str, ...] = DEFAULT_SINK_PATTERNS,
    ) -> None:
        self.primary_root = primary_root
        self.standby_root = standby_root
        self.tenant = tenant or DEFAULT_TENANT
        self.barrier_every = max(1, int(barrier_every))
        self.sink_dir = sink_dir
        self.sink_patterns = tuple(sink_patterns)
        self.rep_dir = replica_dir(standby_root, self.tenant)
        self.tree_dir = os.path.join(self.rep_dir, TREE_DIR)
        self.sink_rep_dir = os.path.join(self.rep_dir, SINK_DIR)
        self.manifest_path = os.path.join(self.rep_dir, MANIFEST_NAME)
        self._barriers = RotatingJsonlWriter(
            os.path.join(self.rep_dir, BARRIER_LOG),
            artifact="repl_barrier", tenant=self.tenant,
            site="repl.barrier",
        )
        self._lock = threading.RLock()
        self._labels = _labels(self.tenant)
        # stamp cache: rel -> {"size", "sha256", "stamp"} per section
        self._entries: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._seq = 0
        self._pending: List[Tuple[int, int, int]] = []  # (bid, end, rows)
        self.ships = 0
        self.ship_errors = 0
        self.barriers_sealed = 0
        self._load_replica_state()

    # -- resume ------------------------------------------------------------

    def _load_replica_state(self) -> None:
        """Adopt the on-disk replica: manifest entries become the skip
        cache (files re-hash, not re-ship, on the first sync) and the
        last sealed barrier anchors the cumulative counters — a plane
        restart never forgets what the standby already holds."""
        try:
            man = load_sealed_json(self.manifest_path)
        except (OSError, StorageCorruptError):
            man = None
        if man:
            self._seq = int(man.get("seq", 0)) + 1
            for section, key, base in (
                ("files", "tree", self.tree_dir),
                ("sink", "sink", self.sink_rep_dir),
            ):
                for rel, (size, sha) in man.get(section, {}).items():
                    # a manifested file missing from the replica (e.g.
                    # quarantined by anti-entropy fsck) must NOT enter
                    # the skip cache — the next pass re-seeds it
                    if not os.path.exists(os.path.join(base, rel)):
                        continue
                    self._entries[(key, rel)] = {
                        "size": int(size), "sha256": sha, "stamp": None,
                    }
        bar = last_barrier(self.standby_root, self.tenant)
        self._rows_through = int(bar["rows_through"]) if bar else 0
        self._rows_exact = bool(bar.get("rows_exact", True)) if bar else True
        self._rows_anchor_batch = int(bar["batch_id"]) if bar else -1
        self._last_barrier = bar
        self._last_barrier_wall = float(bar["wall"]) if bar else 0.0

    # -- the commit listener ----------------------------------------------

    def on_commit(self, batch_id: int, intent: Dict[str, Any],
                  n_rows: int = 0) -> bool:
        """Record one durable engine commit; ship + seal a barrier
        every ``barrier_every`` commits.  Returns True when a barrier
        sealed.  Never raises: a replication failure is counted,
        journaled, and retried at the next commit."""
        with self._lock:
            self._pending.append(
                (int(batch_id), int(intent.get("end", 0)), int(n_rows))
            )
            self._set_lag_gauges()
            if len(self._pending) < self.barrier_every:
                return False
            try:
                self.sync()
                return self._seal_barrier()
            except Exception as e:
                self.ship_errors += 1
                inc("sntc_repl_ships_total", 1, outcome="error",
                    **self._labels)
                emit_event(
                    event="replication_error", tenant=self.tenant,
                    batch_id=int(batch_id), error=repr(e),
                )
                set_gauge("sntc_repl_lag_bytes",
                          self._lag_bytes_estimate(), **self._labels)
                return False

    def _set_lag_gauges(self) -> None:
        set_gauge("sntc_repl_lag_batches", len(self._pending),
                  **self._labels)
        lag_s = (
            max(0.0, time.time() - self._last_barrier_wall)
            if self._last_barrier_wall else 0.0
        )
        set_gauge("sntc_repl_lag_seconds", lag_s, **self._labels)

    def _lag_bytes_estimate(self) -> int:
        """Stat-only estimate of un-replicated primary bytes (what a
        primary loss right now would cost)."""
        total = 0
        for rel in artifact_files(self.primary_root):
            try:
                size = os.path.getsize(
                    os.path.join(self.primary_root, rel)
                )
            except OSError:
                continue
            prev = self._entries.get(("tree", rel))
            total += size if prev is None else max(0, size - prev["size"])
        return total

    # -- shipping ----------------------------------------------------------

    def _discover(self) -> List[Tuple[str, str, str]]:
        """[(section, rel, abspath)] for everything that replicates."""
        out = [
            ("tree", rel, os.path.join(self.primary_root, rel))
            for rel in sorted(artifact_files(self.primary_root))
        ]
        if self.sink_dir:
            for pat in self.sink_patterns:
                for p in sorted(glob.glob(os.path.join(self.sink_dir, pat))):
                    if os.path.isfile(p):
                        out.append(
                            ("sink", os.path.relpath(p, self.sink_dir), p)
                        )
        return out

    def _ship_one(self, section: str, rel: str, src: str) -> Optional[
            Tuple[Dict[str, Any], int]]:
        """Ship one file if its content changed; returns (entry,
        shipped_bytes) or None when the file vanished mid-walk (racing
        retention — the next manifest simply drops it)."""
        try:
            st = os.stat(src)
        except OSError:
            return None
        stamp = f"{st.st_size}:{st.st_mtime_ns}"
        prev = self._entries.get((section, rel))
        if prev is not None and prev["stamp"] == stamp:
            return prev, 0
        try:
            with open(src, "rb") as f:
                data = f.read()
        except OSError:
            return None
        sha = _sha256(data)
        if prev is not None and prev["sha256"] == sha:
            return dict(prev, stamp=stamp), 0
        dest_base = self.tree_dir if section == "tree" else self.sink_rep_dir
        fault_point("repl.ship", tenant=self.tenant)
        atomic_write_bytes(
            os.path.join(dest_base, rel), data,
            site="repl.ship", tenant=self.tenant,
        )
        return (
            {"size": len(data), "sha256": sha, "stamp": stamp},
            len(data),
        )

    def sync(self) -> Dict[str, int]:
        """One ship pass: copy every new/changed artifact file to the
        replica tree, mirror retention deletions, then atomically
        publish the sealed manifest (``repl.apply``).  Raises on
        failure — the caller owns the degrade policy."""
        with self._lock:
            shipped_files = shipped_bytes = 0
            new_entries: Dict[Tuple[str, str], Dict[str, Any]] = {}
            for section, rel, src in self._discover():
                res = self._ship_one(section, rel, src)
                if res is None:
                    continue
                entry, nbytes = res
                new_entries[(section, rel)] = entry
                if nbytes:
                    shipped_files += 1
                    shipped_bytes += nbytes
            # mirror primary retention: a file the primary pruned or
            # compacted away leaves the replica too (the manifest is
            # the single source of replica truth)
            for section, rel in set(self._entries) - set(new_entries):
                base = (
                    self.tree_dir if section == "tree"
                    else self.sink_rep_dir
                )
                try:
                    os.unlink(os.path.join(base, rel))
                except OSError:
                    pass
            fault_point("repl.apply", tenant=self.tenant)
            core = {
                "tenant": self.tenant,
                "seq": self._seq,
                "wall": time.time(),
                "primary_root": os.path.abspath(self.primary_root),
                "files": {
                    rel: [e["size"], e["sha256"]]
                    for (sec, rel), e in sorted(new_entries.items())
                    if sec == "tree"
                },
                "sink": {
                    rel: [e["size"], e["sha256"]]
                    for (sec, rel), e in sorted(new_entries.items())
                    if sec == "sink"
                },
            }
            atomic_write_json(
                self.manifest_path, seal_record(core),
                site="repl.apply", tenant=self.tenant,
            )
            self._entries = new_entries
            self._seq += 1
            self.ships += 1
            inc("sntc_repl_ships_total", 1, outcome="completed",
                **self._labels)
            if shipped_files:
                inc("sntc_repl_ship_files_total", shipped_files,
                    **self._labels)
                inc("sntc_repl_ship_bytes_total", shipped_bytes,
                    **self._labels)
            return {"files": shipped_files, "bytes": shipped_bytes}

    # -- barriers ----------------------------------------------------------

    def _sink_rows(self, ids: List[int]) -> Optional[int]:
        """Data rows of the given sink batch files (for reconciling a
        barrier gap after a crash-between-commit-and-barrier); None
        when any file is unreadable (rows go inexact, never wrong)."""
        if not self.sink_dir:
            return None
        total = 0
        for bid in ids:
            path = os.path.join(self.sink_dir, f"batch_{bid:06d}.csv")
            try:
                with open(path) as f:
                    total += max(0, sum(1 for _ in f) - 1)
            except OSError:
                return None
        return total

    def _seal_barrier(self) -> bool:
        bid, end, _ = self._pending[-1]
        rows = sum(r for _b, _e, r in self._pending)
        seen = {b for b, _e, _r in self._pending}
        missing = [
            i for i in range(self._rows_anchor_batch + 1, bid + 1)
            if i not in seen
        ]
        rows_exact = self._rows_exact
        if missing:
            # commits landed while the plane was down (a crash between
            # commit and barrier): batches stay exact by sequential id;
            # rows reconcile from the replicated sink when possible
            got = self._sink_rows(missing)
            if got is None:
                rows_exact = False
            else:
                rows += got
        core = {
            "tenant": self.tenant,
            "seq": self._seq,
            "batch_id": bid,
            "end": end,
            "batches_through": bid + 1,
            "rows_through": self._rows_through + rows,
            "rows_exact": rows_exact,
            "wall": time.time(),
        }
        fault_point("repl.barrier", tenant=self.tenant)
        if not self._barriers.write(seal_record(core)):
            return False
        self._rows_through = core["rows_through"]
        self._rows_exact = rows_exact
        self._rows_anchor_batch = bid
        self._last_barrier = core
        self._last_barrier_wall = core["wall"]
        self._pending = []
        self.barriers_sealed += 1
        inc("sntc_repl_barriers_sealed_total", 1, **self._labels)
        set_gauge("sntc_repl_lag_batches", 0, **self._labels)
        set_gauge("sntc_repl_lag_seconds", 0.0, **self._labels)
        set_gauge("sntc_repl_lag_bytes", 0, **self._labels)
        return True

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Final ship + barrier for any pending commits (a drain with
        ``barrier_every > 1`` must not strand a replicated-but-unacked
        tail).  Degrades on failure like any other pass."""
        with self._lock:
            if not self._pending:
                return
            try:
                self.sync()
                self._seal_barrier()
            except Exception as e:
                self.ship_errors += 1
                emit_event(
                    event="replication_error", tenant=self.tenant,
                    error=repr(e), phase="close",
                )

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "tenant": self.tenant,
                "ships": self.ships,
                "ship_errors": self.ship_errors,
                "barriers_sealed": self.barriers_sealed,
                "pending_batches": len(self._pending),
                "last_barrier": self._last_barrier,
            }


# ---------------------------------------------------------------------------
# promotion
# ---------------------------------------------------------------------------


def _truncate_wal_to_barrier(ckpt: str, bid: int, end: int) -> int:
    """Drop every WAL record beyond barrier batch ``bid`` from a
    PROMOTED checkpoint root (both modes).  Post-barrier intents are
    dropped too — the crash-before-intent shape; the promoted engine
    replans them deterministically from the barrier offset."""
    dropped = 0
    cdir = os.path.join(ckpt, "commits")
    odir = os.path.join(ckpt, "offsets")
    if os.path.isdir(cdir) or os.path.isdir(odir):
        for d in (cdir, odir):
            for p in glob.glob(os.path.join(d, "*.json")):
                try:
                    rec_id = int(os.path.splitext(os.path.basename(p))[0])
                except ValueError:
                    continue
                if rec_id > bid:
                    try:
                        os.unlink(p)
                        dropped += 1
                    except OSError:
                        pass
        return dropped
    ck = os.path.join(ckpt, "wal_checkpoint.json")
    if os.path.exists(ck):
        try:
            core = load_sealed_json(ck)
        except (OSError, StorageCorruptError):
            core = None
        if core and int(core["last_committed"]) > bid:
            atomic_write_json(
                ck,
                seal_record({
                    "version": core.get("version", 1),
                    "last_committed": bid,
                    "end": end,
                    "pending": {},
                }),
                site="repl.apply",
            )
            dropped += 1
    for name in ("commits.log", "offsets.log"):
        path = os.path.join(ckpt, name)
        if not os.path.exists(path):
            continue
        try:
            recs, _ = read_jsonl_tolerant(path, repair=False)
        except _storage.JsonlCorruptError:
            recs = []
        keep = [r for r in recs if int(r.get("batch_id", -1)) <= bid]
        if len(keep) != len(recs):
            dropped += len(recs) - len(keep)
            atomic_write_bytes(
                path,
                "".join(json.dumps(r) + "\n" for r in keep).encode(),
                site="repl.apply",
            )
    return dropped


def _sink_idx(rel: str) -> Optional[int]:
    m = _SINK_IDX_RE.search(os.path.basename(rel))
    return int(m.group(1)) if m else None


def promote_standby(
    standby_root: str,
    tenant: str,
    dest_root: str,
    *,
    dest_sink: Optional[str] = None,
    primary_root: Optional[str] = None,
    primary_sink: Optional[str] = None,
    repair: bool = True,
) -> Dict[str, Any]:
    """Promote ``<standby_root>/<tenant>`` into ``dest_root``: fsck
    the replica, verify every manifest entry, quarantine torn-ship
    strays, copy the verified tree, truncate to the last sealed
    barrier, and measure RPO/RTO + the loss-accounting law (exact when
    the dead primary's tree is still readable).  ``ok=False`` NEVER
    leaves a promoted tree behind."""
    t0 = time.monotonic()
    rep = replica_dir(standby_root, tenant)
    tree = os.path.join(rep, TREE_DIR)
    labels = _labels(tenant)
    report: Dict[str, Any] = {
        "ok": False, "tenant": tenant, "dest_root": dest_root,
        "divergences": [], "quarantined": [], "reason": None,
    }

    def _fail(reason: str) -> Dict[str, Any]:
        report["reason"] = reason
        report["rto_seconds"] = time.monotonic() - t0
        inc("sntc_repl_promotions_total", 1, outcome="failed")
        emit_event(
            event="replica_diverged", tenant=tenant, reason=reason,
            divergences=report["divergences"][:8],
        )
        if report["divergences"]:
            inc("sntc_repl_divergence_total",
                len(report["divergences"]), **labels)
        return report

    try:
        man = load_sealed_json(os.path.join(rep, MANIFEST_NAME))
    except (OSError, StorageCorruptError) as e:
        report["divergences"].append(
            {"kind": "manifest", "detail": repr(e)}
        )
        return _fail("replica manifest missing or seal broken")
    bar = last_barrier(standby_root, tenant)
    report["barrier"] = bar
    if bar is None:
        return _fail("no sealed barrier — nothing provably consistent")

    # doctor the replica (torn journal tails etc.) before verifying
    for root in {tree, _ckpt_root(tree)}:
        if os.path.isdir(root):
            fs = _storage.fsck_root(root, repair=repair, tenant=tenant)
            if not fs["ok"]:
                report["divergences"].extend(
                    {"kind": "fsck", "detail": err}
                    for err in fs["errors"][:8]
                )
    if any(d["kind"] == "fsck" for d in report["divergences"]):
        return _fail("replica tree fails fsck")

    # verify the manifest: immutable artifacts re-hash to their sealed
    # sha256; a mismatch or a missing file is a torn/diverged replica
    # and the promotion refuses
    for section, base in (("files", tree), ("sink", os.path.join(rep, SINK_DIR))):
        for rel, (size, sha) in man.get(section, {}).items():
            p = os.path.join(base, rel)
            if not os.path.exists(p):
                report["divergences"].append(
                    {"kind": "missing", "rel": rel}
                )
                continue
            if _is_mutable(rel) and section == "files":
                continue
            try:
                with open(p, "rb") as f:
                    got = _sha256(f.read())
            except OSError as e:
                report["divergences"].append(
                    {"kind": "unreadable", "rel": rel, "detail": repr(e)}
                )
                continue
            if got != sha:
                dest_q = _storage.quarantine_blob(
                    p, artifact="repl_manifest",
                    detail="replica sha256 diverges from sealed manifest",
                    root=rep, tenant=tenant,
                )
                report["quarantined"].append(
                    {"rel": rel, "to": dest_q}
                )
                report["divergences"].append(
                    {"kind": "hash", "rel": rel}
                )
    if report["divergences"]:
        return _fail("replica diverges from its sealed manifest")

    # sweep torn-ship strays: an immutable file present in the tree
    # but absent from the sealed manifest was mid-ship when the
    # primary (or the plane) died — quarantine it, never promote it
    manifested = set(man.get("files", {}))
    for rel in sorted(artifact_files(tree)):
        if rel in manifested or _is_mutable(rel):
            continue
        dest_q = _storage.quarantine_blob(
            os.path.join(tree, rel), artifact="repl_manifest",
            detail="un-manifested replica file (torn ship)",
            root=rep, tenant=tenant,
        )
        report["quarantined"].append({"rel": rel, "to": dest_q})

    # copy the verified tree, then truncate to the barrier
    bid, end = int(bar["batch_id"]), int(bar["end"])
    promoted_files = promoted_bytes = 0
    for rel in sorted(man.get("files", {})):
        src = os.path.join(tree, rel)
        try:
            with open(src, "rb") as f:
                data = f.read()
        except OSError:
            continue  # quarantined above
        atomic_write_bytes(
            os.path.join(dest_root, rel), data,
            site="repl.apply", tenant=tenant,
        )
        promoted_files += 1
        promoted_bytes += len(data)
    truncated_sink = 0
    if dest_sink is not None:
        for rel in sorted(man.get("sink", {})):
            idx = _sink_idx(rel)
            if idx is not None and idx > bid:
                truncated_sink += 1
                continue
            try:
                with open(os.path.join(rep, SINK_DIR, rel), "rb") as f:
                    data = f.read()
            except OSError:
                continue
            atomic_write_bytes(
                os.path.join(dest_sink, rel), data,
                site="repl.apply", tenant=tenant,
            )
            promoted_files += 1
            promoted_bytes += len(data)
    truncated_wal = _truncate_wal_to_barrier(
        _ckpt_root(dest_root), bid, end
    )
    report.update(
        promoted_files=promoted_files,
        promoted_bytes=promoted_bytes,
        truncated={"wal_records": truncated_wal,
                   "sink_files": truncated_sink},
        batches_through=int(bar["batches_through"]),
        rows_through=int(bar["rows_through"]),
        rows_exact=bool(bar.get("rows_exact", True)),
    )

    # the loss-accounting law + RPO, exact when the primary's corpse
    # is still readable (committed == through_barrier + tail_loss)
    report["rpo_seconds"] = max(0.0, time.time() - float(bar["wall"]))
    if primary_root is not None and os.path.isdir(primary_root):
        census = committed_batches(_ckpt_root(primary_root))
        tail = census["count"] - int(bar["batches_through"])
        report["committed_primary"] = census["count"]
        report["tail_loss_batches"] = tail
        report["law_exact"] = (
            tail >= 0
            and census["count"]
            == int(bar["batches_through"]) + tail
        )
        rpo_bytes = 0
        for rel in artifact_files(primary_root):
            try:
                size = os.path.getsize(os.path.join(primary_root, rel))
            except OSError:
                continue
            prev = man.get("files", {}).get(rel)
            rpo_bytes += size if prev is None else max(0, size - prev[0])
        report["rpo_bytes"] = rpo_bytes
        if primary_sink is not None and tail > 0:
            tail_rows = 0
            for p in glob.glob(os.path.join(primary_sink, "batch_*.csv")):
                idx = _sink_idx(p)
                if idx is not None and idx > bid:
                    try:
                        with open(p) as f:
                            tail_rows += max(0, sum(1 for _ in f) - 1)
                    except OSError:
                        pass
            report["tail_loss_rows"] = tail_rows
            inc("sntc_repl_tail_loss_rows_total", tail_rows, **labels)
        if not report["law_exact"]:
            return _fail(
                "loss-accounting law violated: replica claims more "
                "than the primary ever committed"
            )
    report["ok"] = True
    report["rto_seconds"] = time.monotonic() - t0
    inc("sntc_repl_promotions_total", 1, outcome="completed")
    emit_event(
        event="standby_promoted", tenant=tenant, dest_root=dest_root,
        batches_through=report["batches_through"],
        rpo_seconds=report["rpo_seconds"],
        rto_seconds=report["rto_seconds"],
    )
    return report


# ---------------------------------------------------------------------------
# anti-entropy: sntc fsck --standby
# ---------------------------------------------------------------------------


def _resolve_primary(primary_root: str, tenant: str) -> Optional[str]:
    """Where tenant ``tenant``'s live tree sits under a primary root:
    a daemon root (``tenant/<tid>``), a fleet root
    (``worker/*/tenant/<tid>``), or the root itself (bare engine)."""
    cands = [os.path.join(primary_root, "tenant", tenant)]
    cands.extend(
        sorted(glob.glob(
            os.path.join(primary_root, "worker", "*", "tenant", tenant)
        ))
    )
    if tenant == DEFAULT_TENANT:
        cands.append(primary_root)
    for c in cands:
        if os.path.isdir(c):
            return c
    return None


def fsck_standby(
    standby_root: str,
    *,
    primary_root: Optional[str] = None,
    tenant: Optional[str] = None,
    repair: bool = False,
) -> Dict[str, Any]:
    """Cross-verify every tenant replica under ``standby_root``:
    manifest seal, replica content vs manifest (immutables re-hashed),
    barrier-log sanity, and — when the primary is reachable —
    primary-vs-replica content for files both sides hold.  Every
    mismatch is a journaled ``replica_diverged`` + counted
    ``sntc_repl_divergence_total``; ``repair=True`` quarantines the
    diverged replica copy so the next ship re-seeds it."""
    tenants = (
        [tenant] if tenant else sorted(
            os.path.basename(d) for d in glob.glob(
                os.path.join(standby_root, "*")
            )
            if os.path.isfile(os.path.join(d, MANIFEST_NAME))
        )
    )
    report: Dict[str, Any] = {
        "standby_root": standby_root, "ok": True, "tenants": {},
    }
    for tid in tenants:
        rep = replica_dir(standby_root, tid)
        tree = os.path.join(rep, TREE_DIR)
        tr: Dict[str, Any] = {
            "files": 0, "divergences": [], "barrier": None,
        }
        report["tenants"][tid] = tr
        try:
            man = load_sealed_json(os.path.join(rep, MANIFEST_NAME))
        except (OSError, StorageCorruptError) as e:
            tr["divergences"].append(
                {"kind": "manifest", "detail": repr(e)}
            )
            man = None
        bar = last_barrier(standby_root, tid)
        tr["barrier"] = (
            {"batch_id": bar["batch_id"], "end": bar["end"]}
            if bar else None
        )
        prim = (
            _resolve_primary(primary_root, tid)
            if primary_root else None
        )
        for rel, (size, sha) in (man or {}).get("files", {}).items():
            tr["files"] += 1
            p = os.path.join(tree, rel)
            mutable = _is_mutable(rel)
            try:
                with open(p, "rb") as f:
                    rep_sha = _sha256(f.read())
            except OSError:
                tr["divergences"].append({"kind": "missing", "rel": rel})
                continue
            if not mutable and rep_sha != sha:
                tr["divergences"].append({"kind": "hash", "rel": rel})
                if repair:
                    _storage.quarantine_blob(
                        p, artifact="repl_manifest",
                        detail="anti-entropy: replica diverges from "
                        "sealed manifest", root=rep, tenant=tid,
                    )
                continue
            if prim is not None and not mutable:
                pp = os.path.join(prim, rel)
                if os.path.exists(pp):
                    try:
                        with open(pp, "rb") as f:
                            if _sha256(f.read()) != rep_sha:
                                tr["divergences"].append(
                                    {"kind": "primary_mismatch",
                                     "rel": rel}
                                )
                    except OSError:
                        pass
        if tr["divergences"]:
            report["ok"] = False
            inc("sntc_repl_divergence_total",
                len(tr["divergences"]), **_labels(tid))
            emit_event(
                event="replica_diverged", tenant=tid,
                standby_root=standby_root,
                divergences=tr["divergences"][:8],
            )
    return report
