"""Live-model lifecycle: incremental fit, drift detection, shadow
promotion, and crash-safe hot-swap on the serving stream (r11).

Four cooperating layers, all riding the contracts PRs 1-5 established:

* **Incremental fit** (:mod:`~sntc_tpu.lifecycle.incremental`) —
  ``partial_fit`` for LogisticRegression / NaiveBayes as device-side
  sufficient-statistic updates (the summarizer pass training already
  runs), accumulated across shards in a decayable host-f64 state;
* **Drift monitor** (:mod:`~sntc_tpu.lifecycle.drift`) — per-batch
  prediction-mix and score-histogram statistics ride the structured
  event stream as ``batch_scored`` events; a windowed
  Jensen-Shannon divergence against a frozen reference window emits
  ``drift_detected`` and flips the ``model`` component to DEGRADED in
  :class:`~sntc_tpu.resilience.health.HealthMonitor`;
* **Shadow promotion** (:mod:`~sntc_tpu.lifecycle.promote`) — a
  :class:`ModelPromoter` shadow-scores a candidate on live batches
  through the same bucketed/fused predict path (zero new
  feature-prefix compile signatures), gates promotion on macro-F1
  beating the incumbent over a window, and journals every verdict;
* **Crash-safe hot-swap** — promotion publishes the candidate through
  the PR-1 atomic-checkpoint machinery (``save_model`` retains
  ``<path>.prev``), swaps the engine predictor only BETWEEN
  micro-batches (never mid-delivery in ``overlap_sink`` mode), and
  rolls back to ``.prev`` on a post-swap failure-rate breach via the
  PR-2 ``predict.dispatch`` circuit breaker.  The WAL/replay contract
  holds across a swap — proven by the kill-mid-promotion scenarios in
  ``scripts/chaos_crash_matrix.py``.

:class:`~sntc_tpu.lifecycle.manager.LifecycleManager` composes the
layers behind the ``StreamingQuery(lifecycle=...)`` hook.  See
``docs/RESILIENCE.md`` "Model lifecycle".
"""

from sntc_tpu.lifecycle.drift import (
    DriftMonitor,
    batch_score_stats,
    js_divergence,
)
from sntc_tpu.lifecycle.incremental import (
    LRPartialFitState,
    NBPartialFitState,
    incremental_estimator_for,
)
from sntc_tpu.lifecycle.manager import LifecycleManager
from sntc_tpu.lifecycle.promote import (
    MODEL_MARKER,
    ModelPromoter,
    graft_head,
    macro_f1,
    read_model_marker,
    terminal_head,
)

__all__ = [
    "DriftMonitor",
    "batch_score_stats",
    "js_divergence",
    "LRPartialFitState",
    "NBPartialFitState",
    "incremental_estimator_for",
    "LifecycleManager",
    "ModelPromoter",
    "MODEL_MARKER",
    "graft_head",
    "macro_f1",
    "read_model_marker",
    "terminal_head",
]
