"""Drift detection from the live serving stream.

Every clean committed batch produces a ``batch_scored`` event on the
structured stream (``LifecycleManager.on_batch`` computes it from the
predictor's output frame): the prediction-mix histogram (rows per
predicted class) and a fixed-bin confidence histogram (max predicted
probability per row).  :class:`DriftMonitor` folds those events — it
can be attached to the process event stream exactly like
:class:`~sntc_tpu.resilience.health.HealthMonitor`, or fed directly —
into two windows:

* **reference** — the first ``window`` batches observed (or an
  explicitly supplied distribution pair), frozen as the incumbent's
  healthy baseline;
* **current** — a sliding window of the last ``window`` batches.

Divergence = max of the Jensen-Shannon divergences between the
reference and current prediction-mix / score-histogram distributions
(JS is symmetric and bounded in [0, ln 2], so one threshold works for
both).  A breach emits ``drift_detected`` (component ``model``, which
:class:`HealthMonitor` maps to DEGRADED) exactly once per episode; a
model swap resets the monitor so the promoted model gets a fresh
baseline.  Everything is deterministic — detection latency on a fixed
stream is a constant the tests pin.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from sntc_tpu.resilience.policy import (
    add_event_observer,
    emit_event,
    remove_event_observer,
)

SCORE_BINS = 10  # fixed confidence-histogram bins over [0, 1]


def js_divergence(p, q, eps: float = 1e-12) -> float:
    """Jensen-Shannon divergence (natural log; bounded by ln 2) between
    two count/probability vectors — 0/0-safe, normalizes internally."""
    p = np.asarray(p, np.float64)
    q = np.asarray(q, np.float64)
    p = p / max(p.sum(), eps)
    q = q / max(q.sum(), eps)
    m = 0.5 * (p + q)

    def kl(a, b):
        mask = a > 0
        return float(np.sum(a[mask] * np.log(a[mask] / b[mask])))

    return 0.5 * kl(p, m) + 0.5 * kl(q, m)


def batch_score_stats(
    out_frame,
    n_classes: int,
    prediction_col: str = "prediction",
    probability_col: str = "probability",
    bins: int = SCORE_BINS,
) -> Dict[str, Any]:
    """Per-batch scoring statistics from a predictor OUTPUT frame: the
    prediction-mix histogram [n_classes] and the max-probability
    confidence histogram [bins] (all-zero when the model exposes no
    probability column)."""
    pred = np.asarray(out_frame[prediction_col]).astype(np.int64)
    mix = np.bincount(
        np.clip(pred, 0, n_classes - 1), minlength=n_classes
    )
    hist = np.zeros(bins, np.int64)
    if probability_col and probability_col in out_frame:
        prob = np.asarray(out_frame[probability_col])
        if prob.ndim == 2 and prob.shape[0]:
            conf = prob.max(axis=1)
            hist, _ = np.histogram(conf, bins=bins, range=(0.0, 1.0))
    return {
        "n_rows": int(pred.shape[0]),
        "prediction_mix": mix.tolist(),
        "score_hist": hist.tolist(),
    }


class DriftMonitor:
    """Windowed divergence test over per-batch scoring statistics.

    ``window`` batches freeze the reference, then every observed batch
    slides the current window; once it is full, divergence >
    ``threshold`` flips :attr:`detected` and emits ``drift_detected``
    (once per episode).  ``health`` (optional) is reported directly;
    an ATTACHED HealthMonitor also picks the event up from the stream.
    """

    def __init__(
        self,
        window: int = 8,
        threshold: float = 0.25,
        health=None,
        component: str = "model",
    ):
        if window < 1:
            raise ValueError("window must be >= 1")
        if threshold <= 0:
            raise ValueError("threshold must be > 0")
        self.window = int(window)
        self.threshold = float(threshold)
        self.health = health
        self.component = component
        self._reference: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._ref_acc: List[Tuple[np.ndarray, np.ndarray]] = []
        self._current: deque = deque(maxlen=self.window)
        self.batches_seen = 0
        self.detected = False
        self.detected_batch: Optional[int] = None
        self.last_divergence = 0.0
        self._observer = None

    # -- event-stream feed --------------------------------------------------

    def observe_event(self, record: Dict[str, Any]) -> None:
        if record.get("event") != "batch_scored":
            return
        self.observe(record)

    def attach(self) -> "DriftMonitor":
        """Subscribe to the process event stream (idempotent)."""
        if self._observer is None:
            self._observer = self.observe_event
            add_event_observer(self._observer)
        return self

    def detach(self) -> None:
        if self._observer is not None:
            remove_event_observer(self._observer)
            self._observer = None

    # -- the divergence test ------------------------------------------------

    def _window_dists(self, acc) -> Tuple[np.ndarray, np.ndarray]:
        mix = np.sum([m for m, _ in acc], axis=0).astype(np.float64)
        hist = np.sum([h for _, h in acc], axis=0).astype(np.float64)
        return mix, hist

    def observe(self, stats: Dict[str, Any]) -> Optional[float]:
        """Fold one batch's statistics; returns the divergence once the
        current window is full (None while warming up / building the
        reference)."""
        self.batches_seen += 1
        pair = (
            np.asarray(stats["prediction_mix"], np.float64),
            np.asarray(stats["score_hist"], np.float64),
        )
        if self._reference is None:
            self._ref_acc.append(pair)
            if len(self._ref_acc) >= self.window:
                self._reference = self._window_dists(self._ref_acc)
                self._ref_acc = []
            return None
        self._current.append(pair)
        if len(self._current) < self.window:
            return None
        cur_mix, cur_hist = self._window_dists(self._current)
        ref_mix, ref_hist = self._reference
        div = max(
            js_divergence(ref_mix, cur_mix),
            js_divergence(ref_hist, cur_hist),
        )
        self.last_divergence = div
        try:  # live divergence on the metrics plane (obs)
            from sntc_tpu.obs.metrics import set_gauge

            set_gauge(
                "sntc_drift_divergence", div, component=self.component
            )
        except Exception:
            pass
        if div > self.threshold and not self.detected:
            self.detected = True
            self.detected_batch = stats.get("batch_id")
            emit_event(
                event="drift_detected", component=self.component,
                batch_id=self.detected_batch,
                divergence=round(div, 6), threshold=self.threshold,
                window=self.window,
            )
            if self.health is not None:
                from sntc_tpu.resilience.health import HealthState

                self.health.report(
                    self.component, HealthState.DEGRADED,
                    reason=f"drift divergence {div:.4f} > "
                    f"{self.threshold}",
                )
        return div

    def reset(self) -> None:
        """Forget reference + episode state (called after a model swap:
        the promoted model earns a fresh baseline)."""
        self._reference = None
        self._ref_acc = []
        self._current.clear()
        self.detected = False
        self.detected_batch = None
        self.last_divergence = 0.0

    def stats(self) -> Dict[str, Any]:
        return {
            "window": self.window,
            "threshold": self.threshold,
            "batches_seen": self.batches_seen,
            "reference_frozen": self._reference is not None,
            "detected": self.detected,
            "detected_batch": self.detected_batch,
            "last_divergence": round(self.last_divergence, 6),
        }
