"""LifecycleManager — the one object the engine talks to.

``StreamingQuery(lifecycle=LifecycleManager(...))`` wires the whole
model-lifecycle subsystem into the serving loop through three
duck-typed hooks:

* ``on_batch(batch_id, frame, finalize)`` — after every CLEAN commit
  (engine thread): emit the ``batch_scored`` event (feeding any
  attached :class:`DriftMonitor`), optionally ``partial_fit`` the
  candidate head from the batch's labels, and shadow-score /
  gate-check via the :class:`ModelPromoter`;
* ``on_tick(query)`` — once per engine round: probation breach check
  (rollback on an open ``predict.dispatch`` breaker);
* ``take_pending_swap()`` / ``on_swap_applied(old)`` — the deferred
  hot-swap handshake: the engine applies a pending swap only BETWEEN
  micro-batches (settling any in-air delivery first) and reports back
  so the promoter advances its state machine and the drift monitor
  resets its baseline for the new model.

A lifecycle hook failure must degrade, never kill, the serving loop:
the engine wraps ``on_batch`` and emits ``lifecycle_error`` on an
exception.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from sntc_tpu.lifecycle.drift import DriftMonitor, batch_score_stats
from sntc_tpu.lifecycle.promote import ModelPromoter
from sntc_tpu.resilience import emit_event


class LifecycleManager:
    """Compose drift monitoring, incremental refit, and promotion.

    ``drift`` and ``promoter`` are each optional — a manager with only
    a DriftMonitor just scores batches; one with only a ModelPromoter
    just shadows/promotes.  ``partial_fit=True`` arms the online-
    learning loop: every labeled batch incrementally refits a candidate
    head cloned from the incumbent (via
    :func:`~sntc_tpu.lifecycle.incremental.incremental_estimator_for`)
    and keeps it shadowed for the promotion gate.
    """

    def __init__(
        self,
        *,
        drift: Optional[DriftMonitor] = None,
        promoter: Optional[ModelPromoter] = None,
        partial_fit: bool = False,
        n_classes: Optional[int] = None,
        prediction_col: str = "prediction",
        probability_col: str = "probability",
        mesh=None,
    ):
        self.drift = drift
        self.promoter = promoter
        self.partial_fit = bool(partial_fit)
        if self.partial_fit and promoter is None:
            raise ValueError(
                "partial_fit=True needs a ModelPromoter to shadow the "
                "refit candidate"
            )
        self.prediction_col = prediction_col
        self.probability_col = probability_col
        self._mesh = mesh
        self._n_classes = n_classes
        self._pf_estimator = None
        self._pf_state = None
        self.batches_scored = 0
        self.partial_fit_batches = 0

    # -- engine hooks --------------------------------------------------------

    def _resolve_classes(self, out_frame) -> int:
        if self._n_classes is None:
            if self.promoter is not None:
                try:
                    from sntc_tpu.lifecycle.promote import terminal_head

                    self._n_classes = terminal_head(
                        self.promoter.incumbent
                    ).num_classes
                except (ValueError, NotImplementedError):
                    pass
            if self._n_classes is None:
                prob = out_frame.column(self.probability_col) if (
                    self.probability_col in out_frame
                ) else None
                self._n_classes = (
                    int(prob.shape[1]) if prob is not None and
                    prob.ndim == 2
                    else int(
                        np.asarray(
                            out_frame[self.prediction_col]
                        ).max(initial=0)
                    ) + 1
                )
        return self._n_classes

    def on_batch(self, batch_id: int, frame, finalize) -> None:
        out = finalize()  # memoized by the predictor: a cached read
        k = self._resolve_classes(out)
        stats = batch_score_stats(
            out, k,
            prediction_col=self.prediction_col,
            probability_col=self.probability_col,
        )
        self.batches_scored += 1
        # the drift monitor (and anything else listening) reads this
        # off the structured stream — scoring statistics are events,
        # not private state
        emit_event(
            event="batch_scored", site="model.score",
            batch_id=batch_id, **stats,
        )
        if self.promoter is None:
            return
        # test-then-train: the gate scores the candidate BEFORE it sees
        # this batch's labels, so incumbent and candidate are judged on
        # the same unseen data (a candidate scored on its own training
        # batch would beat the incumbent spuriously on noisy
        # micro-batches)
        self.promoter.on_batch(batch_id, frame, out)
        if self.partial_fit:
            self._partial_fit_candidate(frame, out)

    def _partial_fit_candidate(self, frame, out_frame) -> None:
        """Fold one labeled batch into the incremental candidate head.
        Features come from the OUTPUT frame (the fused prefix keeps
        the head's input column alive because the head is a later
        reader), labels from the promoter's label mapping."""
        from sntc_tpu.lifecycle.incremental import (
            incremental_estimator_for,
        )
        from sntc_tpu.lifecycle.promote import terminal_head

        y = self.promoter._labels_from(frame)
        if y is None:
            return
        known = y >= 0
        if not known.any():
            return
        if self._pf_estimator is None:
            self._pf_estimator = incremental_estimator_for(
                terminal_head(self.promoter.incumbent), mesh=self._mesh
            )
        head = terminal_head(self.promoter.incumbent)
        feats_col = head.getFeaturesCol()
        if feats_col not in out_frame:
            return
        from sntc_tpu.core.frame import Frame

        X_all = np.asarray(out_frame[feats_col])
        if X_all.shape[0] != y.shape[0]:
            # a row-dropping stage broke input/output row alignment
            # (same skip rule as the promoter's shadow scoring)
            return
        X = X_all[known]
        batch = Frame({
            self._pf_estimator.getFeaturesCol(): X,
            self._pf_estimator.getLabelCol(): y[known].astype(
                np.float64
            ),
        })
        # the incumbent's label universe fixes the state's class count:
        # the first live mini-batch rarely carries every class, and a
        # state frozen at a partial class set would reject later shards
        try:
            k = int(head.num_classes)
        except (NotImplementedError, TypeError):
            k = self._n_classes
        model, self._pf_state = self._pf_estimator.partial_fit(
            batch, self._pf_state, n_classes=k
        )
        self.partial_fit_batches += 1
        self.promoter.update_candidate(model)

    def on_tick(self, query=None) -> None:
        if self.promoter is not None:
            self.promoter.on_tick(query)

    def take_pending_swap(self):
        if self.promoter is None:
            return None
        return self.promoter.take_pending_swap()

    def rearm_pending_swap(self, model) -> None:
        if self.promoter is not None:
            self.promoter.rearm_pending_swap(model)

    def on_swap_applied(self, old_model) -> None:
        if self.promoter is not None:
            self.promoter.on_swap_applied(old_model)
        if self.drift is not None:
            # the promoted (or restored) model earns a fresh baseline —
            # its healthy prediction mix IS expected to differ
            self.drift.reset()

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "batches_scored": self.batches_scored,
            "partial_fit": self.partial_fit,
            "partial_fit_batches": self.partial_fit_batches,
        }
        if self.drift is not None:
            out["drift"] = self.drift.stats()
        if self.promoter is not None:
            out["promoter"] = self.promoter.stats()
        return out
