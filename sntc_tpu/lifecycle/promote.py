"""Shadow promotion and crash-safe hot-swap of the serving model.

:class:`ModelPromoter` owns the candidate's whole life:

1. **shadow** — the candidate head is grafted onto the INCUMBENT's
   feature prefix (:func:`graft_head` reuses the same fitted stage /
   ``FusedSegment`` instances, so shadow dispatches hit the prefix's
   already-compiled programs — zero new feature-prefix compile
   signatures) and scored on every live labeled batch through a
   :class:`~sntc_tpu.serve.transform.BatchPredictor` sharing the
   engine's bucket config (same padded shapes, same program cache);
2. **gate** — per-batch macro-F1 verdicts (incumbent vs candidate) are
   journaled to ``<checkpoint>/promotion.jsonl``; when the candidate's
   mean beats the incumbent's over a full ``window`` (+ ``margin``),
   promotion fires;
3. **publish** — the candidate is persisted OVER the serving model
   path via the PR-1 atomic checkpoint machinery (``save_model``
   stages, seals, renames; the incumbent is retained at
   ``<path>.prev``), then an atomic ``model_marker.json`` records the
   new generation.  Kill points: ``model.publish`` (pre-publish —
   nothing changed on disk), ``model.swap`` first call (post-publish /
   pre-swap — a restart loads and serves the candidate), ``model.swap``
   second call (post-swap);
4. **swap** — the in-engine swap is DEFERRED to the engine's next
   safe point (``StreamingQuery`` applies pending swaps only between
   micro-batches, never mid-delivery in ``overlap_sink`` mode);
5. **probation / rollback** — after the swap, ``probation_batches``
   clean commits must land while the ``predict.dispatch`` circuit
   breaker stays closed; a breach rolls back to the retained
   ``<path>.prev`` snapshot (in-memory, the exact incumbent object —
   predictions restore bitwise) and republishes the incumbent.

The promoter is engine-facing through the duck-typed hooks
``on_batch`` / ``on_tick`` / ``take_pending_swap`` (usually composed
by :class:`~sntc_tpu.lifecycle.manager.LifecycleManager`).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from sntc_tpu.core.base import PipelineModel
from sntc_tpu.core.frame import Frame
from sntc_tpu.models.base import ClassificationModel
from sntc_tpu.resilience import emit_event, fault_point
from sntc_tpu.serve.transform import BatchPredictor

MODEL_MARKER = "model_marker.json"
PROMOTION_JOURNAL = "promotion.jsonl"


def macro_f1(y_true, y_pred, n_classes: Optional[int] = None) -> float:
    """Unweighted mean of per-class F1 over every class seen in labels
    or predictions, 0/0 → 0 — the gating metric ([B:2]'s metric of
    record), as plain numpy so per-batch scoring costs no collective."""
    y = np.asarray(y_true, np.int64)
    p = np.asarray(y_pred, np.int64)
    if y.size == 0:
        return 0.0
    classes = np.union1d(np.unique(y), np.unique(p))
    if n_classes is not None:
        classes = classes[classes < n_classes]
    f1s: List[float] = []
    for c in classes:
        tp = float(np.sum((y == c) & (p == c)))
        fp = float(np.sum((y != c) & (p == c)))
        fn = float(np.sum((y == c) & (p != c)))
        prec = tp / (tp + fp) if tp + fp else 0.0
        rec = tp / (tp + fn) if tp + fn else 0.0
        f1s.append(
            2.0 * prec * rec / (prec + rec) if prec + rec else 0.0
        )
    return float(np.mean(f1s)) if f1s else 0.0


def _locate_head(stages: List) -> int:
    """Index of the terminal plain-stage ClassificationModel; raises
    when the head was fused INTO a segment (its weights are constants
    of the segment's program — swapping it would recompile the whole
    prefix; lifecycle serving compiles with ``fuse_heads=False``)."""
    from sntc_tpu.fuse import FusedSegment

    for i in range(len(stages) - 1, -1, -1):
        stage = stages[i]
        if isinstance(stage, ClassificationModel):
            return i
        if isinstance(stage, FusedSegment) and stage._head is not None:
            raise ValueError(
                "classifier head is fused into a FusedSegment; compile "
                "the serving pipeline with fuse_heads=False to make the "
                "head hot-swappable (the feature-prefix segments stay "
                "fused and their compiled programs are reused across "
                "swaps)"
            )
    raise ValueError("no ClassificationModel head found in pipeline")


def terminal_head(model) -> ClassificationModel:
    """The serving model's classifier head (the swap unit)."""
    if isinstance(model, ClassificationModel):
        return model
    if isinstance(model, PipelineModel):
        return model.getStages()[_locate_head(model.getStages())]
    raise ValueError(
        f"cannot locate a classifier head in {type(model).__name__}"
    )


def graft_head(serving, head: ClassificationModel):
    """A serving model with ``head`` in place of the terminal
    classifier, REUSING every other fitted stage object — compiled
    feature-prefix programs (``FusedSegment`` caches, module-level
    jitted serve programs) carry over, so a swap or shadow adds no
    feature-prefix compile signatures."""
    head = terminal_head(head)
    if isinstance(serving, ClassificationModel):
        return head
    if not isinstance(serving, PipelineModel):
        raise ValueError(
            f"cannot graft a head onto {type(serving).__name__}"
        )
    stages = list(serving.getStages())
    idx = _locate_head(stages)
    old = stages[idx]
    if head.getFeaturesCol() != old.getFeaturesCol():
        raise ValueError(
            f"candidate head reads {head.getFeaturesCol()!r} but the "
            f"incumbent prefix produces {old.getFeaturesCol()!r}"
        )
    stages[idx] = head
    return PipelineModel(stages=stages)


def read_model_marker(checkpoint_dir: str) -> Optional[Dict[str, Any]]:
    """The last published model-generation record, or None."""
    path = os.path.join(checkpoint_dir, MODEL_MARKER)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


class ModelPromoter:
    """Candidate lifecycle: shadow-score → gate → publish → swap →
    probation/rollback (see module docstring).

    ``incumbent`` is the live SERVING model (what the engine's
    predictor wraps); ``incumbent_raw`` the persistable form published
    to ``serving_path`` (the raw fitted pipeline — fused segments are
    a serving-time artifact and are never saved).  ``labels`` maps the
    stream's label strings to class indices (None = the label column
    already holds indices).  ``bucket_rows`` mirrors the engine
    predictor's shape buckets so shadow dispatches reuse its padded
    shapes.
    """

    def __init__(
        self,
        incumbent,
        *,
        incumbent_raw=None,
        serving_path: Optional[str] = None,
        checkpoint_dir: Optional[str] = None,
        window: int = 8,
        margin: float = 0.0,
        label_col: str = "label",
        labels: Optional[List[str]] = None,
        bucket_rows: int = 0,
        probation_batches: int = 8,
        breaker=None,
        health=None,
    ):
        if window < 1:
            raise ValueError("window must be >= 1")
        if (
            serving_path is not None
            and incumbent_raw is None
            and isinstance(incumbent, PipelineModel)
        ):
            # publishing without the raw form would save a bare
            # classifier head over a PIPELINE checkpoint — the live
            # process keeps serving, but a restart loads a model that
            # cannot transform raw flow columns.  Fail at construction,
            # not at the first promotion.
            raise ValueError(
                "ModelPromoter with a serving_path and a pipeline "
                "incumbent needs incumbent_raw (the persistable fitted "
                "pipeline) so promotions publish a restart-servable "
                "checkpoint"
            )
        self.incumbent = incumbent
        self.incumbent_raw = incumbent_raw
        self.serving_path = serving_path
        self.checkpoint_dir = checkpoint_dir
        self.window = int(window)
        self.margin = float(margin)
        self.label_col = label_col
        self.labels = list(labels) if labels is not None else None
        self._label_index = (
            {str(v): i for i, v in enumerate(self.labels)}
            if self.labels is not None
            else None
        )
        self.bucket_rows = int(bucket_rows)
        self.probation_batches = int(probation_batches)
        self.breaker = breaker
        self.health = health
        self.candidate = None  # serving form (grafted onto the prefix)
        self.candidate_head: Optional[ClassificationModel] = None
        self.candidate_source: Optional[str] = None
        self._journal_writer = None
        self._shadow: Optional[BatchPredictor] = None
        self._full_shadow: Optional[BatchPredictor] = None
        self._scores: deque = deque(maxlen=self.window)
        self._pending_swap = None
        self._swap_kind: Optional[str] = None
        # the retained previous generation for in-memory rollback: the
        # EXACT incumbent objects, so restored predictions are bitwise
        self._previous = None  # (serving, raw)
        marker = (
            read_model_marker(checkpoint_dir)
            if checkpoint_dir is not None
            else None
        )
        self.generation = int(marker["generation"]) if marker else 0
        self.state = "idle"
        self._probation_left = 0
        self.promotions = 0
        self.rollbacks = 0

    # -- candidate management ----------------------------------------------

    def _resolve_head(self, model) -> ClassificationModel:
        """The candidate's swap-unit head, normalized to the incumbent
        prefix's output column: when the serving pipeline was compiled
        with the scaler→head weight fold (the default serve path), the
        incumbent head reads the PRE-scaler column — applying the same
        fold to the candidate pipeline bakes ITS OWN scaler into its
        head so both heads read the same prefix output."""
        head = terminal_head(model)
        inc_col = terminal_head(self.incumbent).getFeaturesCol()
        if head.getFeaturesCol() == inc_col or not isinstance(
            model, PipelineModel
        ):
            return head
        from sntc_tpu.fuse.rules import fold_scalers

        folded = PipelineModel(
            stages=fold_scalers(list(model.getStages()))
        )
        folded_head = terminal_head(folded)
        if folded_head.getFeaturesCol() == inc_col:
            return folded_head
        return head  # graft_head names the mismatch

    def set_candidate(self, model, source: Optional[str] = None) -> None:
        """Arm shadow scoring for ``model`` (a bare classifier head or
        a pipeline whose terminal classifier is extracted, scaler-fold
        normalized to the incumbent prefix — see ``_resolve_head``)."""
        head = self._resolve_head(model)
        self.candidate_head = head
        self.candidate = graft_head(self.incumbent, head)
        # shadow the HEAD alone: scoring reads the incumbent's own
        # prefix output off the served frame, so shadowing re-runs
        # zero feature-prefix work (the full graft is only the swap
        # target, and the scoring fallback when the prefix output
        # column is not retained)
        self._shadow = BatchPredictor(head, bucket_rows=self.bucket_rows)
        self._full_shadow = None
        self._scores.clear()
        self.candidate_source = source
        self.state = "shadowing"

    def update_candidate(self, model) -> None:
        """Refresh the shadowed head in place (the ``--partial-fit``
        loop refits the candidate every labeled batch); scoring history
        is KEPT — the gate judges the candidate line, not one frozen
        snapshot."""
        if self.state in ("probation", "promoting"):
            # probation guards the JUST-promoted generation (and
            # "promoting" the one whose swap is still pending): arming
            # a fresh candidate here would flip the state machine back
            # to shadowing and silently disable the breach-rollback
            # check.  The refit loop keeps accumulating; the first
            # labeled batch after this resolves re-arms the shadow.
            return
        if self.state != "shadowing":
            self.set_candidate(model)
            return
        head = terminal_head(model)
        self.candidate_head = head
        self.candidate = graft_head(self.incumbent, head)
        self._shadow.swap_model(head)
        self._full_shadow = None

    def load_candidate(self, path: str) -> None:
        """Load a candidate checkpoint and arm shadow scoring."""
        from sntc_tpu.mlio import load_model

        self.set_candidate(load_model(path), source=path)

    # -- engine hooks --------------------------------------------------------

    def _labels_from(self, frame) -> Optional[np.ndarray]:
        if self.label_col not in frame:
            return None
        col = frame[self.label_col]
        if self._label_index is not None:
            return np.asarray(
                [self._label_index.get(str(v), -1) for v in col],
                np.int64,
            )
        try:
            return np.asarray(col).astype(np.int64)
        except (TypeError, ValueError):
            return None

    def on_batch(self, batch_id: int, frame, out_frame) -> None:
        """One clean committed batch: advance probation, shadow-score
        the candidate when one is armed and the batch carries labels."""
        if self.state == "probation":
            self._probation_left -= 1
            if self._probation_left <= 0:
                self.state = "idle"
                self._journal({
                    "action": "probation_passed",
                    "generation": self.generation,
                    "batch_id": batch_id,
                })
        if self.state != "shadowing" or self._shadow is None:
            return
        y = self._labels_from(frame)
        if y is None:
            return
        known = y >= 0
        if not known.any():
            return
        head = self.candidate_head
        pred_col = head.getPredictionCol()
        inc_pred = np.asarray(out_frame[pred_col])
        if inc_pred.shape[0] != y.shape[0]:
            # a row-dropping stage (handleInvalid=skip) excised rows
            # between the input labels and the served output — the
            # label mask no longer aligns row-for-row, so skip scoring
            # this batch rather than index with a misaligned mask
            return
        feats_col = head.getFeaturesCol()
        if feats_col in out_frame:
            # score on the incumbent's OWN prefix output: the head was
            # normalized to read exactly this column, so shadowing
            # costs one head dispatch and no prefix work
            cand_out = self._shadow.predict_frame(
                Frame({feats_col: out_frame[feats_col]})
            )
        else:
            if self._full_shadow is None:
                self._full_shadow = BatchPredictor(
                    self.candidate, bucket_rows=self.bucket_rows
                )
            cand_out = self._full_shadow.predict_frame(frame)
        f1_inc = macro_f1(y[known], inc_pred[known])
        f1_cand = macro_f1(
            y[known], np.asarray(cand_out[pred_col])[known]
        )
        self._scores.append((f1_inc, f1_cand))
        filled = len(self._scores) == self.window
        mean_inc = float(np.mean([a for a, _ in self._scores]))
        mean_cand = float(np.mean([b for _, b in self._scores]))
        decision = "hold"
        if filled and mean_cand > mean_inc + self.margin:
            decision = "promote"
        self._journal({
            "action": "shadow_score", "batch_id": batch_id,
            "f1_incumbent": round(f1_inc, 6),
            "f1_candidate": round(f1_cand, 6),
            "mean_incumbent": round(mean_inc, 6),
            "mean_candidate": round(mean_cand, 6),
            "window_filled": filled, "decision": decision,
        })
        if decision == "promote":
            self.promote()

    def on_tick(self, query=None) -> None:
        """Per-engine-round probation check: a ``predict.dispatch``
        breaker that OPENED after the swap is the failure-rate breach
        that triggers rollback (the batch itself is deferred by the
        breaker, so no ``on_batch`` would ever see it)."""
        if self.state != "probation":
            return
        br = self.breaker
        if br is None and query is not None:
            br = getattr(query, "breakers", {}).get("predict.dispatch")
        if br is not None and br.state == "open":
            self.rollback(
                "predict.dispatch breaker open during post-swap "
                "probation"
            )

    def take_pending_swap(self):
        swap, self._pending_swap = self._pending_swap, None
        return swap

    def rearm_pending_swap(self, model) -> None:
        """Put a taken-but-unapplied swap back (the engine's safe point
        failed before the predictor flip — e.g. the in-air delivery
        settle raised).  ``_swap_kind`` is untouched: only a landed
        swap (``on_swap_applied``) resolves it, so a re-armed rollback
        is still a rollback on the retry."""
        self._pending_swap = model

    def on_swap_applied(self, old_model) -> None:
        """Called by the engine (via the lifecycle manager) right after
        the in-engine predictor swap landed."""
        # kill point post-swap: the predictor already serves the new
        # model; a crash here must restart into the same model (second
        # call of the model.swap site — chaos arms after=1)
        fault_point("model.swap")
        if self._swap_kind is None:
            # a duplicate apply of an already-resolved swap (nothing is
            # armed): mutating the state machine here would clobber the
            # incumbent with a cleared candidate
            return
        if self._swap_kind == "rollback":
            emit_event(
                event="model_swapped", component="model",
                generation=self.generation, kind="rollback",
            )
            self.state = "rolled_back"
            self._swap_kind = None
            return
        emit_event(
            event="model_swapped", component="model",
            generation=self.generation, kind="promote",
        )
        self._previous = (self.incumbent, self.incumbent_raw)
        self.incumbent = self.candidate
        if self.candidate_head is not None and (
            self.incumbent_raw is not None
        ):
            # same form promote() published (folds the raw prefix when
            # the serving compile folded its scaler into the heads)
            self.incumbent_raw = self._publish_form()
        self.candidate = None
        self.candidate_head = None
        self._shadow = None
        self._full_shadow = None
        self._scores.clear()
        self._swap_kind = None
        self.state = "probation"
        self._probation_left = self.probation_batches

    # -- promote / rollback --------------------------------------------------

    def _write_marker(self, record: Dict[str, Any]) -> None:
        if self.checkpoint_dir is None:
            return
        from sntc_tpu.resilience.storage import write_marker

        # DEGRADE policy (r17): a marker that cannot write counts a
        # storage_degraded episode; the promotion itself already
        # published atomically and must not be failed retroactively
        write_marker(
            os.path.join(self.checkpoint_dir, MODEL_MARKER), record,
            indent=1,
        )

    def _journal(self, record: Dict[str, Any]) -> None:
        if self.checkpoint_dir is None:
            return
        record = dict(record, ts=time.time())
        if self._journal_writer is None:
            from sntc_tpu.resilience.storage import RotatingJsonlWriter

            self._journal_writer = RotatingJsonlWriter(
                os.path.join(self.checkpoint_dir, PROMOTION_JOURNAL),
                artifact="promotion_journal",
            )
        self._journal_writer.write(record)

    def _publish_form(self):
        """The restart-servable pipeline naming the candidate: the raw
        incumbent's stages with the candidate head grafted in.  When
        the serving compile folded a scaler into the heads — so the
        normalized candidate head reads the PRE-scaler column while the
        raw incumbent's head reads the scaler output — the raw prefix
        is folded the same way before grafting; the published
        checkpoint is then the fold-equivalent pipeline, servable on
        restart and reading exactly the columns the candidate head was
        trained on."""
        if not isinstance(self.incumbent_raw, PipelineModel):
            return self.candidate_head
        target = self.incumbent_raw
        if (
            terminal_head(target).getFeaturesCol()
            != self.candidate_head.getFeaturesCol()
        ):
            from sntc_tpu.fuse.rules import fold_scalers

            target = PipelineModel(
                stages=fold_scalers(list(target.getStages()))
            )
        return graft_head(target, self.candidate_head)

    def promote(self) -> None:
        """Publish the candidate durably, then defer the in-engine swap
        to the engine's next between-batches safe point."""
        if self.candidate is None:
            raise RuntimeError("promote() with no candidate armed")
        from sntc_tpu.mlio import save_model

        # kill point pre-publish: nothing on disk has changed — a
        # restart serves the incumbent and the promotion is simply lost
        fault_point("model.publish")
        published = None
        if self.serving_path is not None:
            publish_form = self._publish_form()
            # atomic publish; the incumbent checkpoint is retained at
            # <serving_path>.prev — the rollback snapshot
            save_model(publish_form, self.serving_path)
            published = self.serving_path
        self.generation += 1
        self._write_marker({
            "generation": self.generation,
            "action": "promoted",
            "path": published,
            "source": self.candidate_source,
            "ts": time.time(),
        })
        # kill point post-publish / pre-swap: the serving path and the
        # marker already name the candidate — a restart loads and
        # serves it, and the WAL replays in-flight batches under it
        fault_point("model.swap")
        self._pending_swap = self.candidate
        self._swap_kind = "promote"
        # the gate must not fire again between publish and the engine's
        # swap safe point: a labeled batch settled in that window (e.g.
        # by swap_model's own delivery settle) would re-promote and the
        # stale second apply would wipe the incumbent
        self.state = "promoting"
        self.promotions += 1
        self._journal({
            "action": "promote", "generation": self.generation,
            "path": published, "source": self.candidate_source,
        })

    def rollback(self, reason: str) -> None:
        """Restore the previous generation: the retained in-memory
        incumbent when this process promoted it (bitwise-identical
        predictions), else the ``<serving_path>.prev`` snapshot; the
        restored model is republished so a restart serves it too."""
        restored = restored_raw = None
        if self._previous is not None:
            restored, restored_raw = self._previous
        elif self.serving_path is not None:
            from sntc_tpu.mlio import load_model, prev_checkpoint_path

            raw = load_model(
                prev_checkpoint_path(self.serving_path), fallback=False
            )
            restored_raw = raw
            # _resolve_head folds the .prev pipeline's scaler into its
            # head when the serving compile folded the incumbent's —
            # the restored head must read the compiled prefix's column
            restored = graft_head(self.incumbent, self._resolve_head(raw))
        if restored is None:
            raise RuntimeError(
                "rollback with no previous generation retained and no "
                "serving_path to recover .prev from"
            )
        publish = restored_raw
        if publish is None and not isinstance(restored, PipelineModel):
            # a bare classifier-head incumbent IS its persistable form
            # — without republishing, a restart would load the rolled-
            # back candidate the marker claims was replaced
            publish = restored
        if self.serving_path is not None and publish is not None:
            from sntc_tpu.mlio import save_model

            save_model(publish, self.serving_path)
        self.generation += 1
        self._write_marker({
            "generation": self.generation,
            "action": "rolled_back",
            "reason": reason,
            "path": self.serving_path,
            "ts": time.time(),
        })
        emit_event(
            event="model_rollback", component="model", reason=reason,
            generation=self.generation,
        )
        self.incumbent = restored
        self.incumbent_raw = restored_raw
        self._previous = None
        self._pending_swap = restored
        self._swap_kind = "rollback"
        self.rollbacks += 1
        self.state = "rolling_back"
        self._journal({
            "action": "rollback", "generation": self.generation,
            "reason": reason,
        })

    def stats(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "generation": self.generation,
            "promotions": self.promotions,
            "rollbacks": self.rollbacks,
            "shadow_window": self.window,
            "scores_buffered": len(self._scores),
            "probation_left": self._probation_left,
            "candidate_source": self.candidate_source,
        }
