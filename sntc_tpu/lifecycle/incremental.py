"""Incremental-fit states — sufficient statistics a ``partial_fit``
call folds one mini-batch/shard into.

The estimators own the math (``NaiveBayes.partial_fit`` /
``LogisticRegression.partial_fit`` — the same device summarizer passes
their batch ``_fit`` runs); the states here are the host-side f64
accumulators those methods thread between calls, kept in a separate
module so the serving layer can hold/inspect them without touching
estimator internals.

Equivalence contract (tested in ``tests/test_lifecycle.py``, tolerance
documented in docs/RESILIENCE.md "Model lifecycle"):

* **NaiveBayes** — class counts and per-(class, feature) moments are
  ADDITIVE, so ``partial_fit`` over K shards reconstructs the same
  f64 sufficient statistics as one batch fit over the concatenation,
  up to f32 device-summation order (discrete types: θ within ~1e-5
  rel).  The gaussian type's variance comes from the accumulated
  pilot-shifted moments (one pass) where the batch fit runs a second
  pass about the class means — same statistic, different rounding
  (μ ~1e-5, σ² ~1e-2 rel on flow-scale data; the prediction-agreement
  contract is what the test pins).
* **LogisticRegression** — no finite sufficient statistic exists for
  the logistic loss, so ``partial_fit`` is the MLlib streaming recipe:
  the standardization moments accumulate EXACTLY (they are additive),
  and each call runs the jitted LBFGS program on the new shard
  warm-started from the previous solution, with ``decay`` discounting
  the old moments.  The contract is behavioral, not bitwise:
  predictions agree with the batch fit on held-out data within the
  documented tolerance (iid shards).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np


@dataclass
class NBPartialFitState:
    """Decayable per-(class, feature) moment accumulator (host f64).

    ``s_sh`` / ``sq_sh`` are Σw·(x−p) and Σw·(x−p)² about the FIXED
    pilot row captured on the first call — every later shard shifts
    about the same pilot, so the accumulated sums equal one whole-data
    pass up to f32 summation order.  ``decay`` < 1 on an update
    down-weights history (the streaming forgetfulness knob).
    """

    n_classes: int
    n_features: int
    pilot: np.ndarray  # [F] f32, fixed at first update
    cw: np.ndarray = field(default=None)  # [C] f64 class weights
    s_sh: np.ndarray = field(default=None)  # [C, F] f64 Σ w (x-p)
    sq_sh: np.ndarray = field(default=None)  # [C, F] f64 Σ w (x-p)²
    batches_seen: int = 0
    rows_seen: int = 0

    def __post_init__(self):
        if self.cw is None:
            self.cw = np.zeros(self.n_classes, np.float64)
            self.s_sh = np.zeros(
                (self.n_classes, self.n_features), np.float64
            )
            self.sq_sh = np.zeros_like(self.s_sh)

    def update(self, cw, s_sh, sq_sh, n_rows: int, decay: float = 1.0):
        if not 0.0 < decay <= 1.0:
            raise ValueError("decay must lie in (0, 1]")
        self.cw = decay * self.cw + np.asarray(cw, np.float64)
        self.s_sh = decay * self.s_sh + np.asarray(s_sh, np.float64)
        self.sq_sh = decay * self.sq_sh + np.asarray(sq_sh, np.float64)
        self.batches_seen += 1
        self.rows_seen += int(n_rows)
        return self


@dataclass
class LRPartialFitState:
    """Decayed standardization moments + the warm-start solution.

    The moments (``s1``/``s2``/``cnt``/``class_counts``) are additive
    and accumulate exactly; the coefficients are kept in ORIGINAL
    feature space (standardization changes call-to-call as moments
    accumulate) and re-scaled into each call's optimization space for
    the warm start.
    """

    d: int
    k: int
    binomial: bool
    s1: np.ndarray = field(default=None)  # [D] f64 Σ w x
    s2: np.ndarray = field(default=None)  # [D] f64 Σ w x²
    cnt: float = 0.0
    class_counts: np.ndarray = field(default=None)  # [K] f64
    coef_orig: Optional[np.ndarray] = None  # [D, rows] original space
    intercepts: Optional[np.ndarray] = None  # [rows]
    batches_seen: int = 0
    rows_seen: int = 0

    def __post_init__(self):
        if self.s1 is None:
            self.s1 = np.zeros(self.d, np.float64)
            self.s2 = np.zeros(self.d, np.float64)
            self.class_counts = np.zeros(self.k, np.float64)

    @property
    def rows(self) -> int:
        """Coefficient columns: 1 for binomial, K for multinomial."""
        return 1 if self.binomial else self.k

    def update(self, s1, s2, cnt, class_counts, n_rows: int,
               decay: float = 1.0):
        if not 0.0 < decay <= 1.0:
            raise ValueError("decay must lie in (0, 1]")
        self.s1 = decay * self.s1 + np.asarray(s1, np.float64)
        self.s2 = decay * self.s2 + np.asarray(s2, np.float64)
        self.cnt = decay * self.cnt + float(cnt)
        self.class_counts = decay * self.class_counts + np.asarray(
            class_counts, np.float64
        )
        self.batches_seen += 1
        self.rows_seen += int(n_rows)
        return self


def incremental_estimator_for(model, mesh=None):
    """An estimator whose ``partial_fit`` continues ``model`` — the
    serve-time online-learning entry (``--partial-fit``): the candidate
    head is refit incrementally from live labeled batches with the
    incumbent's own hyperparameters.  Supported heads: the two
    estimators with a sufficient-statistic ``partial_fit`` (LR / NB).
    """
    from sntc_tpu.models.logistic_regression import (
        LogisticRegression,
        LogisticRegressionModel,
    )
    from sntc_tpu.models.naive_bayes import NaiveBayes, NaiveBayesModel

    if isinstance(model, LogisticRegressionModel):
        est = LogisticRegression(mesh=mesh)
    elif isinstance(model, NaiveBayesModel):
        est = NaiveBayes(mesh=mesh)
    else:
        raise ValueError(
            f"no incremental estimator for {type(model).__name__}; "
            "partial_fit supports LogisticRegressionModel and "
            "NaiveBayesModel heads"
        )
    est.setParams(
        **{
            name: val
            for name, val in model.paramValues().items()
            if est.hasParam(name)
        }
    )
    return est
