"""DCT — discrete cosine transform feature stage.

Behavioral spec: upstream ``ml/feature/DCT.scala`` [U]: DCT-II with the
orthonormal ("scaled") normalization along each row vector; ``inverse``
runs DCT-III.  Matches ``scipy.fft.dct(x, type=2, norm='ortho')``,
which is exactly what Spark's edu.emory jtransforms call produces.

TPU design: at feature widths (tens-to-hundreds) the transform is ONE
``[N, F] @ [F, F]`` matmul against the precomputed orthonormal DCT
basis — MXU work with perfect batching, simpler and faster here than an
FFT factorization (F is tiny; N is the big axis).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


@lru_cache(maxsize=None)
def _dct_basis(f: int, inverse: bool) -> np.ndarray:
    """Orthonormal DCT-II basis ``B`` with ``y = x @ B``; the inverse
    (DCT-III) is its transpose (orthogonality)."""
    n = np.arange(f)
    k = n[:, None]
    B = np.cos(np.pi * (2 * n[None, :] + 1) * k / (2 * f))  # [k, n]
    B *= np.sqrt(2.0 / f)
    B[0] *= np.sqrt(0.5)
    basis = B.T.astype(np.float32)  # y = x @ B.T ... (see below)
    return np.ascontiguousarray(basis.T if inverse else basis)


@jax.jit
def _apply(X, basis):
    return jnp.matmul(X, basis, precision=jax.lax.Precision.HIGHEST)


class DCT(Transformer):
    inputCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="dct")
    inverse = Param("run the inverse transform (DCT-III)", default=False,
                    validator=validators.is_bool())

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getInputCol()]
        if X.ndim != 2:
            raise ValueError("inputCol must be a vector column")
        X = X.astype(np.float32, copy=False)
        basis = _dct_basis(X.shape[1], bool(self.getInverse()))
        out = np.asarray(_apply(jnp.asarray(X), jnp.asarray(basis)))
        return frame.with_column(self.getOutputCol(), out)
