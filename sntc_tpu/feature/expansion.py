"""PolynomialExpansion / Interaction — monomial feature construction.

Behavioral spec: upstream ``ml/feature/{PolynomialExpansion,
Interaction}.scala`` [U]:

  * PolynomialExpansion(degree): all monomials of the input vector up to
    ``degree`` (constant term excluded), in SPARK'S expansion order —
    terms grouped by their highest variable index i, each group being
    ``x_i`` followed by ``x_i ×`` every earlier-emitted monomial of
    lower total degree (Spark's ``expandDense`` recursion unrolled):
    ``[x1, x1², x2, x1x2, x2², x3, x1x3, x2x3, x3², ...]`` for
    degree 2.  Output width is C(n+d, d) − 1.
  * Interaction: the full outer product of two or more columns (numeric
    scalars count as width-1 vectors) — output width = Π widths, laid
    out with the LAST input varying fastest (Spark's foldRight
    encoding).

Host-side numpy: monomial products are a static index plan applied as
vectorized column products (the plan is tiny and reused across calls —
this can sit on the serving hot path upstream of FM/GLR models).
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, Tuple

import numpy as np

from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


@lru_cache(maxsize=None)
def _expansion_plan(n: int, degree: int) -> Tuple[Tuple[int, ...], ...]:
    """Spark-ordered monomial index tuples for n features up to degree.

    Emission rule (Spark's ``expandDense`` recursion unrolled): for each
    feature i, emit ``x_i``, then scan the WHOLE emitted list in order —
    including entries appended during this scan — multiplying each
    monomial below the degree cap by ``x_i``.  Every sorted index tuple
    is produced exactly once (drop one trailing i to find its unique
    parent)."""
    terms: List[Tuple[int, ...]] = []
    for i in range(n):
        terms.append((i,))
        j = 0
        while j < len(terms):
            m = terms[j]
            if len(m) < degree:
                terms.append(m + (i,))
            j += 1
    return tuple(terms)


class PolynomialExpansion(Transformer):
    inputCol = Param("input vector column")
    outputCol = Param("output expanded column", default="polyFeatures")
    degree = Param("max monomial degree", default=2,
                   validator=validators.gteq(1))

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getInputCol()]
        if X.ndim != 2:
            raise ValueError(
                f"inputCol {self.getInputCol()!r} must be a vector column"
            )
        X = np.asarray(X, np.float64)
        plan = _expansion_plan(X.shape[1], int(self.getDegree()))
        out = np.empty((X.shape[0], len(plan)), np.float64)
        for j, idxs in enumerate(plan):
            col = X[:, idxs[0]].copy()
            for i in idxs[1:]:
                col *= X[:, i]
            out[:, j] = col
        return frame.with_column(self.getOutputCol(), out)


class Interaction(Transformer):
    inputCols = Param("columns to interact (vectors or numeric scalars)")
    outputCol = Param("output interaction column", default="interaction")

    def transform(self, frame: Frame) -> Frame:
        names = self.getInputCols()
        if not names or len(names) < 2:
            raise ValueError("Interaction needs at least two inputCols")
        mats = []
        for name in names:
            c = np.asarray(frame[name], np.float64)
            mats.append(c[:, None] if c.ndim == 1 else c)
        # Spark foldRight layout: LAST column varies fastest
        out = mats[0]
        for m in mats[1:]:
            out = (out[:, :, None] * m[:, None, :]).reshape(
                out.shape[0], -1
            )
        return frame.with_column(self.getOutputCol(), out)
