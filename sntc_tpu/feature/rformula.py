"""RFormula — R-style model formulas.

Behavioral spec: upstream ``ml/feature/RFormula.scala`` [U]: parse
``label ~ term + term + ...`` where a term is a column, ``.`` (every
column except the label), an interaction ``a:b`` (elementwise product;
string factors cross their dummy encodings), and ``- term`` removes a
term (``- 1`` would drop the intercept — handled by the consuming
estimator's ``fitIntercept``, so ``- 1`` is rejected here like any
unknown column).  String columns become StringIndexer + dummy encoding
DROPPING THE LAST indexed category (R's reference-level convention,
which Spark follows); numeric columns pass through; a string label is
StringIndexed.  ``fit`` captures the encodings, ``transform`` emits
``featuresCol`` + ``labelCol``.

Built by composition: StringIndexer / OneHotEncoder-style dummies /
VectorAssembler are the same machinery the standalone stages use.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param


def _parse(formula: str, columns: List[str]):
    if "~" not in formula:
        raise ValueError("formula must contain '~' (label ~ terms)")
    lhs, rhs = (s.strip() for s in formula.split("~", 1))
    terms: List[str] = []
    removed: List[str] = []
    for raw in rhs.replace("-", "+-").split("+"):
        t = raw.strip()
        if not t:
            continue
        if t.startswith("-"):
            removed.append(t[1:].strip())
        elif t == ".":
            terms.extend(c for c in columns if c != lhs and c not in terms)
        elif t not in terms:  # Spark's RFormulaParser dedups (.distinct)
            terms.append(t)
    for r in removed:
        if r == "1":
            raise ValueError(
                "'- 1' (intercept suppression) is not a feature term "
                "here — set fitIntercept=False on the estimator instead"
            )
        if r not in terms:
            raise ValueError(
                f"formula removes {r!r}, which is not among the selected "
                f"terms {terms}"
            )
    terms = [t for t in terms if t not in removed]
    if not terms:
        raise ValueError(f"formula {formula!r} selects no feature terms")
    return lhs, terms


def _indices(arr, levels: List[str]) -> np.ndarray:
    """Vectorized level lookup: the per-value (not per-row) LUT walk the
    StringIndexer transform uses; −1 marks unseen."""
    vals, inv = np.unique(np.asarray(arr).astype(str), return_inverse=True)
    lut = {v: i for i, v in enumerate(levels)}
    val_idx = np.array([lut.get(str(v), -1) for v in vals], np.int64)
    return val_idx[inv]


class _RfParams:
    formula = Param("R formula: label ~ t1 + t2 + a:b + . - drop",
                    default=None)
    featuresCol = Param("output feature vector column", default="features")
    labelCol = Param("output label column", default="label")


class RFormula(_RfParams, Estimator):
    def _fit(self, frame: Frame) -> "RFormulaModel":
        if not self.getFormula():
            raise ValueError("formula must be set")
        label, terms = _parse(self.getFormula(), frame.columns)
        # per-column encodings: numeric passthrough, string -> ordered
        # category list — REUSING StringIndexer's frequencyDesc ordering
        # (one label-ordering contract in the codebase, not two)
        from sntc_tpu.feature.string_indexer import _order_labels

        encodings: Dict[str, List[str]] = {}

        def want(col: str):
            if col in encodings or col not in frame:
                return
            arr = frame[col]
            if arr.dtype.kind in "OUS":
                encodings[col] = _order_labels(arr, "frequencyDesc")

        for t in terms:
            for c in (t.split(":") if ":" in t else [t]):
                if c not in frame:
                    raise ValueError(f"formula references unknown column {c!r}")
                want(c)
        label_levels = None
        if label in frame and frame[label].dtype.kind in "OUS":
            want(label)
            label_levels = encodings.pop(label)
        model = RFormulaModel(
            label=label, terms=terms, encodings=encodings,
            labelLevels=label_levels,
        )
        model.setParams(**self.paramValues())
        return model


class RFormulaModel(_RfParams, Model):
    def __init__(self, label: str, terms: List[str],
                 encodings: Dict[str, List[str]], labelLevels=None, **kwargs):
        super().__init__(**kwargs)
        self.label = label
        self.terms = list(terms)
        self.encodings = {k: list(v) for k, v in encodings.items()}
        self.labelLevels = list(labelLevels) if labelLevels else None

    def _save_extra(self):
        return (
            {
                "label": self.label, "terms": self.terms,
                "encodings": self.encodings,
                "labelLevels": self.labelLevels,
            },
            {},
        )

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(
            label=extra["label"], terms=extra["terms"],
            encodings=extra["encodings"],
            labelLevels=extra["labelLevels"],
        )
        m.setParams(**params)
        return m

    def _column_block(self, frame: Frame, col: str) -> np.ndarray:
        """[N, w] numeric block for one column: passthrough or dummies
        (last reference level dropped, R/Spark convention)."""
        arr = frame[col]
        levels = self.encodings.get(col)
        if levels is None:
            return np.asarray(arr, np.float32).reshape(len(arr), -1)
        idx = _indices(arr, levels)
        if (idx < 0).any():
            raise ValueError(
                f"unseen category in column {col!r} at transform"
            )
        out = np.zeros((len(arr), max(len(levels) - 1, 1)), np.float32)
        keep = idx < len(levels) - 1
        out[np.nonzero(keep)[0], idx[keep]] = 1.0
        return out

    def transform(self, frame: Frame) -> Frame:
        blocks = []
        for t in self.terms:
            if ":" in t:
                parts = [self._column_block(frame, c) for c in t.split(":")]
                cross = parts[0]
                for p in parts[1:]:
                    # full interaction: outer product per row
                    cross = (
                        cross[:, :, None] * p[:, None, :]
                    ).reshape(len(p), -1)
                blocks.append(cross)
            else:
                blocks.append(self._column_block(frame, t))
        X = np.concatenate(blocks, axis=1).astype(np.float32)
        out = frame.with_column(self.getFeaturesCol(), X)
        if self.label in frame:
            y = frame[self.label]
            if self.labelLevels is not None:
                y = _indices(y, self.labelLevels).astype(np.float64)
                if (y < 0).any():
                    raise ValueError("unseen label value at transform")
            else:
                y = np.asarray(y, np.float64)
            out = out.with_column(self.getLabelCol(), y)
        return out
