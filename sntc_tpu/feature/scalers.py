"""MinMaxScaler / MaxAbsScaler / RobustScaler / Normalizer / Binarizer.

Behavioral spec: upstream ``ml/feature/{MinMaxScaler,MaxAbsScaler,
RobustScaler,Normalizer,Binarizer}.scala`` [U] — the remaining standard
Spark scaling stages a user of the reference stack expects next to
StandardScaler:

  * MinMaxScaler: fit per-feature (Emin, Emax); transform rescales to
    ``[min, max]``; constant features map to ``(min + max) / 2``.
  * MaxAbsScaler: fit per-feature max |x|; transform ``x / maxAbs``
    (maxAbs = 0 → 0), preserving sparsity/sign.
  * Normalizer: stateless row p-norm scaling (p ≥ 1, ``inf`` supported);
    zero-norm rows pass through unchanged.
  * Binarizer: stateless ``x > threshold → 1.0 else 0.0``.

TPU design: the two fitted scalers reduce per-feature extrema with plain
jitted ``jnp.min/max`` over the mesh-sharded matrix — XLA inserts the
all-reduce-min/max collectives itself (no hand-rolled psum needed; the
row-0 padding of ``shard_batch`` is extremum-neutral because row 0 is a
real row).  Transforms are elementwise and fuse into downstream matmuls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model, Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.parallel.collectives import shard_batch
from sntc_tpu.parallel.context import get_default_mesh


@jax.jit
def _extrema(xs):
    return jnp.min(xs, axis=0), jnp.max(xs, axis=0)


@jax.jit
def _max_abs(xs):
    return jnp.max(jnp.abs(xs), axis=0)


class _MinMaxParams:
    inputCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="scaledFeatures")
    min = Param("lower bound of the output range", default=0.0)
    max = Param("upper bound of the output range", default=1.0)


class MinMaxScaler(_MinMaxParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "MinMaxScalerModel":
        if self.getMin() >= self.getMax():
            raise ValueError("min must be < max")
        mesh = self._mesh or get_default_mesh()
        xs, _ = shard_batch(mesh, frame[self.getInputCol()])
        lo, hi = _extrema(xs)
        model = MinMaxScalerModel(
            originalMin=np.asarray(lo), originalMax=np.asarray(hi)
        )
        model.setParams(**self.paramValues())
        return model


class MinMaxScalerModel(_MinMaxParams, Model):
    def __init__(self, originalMin, originalMax, **kwargs):
        super().__init__(**kwargs)
        self.originalMin = np.asarray(originalMin, np.float32)
        self.originalMax = np.asarray(originalMax, np.float32)

    def _save_extra(self):
        return {}, {"min": self.originalMin, "max": self.originalMax}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(originalMin=arrays["min"], originalMax=arrays["max"])
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getInputCol()].astype(np.float32, copy=False)
        lo, hi = self.originalMin, self.originalMax
        span = hi - lo
        out_lo, out_hi = self.getMin(), self.getMax()
        scale = np.divide(
            out_hi - out_lo, span, out=np.zeros_like(span), where=span > 0
        )
        scaled = (X - lo) * scale + out_lo
        # Spark: constant features map to the midpoint of the output range
        scaled = np.where(
            span > 0, scaled, 0.5 * (out_lo + out_hi)
        ).astype(np.float32)
        return frame.with_column(self.getOutputCol(), scaled)


class _MaxAbsParams:
    inputCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="scaledFeatures")


class MaxAbsScaler(_MaxAbsParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "MaxAbsScalerModel":
        mesh = self._mesh or get_default_mesh()
        xs, _ = shard_batch(mesh, frame[self.getInputCol()])
        model = MaxAbsScalerModel(maxAbs=np.asarray(_max_abs(xs)))
        model.setParams(**self.paramValues())
        return model


class MaxAbsScalerModel(_MaxAbsParams, Model):
    def __init__(self, maxAbs, **kwargs):
        super().__init__(**kwargs)
        self.maxAbs = np.asarray(maxAbs, np.float32)

    def _save_extra(self):
        return {}, {"maxAbs": self.maxAbs}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(maxAbs=arrays["maxAbs"])
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getInputCol()].astype(np.float32, copy=False)
        inv = np.divide(
            1.0, self.maxAbs,
            out=np.zeros_like(self.maxAbs), where=self.maxAbs > 0,
        )
        return frame.with_column(self.getOutputCol(), X * inv)


@jax.jit
def _quantile_stats(x, qs):
    """Per-feature quantiles ``[len(qs), F]`` — one on-device column sort
    (linear interpolation, the numpy/sklearn convention; Spark's
    approxQuantile sketch converges to the same values at
    relativeError→0, and an exact on-device sort is cheaper here than a
    distributed sketch)."""
    return jnp.quantile(x, qs, axis=0)


class _RobustParams:
    inputCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="scaledFeatures")
    lower = Param(
        "lower quantile of the scaling range",
        default=0.25,
        validator=validators.in_range(0.0, 1.0),
    )
    upper = Param(
        "upper quantile of the scaling range",
        default=0.75,
        validator=validators.in_range(0.0, 1.0),
    )
    withCentering = Param("subtract the median", default=False)
    withScaling = Param("divide by the quantile range", default=True)


class RobustScaler(_RobustParams, Estimator):
    """Upstream ``ml/feature/RobustScaler.scala`` [U] (Spark 3.0): scale by
    the (lower, upper) quantile range and optionally center on the median —
    the outlier-robust StandardScaler, exactly what heavy-tailed flow
    features (byte/packet counts) want.

    TPU design: the fit is ONE jitted per-column quantile (device sort);
    no sharded pass — quantiles are order statistics, so the matrix goes
    up unpadded (shard_batch's replicated-row padding would bias them).
    The transform is elementwise and fuses downstream.
    """

    def _fit(self, frame: Frame) -> "RobustScalerModel":
        lo_q, hi_q = float(self.getLower()), float(self.getUpper())
        if lo_q >= hi_q:
            raise ValueError("lower must be < upper")
        X = frame[self.getInputCol()].astype(np.float32, copy=False)
        stats = np.asarray(
            _quantile_stats(
                jnp.asarray(X), jnp.asarray([lo_q, 0.5, hi_q], jnp.float32)
            )
        )
        model = RobustScalerModel(
            median=stats[1], range=stats[2] - stats[0]
        )
        model.setParams(**self.paramValues())
        return model


class RobustScalerModel(_RobustParams, Model):
    def __init__(self, median, range, **kwargs):
        super().__init__(**kwargs)
        self.median = np.asarray(median, np.float32)
        self.range = np.asarray(range, np.float32)

    def _save_extra(self):
        return {}, {"median": self.median, "range": self.range}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(median=arrays["median"], range=arrays["range"])
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getInputCol()].astype(np.float32, copy=False)
        if self.getWithCentering():
            X = X - self.median
        if self.getWithScaling():
            inv = np.divide(
                1.0, self.range,
                out=np.zeros_like(self.range), where=self.range > 0,
            )
            X = X * inv  # zero-range features → 0, Spark's std=0 rule
        return frame.with_column(
            self.getOutputCol(), X.astype(np.float32)
        )


class Normalizer(Transformer):
    """Row p-norm scaling — stateless (no fit)."""

    inputCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="normFeatures")
    p = Param(
        "norm order (>= 1; float('inf') supported)",
        default=2.0,
        validator=validators.gteq(1.0),
    )

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getInputCol()].astype(np.float32, copy=False)
        p = float(self.getP())
        if np.isinf(p):
            norm = np.abs(X).max(axis=1)
        elif p == 2.0:
            norm = np.sqrt((X.astype(np.float64) ** 2).sum(axis=1))
        elif p == 1.0:
            norm = np.abs(X.astype(np.float64)).sum(axis=1)
        else:
            norm = (np.abs(X.astype(np.float64)) ** p).sum(axis=1) ** (1.0 / p)
        inv = np.divide(
            1.0, norm, out=np.zeros_like(norm, dtype=np.float64), where=norm > 0
        )
        out = (X * inv[:, None].astype(np.float32)).astype(np.float32)
        # Spark leaves zero-norm rows unchanged
        out = np.where((norm > 0)[:, None], out, X)
        return frame.with_column(self.getOutputCol(), out)


class Binarizer(Transformer):
    """Thresholding — stateless (no fit)."""

    inputCol = Param("input column (scalar or vector)", default="features")
    outputCol = Param("output column", default="binarized")
    threshold = Param("values > threshold become 1.0, else 0.0", default=0.0)

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getInputCol()]
        out = (
            np.asarray(X, np.float32) > float(self.getThreshold())
        ).astype(np.float64 if X.ndim == 1 else np.float32)
        return frame.with_column(self.getOutputCol(), out)
