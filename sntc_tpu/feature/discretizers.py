"""Bucketizer / QuantileDiscretizer / Imputer.

Behavioral spec: upstream ``ml/feature/{Bucketizer,QuantileDiscretizer,
Imputer}.scala`` [U]:

  * Bucketizer: stateless mapping of a scalar column into bucket indices
    by explicit ``splits`` (len ≥ 3, strictly increasing; −inf/+inf
    allowed).  ``handleInvalid`` governs NaN ONLY — error (default) /
    keep (extra bucket) / skip; values outside [splits[0], splits[-1]]
    always raise, exactly as Spark's Bucketizer does.
  * QuantileDiscretizer: fit learns ``numBuckets`` quantile splits of the
    input column (duplicate quantiles collapse, like Spark's
    approxQuantile path), producing a ``Bucketizer``-shaped model.
  * Imputer: fit learns per-column mean or median of the non-missing
    values; transform replaces ``missingValue`` (default NaN) with it.
    Multi-column (``inputCols``/``outputCols``) like Spark 2.2+.

TPU note: these are host-side column ops (one pass each over 1-D
columns); they prepare data for the device-resident stages and need no
SPMD machinery — matching SURVEY.md §1's "host relational work stays on
the host data plane".
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from sntc_tpu.core.base import Estimator, Model, Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


def _missing_mask(v: np.ndarray, mv: float) -> np.ndarray:
    """True where a value counts as missing — ONE definition shared by
    Imputer fit (complement) and ImputerModel transform."""
    return np.isnan(v) if np.isnan(mv) else (v == mv) | np.isnan(v)


def _bucketize(
    values: np.ndarray, splits: np.ndarray, handle_invalid: str, what: str
):
    """(indices f64, keep-mask) under Spark Bucketizer semantics: buckets
    are [s_i, s_{i+1}) with the LAST bucket closed on the right;
    ``handleInvalid`` applies to NaN only — out-of-range values always
    raise (Spark: "values outside the splits are always treated as
    errors")."""
    n_buckets = len(splits) - 1
    idx = np.searchsorted(splits, values, side="right") - 1.0
    idx = np.where(values == splits[-1], n_buckets - 1.0, idx)
    nan = np.isnan(values)
    out_of_range = (~nan) & ((values < splits[0]) | (values > splits[-1]))
    if out_of_range.any():
        raise ValueError(
            f"{what}: value outside the splits range "
            f"[{splits[0]}, {splits[-1]}] (use -inf/+inf end splits for "
            "open-ended buckets)"
        )
    if nan.any():
        if handle_invalid == "error":
            raise ValueError(
                f"{what}: NaN values in the input column (set "
                "handleInvalid='keep' or 'skip')"
            )
        if handle_invalid == "keep":
            return np.where(nan, float(n_buckets), idx), None
        return idx, ~nan  # skip
    return idx, None


class Bucketizer(Model):
    """Explicit-splits binning — stateless (a Model so QuantileDiscretizer
    can return it from fit, exactly as Spark does).  Multi-column mode
    (Spark 3.0): ``inputCols``/``outputCols``/``splitsArray``."""

    inputCol = Param("input scalar column", default="input")
    outputCol = Param("output bucket-index column", default="bucketed")
    inputCols = Param("multi-column mode: input columns", default=None)
    outputCols = Param("multi-column mode: output columns", default=None)
    splitsArray = Param(
        "multi-column mode: one splits list per input column", default=None
    )
    splits = Param(
        "strictly-increasing bucket boundaries (len >= 3; use -inf/+inf "
        "for open ends)",
        default=None,
    )
    handleInvalid = Param(
        "NaN handling: error | keep (extra bucket) | skip (drop rows); "
        "out-of-range values always error (Spark semantics)",
        default="error",
        validator=validators.one_of("error", "keep", "skip"),
    )

    @staticmethod
    def _check_splits(s, what: str) -> np.ndarray:
        if s is None or len(s) < 3:
            raise ValueError(f"{what} must have at least 3 boundaries")
        arr = np.asarray(s, np.float64)
        if not np.all(np.diff(arr) > 0):
            raise ValueError(f"{what} must be strictly increasing")
        return arr

    def _splits(self) -> np.ndarray:
        return self._check_splits(self.getSplits(), "splits")

    def transform(self, frame: Frame) -> Frame:
        multi = self.getInputCols()
        if multi:
            outs = self.getOutputCols()
            sa = self.getSplitsArray()
            if not outs or len(outs) != len(multi):
                raise ValueError(
                    "outputCols must be set and match inputCols in length"
                )
            if not sa or len(sa) != len(multi):
                raise ValueError(
                    "splitsArray must be set and match inputCols in length"
                )
            triples = [
                (c, o, self._check_splits(s, f"splitsArray[{i}]"))
                for i, (c, o, s) in enumerate(zip(multi, outs, sa))
            ]
        else:
            triples = [(self.getInputCol(), self.getOutputCol(),
                        self._splits())]
        mode = self.getHandleInvalid()
        keep_all = None
        results = []
        for c, o, splits in triples:
            values = np.asarray(frame[c], np.float64)
            idx, keep = _bucketize(values, splits, mode, "Bucketizer")
            results.append((o, idx))
            if keep is not None:
                keep_all = keep if keep_all is None else (keep_all & keep)
        if keep_all is not None:
            # skip: a row drops when ANY bucketized column is NaN (Spark)
            frame = frame.filter(keep_all)
            results = [(o, idx[keep_all]) for o, idx in results]
        for o, idx in results:
            frame = frame.with_column(o, idx)
        return frame


class QuantileDiscretizer(Estimator):
    inputCol = Param("input scalar column", default="input")
    outputCol = Param("output bucket-index column", default="bucketed")
    inputCols = Param("multi-column mode: input columns", default=None)
    outputCols = Param("multi-column mode: output columns", default=None)
    numBuckets = Param(
        "number of quantile buckets", default=2, validator=validators.gt(1)
    )
    handleInvalid = Param(
        "out-of-range/NaN handling: error | keep | skip",
        default="error",
        validator=validators.one_of("error", "keep", "skip"),
    )

    @staticmethod
    def _column_splits(frame: Frame, col: str, n_buckets: int):
        values = np.asarray(frame[col], np.float64)
        values = values[~np.isnan(values)]
        if values.size == 0:
            raise ValueError(
                f"QuantileDiscretizer: column {col!r} has no non-NaN "
                "values to fit quantiles on"
            )
        qs = np.linspace(0.0, 1.0, n_buckets + 1)[1:-1]
        inner = np.unique(np.quantile(values, qs))
        return [float(v) for v in
                np.concatenate([[-np.inf], inner, [np.inf]])]

    def _fit(self, frame: Frame) -> "Bucketizer":
        n_buckets = self.getNumBuckets()
        multi = self.getInputCols()
        if multi:
            outs = self.getOutputCols()
            if not outs or len(outs) != len(multi):
                raise ValueError(
                    "outputCols must be set and match inputCols in length"
                )
            return Bucketizer(
                inputCols=list(multi), outputCols=list(outs),
                splitsArray=[
                    self._column_splits(frame, c, n_buckets) for c in multi
                ],
                handleInvalid=self.getHandleInvalid(),
            )
        return Bucketizer(
            inputCol=self.getInputCol(),
            outputCol=self.getOutputCol(),
            splits=self._column_splits(
                frame, self.getInputCol(), n_buckets
            ),
            handleInvalid=self.getHandleInvalid(),
        )


class _ImputerParams:
    inputCols = Param("input scalar columns", default=None)
    outputCols = Param("output columns (same length)", default=None)
    strategy = Param(
        "mean | median | mode (Spark 3.1; mode ties -> smallest value)",
        default="mean",
        validator=validators.one_of("mean", "median", "mode"),
    )
    missingValue = Param(
        "the value treated as missing (NaN compares by isnan)",
        default=float("nan"),
    )


class Imputer(_ImputerParams, Estimator):
    def _cols(self):
        ins = self.getInputCols()
        outs = self.getOutputCols()
        if not ins:
            raise ValueError("inputCols is required")
        outs = outs or ins
        if len(ins) != len(outs):
            raise ValueError("inputCols and outputCols lengths differ")
        return ins, outs

    def _fit(self, frame: Frame) -> "ImputerModel":
        ins, outs = self._cols()
        mv = float(self.getMissingValue())
        surrogates = []
        for c in ins:
            v = np.asarray(frame[c], np.float64)
            good = v[~_missing_mask(v, mv)]
            if good.size == 0:
                raise ValueError(f"Imputer: column {c!r} has no valid values")
            strat = self.getStrategy()
            if strat == "mean":
                surrogates.append(float(np.mean(good)))
            elif strat == "median":
                surrogates.append(float(np.median(good)))
            else:  # mode: most frequent; ties -> smallest (Spark 3.1)
                vals, counts = np.unique(good, return_counts=True)
                surrogates.append(float(vals[np.argmax(counts)]))
        model = ImputerModel(surrogates=surrogates)
        model.setParams(**self.paramValues())
        return model


class ImputerModel(_ImputerParams, Model):
    def __init__(self, surrogates: Sequence[float] = (), **kwargs):
        super().__init__(**kwargs)
        self.surrogates = [float(v) for v in surrogates]

    def _save_extra(self):
        return {"surrogates": self.surrogates}, {}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(surrogates=extra["surrogates"])
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        ins = self.getOrDefault("inputCols")
        outs = self.getOrDefault("outputCols") or ins
        mv = float(self.getOrDefault("missingValue"))
        out = frame
        for c, o, s in zip(ins, outs, self.surrogates):
            v = np.asarray(out[c], np.float64)
            miss = _missing_mask(v, mv)
            out = out.with_column(o, np.where(miss, s, v))
        return out
