from sntc_tpu.feature.vector_assembler import VectorAssembler
from sntc_tpu.feature.string_indexer import IndexToString, StringIndexer, StringIndexerModel
from sntc_tpu.feature.standard_scaler import StandardScaler, StandardScalerModel
from sntc_tpu.feature.chisq_selector import ChiSqSelector, ChiSqSelectorModel
from sntc_tpu.feature.univariate_selector import (
    UnivariateFeatureSelector,
    UnivariateFeatureSelectorModel,
)
from sntc_tpu.feature.scalers import (
    Binarizer,
    MaxAbsScaler,
    MaxAbsScalerModel,
    MinMaxScaler,
    MinMaxScalerModel,
    Normalizer,
    RobustScaler,
    RobustScalerModel,
)
from sntc_tpu.feature.pca import PCA, PCAModel
from sntc_tpu.feature.discretizers import (
    Bucketizer,
    Imputer,
    ImputerModel,
    QuantileDiscretizer,
)
from sntc_tpu.feature.expansion import Interaction, PolynomialExpansion
from sntc_tpu.feature.word2vec import Word2Vec, Word2VecModel
from sntc_tpu.feature.hashing import FeatureHasher
from sntc_tpu.feature.vector_indexer import (
    VectorIndexer,
    VectorIndexerModel,
    VectorSizeHint,
)
from sntc_tpu.feature.dct import DCT
from sntc_tpu.feature.rformula import RFormula, RFormulaModel
from sntc_tpu.feature.sql_transformer import SQLTransformer
from sntc_tpu.feature.variance_selector import (
    VarianceThresholdSelector,
    VarianceThresholdSelectorModel,
)
from sntc_tpu.feature.text import (
    CountVectorizer,
    CountVectorizerModel,
    HashingTF,
    IDF,
    IDFModel,
    NGram,
    RegexTokenizer,
    StopWordsRemover,
    Tokenizer,
)
from sntc_tpu.feature.lsh import (
    BucketedRandomProjectionLSH,
    BucketedRandomProjectionLSHModel,
    MinHashLSH,
    MinHashLSHModel,
)
from sntc_tpu.feature.encoders import (
    ElementwiseProduct,
    OneHotEncoder,
    OneHotEncoderModel,
    VectorSlicer,
)

__all__ = [
    "VarianceThresholdSelector",
    "VarianceThresholdSelectorModel",
    "SQLTransformer",
    "FeatureHasher",
    "VectorIndexer",
    "VectorIndexerModel",
    "VectorSizeHint",
    "DCT",
    "RFormula",
    "RFormulaModel",
    "BucketedRandomProjectionLSH",
    "BucketedRandomProjectionLSHModel",
    "CountVectorizer",
    "CountVectorizerModel",
    "HashingTF",
    "IDF",
    "IDFModel",
    "MinHashLSH",
    "MinHashLSHModel",
    "NGram",
    "RegexTokenizer",
    "RobustScaler",
    "RobustScalerModel",
    "StopWordsRemover",
    "Tokenizer",
    "Word2Vec",
    "Word2VecModel",
    "VectorAssembler",
    "StringIndexer",
    "StringIndexerModel",
    "IndexToString",
    "StandardScaler",
    "StandardScalerModel",
    "ChiSqSelector",
    "ChiSqSelectorModel",
    "UnivariateFeatureSelector",
    "UnivariateFeatureSelectorModel",
    "MinMaxScaler",
    "MinMaxScalerModel",
    "MaxAbsScaler",
    "MaxAbsScalerModel",
    "Normalizer",
    "Binarizer",
    "Interaction",
    "PolynomialExpansion",
    "PCA",
    "PCAModel",
    "Bucketizer",
    "QuantileDiscretizer",
    "Imputer",
    "ImputerModel",
    "OneHotEncoder",
    "OneHotEncoderModel",
    "VectorSlicer",
    "ElementwiseProduct",
]
