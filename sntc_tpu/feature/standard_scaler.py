"""StandardScaler — feature standardization with mesh-reduced moments.

Behavioral spec: SURVEY.md §2.2 (upstream ``ml/feature/StandardScaler.scala``
[U]): fit computes per-feature mean and **unbiased** std; transform applies
``(x - mean) * (1/std)`` per the ``withMean``/``withStd`` flags, with
constant features (std == 0) mapped to 0, exactly as Spark does.

TPU design: the fit is ONE ``tree_aggregate`` pass — per-shard weighted
``(Σx, Σx², Σw)`` partials ``psum``-reduced over ICI (the treeAggregate
summarizer analog, SURVEY.md §3.1).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.parallel.collectives import make_tree_aggregate, shard_batch
from sntc_tpu.parallel.context import get_default_mesh


class _ScalerParams:
    inputCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="scaledFeatures")
    withMean = Param("center to zero mean", default=False, validator=validators.is_bool())
    withStd = Param("scale to unit std", default=True, validator=validators.is_bool())


class StandardScaler(_ScalerParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "StandardScalerModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getInputCol()]
        xs, w = shard_batch(mesh, X)

        def moments(xs, w):
            return {
                "sum": jnp.einsum("n,nd->d", w, xs),
                "sumsq": jnp.einsum("n,nd->d", w, xs * xs),
                "count": jnp.sum(w),
            }

        out = make_tree_aggregate(moments, mesh)(xs, w)
        n = float(out["count"])
        mean = np.asarray(out["sum"], dtype=np.float64) / n
        # unbiased variance, clamped: f32 sumsq can dip slightly negative
        var = (np.asarray(out["sumsq"], dtype=np.float64) - n * mean**2) / max(
            n - 1, 1
        )
        std = np.sqrt(np.maximum(var, 0.0))
        model = StandardScalerModel(
            mean=mean.astype(np.float32), std=std.astype(np.float32)
        )
        model.setParams(**self.paramValues())
        return model


class StandardScalerModel(_ScalerParams, Model):
    def __init__(self, mean: np.ndarray, std: np.ndarray, **kwargs):
        super().__init__(**kwargs)
        self.mean = np.asarray(mean)
        self.std = np.asarray(std)

    def _save_extra(self):
        return {}, {"mean": self.mean, "std": self.std}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(mean=arrays["mean"], std=arrays["std"])
        m.setParams(**params)
        return m

    def affine(self):
        """``(mu, f)`` of the map ``x' = (x - mu) * f`` this model applies
        (float64; honors withMean/withStd, constant features get f=0).
        Single source of truth for both ``transform`` and serving-time
        fusion (``sntc_tpu.serve.fuse``)."""
        std = self.std.astype(np.float64)
        f = (
            np.divide(1.0, std, out=np.zeros_like(std), where=std > 0)
            if self.getWithStd()
            else np.ones_like(std)
        )
        mu = (
            self.mean.astype(np.float64)
            if self.getWithMean()
            else np.zeros_like(self.mean, dtype=np.float64)
        )
        return mu, f

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getInputCol()].astype(np.float32)
        mu, f = self.affine()
        if self.getWithMean():
            X = X - mu.astype(np.float32)
        if self.getWithStd():
            X = X * f.astype(np.float32)
        return frame.with_column(self.getOutputCol(), X)
