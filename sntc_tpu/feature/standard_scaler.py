"""StandardScaler — feature standardization with mesh-reduced moments.

Behavioral spec: SURVEY.md §2.2 (upstream ``ml/feature/StandardScaler.scala``
[U]): fit computes per-feature mean and **unbiased** std; transform applies
``(x - mean) * (1/std)`` per the ``withMean``/``withStd`` flags, with
constant features (std == 0) mapped to 0, exactly as Spark does.

TPU design: the fit is ONE ``tree_aggregate`` pass — per-shard weighted
``(Σx, Σx², Σw)`` partials ``psum``-reduced over ICI (the treeAggregate
summarizer analog, SURVEY.md §3.1).  The fitted model remembers the
sharded device copy of its training input: transforming that same frame
(what ``Pipeline.fit`` does next) scales ON DEVICE and hands downstream
estimators a device-resident column — the 62 MB feature matrix crosses
the host↔device boundary once per pipeline fit, not three times
(SURVEY.md §7.2 item 5: load once, keep device-resident).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.parallel.collectives import make_tree_aggregate, shard_batch
from sntc_tpu.parallel.context import get_default_mesh


class _ScalerParams:
    inputCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="scaledFeatures")
    withMean = Param("center to zero mean", default=False, validator=validators.is_bool())
    withStd = Param("scale to unit std", default=True, validator=validators.is_bool())


def _moments(xs, w, pilot):
    # accumulated about a pilot data row: raw f32 Σx² catastrophically
    # cancels for features whose mean dwarfs their spread (the same
    # hazard fixed in PCA/NaiveBayes); the variance is shift-invariant
    # and the true mean is reconstructed in f64 by the caller
    xs = xs - pilot[None, :]
    return {
        "sum": jnp.einsum("n,nd->d", w, xs),
        "sumsq": jnp.einsum("n,nd->d", w, xs * xs),
        "count": jnp.sum(w),
    }


@lru_cache(maxsize=None)
def _moments_agg(mesh):
    # one compiled program per (mesh, input shape) across ALL fits
    return make_tree_aggregate(_moments, mesh, replicated_args=(2,))


def standardization_moments(mesh, xs, w, X_first_row):
    """``(count, mean, BIASED 1/n variance about the mean)`` of a sharded
    matrix, pilot-shifted — shared by StandardScaler and LinearSVC's
    internal standardization.  Returns f64 host arrays; callers apply
    their own ddof correction (Spark's scaler uses ddof=1)."""
    pilot = np.asarray(X_first_row, np.float32)
    out = _moments_agg(mesh)(xs, w, jnp.asarray(pilot))
    n = float(out["count"])
    mean_sh = np.asarray(out["sum"], np.float64) / max(n, 1e-300)
    mean = pilot.astype(np.float64) + mean_sh
    var = (
        np.asarray(out["sumsq"], np.float64) / max(n, 1e-300) - mean_sh**2
    )
    return n, mean, np.maximum(var, 0.0)


@partial(jax.jit, static_argnames=("n",))
def _affine_dev(xs, mu, f, *, n):
    """(x - mu) * f on the device-resident padded input, sliced back to
    the frame's true row count."""
    return ((xs - mu[None, :]) * f[None, :])[:n]


class StandardScaler(_ScalerParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "StandardScalerModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getInputCol()]
        xs, w = shard_batch(mesh, X)

        n, mean, var_biased = standardization_moments(
            mesh, xs, w, np.asarray(X[0]) if X.shape[0] else np.zeros(X.shape[1])
        )
        # unbiased variance (Spark ddof=1)
        var = var_biased * n / max(n - 1, 1)
        std = np.sqrt(np.maximum(var, 0.0))
        model = StandardScalerModel(
            mean=mean.astype(np.float32), std=std.astype(np.float32)
        )
        model.setParams(**self.paramValues())
        # device-resident reuse: transform(SAME input object) skips the
        # re-upload and scales the already-sharded copy.  Released on first
        # hit (the Pipeline.fit flow uses it exactly once) so a long-lived
        # fitted model does not pin the training set in host RAM + HBM.
        from sntc_tpu.parallel.collectives import _device_cache_max_bytes

        if _device_cache_max_bytes() > 0:
            model._dev_cache = (X, xs)
        return model


class StandardScalerModel(_ScalerParams, Model):
    def __init__(self, mean: np.ndarray, std: np.ndarray, **kwargs):
        super().__init__(**kwargs)
        self.mean = np.asarray(mean)
        self.std = np.asarray(std)
        # (input object, sharded device copy) captured at fit time; see
        # StandardScaler._fit
        self._dev_cache = None

    def _save_extra(self):
        return {}, {"mean": self.mean, "std": self.std}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(mean=arrays["mean"], std=arrays["std"])
        m.setParams(**params)
        return m

    def affine(self):
        """``(mu, f)`` of the map ``x' = (x - mu) * f`` this model applies
        (float64; honors withMean/withStd, constant features get f=0).
        Single source of truth for both ``transform`` and serving-time
        fusion (``sntc_tpu.serve.fuse``)."""
        std = self.std.astype(np.float64)
        f = (
            np.divide(1.0, std, out=np.zeros_like(std), where=std > 0)
            if self.getWithStd()
            else np.ones_like(std)
        )
        mu = (
            self.mean.astype(np.float64)
            if self.getWithMean()
            else np.zeros_like(self.mean, dtype=np.float64)
        )
        return mu, f

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getInputCol()]
        mu, f = self.affine()
        cache = self._dev_cache
        # single-shot either way: the cache exists for the one
        # Pipeline.fit-flow transform right after fit; ANY first transform
        # releases it so a kept model (CV sub-models, serving) never pins
        # the training set in host RAM + HBM
        self._dev_cache = None
        if cache is not None and cache[0] is X:
            # the frame being transformed is the one this model was fit on
            # (the Pipeline.fit flow): scale the device-resident sharded
            # copy — no re-upload, and downstream estimators consume the
            # device column directly
            scaled = _affine_dev(
                cache[1],
                jnp.asarray(mu, jnp.float32),
                jnp.asarray(f, jnp.float32),
                n=X.shape[0],
            )
            return frame.with_column(self.getOutputCol(), scaled)
        X = X.astype(np.float32)
        if self.getWithMean():
            X = X - mu.astype(np.float32)
        if self.getWithStd():
            X = X * f.astype(np.float32)
        return frame.with_column(self.getOutputCol(), X)
