"""VectorIndexer + VectorSizeHint.

Behavioral spec: upstream ``ml/feature/{VectorIndexer,VectorSizeHint}
.scala`` [U]:

  * VectorIndexer: fit scans a vector column and declares every feature
    with ≤ ``maxCategories`` distinct values CATEGORICAL, re-indexing its
    values to ``0..k−1`` in ascending value order; other features pass
    through.  ``handleInvalid`` error | skip | keep (keep maps unseen
    values to index k).  The fitted ``categoryMaps`` feed tree
    estimators' categorical metadata.
  * VectorSizeHint: stateless width check/annotation — error | skip |
    optimistic on rows whose vector width disagrees.

Host-side fit (distinct-value scan = Spark's aggregate over executors);
the transform's per-feature LUT is a vectorized searchsorted.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from sntc_tpu.core.base import Estimator, Model, Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


class _ViParams:
    inputCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="indexed")
    maxCategories = Param(
        "features with <= this many distinct values become categorical",
        default=20, validator=validators.gt(1),
    )
    handleInvalid = Param(
        "error | skip | keep for unseen categorical values", default="error",
        validator=validators.one_of("error", "skip", "keep"),
    )


class VectorIndexer(_ViParams, Estimator):
    def _fit(self, frame: Frame) -> "VectorIndexerModel":
        X = frame[self.getInputCol()]
        if X.ndim != 2:
            raise ValueError("inputCol must be a vector column")
        X = np.asarray(X)
        max_cat = int(self.getMaxCategories())
        maps: Dict[int, np.ndarray] = {}
        for j in range(X.shape[1]):
            vals = np.unique(X[:, j]).astype(np.float64)
            if len(vals) <= max_cat:
                # Spark maps value 0.0 to index 0 when present (sparsity
                # preservation — its scaladoc example {-1.0, 0.0} →
                # {0.0: 0, -1.0: 1}); remaining values keep ascending
                # order
                if 0.0 in vals:
                    vals = np.concatenate(([0.0], vals[vals != 0.0]))
                maps[j] = vals
        model = VectorIndexerModel(
            numFeatures=X.shape[1], categoryMaps=maps
        )
        model.setParams(**self.paramValues())
        return model


class VectorIndexerModel(_ViParams, Model):
    def __init__(self, numFeatures: int, categoryMaps: Dict[int, np.ndarray],
                 **kwargs):
        super().__init__(**kwargs)
        self.numFeatures = int(numFeatures)
        self.categoryMaps = {
            int(j): np.asarray(v, np.float64) for j, v in categoryMaps.items()
        }

    def _save_extra(self):
        return (
            {"numFeatures": self.numFeatures,
             "catKeys": sorted(self.categoryMaps)},
            {f"cat_{j}": v for j, v in self.categoryMaps.items()},
        )

    @classmethod
    def _load_from(cls, params, extra, arrays):
        maps = {int(j): arrays[f"cat_{j}"] for j in extra["catKeys"]}
        m = cls(numFeatures=int(extra["numFeatures"]), categoryMaps=maps)
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        X = np.asarray(frame[self.getInputCol()], np.float64)
        if X.shape[1] != self.numFeatures:
            raise ValueError(
                f"expected {self.numFeatures} features, got {X.shape[1]}"
            )
        mode = self.getHandleInvalid()
        out = X.copy()
        bad_rows = np.zeros(len(X), bool)
        for j, vals in self.categoryMaps.items():
            # vals need not be ascending (0.0 is pinned to index 0):
            # search a sorted view, then permute back to category ids
            order = np.argsort(vals, kind="stable")
            sorted_vals = vals[order]
            pos = np.searchsorted(sorted_vals, X[:, j])
            pos_c = np.clip(pos, 0, len(vals) - 1)
            known = sorted_vals[pos_c] == X[:, j]
            out[:, j] = order[pos_c]
            if not known.all():
                if mode == "error":
                    raise ValueError(
                        f"unseen categorical value in feature {j} "
                        "(handleInvalid='error')"
                    )
                if mode == "keep":
                    # Spark: unseen -> extra bucket k
                    out[~known, j] = len(vals)
                else:
                    bad_rows |= ~known
        g = frame.with_column(
            self.getOutputCol(), out.astype(np.float32)
        )
        if mode == "skip" and bad_rows.any():
            g = g.filter(~bad_rows)
        return g


class VectorSizeHint(Transformer):
    """Stateless vector-width contract [U]: error (raise) | skip (drop
    bad rows) | optimistic (trust and pass through)."""

    inputCol = Param("vector column to check", default="features")
    size = Param("required width", default=None)
    handleInvalid = Param(
        "error | skip | optimistic", default="error",
        validator=validators.one_of("error", "skip", "optimistic"),
    )

    def transform(self, frame: Frame) -> Frame:
        size = self.getSize()
        if size is None:
            raise ValueError("size must be set")
        mode = self.getHandleInvalid()
        if mode == "optimistic":
            return frame
        X = frame[self.getInputCol()]
        width = X.shape[1] if X.ndim == 2 else 1
        if width == int(size):
            return frame
        if mode == "error":
            raise ValueError(
                f"column {self.getInputCol()!r} has width {width}, "
                f"required {int(size)}"
            )
        # fixed-width columns disagree as a whole — skip drops everything
        return frame.slice(0, 0)
