"""Locality-sensitive hashing — ``BucketedRandomProjectionLSH`` (Euclidean)
and ``MinHashLSH`` (Jaccard).

Behavioral spec: upstream ``ml/feature/{LSH,BucketedRandomProjectionLSH,
MinHashLSH}.scala`` [U]:

* fit draws ``numHashTables`` random hash functions (seeded);
* ``transform`` appends one hash value per table;
* ``approxNearestNeighbors(dataset, key, k)``: prefilter to rows sharing a
  hash bucket with the key in ANY table, exact ``keyDistance`` on the
  candidates, top-k ascending (Spark's single-probe mode; like Spark, the
  result can hold fewer than k rows when the buckets are sparse);
* ``approxSimilarityJoin(A, B, threshold)``: candidate pairs share a
  bucket in at least one table, kept where ``keyDistance < threshold``.

TPU design: hashing is the MXU/VPU bulk op — BRP is ONE ``[N,F] @ [F,L]``
matmul + floor; MinHash is an F-step ``fori_loop`` of masked mins over the
precomputed ``((1+j)·a + b) mod p`` table (no ``[N,L,F]`` blow-up).  Exact
candidate distances run on-device (Euclidean via the
``‖a‖²+‖b‖²−2a·b`` matmul identity).  The bucket group-by of the join —
pure integer key matching, no FLOPs — is host work, exactly the Spark
shuffle stage's role.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators

#: Spark's MinHash prime (``MinHashLSH.HASH_PRIME`` [U]).
HASH_PRIME = 2038074743


class _LSHParams:
    inputCol = Param("input vector column", default="features")
    outputCol = Param("output hashes column", default="hashes")
    numHashTables = Param(
        "number of hash tables", default=1, validator=validators.gteq(1)
    )
    seed = Param("random seed", default=0)


@jax.jit
def _brp_hash(X, R, inv_bucket):
    # HIGHEST matmul precision: on TPU the default is bf16 passes, which
    # would move points across floor() bucket boundaries relative to the
    # f32 semantics the tests and the slack bound assume
    return jnp.floor(
        jnp.matmul(X, R.T, precision=jax.lax.Precision.HIGHEST)
        * inv_bucket
    )


@jax.jit
def _minhash(active, vals):
    """``active [N, F]`` bool, ``vals [L, F]`` precomputed hash of each
    index → per-row per-table min over active indices, ``[N, L]``.
    int32 throughout — hash values reach ~2e9, beyond f32's 24-bit
    mantissa (observed error ±8), but inside int32."""
    n, f = active.shape
    big = jnp.int32(HASH_PRIME)  # all real hashes are < HASH_PRIME

    def body(j, acc):
        cand = jnp.where(active[:, j, None], vals[None, :, j], big)
        return jnp.minimum(acc, cand)

    init = jnp.full((n, vals.shape[0]), big, jnp.int32)
    return jax.lax.fori_loop(0, f, body, init)


@jax.jit
def _sq_dists(Xa, Xb):
    """Pairwise squared Euclidean via the matmul identity, ``[Na, Nb]``.
    HIGHEST precision: the prefilter slack bound assumes f32 error, not
    the TPU default bf16 passes (~2^15 larger — true pairs would drop
    before the exact recheck could save them)."""
    aa = (Xa * Xa).sum(axis=1)[:, None]
    bb = (Xb * Xb).sum(axis=1)[None, :]
    cross = jnp.matmul(Xa, Xb.T, precision=jax.lax.Precision.HIGHEST)
    return jnp.maximum(aa + bb - 2.0 * cross, 0.0)


def _matrix(col: np.ndarray) -> np.ndarray:
    """Promote a 1-D column to ``[N, 1]`` (fit accepts either rank; every
    hash/distance path works on matrices)."""
    col = np.asarray(col)
    return col[:, None] if col.ndim == 1 else col


class _LSHModel(Model):
    """Shared LSH model surface: transform + the two approx queries."""

    def _hash(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def keyDistance(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, frame: Frame) -> Frame:
        X = _matrix(frame[self.getInputCol()]).astype(np.float32, copy=False)
        return frame.with_column(self.getOutputCol(), self._hash(X))

    def approxNearestNeighbors(
        self,
        frame: Frame,
        key: np.ndarray,
        numNearestNeighbors: int,
        distCol: str = "distCol",
    ) -> Frame:
        X = _matrix(frame[self.getInputCol()]).astype(np.float32, copy=False)
        key = np.asarray(key, np.float32).reshape(1, -1)
        h_data = self._hash(X)
        h_key = self._hash(key)[0]
        cand = np.nonzero((h_data == h_key[None, :]).any(axis=1))[0]
        if cand.size == 0:
            return frame.slice(0, 0).with_column(
                distCol, np.zeros(0, np.float64)
            )
        # paired (broadcast) form: exact differences — the a²+b²−2ab
        # identity loses ~1e-3 on near-zero distances in f32, enough to
        # misrank close neighbors
        d = self.keyDistance(X[cand], key, paired=True).ravel()
        order = np.argsort(d, kind="stable")[:numNearestNeighbors]
        out = frame.take(cand[order])
        return out.with_column(distCol, d[order].astype(np.float64))

    #: rows of A processed per distance chunk inside one bucket — bounds
    #: peak memory when skewed data collapses into one giant bucket
    _JOIN_CHUNK_A = 4096

    def _prefilter_slack(self, Xa, Xb) -> float:
        """Upper bound on the pairwise-distance error of ``keyDistance``'s
        fast path, in distance units.  0 where the fast path is exact
        (MinHash: f32 matmuls of 0/1 counts)."""
        return 0.0

    def approxSimilarityJoin(
        self,
        frameA: Frame,
        frameB: Frame,
        threshold: float,
        distCol: str = "distCol",
    ) -> Frame:
        Xa = _matrix(frameA[self.getInputCol()]).astype(np.float32, copy=False)
        Xb = _matrix(frameB[self.getInputCol()]).astype(np.float32, copy=False)
        ha, hb = self._hash(Xa), self._hash(Xb)
        # vectorized bucket group-by per table (the Spark shuffle stage):
        # shared unique-value coding, then cartesian pairs per shared
        # bucket, distance-thresholded chunk by chunk — only SURVIVING
        # pairs are ever materialized, so a skewed all-one-bucket input
        # costs time, not memory
        ia_parts, ib_parts, d_parts = [], [], []
        for t in range(ha.shape[1]):
            uniq, codes = np.unique(
                np.concatenate([ha[:, t], hb[:, t]]), return_inverse=True
            )
            ca, cb = codes[: len(ha)], codes[len(ha):]
            # argsort+searchsorted on BOTH sides: O(N log N) bucket
            # indexing (a per-unique-value linear scan of ca would be
            # O(U·N) host work)
            order_a = np.argsort(ca, kind="stable")
            order_b = np.argsort(cb, kind="stable")
            sca, scb = ca[order_a], cb[order_b]
            vals = np.arange(len(uniq))
            a_lo = np.searchsorted(sca, vals, "left")
            a_hi = np.searchsorted(sca, vals, "right")
            b_lo = np.searchsorted(scb, vals, "left")
            b_hi = np.searchsorted(scb, vals, "right")
            shared = np.nonzero((a_hi > a_lo) & (b_hi > b_lo))[0]
            for v in shared:
                jb = order_b[b_lo[v]:b_hi[v]]
                ja = order_a[a_lo[v]:a_hi[v]]
                for s in range(0, ja.size, self._JOIN_CHUNK_A):
                    chunk = ja[s:s + self._JOIN_CHUNK_A]
                    # pairwise prefilter with a MAGNITUDE-SCALED margin
                    # (the f32 a²+b²−2ab identity's error scales with
                    # ‖x‖², so a fixed slack drops true pairs on
                    # large-magnitude features), then exact paired
                    # recheck so over-included pairs cost compute only
                    d = self.keyDistance(Xa[chunk], Xb[jb])
                    slack = self._prefilter_slack(Xa[chunk], Xb[jb])
                    ii, jj = np.nonzero(d < threshold + slack)
                    if ii.size == 0:
                        continue
                    d_ex = self.keyDistance(
                        Xa[chunk[ii]], Xb[jb[jj]], paired=True
                    )
                    keep = d_ex < threshold
                    if keep.any():
                        ia_parts.append(chunk[ii[keep]])
                        ib_parts.append(jb[jj[keep]])
                        d_parts.append(d_ex[keep])
        if not ia_parts:
            ia = np.zeros(0, np.int64)
            ib = np.zeros(0, np.int64)
            d = np.zeros(0, np.float64)
        else:
            ia = np.concatenate(ia_parts).astype(np.int64)
            ib = np.concatenate(ib_parts).astype(np.int64)
            d = np.concatenate(d_parts).astype(np.float64)
            # a pair sharing buckets in several tables appears once per
            # table — dedup on the packed pair id
            packed = ia * len(Xb) + ib
            _, first = np.unique(packed, return_index=True)
            first.sort()
            ia, ib, d = ia[first], ib[first], d[first]
        out = {"idA": ia, "idB": ib, distCol: d.astype(np.float64)}
        return Frame(out)


class BucketedRandomProjectionLSH(_LSHParams, Estimator):
    """Euclidean-distance LSH [U]: ``h(x) = floor(x·r / bucketLength)``
    with unit-norm Gaussian projections ``r``."""

    bucketLength = Param(
        "bucket width of each hash", default=None,
        validator=lambda v: v is None or v > 0,
    )

    def _fit(self, frame: Frame) -> "BucketedRandomProjectionLSHModel":
        if self.getBucketLength() is None:
            raise ValueError("bucketLength must be set")
        X = frame[self.getInputCol()]
        f = X.shape[1] if X.ndim == 2 else 1
        rng = np.random.default_rng(self.getSeed())
        R = rng.normal(size=(int(self.getNumHashTables()), f))
        R /= np.linalg.norm(R, axis=1, keepdims=True)
        model = BucketedRandomProjectionLSHModel(randUnitVectors=R)
        model.setParams(**self.paramValues())
        return model


class BucketedRandomProjectionLSHModel(_LSHParams, _LSHModel):
    bucketLength = BucketedRandomProjectionLSH.bucketLength

    def __init__(self, randUnitVectors, **kwargs):
        super().__init__(**kwargs)
        self.randUnitVectors = np.asarray(randUnitVectors, np.float32)

    def _save_extra(self):
        return {}, {"randUnitVectors": self.randUnitVectors}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(randUnitVectors=arrays["randUnitVectors"])
        m.setParams(**params)
        return m

    def _hash(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(
            _brp_hash(
                jnp.asarray(X),
                jnp.asarray(self.randUnitVectors),
                jnp.float32(1.0 / float(self.getBucketLength())),
            )
        )

    def keyDistance(self, a, b, paired: bool = False) -> np.ndarray:
        if paired:
            return np.sqrt(
                np.asarray(
                    _sq_dists_paired(jnp.asarray(a), jnp.asarray(b)),
                    np.float64,
                )
            )
        return np.sqrt(
            np.asarray(_sq_dists(jnp.asarray(a), jnp.asarray(b)), np.float64)
        )

    def _prefilter_slack(self, Xa, Xb) -> float:
        """The a²+b²−2ab identity accumulates f32 error up to
        ~F·eps·(‖a‖²+‖b‖²); convert that squared-distance bound into
        distance units via √ (conservative near zero, and over-inclusion
        only costs the exact recheck)."""
        eps = float(np.finfo(np.float32).eps)
        aa = float((Xa.astype(np.float64) ** 2).sum(axis=1).max())
        bb = float((Xb.astype(np.float64) ** 2).sum(axis=1).max())
        return float(np.sqrt(4.0 * Xa.shape[1] * eps * (aa + bb)))


@jax.jit
def _sq_dists_paired(Xa, Xb):
    d = Xa - Xb
    return jnp.maximum((d * d).sum(axis=1), 0.0)


class MinHashLSH(_LSHParams, Estimator):
    """Jaccard-distance LSH over binary vectors [U]: ``h(x) = min over
    active indices j of ((1 + j)·a + b) mod HASH_PRIME``."""

    def _fit(self, frame: Frame) -> "MinHashLSHModel":
        X = frame[self.getInputCol()]
        f = X.shape[1] if X.ndim == 2 else 1
        if f > HASH_PRIME:
            raise ValueError("input dimension must be < HASH_PRIME")
        rng = np.random.default_rng(self.getSeed())
        L = int(self.getNumHashTables())
        coeffs = np.stack(
            [
                rng.integers(1, HASH_PRIME, size=L),
                rng.integers(0, HASH_PRIME, size=L),
            ],
            axis=1,
        )
        model = MinHashLSHModel(randCoefficients=coeffs)
        model.setParams(**self.paramValues())
        return model


class MinHashLSHModel(_LSHParams, _LSHModel):
    def __init__(self, randCoefficients, **kwargs):
        super().__init__(**kwargs)
        self.randCoefficients = np.asarray(randCoefficients, np.int64)

    def _save_extra(self):
        return {}, {"randCoefficients": self.randCoefficients}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(randCoefficients=arrays["randCoefficients"])
        m.setParams(**params)
        return m

    def _hash_table(self, f: int) -> np.ndarray:
        """``[L, F]`` hash of every index — int64 products on host (the
        a·j products overflow int32), reduced mod HASH_PRIME into int32
        for the on-device masked-min."""
        j = np.arange(1, f + 1, dtype=np.int64)[None, :]
        a = self.randCoefficients[:, 0][:, None]
        b = self.randCoefficients[:, 1][:, None]
        return ((j * a + b) % HASH_PRIME).astype(np.int32)

    def _hash(self, X: np.ndarray) -> np.ndarray:
        if np.any((X != 0) & (X != 1)):
            raise ValueError("MinHashLSH requires binary (0/1) vectors")
        if not np.asarray(X != 0).any(axis=1).all():
            raise ValueError(
                "MinHashLSH: every vector needs at least one nonzero "
                "entry (Spark raises on empty sets too)"
            )
        vals = self._hash_table(X.shape[1])
        return np.asarray(
            _minhash(jnp.asarray(X != 0), jnp.asarray(vals)), np.int64
        )

    def keyDistance(self, a, b, paired: bool = False) -> np.ndarray:
        """Jaccard distance ``1 − |A∩B| / |A∪B|``."""
        a = np.asarray(a, bool)
        b = np.asarray(b, bool)
        if paired:
            inter = (a & b).sum(axis=1).astype(np.float64)
            union = (a | b).sum(axis=1).astype(np.float64)
        else:
            af = jnp.asarray(a, jnp.float32)
            bf = jnp.asarray(b, jnp.float32)
            inter = np.asarray(af @ bf.T, np.float64)
            union = (
                a.sum(axis=1)[:, None] + b.sum(axis=1)[None, :] - inter
            ).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            d = 1.0 - inter / union
        return np.where(union > 0, d, 0.0)
