"""ChiSqSelector — χ² flow-feature selection [B:9].

Behavioral spec: SURVEY.md §2.2 (upstream ``ml/feature/ChiSqSelector.scala``
-> ``mllib/stat/test/ChiSqTest.scala`` [U]): rank features by χ² p-value
against the label (ascending, i.e. most significant first) and keep the top
``numTopFeatures`` / ``percentile`` / all below ``fpr``.  Spark's χ² needs
categorical features; continuous flow features are quantile-binned first
(SURVEY.md §2.2 rebuild note).

TPU design: binning + the (feature, bin, class) contingency run on-device —
``bin_features`` + ``binned_contingency`` fused in one ``tree_aggregate``
SPMD pass over the mesh; the χ² statistics and selection happen on host
(78×32×15 — trivial).  The same histogram kernel drives the tree growers.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List

import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.feature.selection import select_features_by_mode
from sntc_tpu.ops.binning import bin_features, quantile_bin_edges
from sntc_tpu.ops.histogram import (
    binned_contingency,
    binned_contingency_onehot,
    chi_square,
)
from sntc_tpu.parallel.collectives import make_tree_aggregate, shard_batch
from sntc_tpu.parallel.context import get_default_mesh


@lru_cache(maxsize=None)
def _contingency_agg(mesh, n_bins, n_classes, impl, interpret):
    """One compiled contingency program per configuration across fits
    (edges arrive as a replicated ARGUMENT, not a baked-in constant —
    rebuilding the aggregate per fit recompiled on every call)."""

    def contingency(xs, ys, w, edges):
        binned = bin_features(xs, edges)
        if impl == "pallas":
            return binned_contingency_onehot(
                binned, ys, w, n_bins=n_bins, n_classes=n_classes,
                interpret=interpret,
            )
        return binned_contingency(
            binned, ys, w, n_bins=n_bins, n_classes=n_classes
        )

    return make_tree_aggregate(
        contingency, mesh,
        check_vma=impl != "pallas",
        replicated_args=(3,),
    )


class _SelectorParams:
    featuresCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="selectedFeatures")
    labelCol = Param("label index column", default="label")
    selectorType = Param(
        "selection mode: numTopFeatures | percentile | fpr | fdr | fwe",
        default="numTopFeatures",
        validator=validators.one_of(
            "numTopFeatures", "percentile", "fpr", "fdr", "fwe"
        ),
    )
    numTopFeatures = Param(
        "number of features to keep", default=50, validator=validators.gt(0)
    )
    percentile = Param(
        "fraction of features to keep", default=0.1, validator=validators.in_range(0, 1)
    )
    fpr = Param(
        "highest p-value to keep", default=0.05, validator=validators.in_range(0, 1)
    )
    fdr = Param(
        "upper bound on the expected false-discovery rate "
        "(Benjamini-Hochberg)",
        default=0.05,
        validator=validators.in_range(0, 1),
    )
    fwe = Param(
        "upper bound on the family-wise error rate: keep p < fwe / F "
        "(Bonferroni)",
        default=0.05,
        validator=validators.in_range(0, 1),
    )
    maxBins = Param(
        "quantile bins for continuous features (rebuild-specific; Spark "
        "requires pre-categorical input)",
        default=32,
        validator=validators.gt(1),
    )


def chi2_scores(X: np.ndarray, y: np.ndarray, mesh, n_bins: int):
    """``(stats [F], p_values [F])`` of the binned χ² test — the one chi2
    scoring pipeline shared by ChiSqSelector and
    UnivariateFeatureSelector's categorical/categorical mode."""
    import jax

    from sntc_tpu.ops.pallas_histogram import resolve_hist_impl

    y = np.asarray(y).astype(np.int32)
    n_classes = int(y.max()) + 1 if len(y) else 1
    edges = quantile_bin_edges(X, max_bins=n_bins)
    xs, ys, w = shard_batch(mesh, X, y)
    on_tpu = jax.default_backend() == "tpu"
    impl = resolve_hist_impl(1, n_bins, mesh)
    observed = np.asarray(
        _contingency_agg(mesh, n_bins, n_classes, impl, not on_tpu)(
            xs, ys, w, jnp.asarray(edges)
        )
    )
    stats, p_values, _ = chi_square(observed)
    return stats, p_values


class ChiSqSelector(_SelectorParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "ChiSqSelectorModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getFeaturesCol()].astype(np.float32)
        y = frame[self.getLabelCol()]
        stats, p_values = chi2_scores(X, y, mesh, self.getMaxBins())

        mode = self.getSelectorType()
        threshold = {
            "numTopFeatures": self.getNumTopFeatures(),
            "percentile": self.getPercentile(),
            "fpr": self.getFpr(),
            "fdr": self.getFdr(),
            "fwe": self.getFwe(),
        }[mode]
        selected = select_features_by_mode(
            stats, p_values, mode, threshold, X.shape[1]
        )

        model = ChiSqSelectorModel(selected_features=selected)
        model.setParams(**self.paramValues())
        return model


class ChiSqSelectorModel(_SelectorParams, Model):
    def __init__(self, selected_features: List[int], **kwargs):
        super().__init__(**kwargs)
        self.selected_features = list(selected_features)

    def _save_extra(self):
        return {"selected_features": self.selected_features}, {}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(selected_features=extra["selected_features"])
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()]
        out = np.ascontiguousarray(X[:, self.selected_features])
        return frame.with_column(self.getOutputCol(), out)
