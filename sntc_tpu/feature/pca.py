"""PCA — principal component projection.

Behavioral spec: upstream ``ml/feature/PCA.scala`` →
``mllib/linalg/distributed/RowMatrix.computePrincipalComponentsAndExplainedVariance``
[U]: fit eigen-decomposes the sample covariance of the input vectors and
keeps the top-``k`` components (descending eigenvalue); ``transform``
multiplies the RAW (uncentered) vector by the component matrix, exactly
as Spark does; ``explainedVariance`` is the kept eigenvalues' fraction
of the total variance.  Component sign is arbitrary (as in Spark and
sklearn).

TPU design: the covariance reduces to ``(Σx, X^T X, n)`` — one
``tree_aggregate`` SPMD pass whose ``X^T X`` is a single MXU matmul per
shard, ``psum``-reduced over ICI; the ``[D, D]`` eigh runs on host
(78×78 — trivial).  The projection is one jitted matmul.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.parallel.collectives import make_tree_aggregate, shard_batch
from sntc_tpu.parallel.context import get_default_mesh


@lru_cache(maxsize=None)
def _cov_agg(mesh):
    def moments(xs, w, pilot):
        # accumulate about a pilot point (a real data row): uncentered
        # f32 X^T X catastrophically cancels when feature means are large
        # relative to their spread — shifting keeps magnitudes O(spread)
        xs = xs - pilot[None, :]
        wx = xs * w[:, None]
        return {
            "sum": wx.sum(axis=0),
            "xxt": jnp.einsum("nd,ne->de", xs, wx),
            "count": w.sum(),
        }

    return make_tree_aggregate(moments, mesh, replicated_args=(2,))


@jax.jit
def _project(X, pc):
    return X @ pc


class _PcaParams:
    inputCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="pcaFeatures")
    k = Param("number of principal components", default=2,
              validator=validators.gt(0))


class PCA(_PcaParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "PCAModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getInputCol()]
        d = X.shape[1]
        k = self.getK()
        if k > d:
            raise ValueError(f"k={k} exceeds the feature width {d}")
        if X.shape[0] == 0:
            raise ValueError("PCA requires a non-empty dataset")
        xs, w = shard_batch(mesh, X)
        pilot = np.asarray(X[0], np.float32)
        out = _cov_agg(mesh)(xs, w, jnp.asarray(pilot))
        n = float(out["count"])
        # moments are about the pilot; the covariance is shift-invariant
        mean_s = np.asarray(out["sum"], np.float64) / n
        cov = (
            np.asarray(out["xxt"], np.float64) - n * np.outer(mean_s, mean_s)
        ) / max(n - 1.0, 1.0)
        eigvals, eigvecs = np.linalg.eigh(cov)  # ascending
        order = np.argsort(eigvals)[::-1]
        eigvals = np.maximum(eigvals[order], 0.0)
        pc = eigvecs[:, order[:k]]
        total = eigvals.sum()
        explained = eigvals[:k] / total if total > 0 else np.zeros(k)
        model = PCAModel(
            pc=pc.astype(np.float32),
            explainedVariance=explained.astype(np.float64),
        )
        model.setParams(**self.paramValues())
        return model


class PCAModel(_PcaParams, Model):
    def __init__(self, pc: np.ndarray, explainedVariance: np.ndarray, **kwargs):
        super().__init__(**kwargs)
        self.pc = np.asarray(pc, np.float32)  # [D, k]
        self.explainedVariance = np.asarray(explainedVariance, np.float64)

    def _save_extra(self):
        return {}, {"pc": self.pc, "explainedVariance": self.explainedVariance}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(pc=arrays["pc"], explainedVariance=arrays["explainedVariance"])
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getInputCol()].astype(np.float32, copy=False)
        # Spark projects the RAW vectors (no centering at transform time)
        out = np.asarray(_project(jnp.asarray(X), jnp.asarray(self.pc)))
        return frame.with_column(self.getOutputCol(), out)
