"""Text pipeline stages — Tokenizer / RegexTokenizer / StopWordsRemover /
NGram / HashingTF / CountVectorizer / IDF.

Behavioral spec: upstream ``ml/feature/{Tokenizer,RegexTokenizer,
StopWordsRemover,NGram,HashingTF,CountVectorizer,IDF}.scala`` [U]:

  * Tokenizer: lowercase + split on whitespace.
  * RegexTokenizer: ``pattern`` as splitter (``gaps=True``) or token
    matcher (``gaps=False``); ``minTokenLength``; ``toLowercase``.
  * StopWordsRemover: filter a stop-word list, optional case sensitivity
    (default English list).
  * NGram: sliding windows of ``n`` tokens joined by single spaces.
  * HashingTF: term-frequency vectors by murmur3_32(seed=42) of the
    term's UTF-8 bytes, ``nonNegativeMod`` into ``numFeatures`` — EXACT
    Spark bucket parity at any width (default 4096 here vs Spark's
    sparse-vector 2^18; documented delta on the Param); optional
    ``binary``.
  * CountVectorizer: vocabulary by corpus term frequency (``vocabSize``,
    ``minDF``/``maxDF`` document-frequency bounds, ``minTF`` per-doc
    filter, ``binary``); ties broken by term (deterministic).
  * IDF: ``log((m + 1) / (df + 1))`` with ``minDocFreq`` zeroing.

TPU design: tokenization and vocabulary building are host string work
(exactly Spark's executor-side JVM string stage — no FLOPs to place on
an accelerator); the numeric tail is where the device earns its keep:
token-count MATRICES are the interchange format, the IDF document
frequency is ONE jitted SPMD pass over the mesh-sharded count matrix,
and the IDF transform is an elementwise broadcast that fuses into
whatever consumes it.  Token columns are Python-list object arrays —
``Frame`` holds them as 1-D object columns, the analog of Spark's
``Array[String]`` columns.
"""

from __future__ import annotations

import re
from functools import lru_cache
from typing import List, Sequence

import numpy as np

from sntc_tpu.core.base import Estimator, Model, Transformer
from sntc_tpu.core.frame import Frame, object_column
from sntc_tpu.core.params import Param, validators
from sntc_tpu.parallel.collectives import make_tree_aggregate, shard_batch
from sntc_tpu.parallel.context import get_default_mesh

__all__ = [
    "CountVectorizer",
    "CountVectorizerModel",
    "HashingTF",
    "IDF",
    "IDFModel",
    "NGram",
    "RegexTokenizer",
    "StopWordsRemover",
    "Tokenizer",
]

#: Spark's default English stop words (``StopWordsRemover
#: .loadDefaultStopWords("english")`` [U] ships the snowball list; this is
#: the same canonical set).
ENGLISH_STOP_WORDS = (
    "i me my myself we our ours ourselves you your yours yourself "
    "yourselves he him his himself she her hers herself it its itself "
    "they them their theirs themselves what which who whom this that "
    "these those am is are was were be been being have has had having "
    "do does did doing a an the and but if or because as until while "
    "of at by for with about against between into through during "
    "before after above below to from up down in out on off over under "
    "again further then once here there when where why how all any "
    "both each few more most other some such no nor not only own same "
    "so than too very s t can will just don should now"
).split()


def _tokens_column(frame: Frame, col: str) -> List[List[str]]:
    raw = frame[col]
    return [list(v) for v in raw]


class Tokenizer(Transformer):
    """Lowercase + whitespace split [U]."""

    inputCol = Param("input string column", default="text")
    outputCol = Param("output token column", default="tokens")

    def transform(self, frame: Frame) -> Frame:
        toks = [str(s).lower().split() for s in frame[self.getInputCol()]]
        return frame.with_column(self.getOutputCol(), object_column(toks))


class RegexTokenizer(Transformer):
    inputCol = Param("input string column", default="text")
    outputCol = Param("output token column", default="tokens")
    pattern = Param("split/match regex", default=r"\s+")
    gaps = Param(
        "True: pattern splits; False: pattern matches tokens",
        default=True, validator=validators.is_bool(),
    )
    minTokenLength = Param(
        "drop tokens shorter than this", default=1,
        validator=validators.gteq(0),
    )
    toLowercase = Param("lowercase before tokenizing", default=True,
                        validator=validators.is_bool())

    def transform(self, frame: Frame) -> Frame:
        rx = re.compile(self.getPattern())
        gaps = self.getGaps()
        lo = self.getToLowercase()
        mtl = int(self.getMinTokenLength())
        out = []
        for s in frame[self.getInputCol()]:
            s = str(s).lower() if lo else str(s)
            toks = rx.split(s) if gaps else rx.findall(s)
            out.append([t for t in toks if len(t) >= mtl])
        return frame.with_column(self.getOutputCol(), object_column(out))


class StopWordsRemover(Transformer):
    inputCol = Param("input token column", default="tokens")
    outputCol = Param("output token column", default="filtered")
    stopWords = Param("stop word list", default=tuple(ENGLISH_STOP_WORDS))
    caseSensitive = Param("case-sensitive matching", default=False,
                          validator=validators.is_bool())

    def transform(self, frame: Frame) -> Frame:
        if self.getCaseSensitive():
            stop = set(self.getStopWords())
            keep = lambda t: t not in stop  # noqa: E731
        else:
            stop = {w.lower() for w in self.getStopWords()}
            keep = lambda t: t.lower() not in stop  # noqa: E731
        out = [
            [t for t in doc if keep(t)]
            for doc in _tokens_column(frame, self.getInputCol())
        ]
        return frame.with_column(self.getOutputCol(), object_column(out))


class NGram(Transformer):
    inputCol = Param("input token column", default="tokens")
    outputCol = Param("output n-gram column", default="ngrams")
    n = Param("tokens per n-gram", default=2, validator=validators.gteq(1))

    def transform(self, frame: Frame) -> Frame:
        n = int(self.getN())
        out = [
            [" ".join(doc[i:i + n]) for i in range(len(doc) - n + 1)]
            for doc in _tokens_column(frame, self.getInputCol())
        ]
        return frame.with_column(self.getOutputCol(), object_column(out))


# ---------------------------------------------------------------------------
# murmur3_32 — Spark's HashingTF term hash (seed 42) [U]
# ---------------------------------------------------------------------------

def murmur3_32(data: bytes, seed: int = 42) -> int:
    """Exact Murmur3_x86_32 (the hash behind Spark's HashingTF bucket
    assignment), returned as UNSIGNED 32-bit."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    n4 = len(data) // 4
    for i in range(n4):
        k = int.from_bytes(data[4 * i:4 * i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    tail = data[4 * n4:]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def _spark_bucket(term: str, num_features: int) -> int:
    """Spark ``HashingTF.indexOf``: signed-int32 murmur3, nonNegativeMod."""
    h = murmur3_32(term.encode("utf-8"))
    signed = h - (1 << 32) if h >= (1 << 31) else h
    return ((signed % num_features) + num_features) % num_features


class HashingTF(Transformer):
    """Term-frequency vectors with EXACT Spark bucket parity (murmur3
    seed 42 + nonNegativeMod) [U]."""

    inputCol = Param("input token column", default="tokens")
    outputCol = Param("output vector column", default="rawFeatures")
    #: documented delta: Spark defaults to 2^18 assuming SPARSE vectors;
    #: dense-columnar frames want a smaller width (buckets still match
    #: Spark exactly at any matching numFeatures)
    numFeatures = Param("vector width", default=4096,
                        validator=validators.gt(0))
    binary = Param("presence (1.0) instead of counts", default=False,
                   validator=validators.is_bool())

    def indexOf(self, term: str) -> int:
        return _spark_bucket(str(term), int(self.getNumFeatures()))

    def transform(self, frame: Frame) -> Frame:
        nf = int(self.getNumFeatures())
        binary = self.getBinary()
        docs = _tokens_column(frame, self.getInputCol())
        if nf * max(len(docs), 1) > 1 << 30:
            raise ValueError(
                f"dense output would hold {nf}×{len(docs)} floats; this "
                "frame is dense-columnar (no sparse vectors) — lower "
                "numFeatures (e.g. 4096) for corpora of this size"
            )
        out = np.zeros((len(docs), nf), np.float32)
        cache: dict = {}
        for i, doc in enumerate(docs):
            for t in doc:
                j = cache.get(t)
                if j is None:
                    j = cache[t] = _spark_bucket(str(t), nf)
                if binary:
                    out[i, j] = 1.0
                else:
                    out[i, j] += 1.0
        return frame.with_column(self.getOutputCol(), out)


class _CvParams:
    inputCol = Param("input token column", default="tokens")
    outputCol = Param("output vector column", default="features")
    vocabSize = Param("max vocabulary size", default=1 << 18,
                      validator=validators.gt(0))
    minDF = Param(
        "min documents a term must appear in (>=1: count, <1: fraction)",
        default=1.0, validator=validators.gteq(0),
    )
    maxDF = Param(
        "max documents a term may appear in (>=1: count, <1: fraction)",
        default=2**63, validator=validators.gt(0),
    )
    minTF = Param(
        "per-document min term count (>=1: count, <1: fraction of doc)",
        default=1.0, validator=validators.gteq(0),
    )
    binary = Param("presence instead of counts", default=False,
                   validator=validators.is_bool())


class CountVectorizer(_CvParams, Estimator):
    def _fit(self, frame: Frame) -> "CountVectorizerModel":
        docs = _tokens_column(frame, self.getInputCol())
        m = len(docs)
        df: dict = {}
        tf: dict = {}
        for doc in docs:
            seen = set()
            for t in doc:
                t = str(t)
                tf[t] = tf.get(t, 0) + 1
                seen.add(t)
            for t in seen:
                df[t] = df.get(t, 0) + 1
        lo = self.getMinDF()
        hi = self.getMaxDF()
        lo = lo if lo >= 1 else lo * m
        hi = hi if hi >= 1 else hi * m
        if hi < lo:
            # Spark fails fast: require(maxDF >= minDF) [U]
            raise ValueError(
                f"maxDF (resolves to {hi}) must be >= minDF (resolves "
                f"to {lo})"
            )
        kept = [t for t, c in df.items() if lo <= c <= hi]
        # corpus-frequency descending, term ascending for determinism
        kept.sort(key=lambda t: (-tf[t], t))
        vocab = kept[: int(self.getVocabSize())]
        model = CountVectorizerModel(vocabulary=vocab)
        model.setParams(**self.paramValues())
        return model


class CountVectorizerModel(_CvParams, Model):
    def __init__(self, vocabulary: Sequence[str] = (), **kwargs):
        super().__init__(**kwargs)
        self.vocabulary = list(vocabulary)
        self._index = {t: i for i, t in enumerate(self.vocabulary)}

    def _save_extra(self):
        return {"vocabulary": self.vocabulary}, {}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(vocabulary=extra["vocabulary"])
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        docs = _tokens_column(frame, self.getInputCol())
        v = len(self.vocabulary)
        minTF = float(self.getMinTF())
        binary = self.getBinary()
        out = np.zeros((len(docs), v), np.float32)
        for i, doc in enumerate(docs):
            for t in doc:
                j = self._index.get(str(t))
                if j is not None:
                    out[i, j] += 1.0
            thr = minTF if minTF >= 1 else minTF * len(doc)
            row = out[i]
            row[row < thr] = 0.0
            if binary:
                row[row > 0] = 1.0
        return frame.with_column(self.getOutputCol(), out)


@lru_cache(maxsize=None)
def _df_agg(mesh):
    """Document frequency of every column in ONE SPMD pass."""

    def doc_freq(xs, w):
        return ((xs > 0) * w[:, None]).sum(axis=0)

    return make_tree_aggregate(doc_freq, mesh)


class IDF(Estimator):
    """``log((m + 1) / (df + 1))`` [U]; the document-frequency reduction is
    one jitted SPMD pass over the mesh-sharded count matrix."""

    inputCol = Param("input count-vector column", default="rawFeatures")
    outputCol = Param("output vector column", default="features")
    minDocFreq = Param("terms below this df get idf 0", default=0,
                       validator=validators.gteq(0))

    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _fit(self, frame: Frame) -> "IDFModel":
        mesh = self._mesh or get_default_mesh()
        X = frame[self.getInputCol()].astype(np.float32, copy=False)
        m = X.shape[0]
        xs, w = shard_batch(mesh, X)
        df = np.asarray(_df_agg(mesh)(xs, w), np.float64)
        idf = np.log((m + 1.0) / (df + 1.0))
        idf[df < float(self.getMinDocFreq())] = 0.0
        model = IDFModel(idf=idf, docFreq=df, numDocs=m)
        model.setParams(**self.paramValues())
        return model


class IDFModel(Model):
    inputCol = IDF.inputCol
    outputCol = IDF.outputCol
    minDocFreq = IDF.minDocFreq

    def __init__(self, idf, docFreq=None, numDocs: int = 0, **kwargs):
        super().__init__(**kwargs)
        self.idf = np.asarray(idf, np.float64)
        self.docFreq = (
            np.asarray(docFreq, np.float64)
            if docFreq is not None else np.zeros_like(self.idf)
        )
        self.numDocs = int(numDocs)

    def _save_extra(self):
        return {"numDocs": self.numDocs}, {
            "idf": self.idf, "docFreq": self.docFreq,
        }

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(
            idf=arrays["idf"], docFreq=arrays["docFreq"],
            numDocs=int(extra["numDocs"]),
        )
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getInputCol()].astype(np.float32, copy=False)
        out = (X * self.idf[None, :].astype(np.float32))
        return frame.with_column(self.getOutputCol(), out)
