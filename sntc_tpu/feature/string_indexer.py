"""StringIndexer / IndexToString — label string <-> index encoding.

Behavioral spec: SURVEY.md §2.2 (upstream ``ml/feature/StringIndexer.scala``
[U]).  Ordering parity matters for macro-F1 parity (SURVEY.md §7.2 item 3):
the default ``frequencyDesc`` orders labels by descending frequency with ties
broken by the string ascending — reproduced exactly here.  ``handleInvalid``:
``error`` | ``skip`` (drop unseen rows) | ``keep`` (unseen -> index
``len(labels)``).  Output indices are float64, as in Spark.
"""

from __future__ import annotations

from collections import Counter
from typing import List

import numpy as np

from sntc_tpu.core.base import Estimator, Model, Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


def _order_labels(values: np.ndarray, order: str) -> List[str]:
    counts = Counter(str(v) for v in values)
    if order == "frequencyDesc":
        return [l for l, _ in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))]
    if order == "frequencyAsc":
        return [l for l, _ in sorted(counts.items(), key=lambda kv: (kv[1], kv[0]))]
    if order == "alphabetDesc":
        return sorted(counts, reverse=True)
    if order == "alphabetAsc":
        return sorted(counts)
    raise ValueError(f"unknown stringOrderType {order!r}")


class _StringIndexerParams:
    inputCol = Param("input string column", default="label")
    outputCol = Param("output index column", default="labelIndex")
    inputCols = Param(
        "multi-column mode (Spark 3.0): input columns", default=None
    )
    outputCols = Param(
        "multi-column mode: output columns (same length)", default=None
    )
    stringOrderType = Param(
        "label ordering: frequencyDesc | frequencyAsc | alphabetDesc | alphabetAsc",
        default="frequencyDesc",
        validator=validators.one_of(
            "frequencyDesc", "frequencyAsc", "alphabetDesc", "alphabetAsc"
        ),
    )
    handleInvalid = Param(
        "unseen labels at transform: error | skip | keep",
        default="error",
        validator=validators.one_of("error", "skip", "keep"),
    )


def _resolve_cols(stage) -> tuple:
    """(ins, outs) for single- or multi-column mode (Spark 3.0: exactly
    one of inputCol/inputCols drives)."""
    multi_in = stage.getInputCols()
    if multi_in:
        outs = stage.getOutputCols()
        if not outs or len(outs) != len(multi_in):
            raise ValueError(
                "outputCols must be set and match inputCols in length"
            )
        return list(multi_in), list(outs)
    return [stage.getInputCol()], [stage.getOutputCol()]


def _index_values(values: np.ndarray, labels: List[str]):
    """Vectorized vocab lookup: hash-factorize the column once (C-level,
    no per-row Python), then map the few unique values through the
    fitted vocabulary (~7x faster than a per-row dict loop at 1M rows).
    Returns ``(indices f64 with len(labels) marking unseen, bad mask)``."""
    import pandas as pd

    unseen_idx = float(len(labels))
    # NA-ish values (None, nan, NaT) must round-trip through str()
    # exactly like _fit indexed them — factorize would collapse None
    # into the NaN unique, so stringify NA rows first (Python cost only
    # on the NA rows themselves)
    if values.dtype == object:
        na = pd.isna(values)
        if na.any():
            values = values.copy()
            values[na] = np.array(
                [str(v) for v in values[na]], dtype=object
            )
    codes, uniques = pd.factorize(values, use_na_sentinel=False)
    index = {l: float(i) for i, l in enumerate(labels)}
    lut = np.array(
        [index.get(str(u), unseen_idx) for u in uniques], dtype=np.float64
    )
    if len(lut) == 0:
        out = np.full(len(codes), unseen_idx, dtype=np.float64)
    else:
        out = lut[codes]
    return values, out, out == unseen_idx


class StringIndexer(_StringIndexerParams, Estimator):
    def _fit(self, frame: Frame) -> "StringIndexerModel":
        ins, _ = _resolve_cols(self)
        order = self.getStringOrderType()
        labels_array = [_order_labels(frame[c], order) for c in ins]
        model = StringIndexerModel(labelsArray=labels_array)
        model.setParams(**self.paramValues())
        return model


class StringIndexerModel(_StringIndexerParams, Model):
    def __init__(self, labels: List[str] = None, labelsArray=None, **kwargs):
        super().__init__(**kwargs)
        if labelsArray is None:
            labelsArray = [list(labels or [])]
        self.labelsArray = [list(ls) for ls in labelsArray]

    @property
    def labels(self) -> List[str]:
        """Single-column accessor (the Spark attribute); multi-column
        models expose ``labelsArray``."""
        return self.labelsArray[0]

    def _save_extra(self):
        return {"labelsArray": self.labelsArray}, {}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        if "labelsArray" in extra:
            m = cls(labelsArray=extra["labelsArray"])
        else:  # models persisted before multi-column support
            m = cls(labels=extra["labels"])
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        ins, outs = _resolve_cols(self)
        if len(ins) != len(self.labelsArray):
            raise ValueError(
                f"model was fitted on {len(self.labelsArray)} columns, "
                f"transform asked for {len(ins)}"
            )
        mode = self.getHandleInvalid()
        results, bad_any = [], np.zeros(frame.num_rows, bool)
        for c, labels in zip(ins, self.labelsArray):
            values, out, bad = _index_values(frame[c], labels)
            if bad.any() and mode == "error":
                unseen = sorted({str(v) for v in np.asarray(values)[bad]})
                raise ValueError(
                    f"StringIndexer: unseen labels {unseen} in column "
                    f"{c!r} (handleInvalid='error')"
                )
            results.append(out)
            bad_any |= bad
        if mode == "skip" and bad_any.any():
            # Spark drops the ROW if any indexed column is unseen
            keep = ~bad_any
            frame = frame.filter(keep)
            results = [r[keep] for r in results]
        for name, out in zip(outs, results):
            frame = frame.with_column(name, out)
        return frame


class IndexToString(Transformer):
    """Inverse map: index column -> label strings (Spark ``IndexToString``)."""

    inputCol = Param("input index column", default="prediction")
    outputCol = Param("output string column", default="predictedLabel")
    labels = Param("label vocabulary, index order")

    def transform(self, frame: Frame) -> Frame:
        labels = self.getLabels()
        idx = frame[self.getInputCol()].astype(np.int64)
        if (idx < 0).any() or (idx >= len(labels)).any():
            raise ValueError("IndexToString: index out of label range")
        out = np.asarray(labels, dtype=object)[idx]
        return frame.with_column(self.getOutputCol(), out)
