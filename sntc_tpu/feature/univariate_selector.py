"""UnivariateFeatureSelector — score-function feature selection.

Behavioral spec: upstream ``ml/feature/UnivariateFeatureSelector.scala``
[U] (Spark 3.1's successor to ChiSqSelector, same selection surface the
reference's χ² stage uses [B:9]): the score function is chosen by the
(featureType, labelType) pair —

  * categorical/categorical → χ² test,
  * continuous/categorical  → ANOVA F-test (``f_classif``),
  * continuous/continuous   → F-regression (``f_regression``),

with ``selectionMode`` ∈ {numTopFeatures, percentile, fpr, fdr, fwe} and
one numeric ``selectionThreshold`` knob (defaults: 50 / 0.1 / 0.05 /
0.05 / 0.05).

TPU design: every score reduces to per-feature moments computed in ONE
``tree_aggregate`` SPMD pass over the mesh (χ² reuses the binned
contingency kernel; ANOVA needs per-(feature, class) weight/sum/sumsq;
F-regression needs per-feature x/x²/xy moments).  The F statistics and
p-values (scipy ``f.sf``) are host-side on ``[F]``-sized arrays.
"Categorical" features are quantile-binned like ChiSqSelector (this
framework's continuous-flow extension, SURVEY.md §2.2).
"""

from __future__ import annotations

from functools import lru_cache
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.core.base import Estimator, Model
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.feature.selection import select_features_by_mode
from sntc_tpu.parallel.collectives import make_tree_aggregate, shard_batch
from sntc_tpu.parallel.context import get_default_mesh


@lru_cache(maxsize=None)
def _anova_moments_agg(mesh, n_classes):
    """Per-(feature, class) [count, sum, sumsq] in one SPMD pass,
    accumulated about a pilot row (replicated arg): the F statistic is
    shift-invariant, and raw f32 x² sums catastrophically cancel for
    large-mean features."""

    def moments(xs, ys, w, pilot):
        xs = xs - pilot[None, :]
        oh = jax.nn.one_hot(ys, n_classes, dtype=jnp.float32) * w[:, None]
        cnt = oh.sum(axis=0)  # weighted per-class count
        s = jnp.einsum("nf,nc->fc", xs, oh)
        sq = jnp.einsum("nf,nc->fc", xs * xs, oh)
        return cnt, s, sq

    return make_tree_aggregate(moments, mesh, replicated_args=(3,))


@lru_cache(maxsize=None)
def _regression_moments_agg(mesh):
    """Per-feature [Σw, Σx, Σx², Σy, Σy², Σxy] in one SPMD pass, about
    per-variable pilots (the correlation is shift-invariant; raw f32
    squares cancel for large means)."""

    def moments(xs, ys, w, pilot_x, pilot_y):
        xs = xs - pilot_x[None, :]
        ys = ys - pilot_y
        wx = xs * w[:, None]
        return (
            w.sum(),
            wx.sum(axis=0),
            (xs * wx).sum(axis=0),
            (ys * w).sum(),
            (ys * ys * w).sum(),
            (ys[:, None] * wx).sum(axis=0),
        )

    return make_tree_aggregate(moments, mesh, replicated_args=(3, 4))


def f_classif(X_moments, eps: float = 1e-12):
    """ANOVA F per feature from per-class moments ``(cnt [C], s [F,C],
    sq [F,C])`` — the sklearn ``f_classif`` statistic."""
    from scipy.stats import f as f_dist

    cnt, s, sq = (np.asarray(a, np.float64) for a in X_moments)
    nz = cnt > 0
    k = int(nz.sum())
    n = float(cnt.sum())
    if k < 2 or n <= k:
        F = np.zeros(s.shape[0])
        return F, np.ones_like(F)
    mean_c = s[:, nz] / cnt[nz]
    grand = s.sum(axis=1) / n
    ss_between = (cnt[nz] * (mean_c - grand[:, None]) ** 2).sum(axis=1)
    ss_within = (sq[:, nz] - cnt[nz] * mean_c**2).sum(axis=1)
    F = (ss_between / (k - 1)) / np.maximum(ss_within / (n - k), eps)
    p = f_dist.sf(F, k - 1, n - k)
    return F, p


def f_regression(moments, eps: float = 1e-12):
    """F statistic of the univariate linear fit per feature from
    ``(n, sx, sxx, sy, syy, sxy)`` — the sklearn ``f_regression`` form."""
    from scipy.stats import f as f_dist

    n, sx, sxx, sy, syy, sxy = (np.asarray(a, np.float64) for a in moments)
    n = float(n)
    if n <= 2:
        F = np.zeros(sx.shape[0])
        return F, np.ones_like(F)
    cov = sxy - sx * sy / n
    var_x = sxx - sx**2 / n
    var_y = syy - sy**2 / n
    r2 = cov**2 / np.maximum(var_x * var_y, eps)
    r2 = np.clip(r2, 0.0, 1.0 - eps)
    F = r2 / (1.0 - r2) * (n - 2)
    p = f_dist.sf(F, 1, n - 2)
    return F, p


class _UfsParams:
    featuresCol = Param("input vector column", default="features")
    outputCol = Param("output vector column", default="selectedFeatures")
    labelCol = Param("label column", default="label")
    featureType = Param(
        "categorical | continuous",
        default=None,
        validator=lambda v: v in (None, "categorical", "continuous"),
    )
    labelType = Param(
        "categorical | continuous",
        default=None,
        validator=lambda v: v in (None, "categorical", "continuous"),
    )
    selectionMode = Param(
        "numTopFeatures | percentile | fpr | fdr | fwe",
        default="numTopFeatures",
        validator=validators.one_of(
            "numTopFeatures", "percentile", "fpr", "fdr", "fwe"
        ),
    )
    selectionThreshold = Param(
        "k for numTopFeatures, fraction for percentile, p-cutoff otherwise "
        "(None -> Spark's per-mode default)",
        default=None,
    )
    maxBins = Param(
        "quantile bins when categorical features must be derived from "
        "continuous flows (rebuild-specific)",
        default=32,
        validator=validators.gt(1),
    )


_MODE_DEFAULTS = {
    "numTopFeatures": 50,
    "percentile": 0.1,
    "fpr": 0.05,
    "fdr": 0.05,
    "fwe": 0.05,
}


class UnivariateFeatureSelector(_UfsParams, Estimator):
    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._mesh = mesh

    def _score(self, X, y, mesh):
        if X.shape[0] == 0:
            raise ValueError(
                "UnivariateFeatureSelector requires a non-empty dataset"
            )
        ftype, ltype = self.getFeatureType(), self.getLabelType()
        if ftype is None or ltype is None:
            raise ValueError(
                "featureType and labelType must both be set (Spark "
                "requires them; they choose the score function)"
            )
        if ftype == "categorical" and ltype == "categorical":
            # χ² on the binned contingency — ChiSqSelector's one pipeline
            from sntc_tpu.feature.chisq_selector import chi2_scores

            return chi2_scores(X, y, mesh, self.getMaxBins())
        if ltype == "categorical":  # continuous features, ANOVA F
            n_classes = int(y.max()) + 1 if len(y) else 1
            xs, ys, w = shard_batch(mesh, X, y.astype(np.int32))
            pilot = jnp.asarray(np.asarray(X[0], np.float32))
            m = _anova_moments_agg(mesh, n_classes)(xs, ys, w, pilot)
            return f_classif(m)
        if ftype == "categorical":
            raise ValueError(
                "categorical features with a continuous label have no "
                "Spark score function (Spark rejects this combination too)"
            )
        y32 = y.astype(np.float32)
        xs, ys, w = shard_batch(mesh, X, y32)
        m = _regression_moments_agg(mesh)(
            xs, ys, w,
            jnp.asarray(np.asarray(X[0], np.float32)),
            jnp.float32(y32[0]),
        )
        return f_regression(m)

    def _resolved_threshold(self):
        """The mode's threshold, validated BEFORE any distributed scoring
        (threshold semantics depend on the mode, so validation can't live
        in a mode-blind Param validator)."""
        mode = self.getSelectionMode()
        threshold = self.getSelectionThreshold()
        if threshold is None:
            threshold = _MODE_DEFAULTS[mode]
        if mode == "numTopFeatures":
            if float(threshold) != int(threshold):
                raise ValueError(
                    f"selectionThreshold={threshold!r} must be an integer "
                    "feature count for numTopFeatures (Spark IntParam)"
                )
            if int(threshold) < 1:
                raise ValueError(
                    f"selectionThreshold={threshold!r} must be a positive "
                    "feature count for numTopFeatures"
                )
        elif not 0.0 <= float(threshold) <= 1.0:
            raise ValueError(
                f"selectionThreshold={threshold!r} must be in [0, 1] for "
                f"selectionMode={mode!r}"
            )
        return mode, threshold

    def _fit(self, frame: Frame) -> "UnivariateFeatureSelectorModel":
        mesh = self._mesh or get_default_mesh()
        mode, threshold = self._resolved_threshold()  # fail fast
        X = frame[self.getFeaturesCol()].astype(np.float32, copy=False)
        y = np.asarray(frame[self.getLabelCol()])
        stats, p_values = self._score(X, y, mesh)
        selected = select_features_by_mode(
            np.asarray(stats), np.asarray(p_values), mode, threshold,
            X.shape[1],
        )
        model = UnivariateFeatureSelectorModel(selected_features=selected)
        model.setParams(**self.paramValues())
        return model


class UnivariateFeatureSelectorModel(_UfsParams, Model):
    def __init__(self, selected_features: List[int] = (), **kwargs):
        super().__init__(**kwargs)
        self.selected_features = list(selected_features)

    def _save_extra(self):
        return {"selected_features": self.selected_features}, {}

    @classmethod
    def _load_from(cls, params, extra, arrays):
        m = cls(selected_features=extra["selected_features"])
        m.setParams(**params)
        return m

    def transform(self, frame: Frame) -> Frame:
        X = frame[self.getFeaturesCol()]
        out = np.ascontiguousarray(X[:, self.selected_features])
        return frame.with_column(self.getOutputCol(), out)
