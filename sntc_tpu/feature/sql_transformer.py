"""SQLTransformer — SQL-statement feature stage (restricted grammar).

Behavioral spec: upstream ``ml/feature/SQLTransformer.scala`` [U]:
``statement`` is a SQL string with the placeholder ``__THIS__`` for the
input dataset, e.g. ``SELECT *, (v1 + v2) AS v3 FROM __THIS__ WHERE
v1 > 2``.

Documented delta: Spark hands the statement to a full Catalyst SQL
engine; there is no SQL engine in this stack (Catalyst's role belongs
to XLA — SURVEY.md §1 L4), so this stage supports the restricted
grammar that covers the transformer's actual ML-pipeline uses:

    SELECT <item> [, <item> ...] FROM __THIS__ [WHERE <condition>]

where ``<item>`` is ``*``, a column name, or ``<expression> AS name``,
and expressions/conditions are arithmetic/comparison/boolean
combinations of scalar columns and literals, with the SQL spellings
``=``, ``<>``, ``AND``/``OR``/``NOT`` rewritten to their pandas.eval
forms before evaluation.  Column names with spaces — the CICIDS2017
flow schema is full of them — are referenced with backticks, Spark's
own quoting: ``SELECT (`Destination Port` * 2) AS dp2 FROM __THIS__``.  Anything the grammar or the evaluator cannot
express (joins, aggregates, UDFs, nested selects) raises ``ValueError``
— the statement regex only admits a single ``__THIS__`` table, and item
expressions must evaluate.
"""

from __future__ import annotations

import re
from typing import List

import numpy as np

from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param

_STMT = re.compile(
    r"^\s*SELECT\s+(?P<items>.+?)\s+FROM\s+__THIS__"
    r"(?:\s+WHERE\s+(?P<where>.+?))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL,
)


def _outside_quotes(s: str, fn) -> str:
    """Apply ``fn`` to every segment of ``s`` OUTSIDE single-quoted
    string literals and backtick-quoted identifiers — operator
    rewriting must never touch either.  The SQL escaped quote ``''``
    inside a literal stays inside it and is rewritten to the Python
    escape ``\\'`` pandas.eval understands."""
    out: List[str] = []
    seg: List[str] = []
    state = None  # None | "'" | "`"
    i, n = 0, len(s)
    while i < n:
        ch = s[i]
        if state is None:
            if ch in ("'", "`"):
                out.append(fn("".join(seg)))
                seg = []
                out.append(ch)
                state = ch
            else:
                seg.append(ch)
        elif state == "'" and ch == "'" and i + 1 < n and s[i + 1] == "'":
            out.append("\\'")  # SQL '' -> Python \' (still in literal)
            i += 2
            continue
        else:
            out.append(ch)
            if ch == state:
                state = None
        i += 1
    out.append(fn("".join(seg)))
    return "".join(out)


def _sqlize(expr: str) -> str:
    """SQL operator spellings → pandas.eval spellings (outside quotes):
    ``<>`` → ``!=``, bare ``=`` → ``==`` (leaves ``==``/``<=``/``>=``/
    ``!=`` alone), ``AND``/``OR``/``NOT`` (any case) → lowercase."""

    def rewrite(seg: str) -> str:
        seg = seg.replace("<>", "!=")
        seg = re.sub(r"(?<![<>!=])=(?!=)", "==", seg)
        for kw in ("and", "or", "not"):
            seg = re.sub(rf"\b{kw}\b", kw, seg, flags=re.IGNORECASE)
        return seg

    return _outside_quotes(expr, rewrite)


def _split_items(items: str) -> List[str]:
    """Split the select list on top-level commas — parentheses nest,
    and commas inside string literals (incl. SQL ``''`` escapes) or
    backticked names don't split."""
    out, depth, cur = [], 0, []
    state = None  # None | "'" | "`"
    i, n = 0, len(items)
    while i < n:
        ch = items[i]
        if state is None:
            if ch in ("'", "`"):
                state = ch
            elif ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            elif ch == "," and depth == 0:
                out.append("".join(cur).strip())
                cur = []
                i += 1
                continue
        elif state == "'" and ch == "'" and i + 1 < n and items[i + 1] == "'":
            cur.append("''")
            i += 2
            continue
        elif ch == state:
            state = None
        cur.append(ch)
        i += 1
    if cur:
        out.append("".join(cur).strip())
    return [s for s in out if s]


def _eval(df, expr: str, n: int) -> np.ndarray:
    """Evaluate one expression against the scalar columns, broadcasting
    literal constants to the row count; evaluator failures surface as
    grammar errors."""
    try:
        val = df.eval(_sqlize(expr))
    except Exception as e:  # pandas raises a zoo of parser error types
        raise ValueError(
            f"cannot evaluate expression {expr!r} (restricted "
            f"SQLTransformer grammar): {e}"
        ) from e
    arr = np.asarray(val)
    if arr.ndim == 0:
        arr = np.full(n, arr[()])
    if arr.ndim != 1 or arr.shape[0] != n:
        raise ValueError(
            f"expression {expr!r} did not produce one value per row"
        )
    return arr


class SQLTransformer(Transformer):
    statement = Param(
        "SELECT <items> FROM __THIS__ [WHERE <cond>] (restricted grammar "
        "— see module docstring)",
        default=None,
    )

    def transform(self, frame: Frame) -> Frame:
        stmt = self.getStatement()
        if not stmt:
            raise ValueError("statement must be set")
        m = _STMT.match(stmt)
        if not m:
            raise ValueError(
                f"unsupported statement {stmt!r}: expected "
                "'SELECT <items> FROM __THIS__ [WHERE <cond>]'"
            )
        import pandas as pd

        scalar_cols = [c for c in frame.columns if frame[c].ndim == 1]
        df = pd.DataFrame({c: np.asarray(frame[c]) for c in scalar_cols})

        where = m.group("where")
        src = frame
        if where:
            mask = np.asarray(
                _eval(df, where, frame.num_rows), bool
            )
            src = frame.filter(mask)
            df = df[mask]

        out_cols = {}
        for item in _split_items(m.group("items")):
            if item == "*":
                for c in src.columns:
                    out_cols[c] = src[c]
                continue
            as_m = re.match(
                r"^(?P<expr>.+?)\s+AS\s+(?P<name>\w+|`[^`]+`)$", item,
                re.IGNORECASE | re.DOTALL,
            )
            bare = re.fullmatch(r"\w+|`[^`]+`", item)
            if as_m:
                expr, name = as_m.group("expr"), as_m.group("name")
                out_cols[name.strip("`")] = _eval(df, expr, src.num_rows)
            elif bare:
                col = item.strip("`")
                if col not in src:
                    raise ValueError(f"unknown column {col!r}")
                out_cols[col] = src[col]
            else:
                raise ValueError(
                    f"select item {item!r} needs 'AS <name>' (bare "
                    "expressions have no output column name)"
                )
        return Frame(out_cols)
