"""FeatureHasher — the hashing trick over mixed-type columns.

Behavioral spec: upstream ``ml/feature/FeatureHasher.scala`` [U]:
project any set of numeric / string / boolean columns into a
``numFeatures`` vector with murmur3(seed 42):

  * numeric column: bucket = hash(colName), value added as-is;
  * categorical (string, boolean, or listed in ``categoricalCols``):
    bucket = hash("colName=value"), adds 1.0;

colliding buckets accumulate.  Shares the exact Spark hash/bucket path
with :class:`~sntc_tpu.feature.text.HashingTF`.
"""

from __future__ import annotations

import numpy as np

from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators
from sntc_tpu.feature.text import _spark_bucket


class FeatureHasher(Transformer):
    inputCols = Param("columns to hash", default=())
    outputCol = Param("output vector column", default="features")
    #: documented delta: Spark defaults to 2^18 assuming SPARSE vectors;
    #: this frame is dense-columnar, where 2^18 × rows is unusable past a
    #: few thousand rows — the default is 4096 (hash buckets still match
    #: Spark exactly at any matching width)
    numFeatures = Param("vector width", default=4096,
                        validator=validators.gt(0))
    categoricalCols = Param(
        "numeric columns to force categorical treatment", default=(),
    )

    def transform(self, frame: Frame) -> Frame:
        cols = list(self.getInputCols())
        if not cols:
            raise ValueError("inputCols must be set")
        nf = int(self.getNumFeatures())
        forced = set(self.getCategoricalCols())
        n = frame.num_rows
        if nf * max(n, 1) > 1 << 30:
            raise ValueError(
                f"dense output would hold {nf}×{n} floats; lower "
                "numFeatures (this frame has no sparse vectors)"
            )
        out = np.zeros((n, nf), np.float32)
        for c in cols:
            col = frame[c]
            numeric = (
                np.issubdtype(col.dtype, np.number)
                and not np.issubdtype(col.dtype, np.bool_)
                and c not in forced
            )
            if numeric:
                j = _spark_bucket(c, nf)
                out[:, j] += np.asarray(col, np.float32)
            else:
                cache: dict = {}
                for r, v in enumerate(col):
                    if isinstance(v, (bool, np.bool_)):
                        # Scala Boolean.toString is lowercase — Python's
                        # str(True) would hash a different bucket
                        key = f"{c}={'true' if v else 'false'}"
                    else:
                        key = f"{c}={v}"
                    j = cache.get(key)
                    if j is None:
                        j = cache[key] = _spark_bucket(key, nf)
                    out[r, j] += 1.0
        return frame.with_column(self.getOutputCol(), out)
