"""Shared univariate feature-selection modes.

One implementation of Spark's five ``selectorType``/``selectionMode``
semantics (upstream ``ml/feature/{ChiSqSelector,UnivariateFeatureSelector}.
scala`` [U]) used by both selectors: rank by p-value ascending (stat
descending, index ascending on ties) and keep

  * ``numTopFeatures`` — the best k,
  * ``percentile``     — the best ``ceil-free int(F * fraction)`` (min 1),
  * ``fpr``            — every feature with ``p < threshold``,
  * ``fdr``            — Benjamini-Hochberg step-up at ``threshold``,
  * ``fwe``            — Bonferroni: ``p < threshold / F``.
"""

from __future__ import annotations

from typing import List

import numpy as np


def select_features_by_mode(
    stats: np.ndarray,
    p_values: np.ndarray,
    mode: str,
    threshold,
    n_features: int,
) -> List[int]:
    """Sorted selected feature indices; ``threshold`` is the mode's knob
    (k / fraction / p-cutoff)."""
    order = np.lexsort((np.arange(len(stats)), -stats, p_values))
    if mode == "numTopFeatures":
        chosen = order[: min(int(threshold), n_features)]
    elif mode == "percentile":
        chosen = order[: max(1, int(n_features * float(threshold)))]
    elif mode == "fpr":
        chosen = np.flatnonzero(p_values < float(threshold))
    elif mode == "fdr":
        # Benjamini-Hochberg step-up: largest k with p_(k) <= k/F * fdr,
        # then every feature at or below that cutoff
        sorted_p = p_values[order]
        cuts = (np.arange(1, n_features + 1) / n_features) * float(threshold)
        below = np.flatnonzero(sorted_p <= cuts)
        chosen = order[: below[-1] + 1] if below.size else order[:0]
    elif mode == "fwe":
        chosen = np.flatnonzero(p_values < float(threshold) / n_features)
    else:
        raise ValueError(f"unknown selection mode {mode!r}")
    return sorted(int(i) for i in chosen)
