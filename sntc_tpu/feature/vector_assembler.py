"""VectorAssembler — concatenate numeric columns into one feature vector.

Behavioral spec: SURVEY.md §2.2 (upstream ``ml/feature/VectorAssembler.scala``
[U]): dense concatenation in declared column order; ``handleInvalid`` is
``error`` (raise on NaN), ``skip`` (drop rows), or ``keep`` (pass NaN
through).  Output is a ``(N, D)`` float32 vector column — this framework's
``VectorUDT`` analog (sntc_tpu.core.frame).
"""

from __future__ import annotations

from typing import List

import numpy as np

from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param, validators


class VectorAssembler(Transformer):
    inputCols = Param("input column names, concatenated in order")
    outputCol = Param("output vector column", default="features")
    handleInvalid = Param(
        "how to handle NaN/Inf rows: error | skip | keep",
        default="error",
        validator=validators.one_of("error", "skip", "keep"),
    )

    def transform(self, frame: Frame) -> Frame:
        names: List[str] = self.getInputCols()
        parts = []
        for name in names:
            col = frame[name]
            if col.ndim == 1:
                parts.append(col.astype(np.float32)[:, None])
            else:
                parts.append(col.astype(np.float32))
        X = np.concatenate(parts, axis=1) if parts else np.zeros((frame.num_rows, 0), np.float32)

        mode = self.getHandleInvalid()
        if mode != "keep":
            invalid = ~np.isfinite(X).all(axis=1)
            if invalid.any():
                if mode == "error":
                    raise ValueError(
                        f"VectorAssembler: {int(invalid.sum())} rows contain "
                        "NaN/Inf (handleInvalid='error'); clean the data or "
                        "use handleInvalid='skip'"
                    )
                frame = frame.filter(~invalid)
                X = X[~invalid]
        return frame.with_column(self.getOutputCol(), X)
